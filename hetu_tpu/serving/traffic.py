"""Seeded fleet traffic generator: diurnal load, zipf sessions, flash
crowds, mixed SLO classes — the "millions of users" trace the elastic
fleet is sized against.

A production fleet is never offered a flat request rate.  The shape
that matters for autoscaling is the DIURNAL curve (a daily peak/trough
swing, here one raised cosine per ``cycle_s``), punctuated by FLASH
CROWDS (a multiplier window landing with no warning) and skewed by
session popularity (a zipf over session ids: a few hot tenants produce
most of the traffic, so their shared system-prompt prefixes dominate
the prefix-cache economy).  :class:`TrafficGenerator` renders that
shape into a replayable list of :class:`TrafficSpec` rows — every draw
comes from one ``numpy.random.RandomState(seed)``, so the same seed
always yields byte-identical traces (the determinism contract the
chaos gates and the autoscale A/B bench both lean on).

Workload mix (``mix=`` weights, defaults below):

- ``chat``     latency-class short prompt / short decode — the
               interactive GPT turn; rides a zipf-popular session id so
               returning sessions re-hit their prefix blocks;
- ``longctx``  throughput-class prefill-heavy — a long prompt decoding
               only a few tokens (summarize-the-document shape);
- ``ctr``      throughput-class tiny prompt / one-to-two token decode —
               the CTR embed-wave stand-in, GPT-shaped so one fleet
               serves the whole trace (the real recommendation wave
               runs on the EmbedServingEngine fleet, PR 14).

Virtual time: ``trace()`` stamps each spec with an arrival offset ``t``
in virtual seconds; :func:`replay` submits specs into a ``ServingRouter``
against a virtual clock advanced ``step_s`` per ``router.step()`` —
wall-clock independent, so a trace replays identically on a loaded CI
box and a quiet workstation.  Shed/rejected submissions are returned,
never retried silently: the caller owns the zero-loss accounting.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Tuple

import numpy as np

from .engine import QueueFull
from .request import Request
from .router import RouterShed

__all__ = ["TrafficSpec", "TrafficGenerator", "replay"]

# class name -> (slo_class, prompt-span range, decode range); spans are
# fractions of the generator's prompt budget so one mix serves any s_max
_CLASSES = {
    "chat": ("latency", (0.10, 0.30), (0.20, 0.50)),
    "longctx": ("throughput", (0.55, 0.85), (0.05, 0.15)),
    "ctr": ("throughput", (0.05, 0.12), (0.02, 0.06)),
}
_DEFAULT_MIX = (("chat", 0.6), ("longctx", 0.25), ("ctr", 0.15))


@dataclasses.dataclass
class TrafficSpec:
    """One arrival: everything needed to build its Request, plus the
    virtual arrival time.  Greedy (temperature 0) by construction so a
    replay's outputs are token-identical to an offline decode of the
    same specs — the chaos gates compare exactly that."""

    t: float
    workload: str
    prompt: List[int]
    max_new_tokens: int
    slo_class: str
    session_id: Optional[str]
    seed: int
    request_id: str

    def to_request(self, **overrides):
        kw = dict(prompt=list(self.prompt),
                  max_new_tokens=self.max_new_tokens,
                  temperature=0.0, seed=self.seed,
                  slo_class=self.slo_class, session_id=self.session_id,
                  request_id=self.request_id)
        kw.update(overrides)
        return Request(**kw)


class TrafficGenerator:
    """Render a seeded diurnal/zipf/flash traffic shape into specs.

    ``flash`` is a tuple of ``(t0, duration_s, multiplier)`` windows —
    inside one, the instantaneous rate is multiplied (the flash crowd
    the scale-down chaos gate lands mid-drain).  ``prefix_len`` > 0
    gives every session a deterministic shared prompt head of that many
    tokens, so popular sessions exercise the prefix cache + directory
    the way real multi-tenant system prompts do."""

    def __init__(self, *, seed=0, vocab=61, s_max=32, horizon_s=8.0,
                 base_rps=2.0, peak_rps=10.0, cycle_s=None,
                 n_sessions=32, zipf_a=1.4, flash=(), mix=None,
                 prefix_len=0):
        self.seed = int(seed)
        self.vocab = int(vocab)
        self.s_max = int(s_max)
        self.horizon_s = float(horizon_s)
        self.base_rps = float(base_rps)
        self.peak_rps = float(peak_rps)
        # one full trough->peak->trough swing across the horizon unless
        # the caller wants several "days"
        self.cycle_s = float(cycle_s if cycle_s is not None
                             else horizon_s)
        self.n_sessions = int(n_sessions)
        self.zipf_a = float(zipf_a)
        self.flash = tuple((float(t0), float(d), float(m))
                           for t0, d, m in flash)
        self.mix = tuple(mix) if mix is not None else _DEFAULT_MIX
        for name, _w in self.mix:
            if name not in _CLASSES:
                raise ValueError(
                    f"unknown traffic class {name!r} "
                    f"(expected one of {sorted(_CLASSES)})")
        self.prefix_len = int(prefix_len)
        if self.prefix_len >= self.s_max:
            raise ValueError(
                f"prefix_len {self.prefix_len} leaves no prompt room "
                f"under s_max {self.s_max}")

    # ------------------------------------------------------------- #

    def rate(self, t):
        """Instantaneous arrival rate (req/s) at virtual second ``t``:
        the raised-cosine diurnal curve times any flash window."""
        swing = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / self.cycle_s))
        r = self.base_rps + (self.peak_rps - self.base_rps) * swing
        for t0, dur, mult in self.flash:
            if t0 <= t < t0 + dur:
                r *= mult
        return r

    def _session_prefix(self, sess):
        """Deterministic shared prompt head per session (its "system
        prompt") — same session, same head, every trace."""
        if self.prefix_len <= 0:
            return []
        rng = np.random.RandomState(
            (self.seed * 1_000_003 + sess) % (2 ** 31 - 1))
        return [int(x) for x in
                rng.randint(1, self.vocab, size=self.prefix_len)]

    def trace(self, dt=0.1):
        """The full replayable trace: Poisson arrivals in ``dt``-second
        bins against :meth:`rate`, each assigned a zipf-drawn session,
        a mix-drawn workload class, and a seeded prompt.  Pure function
        of the constructor arguments + ``dt``."""
        rng = np.random.RandomState(self.seed)
        names = [m[0] for m in self.mix]
        weights = np.asarray([m[1] for m in self.mix], np.float64)
        weights = weights / weights.sum()
        specs = []
        i = 0
        t = 0.0
        while t < self.horizon_s:
            n = int(rng.poisson(max(self.rate(t), 0.0) * dt))
            for _ in range(n):
                cls = names[int(rng.choice(len(names), p=weights))]
                slo_class, p_span, d_span = _CLASSES[cls]
                sess = int(rng.zipf(self.zipf_a) - 1) % self.n_sessions
                head = self._session_prefix(sess)
                budget = self.s_max - len(head)
                p_lo, p_hi = p_span
                lo = max(2, int(budget * p_lo))
                hi = max(lo + 1, int(budget * p_hi))
                n_prompt = int(rng.randint(lo, hi))
                d_lo, d_hi = d_span
                lo = max(1, int(budget * d_lo))
                hi = max(lo + 1, int(budget * d_hi))
                n_new = int(rng.randint(lo, hi))
                # clamp the pair into the sequence budget (prompt wins:
                # a longctx request is DEFINED by its prompt)
                n_prompt = min(n_prompt, budget - 1)
                n_new = min(n_new, budget - n_prompt)
                body = [int(x) for x in
                        rng.randint(1, self.vocab, size=n_prompt)]
                specs.append(TrafficSpec(
                    t=round(t + float(rng.uniform(0.0, dt)), 6),
                    workload=cls, prompt=head + body,
                    max_new_tokens=max(n_new, 1), slo_class=slo_class,
                    session_id=f"s{sess}",
                    seed=self.seed * 100_000 + i,
                    request_id=f"tg{self.seed}-{i}"))
                i += 1
            t += dt
        specs.sort(key=lambda s: (s.t, s.request_id))
        return specs

    def describe(self):
        """JSON-able provenance block for bench artifacts."""
        return {
            "seed": self.seed, "horizon_s": self.horizon_s,
            "base_rps": self.base_rps, "peak_rps": self.peak_rps,
            "cycle_s": self.cycle_s, "n_sessions": self.n_sessions,
            "zipf_a": self.zipf_a, "flash": list(self.flash),
            "mix": {k: v for k, v in self.mix},
            "prefix_len": self.prefix_len,
        }


def replay(router, specs, *, step_s=0.02, tail_s=0.0):
    """Play a trace into a router against a VIRTUAL clock: all specs
    due by the clock are submitted, then one ``router.step()`` advances
    the clock ``step_s``.  Runs until every submitted request retires
    (plus ``tail_s`` more virtual seconds of idle stepping — long
    enough for a scale-down to show, when an autoscaler rides the
    router).  Returns ``(results, report)``: results by request id and
    ``{"shed": [rids], "rejected": [rids], "steps": n}``.  A hard
    QueueFull submit is retried once after a step; a second refusal is
    recorded as rejected (never admitted — not a loss)."""
    specs = sorted(specs, key=lambda s: (s.t, s.request_id))
    out = {}
    shed, rejected = [], []
    vt = 0.0
    steps = 0
    i = 0
    horizon = (specs[-1].t if specs else 0.0) + float(tail_s)
    while i < len(specs) or router.pending or vt <= horizon:
        while i < len(specs) and specs[i].t <= vt:
            sp = specs[i]
            i += 1
            try:
                router.submit(sp.to_request())
                continue
            except RouterShed:
                shed.append(sp.request_id)
                continue
            except QueueFull:
                pass
            for res in router.step():
                out[res.request_id] = res
            steps += 1
            try:
                router.submit(sp.to_request())
            except QueueFull:   # RouterShed included: still full
                rejected.append(sp.request_id)
        for res in router.step():
            out[res.request_id] = res
        steps += 1
        vt += step_s
    return out, {"shed": shed, "rejected": rejected, "steps": steps}
