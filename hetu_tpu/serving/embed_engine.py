"""EmbedServingEngine: batched low-latency recommendation scoring over
the HET embedding cache (the second production workload ROADMAP item 4
calls for — the one the serving substrate went model-agnostic for).

Requests carry ``(user_ids, item_ids, dense_features)`` instead of a
token prompt.  The engine runs in WAVES: each step claims up to
``wave`` queued requests, gathers every embedding row they need through
:class:`~hetu_tpu.cache.cstable.CacheSparseTable` — cache hits are
served locally, misses sparse-pull from the PS (int8 on the wire under
``HETU_PS_QUANT``, the EQuARX-motivated byte diet) — then scores the
whole wave in ONE jitted dense-tower forward, bucket-padded so repeat
wave sizes reuse the compile.  Towers are pure-jax twins of the graph
builders in ``models/ctr.py`` / ``models/ncf.py`` (same param names,
same math), so a PS checkpoint trained by the hybrid path serves
as-is.

Degradation mirrors training exactly, because it IS the training
cache: through a PS outage the cstable serves stale rows within its
staleness budget, unfetchable rows come back as zero vectors (the
standard missing-embedding fallback, never inserted), and the engine
keeps answering — zero request loss, chaos-tested with a mid-trace PS
kill.  Hit-rate / staleness / pull-bytes ride the telemetry registry
(``cache.*`` gauges) next to the serve stream.

Lifecycle telemetry is the GPT engine's vocabulary with the KV phases
replaced by ``gather``/``forward`` (serving/metrics.py
EmbedServingMetrics): submit -> queue -> gather -> forward -> retire,
one req_span per phase, serve_admit/serve_finish pairing intact so
``hetu_trace --check`` span balance, ``hetu_top`` (workload column
"embed"), the SLO monitor, and the fleet router all work unmodified.

Quickstart::

    from hetu_tpu.serving import EmbedServingEngine, EmbedRequest
    eng = EmbedServingEngine(params, tables={"snd_order_embedding": t},
                             model="wdl", embedding_size=8)
    eng.submit(EmbedRequest(item_ids=sparse[i], dense_features=dense[i]))
    results = eng.run()           # {request_id: EmbedResult}
"""

from __future__ import annotations

import collections
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import envvars
from ..telemetry import flight
from ..telemetry import slo as slo_mod
from .engine import QueueFull, _STORM_REJECTS
from .kv_manager import round_up_pow2
from .metrics import EmbedServingMetrics
from .request import EmbedRequest, EmbedResult

# sparse/dense field counts of the Criteo towers (models/ctr.py)
CRITEO_SPARSE_FIELDS = 26
CRITEO_DENSE_FIELDS = 13


# ------------------------------------------------------------------- #
# pure-jax dense towers — numerically the graph builders in
# models/ctr.py / models/ncf.py with the embedding lookup factored out
# (the cache owns it); param names match the builders so a PS
# checkpoint maps 1:1
# ------------------------------------------------------------------- #

def _mlp_tower(x, params):
    """The shared W1->W2->W3 relu tower of wdl_criteo/dcn_criteo:
    y3 = relu(relu(x @ W1) @ W2) @ W3 (no activation after W3)."""
    y = jax.nn.relu(x @ params["W1"])
    y = jax.nn.relu(y @ params["W2"])
    return y @ params["W3"]


def wdl_forward(params, sparse_emb, dense):
    """wdl_criteo minus lookup: sparse_emb [B, 26*E], dense [B, 13]."""
    y3 = _mlp_tower(dense, params)
    y = jnp.concatenate([sparse_emb, y3], axis=1) @ params["W4"]
    return jax.nn.sigmoid(y)[:, 0]


def dcn_forward(params, sparse_emb, dense, num_cross_layers=3):
    """dcn_criteo minus lookup: cross network over x = [sparse|dense]
    plus the shared MLP tower, fused by W4."""
    x = jnp.concatenate([sparse_emb, dense], axis=1)
    cross = x
    for i in range(num_cross_layers):
        x1w = cross @ params[f"cross{i}_weight"]          # [B, 1]
        cross = x * x1w + cross + params[f"cross{i}_bias"]
    y3 = _mlp_tower(x, params)
    y = jnp.concatenate([cross, y3], axis=1) @ params["W4"]
    return jax.nn.sigmoid(y)[:, 0]


def ncf_forward(params, user_latent, item_latent, embed_dim,
                n_mlp_layers):
    """neural_mf minus lookup: GMF product of the first ``embed_dim``
    factors + MLP over the rest, fused by W{len(layers)}."""
    gmf = user_latent[:, :embed_dim] * item_latent[:, :embed_dim]
    x = jnp.concatenate([user_latent[:, embed_dim:],
                         item_latent[:, embed_dim:]], axis=1)
    for i in range(1, n_mlp_layers):
        x = jax.nn.relu(x @ params[f"W{i}"])
    y = jnp.concatenate([gmf, x], axis=1) @ params[f"W{n_mlp_layers}"]
    return jax.nn.sigmoid(y)[:, 0]


class _WaveSlots:
    """Duck-typed stand-in for the KV-manager surface the fleet tier
    reads off an engine (Replica.live/occupancy, the router's capacity
    probe).  Waves complete synchronously inside step(), so nothing is
    ever "live" between steps; ``s_max`` is None — embedding requests
    have no sequence bound (RequestCore.capacity_tokens)."""

    def __init__(self, n_slots):
        self.n_slots = int(n_slots)
        self.s_max = None

    def live(self):
        return []


class EmbedServingEngine:
    """Continuous-wave embedding inference over one or two
    CacheSparseTables plus a jitted dense tower.

    ``params``: dict of tower weights (numpy/jax arrays) named like the
    graph builders (W1..W4 + cross{i}_* for CTR, W1..Wn for NCF).
    ``tables``: name -> CacheSparseTable; ``"snd_order_embedding"``
    for wdl/dcn, ``"user_embed"`` + ``"item_embed"`` for ncf.
    ``model``: "wdl" | "dcn" | "ncf".  ``wave``/``queue_limit`` default
    from ``HETU_EMBED_WAVE``/``HETU_EMBED_QUEUE``; ``slo`` wires an
    SLOMonitor exactly like ServingEngine (env-declared by default).
    """

    def __init__(self, params, tables, model="wdl", *,
                 embedding_size=None, embed_dim=8,
                 mlp_layers=(64, 32, 16, 8), num_cross_layers=3,
                 wave=None, queue_limit=None, slo=None, tags=None,
                 log_path=None):
        if model not in ("wdl", "dcn", "ncf"):
            raise ValueError(
                f"model must be 'wdl', 'dcn' or 'ncf', got {model!r}")
        self.model = model
        self.tables = dict(tables)
        need = (("user_embed", "item_embed") if model == "ncf"
                else ("snd_order_embedding",))
        for name in need:
            if name not in self.tables:
                raise ValueError(
                    f"model {model!r} needs table {name!r}; got "
                    f"{sorted(self.tables)}")
        self.params = {k: jnp.asarray(v, jnp.float32)
                       for k, v in params.items()}
        if model == "ncf":
            self.embed_dim = int(embed_dim)
            self.n_mlp_layers = len(mlp_layers)
        else:
            self.embedding_size = int(
                embedding_size if embedding_size is not None
                else self.tables["snd_order_embedding"].width)
            self.num_cross_layers = int(num_cross_layers)
        self.wave = int(wave if wave is not None
                        else envvars.get_int("HETU_EMBED_WAVE"))
        self.queue_limit = int(
            queue_limit if queue_limit is not None
            else envvars.get_int("HETU_EMBED_QUEUE"))
        self._queue = collections.deque()
        self.metrics = EmbedServingMetrics(log_path, tags=tags)
        # optional fn(request, slot) called at retirement — same seam
        # the router's GPT engines expose
        self.retire_hook = None
        if isinstance(slo, slo_mod.SLOMonitor):
            self.slo = slo
            self.slo.emit_fn = self.metrics.event
        elif slo is not None:
            self.slo = slo_mod.SLOMonitor(slo,
                                          emit_fn=self.metrics.event)
        else:
            self.slo = slo_mod.SLOMonitor.from_env(
                emit_fn=self.metrics.event)
        self._reject_streak = 0
        self.kv = _WaveSlots(self.wave)
        self.steps = 0
        self.peak_live = 0
        self._fwd_cache = {}        # row bucket -> jitted forward
        # live weight sync: version of the resident tower params
        # (None = unversioned); waves are atomic, so every result of a
        # wave carries the one version it scored under
        self.weight_version = None
        self.last_swap_at = None

    # ------------------------------------------------------------- #
    # live weight sync (serving/weight_sync.py)
    # ------------------------------------------------------------- #

    def set_weight_version(self, version):
        """Stamp the current params; rides ``metrics.tags`` so every
        serve event carries ``weight_version``."""
        self.weight_version = int(version)
        self.metrics.tags["weight_version"] = self.weight_version

    def swap_params(self, params, *, version=None):
        """Replace the tower params between waves (the rolling-swap
        primitive; the jitted forwards take params as arguments, so no
        recompile).  Key-set and shapes must match the resident dict —
        a corrupt push fails here, before anything moves.  Call only on
        a drained engine (``pending == 0``)."""
        new = {}
        for k, v in params.items():
            p = jnp.asarray(v, jnp.float32)
            old = self.params.get(k)
            if old is not None and tuple(p.shape) != tuple(old.shape):
                raise ValueError(
                    f"swap_params: {k} has shape {tuple(p.shape)}, "
                    f"resident is {tuple(old.shape)}")
            new[k] = p
        if set(new) != set(self.params):
            missing = sorted(set(self.params) - set(new))
            extra = sorted(set(new) - set(self.params))
            raise ValueError(
                f"swap_params key mismatch: missing {missing[:4]}, "
                f"unexpected {extra[:4]}")
        self.params = new
        self.last_swap_at = time.perf_counter()
        if version is not None:
            self.set_weight_version(version)
        self.metrics.event("weight_swap", version=self.weight_version)

    # ------------------------------------------------------------- #

    def submit(self, request):
        """Enqueue an EmbedRequest; raises QueueFull at ``queue_limit``
        pending admissions (same bounded-queue backpressure + storm
        flight-dump contract as the GPT engine).  Returns the
        request."""
        req = request
        if not isinstance(req, EmbedRequest):
            raise TypeError(
                f"EmbedServingEngine serves EmbedRequest, got "
                f"{type(req).__name__}")
        if len(self._queue) >= self.queue_limit:
            self.metrics.record_reject(req.request_id, len(self._queue))
            self._reject_streak += 1
            if self._reject_streak == _STORM_REJECTS:
                # once per storm: the streak resets on the next accept
                flight.RECORDER.dump(
                    "queue_storm", rejects=self._reject_streak,
                    queue_depth=len(self._queue),
                    queue_limit=self.queue_limit)
            raise QueueFull(
                f"admission queue at capacity ({self.queue_limit})")
        self._reject_streak = 0
        req.submitted_at = time.perf_counter()
        self._queue.append(req)
        self.metrics.record_submit(req.request_id, len(self._queue))
        return req

    @property
    def pending(self):
        """Requests not yet scored (waves retire synchronously, so
        this is the queue)."""
        return len(self._queue)

    @property
    def queue_depth(self):
        return len(self._queue)

    # ------------------------------------------------------------- #

    def step(self):
        """One scoring wave: claim up to ``wave`` queued requests,
        gather their embedding rows through the cache, run ONE jitted
        tower forward over the bucket-padded wave, retire everything.
        Returns the EmbedResults.  An escaping exception dumps the
        flight recorder first (same black-box contract as the GPT
        engine)."""
        try:
            return self._step_wave()
        except QueueFull:
            raise
        except Exception as e:   # noqa: BLE001 — dump-and-reraise
            flight.RECORDER.dump(
                "engine_exception",
                error=f"{type(e).__name__}: {e}"[:200],
                step=self.steps, live=0,
                queue_depth=len(self._queue))
            raise

    def _claim_wave(self):
        reqs = []
        while self._queue and len(reqs) < self.wave:
            req = self._queue.popleft()
            self.metrics.lc_claimed(req.request_id)
            reqs.append(req)
        return reqs

    def _step_wave(self):
        reqs = self._claim_wave()
        if not reqs:
            return []
        self.peak_live = max(self.peak_live, len(reqs))
        t_wave = time.perf_counter()
        rids = [r.request_id for r in reqs]
        rows = sum(r.n_pairs for r in reqs)

        # ---- gather: every embedding row the wave needs, through the
        # cache (hit = local, miss = PS pull, outage = stale/zero) ----
        hits0, total0 = self._cache_counts()
        t_g = time.perf_counter()
        if self.model == "ncf":
            users = np.concatenate([r.user_ids for r in reqs])
            items = np.concatenate([r.item_ids.reshape(-1)
                                    for r in reqs])
            u_lat = self.tables["user_embed"].embedding_lookup(users)
            i_lat = self.tables["item_embed"].embedding_lookup(items)
            gathered = (u_lat.astype(np.float32),
                        i_lat.astype(np.float32))
        else:
            sparse_ids = np.concatenate(
                [r.item_ids.reshape(r.n_pairs, -1) for r in reqs])
            emb = self.tables["snd_order_embedding"].embedding_lookup(
                sparse_ids)
            gathered = (np.asarray(emb, np.float32).reshape(
                rows, -1),)
            dense = np.concatenate(
                [np.zeros((r.n_pairs, CRITEO_DENSE_FIELDS), np.float32)
                 if r.dense_features is None else r.dense_features
                 for r in reqs])
        gather_s = time.perf_counter() - t_g
        hits1, total1 = self._cache_counts()
        d_total = total1 - total0
        hit_rate = (hits1 - hits0) / d_total if d_total else 1.0
        self.metrics.record_gather(len(reqs), rows, gather_s, hit_rate,
                                   requests=rids)

        # ---- forward: one jitted call over the pow2-padded wave ----
        bucket = round_up_pow2(rows)
        if self.model == "ncf":
            u_pad = self._pad(gathered[0], bucket)
            i_pad = self._pad(gathered[1], bucket)
            scores = self._forward(bucket)(self.params, u_pad, i_pad)
        else:
            s_pad = self._pad(gathered[0], bucket)
            d_pad = self._pad(dense, bucket)
            scores = self._forward(bucket)(self.params, s_pad, d_pad)
        scores = np.asarray(jax.block_until_ready(scores))[:rows]
        wave_s = time.perf_counter() - t_wave

        # ---- retire: scores land for every participant at once ----
        results = []
        now = time.perf_counter()
        offset = 0
        for slot, req in enumerate(reqs):
            s = scores[offset:offset + req.n_pairs].copy()
            offset += req.n_pairs
            req.first_token_at = now
            ttft = now - req.submitted_at
            self.metrics.record_admit(
                req.request_id, slot,
                queue_wait_s=max(t_wave - req.submitted_at, 0.0),
                ttft_s=ttft)
            res = EmbedResult(
                request_id=req.request_id, scores=s,
                n_pairs=req.n_pairs, finish_reason="scored",
                ttft_s=ttft, latency_s=ttft, slot=slot,
                cache_hit_rate=hit_rate,
                weight_version=self.weight_version)
            self.metrics.record_finish(req.request_id, "scored",
                                       req.n_pairs, ttft)
            self.slo.observe(request_id=req.request_id,
                             ttft_ms=ttft * 1e3, tok_s=None)
            if self.retire_hook is not None:
                self.retire_hook(req, slot)
            results.append(res)
        self.metrics.record_step(
            live=len(reqs), slots=self.wave,
            queue_depth=len(self._queue), dt_s=wave_s, rows=rows,
            gather_s=gather_s, step=self.steps, requests=rids)
        self.steps += 1
        return results

    def run(self, requests=()):
        """Submit ``requests`` then step until the queue drains;
        returns {request_id: EmbedResult}."""
        for r in requests:
            self.submit(r)
        out = {}
        while self.pending:
            for res in self.step():
                out[res.request_id] = res
        return out

    # ------------------------------------------------------------- #

    def _cache_counts(self):
        hits = total = 0
        for t in self.tables.values():
            c = t.cache.counters()
            hits += c["hits"]
            total += c["hits"] + c["misses"]
        return hits, total

    @staticmethod
    def _pad(arr, bucket):
        if len(arr) == bucket:
            return arr
        pad = np.zeros((bucket - len(arr), arr.shape[1]), arr.dtype)
        return np.concatenate([arr, pad])

    def _forward(self, bucket):
        """The wave's jitted tower, cached per row bucket (pow2
        padding keeps the compile count logarithmic in wave size)."""
        fn = self._fwd_cache.get(bucket)
        if fn is None:
            if self.model == "wdl":
                fn = jax.jit(wdl_forward)
            elif self.model == "dcn":
                n = self.num_cross_layers
                fn = jax.jit(
                    lambda p, s, d: dcn_forward(p, s, d,
                                                num_cross_layers=n))
            else:
                ed, nl = self.embed_dim, self.n_mlp_layers
                fn = jax.jit(
                    lambda p, u, i: ncf_forward(p, u, i, ed, nl))
            self._fwd_cache[bucket] = fn
        return fn

    def cache_summary(self):
        """Per-table CacheSparseTable.perf_summary() (hit rate,
        pull bytes, staleness, outage counters) — the engine's
        dashboard feed, no private counters."""
        return {name: t.perf_summary()
                for name, t in self.tables.items()}

    def health(self):
        """The admission signal: the SLO monitor's worst-burn state
        ("ok" / "degraded" / "breach"), same contract as
        ServingEngine.health()."""
        return self.slo.health()
