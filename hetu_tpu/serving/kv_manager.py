"""Slot-structured KV-cache management for continuous batching.

One preallocated ``[L, B_slots, S_max, H, Dh]`` cache pair (k and v)
lives on device for the engine's lifetime; this manager owns the pair
plus the host-side slot bookkeeping: a free list, per-slot filled
lengths, and the owner map.  Slots are the unit of admission — a
sequence holds one row from prefill to retirement, then the row is
recycled (numerically safe: attention masks to each slot's own filled
prefix, and every position is rewritten before the mask admits it).

Shapes are BUCKETED to powers of two (``B_slots`` and ``S_max``
independently) so engines configured for nearby workloads land on the
same jit cache entries — the compile cache stays bounded by the ladder,
not by the number of distinct deployment configs.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def round_up_pow2(n, floor=1):
    """Smallest power of two >= max(n, floor)."""
    n = max(int(n), int(floor))
    return 1 << (n - 1).bit_length()


class KVCacheManager:
    """Free-slot allocator over one preallocated cache pair.

    layers/heads/head_dim: model shape; slots: requested concurrent
    sequences (bucketed up to a power of two); max_seq_len: longest
    prompt+generation to admit (bucketed, then capped at ``pos_cap`` —
    the model's max_position_embeddings, since the position table can't
    index past it); dtype: cache dtype (follow the weights: bf16 halves
    the cache).  Memory: L*B*S*H*Dh * itemsize * 2.
    """

    def __init__(self, *, layers, heads, head_dim, slots, max_seq_len,
                 pos_cap=None, dtype=jnp.float32, bucket=True):
        if bucket:
            slots = round_up_pow2(slots)
            s = round_up_pow2(max_seq_len, floor=16)
        else:
            s = int(max_seq_len)
        if pos_cap is not None:
            s = min(s, int(pos_cap))
        if s < max_seq_len:
            raise ValueError(
                f"max_seq_len={max_seq_len} exceeds the position-table "
                f"cap {pos_cap}")
        self.n_slots = int(slots)
        self.s_max = int(s)
        self.cache_k = jnp.zeros(
            (layers, self.n_slots, self.s_max, heads, head_dim), dtype)
        self.cache_v = jnp.zeros_like(self.cache_k)
        self._free = list(range(self.n_slots))
        self.lengths = np.zeros(self.n_slots, np.int32)
        self.owner = [None] * self.n_slots
        self.total_allocs = 0

    @property
    def free_slots(self):
        return len(self._free)

    @property
    def occupancy(self):
        return 1.0 - len(self._free) / self.n_slots

    def live(self):
        """Slot indices currently holding a sequence (ascending)."""
        return [i for i in range(self.n_slots) if self.owner[i] is not None]

    def bucket_prompt(self, p):
        """Prompt-length bucket for the prefill scan: pow2, floor 8,
        capped at S_max — a handful of prefill compiles serves every
        prompt length."""
        return min(round_up_pow2(p, floor=8), self.s_max)

    def alloc(self, owner, length):
        """Claim a free slot for ``owner`` whose prompt fills ``length``
        positions; returns the slot index or None when full."""
        if length > self.s_max:
            raise ValueError(
                f"sequence length {length} exceeds S_max {self.s_max}")
        if not self._free:
            return None
        slot = self._free.pop()
        self.owner[slot] = owner
        self.lengths[slot] = length
        self.total_allocs += 1
        return slot

    def advance(self, slot, n=1):
        """Record ``n`` more filled positions in ``slot``."""
        self.lengths[slot] += n

    def release(self, slot):
        """Return a retired sequence's slot to the free list (its cache
        rows are left as-is — recycled content is masked/overwritten)."""
        if self.owner[slot] is None:
            raise ValueError(f"slot {slot} is already free")
        self.owner[slot] = None
        self.lengths[slot] = 0
        self._free.append(slot)
