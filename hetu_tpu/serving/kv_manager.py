"""KV-cache management for continuous batching: the slot-contiguous
reference layout and the block-table paged layout.

``KVCacheManager`` is the original design: one preallocated
``[L, B_slots, S_max, H, Dh]`` cache pair, one contiguous row per
admitted sequence — every sequence pays for ``S_max`` positions no
matter how short it is, and identical system prompts are stored once
PER SLOT.  It remains the off-TPU reference (and the layout offline
``generate_fast`` uses).

``PagedKVManager`` is the production layout: a fixed pool of
``[L, N_blocks, block, H, Dh]`` KV blocks with a free list, a
per-request BLOCK TABLE mapping sequence positions to pool blocks, and
refcounted copy-on-write prefix sharing keyed by a prompt-prefix hash —
N requests with the same system prompt reference its KV blocks once.
Concurrent sequences per HBM byte become a function of *actual* tokens
held (prompt + generation, shared prefixes amortized) instead of the
worst-case ``S_max``, which is the number that caps serving occupancy.

Shapes are BUCKETED to powers of two (``B_slots`` and ``S_max``
independently) so engines configured for nearby workloads land on the
same jit cache entries — the compile cache stays bounded by the ladder,
not by the number of distinct deployment configs.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .. import envvars, quant, telemetry


def round_up_pow2(n, floor=1):
    """Smallest power of two >= max(n, floor)."""
    n = max(int(n), int(floor))
    return 1 << (n - 1).bit_length()


def _bucket_prompt(p, s_max, pos_cap):
    """Prompt-length bucket for prefill compiles: pow2, floor 8, capped
    at BOTH ``s_max`` and the model's position table ``pos_cap``.  The
    pos_cap clamp is load-bearing: when ``s_max`` was capped to a
    non-pow2 position-table size, the pow2 round-up alone could pad a
    prompt past the positions the wpe table can index (silent clamp =
    wrong embeddings), so the bucket must never exceed the cap."""
    b = min(round_up_pow2(p, floor=8), int(s_max))
    if pos_cap is not None:
        b = min(b, int(pos_cap))
    return b


def assemble_mixed_wave(n_slots, entries, q_floor=1):
    """Pack per-slot ragged q-blocks into ONE padded mixed-wave
    descriptor (the `$HETU_SERVE_RAGGED` hot loop).

    ``entries`` maps slot -> ``(tokens, pos, first_row, self_fresh)``:

    * ``tokens``     the slot's q-block this step — a full prompt, a
                     prompt chunk, ``[cur] + draft`` for spec-verify,
                     or ``[cur]`` for plain decode (len >= 1);
    * ``pos``        cache position of ``tokens[0]``;
    * ``first_row``  index of the first row whose rng stream splits
                     (== ``len(tokens)`` for mid-prompt chunks that
                     sample nothing);
    * ``self_fresh`` True when the q-block's own K/V must be read
                     through the two-part fresh-self softmax (paged
                     prompt chunks) rather than the written cache.

    Width is bucketed to a power of two so waves with nearby shapes
    land on the same jit entry.  Slots absent from ``entries`` ride
    along inactive (``q_len = 0``): the kernel masks their attention
    and their clipped writes land on dead positions, same as free
    slots in the phase-split decode wave.
    """
    width = max((len(t) for t, *_ in entries.values()), default=1)
    q = round_up_pow2(width, floor=q_floor)
    tokens = np.zeros((n_slots, q), np.int32)
    pos = np.zeros(n_slots, np.int32)
    q_len = np.zeros(n_slots, np.int32)
    first_row = np.zeros(n_slots, np.int32)
    self_fresh = np.zeros(n_slots, bool)
    for s, (toks, p, fr, fresh) in entries.items():
        n = len(toks)
        tokens[s, :n] = toks
        pos[s] = p
        q_len[s] = n
        first_row[s] = fr
        self_fresh[s] = fresh
    return {
        "q": q,
        "tokens": tokens,
        "pos": pos,
        "q_len": q_len,
        "first_row": first_row,
        "self_fresh": self_fresh,
    }


def _is_int8(dtype):
    """True when ``dtype`` selects the quantized int8 cache layout
    (the string sentinel "int8" or jnp.int8 itself)."""
    if dtype is None:
        return False
    if isinstance(dtype, str):
        return dtype.strip().lower() == "int8"
    try:
        return jnp.dtype(dtype) == jnp.int8
    except TypeError:
        return False


def resolve_kv_quant(kv_quant=None, dtype=None):
    """Serving KV quantization selection shared by the engine and
    bench: an explicit ``kv_quant`` ("int8"/None) wins, then an int8
    ``dtype``, then ``$HETU_KV_QUANT``.  Returns "int8" or None."""
    if _is_int8(dtype):
        return "int8"
    return quant.resolve_quant(kv_quant, "HETU_KV_QUANT")


def _alloc_cache(shape, dtype, quantized):
    """One cache array — or, quantized, the ``(int8 data, f32 scales)``
    pair with one scale per (layer, slot/block, position, head): the
    payload keeps ``shape``, the scales drop the head_dim axis.  The
    pair is a pytree, so it threads through the jitted decode/prefill
    functions (and their donation) exactly like a plain array."""
    if quantized:
        return (jnp.zeros(shape, jnp.int8),
                jnp.zeros(shape[:-1], jnp.float32))
    return jnp.zeros(shape, dtype)


def cache_nbytes(cache):
    """HBM bytes of a cache value (plain array or quantized pair)."""
    if isinstance(cache, (tuple, list)):
        return sum(int(a.nbytes) for a in cache)
    return int(cache.nbytes)


def resolve_handoff_quant(mode=None):
    """Replica-to-replica KV handoff WIRE selection.  "auto" (default,
    ``$HETU_HANDOFF_QUANT``) ships the pool's native bytes — an int8
    pool's (payload, scales) pair already IS the cheap wire, an exact
    pool ships exact; "int8" forces an exact (f32/bf16) pool's export
    through the per-head codec (:func:`quant.kv_encode`, ~4x fewer
    bytes, small quantization error); "0"/"off" pins the exact wire.
    Returns "auto", "int8", or None."""
    if mode is None:
        mode = envvars.get_str("HETU_HANDOFF_QUANT")
    s = str(mode).strip().lower() if mode is not None else "auto"
    if s in ("", "auto"):
        return "auto"
    if s in ("0", "off", "none", "false"):
        return None
    if s == "int8":
        return "int8"
    raise ValueError(f"unknown handoff quant mode {mode!r} "
                     "(expected 'auto', 'int8', or 'off')")


def _wire_repr(gathered, pool_quant, mode):
    """Resolve one exported cache value to its wire form.  Returns
    (value, wire_quant) where ``value`` is an exact host array or an
    (int8, scales) pair and ``wire_quant`` is "int8" or None."""
    if pool_quant:                      # native pair is already int8
        return gathered, "int8"
    if mode == "int8":
        q, s = quant.kv_encode(jnp.asarray(np.asarray(gathered,
                                                      np.float32)))
        return (np.asarray(q), np.asarray(s)), "int8"
    return gathered, None


def _wire_to_pool(wire, wire_quant, pool_cache):
    """Convert a wire value into the destination pool's representation:
    (q, scales) for an int8 pool, an array in the pool dtype otherwise.
    Requantizing an exact wire / dequantizing an int8 wire as needed —
    so handoffs compose across mixed-precision fleets."""
    if isinstance(pool_cache, (tuple, list)):           # int8 pool
        if wire_quant:
            q, s = wire
        else:
            q, s = quant.kv_encode(jnp.asarray(np.asarray(wire,
                                                          np.float32)))
        return jnp.asarray(q, jnp.int8), jnp.asarray(s, jnp.float32)
    if wire_quant:
        vals = quant.kv_decode(jnp.asarray(wire[0]), jnp.asarray(wire[1]))
    else:
        vals = jnp.asarray(np.asarray(wire))
    return vals.astype(pool_cache.dtype)


def resolve_kv_block(paged=None, block=None):
    """Paged-layout selection shared by the engine and bench: returns
    the block size in tokens (0 = slot-contiguous layout).  An explicit
    ``block`` wins; else ``$HETU_KV_BLOCK`` ("0" pins contiguous, an
    integer enables paging at that block size, "auto" = paged with
    block 16 on TPU, contiguous elsewhere — mirroring the
    ``$HETU_SERVE_FAST`` convention).  ``paged=True`` forces paging
    (default block 16), ``paged=False`` forces contiguous."""
    if paged is False:
        return 0
    if block is None:
        raw = str(envvars.get_str("HETU_KV_BLOCK") or "auto").strip().lower()
        if raw in ("auto", ""):
            block = 16 if (paged or jax.default_backend() == "tpu") else 0
        else:
            block = int(raw)
    block = int(block)
    if paged and block <= 0:
        block = 16
    return max(block, 0)


class KVCacheManager:
    """Free-slot allocator over one preallocated cache pair.

    layers/heads/head_dim: model shape; slots: requested concurrent
    sequences (bucketed up to a power of two); max_seq_len: longest
    prompt+generation to admit (bucketed, then capped at ``pos_cap`` —
    the model's max_position_embeddings, since the position table can't
    index past it); dtype: cache dtype — follow the weights (the engine
    passes its param dtype, so bf16 params mean a bf16 cache), or
    "int8"/jnp.int8 for the QUANTIZED layout: an int8 payload with one
    f32 scale per (layer, slot, position, head), ~3.7x more tokens per
    HBM byte, dequantized inside the decode kernels.  Memory:
    L*B*S*H*Dh * itemsize * 2 (+ the scale planes when quantized).
    """

    def __init__(self, *, layers, heads, head_dim, slots, max_seq_len,
                 pos_cap=None, dtype=jnp.float32, bucket=True):
        if bucket:
            slots = round_up_pow2(slots)
            s = round_up_pow2(max_seq_len, floor=16)
        else:
            s = int(max_seq_len)
        if pos_cap is not None:
            s = min(s, int(pos_cap))
        if s < max_seq_len:
            raise ValueError(
                f"max_seq_len={max_seq_len} exceeds the position-table "
                f"cap {pos_cap}")
        self.n_slots = int(slots)
        self.s_max = int(s)
        self.pos_cap = int(pos_cap) if pos_cap is not None else self.s_max
        self.quant = "int8" if _is_int8(dtype) else None
        shape = (layers, self.n_slots, self.s_max, heads, head_dim)
        self.cache_k = _alloc_cache(shape, dtype, self.quant)
        self.cache_v = _alloc_cache(shape, dtype, self.quant)
        self._free = list(range(self.n_slots))
        self.lengths = np.zeros(self.n_slots, np.int32)
        self.owner = [None] * self.n_slots
        self.total_allocs = 0

    @property
    def free_slots(self):
        return len(self._free)

    @property
    def occupancy(self):
        return 1.0 - len(self._free) / self.n_slots

    @property
    def cache_bytes(self):
        """Total HBM bytes of the cache pair (scales included when
        quantized) — the equal-bytes denominator every capacity A/B
        uses."""
        return cache_nbytes(self.cache_k) + cache_nbytes(self.cache_v)

    def live(self):
        """Slot indices currently holding a sequence (ascending)."""
        return [i for i in range(self.n_slots) if self.owner[i] is not None]

    def _gauges(self):
        telemetry.set_gauge("serve.occupancy", round(self.occupancy, 4))
        telemetry.set_gauge("serve.slots_free", self.free_slots)

    def bucket_prompt(self, p):
        """Prompt-length bucket for the prefill scan: pow2, floor 8,
        capped at S_max AND the position-table cap — a handful of
        prefill compiles serves every prompt length, and the bucket can
        never index past the wpe table (regression: the pow2 round-up
        used to consult only s_max, which is safe solely because s_max
        itself is capped — the explicit clamp pins the contract)."""
        return _bucket_prompt(p, self.s_max, self.pos_cap)

    def alloc(self, owner, length):
        """Claim a free slot for ``owner`` whose prompt fills ``length``
        positions; returns the slot index or None when full."""
        if length > self.s_max:
            raise ValueError(
                f"sequence length {length} exceeds S_max {self.s_max}")
        if not self._free:
            return None
        slot = self._free.pop()
        self.owner[slot] = owner
        self.lengths[slot] = length
        self.total_allocs += 1
        self._gauges()
        return slot

    def advance(self, slot, n=1):
        """Record ``n`` more filled positions in ``slot``."""
        self.lengths[slot] += n

    def truncate(self, slot, n):
        """Roll ``slot`` back to ``n`` filled positions (speculative-
        decode rejection rollback).  Contiguous rows need only the
        length decrement: positions at or past ``n`` are never admitted
        by the per-slot attention masks and are overwritten in place by
        the next writes at those positions — and a quantized cache's
        scale planes share the position axis, so they truncate in
        lockstep by the same argument."""
        n = int(n)
        if self.owner[slot] is None:
            raise ValueError(f"slot {slot} is free")
        if not 0 <= n <= int(self.lengths[slot]):
            raise ValueError(
                f"cannot truncate slot {slot} to {n} "
                f"(filled {int(self.lengths[slot])})")
        self.lengths[slot] = n

    def release(self, slot):
        """Return a retired sequence's slot to the free list (its cache
        rows are left as-is — recycled content is masked/overwritten)."""
        if self.owner[slot] is None:
            raise ValueError(f"slot {slot} is already free")
        self.owner[slot] = None
        self.lengths[slot] = 0
        self._free.append(slot)
        self._gauges()

    # ------------------------------------------------------------- #
    # replica-to-replica handoff (span export — paged parity)
    # ------------------------------------------------------------- #

    def export_blocks(self, slot, quant_mode=None):
        """Serialize ``slot``'s filled KV span to a host-side payload
        (the contiguous parity of ``PagedKVManager.export_blocks``:
        one dense ``[L, length, H, Dh]`` span per cache instead of a
        block list).  Refcounts don't exist in this layout, so export
        is a pure read.  See the paged docstring for the wire grammar."""
        if self.owner[slot] is None:
            raise ValueError(f"slot {slot} is free")
        length = int(self.lengths[slot])
        mode = resolve_handoff_quant(quant_mode)

        def gather(cache):
            if isinstance(cache, (tuple, list)):
                return tuple(np.asarray(a[:, slot, :length]) for a in cache)
            return np.asarray(cache[:, slot, :length])

        k, kq = _wire_repr(gather(self.cache_k), self.quant, mode)
        v, _ = _wire_repr(gather(self.cache_v), self.quant, mode)
        nbytes = cache_nbytes(k) + cache_nbytes(v)
        shape = (k[0] if isinstance(k, tuple) else k).shape
        raw = 2 * 4 * int(np.prod(shape))        # f32-equivalent bytes
        return {"layout": "contiguous", "length": length,
                "quant": kq, "k": k, "v": v,
                "nbytes": nbytes, "raw_nbytes": raw}

    def import_blocks(self, payload, owner, *, reserve=None):
        """Materialize an exported contiguous span into a fresh slot
        (dequantizing/requantizing the wire into this pool's layout as
        needed).  Returns the slot, or None when slots are short."""
        if payload.get("layout") != "contiguous":
            raise ValueError(
                f"cannot import a {payload.get('layout')!r} payload "
                "into a contiguous manager")
        length = int(payload["length"])
        reserve = length if reserve is None else int(reserve)
        if reserve < length:
            raise ValueError(
                f"reserve {reserve} below payload length {length}")
        slot = self.alloc(owner, reserve)
        if slot is None:
            return None
        self.lengths[slot] = length
        wq = payload["quant"]
        for name in ("cache_k", "cache_v"):
            cache = getattr(self, name)
            vals = _wire_to_pool(payload["k" if name == "cache_k" else "v"],
                                 wq, cache)
            if isinstance(cache, (tuple, list)):
                cache = (cache[0].at[:, slot, :length].set(vals[0]),
                         cache[1].at[:, slot, :length].set(vals[1]))
            else:
                cache = cache.at[:, slot, :length].set(vals)
            setattr(self, name, cache)
        return slot


class _PrefixEntry:
    """One registered prompt prefix: the tokens (collision-proof key
    verification), the pool blocks holding its KV (each refcounted on
    behalf of the cache so they outlive the registering request), and
    an LRU stamp for eviction under pool pressure."""

    __slots__ = ("tokens", "blocks", "length", "used")

    def __init__(self, tokens, blocks, length, used):
        self.tokens = tokens
        self.blocks = blocks
        self.length = length
        self.used = used


class PagedKVManager:
    """Block-pool allocator with per-request block tables.

    The cache pair is ``[L, N_blocks, block, H, Dh]``; a request holds
    ``ceil(tokens / block)`` blocks listed in its slot's block-table
    row, so pool bytes bound the TOKENS held, not slots * S_max.  Block
    id 0 is a permanent scratch block: dead table entries point at it
    and inert slots' ride-along decode writes land in it, so nothing a
    mask admits is ever clobbered.

    Admission RESERVES the request's whole span (prompt +
    max_new_tokens, minus shared prefix blocks) up front, so decode
    waves never allocate and never preempt — the engine requeues an
    admission the pool cannot hold yet (backpressure), and ``submit``
    rejects one it can never hold.

    Prefix sharing (``prefix_share``): completed prompts register their
    blocks keyed by the prompt-token hash; a later request whose prompt
    starts with a registered prefix attaches those blocks refcounted
    instead of recomputing them.  A shared block whose tail the new
    request must overwrite (the prefix ends mid-block) is COPY-ON-WRITE
    forked at admission.  Retirement decrements refcounts and returns a
    block to the free list only at zero; registered prefixes are
    LRU-evicted when the pool runs short.
    """

    def __init__(self, *, layers, heads, head_dim, slots, max_seq_len,
                 pos_cap=None, dtype=jnp.float32, bucket=True,
                 block=16, pool_blocks=None, prefix_share=None):
        if bucket:
            slots = round_up_pow2(slots)
            s = round_up_pow2(max_seq_len, floor=16)
        else:
            s = int(max_seq_len)
        if pos_cap is not None:
            s = min(s, int(pos_cap))
        if s < max_seq_len:
            raise ValueError(
                f"max_seq_len={max_seq_len} exceeds the position-table "
                f"cap {pos_cap}")
        self.n_slots = int(slots)
        self.s_max = int(s)
        self.pos_cap = int(pos_cap) if pos_cap is not None else self.s_max
        self.block = int(block)
        if self.block < 1:
            raise ValueError(f"block size must be >= 1, got {block}")
        # table width: blocks needed for a brim-full sequence
        self.table_width = -(-self.s_max // self.block)
        if pool_blocks is None:
            # contiguous-equivalent capacity (+1 for the scratch block)
            pool_blocks = self.n_slots * self.table_width + 1
        self.n_blocks = int(pool_blocks)
        if self.n_blocks < 2:
            raise ValueError("pool needs at least 2 blocks "
                             "(scratch + one allocatable)")
        if prefix_share is None:
            prefix_share = envvars.get_bool("HETU_KV_PREFIX_SHARE")
        self.prefix_share = bool(prefix_share)
        self.quant = "int8" if _is_int8(dtype) else None
        shape = (layers, self.n_blocks, self.block, heads, head_dim)
        self.cache_k = _alloc_cache(shape, dtype, self.quant)
        self.cache_v = _alloc_cache(shape, dtype, self.quant)
        self._free = list(range(1, self.n_blocks))   # 0 = scratch
        self.ref = np.zeros(self.n_blocks, np.int32)
        self.tables = np.zeros((self.n_slots, self.table_width), np.int32)
        self.n_table = np.zeros(self.n_slots, np.int32)
        self.lengths = np.zeros(self.n_slots, np.int32)
        self.owner = [None] * self.n_slots
        self._free_slots = list(range(self.n_slots))
        self._prefix = {}                            # tokens -> entry
        self._clock = 0
        self.total_allocs = 0
        self.cow_copies = 0
        self.prefix_hits = 0
        self.evictions = 0
        # fleet directory feed: the router's PrefixDirectory wires
        # these so registrations/evictions on THIS replica become
        # fleet-visible hints (None = standalone engine, no directory)
        self.on_prefix_register = None   # fn(tokens, entry)
        self.on_prefix_evict = None      # fn(tokens)
        # tiered KV (serving/kv_tiers.py): eviction-to-tier instead of
        # eviction-to-drop.  The spill hook gets the doomed prefix's
        # wire payload BEFORE its blocks are freed; tier_store is the
        # engine admission path's fetch handle.  Both None = today's
        # drop-on-evict, byte-identical
        self.on_prefix_spill = None      # fn(tokens, payload) -> bool
        self.tier_store = None
        self.spills = 0
        self.prefix_hit_tokens = 0       # recompute tokens saved
        # replica-to-replica handoff accounting
        self.exports = 0
        self.imports = 0
        self.export_bytes = 0
        self.import_bytes = 0

    # ------------------------------------------------------------- #

    @property
    def capacity_blocks(self):
        """Blocks a single request could ever hold (pool minus scratch)."""
        return self.n_blocks - 1

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def free_slots(self):
        return len(self._free_slots)

    @property
    def blocks_shared(self):
        """Blocks referenced by more than one holder (requests and/or
        the prefix cache)."""
        return int(np.sum(self.ref > 1))

    @property
    def cache_bytes(self):
        """Total HBM bytes of the pool pair (scales included when
        quantized)."""
        return cache_nbytes(self.cache_k) + cache_nbytes(self.cache_v)

    @property
    def occupancy(self):
        return 1.0 - len(self._free_slots) / self.n_slots

    def live(self):
        return [i for i in range(self.n_slots) if self.owner[i] is not None]

    def blocks_needed(self, tokens):
        return -(-int(tokens) // self.block)

    def bucket_prompt(self, p):
        """Same contract as ``KVCacheManager.bucket_prompt`` (pos_cap
        clamp included)."""
        return _bucket_prompt(p, self.s_max, self.pos_cap)

    def _gauges(self):
        telemetry.set_gauge("serve.occupancy", round(self.occupancy, 4))
        telemetry.set_gauge("serve.blocks_free", self.free_blocks)
        telemetry.set_gauge("serve.blocks_shared", self.blocks_shared)
        telemetry.set_gauge("serve.prefix_entries", len(self._prefix))

    # ------------------------------------------------------------- #
    # prefix cache
    # ------------------------------------------------------------- #

    def match_prefix(self, prompt):
        """Longest registered prefix of ``prompt`` (token-verified, so
        a hash collision can never attach wrong KV); returns
        (entry, usable_len) or (None, 0).  ``usable_len`` is capped at
        len(prompt) - 1: the LAST prompt position is always recomputed,
        because sampling the first token needs its logits (KV alone is
        not enough)."""
        if not self.prefix_share:
            return None, 0
        p = tuple(int(t) for t in prompt)
        best, best_len = None, 0
        for key, e in self._prefix.items():
            if e.length <= len(p) - 1 and e.length > best_len \
                    and key == p[:e.length]:
                best, best_len = e, e.length
        if best is not None:
            self._clock += 1
            best.used = self._clock
        return best, best_len

    def register_prefix(self, prompt, slot):
        """Register ``slot``'s prompt blocks for future sharing (called
        once the prompt's KV is fully written).  An entry is keyed at
        EVERY full-block boundary of the prompt plus its full length —
        a later prompt sharing only the system-prompt head still finds
        the longest common block run, and one extending this prompt
        verbatim attaches its partial tail block too (COW-forked at
        admission).  The cache takes its own refcount on each block so
        the blocks survive the registering request's retirement."""
        if not self.prefix_share:
            return
        p = tuple(int(t) for t in prompt)
        cuts = {k * self.block
                for k in range(1, len(p) // self.block + 1)}
        cuts.add(len(p))
        for n in sorted(cuts):
            key = p[:n]
            if key in self._prefix:
                self._clock += 1
                self._prefix[key].used = self._clock
                if self.on_prefix_register is not None:
                    # re-registration refreshes the directory's
                    # last-use stamp (TTL staleness tracks real use)
                    self.on_prefix_register(key, self._prefix[key])
                continue
            blocks = [int(b)
                      for b in self.tables[slot, :self.blocks_needed(n)]]
            for b in blocks:
                self.ref[b] += 1
            self._clock += 1
            e = _PrefixEntry(key, blocks, n, self._clock)
            self._prefix[key] = e
            if self.on_prefix_register is not None:
                self.on_prefix_register(key, e)
        self._gauges()

    def _evict_for(self, need, keep=None):
        """LRU-drop registered prefixes until ``need`` blocks are free
        (blocks still referenced by live requests stay allocated)."""
        while len(self._free) < need and self._prefix:
            candidates = [(e.used, k) for k, e in self._prefix.items()
                          if e is not keep]
            if not candidates:
                break
            _, key = min(candidates)
            if self.on_prefix_spill is not None:
                # eviction-to-tier: serialize the doomed prefix while
                # its blocks are still resident (export_prefix is a
                # pure read) and offer it to the tier ladder; a
                # declined spill proceeds as today's drop
                try:
                    payload = self.export_prefix(key, count=False)
                except ValueError:
                    payload = None
                if payload is not None \
                        and self.on_prefix_spill(key, payload):
                    self.spills += 1
                    telemetry.inc("serve.prefix_spills")
            e = self._prefix.pop(key)
            for b in e.blocks:
                self.ref[b] -= 1
                if self.ref[b] == 0:
                    self._free.append(b)
            self.evictions += 1
            telemetry.inc("serve.prefix_evictions")
            if self.on_prefix_evict is not None:
                self.on_prefix_evict(key)

    # ------------------------------------------------------------- #
    # alloc / fork / release
    # ------------------------------------------------------------- #

    def alloc(self, owner, prompt, reserve):
        """Claim a slot plus blocks for a request reserving ``reserve``
        total positions (prompt + max_new_tokens).  Attaches the longest
        registered prefix refcounted, COW-forks a mid-block prefix tail,
        and materializes fresh blocks for the rest of the span.  Returns
        (slot, cached_len) — cached_len prompt positions already hold
        valid KV — or (None, 0) when slots or blocks are short (the
        engine requeues: backpressure, not failure)."""
        if reserve > self.s_max:
            raise ValueError(
                f"sequence length {reserve} exceeds S_max {self.s_max}")
        if not self._free_slots:
            return None, 0
        entry, cached = self.match_prefix(prompt)
        n_shared = cached // self.block          # full shared blocks
        straddle = cached % self.block != 0      # mid-block tail -> COW
        total = self.blocks_needed(reserve)
        need = total - n_shared                  # fork counts as fresh
        if len(self._free) < need:
            self._evict_for(need, keep=entry)
            # eviction may have dropped the matched entry's blocks to
            # ref 0 only if it was not kept — `keep` pins it
            if len(self._free) < need:
                return None, 0
        slot = self._free_slots.pop()
        row = []
        for j in range(n_shared):
            b = entry.blocks[j]
            self.ref[b] += 1
            row.append(b)
        if straddle:
            src = entry.blocks[n_shared]
            dst = self._free.pop()
            self.ref[dst] = 1
            # device-side block copy: the forked block starts as an
            # exact copy of the shared one, then takes private writes
            # (a quantized pool copies payload AND scale planes)
            self.cache_k = self._block_copy(self.cache_k, src, dst)
            self.cache_v = self._block_copy(self.cache_v, src, dst)
            row.append(dst)
            self.cow_copies += 1
            telemetry.inc("serve.cow_copies")
        for _ in range(total - len(row)):
            b = self._free.pop()
            self.ref[b] = 1
            row.append(b)
        self.tables[slot, :] = 0
        self.tables[slot, :len(row)] = row
        self.n_table[slot] = len(row)
        self.owner[slot] = owner
        self.lengths[slot] = cached
        self.total_allocs += 1
        if cached:
            self.prefix_hits += 1
            self.prefix_hit_tokens += cached
            telemetry.inc("serve.prefix_hits")
        self._gauges()
        return slot, cached

    @staticmethod
    def _block_copy(cache, src, dst):
        """Copy pool block ``src`` onto ``dst`` (plain array or the
        quantized (data, scale) pair — both leaves move together so a
        COW fork never mixes one block's payload with another's
        scales)."""
        if isinstance(cache, (tuple, list)):
            return tuple(a.at[:, dst].set(a[:, src]) for a in cache)
        return cache.at[:, dst].set(cache[:, src])

    def advance(self, slot, n=1):
        """Record ``n`` more filled positions (blocks were reserved at
        admission — nothing to allocate)."""
        self.lengths[slot] += n

    def truncate(self, slot, n):
        """Roll ``slot`` back to ``n`` filled positions at refcount
        discipline (speculative-decode rejection rollback).  The slot's
        whole-span reservation is KEPT — a never-speculated replay
        holds the same blocks, so rollback must not shrink it — but any
        reserved block the slot will now REWRITE (covering positions at
        or past ``n``) that is still SHARED (refcount > 1: attached
        from the prefix cache or another request) is detached and
        replaced with a private block: the boundary block still holding
        live positions below ``n`` is copy-on-write FORKED (content
        preserved), wholly-dead trailing blocks are swapped for fresh
        blocks with no copy.  A shared block is NEVER freed here — its
        refcount drops by one and every other holder keeps it.  In the
        engine's speculative path this loop is a no-op (generation
        never writes into a shared block: ``match_prefix`` caps sharing
        below the last prompt position, so every writable block is
        already private), but the discipline holds for any caller.
        Quantized pools move payload and scale planes together
        (``_block_copy``)."""
        if self.owner[slot] is None:
            raise ValueError(f"slot {slot} is free")
        old = int(self.lengths[slot])
        n = int(n)
        if not 0 <= n <= old:
            raise ValueError(
                f"cannot truncate slot {slot} to {n} (filled {old})")
        first_w = n // self.block   # first block future writes touch
        for j in range(first_w, int(self.n_table[slot])):
            b = int(self.tables[slot, j])
            if self.ref[b] <= 1:
                continue
            partial = j == first_w and n % self.block != 0
            if not self._free:
                self._evict_for(1)
            if not self._free:
                raise RuntimeError(
                    f"pool exhausted un-COWing rollback of slot {slot} "
                    f"(block {b} shared at ref {int(self.ref[b])})")
            dst = self._free.pop()
            self.ref[dst] = 1
            self.ref[b] -= 1
            if partial:
                # live positions below n survive in the private fork
                self.cache_k = self._block_copy(self.cache_k, b, dst)
                self.cache_v = self._block_copy(self.cache_v, b, dst)
                self.cow_copies += 1
                telemetry.inc("serve.cow_copies")
            self.tables[slot, j] = dst
        self.lengths[slot] = n
        self._gauges()

    def release(self, slot):
        """Retire a sequence: decrement each held block's refcount and
        free it only at zero — blocks shared with other requests or the
        prefix cache stay resident."""
        if self.owner[slot] is None:
            raise ValueError(f"slot {slot} is already free")
        for j in range(int(self.n_table[slot])):
            b = int(self.tables[slot, j])
            self.ref[b] -= 1
            if self.ref[b] == 0:
                self._free.append(b)
        self.tables[slot, :] = 0
        self.n_table[slot] = 0
        self.owner[slot] = None
        self.lengths[slot] = 0
        self._free_slots.append(slot)
        self._gauges()

    # ------------------------------------------------------------- #
    # replica-to-replica handoff (block export / import)
    # ------------------------------------------------------------- #

    def export_blocks(self, slot, quant_mode=None):
        """Serialize ``slot``'s FILLED blocks to a host-side payload a
        peer replica can :meth:`import_blocks`.  Ships exactly
        ``blocks_needed(length)`` blocks (the filled span, not the
        whole reservation), as ``[L, n, block, H, Dh]`` host arrays —
        or the (int8, scales) pair when the pool is quantized or the
        wire mode forces int8 (:func:`resolve_handoff_quant`), ~4x
        fewer bytes with scale planes moving in lockstep.  A pure
        read: refcounts, tables, and the prefix cache are untouched,
        so COW-shared blocks stay shared on the source."""
        if self.owner[slot] is None:
            raise ValueError(f"slot {slot} is free")
        length = int(self.lengths[slot])
        n = self.blocks_needed(length)
        idx = np.asarray([int(b) for b in self.tables[slot, :n]], np.int32)
        return self._export_span(idx, length, quant_mode)

    def export_prefix(self, tokens, quant_mode=None, *, count=True):
        """Serialize a REGISTERED prefix's blocks to the same wire
        payload as :meth:`export_blocks` — no live slot required (the
        prefix cache holds its own refcounts), which is how a fleet
        moves warmth without a resident request: elastic scale-up
        warming and retirement export (serving/router.py) both ride
        this.  Returns None when the prefix is not registered here (or
        sharing is off).  A pure read."""
        if not self.prefix_share:
            return None
        e = self._prefix.get(tuple(int(t) for t in tokens))
        if e is None:
            return None
        idx = np.asarray([int(b) for b in e.blocks], np.int32)
        return self._export_span(idx, int(e.length), quant_mode,
                                 count=count)

    def _export_span(self, idx, length, quant_mode, *, count=True):
        """Gather pool blocks ``idx`` into the wire payload (shared by
        the slot and prefix export paths).  ``count=False`` keeps the
        gather out of the handoff ledger — the tier-spill path uses it
        so spill bytes don't masquerade as replica-to-replica wire
        traffic (the tier store keeps its own byte counters)."""
        mode = resolve_handoff_quant(quant_mode)

        def gather(cache):
            if isinstance(cache, (tuple, list)):
                return tuple(np.asarray(a[:, idx]) for a in cache)
            return np.asarray(cache[:, idx])

        k, kq = _wire_repr(gather(self.cache_k), self.quant, mode)
        v, _ = _wire_repr(gather(self.cache_v), self.quant, mode)
        nbytes = cache_nbytes(k) + cache_nbytes(v)
        shape = (k[0] if isinstance(k, tuple) else k).shape
        raw = 2 * 4 * int(np.prod(shape))        # f32-equivalent bytes
        if count:
            self.exports += 1
            self.export_bytes += nbytes
            telemetry.inc("serve.kv_export_bytes", nbytes)
        return {"layout": "paged", "block": self.block, "length": length,
                "quant": kq, "k": k, "v": v,
                "nbytes": nbytes, "raw_nbytes": raw}

    def import_blocks(self, payload, owner, *, reserve=None, prompt=None):
        """Materialize an exported span into THIS pool: claims a slot
        plus fresh blocks for ``reserve`` positions (default: the
        payload's filled length), writes the wire blocks (requantizing
        an exact wire into an int8 pool / dequantizing an int8 wire
        into an exact pool as needed), and — given ``prompt`` — re-
        registers the prompt's prefix over the imported blocks so later
        admissions here attach them refcounted (the whole point of a
        prefill→decode handoff).  Returns the slot, or None when slots
        or blocks are short (backpressure, same contract as ``alloc``).
        Block size and layout must match; a mismatch raises."""
        if payload.get("layout") != "paged":
            raise ValueError(
                f"cannot import a {payload.get('layout')!r} payload "
                "into a paged manager")
        if int(payload["block"]) != self.block:
            raise ValueError(
                f"payload block size {payload['block']} != pool block "
                f"size {self.block}")
        length = int(payload["length"])
        reserve = length if reserve is None else int(reserve)
        if reserve < length:
            raise ValueError(
                f"reserve {reserve} below payload length {length}")
        if reserve > self.s_max:
            raise ValueError(
                f"sequence length {reserve} exceeds S_max {self.s_max}")
        if not self._free_slots:
            return None
        n_pay = self.blocks_needed(length)
        total = self.blocks_needed(reserve)
        if len(self._free) < total:
            self._evict_for(total)
            if len(self._free) < total:
                return None
        slot = self._free_slots.pop()
        row = []
        for _ in range(total):
            b = self._free.pop()
            self.ref[b] = 1
            row.append(b)
        dst = np.asarray(row[:n_pay], np.int32)
        wq = payload["quant"]
        for name in ("cache_k", "cache_v"):
            cache = getattr(self, name)
            vals = _wire_to_pool(payload["k" if name == "cache_k" else "v"],
                                 wq, cache)
            if isinstance(cache, (tuple, list)):
                cache = (cache[0].at[:, dst].set(vals[0]),
                         cache[1].at[:, dst].set(vals[1]))
            else:
                cache = cache.at[:, dst].set(vals)
            setattr(self, name, cache)
        self.tables[slot, :] = 0
        self.tables[slot, :len(row)] = row
        self.n_table[slot] = len(row)
        self.owner[slot] = owner
        self.lengths[slot] = length
        self.total_allocs += 1
        self.imports += 1
        self.import_bytes += int(payload["nbytes"])
        telemetry.inc("serve.kv_import_bytes", int(payload["nbytes"]))
        if prompt is not None and len(prompt) <= length:
            self.register_prefix(prompt, slot)
        self._gauges()
        return slot

    # ------------------------------------------------------------- #

    def stats(self):
        """JSON-able pool view (bench/telemetry surface)."""
        return {
            "block": self.block,
            "n_blocks": self.n_blocks,
            "blocks_free": self.free_blocks,
            "blocks_shared": self.blocks_shared,
            "prefix_entries": len(self._prefix),
            "prefix_hits": self.prefix_hits,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "cow_copies": self.cow_copies,
            "evictions": self.evictions,
            "spills": self.spills,
            "exports": self.exports,
            "imports": self.imports,
            "export_bytes": self.export_bytes,
            "import_bytes": self.import_bytes,
            "quant": self.quant or "off",
            "cache_bytes": self.cache_bytes,
        }
