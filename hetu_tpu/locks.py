"""Instrumented locks: the repo's one place threads may synchronize.

Every ``threading.Lock``/``RLock``/``Condition`` in the tree is
constructed HERE (lint rule ``raw-lock`` keeps it that way, the same
way ``env-registry`` keeps the env registry authoritative) as a
``TracedLock``/``TracedRLock``/``TracedCondition`` — API-compatible
wrappers that are plain pass-throughs by default and grow two
sanitizer personalities on demand:

- **Lockdep** (``HETU_LOCKDEP=1``): every acquisition records the
  per-thread held-lock stack into a global lock-ORDER graph keyed by
  lock class name (the string given at construction: ``ps.server``,
  ``cstable``, ...).  A cycle in that graph is a potential deadlock
  even if this run never interleaved into it — reported the moment the
  second edge lands, naming both lock classes and BOTH acquisition
  stacks, appended to :func:`lockdep_violations` and emitted as a
  contract-valid ``lockdep_violation`` telemetry event.  Lockdep also
  flags *blocking work under a lock* — call sites that may stall
  (PS RPC, big ``wire.dumps``) declare themselves via
  :func:`note_blocking` and are flagged when any traced lock is held —
  and feeds a per-lock-class hold-time histogram
  (``lock.hold_ms.<name>``) into the metrics registry;
  ``HETU_LOCKDEP_HOLD_MS > 0`` additionally reports any single hold
  longer than that many milliseconds.  Reentrant ``TracedRLock``
  re-acquires insert no self-edges.

- **Deterministic interleaving fuzz** (``HETU_SCHED_FUZZ=<seed>`` via
  ``analysis/concurrency.run_interleaved``): a seeded cooperative
  scheduler (:class:`InterleaveScheduler`) owns a single run token;
  only threads explicitly REGISTERED with it participate, and at every
  traced acquire/release (plus explicit ``sched_point()`` calls) the
  token holder lets a seeded ``random.Random`` pick the next runnable
  thread.  A blocking acquire under fuzz is a try-acquire loop that
  hands the token away on failure, so the schedule — and therefore any
  race it exposes — is a pure function of the seed: a race found on
  seed N reproduces on seed N, the ``HETU_CHAOS`` determinism model
  applied to thread schedules.  Unregistered threads and runs with the
  scheduler uninstalled take one ``is None`` check and nothing else.

Cost model when both are off (the default): one module-global ``None``
check for the fuzzer plus one env-registry read for lockdep per
acquire — the same guard discipline as ``telemetry.enabled()``, bounded
by the same kind of smoke-tier overhead test.
"""

from __future__ import annotations

import random
import threading
import time
import traceback
from dataclasses import dataclass, field

from . import envvars

__all__ = [
    "TracedLock", "TracedRLock", "TracedCondition",
    "InterleaveScheduler", "install_scheduler", "current_scheduler",
    "sched_point", "note_blocking",
    "lockdep_enabled", "lockdep_reset", "lockdep_violations",
    "lockdep_edges", "format_violation",
]

# --------------------------------------------------------------------- #
# thread-local state
# --------------------------------------------------------------------- #

_TL = threading.local()


def _held():
    h = getattr(_TL, "held", None)
    if h is None:
        h = _TL.held = []
    return h


def _dep_on() -> bool:
    return envvars.get_bool("HETU_LOCKDEP")


def lockdep_enabled() -> bool:
    """True when ``HETU_LOCKDEP`` is set truthy (read per call — tests
    toggle it at runtime)."""
    return _dep_on()


# --------------------------------------------------------------------- #
# lockdep: global lock-order graph + violations
# --------------------------------------------------------------------- #

# raw internals: this module is the ONE place raw threading primitives
# are legal (lint rule raw-lock), and the sanitizer's own bookkeeping
# must not recurse into itself
_graph_mu = threading.Lock()
_EDGES: dict = {}        # (a_name, b_name) -> edge info (sites + stacks)
_ADJ: dict = {}          # a_name -> set of b_names
_REPORTED: set = set()   # dedupe keys for emitted violations
_VIOLATIONS: list = []   # violation dicts, append-only
_MAX_VIOLATIONS = 256


@dataclass
class _Held:
    """One live acquisition on some thread's held stack."""
    name: str
    site: str
    stack: str
    t0: float = field(default_factory=time.perf_counter)


def _capture(skip_hint="locks.py"):
    """(site, stack): innermost non-locks.py frame + formatted stack."""
    frames = traceback.extract_stack(limit=24)
    site = "<unknown>"
    for fr in reversed(frames):
        if skip_hint not in fr.filename:
            site = f"{fr.filename}:{fr.lineno} in {fr.name}"
            break
    text = "".join(traceback.format_list(
        [fr for fr in frames if skip_hint not in fr.filename][-8:]))
    return site, text


def _find_path(src, dst):
    """DFS: a list of lock names src -> ... -> dst, or None."""
    stack, seen = [(src, [src])], {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _ADJ.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def format_violation(v) -> str:
    """GraphVerifyError-style multi-line diagnostic for one violation."""
    lines = [f"lockdep [{v['kind']}] lock {v['lock']!r}"
             + (f" vs {v['other']!r}" if v.get("other") else "")
             + f": {v['msg']}"]
    for label, stk in v.get("stacks", ()):
        lines.append(f"  {label}:")
        lines.extend("    " + ln for ln in stk.rstrip().splitlines())
    return "\n".join(lines)


def _report(kind, lock, other=None, msg="", stacks=(), site=""):
    """Record one violation + emit the contract event.  Runs with the
    thread-local ``busy`` flag set so the sink/metrics locks it touches
    behave as plain locks (no sanitizer recursion)."""
    if len(_VIOLATIONS) >= _MAX_VIOLATIONS:
        return
    v = {"kind": kind, "lock": lock, "other": other, "msg": msg,
         "stacks": tuple(stacks), "site": site}
    _VIOLATIONS.append(v)
    prev, _TL.busy = getattr(_TL, "busy", False), True
    try:
        from .telemetry import events as _events
        _events.emit("lockdep_violation", _stream="validate",
                     kind=kind, lock=lock, other=other, site=site,
                     msg=msg)
    except Exception:
        pass
    finally:
        _TL.busy = prev


def _edge(held_rec, name, site, stack):
    """Insert the order edge held_rec.name -> name; report a cycle."""
    key = (held_rec.name, name)
    viol = None
    with _graph_mu:
        if key not in _EDGES:
            _EDGES[key] = {"a_site": held_rec.site, "b_site": site,
                           "a_stack": held_rec.stack, "b_stack": stack}
            _ADJ.setdefault(held_rec.name, set()).add(name)
            path = _find_path(name, held_rec.name)
            if path:
                cyc = tuple(sorted((held_rec.name, name)))
                if cyc not in _REPORTED:
                    _REPORTED.add(cyc)
                    rev = _EDGES.get((path[0], path[1]), {})
                    viol = {
                        "other": name,
                        "msg": (f"lock-order inversion: "
                                f"{held_rec.name!r} -> {name!r} here, "
                                f"but {' -> '.join(repr(p) for p in path)}"
                                f" was established earlier — the two "
                                f"orders can deadlock"),
                        "stacks": (
                            (f"{held_rec.name!r} acquired at "
                             f"{held_rec.site}", held_rec.stack),
                            (f"{name!r} acquired at {site}", stack),
                            (f"reverse edge {path[0]!r} -> {path[1]!r} "
                             f"acquired at {rev.get('b_site', '?')}",
                             rev.get("b_stack", "")),
                        ),
                        "site": site,
                    }
    if viol is not None:
        _report("order", held_rec.name, **viol)


def _on_acquired(name):
    """First (non-reentrant) acquisition bookkeeping; returns the
    held-stack record, or None when the sanitizer is busy/off."""
    if getattr(_TL, "busy", False):
        return None
    site, stack = _capture()
    rec = _Held(name, site, stack)
    for h in _held():
        if h.name != name:
            _edge(h, name, site, stack)
    _held().append(rec)
    return rec


def _drop_held(rec):
    try:
        _held().remove(rec)
    except ValueError:
        pass


def _hold_metrics(rec):
    """Post-release hold-time accounting (lock already released)."""
    dt_ms = (time.perf_counter() - rec.t0) * 1e3
    prev, _TL.busy = getattr(_TL, "busy", False), True
    try:
        from .telemetry.metrics import REGISTRY
        REGISTRY.histogram("lock.hold_ms." + rec.name).observe(dt_ms)
    except Exception:
        pass
    finally:
        _TL.busy = prev
    thresh = envvars.get_float("HETU_LOCKDEP_HOLD_MS")
    if thresh and dt_ms > thresh:
        _report("long_hold", rec.name, site=rec.site,
                msg=f"held {dt_ms:.2f} ms (> HETU_LOCKDEP_HOLD_MS="
                    f"{thresh:g})",
                stacks=((f"{rec.name!r} acquired at {rec.site}",
                         rec.stack),))


def note_blocking(op, **info):
    """Declare that the caller is about to do work that can BLOCK
    (a PS RPC, a big wire encode, a jit dispatch, a sleep).  Under
    lockdep, doing so while holding any traced lock is a
    ``held_across`` violation naming the lock's acquisition stack and
    the blocking site — the latency/deadlock smell the hold-time
    histogram only shows after the fact."""
    if not _dep_on() or getattr(_TL, "busy", False):
        return
    held = getattr(_TL, "held", None)
    if not held:
        return
    h = held[-1]
    site, stack = _capture()
    key = ("held_across", op, h.name, h.site)
    with _graph_mu:
        if key in _REPORTED:
            return
        _REPORTED.add(key)
    extra = ", ".join(f"{k}={v}" for k, v in info.items())
    _report("held_across", h.name, other=op, site=site,
            msg=f"blocking op {op!r}{' (' + extra + ')' if extra else ''}"
                f" while holding {h.name!r} — the lock's critical "
                f"section now includes an unbounded wait",
            stacks=((f"{h.name!r} acquired at {h.site}", h.stack),
                    (f"blocking {op!r} at {site}", stack)))


def lockdep_violations() -> list:
    """All violations recorded since the last :func:`lockdep_reset`."""
    return list(_VIOLATIONS)


def lockdep_edges() -> dict:
    """Snapshot of the lock-order graph {(a, b): {a_site, b_site}}."""
    with _graph_mu:
        return {k: {"a_site": v["a_site"], "b_site": v["b_site"]}
                for k, v in _EDGES.items()}


def lockdep_reset():
    """Clear the order graph + violations (test isolation)."""
    global _VIOLATIONS
    with _graph_mu:
        _EDGES.clear()
        _ADJ.clear()
        _REPORTED.clear()
        _VIOLATIONS = []


# --------------------------------------------------------------------- #
# deterministic interleaving scheduler (HETU_SCHED_FUZZ)
# --------------------------------------------------------------------- #

class InterleaveScheduler:
    """Seeded cooperative scheduler: one run token, rng-picked handoff.

    Threads participate only after :meth:`register` (done by
    ``analysis/concurrency.run_interleaved``'s thread wrapper, keyed by
    a deterministic per-thread index — NOT the OS ident, so the
    schedule is a pure function of the seed).  All registrants rally at
    a start barrier (``expect(n)``) before the first pick, which makes
    thread start-order irrelevant.  ``yield_point()`` offers the token
    to an rng-picked runnable thread (possibly self); ``yield_to_other``
    is the blocked-acquire variant that must hand it away;
    ``detach``/``reattach`` bracket real blocking waits (condvars) so a
    waiter never wedges the token.  Lock/condvar waits in HERE are raw
    by design — the sanitizer's machinery cannot run under itself."""

    def __init__(self, seed, expected=0, max_wait=30.0):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._cv = threading.Condition(threading.Lock())
        self._expected = int(expected)
        self._threads = {}     # OS ident -> index
        self._runnable = {}    # index -> ident
        self._current = None
        self._started = False
        self._max_wait = float(max_wait)

    # -- internals (call with self._cv held) ------------------------- #

    def _pick(self, exclude=None):
        """rng-pick among runnable threads (minus ``exclude``); the
        caller decides whether self is a legal choice by excluding."""
        choices = sorted(i for i in self._runnable if i != exclude)
        if not choices:
            return None
        return self._rng.choice(choices)

    def _wait_for_token(self, index):
        deadline = time.monotonic() + self._max_wait
        while self._current != index:
            if index not in self._runnable:
                return      # detached/unregistered concurrently
            self._cv.wait(0.5)
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"interleave fuzz seed={self.seed}: thread "
                    f"{index} starved {self._max_wait}s waiting for "
                    f"the token (deadlock in the code under test?)")

    # -- registration ------------------------------------------------ #

    def expect(self, n):
        with self._cv:
            self._expected = int(n)

    def register(self, index):
        me = threading.get_ident()
        with self._cv:
            self._threads[me] = index
            self._runnable[index] = me
            if not self._started \
                    and len(self._runnable) >= self._expected:
                self._started = True
                self._current = self._pick()
                self._cv.notify_all()
            deadline = time.monotonic() + self._max_wait
            while not self._started or self._current != index:
                self._cv.wait(0.5)
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"interleave fuzz seed={self.seed}: start "
                        f"barrier starved (expected="
                        f"{self._expected}, registered="
                        f"{len(self._runnable)})")
        _TL.fuzz = self

    def unregister(self):
        me = threading.get_ident()
        with self._cv:
            idx = self._threads.pop(me, None)
            self._runnable.pop(idx, None)
            if self._current == idx:
                self._current = self._pick()
                self._cv.notify_all()
        _TL.fuzz = None

    # -- scheduling points ------------------------------------------- #

    def _my_index(self):
        return self._threads.get(threading.get_ident())

    def yield_point(self):
        """Offer the token to an rng-picked runnable thread."""
        with self._cv:
            idx = self._my_index()
            if idx is None or self._current != idx:
                return
            nxt = self._pick()
            if nxt != idx:
                self._current = nxt
                self._cv.notify_all()
                self._wait_for_token(idx)

    def yield_to_other(self) -> bool:
        """Hand the token to some OTHER runnable thread; False when
        this thread is the only runnable one."""
        with self._cv:
            idx = self._my_index()
            if idx is None or self._current != idx:
                return False
            nxt = self._pick(exclude=idx)
            if nxt is None or nxt == idx:
                return False
            self._current = nxt
            self._cv.notify_all()
            self._wait_for_token(idx)
            return True

    def detach(self):
        """Leave the runnable set before a REAL blocking wait."""
        with self._cv:
            idx = self._my_index()
            if idx is None:
                return
            self._runnable.pop(idx, None)
            if self._current == idx:
                self._current = self._pick()
                self._cv.notify_all()

    def reattach(self):
        """Rejoin the runnable set after a real wait; blocks until the
        token comes around."""
        with self._cv:
            idx = self._my_index()
            if idx is None:
                return
            self._runnable[idx] = threading.get_ident()
            if self._current is None:
                self._current = idx
            self._cv.notify_all()
            self._wait_for_token(idx)


_SCHED: InterleaveScheduler | None = None


def install_scheduler(sched):
    """Install (or, with None, remove) the process-wide fuzz
    scheduler.  ``analysis/concurrency.run_interleaved`` owns this."""
    global _SCHED
    _SCHED = sched


def current_scheduler():
    return _SCHED


def _sched():
    """The scheduler IF this thread is registered with it, else None —
    the one check unregistered threads pay under fuzz."""
    s = _SCHED
    if s is None:
        return None
    return s if getattr(_TL, "fuzz", None) is s else None


def sched_point():
    """Explicit preemption point for fuzzed code paths (FakeComm seams,
    hammer-test bodies).  No-op unless this thread is registered with
    an installed scheduler."""
    s = _sched()
    if s is not None:
        s.yield_point()


def _fuzz_acquire(inner, sched, blocking, timeout):
    """Token-safe acquire: never block the OS thread while holding the
    token — try, hand the token away on failure, retry."""
    sched.yield_point()
    if inner.acquire(False):
        return True
    if not blocking:
        return False
    deadline = None if timeout is None or timeout < 0 \
        else time.monotonic() + timeout
    spins = 0
    while True:
        if not sched.yield_to_other():
            # lock held by an unregistered thread (or a bug): spin
            # politely off-token rather than wedging the schedule
            time.sleep(0.0005)
            spins += 1
            if spins > 20000:
                raise RuntimeError(
                    f"interleave fuzz seed={sched.seed}: acquire "
                    f"starved with no other runnable thread")
        if inner.acquire(False):
            return True
        if deadline is not None and time.monotonic() >= deadline:
            return False


# --------------------------------------------------------------------- #
# the wrappers
# --------------------------------------------------------------------- #

def _inner_acquire(inner, blocking, timeout):
    if timeout is None or timeout < 0:
        return inner.acquire(blocking)
    return inner.acquire(blocking, timeout)


class TracedLock:
    """Drop-in ``threading.Lock`` with the lockdep/fuzz personalities.

    ``name`` is the LOCK CLASS (shared by every instance guarding the
    same kind of state — all ``_Param`` locks are ``"ps.param"``): the
    lock-order graph, hold histograms, and diagnostics are keyed by it.
    """

    __slots__ = ("_inner", "_name", "_rec")

    def __init__(self, name="lock"):
        self._inner = threading.Lock()
        self._name = str(name)
        self._rec = None

    @property
    def name(self):
        return self._name

    def acquire(self, blocking=True, timeout=-1):
        s = _sched()
        if s is not None:
            ok = _fuzz_acquire(self._inner, s, blocking, timeout)
        else:
            ok = _inner_acquire(self._inner, blocking, timeout)
        if ok and _dep_on():
            self._rec = _on_acquired(self._name)
        return ok

    def release(self):
        rec, self._rec = self._rec, None
        if rec is not None:
            _drop_held(rec)
        self._inner.release()
        if rec is not None:
            _hold_metrics(rec)
        s = _sched()
        if s is not None:
            s.yield_point()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def __repr__(self):
        return f"<TracedLock {self._name!r} at {id(self):#x}>"

    # condvar-wait plumbing (TracedCondition suspends the holder's
    # bookkeeping around the real wait)
    def _suspend(self):
        rec, self._rec = self._rec, None
        if rec is not None:
            _drop_held(rec)
        return rec

    def _resume(self, rec):
        if rec is not None:
            rec.t0 = time.perf_counter()
            _held().append(rec)
            self._rec = rec


def _rl_recs():
    r = getattr(_TL, "rl_recs", None)
    if r is None:
        r = _TL.rl_recs = {}
    return r


class TracedRLock:
    """Drop-in ``threading.RLock``: reentrant re-acquires are counted
    per thread and insert NO order edges (a lock class never conflicts
    with itself through recursion)."""

    __slots__ = ("_inner", "_name")

    def __init__(self, name="rlock"):
        self._inner = threading.RLock()
        self._name = str(name)

    @property
    def name(self):
        return self._name

    def acquire(self, blocking=True, timeout=-1):
        s = _sched()
        if s is not None:
            ok = _fuzz_acquire(self._inner, s, blocking, timeout)
        else:
            ok = _inner_acquire(self._inner, blocking, timeout)
        if ok and _dep_on():
            recs = _rl_recs()
            ent = recs.get(id(self))
            if ent is None:
                rec = _on_acquired(self._name)
                if rec is not None:
                    recs[id(self)] = [rec, 1]
            else:
                ent[1] += 1
        return ok

    def release(self):
        recs = getattr(_TL, "rl_recs", None)
        ent = recs.get(id(self)) if recs else None
        rec = None
        if ent is not None:
            ent[1] -= 1
            if ent[1] <= 0:
                rec = ent[0]
                del recs[id(self)]
                _drop_held(rec)
        self._inner.release()
        if rec is not None:
            _hold_metrics(rec)
        s = _sched()
        if s is not None:
            s.yield_point()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def __repr__(self):
        return f"<TracedRLock {self._name!r} at {id(self):#x}>"

    def _is_owned(self):
        return self._inner._is_owned()

    def _suspend(self):
        recs = getattr(_TL, "rl_recs", None)
        ent = recs.pop(id(self), None) if recs else None
        if ent is not None:
            _drop_held(ent[0])
        return ent

    def _resume(self, ent):
        if ent is not None:
            ent[0].t0 = time.perf_counter()
            _held().append(ent[0])
            _rl_recs()[id(self)] = ent


class TracedCondition:
    """Drop-in ``threading.Condition`` over a traced lock.

    The inner ``threading.Condition`` is built on the traced lock's RAW
    lock, so wait/notify semantics (including RLock ``_release_save``)
    are stdlib-exact; the wrapper keeps the sanitizer's held-stack and
    hold-window honest across the wait, and detaches from the fuzz
    token while really blocked so a waiter never wedges the schedule.
    """

    __slots__ = ("_tlock", "_cv", "_name")

    def __init__(self, lock=None, name="cv"):
        if lock is None:
            lock = TracedRLock(name=str(name))
        self._tlock = lock
        self._name = str(name)
        self._cv = threading.Condition(lock._inner)

    @property
    def name(self):
        return self._name

    @property
    def lock(self):
        return self._tlock

    def acquire(self, *args, **kw):
        return self._tlock.acquire(*args, **kw)

    def release(self):
        self._tlock.release()

    def __enter__(self):
        self._tlock.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tlock.release()
        return False

    def wait(self, timeout=None):
        state = self._tlock._suspend()
        s = _sched()
        if s is not None:
            s.detach()
        try:
            return self._cv.wait(timeout)
        finally:
            if s is not None:
                s.reattach()
            self._tlock._resume(state)

    def wait_for(self, predicate, timeout=None):
        endtime = None
        waittime = timeout
        result = predicate()
        while not result:
            if waittime is not None:
                if endtime is None:
                    endtime = time.monotonic() + waittime
                else:
                    waittime = endtime - time.monotonic()
                    if waittime <= 0:
                        break
            self.wait(waittime)
            result = predicate()
        return result

    def notify(self, n=1):
        self._cv.notify(n)

    def notify_all(self):
        self._cv.notify_all()

    def __repr__(self):
        return f"<TracedCondition {self._name!r} at {id(self):#x}>"
