"""Process launcher (reference bin/heturun + python/runner.py:150-260 +
python/hetu/launcher.py).

The reference spawns PS scheduler/server processes locally, starts remote
processes over ssh/paramiko, and runs workers under mpirun with DMLC_*
env vars.  The TPU build has no MPI and no scheduler role (the TCP PS
server is self-contained): `heturun -c cluster.yml python train.py`

- starts `servers:` PS processes per host (local ones directly; remote
  ones via the system `ssh` when configured),
- starts `workers:` worker processes per host with HETU_PS_* and
  JAX_COORDINATOR_* env so workers reach the PS and, on TPU pods,
  `jax.distributed.initialize()` finds the coordinator,
- tears everything down on SIGINT like the reference runner
  (runner.py:16-22).

The python API `launch(target, args)` mirrors reference launcher.py:18:
run a callable under a local PS "cluster" (used by the cache tests the
same way hetu_cache_test.py:11-34 uses it).
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
import multiprocessing

from .context import DistConfig

from . import envvars

_procs: list = []
DEFAULT_PS_PORT = 23455

# the most recent run_cluster's structured failure/restart event log
# (worker_exit / worker_restart / ps_server_exit / ps_restart /
# ps_resynced ... records); also appended as JSONL to $HETU_FAILURE_LOG
last_failure_events: list = []


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _start_ps_process(port, extra_env=None):
    proc = multiprocessing.get_context("spawn").Process(
        target=_ps_main, args=(port, extra_env), daemon=True)
    proc.start()
    _procs.append(proc)
    return proc


def _ps_main(port, extra_env=None):
    # env set in the CHILD only — mutating the launcher's own environ
    # would leak role variables (e.g. HETU_SCHEDULER_ADDR) into later
    # in-process PSClient.get() resolution
    os.environ.update(extra_env or {})
    os.environ["HETU_PS_PORT"] = str(port)
    from .ps.server import PSServer
    PSServer.serve_from_env()


def _scheduler_main(port):
    os.environ["HETU_SCHEDULER_PORT"] = str(port)
    from .ps.server import Scheduler
    Scheduler.serve_from_env()


def _start_scheduler_process(port):
    proc = multiprocessing.get_context("spawn").Process(
        target=_scheduler_main, args=(port,), daemon=True)
    proc.start()
    _procs.append(proc)
    return proc


def _wait_ps(host, port, timeout=20.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            s = socket.create_connection((host, port), timeout=1.0)
            s.close()
            return
        except OSError:
            time.sleep(0.1)
    raise TimeoutError(f"PS at {host}:{port} did not come up")


def _worker_env(config, host, rank, nrank, ps_host, ps_port,
                coordinator=None):
    env = dict(os.environ)
    # make hetu_tpu importable from any cwd (reference hetu.exp sets
    # PYTHONPATH the same way)
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    if ps_port is not None:
        env["HETU_PS_ADDR"] = f"{ps_host}:{ps_port}"
        env["HETU_PS_RANK"] = str(rank)
        env["HETU_PS_NRANK"] = str(nrank)
    if coordinator and nrank > 1:
        # JAX_COORDINATOR_ADDRESS is read by jax.distributed.initialize();
        # process counts are NOT read from env by jax, so workers call our
        # distributed_init() helper (below) which passes them explicitly
        env["JAX_COORDINATOR_ADDRESS"] = coordinator
        env["HETU_NUM_PROCESSES"] = str(nrank)
        env["HETU_PROCESS_ID"] = str(rank)
    return env


def distributed_init():
    """Worker-side bring-up for multi-host meshes (replaces the
    reference's wrapped_mpi_nccl_init, executor.py:60-71): call this at
    the top of a worker script launched by heturun.  No-op single-host."""
    import jax

    nrank = envvars.get_int("HETU_NUM_PROCESSES")
    if nrank <= 1:
        return
    # pre-0.5 jax needs the gloo CPU-collectives implementation selected
    # explicitly or multi-process CPU meshes abort with "Multiprocess
    # computations aren't implemented".  Unconditional: the option only
    # affects the CPU backend, and probing the backend here would
    # initialize jax before distributed.initialize (which it forbids).
    from ._compat import enable_cpu_collectives
    enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=nrank,
        process_id=envvars.require_int("HETU_PROCESS_ID"))


def _sigint(sig, frame):
    for p in _procs:
        try:
            (p.kill if hasattr(p, "kill") else p.terminate)()
        except Exception:
            pass
    sys.exit(0)


def _proc_poll(p):
    """Exit code or None, across subprocess.Popen and mp.Process."""
    if hasattr(p, "poll"):
        return p.poll()
    return None if p.is_alive() else p.exitcode


def run_cluster(config: DistConfig, command, coordinator_port=6655,
                supervise=None):
    """heturun main path: PS process(es) + worker subprocesses running
    `command` (argv list), SUPERVISED.  Returns worker exit codes.

    Multiple servers get sequential ports (our PS server is one process
    per port, unlike ps-lite's key-sharded server group); workers see the
    first as HETU_PS_ADDR and the full list as HETU_PS_ADDRS.

    The supervisor (default on; ``supervise=False`` or HETU_SUPERVISE=0
    restores fire-and-wait) watches child exit codes and respawns:

    - a dead PS server is restarted on its port and, when the group is
      replicated (HETU_PS_REPLICATE=1, >1 server), re-seeded from its
      ring replica via ``ps.sharded.resync_primary`` before workers
      route traffic back to it;
    - a worker exiting nonzero is restarted (the worker script resumes
      from its latest checkpoint — Executor.save/load persists params,
      optimizer slots, step, rng and dataloader positions; the child
      sees HETU_RESTART_COUNT);
    - each slot has an exponential-backoff restart budget:
      HETU_RESTART_LIMIT (default 3) restarts, HETU_RESTART_BACKOFF
      (default 0.5) * 2^attempt seconds apart;
    - every failure/restart appends a structured record to
      ``launcher.last_failure_events`` and (JSONL) to
      $HETU_FAILURE_LOG.

    With HETU_LIVENESS_STALE=<seconds> > 0 the supervisor also polls the
    rendezvous scheduler's heartbeat map and kills a *wedged* server
    (process alive, heartbeats stale) so the restart path above takes
    over — the mid-run wedge class of failure, not just clean exits."""
    signal.signal(signal.SIGINT, _sigint)
    _procs.clear()
    global last_failure_events
    events = last_failure_events = []

    def _event(kind, **fields):
        # ONE emitter repo-wide (telemetry/events.py): the sink appends
        # to $HETU_FAILURE_LOG (legacy stream path) and the merged
        # $HETU_TELEMETRY_LOG in the same {t, event, ...} shape
        from .telemetry import emit
        rec = emit(kind, _stream="failure", **fields)
        events.append(rec)
        if kind in ("worker_failed", "ps_server_dead",
                    "ps_restart_failed"):
            # terminal supervisor outcomes (budget spent / respawn
            # impossible): dump the flight ring so the post-mortem has
            # the restart/backoff records that led here
            from .telemetry.flight import RECORDER
            RECORDER.dump("launcher_failure", trigger=kind)
        print(f"[heturun] {kind}: {fields}", flush=True)

    if supervise is None:
        supervise = envvars.get_bool("HETU_SUPERVISE")
    restart_limit = envvars.get_int("HETU_RESTART_LIMIT")
    backoff0 = envvars.get_float("HETU_RESTART_BACKOFF")
    liveness_stale = envvars.get_float("HETU_LIVENESS_STALE")

    ps_port = None
    local_names = ("localhost", "127.0.0.1", socket.gethostname())
    # PS lives on the first host that configures servers (NOT necessarily
    # the chief)
    ps_host = next(iter(config.servers), config.chief or "localhost")
    ps_addrs = []
    sched_addr = None
    sched_port = None
    server_slots = []
    if config.enable_PS:
        base_port = envvars.get_int("HETU_PS_PORT", DEFAULT_PS_PORT)
        # scheduler rendezvous (ps-lite Postoffice role): servers
        # register; workers can resolve the group dynamically.  Static
        # HETU_PS_ADDRS is still exported and takes precedence — the
        # scheduler is the contract for deployments where ports are not
        # known up front.
        sched_port = _free_port()
        _start_scheduler_process(sched_port)
        _wait_ps("localhost", sched_port)
        sched_addr = f"{config.chief or 'localhost'}:{sched_port}"
        idx = 0
        for host, n in config.servers.items():
            for _ in range(n):
                port = base_port + idx
                env_extra = {"HETU_SCHEDULER_ADDR":
                             f"localhost:{sched_port}"
                             if host in local_names else sched_addr,
                             "HETU_PS_INDEX": str(idx),
                             "HETU_PS_ADVERTISE": f"{host}:{port}",
                             "HETU_CHAOS_ROLE": f"server:{idx}"}
                if host in local_names:
                    def spawn(port=port, env_extra=env_extra, restarts=0):
                        return _start_ps_process(port, dict(
                            env_extra, HETU_RESTART_COUNT=str(restarts)))
                else:
                    def spawn(host=host, port=port, env_extra=env_extra,
                              restarts=0):
                        return _ssh_spawn(host, [
                            sys.executable, "-m", "hetu_tpu.launcher",
                            "--serve-ps", str(port)], env=dict(
                                env_extra,
                                HETU_RESTART_COUNT=str(restarts)))
                server_slots.append({
                    "index": idx, "host": host, "port": port,
                    "spawn": spawn, "proc": spawn(), "restarts": 0,
                    "next_at": None})
                idx += 1
                ps_addrs.append(f"{host}:{port}")
        ps_host, ps_port = ps_addrs[0].rsplit(":", 1)
        ps_port = int(ps_port)
        for slot in server_slots:
            _wait_ps("localhost" if slot["host"] in local_names
                     else slot["host"], slot["port"])
    replicated = len(ps_addrs) > 1 and \
        envvars.get_bool("HETU_PS_REPLICATE")

    nrank = config.num_workers
    chief = config.chief or "localhost"
    coordinator = f"{chief}:{coordinator_port}" if nrank > 1 else None
    worker_slots = []
    rank = 0
    for host, n in config.workers.items():
        for _ in range(n):
            env = _worker_env(config, host, rank, nrank, ps_host, ps_port,
                              coordinator)
            if ps_addrs:
                env["HETU_PS_ADDRS"] = ",".join(ps_addrs)
                env["HETU_PS_NSERVERS"] = str(len(ps_addrs))
            if sched_addr:
                env["HETU_SCHEDULER_ADDR"] = sched_addr
            env["HETU_CHAOS_ROLE"] = f"worker:{rank}"

            def spawn(host=host, env=env, restarts=0):
                env = dict(env, HETU_RESTART_COUNT=str(restarts))
                if host in local_names:
                    p = subprocess.Popen(command, env=env)
                    _procs.append(p)
                    return p
                return _ssh_spawn(host, command, env={
                    k: v for k, v in env.items()
                    if k.startswith(("HETU_", "JAX_"))})
            worker_slots.append({
                "rank": rank, "spawn": spawn, "proc": spawn(),
                "restarts": 0, "next_at": None, "code": None})
            rank += 1

    def _respawn_server(slot):
        slot["proc"] = slot["spawn"](restarts=slot["restarts"])
        try:
            _wait_ps("localhost" if slot["host"] in local_names
                     else slot["host"], slot["port"])
        except TimeoutError as e:
            _event("ps_restart_failed", index=slot["index"],
                   error=str(e))
            return
        _event("ps_restart", index=slot["index"], port=slot["port"],
               attempt=slot["restarts"])
        if replicated:
            try:
                from .ps.sharded import resync_primary
                keys = resync_primary(ps_addrs, slot["index"])
                _event("ps_resynced", index=slot["index"],
                       keys=len(keys))
            except Exception as e:  # noqa: BLE001 — degraded, not fatal
                _event("ps_resync_failed", index=slot["index"],
                       error=f"{type(e).__name__}: {e}"[:200])

    def _check_liveness(now, state={"last": 0.0}):
        """Kill wedged-but-running servers flagged dead by the
        scheduler's heartbeat map (HETU_LIVENESS_STALE seconds)."""
        if liveness_stale <= 0 or sched_port is None or \
                now - state["last"] < max(liveness_stale / 2, 1.0):
            return
        state["last"] = now
        try:
            from .ps.client import _TCPTransport
            t = _TCPTransport("localhost", sched_port, timeout=2.0,
                              connect_timeout=2.0, retries=1)
            health = t.call("health", liveness_stale)
            t.close()
        except Exception:
            return
        for slot in server_slots:
            node = f"server:{slot['index']}"
            if health.get(node, {}).get("alive", True):
                continue
            if _proc_poll(slot["proc"]) is None:
                _event("ps_wedged_kill", index=slot["index"],
                       age_s=health[node]["age_s"])
                try:
                    (slot["proc"].kill if hasattr(slot["proc"], "kill")
                     else slot["proc"].terminate)()
                except Exception:
                    pass

    if not supervise:
        codes = [w["proc"].wait() for w in worker_slots]
    else:
        while any(w["code"] is None for w in worker_slots):
            now = time.monotonic()
            for w in worker_slots:
                if w["code"] is not None:
                    continue
                if w["proc"] is None:          # backoff window
                    if now >= w["next_at"]:
                        w["proc"] = w["spawn"](restarts=w["restarts"])
                        _event("worker_restart", rank=w["rank"],
                               attempt=w["restarts"])
                    continue
                rc = _proc_poll(w["proc"])
                if rc is None:
                    continue
                if rc == 0:
                    w["code"] = 0
                    continue
                _event("worker_exit", rank=w["rank"], rc=rc,
                       restarts=w["restarts"])
                if w["restarts"] < restart_limit:
                    w["restarts"] += 1
                    backoff = backoff0 * 2 ** (w["restarts"] - 1)
                    w["proc"], w["next_at"] = None, now + backoff
                    _event("worker_restart_scheduled", rank=w["rank"],
                           attempt=w["restarts"],
                           backoff_s=round(backoff, 3))
                else:
                    w["code"] = rc
                    _event("worker_failed", rank=w["rank"], rc=rc,
                           restarts=w["restarts"])
            for slot in server_slots:
                if slot["proc"] is None:       # backoff window
                    if now >= slot["next_at"]:
                        slot["next_at"] = None
                        _respawn_server(slot)
                    continue
                rc = _proc_poll(slot["proc"])
                if rc is None:
                    continue
                _event("ps_server_exit", index=slot["index"], rc=rc,
                       restarts=slot["restarts"])
                if slot["restarts"] < restart_limit:
                    slot["restarts"] += 1
                    backoff = backoff0 * 2 ** (slot["restarts"] - 1)
                    slot["proc"], slot["next_at"] = None, now + backoff
                else:
                    # terminal: budget spent — workers keep running on
                    # the replica (or fail with PSConnectionError)
                    _event("ps_server_dead", index=slot["index"], rc=rc)
                    slot["proc"], slot["next_at"] = None, float("inf")
            _check_liveness(now)
            time.sleep(0.2)
        codes = [w["code"] for w in worker_slots]
    for p in _procs:
        if hasattr(p, "poll") and p.poll() is None:
            p.terminate()
        elif hasattr(p, "is_alive") and p.is_alive():
            p.terminate()
    return codes


def _ssh_spawn(host, command, env=None):
    """Remote start over the system ssh (reference uses paramiko,
    runner.py:36-148).  Untested without a cluster; kept narrow."""
    import shlex

    parts = [f"{k}={shlex.quote(str(v))}" for k, v in (env or {}).items()]
    parts += [shlex.quote(str(c)) for c in command]
    cmd = ["ssh", "-o", "StrictHostKeyChecking=no", host,
           " ".join(parts)]
    p = subprocess.Popen(cmd)
    _procs.append(p)
    return p


def launch(target, args=(), num_servers=1):
    """Python-API launcher (reference launcher.py:18): run `target(args)`
    with a freshly started local PS; tears the PS down after."""
    port = _free_port()
    proc = _start_ps_process(port)
    _wait_ps("localhost", port)
    old = envvars.get_str("HETU_PS_ADDR")
    os.environ["HETU_PS_ADDR"] = f"localhost:{port}"
    try:
        from .ps.client import PSClient
        PSClient._instance = None  # re-resolve transport from env
        return target(*args) if args else target()
    finally:
        if old is None:
            os.environ.pop("HETU_PS_ADDR", None)
        else:
            os.environ["HETU_PS_ADDR"] = old
        from .ps.client import PSClient
        PSClient._instance = None
        proc.terminate()
        proc.join(timeout=5)
        _procs.remove(proc) if proc in _procs else None


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(
        prog="heturun",
        description="hetu_tpu cluster launcher (reference bin/heturun)")
    parser.add_argument("-c", "--config", default=None,
                        help="cluster yaml (DistConfig format)")
    parser.add_argument("-s", "--servers", type=int, default=0,
                        help="local PS server count (no yaml)")
    parser.add_argument("-w", "--workers", type=int, default=1,
                        help="local worker count (no yaml)")
    parser.add_argument("--serve-ps", type=int, default=None,
                        help=argparse.SUPPRESS)  # internal: PS role
    parser.add_argument("--serve-scheduler", type=int, default=None,
                        help=argparse.SUPPRESS)  # internal: rendezvous
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="worker command, e.g. python train.py")
    args = parser.parse_args(argv)

    if args.serve_ps is not None:
        _ps_main(args.serve_ps)
        return 0
    if args.serve_scheduler is not None:
        _scheduler_main(args.serve_scheduler)
        return 0
    if not args.command:
        parser.error("no worker command given")
    if args.config:
        config = DistConfig(file=args.config)
    else:
        config = DistConfig(num_servers=args.servers,
                            num_workers=args.workers)
    codes = run_cluster(config, args.command)
    return max(codes) if codes else 0


if __name__ == "__main__":
    sys.exit(main())
