"""ONNX interop (reference python/hetu/onnx/, 2,337 LoC).

Self-contained: includes a minimal protobuf wire-format implementation of
the public onnx.proto schema (proto.py) because the image ships no `onnx`
package.  Export traces the inference subgraph to a jaxpr and maps XLA
primitives to ONNX ops; import builds normal hetu_tpu graph nodes from
ONNX nodes, so imported models can be trained and re-exported.
"""

from .hetu2onnx import export
from .onnx2hetu import load_onnx
from .proto import ModelProto, load_model, save_model

__all__ = ["export", "load_onnx", "ModelProto", "load_model",
           "save_model"]
