"""Export hetu_tpu graphs to ONNX (reference python/hetu/onnx/hetu2onnx.py).

The reference maps its graph nodes 1:1 through per-op opset handlers
(hetu2onnx.py:27-130, onnx_opset/).  The TPU build exports from one level
lower — the traced **jaxpr** of the inference subgraph — so every op built
from jax compositions (the whole ~100-op surface plus anything user-
written) exports through a small set of XLA-primitive handlers instead of
one handler per framework op.  Parameters become initializers; any
primitive whose inputs are all compile-time constants is folded into an
initializer, which subsumes iota/eps-constants/shape arithmetic.

Entry point mirrors the reference:

    export(executor, [x, y_], [pred], "model.onnx")
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.extend import core as jcore

from . import proto as P
from .proto import (AttributeProto, GraphProto, ModelProto, NodeProto,
                    OperatorSetIdProto, TensorProto, attr,
                    tensor_from_numpy, value_info)

OPSET_VERSION = 17
_IR_VERSION = 8


class _Ctx:
    def __init__(self, opset=OPSET_VERSION):
        self.opset = opset
        self.nodes = []          # NodeProto list
        self.initializers = []   # TensorProto list
        self.names = {}          # jaxpr Var -> onnx name
        self.consts = {}         # jaxpr Var -> np.ndarray (foldable)
        self.counter = 0

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def name_of(self, v):
        if isinstance(v, jcore.Literal):
            return self.add_const(np.asarray(v.val))
        if v not in self.names and v in self.consts:
            # folded constant referenced by a live node: materialize now
            # (intermediates consumed only by other folds never emit)
            self.names[v] = self.add_const(self.consts[v], "fold")
        return self.names[v]

    def const_of(self, v):
        """numpy value if v is known at export time, else None."""
        if isinstance(v, jcore.Literal):
            return np.asarray(v.val)
        return self.consts.get(v)

    def add_const(self, arr, hint="const"):
        name = self.fresh(hint)
        self.initializers.append(tensor_from_numpy(np.asarray(arr), name))
        return name

    def emit(self, op_type, inputs, n_out=1, attrs=None, hint=None):
        outs = [self.fresh(hint or op_type.lower()) for _ in range(n_out)]
        self.nodes.append(NodeProto(
            op_type=op_type, input=list(inputs), output=outs,
            name=self.fresh(op_type), attribute=[
                attr(k, v) for k, v in (attrs or {}).items()]))
        return outs if n_out > 1 else outs[0]


# --------------------------------------------------------------- handlers

def _einsum_eq(dimension_numbers, lhs_ndim, rhs_ndim):
    (lc, rc), (lb, rb) = dimension_numbers
    letters = iter("abcdefghijklmnopqrstuvwxyz")
    lhs = [None] * lhs_ndim
    rhs = [None] * rhs_ndim
    for i, j in zip(lb, rb):
        c = next(letters)
        lhs[i] = rhs[j] = c
    for i, j in zip(lc, rc):
        c = next(letters)
        lhs[i] = rhs[j] = c
    for i in range(lhs_ndim):
        if lhs[i] is None:
            lhs[i] = next(letters)
    for j in range(rhs_ndim):
        if rhs[j] is None:
            rhs[j] = next(letters)
    out = ([lhs[i] for i in lb]
           + [lhs[i] for i in range(lhs_ndim) if i not in lb + lc]
           + [rhs[j] for j in range(rhs_ndim) if j not in rb + rc])
    return f"{''.join(lhs)},{''.join(rhs)}->{''.join(out)}"


_UNARY = {"neg": "Neg", "exp": "Exp", "log": "Log", "tanh": "Tanh",
          "logistic": "Sigmoid", "sqrt": "Sqrt", "abs": "Abs",
          "erf": "Erf", "sin": "Sin", "cos": "Cos", "floor": "Floor",
          "ceil": "Ceil", "sign": "Sign",
          "not": "Not"}
_BINARY = {"add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
           "max": "Max", "min": "Min", "pow": "Pow",
           "and": "And", "or": "Or", "xor": "Xor",
           "atan2": "Atan2"}
_COMPARE = {"eq": "Equal", "lt": "Less", "gt": "Greater",
            "le": "LessOrEqual", "ge": "GreaterOrEqual"}
_REDUCE = {"reduce_sum": "ReduceSum", "reduce_max": "ReduceMax",
           "reduce_min": "ReduceMin", "reduce_prod": "ReduceProd"}

_ONNX_DTYPE = {np.dtype("float32"): TensorProto.FLOAT,
               np.dtype("float64"): TensorProto.DOUBLE,
               np.dtype("int32"): TensorProto.INT32,
               np.dtype("int64"): TensorProto.INT64,
               np.dtype("bool"): TensorProto.BOOL,
               np.dtype("float16"): TensorProto.FLOAT16,
               np.dtype("uint8"): TensorProto.UINT8,
               np.dtype("int8"): TensorProto.INT8}


def _handle(ctx, eqn, invals):
    """Emit ONNX node(s) for one jaxpr eqn; return output names list."""
    prim = eqn.primitive.name
    params = eqn.params
    names = [ctx.name_of(v) for v in eqn.invars]
    out_aval = eqn.outvars[0].aval

    if prim in _UNARY:
        if prim == "not":
            return [ctx.emit("Not", names)]
        return [ctx.emit(_UNARY[prim], names)]
    if prim in _BINARY:
        return [ctx.emit(_BINARY[prim], names)]
    if prim in _COMPARE:
        return [ctx.emit(_COMPARE[prim], names)]
    if prim == "ne":
        eq = ctx.emit("Equal", names)
        return [ctx.emit("Not", [eq])]
    if prim in _REDUCE:
        # axes moved from attribute to input at opset 13 for ReduceSum
        # but only at opset 18 for Max/Min/Prod — emit the form the
        # stamped opset actually allows
        as_input = ctx.opset >= 18 or \
            (prim == "reduce_sum" and ctx.opset >= 13)
        if as_input:
            axes = ctx.add_const(np.asarray(params["axes"], np.int64))
            return [ctx.emit(_REDUCE[prim], [names[0], axes],
                             attrs={"keepdims": 0})]
        return [ctx.emit(_REDUCE[prim], [names[0]],
                         attrs={"keepdims": 0,
                                "axes": [int(a)
                                         for a in params["axes"]]})]
    if prim == "rsqrt":
        s = ctx.emit("Sqrt", [names[0]])
        return [ctx.emit("Reciprocal", [s])]
    if prim == "square":
        return [ctx.emit("Mul", [names[0], names[0]])]
    if prim == "is_finite":
        # finite = not (isnan or isinf)
        nan = ctx.emit("IsNaN", [names[0]])
        inf = ctx.emit("IsInf", [names[0]])
        bad = ctx.emit("Or", [nan, inf])
        return [ctx.emit("Not", [bad])]
    if prim == "rem":
        # lax.rem is truncated (C-style) remainder => fmod=1; also the
        # only Mod form ONNX allows on floats
        return [ctx.emit("Mod", names, attrs={"fmod": 1})]
    if prim == "integer_pow":
        y = ctx.add_const(np.asarray(params["y"],
                                     out_aval.dtype))
        return [ctx.emit("Pow", [names[0], y])]
    if prim == "dot_general":
        eq = _einsum_eq(params["dimension_numbers"],
                        eqn.invars[0].aval.ndim, eqn.invars[1].aval.ndim)
        return [ctx.emit("Einsum", names, attrs={"equation": eq})]
    if prim == "reshape":
        shape = ctx.add_const(np.asarray(params["new_sizes"], np.int64))
        return [ctx.emit("Reshape", [names[0], shape])]
    if prim == "squeeze":
        axes = ctx.add_const(np.asarray(params["dimensions"], np.int64))
        return [ctx.emit("Squeeze", [names[0], axes])]
    if prim == "expand_dims":
        axes = ctx.add_const(np.asarray(params["dimensions"], np.int64))
        return [ctx.emit("Unsqueeze", [names[0], axes])]
    if prim == "transpose":
        return [ctx.emit("Transpose", names,
                         attrs={"perm": list(params["permutation"])})]
    if prim == "broadcast_in_dim":
        shape = params["shape"]
        bdims = params["broadcast_dimensions"]
        in_aval = eqn.invars[0].aval
        x = names[0]
        # insert singleton dims so rank matches, then Expand
        if in_aval.ndim != len(shape):
            interm = [1] * len(shape)
            for src, dst in enumerate(bdims):
                interm[dst] = in_aval.shape[src]
            rs = ctx.add_const(np.asarray(interm, np.int64))
            x = ctx.emit("Reshape", [x, rs])
        tgt = ctx.add_const(np.asarray(shape, np.int64))
        return [ctx.emit("Expand", [x, tgt])]
    if prim == "concatenate":
        return [ctx.emit("Concat", names,
                         attrs={"axis": int(params["dimension"])})]
    if prim == "slice":
        starts = ctx.add_const(np.asarray(params["start_indices"],
                                          np.int64))
        ends = ctx.add_const(np.asarray(params["limit_indices"], np.int64))
        axes = ctx.add_const(np.arange(len(params["start_indices"]),
                                       dtype=np.int64))
        strides = params.get("strides")
        ins = [names[0], starts, ends, axes]
        if strides is not None:
            ins.append(ctx.add_const(np.asarray(strides, np.int64)))
        return [ctx.emit("Slice", ins)]
    if prim == "rev":
        # Slice with negative steps
        dims = list(params["dimensions"])
        starts = ctx.add_const(np.full(len(dims), -1, np.int64))
        ends = ctx.add_const(np.full(len(dims), np.iinfo(np.int64).min,
                                     np.int64))
        axes = ctx.add_const(np.asarray(dims, np.int64))
        steps = ctx.add_const(np.full(len(dims), -1, np.int64))
        return [ctx.emit("Slice", [names[0], starts, ends, axes, steps])]
    if prim == "select_n":
        # select_n(pred, x, y) -> y where pred else x
        assert len(names) == 3, "select_n with >2 cases unsupported"
        return [ctx.emit("Where", [names[0], names[2], names[1]])]
    if prim == "convert_element_type":
        to = _ONNX_DTYPE[np.dtype(params["new_dtype"])]
        return [ctx.emit("Cast", [names[0]], attrs={"to": int(to)})]
    if prim == "stop_gradient":
        return [ctx.emit("Identity", names)]
    if prim == "copy":
        return [ctx.emit("Identity", names)]
    if prim == "clamp":
        # clamp(min, x, max) -> Clip(x, min, max)
        return [ctx.emit("Clip", [names[1], names[0], names[2]])]
    if prim == "conv_general_dilated":
        return [_conv(ctx, eqn, names)]
    if prim == "reduce_window_max":
        return [_pool(ctx, eqn, names, "MaxPool")]
    if prim == "reduce_window_sum":
        return [_pool(ctx, eqn, names, "_SumPool")]
    if prim == "gather":
        g = _gather(ctx, eqn, names)
        if g is not None:
            return [g]
    if prim == "dynamic_slice":
        starts = ctx.emit("Concat", [
            ctx.emit("Unsqueeze",
                     [n, ctx.add_const(np.asarray([0], np.int64))])
            for n in names[1:]], attrs={"axis": 0})
        starts = ctx.emit("Cast", [starts],
                          attrs={"to": int(TensorProto.INT64)})
        sizes = np.asarray(params["slice_sizes"], np.int64)
        ends = ctx.emit("Add", [starts, ctx.add_const(sizes)])
        axes = ctx.add_const(np.arange(len(sizes), dtype=np.int64))
        return [ctx.emit("Slice", [names[0], starts, ends, axes])]
    if prim == "argmax":
        axes = params["axes"]
        assert len(axes) == 1
        out = ctx.emit("ArgMax", [names[0]],
                       attrs={"axis": int(axes[0]), "keepdims": 0})
        to = _ONNX_DTYPE[np.dtype(out_aval.dtype)]
        return [ctx.emit("Cast", [out], attrs={"to": int(to)})]
    if prim == "cumsum":
        ax = ctx.add_const(np.asarray(params["axis"], np.int64))
        return [ctx.emit("CumSum", [names[0], ax],
                         attrs={"reverse": int(params.get("reverse",
                                                          False))})]
    if prim == "iota":
        aval = out_aval
        arr = np.asarray(jax.lax.iota(aval.dtype, aval.shape[
            params["dimension"]]))
        full = np.broadcast_to(
            arr.reshape([-1 if d == params["dimension"] else 1
                         for d in range(aval.ndim)]), aval.shape)
        return [ctx.add_const(np.ascontiguousarray(full), "iota")]

    raise NotImplementedError(
        f"onnx export: unsupported primitive '{prim}' "
        f"(params={list(params)})")


def _conv(ctx, eqn, names):
    p = eqn.params
    dn = p["dimension_numbers"]
    # we emit NCHW/OIHW (jax defaults for lax.conv / our conv2d_op)
    lhs_spec = dn.lhs_spec if hasattr(dn, "lhs_spec") else dn[0]
    assert tuple(lhs_spec[:2]) == (0, 1), (
        "only NCHW conv layouts supported for export")
    pads = p["padding"]
    attrs = {
        "strides": [int(s) for s in p["window_strides"]],
        "pads": ([int(lo) for lo, _ in pads]
                 + [int(hi) for _, hi in pads]),
        "dilations": [int(d) for d in p["rhs_dilation"]],
        "group": int(p["feature_group_count"]),
    }
    return ctx.emit("Conv", names, attrs=attrs)


def _pool(ctx, eqn, names, kind):
    p = eqn.params
    dims = p["window_dimensions"]
    strides = p["window_strides"]
    pads = p["padding"]
    assert dims[0] == dims[1] == 1, "pooling over batch/channel unsupported"
    attrs = {"kernel_shape": [int(d) for d in dims[2:]],
             "strides": [int(s) for s in strides[2:]],
             "pads": ([int(lo) for lo, _ in pads[2:]]
                      + [int(hi) for _, hi in pads[2:]])}
    if kind == "MaxPool":
        return ctx.emit("MaxPool", names, attrs=attrs)
    # reduce_window_sum = AveragePool(count_include_pad) * window_size —
    # include pads so border windows divide by the full window, making
    # the * window_size exact everywhere
    attrs["count_include_pad"] = 1
    out = ctx.emit("AveragePool", names, attrs=attrs)
    scale = float(np.prod([d for d in dims[2:]]))
    s = ctx.add_const(np.asarray(scale, np.float32))
    return ctx.emit("Mul", [out, s])


def _gather(ctx, eqn, names):
    """Map the jnp.take(table, ids, axis=0) pattern to ONNX Gather."""
    p = eqn.params
    dn = p["dimension_numbers"]
    operand = eqn.invars[0].aval
    # embedding-style: collapse dim 0, offset dims cover the rest
    if (tuple(dn.collapsed_slice_dims) == (0,)
            and tuple(dn.start_index_map) == (0,)
            and tuple(dn.offset_dims)
            and p["slice_sizes"][0] == 1
            and tuple(p["slice_sizes"][1:]) == tuple(operand.shape[1:])):
        idx = ctx.emit("Cast", [names[1]],
                       attrs={"to": int(TensorProto.INT64)})
        # indices carry a trailing singleton index-vector dim
        sq = ctx.add_const(np.asarray([eqn.invars[1].aval.ndim - 1],
                                      np.int64))
        idx = ctx.emit("Squeeze", [idx, sq])
        return ctx.emit("Gather", [names[0], idx], attrs={"axis": 0})
    return None


_CALL_PRIMS = {"jit", "pjit", "closed_call", "custom_jvp_call",
               "custom_vjp_call", "custom_jvp_call_jaxpr", "remat",
               "checkpoint", "custom_vjp_call_jaxpr"}


def _convert_jaxpr(ctx, jaxpr, in_names):
    """Recursively convert a (open) jaxpr; in_names aligns with invars."""
    for v, n in zip(jaxpr.invars, in_names):
        ctx.names[v] = n
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        # constant folding: every input known -> evaluate now
        in_consts = [ctx.const_of(v) for v in eqn.invars]
        if (all(c is not None for c in in_consts)
                and prim not in _CALL_PRIMS
                and not eqn.primitive.multiple_results):
            val = eqn.primitive.bind(*[jnp.asarray(c) for c in in_consts],
                                     **eqn.params)
            ctx.consts[eqn.outvars[0]] = np.asarray(val)
            continue
        if prim in _CALL_PRIMS:
            inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            if hasattr(inner, "jaxpr"):   # ClosedJaxpr
                closed = inner
            else:
                closed = jcore.ClosedJaxpr(inner, ())
            inner_in = [ctx.name_of(v) for v in eqn.invars]
            # custom_jvp_call passes (fn-args) identically; consts first
            const_names = [ctx.add_const(np.asarray(c), "cc")
                           for c in closed.consts]
            outs = _convert_jaxpr(ctx, closed.jaxpr,
                                  const_names + inner_in)
            for v, n in zip(eqn.outvars, outs):
                ctx.names[v] = n
            continue
        outs = _handle(ctx, eqn, None)
        for v, n in zip(eqn.outvars, outs):
            ctx.names[v] = n
    return [ctx.name_of(v) for v in jaxpr.outvars]


# --------------------------------------------------------------- entry

def export(executor, inputs, outputs, path, name="hetu_tpu",
           feed_shapes=None, opset=OPSET_VERSION):
    """Export the inference subgraph computing `outputs` from `inputs`.

    `executor` supplies parameter values (executor.var_values); `inputs`
    are placeholder nodes (or names); `outputs` are graph nodes.  Mirrors
    reference export(executor, inputs, outputs, path) (hetu2onnx.py:27).
    `feed_shapes` maps input name -> shape when the executor has not run
    yet (otherwise shapes come from node.shape hints).  ``opset`` stamps
    the emitted opset_import (the op surface used is stable across
    13-18, so any of those versions loads elsewhere).
    """
    from ..executor import SubExecutor
    from ..graph.node import TraceContext, Op

    in_names = [n.name if isinstance(n, Op) else n for n in inputs]
    if getattr(executor, "ps_sparse_vars", None) or \
            getattr(executor, "ps_dense_vars", None):
        raise NotImplementedError(
            "ONNX export of a PS/Hybrid executor: embedding tables live "
            "on the parameter server; rebuild the graph with a dense "
            "executor (load weights via executor.return_tensor_values())")
    sub = SubExecutor("__onnx__", list(outputs), executor)
    assert not sub.training, "export expects an inference subgraph"

    shapes = {}
    for n, nm in zip(inputs, in_names):
        shape = None
        if feed_shapes and nm in feed_shapes:
            shape = feed_shapes[nm]
        elif feed_shapes and n in feed_shapes:
            shape = feed_shapes[n]
        elif isinstance(n, Op) and getattr(n, "shape", None):
            shape = n.shape
        assert shape is not None, f"need feed_shapes for input '{nm}'"
        shapes[nm] = tuple(shape)

    params = {k: np.asarray(v) for k, v in executor.var_values.items()}

    def fwd(feeds):
        _, _, outs, _ = sub._trace(executor.var_values, executor.opt_states,
                                0, None, feeds)
        return outs

    feed_struct = {nm: jax.ShapeDtypeStruct(shapes[nm], _feed_dtype(
        executor, nm)) for nm in in_names}
    closed = jax.make_jaxpr(fwd)(feed_struct)

    ctx = _Ctx(opset=opset)
    # params appear as consts of the closed jaxpr
    const_names = []
    used_names = set()
    for cv, cval in zip(closed.jaxpr.constvars, closed.consts):
        arr = np.asarray(cval)
        nm = _const_param_name(arr, params, used_names) or ctx.fresh("w")
        used_names.add(nm)
        ctx.names[cv] = nm
        ctx.initializers.append(tensor_from_numpy(arr, nm))
    # feeds: make_jaxpr flattens the dict pytree in sorted-key order
    feed_order = sorted(in_names)
    out_names = _convert_jaxpr(
        ctx, closed.jaxpr, const_names + feed_order)

    graph = GraphProto(
        name=name, node=ctx.nodes, initializer=ctx.initializers,
        input=[value_info(nm, shapes[nm],
                          P._NP2ONNX[np.dtype(_feed_dtype(executor, nm))])
               for nm in in_names],
        output=[value_info(o, list(v.aval.shape),
                           P._NP2ONNX[np.dtype(v.aval.dtype)])
                for o, v in zip(out_names, closed.jaxpr.outvars)])
    model = ModelProto(ir_version=_IR_VERSION, producer_name="hetu_tpu",
                       producer_version="0.1", graph=graph,
                       opset_import=[OperatorSetIdProto(
                           domain="", version=opset)])
    P.save_model(model, path)
    return model


def _feed_dtype(executor, name):
    dt = getattr(executor.config, "feed_dtypes", {}) or {}
    return dt.get(name, np.float32)


def _const_param_name(arr, params, used_names=()):
    for k, v in params.items():
        if k not in used_names and v.shape == arr.shape \
                and v.dtype == arr.dtype and np.array_equal(v, arr):
            return k
    return None
