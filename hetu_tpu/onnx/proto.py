"""Self-contained ONNX protobuf reader/writer (no `onnx` dependency).

The image has no `onnx` package, so this module implements the protobuf
wire format (varint / 64-bit / length-delimited / 32-bit fields) plus just
enough of the public onnx.proto schema — ModelProto, GraphProto, NodeProto,
AttributeProto, TensorProto, ValueInfoProto, TypeProto,
OperatorSetIdProto — to emit and parse real `.onnx` files that other
toolchains accept.  Field numbers follow the onnx.proto3 spec.

Messages are plain Python objects with typed descriptors; `encode()`
returns bytes, `decode(cls, data)` parses.  Reference counterpart:
python/hetu/onnx/{hetu2onnx,onnx2hetu}.py build onnx graphs via the onnx
package's helpers; here the helpers are ours.
"""

from __future__ import annotations

import struct


# ------------------------------------------------------------ wire format

def _enc_varint(v):
    v &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _dec_varint(data, pos):
    result = shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _zz(v):  # signed int64 -> two's complement varint domain
    return v if v >= 0 else v + (1 << 64)


def _unzz(v):
    return v - (1 << 64) if v >= (1 << 63) else v


def _tag(field, wire):
    return _enc_varint((field << 3) | wire)


def _enc_field(field, wire, payload):
    if wire == 0:
        return _tag(field, 0) + _enc_varint(payload)
    if wire == 2:
        return _tag(field, 2) + _enc_varint(len(payload)) + payload
    if wire == 1:
        return _tag(field, 1) + struct.pack("<d", payload)
    if wire == 5:
        return _tag(field, 5) + struct.pack("<f", payload)
    raise ValueError(wire)


# ------------------------------------------------------------ descriptors
# kind: 'int' varint, 'sint' signed varint, 'float' 32-bit, 'double'
# 64-bit, 'bytes'/'string' length-delimited, 'msg' nested message,
# 'packed_int64'/'packed_float'/'packed_int32' packed repeated scalars.

class Message:
    FIELDS = {}  # field_number -> (name, kind, repeated, msg_cls_or_None)

    def __init__(self, **kw):
        for num, (name, kind, rep, _) in self.FIELDS.items():
            default = [] if rep else (
                0 if kind in ("int", "sint") else
                0.0 if kind in ("float", "double") else
                b"" if kind == "bytes" else
                "" if kind == "string" else None)
            setattr(self, name, kw.pop(name, default))
        if kw:
            raise TypeError(f"unknown fields {list(kw)}")

    # ---- encode
    def encode(self):
        out = bytearray()
        for num, (name, kind, rep, cls) in sorted(self.FIELDS.items()):
            val = getattr(self, name)
            if val is None or (rep and not val):
                continue
            if kind.startswith("packed_"):
                if kind in ("packed_int64", "packed_int32"):
                    payload = b"".join(_enc_varint(_zz(int(x)))
                                       for x in val)
                else:
                    payload = b"".join(struct.pack("<f", float(x))
                                       for x in val)
                out += _enc_field(num, 2, payload)
                continue
            vals = val if rep else [val]
            for v in vals:
                if kind == "int":
                    if v == 0 and not rep:
                        continue
                    out += _enc_field(num, 0, _zz(int(v)))
                elif kind == "float":
                    if v == 0.0 and not rep:
                        continue
                    out += _enc_field(num, 5, float(v))
                elif kind == "double":
                    if v == 0.0 and not rep:
                        continue
                    out += _enc_field(num, 1, float(v))
                elif kind == "string":
                    if not v and not rep:
                        continue
                    out += _enc_field(num, 2, v.encode("utf-8"))
                elif kind == "bytes":
                    if not v and not rep:
                        continue
                    out += _enc_field(num, 2, bytes(v))
                elif kind == "msg":
                    out += _enc_field(num, 2, v.encode())
                else:
                    raise ValueError(kind)
        return bytes(out)

    # ---- decode
    @classmethod
    def decode(cls, data, pos=0, end=None):
        self = cls()
        end = len(data) if end is None else end
        while pos < end:
            key, pos = _dec_varint(data, pos)
            field, wire = key >> 3, key & 7
            spec = cls.FIELDS.get(field)
            if wire == 0:
                raw, pos = _dec_varint(data, pos)
                val = _unzz(raw)
            elif wire == 2:
                ln, pos = _dec_varint(data, pos)
                val = data[pos:pos + ln]
                pos += ln
            elif wire == 5:
                val = struct.unpack_from("<f", data, pos)[0]
                pos += 4
            elif wire == 1:
                val = struct.unpack_from("<d", data, pos)[0]
                pos += 8
            else:
                raise ValueError(f"wire type {wire}")
            if spec is None:
                continue  # unknown field: skip
            name, kind, rep, mcls = spec
            if kind == "msg":
                val = mcls.decode(bytes(val))
            elif kind == "string" and wire == 2:
                val = val.decode("utf-8")
            elif kind == "bytes" and wire == 2:
                val = bytes(val)
            elif kind in ("packed_int64", "packed_int32"):
                if wire == 2:
                    vals, p2 = [], 0
                    buf = bytes(val)
                    while p2 < len(buf):
                        x, p2 = _dec_varint(buf, p2)
                        vals.append(_unzz(x))
                    getattr(self, name).extend(vals)
                    continue
                # non-packed encoding of a packed-declared field
                getattr(self, name).append(val)
                continue
            elif kind == "packed_float":
                if wire == 2:
                    buf = bytes(val)
                    vals = [struct.unpack_from("<f", buf, i)[0]
                            for i in range(0, len(buf), 4)]
                    getattr(self, name).extend(vals)
                    continue
                getattr(self, name).append(val)
                continue
            if rep:
                getattr(self, name).append(val)
            else:
                setattr(self, name, val)
        return self

    def __repr__(self):
        fields = {name: getattr(self, name)
                  for _, (name, _, _, _) in self.FIELDS.items()
                  if getattr(self, name)}
        return f"{type(self).__name__}({fields})"


# ------------------------------------------------------------ onnx schema

class TensorShapeDim(Message):
    FIELDS = {1: ("dim_value", "int", False, None),
              2: ("dim_param", "string", False, None)}


class TensorShape(Message):
    FIELDS = {1: ("dim", "msg", True, TensorShapeDim)}


class TensorTypeProto(Message):
    FIELDS = {1: ("elem_type", "int", False, None),
              2: ("shape", "msg", False, TensorShape)}


class TypeProto(Message):
    FIELDS = {1: ("tensor_type", "msg", False, TensorTypeProto)}


class ValueInfoProto(Message):
    FIELDS = {1: ("name", "string", False, None),
              2: ("type", "msg", False, TypeProto),
              3: ("doc_string", "string", False, None)}


class TensorProto(Message):
    # data_type enum values (onnx.proto3 TensorProto.DataType)
    FLOAT, UINT8, INT8, INT32, INT64 = 1, 2, 3, 6, 7
    BOOL, FLOAT16, DOUBLE, BFLOAT16 = 9, 10, 11, 16
    FIELDS = {1: ("dims", "packed_int64", True, None),
              2: ("data_type", "int", False, None),
              4: ("float_data", "packed_float", True, None),
              5: ("int32_data", "packed_int32", True, None),
              7: ("int64_data", "packed_int64", True, None),
              8: ("name", "string", False, None),
              9: ("raw_data", "bytes", False, None)}


class AttributeProto(Message):
    # type enum
    FLOAT, INT, STRING, TENSOR = 1, 2, 3, 4
    GRAPH, FLOATS, INTS, STRINGS = 5, 6, 7, 8
    FIELDS = {1: ("name", "string", False, None),
              2: ("f", "float", False, None),
              3: ("i", "int", False, None),
              4: ("s", "bytes", False, None),
              5: ("t", "msg", False, TensorProto),
              7: ("floats", "packed_float", True, None),
              8: ("ints", "packed_int64", True, None),
              9: ("strings", "bytes", True, None),
              20: ("type", "int", False, None)}


class NodeProto(Message):
    FIELDS = {1: ("input", "string", True, None),
              2: ("output", "string", True, None),
              3: ("name", "string", False, None),
              4: ("op_type", "string", False, None),
              5: ("attribute", "msg", True, AttributeProto),
              6: ("doc_string", "string", False, None),
              7: ("domain", "string", False, None)}


class GraphProto(Message):
    FIELDS = {1: ("node", "msg", True, NodeProto),
              2: ("name", "string", False, None),
              5: ("initializer", "msg", True, TensorProto),
              10: ("doc_string", "string", False, None),
              11: ("input", "msg", True, ValueInfoProto),
              12: ("output", "msg", True, ValueInfoProto),
              13: ("value_info", "msg", True, ValueInfoProto)}


class OperatorSetIdProto(Message):
    FIELDS = {1: ("domain", "string", False, None),
              2: ("version", "int", False, None)}


class ModelProto(Message):
    FIELDS = {1: ("ir_version", "int", False, None),
              2: ("producer_name", "string", False, None),
              3: ("producer_version", "string", False, None),
              4: ("domain", "string", False, None),
              5: ("model_version", "int", False, None),
              6: ("doc_string", "string", False, None),
              7: ("graph", "msg", False, GraphProto),
              8: ("opset_import", "msg", True, OperatorSetIdProto)}


# ------------------------------------------------------------ helpers

import numpy as np

_NP2ONNX = {np.dtype("float32"): TensorProto.FLOAT,
            np.dtype("float64"): TensorProto.DOUBLE,
            np.dtype("float16"): TensorProto.FLOAT16,
            np.dtype("int32"): TensorProto.INT32,
            np.dtype("int64"): TensorProto.INT64,
            np.dtype("uint8"): TensorProto.UINT8,
            np.dtype("int8"): TensorProto.INT8,
            np.dtype("bool"): TensorProto.BOOL}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}


def tensor_from_numpy(arr, name=""):
    arr = np.asarray(arr)
    t = TensorProto(name=name, dims=list(arr.shape),
                    data_type=_NP2ONNX[arr.dtype],
                    raw_data=arr.tobytes())
    return t


def tensor_to_numpy(t):
    dtype = _ONNX2NP[t.data_type]
    if t.raw_data:
        arr = np.frombuffer(t.raw_data, dtype=dtype)
    elif t.float_data:
        arr = np.array(t.float_data, dtype=dtype)
    elif t.int64_data:
        arr = np.array(t.int64_data, dtype=dtype)
    elif t.int32_data:
        arr = np.array(t.int32_data, dtype=dtype)
    else:
        arr = np.zeros(0, dtype=dtype)
    return arr.reshape(list(t.dims))


def value_info(name, shape, elem_type=TensorProto.FLOAT):
    dims = [TensorShapeDim(dim_param=d) if isinstance(d, str)
            else TensorShapeDim(dim_value=int(d)) for d in (shape or [])]
    return ValueInfoProto(name=name, type=TypeProto(
        tensor_type=TensorTypeProto(elem_type=elem_type,
                                    shape=TensorShape(dim=dims))))


def attr(name, value):
    """Build an AttributeProto from a python value."""
    if isinstance(value, bool):
        return AttributeProto(name=name, i=int(value),
                              type=AttributeProto.INT)
    if isinstance(value, int):
        return AttributeProto(name=name, i=value, type=AttributeProto.INT)
    if isinstance(value, float):
        return AttributeProto(name=name, f=value,
                              type=AttributeProto.FLOAT)
    if isinstance(value, str):
        return AttributeProto(name=name, s=value.encode("utf-8"),
                              type=AttributeProto.STRING)
    if isinstance(value, np.ndarray):
        return AttributeProto(name=name, t=tensor_from_numpy(value),
                              type=AttributeProto.TENSOR)
    if isinstance(value, (list, tuple)):
        if all(isinstance(x, int) for x in value):
            return AttributeProto(name=name, ints=list(value),
                                  type=AttributeProto.INTS)
        if all(isinstance(x, (int, float)) for x in value):
            return AttributeProto(name=name,
                                  floats=[float(x) for x in value],
                                  type=AttributeProto.FLOATS)
    raise TypeError(f"unsupported attribute {name}={value!r}")


def attr_value(a):
    """AttributeProto -> python value."""
    if a.type == AttributeProto.INT:
        return a.i
    if a.type == AttributeProto.FLOAT:
        return a.f
    if a.type == AttributeProto.STRING:
        return a.s.decode("utf-8")
    if a.type == AttributeProto.INTS:
        return list(a.ints)
    if a.type == AttributeProto.FLOATS:
        return list(a.floats)
    if a.type == AttributeProto.TENSOR:
        return tensor_to_numpy(a.t)
    raise TypeError(f"unsupported attribute type {a.type}")


def save_model(model, path):
    with open(path, "wb") as f:
        f.write(model.encode())


def load_model(path):
    with open(path, "rb") as f:
        return ModelProto.decode(f.read())
