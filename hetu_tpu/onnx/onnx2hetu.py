"""Import ONNX models as hetu_tpu graphs (reference onnx2hetu.py).

Each ONNX node maps to a SimpleOp built from a jax closure — the same
mechanism the op factory surface uses — so an imported model is a normal
graph: it can be jitted, sharded, trained (gradients flow through the
imported ops via the vjp fallback), and re-exported.

    outputs, placeholders, weights = load_onnx("model.onnx")
    ex = Executor({"pred": outputs})
    ex.run("pred", feed_dict={placeholders["x"]: batch})
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .proto import (AttributeProto, ModelProto, TensorProto, attr_value,
                    load_model, tensor_to_numpy)
from ..graph.ops_math import _simple
from ..graph import ops_misc


def _attrs(node):
    return {a.name: attr_value(a) for a in node.attribute}


# our exporter (hetu2onnx) names constant-folded initializers with these
# prefixes (iota tables, eps scalars, shape/slice index vectors, folded
# subgraphs); they are NOT parameters and must not be trained on re-import
_FOLDED_PREFIXES = ("const_", "fold_", "iota_", "cc_")


class _Importer:
    def __init__(self, graph, trainable_names=None):
        self.graph = graph
        self.values = {}     # onnx name -> Op node
        self.consts = {}     # onnx name -> np.ndarray (initializers)
        self.placeholders = {}
        # None = heuristic (float and not a folded-constant name);
        # otherwise an explicit allowlist of initializer names to train
        self.trainable_names = (set(trainable_names)
                                if trainable_names is not None else None)

    def _is_trainable(self, name, arr):
        if self.trainable_names is not None:
            return name in self.trainable_names
        return (np.issubdtype(arr.dtype, np.floating)
                and not name.startswith(_FOLDED_PREFIXES))

    def const(self, name):
        return self.consts.get(name)

    def node(self, name):
        if name in self.values:
            return self.values[name]
        if name in self.consts:
            arr = self.consts[name]
            v = ops_misc.Variable(f"onnx_{name}", value=arr,
                                  trainable=self._is_trainable(name, arr))
            self.values[name] = v
            return v
        raise KeyError(f"onnx value '{name}' is not defined yet")

    # ------------------------------------------------------------ run
    def run(self):
        for t in self.graph.initializer:
            self.consts[t.name] = tensor_to_numpy(t)
        for vi in self.graph.input:
            if vi.name in self.consts:
                continue
            ph = ops_misc.placeholder_op(vi.name)
            self.placeholders[vi.name] = ph
            self.values[vi.name] = ph
        for n in self.graph.node:
            handler = _HANDLERS.get(n.op_type)
            if handler is None:
                raise NotImplementedError(
                    f"onnx import: unsupported op '{n.op_type}'")
            outs = handler(self, n, _attrs(n))
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            for name, op in zip(n.output, outs):
                if op is not None:
                    self.values[name] = op
        return [self.node(o.name) for o in self.graph.output]


# ------------------------------------------------------------- handlers

_HANDLERS = {}


def handler(*op_types):
    def deco(fn):
        for t in op_types:
            _HANDLERS[t] = fn
        return fn
    return deco


def _in(imp, node, i):
    return imp.node(node.input[i])


@handler("Add", "Sub", "Mul", "Div", "Pow", "Max", "Min", "And", "Or",
         "Xor", "Equal", "Less", "Greater", "LessOrEqual",
         "GreaterOrEqual", "Mod")
def _binary(imp, node, attrs):
    fns = {"Add": jnp.add, "Sub": jnp.subtract, "Mul": jnp.multiply,
           "Div": jnp.divide, "Pow": jnp.power, "Max": jnp.maximum,
           "Min": jnp.minimum, "And": jnp.logical_and,
           "Or": jnp.logical_or, "Xor": jnp.logical_xor,
           "Equal": lambda a, b: (a == b), "Less": lambda a, b: (a < b),
           "Greater": lambda a, b: (a > b),
           "LessOrEqual": lambda a, b: (a <= b),
           "GreaterOrEqual": lambda a, b: (a >= b), "Mod": jnp.mod}
    f = fns[node.op_type]
    return _simple(node.op_type, f, _in(imp, node, 0), _in(imp, node, 1))


@handler("Neg", "Exp", "Log", "Tanh", "Sigmoid", "Sqrt", "Abs", "Erf",
         "Sin", "Cos", "Floor", "Ceil", "Sign", "Relu", "Reciprocal",
         "Identity", "Not", "Softplus")
def _unary(imp, node, attrs):
    fns = {"Neg": jnp.negative, "Exp": jnp.exp, "Log": jnp.log,
           "Tanh": jnp.tanh, "Sigmoid": jax.nn.sigmoid, "Sqrt": jnp.sqrt,
           "Abs": jnp.abs, "Erf": jax.scipy.special.erf, "Sin": jnp.sin,
           "Cos": jnp.cos, "Floor": jnp.floor, "Ceil": jnp.ceil,
           "Sign": jnp.sign, "Relu": jax.nn.relu,
           "Reciprocal": lambda x: 1.0 / x, "Identity": lambda x: x,
           "Not": jnp.logical_not, "Softplus": jax.nn.softplus}
    return _simple(node.op_type, fns[node.op_type], _in(imp, node, 0))


@handler("IsNaN")
def _isnan(imp, node, attrs):
    return _simple("IsNaN", jnp.isnan, _in(imp, node, 0))


@handler("IsInf")
def _isinf(imp, node, attrs):
    return _simple("IsInf", jnp.isinf, _in(imp, node, 0))


@handler("Gelu")
def _gelu(imp, node, attrs):
    approx = attrs.get("approximate", "none") == "tanh"
    return _simple("Gelu", lambda x: jax.nn.gelu(x, approximate=approx),
                   _in(imp, node, 0))


@handler("LeakyRelu")
def _leaky(imp, node, attrs):
    alpha = attrs.get("alpha", 0.01)
    return _simple("LeakyRelu",
                   lambda x: jax.nn.leaky_relu(x, negative_slope=alpha),
                   _in(imp, node, 0))


@handler("Clip")
def _clip(imp, node, attrs):
    # opset>=11: min/max as inputs (const or dynamic); opset 6: attributes
    ins = [_in(imp, node, 0)]
    consts = [None, None]
    for slot, i in enumerate((1, 2)):
        if len(node.input) > i and node.input[i]:
            c = imp.const(node.input[i])
            if c is not None:
                consts[slot] = np.asarray(c).reshape(())
            else:
                ins.append(_in(imp, node, i))
                consts[slot] = len(ins) - 1  # positional marker
    if "min" in attrs:
        consts[0] = attrs["min"]
    if "max" in attrs:
        consts[1] = attrs["max"]

    def f(x, *dyn):
        lo, hi = consts
        lo = dyn[lo - 1] if isinstance(lo, int) else lo
        hi = dyn[hi - 1] if isinstance(hi, int) else hi
        return jnp.clip(x, lo, hi)
    return _simple("Clip", f, *ins)


@handler("Softmax")
def _softmax(imp, node, attrs):
    axis = attrs.get("axis", -1)
    return _simple("Softmax", lambda x: jax.nn.softmax(x, axis=axis),
                   _in(imp, node, 0))


@handler("LogSoftmax")
def _log_softmax(imp, node, attrs):
    axis = attrs.get("axis", -1)
    return _simple("LogSoftmax",
                   lambda x: jax.nn.log_softmax(x, axis=axis),
                   _in(imp, node, 0))


@handler("MatMul")
def _matmul(imp, node, attrs):
    return _simple("MatMul", jnp.matmul, _in(imp, node, 0),
                   _in(imp, node, 1))


@handler("Gemm")
def _gemm(imp, node, attrs):
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    ta, tb = attrs.get("transA", 0), attrs.get("transB", 0)

    def f(a, b, *c):
        if ta:
            a = a.T
        if tb:
            b = b.T
        out = alpha * (a @ b)
        if c:
            out = out + beta * c[0]
        return out
    ins = [_in(imp, node, i) for i in range(len(node.input))]
    return _simple("Gemm", f, *ins)


@handler("Einsum")
def _einsum(imp, node, attrs):
    eq = attrs["equation"]
    ins = [_in(imp, node, i) for i in range(len(node.input))]
    return _simple("Einsum", lambda *xs: jnp.einsum(eq, *xs), *ins)


@handler("Conv")
def _conv(imp, node, attrs):
    strides = attrs.get("strides", [1, 1])
    dil = attrs.get("dilations", [1, 1])
    group = attrs.get("group", 1)
    pads = attrs.get("pads")
    if pads:
        half = len(pads) // 2
        padding = list(zip(pads[:half], pads[half:]))
    else:
        padding = "VALID" if attrs.get("auto_pad", "NOTSET") in (
            "NOTSET", "VALID") else "SAME"

    def f(x, w, *b):
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            rhs_dilation=dil, feature_group_count=group,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        if b:
            out = out + b[0].reshape(1, -1, 1, 1)
        return out
    ins = [_in(imp, node, i) for i in range(len(node.input))]
    return _simple("Conv", f, *ins)


def _pool_common(attrs):
    ks = attrs["kernel_shape"]
    strides = attrs.get("strides", [1] * len(ks))
    pads = attrs.get("pads", [0] * (2 * len(ks)))
    half = len(pads) // 2
    padding = [(0, 0), (0, 0)] + list(zip(pads[:half], pads[half:]))
    window = (1, 1) + tuple(ks)
    stride = (1, 1) + tuple(strides)
    return window, stride, padding


@handler("MaxPool")
def _maxpool(imp, node, attrs):
    window, stride, padding = _pool_common(attrs)
    return _simple("MaxPool", lambda x: jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, window, stride, padding),
        _in(imp, node, 0))


@handler("AveragePool")
def _avgpool(imp, node, attrs):
    window, stride, padding = _pool_common(attrs)
    cip = attrs.get("count_include_pad", 0)

    def f(x):
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride,
                                  padding)
        if cip:
            return s / np.prod(window)
        ones = jnp.ones_like(x)
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                    stride, padding)
        return s / cnt
    return _simple("AveragePool", f, _in(imp, node, 0))


@handler("GlobalAveragePool")
def _gap(imp, node, attrs):
    return _simple("GlobalAveragePool",
                   lambda x: jnp.mean(x, axis=(2, 3), keepdims=True),
                   _in(imp, node, 0))


@handler("BatchNormalization")
def _bn(imp, node, attrs):
    eps = attrs.get("epsilon", 1e-5)

    def f(x, scale, b, mean, var):
        shape = (1, -1) + (1,) * (x.ndim - 2)
        return ((x - mean.reshape(shape))
                / jnp.sqrt(var.reshape(shape) + eps)
                * scale.reshape(shape) + b.reshape(shape))
    ins = [_in(imp, node, i) for i in range(5)]
    return _simple("BatchNorm", f, *ins)


@handler("LayerNormalization")
def _ln(imp, node, attrs):
    eps = attrs.get("epsilon", 1e-5)
    axis = attrs.get("axis", -1)

    def f(x, scale, *b):
        # ONNX normalizes over all axes from `axis` through the last
        axes = tuple(range(axis % x.ndim, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        out = (x - mean) / jnp.sqrt(var + eps) * scale
        if b:
            out = out + b[0]
        return out
    ins = [_in(imp, node, i) for i in range(len(node.input))]
    return _simple("LayerNorm", f, *ins)


@handler("Reshape")
def _reshape(imp, node, attrs):
    shape = imp.const(node.input[1])
    assert shape is not None, "dynamic Reshape target unsupported"
    shape = [int(s) for s in shape]
    return _simple("Reshape", lambda x: jnp.reshape(x, shape),
                   _in(imp, node, 0))


@handler("Transpose")
def _transpose(imp, node, attrs):
    perm = attrs.get("perm")
    return _simple("Transpose",
                   lambda x: jnp.transpose(x, perm), _in(imp, node, 0))


@handler("Expand")
def _expand(imp, node, attrs):
    shape = imp.const(node.input[1])
    assert shape is not None, "dynamic Expand target unsupported"
    shape = [int(s) for s in shape]

    def f(x):
        # ONNX Expand is bidirectional broadcast: the shape tensor may have
        # lower rank than the input, so left-pad both to a common rank with
        # 1s before resolving dims (a target dim of 1 keeps the input dim)
        rank = max(len(shape), x.ndim)
        tshape = [1] * (rank - len(shape)) + list(shape)
        xshape = (1,) * (rank - x.ndim) + x.shape
        tgt = [xs if s == 1 else s for s, xs in zip(tshape, xshape)]
        return jnp.broadcast_to(jnp.reshape(x, xshape), tgt)
    return _simple("Expand", f, _in(imp, node, 0))


@handler("Concat")
def _concat(imp, node, attrs):
    axis = attrs.get("axis", 0)
    ins = [_in(imp, node, i) for i in range(len(node.input))]
    return _simple("Concat", lambda *xs: jnp.concatenate(xs, axis=axis),
                   *ins)


@handler("Split")
def _split(imp, node, attrs):
    axis = attrs.get("axis", 0)
    splits = attrs.get("split")
    if splits is None and len(node.input) > 1:
        splits = [int(s) for s in imp.const(node.input[1])]
    n_out = len(node.output)
    x = _in(imp, node, 0)
    outs = []
    for i in range(n_out):
        def f(v, i=i):
            if splits is None:
                return jnp.split(v, n_out, axis=axis)[i]
            offs = np.cumsum([0] + list(splits))
            sl = [slice(None)] * v.ndim
            sl[axis] = slice(int(offs[i]), int(offs[i + 1]))
            return v[tuple(sl)]
        outs.append(_simple(f"Split{i}", f, x))
    return outs


@handler("Slice")
def _slice(imp, node, attrs):
    starts = imp.const(node.input[1])
    ends = imp.const(node.input[2])
    axes = imp.const(node.input[3]) if len(node.input) > 3 else None
    steps = imp.const(node.input[4]) if len(node.input) > 4 else None
    assert starts is not None and ends is not None, \
        "dynamic Slice unsupported"

    def f(x):
        sl = [slice(None)] * x.ndim
        ax = axes if axes is not None else np.arange(len(starts))
        st = steps if steps is not None else np.ones(len(starts), int)
        for a, s, e, p in zip(ax, starts, ends, st):
            s, e, p = int(s), int(e), int(p)
            e = None if e >= np.iinfo(np.int32).max else e
            e = None if (p < 0 and e < -x.shape[int(a)]) else e
            sl[int(a)] = slice(s, e, p)
        return x[tuple(sl)]
    return _simple("Slice", f, _in(imp, node, 0))


@handler("Gather")
def _gather(imp, node, attrs):
    axis = attrs.get("axis", 0)
    idx = imp.const(node.input[1])
    if idx is not None:
        return _simple("Gather",
                       lambda x: jnp.take(x, jnp.asarray(idx), axis=axis),
                       _in(imp, node, 0))
    return _simple("Gather",
                   lambda x, i: jnp.take(x, i.astype(jnp.int32),
                                         axis=axis),
                   _in(imp, node, 0), _in(imp, node, 1))


@handler("Cast")
def _cast(imp, node, attrs):
    from .proto import _ONNX2NP
    to = _ONNX2NP[attrs["to"]]
    return _simple("Cast", lambda x: x.astype(to), _in(imp, node, 0))


@handler("Where")
def _where(imp, node, attrs):
    return _simple("Where", jnp.where, _in(imp, node, 0),
                   _in(imp, node, 1), _in(imp, node, 2))


@handler("ReduceSum", "ReduceMax", "ReduceMin", "ReduceMean",
         "ReduceProd")
def _reduce(imp, node, attrs):
    fns = {"ReduceSum": jnp.sum, "ReduceMax": jnp.max,
           "ReduceMin": jnp.min, "ReduceMean": jnp.mean,
           "ReduceProd": jnp.prod}
    f = fns[node.op_type]
    keep = bool(attrs.get("keepdims", 1))
    axes = attrs.get("axes")
    if axes is None and len(node.input) > 1:
        c = imp.const(node.input[1])
        axes = [int(a) for a in c] if c is not None else None
    axes_t = tuple(axes) if axes is not None else None
    return _simple(node.op_type,
                   lambda x: f(x, axis=axes_t, keepdims=keep),
                   _in(imp, node, 0))


@handler("ArgMax")
def _argmax(imp, node, attrs):
    axis = attrs.get("axis", 0)
    keep = bool(attrs.get("keepdims", 1))

    def f(x):
        out = jnp.argmax(x, axis=axis)
        if keep:
            out = jnp.expand_dims(out, axis)
        return out
    return _simple("ArgMax", f, _in(imp, node, 0))


@handler("CumSum")
def _cumsum(imp, node, attrs):
    ax = imp.const(node.input[1])
    assert ax is not None
    reverse = bool(attrs.get("reverse", 0))

    def f(x):
        a = int(ax)
        if reverse:
            return jnp.flip(jnp.cumsum(jnp.flip(x, a), axis=a), a)
        return jnp.cumsum(x, axis=a)
    return _simple("CumSum", f, _in(imp, node, 0))


@handler("Squeeze")
def _squeeze(imp, node, attrs):
    axes = attrs.get("axes")
    if axes is None and len(node.input) > 1:
        axes = [int(a) for a in imp.const(node.input[1])]
    axes_t = tuple(axes) if axes else None
    return _simple("Squeeze", lambda x: jnp.squeeze(x, axis=axes_t),
                   _in(imp, node, 0))


@handler("Unsqueeze")
def _unsqueeze(imp, node, attrs):
    axes = attrs.get("axes")
    if axes is None and len(node.input) > 1:
        axes = [int(a) for a in imp.const(node.input[1])]

    def f(x):
        out = x
        for a in sorted(axes):
            out = jnp.expand_dims(out, a)
        return out
    return _simple("Unsqueeze", f, _in(imp, node, 0))


@handler("Flatten")
def _flatten(imp, node, attrs):
    axis = attrs.get("axis", 1)
    return _simple("Flatten",
                   lambda x: x.reshape(
                       int(np.prod(x.shape[:axis]) or 1), -1),
                   _in(imp, node, 0))


@handler("Constant")
def _constant(imp, node, attrs):
    for key in ("value", "value_float", "value_int", "value_floats",
                "value_ints"):
        if key in attrs:
            arr = np.asarray(attrs[key])
            imp.consts[node.output[0]] = arr
            return None
    raise NotImplementedError("Constant without tensor value")


@handler("ConstantOfShape")
def _cos_(imp, node, attrs):
    shape = imp.const(node.input[0])
    assert shape is not None
    val = attrs.get("value", np.zeros(1, np.float32))
    arr = np.full([int(s) for s in shape], np.asarray(val).reshape(-1)[0])
    imp.consts[node.output[0]] = arr
    return None


@handler("Dropout")
def _dropout(imp, node, attrs):
    # inference: identity (reference onnx handlers do the same)
    return _simple("Dropout", lambda x: x, _in(imp, node, 0))


@handler("Shape")
def _shape(imp, node, attrs):
    return _simple("Shape",
                   lambda x: jnp.asarray(x.shape, jnp.int64),
                   _in(imp, node, 0))


# --------------------------------------------------------------- entry

def load_onnx(path, trainable_names=None):
    """Parse an .onnx file -> (output nodes, placeholders, weights).

    Mirrors reference onnx2hetu.load_onnx returning executor-ready graph
    nodes (onnx2hetu.py).  ``trainable_names`` optionally restricts which
    initializers import as trainable Variables; by default all float
    initializers except exporter-folded constants (const_/fold_/iota_/cc_
    names) are trainable."""
    model = load_model(path)
    imp = _Importer(model.graph, trainable_names=trainable_names)
    outputs = imp.run()
    weights = {f"onnx_{k}": v for k, v in imp.consts.items()}
    return outputs, imp.placeholders, weights
