"""Profilers: per-step timing, per-op HLO cost attribution, comm probe.

Reference: python/hetu/profiler.py (HetuProfiler:55 times each node over
synthetic inputs with CUDA events; NCCLProfiler:389 measures allreduce
bandwidth per group topology; TimerSubExecutor wraps each compute).

TPU-native: the per-op wall-clock loop is meaningless under XLA fusion, so
HetuProfiler reports (a) whole-step wall time with device sync, (b) XLA
cost-analysis FLOPs/bytes per compiled step, and (c) optional xprof trace
capture via jax.profiler.  NCCLProfiler becomes a collective probe over
mesh axes (ICI/DCN bandwidth), feeding the planner's cost model exactly as
the reference's fed Galvatron.
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp


def materialize_barrier(out):
    """Force device completion by fetching one scalar of ``out`` to the
    host.  ``jax.block_until_ready`` is NOT a reliable barrier on every
    backend we run on — through the axon TPU tunnel it returns before
    execution finishes (measured in round 3: a repeat-call matmul probe
    reported 167x the chip's physical peak) — while a host fetch of a
    result element cannot return early.  EVERY array leaf is fetched
    (one scalar each): outputs may come from SEPARATE dispatches (e.g. a
    PS-mode step's phases), and awaiting only one would let the others
    float past the timer."""
    val = None
    for leaf in jax.tree_util.tree_leaves(out):
        if leaf is None or not hasattr(leaf, "dtype") or leaf.size == 0:
            continue
        val = np.asarray(jnp.ravel(leaf)[0])
    return val


class HetuProfiler:
    def __init__(self, executor=None, feed_shapes=None, log_file=None):
        self.executor = executor
        self.feed_shapes = feed_shapes or {}
        self.log_file = log_file
        self.records = []

    def profile_step(self, name="train", feed_dict=None, warmup=2, iters=10):
        """Whole-step timing with blocking on outputs."""
        feed_dict = feed_dict or self._synth_feeds()
        sub = self.executor.subexecutor[name]
        for _ in range(warmup):
            res = sub.run(feed_dict)
        materialize_barrier(res)
        t0 = time.perf_counter()
        for _ in range(iters):
            res = sub.run(feed_dict)
        materialize_barrier(res)
        dt = (time.perf_counter() - t0) / iters
        self.records.append({"name": name, "step_time_s": dt})
        if self.log_file:
            with open(self.log_file, "a") as f:
                f.write(f"{name} step_time_s={dt:.6f}\n")
        return dt

    def _compiled_step(self, name):
        """AOT-lower + compile the step once for analysis (a full extra
        XLA compile — shared by cost_analysis/memory_analysis so asking
        for both pays it once)."""
        cached = getattr(self, "_analysis_cache", {}).get(name)
        if cached is not None:
            return cached
        sub = self.executor.subexecutor[name]
        if not sub._compiled:
            return None
        fn = next(iter(sub._compiled.values()))
        try:
            from .executor import gather_feeds
            # the compiled step takes NAME-keyed feeds (node-keyed dicts
            # don't even sort as a jax pytree); route synthetic feeds
            # through the same conversion SubExecutor.run uses — with
            # peek=True so the analysis never consumes a training batch
            compiled = fn.lower(
                self.executor.var_values, self.executor.opt_states,
                self.executor.step, self.executor.rng,
                gather_feeds(sub, self._synth_feeds(),
                             peek=True)).compile()
        except Exception:
            return None
        if not hasattr(self, "_analysis_cache"):
            self._analysis_cache = {}
        self._analysis_cache[name] = compiled
        return compiled

    def cost_analysis(self, name="train"):
        """FLOPs / bytes-accessed of the compiled step (XLA cost model)."""
        compiled = self._compiled_step(name)
        if compiled is None:
            return None
        try:
            cost = compiled.cost_analysis()
        except Exception:
            return None
        # pre-0.5 jax returns a one-element list of per-device dicts;
        # newer jax returns the dict directly
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        return cost

    def memory_analysis(self, name="train"):
        """HBM footprint of the compiled step — the role of the
        reference's memory-plan dry-run (memory_pool.py:142 test_memory):
        bytes for arguments (params+opt state+feeds), outputs, temps, and
        the generated program, per the XLA allocator.  Returns a dict or
        None before first compile."""
        compiled = self._compiled_step(name)
        if compiled is None:
            return None
        try:
            m = compiled.memory_analysis()
        except Exception:
            return None
        if m is None:
            return None
        out = {k: int(getattr(m, k)) for k in (
            "argument_size_in_bytes", "output_size_in_bytes",
            "temp_size_in_bytes", "generated_code_size_in_bytes",
            "alias_size_in_bytes", "peak_memory_in_bytes")
            if hasattr(m, k)}
        if out:
            # donation aliases params/opt state into outputs; only the
            # NON-aliased output bytes (losses, metrics, PS side grads)
            # are additional live memory at step end
            out["peak_estimate_bytes"] = (
                out.get("argument_size_in_bytes", 0)
                + out.get("temp_size_in_bytes", 0)
                + out.get("generated_code_size_in_bytes", 0)
                + max(0, out.get("output_size_in_bytes", 0)
                      - out.get("alias_size_in_bytes", 0)))
        return out or None

    def _synth_feeds(self):
        return {k: np.zeros(s, np.float32) for k, s in self.feed_shapes.items()}

    def start_trace(self, logdir="/tmp/hetu_tpu_trace"):
        jax.profiler.start_trace(logdir)

    def stop_trace(self):
        jax.profiler.stop_trace()


class TPUProfiler(HetuProfiler):
    pass


class NCCLProfiler:
    """Collective bandwidth probe over mesh axes (reference profiler.py:389
    NCCLProfiler measured allreduce over enumerated NCCL groups; here we
    measure psum/all_gather/all_to_all over each axis of a mesh — the
    numbers feed the auto-parallel cost model)."""

    def __init__(self, mesh=None):
        from .parallel.mesh import default_mesh
        self.mesh = mesh if mesh is not None else default_mesh()

    def profile_allreduce(self, size_mb=16, axis=None, iters=5):
        from jax import shard_map
        from jax.sharding import PartitionSpec as P
        axis = axis or self.mesh.axis_names[0]
        n = self.mesh.shape[axis]
        nelem = int(size_mb * 1024 * 1024 / 4)
        x = jnp.ones((n * ((nelem + n - 1) // n),), jnp.float32)

        @jax.jit
        def f(x):
            return shard_map(lambda v: jax.lax.psum(v, axis), mesh=self.mesh,
                             in_specs=P(axis), out_specs=P(axis))(x)

        materialize_barrier(f(x))
        t0 = time.perf_counter()
        for i in range(iters):
            # distinct input + per-call fetch: successive f(x) calls are
            # independent dispatches, and identical ones can be memoized
            # (see materialize_barrier's docstring for the tunnel model)
            r = f(x.at[0].set(i + 1))
            materialize_barrier(r)
        dt = (time.perf_counter() - t0) / iters
        bytes_moved = 2 * (n - 1) / n * x.nbytes
        return {"axis": axis, "time_s": dt,
                "algo_bw_gbps": bytes_moved / dt / 1e9}
