"""Model-quality metrics (reference python/hetu/metrics.py:17-315).

Numpy-side like the reference: accuracy, precision/recall/F1, AUC (ROC and
PR), confusion helpers.
"""

from __future__ import annotations

import numpy as np


def softmax_np(x, axis=-1):
    x = x - np.max(x, axis=axis, keepdims=True)
    e = np.exp(x)
    return e / np.sum(e, axis=axis, keepdims=True)


def accuracy(y_pred, y_true):
    """y_pred logits/probs (N,C) or labels (N,); y_true one-hot or labels."""
    y_pred = np.asarray(y_pred)
    y_true = np.asarray(y_true)
    if y_pred.ndim > 1:
        y_pred = np.argmax(y_pred, axis=-1)
    if y_true.ndim > 1:
        y_true = np.argmax(y_true, axis=-1)
    return float(np.mean(y_pred == y_true))


def _binary_counts(y_pred, y_true, threshold=0.5):
    y_pred = np.asarray(y_pred).reshape(-1) >= threshold
    y_true = np.asarray(y_true).reshape(-1) >= 0.5
    tp = np.sum(y_pred & y_true)
    fp = np.sum(y_pred & ~y_true)
    fn = np.sum(~y_pred & y_true)
    tn = np.sum(~y_pred & ~y_true)
    return tp, fp, fn, tn


def precision(y_pred, y_true, threshold=0.5):
    tp, fp, _, _ = _binary_counts(y_pred, y_true, threshold)
    return float(tp / (tp + fp)) if tp + fp else 0.0


def recall(y_pred, y_true, threshold=0.5):
    tp, _, fn, _ = _binary_counts(y_pred, y_true, threshold)
    return float(tp / (tp + fn)) if tp + fn else 0.0


def f1_score(y_pred, y_true, threshold=0.5):
    p = precision(y_pred, y_true, threshold)
    r = recall(y_pred, y_true, threshold)
    return 2 * p * r / (p + r) if p + r else 0.0


def auc_score(y_pred, y_true):
    """ROC AUC by rank statistic (reference metrics.py auc)."""
    y_pred = np.asarray(y_pred).reshape(-1)
    y_true = np.asarray(y_true).reshape(-1) >= 0.5
    n_pos = int(np.sum(y_true))
    n_neg = len(y_true) - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.5
    order = np.argsort(y_pred)
    ranks = np.empty_like(order, dtype=np.float64)
    # average ranks for ties
    sorted_pred = y_pred[order]
    ranks[order] = np.arange(1, len(y_pred) + 1)
    i = 0
    while i < len(sorted_pred):
        j = i
        while j + 1 < len(sorted_pred) and sorted_pred[j + 1] == sorted_pred[i]:
            j += 1
        if j > i:
            avg = (i + j + 2) / 2.0
            ranks[order[i:j + 1]] = avg
        i = j + 1
    sum_pos = np.sum(ranks[y_true])
    return float((sum_pos - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg))


def pr_auc_score(y_pred, y_true):
    """Area under precision-recall curve (trapezoid)."""
    y_pred = np.asarray(y_pred).reshape(-1)
    y_true = np.asarray(y_true).reshape(-1) >= 0.5
    order = np.argsort(-y_pred)
    y_true = y_true[order]
    tp = np.cumsum(y_true)
    fp = np.cumsum(~y_true)
    n_pos = tp[-1] if len(tp) else 0
    if n_pos == 0:
        return 0.0
    prec = tp / np.maximum(tp + fp, 1)
    rec = tp / n_pos
    return float(np.trapezoid(prec, rec))


class Accuracy:
    def __init__(self):
        self.correct = 0
        self.total = 0

    def update(self, y_pred, y_true):
        y_pred = np.asarray(y_pred)
        y_true = np.asarray(y_true)
        if y_pred.ndim > 1:
            y_pred = np.argmax(y_pred, axis=-1)
        if y_true.ndim > 1:
            y_true = np.argmax(y_true, axis=-1)
        self.correct += int(np.sum(y_pred == y_true))
        self.total += len(y_pred)

    def result(self):
        return self.correct / self.total if self.total else 0.0
