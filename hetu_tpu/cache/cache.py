"""Embedding cache core: ctypes binding over the C++ library, with a
pure-Python mirror used when no toolchain is available.

Both expose the same interface; `EmbeddingCache(...)` picks native when the
.so builds.  Policies: 'LRU', 'LFU', 'LFUOpt' (reference lru_cache.h:17,
lfu_cache.h:17, lfuopt_cache.h:18).
"""

from __future__ import annotations

import ctypes
from collections import OrderedDict

import numpy as np

_POLICIES = {"LRU": 0, "LFU": 1, "LFUOPT": 2}


def _policy_code(name):
    code = _POLICIES.get(str(name).upper())
    if code is None:
        raise ValueError(f"unknown cache policy {name!r}; "
                         f"choose from {sorted(_POLICIES)}")
    return code


class NativeCache:
    """ctypes wrapper over native/cache.cpp (flat C ABI)."""

    _lib = None

    @classmethod
    def load_lib(cls):
        if cls._lib is None:
            from ..native import build_and_load
            lib = build_and_load("cache.cpp", "libhetu_cache.so")
            if lib is not None:
                i64p = ctypes.POINTER(ctypes.c_int64)
                f32p = ctypes.POINTER(ctypes.c_float)
                u8p = ctypes.POINTER(ctypes.c_uint8)
                lib.cache_create.restype = ctypes.c_void_p
                lib.cache_create.argtypes = [ctypes.c_int, ctypes.c_int64,
                                             ctypes.c_int64]
                lib.cache_destroy.argtypes = [ctypes.c_void_p]
                lib.cache_size.restype = ctypes.c_int64
                lib.cache_size.argtypes = [ctypes.c_void_p]
                lib.cache_counters.argtypes = [ctypes.c_void_p, i64p, i64p,
                                               i64p]
                lib.cache_lookup.argtypes = [ctypes.c_void_p, i64p,
                                             ctypes.c_int64, f32p, u8p]
                lib.cache_versions.argtypes = [ctypes.c_void_p, i64p,
                                               ctypes.c_int64, i64p]
                lib.cache_insert.restype = ctypes.c_int64
                lib.cache_insert.argtypes = [ctypes.c_void_p, i64p,
                                             ctypes.c_int64, f32p, i64p,
                                             i64p, f32p, ctypes.c_int64]
                lib.cache_update.restype = ctypes.c_int64
                lib.cache_update.argtypes = [ctypes.c_void_p, i64p,
                                             ctypes.c_int64, f32p]
                lib.cache_max_updates.restype = ctypes.c_int64
                lib.cache_max_updates.argtypes = [ctypes.c_void_p]
                lib.cache_dirty.argtypes = [ctypes.c_void_p, i64p,
                                            ctypes.c_int64, u8p]
                lib.cache_collect_dirty.restype = ctypes.c_int64
                lib.cache_collect_dirty.argtypes = [ctypes.c_void_p, i64p,
                                                    f32p, ctypes.c_int64]
                lib.cache_refresh.argtypes = [ctypes.c_void_p, i64p,
                                              ctypes.c_int64, f32p, i64p]
            cls._lib = lib if lib is not None else False
        return cls._lib or None

    def __init__(self, limit, width, policy="LRU"):
        lib = self.load_lib()
        assert lib is not None, "native cache library unavailable"
        self._l = lib
        self.limit = int(limit)
        self.width = int(width)
        self._h = lib.cache_create(_policy_code(policy), self.limit,
                                   self.width)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._l.cache_destroy(self._h)
        except Exception:
            pass

    @staticmethod
    def _i64(a):
        return np.ascontiguousarray(a, np.int64)

    @staticmethod
    def _f32(a):
        return np.ascontiguousarray(a, np.float32)

    def _ptr(self, a, typ):
        return a.ctypes.data_as(ctypes.POINTER(typ))

    def lookup(self, ids):
        ids = self._i64(ids)
        n = len(ids)
        out = np.zeros((n, self.width), np.float32)
        hit = np.zeros(n, np.uint8)
        self._l.cache_lookup(self._h, self._ptr(ids, ctypes.c_int64), n,
                             self._ptr(out, ctypes.c_float),
                             self._ptr(hit, ctypes.c_uint8))
        return out, hit.astype(bool)

    def versions(self, ids):
        ids = self._i64(ids)
        n = len(ids)
        out = np.zeros(n, np.int64)
        self._l.cache_versions(self._h, self._ptr(ids, ctypes.c_int64), n,
                               self._ptr(out, ctypes.c_int64))
        return out

    def insert(self, ids, rows, versions=None):
        ids = self._i64(ids)
        rows = self._f32(rows)
        n = len(ids)
        if versions is None:
            versions = np.zeros(n, np.int64)
        versions = self._i64(versions)
        ev_ids = np.zeros(n + 1, np.int64)
        ev_grads = np.zeros((n + 1, self.width), np.float32)
        n_ev = self._l.cache_insert(
            self._h, self._ptr(ids, ctypes.c_int64), n,
            self._ptr(rows, ctypes.c_float),
            self._ptr(versions, ctypes.c_int64),
            self._ptr(ev_ids, ctypes.c_int64),
            self._ptr(ev_grads, ctypes.c_float), n + 1)
        return ev_ids[:n_ev], ev_grads[:n_ev]

    def update(self, ids, deltas):
        ids = self._i64(ids)
        deltas = self._f32(deltas)
        return int(self._l.cache_update(
            self._h, self._ptr(ids, ctypes.c_int64), len(ids),
            self._ptr(deltas, ctypes.c_float)))

    def max_updates(self):
        return int(self._l.cache_max_updates(self._h))

    def dirty(self, ids):
        ids = self._i64(ids)
        out = np.zeros(len(ids), np.uint8)
        self._l.cache_dirty(self._h, self._ptr(ids, ctypes.c_int64),
                            len(ids), self._ptr(out, ctypes.c_uint8))
        return out.astype(bool)

    def collect_dirty(self):
        cap = max(1, self.size())
        ids = np.zeros(cap, np.int64)
        grads = np.zeros((cap, self.width), np.float32)
        k = self._l.cache_collect_dirty(
            self._h, self._ptr(ids, ctypes.c_int64),
            self._ptr(grads, ctypes.c_float), cap)
        return ids[:k], grads[:k]

    def refresh(self, ids, rows, versions):
        ids = self._i64(ids)
        rows = self._f32(rows)
        versions = self._i64(versions)
        self._l.cache_refresh(self._h, self._ptr(ids, ctypes.c_int64),
                              len(ids), self._ptr(rows, ctypes.c_float),
                              self._ptr(versions, ctypes.c_int64))

    def size(self):
        return int(self._l.cache_size(self._h))

    def counters(self):
        h = ctypes.c_int64()
        m = ctypes.c_int64()
        e = ctypes.c_int64()
        self._l.cache_counters(self._h, ctypes.byref(h), ctypes.byref(m),
                               ctypes.byref(e))
        return {"hits": h.value, "misses": m.value, "evictions": e.value}


class PythonCache:
    """Pure-Python mirror of the native cache (same interface/semantics)."""

    def __init__(self, limit, width, policy="LRU"):
        self.limit = int(limit)
        self.width = int(width)
        self.policy = _policy_code(policy)
        self.store = OrderedDict()  # id -> [row, grad, version, updates, dirty, freq]
        self.hits = self.misses = self.evictions = 0
        self._max_upd = 0

    def _touch(self, id_):
        e = self.store[id_]
        if self.policy == 0:
            self.store.move_to_end(id_)
        else:
            e[5] += 1

    def _evict_one(self):
        if self.policy == 0:
            vid = next(iter(self.store))
        else:
            minf = min(e[5] for e in self.store.values())
            vid = next(i for i, e in self.store.items() if e[5] == minf)
            if self.policy == 2 and \
                    sum(1 for e in self.store.values() if e[5] == minf) == 1:
                for e in self.store.values():
                    e[5] //= 2
        e = self.store.pop(vid)
        self.evictions += 1
        if e[4]:
            return vid, e[1]
        return None

    def lookup(self, ids):
        ids = np.asarray(ids, np.int64)
        out = np.zeros((len(ids), self.width), np.float32)
        hit = np.zeros(len(ids), bool)
        for i, id_ in enumerate(ids):
            e = self.store.get(int(id_))
            if e is None:
                self.misses += 1
                continue
            hit[i] = True
            self.hits += 1
            out[i] = e[0]
            self._touch(int(id_))
        return out, hit

    def versions(self, ids):
        return np.array([self.store[int(i)][2] if int(i) in self.store
                         else -1 for i in np.asarray(ids)], np.int64)

    def insert(self, ids, rows, versions=None):
        ids = np.asarray(ids, np.int64)
        rows = np.asarray(rows, np.float32)
        if versions is None:
            versions = np.zeros(len(ids), np.int64)
        ev_ids, ev_grads = [], []
        for i, id_ in enumerate(ids):
            id_ = int(id_)
            if id_ in self.store:
                e = self.store[id_]
                e[0] = rows[i].copy()
                e[2] = int(versions[i])
                self._touch(id_)
                continue
            if len(self.store) >= self.limit:
                ev = self._evict_one()
                if ev is not None:
                    ev_ids.append(ev[0])
                    ev_grads.append(ev[1])
            self.store[id_] = [rows[i].copy(),
                               np.zeros(self.width, np.float32),
                               int(versions[i]), 0, False, 1]
        if ev_ids:
            return np.asarray(ev_ids, np.int64), np.stack(ev_grads)
        return (np.zeros(0, np.int64),
                np.zeros((0, self.width), np.float32))

    def update(self, ids, deltas):
        ids = np.asarray(ids, np.int64)
        deltas = np.asarray(deltas, np.float32)
        missed = 0
        for i, id_ in enumerate(ids):
            e = self.store.get(int(id_))
            if e is None:
                missed += 1
                continue
            e[1] += deltas[i]
            e[0] += deltas[i]
            e[3] += 1
            e[4] = True
            self._max_upd = max(self._max_upd, e[3])
            self._touch(int(id_))
        return missed

    def max_updates(self):
        return self._max_upd

    def dirty(self, ids):
        return np.array([int(i) in self.store and self.store[int(i)][4]
                         for i in np.asarray(ids)], bool)

    def collect_dirty(self):
        ids, grads = [], []
        for id_, e in self.store.items():
            if e[4]:
                ids.append(id_)
                grads.append(e[1].copy())
                e[1][:] = 0
                e[3] = 0
                e[4] = False
        self._max_upd = 0
        if ids:
            return np.asarray(ids, np.int64), np.stack(grads)
        return np.zeros(0, np.int64), np.zeros((0, self.width), np.float32)

    def refresh(self, ids, rows, versions):
        for i, id_ in enumerate(np.asarray(ids, np.int64)):
            e = self.store.get(int(id_))
            if e is None:
                continue
            e[0] = np.asarray(rows[i], np.float32).copy()
            e[2] = int(np.asarray(versions)[i])

    def size(self):
        return len(self.store)

    def counters(self):
        return {"hits": self.hits, "misses": self.misses,
                "evictions": self.evictions}


def merge_sparse(ids_a, rows_a, ids_b, rows_b):
    """Merge two (ids, rows) sparse delta sets, summing duplicate ids
    (scatter-add semantics — write-back deltas commute, so an outage
    replay buffer can keep merging new pushes into itself without
    growing per step).  Returns sorted unique ids + merged float32 rows.
    Used by CacheSparseTable's PS-outage push backlog."""
    ids = np.concatenate([np.asarray(ids_a, np.int64).reshape(-1),
                          np.asarray(ids_b, np.int64).reshape(-1)])
    rows = np.concatenate([np.asarray(rows_a, np.float32),
                           np.asarray(rows_b, np.float32)])
    uniq, inv = np.unique(ids, return_inverse=True)
    merged = np.zeros((len(uniq), rows.shape[1]), np.float32)
    np.add.at(merged, inv, rows)
    return uniq, merged


def EmbeddingCache(limit, width, policy="LRU", prefer_native=True):
    """Factory: native C++ cache when buildable, Python mirror otherwise."""
    if prefer_native and NativeCache.load_lib() is not None:
        return NativeCache(limit, width, policy)
    return PythonCache(limit, width, policy)
