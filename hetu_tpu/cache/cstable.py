"""CacheSparseTable: the cache-enabled embedding facade (HET, VLDB'22).

Reference: python/hetu/cstable.py:19-187 (embedding_lookup/update/
push_pull + perf counters) over src/hetu_cache's bounded-staleness sync
protocol (hetu_client.cc kSyncEmbedding/kPushEmbedding/kPushSyncEmbedding).

Protocol here (same semantics, TPU-shaped):
  lookup(ids):
    - cache hits within the pull staleness bound are served locally;
    - hits whose version lags the server by > pull_bound are re-synced via
      the PS sync_embedding RPC (server returns only rows that moved);
    - misses are sparse-pulled and inserted (evicted dirty lines flush
      their accumulated updates to the PS on the way out).
  update(ids, deltas):
    - deltas (already optimizer-scaled, e.g. -lr*grad) accumulate into
      cached lines (write-back);
    - once any line holds > push_bound unpushed updates, all dirty lines
      are pushed via push_embedding.

Async variants return concurrent.futures so the next batch's lookup can
overlap the current step (reference prefetch + CSEvent, stream.py:90-105).

Graceful degradation during a PS outage (the comm raising
ConnectionError / PSConnectionError): cached lines keep being served
within a bounded staleness window, rows that cannot be fetched are
served as zero vectors (the standard missing-embedding fallback — NOT
inserted, so they re-fetch after recovery), and pushes accumulate into
a bounded replay backlog that drains on the next successful PS contact.
The bounds: HETU_CACHE_MAX_STALE consecutive failed RPCs (default 100)
or HETU_CACHE_BACKLOG_ROWS buffered rows (default 100000), after which
the outage surfaces to the caller instead of degrading further.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from .. import envvars, locks, telemetry

import numpy as np

from .cache import EmbeddingCache, merge_sparse


class CacheSparseTable:
    def __init__(self, limit, vocab_size, width, key, comm=None,
                 policy="LFUOpt", pull_bound=0, push_bound=0,
                 prefer_native=True):
        """``comm``: a PS client/server exposing sparse_pull/sparse_push/
        sync_embedding/push_embedding (ps/client.py or ps/server.py)."""
        self.key = key
        self.vocab = int(vocab_size)
        self.width = int(width)
        self.comm = comm
        self.pull_bound = int(pull_bound)
        self.push_bound = int(push_bound)
        self.cache = EmbeddingCache(limit, width, policy,
                                    prefer_native=prefer_native)
        self._pool = ThreadPoolExecutor(max_workers=1)
        # cache state is not thread-safe; one lock serializes the sync
        # methods against pool-submitted async calls.  LOCKING CONTRACT
        # (audited for concurrent serving waves): every public entry
        # point — embedding_lookup/update/push_pull/flush/perf_summary
        # and the async variants (which run the sync methods on the
        # single pool thread) — takes self._lock; _replay and
        # _push_or_buffer mutate the outage backlog and MUST only be
        # called with the lock held (they are internal to the locked
        # region, never a public surface).  RLock, not Lock: the fused
        # push_pull holds it across _update + _lookup.
        self._lock = locks.TracedRLock("cstable")
        # perf counters (reference cstable.py:126-187)
        self.num_lookups = 0
        self.num_rows_looked = 0
        self.num_pulled_rows = 0
        self.num_pulled_bytes = 0
        self.num_pushed_rows = 0
        self.num_synced_rows = 0
        # outage degradation state (module docstring)
        self.max_stale = envvars.get_int("HETU_CACHE_MAX_STALE")
        self.max_backlog_rows = envvars.get_int(
            "HETU_CACHE_BACKLOG_ROWS")
        self._outage = 0            # consecutive failed PS RPCs
        self._backlog = (np.zeros(0, np.int64),
                         np.zeros((0, self.width), np.float32))
        self._backlog_t0 = None     # when the oldest buffered push
        # landed (drives the cache.staleness_s gauge)
        self.num_ps_failures = 0
        self.num_stale_served = 0
        self.num_zero_served = 0
        self.num_replayed_rows = 0
        self._evictions_seen = 0    # telemetry delta base (clean
        # evictions don't surface through insert's dirty write-back)

    # ---------------- outage machinery ---------------- #

    def _outage_tick(self, err):
        """Count one failed PS RPC; degrade silently within the budget,
        surface the outage once past it."""
        self._outage += 1
        self.num_ps_failures += 1
        if self._outage > self.max_stale:
            raise ConnectionError(
                f"PS outage for table {self.key!r} exceeded the "
                f"staleness budget (HETU_CACHE_MAX_STALE="
                f"{self.max_stale} consecutive failed RPCs; "
                f"{len(self._backlog[0])} rows buffered); last error: "
                f"{err}") from err

    def _replay(self):
        """Drain the push backlog on (re-)contact; no-op while empty.
        Caller MUST hold self._lock (see the locking contract in
        __init__)."""
        bids, bgrads = self._backlog
        if bids.size == 0 or self.comm is None:
            return
        try:
            self.comm.push_embedding(self.key, bids, bgrads)
        except ConnectionError as e:
            self._outage_tick(e)
            return
        self._backlog = (np.zeros(0, np.int64),
                         np.zeros((0, self.width), np.float32))
        self._backlog_t0 = None
        telemetry.set_gauge("cache.staleness_s", 0.0)
        self.num_replayed_rows += len(bids)
        self.num_pushed_rows += len(bids)
        telemetry.inc("cache.writeback_rows", len(bids))
        self._outage = 0

    def _push_or_buffer(self, ids, grads):
        """push_embedding with outage buffering: deltas that cannot
        reach the PS merge into the bounded backlog for replay.
        Caller MUST hold self._lock (see the locking contract in
        __init__)."""
        if len(ids) == 0:
            return
        self._replay()
        if self._backlog[0].size == 0:
            try:
                self.comm.push_embedding(self.key, ids, grads)
                self.num_pushed_rows += len(ids)
                telemetry.inc("cache.writeback_rows", len(ids))
                self._outage = 0
                return
            except ConnectionError as e:
                self._outage_tick(e)
        bids, bgrads = merge_sparse(*self._backlog, ids, grads)
        if len(bids) > self.max_backlog_rows:
            raise ConnectionError(
                f"PS outage push backlog for table {self.key!r} "
                f"exceeded HETU_CACHE_BACKLOG_ROWS="
                f"{self.max_backlog_rows} ({len(bids)} rows)")
        if self._backlog_t0 is None:
            self._backlog_t0 = time.monotonic()
        self._backlog = (bids, bgrads)
        telemetry.set_gauge("cache.staleness_s", self.staleness_s())

    # ------------------------------------------------------------------ #

    def embedding_lookup(self, ids):
        """ids: any int array; returns float32 rows [..., width]."""
        with self._lock:
            return self._lookup(ids)

    def _lookup(self, ids):
        shape = np.shape(ids)
        flat = np.asarray(ids, np.int64).reshape(-1)
        uniq, inv = np.unique(flat, return_inverse=True)
        self.num_lookups += 1
        self.num_rows_looked += len(uniq)

        rows, hit = self.cache.lookup(uniq)
        # process-wide cache accounting (telemetry registry) on top of
        # the per-table instance counters below
        n_hit = int(hit.sum())
        telemetry.inc("cache.hits", n_hit)
        telemetry.inc("cache.misses", len(uniq) - n_hit)

        # bounded-staleness re-sync of hits.  Locally-dirty lines are
        # excluded from the refresh: overwriting them would drop our own
        # unpushed updates (read-your-writes); they re-sync right after
        # their flush (reference orders this with push_sync_embedding).
        if hit.any() and self.comm is not None:
            self._replay()
            hit_ids = uniq[hit]
            clean = ~self.cache.dirty(hit_ids)
            sync_ids = hit_ids[clean]
            if len(sync_ids):
                stored_v = self.cache.versions(sync_ids)
                try:
                    s_ids, s_rows, s_vers = self.comm.sync_embedding(
                        self.key, sync_ids, stored_v, self.pull_bound)
                except ConnectionError as e:
                    # outage: the cached copies ARE the answer (stale
                    # within the budget)
                    self._outage_tick(e)
                    self.num_stale_served += len(sync_ids)
                    s_ids = ()
                else:
                    self._outage = 0
                if len(s_ids):
                    self.cache.refresh(s_ids, s_rows, s_vers)
                    self.num_synced_rows += len(s_ids)
                    # uniq is sorted (np.unique): vectorized placement
                    rows[np.searchsorted(uniq, np.asarray(s_ids))] = s_rows

        # pull misses — one RPC: sync_embedding against -inf versions
        # returns (ids, rows, versions) together
        miss_ids = uniq[~hit]
        if len(miss_ids):
            assert self.comm is not None, "cache miss with no PS attached"
            try:
                pulled, vers = self._fetch_rows(miss_ids)
            except ConnectionError as e:
                # outage: serve zero vectors (missing-embedding
                # fallback), do NOT insert — they re-fetch on recovery
                self._outage_tick(e)
                self.num_zero_served += len(miss_ids)
            else:
                self._outage = 0
                ev_ids, ev_grads = self.cache.insert(miss_ids, pulled,
                                                     vers)
                ev_total = self.cache.counters()["evictions"]
                telemetry.inc("cache.evictions",
                              ev_total - self._evictions_seen)
                self._evictions_seen = ev_total
                self._push_or_buffer(ev_ids, ev_grads)
                self.num_pulled_rows += len(miss_ids)
                self.num_pulled_bytes += int(pulled.nbytes)
                telemetry.inc("cache.pull_bytes", int(pulled.nbytes))
                rows[~hit] = pulled

        return rows[inv].reshape(*shape, self.width)

    def embedding_update(self, ids, deltas, assume_unique=False):
        """Accumulate optimizer-scaled deltas; push when past push_bound.
        ``assume_unique``: ids are already deduplicated (the executor's
        device-side segment-sum emits unique sorted rows) — skips the
        host re-dedup pass."""
        with self._lock:
            self._update(ids, deltas, assume_unique)

    def _update(self, ids, deltas, assume_unique=False):
        flat = np.asarray(ids, np.int64).reshape(-1)
        deltas = np.asarray(deltas, np.float32).reshape(len(flat), self.width)
        if assume_unique:
            uniq, merged = flat, deltas
        else:
            # merge duplicate ids (scatter-add semantics)
            uniq, inv = np.unique(flat, return_inverse=True)
            merged = np.zeros((len(uniq), self.width), np.float32)
            np.add.at(merged, inv, deltas)
        missed = self.cache.update(uniq, merged)
        if missed and self.comm is not None:
            # uncached ids (version query leaves policy state untouched):
            # push straight through to the PS (buffered during outage)
            cold_mask = self.cache.versions(uniq) == -1
            self._push_or_buffer(uniq[cold_mask], merged[cold_mask])
        if self.comm is not None and \
                self.cache.max_updates() > self.push_bound:
            self.flush()

    def embedding_push_pull(self, push_ids, deltas, pull_ids):
        """Fused update+lookup (reference push_pull, cstable.py:95-116)."""
        with self._lock:
            self._update(push_ids, deltas)
            return self._lookup(pull_ids)

    def flush(self):
        """Push all dirty lines to the PS.  No-op without a PS (draining
        the accumulators with nowhere to send them would lose updates).
        During an outage the collected deltas land in the replay
        backlog instead of being lost."""
        if self.comm is None:
            return
        with self._lock:
            self._replay()
            ids, grads = self.cache.collect_dirty()
            if len(ids):
                self._push_or_buffer(ids, grads)

    # async variants (reference wait_t futures, python_api.cc:76);
    # safe to overlap with the sync methods — everything serializes on
    # self._lock
    def embedding_lookup_async(self, ids):
        return self._pool.submit(self.embedding_lookup, ids)

    def embedding_update_async(self, ids, deltas):
        return self._pool.submit(self.embedding_update, ids, deltas)

    # ------------------------------------------------------------------ #

    def _fetch_rows(self, ids):
        """Rows + versions for uncached ids in ONE RPC when the comm
        speaks sync_embedding (stored_version=-inf returns everything);
        falls back to sparse_pull (versions unknown -> 0)."""
        sync = getattr(self.comm, "sync_embedding", None)
        if sync is not None:
            s_ids, s_rows, s_vers = sync(
                self.key, ids, np.full(len(ids), -1 << 40, np.int64), 0)
            if len(s_ids) == len(ids):
                # align server order to request order, vectorized (the
                # per-id dict loop here was the hottest line of the whole
                # hybrid host path at CTR scale)
                s_ids = np.asarray(s_ids, np.int64)
                sort = np.argsort(s_ids)
                perm = sort[np.searchsorted(s_ids[sort], ids)]
                return (np.asarray(s_rows, np.float32)[perm],
                        np.asarray(s_vers, np.int64)[perm])
        return (np.asarray(self.comm.sparse_pull(self.key, ids),
                           np.float32), None)

    def staleness_s(self):
        """Age of the OLDEST buffered push (seconds): 0 with an empty
        backlog — the observable behind the cache.staleness_s gauge."""
        t0 = self._backlog_t0
        return 0.0 if t0 is None else max(time.monotonic() - t0, 0.0)

    def perf_summary(self):
        """Counter snapshot; locked — serving waves read it from other
        threads while lookups mutate the counters.  Also refreshes the
        cache.staleness_s gauge so dashboards see backlog age advance
        between pushes."""
        with self._lock:
            c = self.cache.counters()
            total = c["hits"] + c["misses"]
            staleness = self.staleness_s()
            telemetry.set_gauge("cache.staleness_s", staleness)
            return {
                "lookups": self.num_lookups,
                "rows_looked": self.num_rows_looked,
                "hit_rate": c["hits"] / total if total else 0.0,
                "pulled_rows": self.num_pulled_rows,
                "pull_bytes": self.num_pulled_bytes,
                "pushed_rows": self.num_pushed_rows,
                "synced_rows": self.num_synced_rows,
                "evictions": c["evictions"],
                "cache_size": self.cache.size(),
                # outage degradation counters
                "ps_failures": self.num_ps_failures,
                "stale_served_rows": self.num_stale_served,
                "zero_served_rows": self.num_zero_served,
                "replayed_rows": self.num_replayed_rows,
                "backlog_rows": len(self._backlog[0]),
                "staleness_s": round(staleness, 6),
            }
