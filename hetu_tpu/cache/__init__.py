"""HET embedding cache (client side): native core + CacheSparseTable.

Reference: src/hetu_cache (CacheBase cache.h:21-60, LRU/LFU/LFUOpt
policies, per-row versioned Lines embedding.h:19, sync protocol
hetu_client.cc) and its Python facade cstable.py:19-187.
"""

from .cache import EmbeddingCache, PythonCache, NativeCache
from .cstable import CacheSparseTable

__all__ = ["EmbeddingCache", "PythonCache", "NativeCache",
           "CacheSparseTable"]
