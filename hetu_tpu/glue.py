"""GLUE task processors (reference
examples/nlp/bert/glue_processor/glue.py:54-325).

Each processor reads the task's official TSV column layout and yields
(text_a, text_b, label) examples; ``convert_examples_to_arrays`` encodes
them straight into the dense [N, S] numpy arrays the BERT models feed
([CLS] a [SEP] b [SEP] with segment ids and padding mask) — the
reference materializes per-example InputFeatures objects first; arrays
are the TPU-shaped form.

Per-task metrics follow the GLUE evaluation spec: accuracy everywhere,
Matthews correlation for CoLA, F1 (+accuracy) for MRPC/QQP.
"""

from __future__ import annotations

import csv
import os

import numpy as np


class InputExample:
    __slots__ = ("guid", "text_a", "text_b", "label")

    def __init__(self, guid, text_a, text_b=None, label=None):
        self.guid = guid
        self.text_a = text_a
        self.text_b = text_b
        self.label = label


class DataProcessor:
    """Base: TSV reading + the per-split example builders."""

    def get_train_examples(self, data_dir):
        return self._create_examples(
            self._read_tsv(os.path.join(data_dir, "train.tsv")), "train")

    def get_dev_examples(self, data_dir):
        return self._create_examples(
            self._read_tsv(os.path.join(data_dir, "dev.tsv")), "dev")

    def get_labels(self):
        raise NotImplementedError

    @staticmethod
    def _read_tsv(path):
        with open(path, "r", encoding="utf-8") as f:
            return list(csv.reader(f, delimiter="\t",
                                   quotechar=None))

    def _create_examples(self, lines, set_type):
        raise NotImplementedError


class ColaProcessor(DataProcessor):
    """CoLA: no header; source \\t label \\t star \\t sentence."""

    def get_labels(self):
        return ["0", "1"]

    def _create_examples(self, lines, set_type):
        return [InputExample(f"{set_type}-{i}", text_a=ln[3],
                             label=ln[1])
                for i, ln in enumerate(lines)]


class Sst2Processor(DataProcessor):
    """SST-2: header; sentence \\t label."""

    def get_labels(self):
        return ["0", "1"]

    def _create_examples(self, lines, set_type):
        return [InputExample(f"{set_type}-{i}", text_a=ln[0],
                             label=ln[1])
                for i, ln in enumerate(lines[1:])]


class MrpcProcessor(DataProcessor):
    """MRPC: header; Quality \\t id1 \\t id2 \\t s1 \\t s2."""

    def get_labels(self):
        return ["0", "1"]

    def _create_examples(self, lines, set_type):
        return [InputExample(f"{set_type}-{i}", text_a=ln[3],
                             text_b=ln[4], label=ln[0])
                for i, ln in enumerate(lines[1:])]


class MnliProcessor(DataProcessor):
    """MNLI: header; sentence1 at col 8, sentence2 at col 9, gold label
    last."""

    def get_labels(self):
        return ["contradiction", "entailment", "neutral"]

    def get_dev_examples(self, data_dir):
        return self._create_examples(
            self._read_tsv(os.path.join(data_dir, "dev_matched.tsv")),
            "dev_matched")

    def _create_examples(self, lines, set_type):
        return [InputExample(f"{set_type}-{i}", text_a=ln[8],
                             text_b=ln[9], label=ln[-1])
                for i, ln in enumerate(lines[1:])]


class QqpProcessor(DataProcessor):
    """QQP: header; id, qid1, qid2, question1(3), question2(4),
    is_duplicate(5)."""

    def get_labels(self):
        return ["0", "1"]

    def _create_examples(self, lines, set_type):
        out = []
        for i, ln in enumerate(lines[1:]):
            if len(ln) < 6:
                continue                   # malformed rows exist in QQP
            out.append(InputExample(f"{set_type}-{i}", text_a=ln[3],
                                    text_b=ln[4], label=ln[5]))
        return out


PROCESSORS = {
    "cola": ColaProcessor,
    "mnli": MnliProcessor,
    "mrpc": MrpcProcessor,
    "sst-2": Sst2Processor,
    "qqp": QqpProcessor,
}


def convert_examples_to_arrays(examples, label_list, max_seq_length,
                               tokenizer):
    """[CLS] a [SEP] (b [SEP]) -> dense arrays:
    (input_ids [N,S] i32, attention_mask [N,S] f32,
     token_type_ids [N,S] i32, labels [N] i32).

    Pair truncation trims the longer side token-by-token (reference
    _truncate_seq_pair); single sequences clip at S-2."""
    label_map = {lab: i for i, lab in enumerate(label_list)}
    n, s = len(examples), max_seq_length
    pad_id = tokenizer.vocab.get("[PAD]", 0)
    ids = np.full((n, s), pad_id, np.int32)
    mask = np.zeros((n, s), np.float32)
    seg = np.zeros((n, s), np.int32)
    labels = np.zeros((n,), np.int32)
    for j, ex in enumerate(examples):
        ta = tokenizer.tokenize(ex.text_a)
        tb = tokenizer.tokenize(ex.text_b) if ex.text_b else None
        if tb is not None:
            while len(ta) + len(tb) > s - 3:
                (ta if len(ta) > len(tb) else tb).pop()
        else:
            ta = ta[:s - 2]
        tokens = ["[CLS]"] + ta + ["[SEP]"]
        seg_ids = [0] * len(tokens)
        if tb is not None:
            tokens += tb + ["[SEP]"]
            seg_ids += [1] * (len(tb) + 1)
        tok_ids = tokenizer.convert_tokens_to_ids(tokens)
        ids[j, :len(tok_ids)] = tok_ids
        mask[j, :len(tok_ids)] = 1.0
        seg[j, :len(seg_ids)] = seg_ids
        labels[j] = label_map[ex.label]
    return ids, mask, seg, labels


# --------------------------------------------------------------------- #
# GLUE metrics (reference compute_metrics role; the GLUE spec's per-task
# choices)
# --------------------------------------------------------------------- #

def accuracy(preds, labels):
    preds = np.asarray(preds)
    labels = np.asarray(labels)
    return float((preds == labels).mean())


def matthews_corr(preds, labels):
    """CoLA's metric.  Clean-room from the definition:
    (TP*TN - FP*FN) / sqrt((TP+FP)(TP+FN)(TN+FP)(TN+FN))."""
    preds = np.asarray(preds).astype(bool)
    labels = np.asarray(labels).astype(bool)
    tp = float(np.sum(preds & labels))
    tn = float(np.sum(~preds & ~labels))
    fp = float(np.sum(preds & ~labels))
    fn = float(np.sum(~preds & labels))
    denom = np.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
    return float((tp * tn - fp * fn) / denom) if denom else 0.0


def f1(preds, labels):
    preds = np.asarray(preds).astype(bool)
    labels = np.asarray(labels).astype(bool)
    tp = float(np.sum(preds & labels))
    fp = float(np.sum(preds & ~labels))
    fn = float(np.sum(~preds & labels))
    if tp == 0:
        return 0.0
    prec = tp / (tp + fp)
    rec = tp / (tp + fn)
    return float(2 * prec * rec / (prec + rec))


def compute_metrics(task, preds, labels):
    task = task.lower()
    out = {"accuracy": accuracy(preds, labels)}
    if task == "cola":
        out["matthews_corr"] = matthews_corr(preds, labels)
    if task in ("mrpc", "qqp"):
        out["f1"] = f1(preds, labels)
    return out
