"""hetu_tpu: a TPU-native distributed deep-learning framework.

Brand-new implementation of the capabilities of Hetu (Hankpipi/Hetu,
PKU DAIR Lab) on JAX/XLA/Pallas/pjit: dataflow-graph training API with
autodiff and named subgraphs, compiled to single jitted XLA step programs;
data/tensor/pipeline/expert/context parallelism as mesh shardings; host-side
parameter server with HET-style embedding cache; MoE; auto-parallel planner.

Public surface mirrors the reference package exports
(python/hetu/__init__.py:1-13 + gpu_ops/__init__.py; SURVEY.md Appendix A)
so code written against `import hetu as ht` works with
`import hetu_tpu as ht`.
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    # The TPU plugin in some images auto-registers and ignores the
    # JAX_PLATFORMS env var; honor the user's intent via jax.config (wins
    # as long as no backend has initialized yet).
    import jax as _jax
    _jax.config.update("jax_platforms", "cpu")

from ._compat import ensure_jax_compat as _ensure_jax_compat

_ensure_jax_compat()

from .context import (
    DLContext, DeviceGroup, DistConfig, context, get_current_context,
    cpu, gpu, tpu, rcpu, rgpu, rtpu, is_gpu_ctx, check_worker,
)
from .ndarray import (
    NDArray, array, empty, sparse_array, IndexedSlices, ND_Sparse_Array,
)
from .graph import *  # noqa: F401,F403 — the op-factory surface
from .graph import Op, PlaceholderOp, Variable, placeholder_op
from .graph.autodiff import gradients
from .executor import Executor, HetuConfig, SubExecutor
from .dataloader import Dataloader, DataloaderOp, dataloader_op, GNNDataLoaderOp
from .gpu_ops import scheduler_init, scheduler_finish, worker_init, \
    worker_finish, server_init, server_finish, get_worker_communicate, \
    wrapped_mpi_nccl_init, new_group_comm

from . import optimizer as optim
from . import initializers as init
from . import lr_scheduler as lr
from . import data
from . import layers
from . import metrics
from . import parallel
from .parallel import distributed_strategies as dist
from .profiler import HetuProfiler, NCCLProfiler, TPUProfiler
from .cache import CacheSparseTable, EmbeddingCache
from . import tokenizers
from . import planner
from . import onnx
from . import graphboard
from . import hf
from . import launcher
from . import serving
from . import envvars
from . import analysis

# MoE / communication op surface
from .graph.ops_moe import (
    layout_transform_op, reverse_layout_transform_op,
    reverse_layout_transform_no_gate_op, alltoall_op, halltoall_op,
    balance_assignment_op, group_topk_idx_op, sam_group_sum_op, sam_max_op,
    dispatch,
)
from .graph.ops_attention import flash_attention_op, ring_attention_op
from .graph.ops_comm import (
    allreduceCommunicate_op, allreduceCommunicatep2p_op,
    groupallreduceCommunicate_op, allgatherCommunicate_op,
    reducescatterCommunicate_op, broadcastCommunicate_op,
    reduceCommunicate_op, pipeline_send_op, pipeline_receive_op,
    parameterServerCommunicate_op, parameterServerSparsePull_op,
    datah2d_op, datad2h_op, quantized_allreduce_op,
)
