"""HuggingFace checkpoint import: torch ``state_dict`` -> hetu_tpu
parameter dicts for the BERT and GPT-2 families.

Beyond-reference interop (the reference has no pretrained-weight
import): a ``transformers`` user loads their checkpoint into this
framework with one call and the forward pass matches the canonical
implementation numerically — the parity tests in tests/test_hf.py run
the SAME random weights through transformers (torch) and through this
framework's executor and compare outputs.

Layout notes:
* torch ``nn.Linear`` stores [out, in] — transposed into our [in, out];
* HF GPT-2 uses ``Conv1D`` with [in, out] — NOT transposed; its fused
  ``c_attn`` [in, 3H] is split into our q/k/v;
* our gelu is the tanh approximation (reference kernel parity), which
  equals HF's ``gelu_new`` — BERT checkpoints trained with exact gelu
  import fine but carry the usual ~1e-3 activation difference; the
  parity tests pin ``hidden_act='gelu_new'``.

Use:
    params = ht.hf.convert_bert(torch_model.state_dict())
    executor.load_dict(params)
"""

from __future__ import annotations

import numpy as np

__all__ = ["convert_bert", "convert_bert_pretraining_heads",
           "convert_bert_classifier", "convert_bert_qa",
           "convert_gpt2", "export_bert", "export_bert_classifier",
           "export_bert_qa", "export_gpt2"]


def _np(t):
    if hasattr(t, "detach"):
        # .float() first: torch's .numpy() rejects bfloat16 tensors
        # (bf16-loaded checkpoints must still import in one call)
        t = t.detach().float().cpu().numpy()
    return np.asarray(t, np.float32)


def _lin(sd, key):
    """torch Linear -> (weight [in,out], bias)."""
    return _np(sd[key + ".weight"]).T.copy(), _np(sd[key + ".bias"])


def convert_bert(state_dict, name="bert", prefix=""):
    """HF ``BertModel`` weights -> {our param name: array}.

    ``prefix``: the HF-side key prefix when the backbone is nested
    (e.g. ``"bert."`` inside BertForPreTraining)."""
    sd = {k[len(prefix):]: v for k, v in state_dict.items()
          if k.startswith(prefix)}
    out = {}
    emb = f"{name}_embeddings"
    out[f"{emb}_word_embeddings"] = _np(
        sd["embeddings.word_embeddings.weight"])
    out[f"{emb}_position_embeddings"] = _np(
        sd["embeddings.position_embeddings.weight"])
    out[f"{emb}_token_type_embeddings"] = _np(
        sd["embeddings.token_type_embeddings.weight"])
    out[f"{emb}_ln_scale"] = _np(sd["embeddings.LayerNorm.weight"])
    out[f"{emb}_ln_bias"] = _np(sd["embeddings.LayerNorm.bias"])

    i = 0
    while f"encoder.layer.{i}.attention.self.query.weight" in sd:
        hf = f"encoder.layer.{i}"
        us = f"{name}_layer{i}"
        for hname, uname in (("attention.self.query", "attn_q"),
                             ("attention.self.key", "attn_k"),
                             ("attention.self.value", "attn_v"),
                             ("attention.output.dense", "attn_proj"),
                             ("intermediate.dense", "intermediate"),
                             ("output.dense", "output")):
            w, b = _lin(sd, f"{hf}.{hname}")
            out[f"{us}_{uname}_weight"] = w
            out[f"{us}_{uname}_bias"] = b
        out[f"{us}_attn_ln_scale"] = _np(
            sd[f"{hf}.attention.output.LayerNorm.weight"])
        out[f"{us}_attn_ln_bias"] = _np(
            sd[f"{hf}.attention.output.LayerNorm.bias"])
        out[f"{us}_out_ln_scale"] = _np(
            sd[f"{hf}.output.LayerNorm.weight"])
        out[f"{us}_out_ln_bias"] = _np(sd[f"{hf}.output.LayerNorm.bias"])
        i += 1

    if "pooler.dense.weight" in sd:
        w, b = _lin(sd, "pooler.dense")
        out[f"{name}_pooler_dense_weight"] = w
        out[f"{name}_pooler_dense_bias"] = b
    return out


def convert_bert_pretraining_heads(state_dict, name="bert"):
    """HF ``BertForPreTraining`` -> backbone + MLM/NSP head params."""
    out = convert_bert(state_dict, name=name, prefix="bert.")
    sd = state_dict
    w, b = _lin(sd, "cls.predictions.transform.dense")
    out[f"{name}_mlm_transform_weight"] = w
    out[f"{name}_mlm_transform_bias"] = b
    out[f"{name}_mlm_ln_scale"] = _np(
        sd["cls.predictions.transform.LayerNorm.weight"])
    out[f"{name}_mlm_ln_bias"] = _np(
        sd["cls.predictions.transform.LayerNorm.bias"])
    out[f"{name}_mlm_bias"] = _np(sd["cls.predictions.bias"])
    w, b = _lin(sd, "cls.seq_relationship")
    out[f"{name}_nsp_weight"] = w
    out[f"{name}_nsp_bias"] = b
    return out


def convert_bert_classifier(state_dict, name="bert"):
    """HF ``BertForSequenceClassification`` -> backbone + classifier
    params (the import path for fine-tuning an HF-pretrained BERT
    through the GLUE pipeline)."""
    out = convert_bert(state_dict, name=name, prefix="bert.")
    w, b = _lin(state_dict, "classifier")
    out[f"{name}_classifier_weight"] = w
    out[f"{name}_classifier_bias"] = b
    return out


def convert_bert_qa(state_dict, name="bert"):
    """HF ``BertForQuestionAnswering`` -> backbone + qa_outputs params
    (the import path for fine-tuning an HF-pretrained BERT through the
    SQuAD pipeline — hetu_tpu.squad + BertForQuestionAnswering)."""
    out = convert_bert(state_dict, name=name, prefix="bert.")
    w, b = _lin(state_dict, "qa_outputs")
    out[f"{name}_qa_outputs_weight"] = w
    out[f"{name}_qa_outputs_bias"] = b
    return out


def convert_gpt2(state_dict, name="gpt", prefix=""):
    """HF ``GPT2Model`` weights -> {our param name: array}.

    GPT-2's Conv1D weights are already [in, out]; the fused c_attn
    [H, 3H] splits into our separate q/k/v projections."""
    sd = {k[len(prefix):]: v for k, v in state_dict.items()
          if k.startswith(prefix)}
    out = {
        f"{name}_wte_table": _np(sd["wte.weight"]),
        f"{name}_wpe": _np(sd["wpe.weight"]),
        f"{name}_ln_f_scale": _np(sd["ln_f.weight"]),
        f"{name}_ln_f_bias": _np(sd["ln_f.bias"]),
    }
    i = 0
    while f"h.{i}.ln_1.weight" in sd:
        hf = f"h.{i}"
        us = f"{name}_h{i}"
        out[f"{us}_ln1_scale"] = _np(sd[f"{hf}.ln_1.weight"])
        out[f"{us}_ln1_bias"] = _np(sd[f"{hf}.ln_1.bias"])
        out[f"{us}_ln2_scale"] = _np(sd[f"{hf}.ln_2.weight"])
        out[f"{us}_ln2_bias"] = _np(sd[f"{hf}.ln_2.bias"])
        ca_w = _np(sd[f"{hf}.attn.c_attn.weight"])     # [H, 3H]
        ca_b = _np(sd[f"{hf}.attn.c_attn.bias"])       # [3H]
        H = ca_w.shape[0]
        for j, nm in enumerate(("q", "k", "v")):
            out[f"{us}_attn_{nm}_weight"] = \
                ca_w[:, j * H:(j + 1) * H].copy()
            out[f"{us}_attn_{nm}_bias"] = ca_b[j * H:(j + 1) * H].copy()
        out[f"{us}_attn_proj_weight"] = _np(sd[f"{hf}.attn.c_proj.weight"])
        out[f"{us}_attn_proj_bias"] = _np(sd[f"{hf}.attn.c_proj.bias"])
        out[f"{us}_ffn_wi_weight"] = _np(sd[f"{hf}.mlp.c_fc.weight"])
        out[f"{us}_ffn_wi_bias"] = _np(sd[f"{hf}.mlp.c_fc.bias"])
        out[f"{us}_ffn_wo_weight"] = _np(sd[f"{hf}.mlp.c_proj.weight"])
        out[f"{us}_ffn_wo_bias"] = _np(sd[f"{hf}.mlp.c_proj.bias"])
        i += 1
    return out


# ------------------------------------------------------------------ #
# the REVERSE direction: our trained parameters -> HF state_dicts, so
# models trained here load into transformers (torch) for serving /
# evaluation in that ecosystem.  Exact inverses of the importers.
# ------------------------------------------------------------------ #

def _t(arr):
    import torch
    return torch.from_numpy(np.ascontiguousarray(np.asarray(arr),
                                                 np.float32))


def export_bert(params, name="bert", prefix=""):
    """{our param name: array} -> HF ``BertModel`` state_dict keys
    (load with ``hf_model.load_state_dict(out, strict=False)``)."""
    p = {k[len(name) + 1:]: v for k, v in params.items()
         if k.startswith(name + "_")}
    out = {}

    def put(hf_key, arr, transpose=False):
        a = np.asarray(arr)
        out[prefix + hf_key] = _t(a.T if transpose else a)

    put("embeddings.word_embeddings.weight",
        p["embeddings_word_embeddings"])
    put("embeddings.position_embeddings.weight",
        p["embeddings_position_embeddings"])
    if "embeddings_token_type_embeddings" in p:
        put("embeddings.token_type_embeddings.weight",
            p["embeddings_token_type_embeddings"])
    put("embeddings.LayerNorm.weight", p["embeddings_ln_scale"])
    put("embeddings.LayerNorm.bias", p["embeddings_ln_bias"])
    i = 0
    while f"layer{i}_attn_q_weight" in p:
        us = f"layer{i}"
        hf = f"encoder.layer.{i}"
        for uname, hname in (("attn_q", "attention.self.query"),
                             ("attn_k", "attention.self.key"),
                             ("attn_v", "attention.self.value"),
                             ("attn_proj", "attention.output.dense"),
                             ("intermediate", "intermediate.dense"),
                             ("output", "output.dense")):
            put(f"{hf}.{hname}.weight", p[f"{us}_{uname}_weight"],
                transpose=True)
            put(f"{hf}.{hname}.bias", p[f"{us}_{uname}_bias"])
        put(f"{hf}.attention.output.LayerNorm.weight",
            p[f"{us}_attn_ln_scale"])
        put(f"{hf}.attention.output.LayerNorm.bias",
            p[f"{us}_attn_ln_bias"])
        put(f"{hf}.output.LayerNorm.weight", p[f"{us}_out_ln_scale"])
        put(f"{hf}.output.LayerNorm.bias", p[f"{us}_out_ln_bias"])
        i += 1
    if "pooler_dense_weight" in p:
        put("pooler.dense.weight", p["pooler_dense_weight"],
            transpose=True)
        put("pooler.dense.bias", p["pooler_dense_bias"])
    return out


def _export_bert_with_head(params, name, head_param, hf_head):
    """Backbone under ``bert.`` + one Linear head — the shared shape of
    the classifier/QA exporters (exact inverses of their importers)."""
    out = export_bert(params, name=name, prefix="bert.")
    w = np.asarray(params[f"{name}_{head_param}_weight"])
    b = np.asarray(params[f"{name}_{head_param}_bias"])
    out[f"{hf_head}.weight"] = _t(w.T)
    out[f"{hf_head}.bias"] = _t(b)
    return out


def export_bert_classifier(params, name="bert"):
    """Our fine-tuned classifier -> HF ``BertForSequenceClassification``
    state_dict (serve a GLUE model from transformers)."""
    return _export_bert_with_head(params, name, "classifier",
                                  "classifier")


def export_bert_qa(params, name="bert"):
    """Our fine-tuned span head -> HF ``BertForQuestionAnswering``
    state_dict (serve a SQuAD model from transformers)."""
    return _export_bert_with_head(params, name, "qa_outputs",
                                  "qa_outputs")


def export_gpt2(params, name="gpt", prefix=""):
    """{our param name: array} -> HF ``GPT2Model`` state_dict keys
    (Conv1D layout kept; q/k/v re-fused into c_attn)."""
    p = {k[len(name) + 1:]: v for k, v in params.items()
         if k.startswith(name + "_")}
    out = {
        prefix + "wte.weight": _t(p["wte_table"]),
        prefix + "wpe.weight": _t(p["wpe"]),
        prefix + "ln_f.weight": _t(p["ln_f_scale"]),
        prefix + "ln_f.bias": _t(p["ln_f_bias"]),
    }
    i = 0
    while f"h{i}_ln1_scale" in p:
        us = f"h{i}"
        hf = prefix + f"h.{i}"
        out[f"{hf}.ln_1.weight"] = _t(p[f"{us}_ln1_scale"])
        out[f"{hf}.ln_1.bias"] = _t(p[f"{us}_ln1_bias"])
        out[f"{hf}.ln_2.weight"] = _t(p[f"{us}_ln2_scale"])
        out[f"{hf}.ln_2.bias"] = _t(p[f"{us}_ln2_bias"])
        out[f"{hf}.attn.c_attn.weight"] = _t(np.concatenate(
            [np.asarray(p[f"{us}_attn_{nm}_weight"])
             for nm in ("q", "k", "v")], axis=1))
        out[f"{hf}.attn.c_attn.bias"] = _t(np.concatenate(
            [np.asarray(p[f"{us}_attn_{nm}_bias"])
             for nm in ("q", "k", "v")]))
        out[f"{hf}.attn.c_proj.weight"] = _t(p[f"{us}_attn_proj_weight"])
        out[f"{hf}.attn.c_proj.bias"] = _t(p[f"{us}_attn_proj_bias"])
        out[f"{hf}.mlp.c_fc.weight"] = _t(p[f"{us}_ffn_wi_weight"])
        out[f"{hf}.mlp.c_fc.bias"] = _t(p[f"{us}_ffn_wi_bias"])
        out[f"{hf}.mlp.c_proj.weight"] = _t(p[f"{us}_ffn_wo_weight"])
        out[f"{hf}.mlp.c_proj.bias"] = _t(p[f"{us}_ffn_wo_bias"])
        i += 1
    return out
