"""Version-compat shims for the jax API surface this package targets.

The codebase is written against jax >= 0.5/0.6 where:

- ``shard_map`` is a top-level export (``from jax import shard_map``)
  taking ``axis_names=`` (the manual axes) and ``check_vma=``;
- ``jax.lax.pcast(x, axes, to="varying")`` marks replicated values as
  device-varying under the vma tracker;
- ``jax.lax.axis_size(name)`` reads a mapped axis' static size.

Older runtimes (this image ships 0.4.x) carry the same machinery under
pre-promotion names: ``jax.experimental.shard_map.shard_map`` with
``auto=`` (the complement of ``axis_names``) and ``check_rep=``, no vma
tracking at all (so the ``to="varying"`` cast is the identity), and the
static axis size via ``jax.core.axis_frame``.  Publishing the new names
once keeps every call site (package, tests, examples) working on both
sides of the promotion without per-site guards.

Idempotent and import-order safe: call it before any module that does
``from jax import shard_map`` executes (hetu_tpu/__init__.py and
tests/conftest.py both do).
"""

from __future__ import annotations

import functools
import inspect


def _adapt_shard_map(sm):
    """Old-signature shard_map -> new-API kwargs (axis_names/check_vma)."""
    params = inspect.signature(sm).parameters
    if "axis_names" in params:        # already the new API
        return sm

    @functools.wraps(sm)
    def shard_map(f, *, mesh=None, in_specs=None, out_specs=None,
                  axis_names=None, check_vma=None, check_rep=None,
                  auto=None, **kw):
        if check_rep is None:
            check_rep = True if check_vma is None else bool(check_vma)
        if auto is None and axis_names is not None:
            # new API names the MANUAL axes; old API names the AUTO ones
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto is not None:
            kw["auto"] = auto
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_rep, **kw)
    return shard_map


def ensure_jax_compat():
    import jax

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map
        jax.shard_map = _adapt_shard_map(shard_map)

    if not hasattr(jax.lax, "pcast"):
        def pcast(x, axes, *, to=None):
            # pre-vma runtimes track no varying-ness: the cast is purely
            # a type-system annotation there, so identity is exact
            del axes, to
            return x
        jax.lax.pcast = pcast

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(name):
            import jax.core as core
            size = core.axis_frame(name)
            return getattr(size, "size", size)   # int on 0.4.x
        jax.lax.axis_size = axis_size

    return jax


def enable_cpu_collectives():
    """Multi-process CPU meshes: newer jax routes cross-process CPU
    collectives automatically; 0.4.x needs the gloo implementation
    selected before ``jax.distributed.initialize``.  No-op where the
    option no longer exists."""
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, KeyError, ValueError):
        pass
