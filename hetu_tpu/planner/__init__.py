"""Auto-parallel planner (Galvatron-equivalent, SURVEY.md §2.6).

Reference: tools/Galvatron — profiler scripts (test_env), cost models
(utils/cost_model.py), per-layer DP search (utils/dp_utils.py:56-130), and
a runtime that consumes per-layer (pp,tp,dp,fsdp) configs.  The TPU build
searches the same lattice plus a `cp` (context-parallel) axis, against
ICI/DCN-retargeted analytic cost models optionally calibrated by live
probes, and emits a `jax.sharding.Mesh` + per-layer NamedShardings.

    layers = [LayerSpec.transformer_encoder(1024, 512)] * 24
    plan = PlannerSearch(layers, global_batch_size=64,
                         cluster=measure_cluster()).search()
    ex = Executor(graph, dist_strategy=AutoParallel(plan))
"""

from .cost_model import (ClusterSpec, LayerSpec, MemoryCostModel,
                         ParallelStrategy, TimeCostModel,
                         candidate_strategies)
from .search import DPAlg, ParallelPlan, PlannerSearch, \
    pipeline_division_even
from .profiler import (calibrate_layers, graph_layer_fn, measure_cluster,
                       profile_collective_bandwidth, profile_layer,
                       profile_matmul_throughput)
from .apply import AutoParallel, plan_to_json

__all__ = [
    "ClusterSpec", "LayerSpec", "MemoryCostModel", "TimeCostModel",
    "ParallelStrategy", "candidate_strategies", "DPAlg", "ParallelPlan",
    "PlannerSearch", "pipeline_division_even", "measure_cluster",
    "profile_collective_bandwidth", "profile_layer",
    "profile_matmul_throughput", "calibrate_layers", "graph_layer_fn",
    "AutoParallel", "plan_to_json",
]
