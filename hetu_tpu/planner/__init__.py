"""Auto-parallel planner (Galvatron-equivalent, SURVEY.md §2.6).

Searches per-layer (pp, tp, dp, fsdp, cp) strategies with memory/time cost
models fed by the collective bandwidth probe (profiler.NCCLProfiler) and
emits mesh + sharding specs.  Modules land incrementally; see
planner/cost_model.py and planner/search.py once present.
"""
