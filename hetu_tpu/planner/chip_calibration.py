"""On-chip planner calibration (VERDICT r2 item 4).

Galvatron measures its cost-model constants with dedicated scripts on
the target cluster (tools/Galvatron/test_env bandwidth/overlap probes,
utils/cost_model.py:38-60 consumes the coefficients); until round 3 this
build's planner calibrated only against the virtual CPU mesh and assumed
``overlap=0.7``.  This module measures every SINGLE-CHIP-measurable
constant on the live backend and records which constants cannot be
measured without multi-chip hardware:

* achieved bf16 matmul TFLOP/s across sizes (the MXU utilization curve),
* H2D / D2H host-link bandwidth,
* HBM capacity,
* an MEASURED overlap coefficient: how much host->device transfer hides
  under compute when dispatched concurrently (the single-chip analogue
  of Galvatron's comm/compute overlap probe — ICI/DCN overlap still
  needs chips we don't have, and the artifact says so),
* a measured kernel-choice micro-search: flash-attention block sizes
  (Galvatron-style profiling IS search over measured configs).

``plan_vs_naive`` closes the loop the VERDICT asked for: the
calibration-driven choice (best-measured flash blocks) against the
naive default (square 128x128 blocks, what a GPU port would pick),
with the MEASURED step-time delta recorded next to the prediction.

Run ``python -m hetu_tpu.planner.chip_calibration`` on the target chip;
the artifact lands in CALIBRATION_TPU.json at the repo root and
``load_calibration`` feeds it back into a ClusterSpec for the search.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import jax
import jax.numpy as jnp

from .cost_model import ClusterSpec

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
CALIBRATION_FILE = os.path.join(_REPO, "CALIBRATION_TPU.json")


def _timeit(fn, *args, warmup=2, iters=8):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def measure_matmul_curve(dims=(1024, 2048, 4096, 8192),
                         dtype=jnp.bfloat16):
    """Achieved TFLOP/s per matmul size — the utilization curve the
    cost model's flops_per_sec should reflect (small layers never reach
    the peak the spec sheet quotes)."""
    out = {}
    for d in dims:
        a = jnp.ones((d, d), dtype)
        b = jnp.ones((d, d), dtype)
        f = jax.jit(lambda x, y: x @ y)
        t = _timeit(f, a, b)
        out[str(d)] = round(2.0 * d ** 3 / t / 1e12, 2)
    return out


def measure_host_link(size_mb=256):
    """H2D and D2H bandwidth (bytes/s) — phase A/B of the PS path and
    the dataloader ride this link."""
    n = int(size_mb) * (1 << 20)
    host = np.ones(n // 4, np.float32)

    def h2d():
        return jax.device_put(host)
    for _ in range(2):
        jax.block_until_ready(h2d())
    t0 = time.perf_counter()
    for _ in range(4):
        dev = h2d()
    jax.block_until_ready(dev)
    t_h2d = (time.perf_counter() - t0) / 4

    t0 = time.perf_counter()
    for _ in range(4):
        back = np.asarray(dev)
    t_d2h = (time.perf_counter() - t0) / 4
    del back
    return {"h2d_gbps": round(n / t_h2d / 1e9, 2),
            "d2h_gbps": round(n / t_d2h / 1e9, 2)}


def measure_overlap_coefficient(compute_dim=4096, transfer_mb=128):
    """Fraction of a host->device transfer hidden under concurrently
    dispatched device compute.

    overlap = (t_compute + t_transfer - t_both) / min(t_compute,
    t_transfer): 1 = fully hidden, 0 = fully serialized.  This is the
    single-chip analogue of Galvatron's overlap-slowdown probe
    (utils/cost_model.py:49-56 coefficients); ICI-collective overlap
    needs >1 chip and stays an assumption (recorded as such)."""
    a = jnp.ones((compute_dim, compute_dim), jnp.bfloat16)
    chain = jax.jit(lambda x: x @ x @ x @ x)
    host = np.ones(int(transfer_mb) * (1 << 20) // 4, np.float32)

    t_compute = _timeit(chain, a)
    t_transfer = _timeit(lambda: jax.device_put(host))

    def both():
        out = chain(a)             # async dispatch
        dev = jax.device_put(host)
        return out, dev
    t_both = _timeit(lambda: both())
    hidden = max(0.0, t_compute + t_transfer - t_both)
    denom = min(t_compute, t_transfer)
    return {
        "t_compute_ms": round(t_compute * 1e3, 3),
        "t_transfer_ms": round(t_transfer * 1e3, 3),
        "t_both_ms": round(t_both * 1e3, 3),
        "overlap_h2d": round(min(1.0, hidden / denom), 3)
        if denom > 0 else 0.0,
    }


def measure_flash_block_choice(seq=4096, heads=8, head_dim=64, batch=2,
                               candidates=((128, 128), (256, 512),
                                           (512, 1024), (1024, 1024))):
    """Measured fwd+bwd step time of the Pallas flash kernel per block
    config at a long-context shape.  The planner's kernel choice = the
    argmin; 'naive' = square 128x128 (the config a straight GPU port
    ships)."""
    from ..kernels.flash_attention import flash_attention
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (batch, seq, heads, head_dim),
                          jnp.bfloat16)
    k = jax.random.normal(jax.random.fold_in(key, 1), q.shape,
                          jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(key, 2), q.shape,
                          jnp.bfloat16)
    out = {}
    for bq, bk in candidates:
        def loss(q, k, v, _bq=bq, _bk=bk):
            o = flash_attention(q, k, v, causal=True, block_q=_bq,
                                block_k=_bk)
            return (o.astype(jnp.float32) ** 2).sum()
        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        t = _timeit(g, q, k, v, warmup=1, iters=4)
        out[f"{bq}x{bk}"] = round(t * 1e3, 3)
    best = min(out, key=out.get)
    return {"step_ms": out, "chosen": best,
            "config": {"seq": seq, "heads": heads, "head_dim": head_dim,
                       "batch": batch}}


def plan_vs_naive(flash_result):
    """The measured plan-vs-naive delta the VERDICT asked for: the
    calibration-driven flash block choice vs the naive 128x128 default,
    both MEASURED (flash_result comes from measure_flash_block_choice)."""
    times = flash_result["step_ms"]
    naive = times.get("128x128")
    chosen = times[flash_result["chosen"]]
    return {
        "decision": "flash_attention_block_sizes",
        "naive": {"config": "128x128", "step_ms": naive},
        "planned": {"config": flash_result["chosen"],
                    "step_ms": chosen},
        "measured_speedup_vs_naive": round(naive / chosen, 3)
        if naive and chosen else None,
    }


def calibrate_chip(small=False):
    """Measure everything; ``small`` shrinks probes for CPU test runs."""
    dev = jax.devices()[0]
    dims = (256, 512) if small else (1024, 2048, 4096, 8192)
    art = {
        "platform": jax.default_backend(),
        "device_kind": dev.device_kind,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime()),
        "matmul_tflops_bf16": measure_matmul_curve(dims=dims),
        "host_link": measure_host_link(size_mb=8 if small else 256),
        "overlap": measure_overlap_coefficient(
            compute_dim=512 if small else 4096,
            transfer_mb=4 if small else 128),
        "flash_blocks": measure_flash_block_choice(
            seq=256 if small else 4096,
            candidates=((128, 128), (256, 256)) if small
            else ((128, 128), (256, 512), (512, 1024), (1024, 1024))),
        "unmeasurable_on_one_chip": [
            "ici_bandwidth (needs >1 chip; ClusterSpec keeps the 45GB/s "
            "v5e link spec)",
            "dcn_bandwidth (needs >1 host)",
            "collective/compute overlap over ICI (overlap_h2d above is "
            "the host-link analogue; ClusterSpec.overlap uses it as the "
            "measured stand-in)",
        ],
    }
    try:
        stats = dev.memory_stats()
        if stats and "bytes_limit" in stats:
            art["hbm_bytes"] = int(stats["bytes_limit"])
    except Exception:
        pass
    art["plan_vs_naive"] = plan_vs_naive(art["flash_blocks"])
    peak_tflops = max(art["matmul_tflops_bf16"].values())
    art["cluster_spec"] = {
        "flops_per_sec": peak_tflops * 1e12,
        "mfu": 1.0,
        "overlap": art["overlap"]["overlap_h2d"],
        **({"hbm_bytes": float(art["hbm_bytes"])}
           if "hbm_bytes" in art else {}),
    }
    return art


def load_calibration(path=CALIBRATION_FILE, n_devices=None):
    """ClusterSpec from a checked-in calibration artifact; measured
    fields override the analytic defaults."""
    with open(path) as f:
        art = json.load(f)
    spec = ClusterSpec()
    for k, v in art.get("cluster_spec", {}).items():
        setattr(spec, k, v)
    if n_devices is not None:
        spec.n_devices = n_devices
    return spec


def main():
    art = calibrate_chip(small=bool(os.environ.get("HETU_CALIB_SMALL")))
    with open(CALIBRATION_FILE, "w") as f:
        json.dump(art, f, indent=1)
    print(json.dumps({"platform": art["platform"],
                      "device_kind": art["device_kind"],
                      "peak_tflops": max(
                          art["matmul_tflops_bf16"].values()),
                      "overlap_h2d": art["overlap"]["overlap_h2d"],
                      "plan_vs_naive": art["plan_vs_naive"][
                          "measured_speedup_vs_naive"]}))


if __name__ == "__main__":
    main()
