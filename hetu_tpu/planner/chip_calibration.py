"""On-chip planner calibration (VERDICT r2 item 4).

Galvatron measures its cost-model constants with dedicated scripts on
the target cluster (tools/Galvatron/test_env bandwidth/overlap probes,
utils/cost_model.py:38-60 consumes the coefficients); until round 3 this
build's planner calibrated only against the virtual CPU mesh and assumed
``overlap=0.7``.  This module measures every SINGLE-CHIP-measurable
constant on the live backend and records which constants cannot be
measured without multi-chip hardware:

* achieved bf16 matmul TFLOP/s across sizes (the MXU utilization curve),
* H2D / D2H host-link bandwidth,
* HBM capacity,
* an MEASURED overlap coefficient: how much host->device transfer hides
  under compute when dispatched concurrently (the single-chip analogue
  of Galvatron's comm/compute overlap probe — ICI/DCN overlap still
  needs chips we don't have, and the artifact says so),
* a measured kernel-choice micro-search: flash-attention block sizes
  (Galvatron-style profiling IS search over measured configs).

``plan_vs_naive`` closes the loop the VERDICT asked for: the
calibration-driven choice (best-measured flash blocks) against the
naive default (square 128x128 blocks, what a GPU port would pick),
with the MEASURED step-time delta recorded next to the prediction.

Run ``python -m hetu_tpu.planner.chip_calibration`` on the target chip;
the artifact lands in CALIBRATION_TPU.json at the repo root and
``load_calibration`` feeds it back into a ClusterSpec for the search.
"""

from __future__ import annotations

import json
import os
import time

from .. import envvars

import numpy as np
import jax
import jax.numpy as jnp

from .cost_model import ClusterSpec

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
CALIBRATION_FILE = os.path.join(_REPO, "CALIBRATION_TPU.json")


# The shared scalar-fetch completion barrier (see its docstring for the
# round-3 axon-tunnel measurements that forced it).  It fetches a scalar
# from EVERY tree leaf; measure_overlap_coefficient still combine()s its
# two concurrent dispatches into one output, but to serialize them into
# a single dependent program (so neither can complete early), not to
# work around the barrier.
from ..profiler import materialize_barrier as _materialize


def _timeit(fn, *args, warmup=2, iters=8):
    """Median-of-3 wall time per call; completion forced by a scalar
    fetch of the last output (see _materialize)."""
    for _ in range(max(1, warmup)):
        _materialize(fn(*args))
    reps = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        _materialize(out)
        reps.append((time.perf_counter() - t0) / iters)
    return sorted(reps)[1]


def measure_matmul_curve(dims=(1024, 2048, 4096, 8192),
                         dtype=jnp.bfloat16, light=False):
    """Achieved TFLOP/s per matmul size — the utilization curve the
    cost model's flops_per_sec should reflect (small layers never reach
    the peak the spec sheet quotes).

    Returns ``(curve, raw)``: ``curve`` holds the physics-clamped values
    the cost model consumes; ``raw`` holds the unclamped slope readings,
    so a value calibrated FROM the spec peak (raw > spec, clamped to it)
    is distinguishable in the artifact from a genuine measurement.

    Methodology (tunnel-proof): one jitted program per (size, K) holding
    K UNROLLED chained matmuls (chaining defeats result memoization and
    CSE; unrolling avoids the per-iteration stalls lax loops showed over
    the tunnel), timed with a scalar-fetch barrier.  Per-matmul time is
    the (t_K2 - t_K1)/(K2 - K1) slope, which cancels the fixed
    per-program dispatch latency (~6 ms through the axon tunnel)."""
    out = {}
    raw_out = {}
    for d in dims:
        a = jnp.full((d, d), 1.0 / d, dtype)
        b = jnp.eye(d, dtype=dtype)

        def make(K):
            def chain(x, y):
                for _ in range(K):
                    x = x @ y        # x @ eye: bounded numerics
                return x             # full matrix out, so it can feed back
            return jax.jit(chain)

        def time_per_call(f, iters=3):
            # CALL-LEVEL chaining: each call consumes the previous
            # call's output buffer, so no two dispatches are identical
            # and none can be served from the tunnel's memo cache.
            x = f(a, b)
            _materialize(x)          # warmup (compile) + barrier
            reps = []
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(iters):
                    x = f(x, b)
                _materialize(x)
                reps.append((time.perf_counter() - t0) / iters)
            return sorted(reps)[1]

        # K spans sized so the K2-K1 slope clears multi-ms tunnel jitter
        # at EVERY dim (a ~0.01ms d=1024 matmul needs ~500 extra
        # copies in the K2 program to produce a >5ms signal); ``light``
        # (CPU test mode) has no tunnel to outshout and keeps compiles
        # small
        if light:
            k1, k2 = (2, 10)
        else:
            k1, k2 = {8192: (2, 10), 4096: (4, 40), 2048: (8, 232)}.get(
                d, (8, 512) if d <= 1024 else (4, 40))
        t1 = time_per_call(make(k1))
        t2 = time_per_call(make(k2))
        # A slope that doesn't clear the dispatch-jitter floor is NOISE,
        # not a measurement — record it as unmeasurable rather than
        # dividing by epsilon and writing a fantasy TFLOP/s number into
        # the artifact (the failure mode this module exists to prevent).
        if t2 - t1 > max(3e-4, 0.05 * t1):
            t = (t2 - t1) / (k2 - k1)
            tflops = round(2.0 * d ** 3 / t / 1e12, 2)
            raw_out[str(d)] = tflops
            # physics check: a reading above the device's spec-sheet
            # peak is residual slope jitter, not throughput — >1.1x is
            # rejected outright, <=1.1x is clamped TO the spec peak so
            # the cost model never calibrates to an above-physical rate
            # (raw_out keeps the unclamped reading for the artifact)
            spec = _spec_peak_tflops()
            if spec is not None and tflops > 1.1 * spec:
                out[str(d)] = None
            elif spec is not None:
                out[str(d)] = min(tflops, spec)
            else:
                out[str(d)] = tflops
        else:
            out[str(d)] = None   # dispatch-latency-dominated at this size
            raw_out[str(d)] = None
    return out, raw_out


# bf16 spec-sheet peak TFLOP/s by device-kind substring (public specs).
# The single source of truth — bench.py's MFU denominator imports it too.
SPEC_PEAKS = [("v6", 918.0), ("v5p", 459.0), ("v5", 197.0),
              ("v4", 275.0), ("v3", 123.0), ("v2", 45.0)]


def spec_peak_tflops(device_kind=None):
    kind = (device_kind if device_kind is not None
            else jax.devices()[0].device_kind).lower()
    for sub, peak in SPEC_PEAKS:
        if sub in kind:
            return peak
    return None


_spec_peak_tflops = spec_peak_tflops


def measure_host_link(size_mb=256):
    """H2D and D2H bandwidth (bytes/s) — phase A/B of the PS path and
    the dataloader ride this link.

    NOTE: through the axon tunnel this measures the TUNNEL, not a
    TPU-VM PCIe/DMA link (observed ~0.06 GB/s vs the >10 GB/s a real
    TPU VM host link delivers); the artifact flags implausibly low
    results so the planner's consumers can tell which regime they got."""
    n = int(size_mb) * (1 << 20)
    host = np.ones(n // 4, np.float32)

    # distinct host buffers per transfer (identical dispatches can be
    # memoized/coalesced by the tunnel) and a fetch barrier per transfer
    # — strict serialization slightly overcounts, which is the honest
    # direction for a bandwidth figure
    hosts = [host + np.float32(i + 1) for i in range(4)]
    _materialize(jax.device_put(host))       # warmup (distinct buffer)
    devs = []
    t0 = time.perf_counter()
    for h in hosts:
        dev = jax.device_put(h)
        _materialize(dev)
        devs.append(dev)
    t_h2d = (time.perf_counter() - t0) / 4

    t0 = time.perf_counter()
    for dev in devs:                 # distinct arrays: no cached fetches
        back = np.asarray(dev)
    t_d2h = (time.perf_counter() - t0) / 4
    del back
    h2d_gbps = round(n / t_h2d / 1e9, 2)
    return {"h2d_gbps": h2d_gbps,
            "d2h_gbps": round(n / t_d2h / 1e9, 2),
            # <1 GB/s is not a physical host link; it's the axon tunnel
            "tunnel_limited": h2d_gbps < 1.0}


def measure_overlap_coefficient(compute_dim=4096, transfer_mb=128):
    """Fraction of a host->device transfer hidden under concurrently
    dispatched device compute.

    overlap = (t_compute + t_transfer - t_both) / min(t_compute,
    t_transfer): 1 = fully hidden, 0 = fully serialized.  This is the
    single-chip analogue of Galvatron's overlap-slowdown probe
    (utils/cost_model.py:49-56 coefficients); ICI-collective overlap
    needs >1 chip and stays an assumption (recorded as such)."""
    a = jnp.ones((compute_dim, compute_dim), jnp.bfloat16)
    eye = jnp.eye(compute_dim, dtype=jnp.bfloat16)
    # feed the output back through an identity matmul chain: numerics
    # stay bounded while every dispatch sees a FRESH input buffer (the
    # axon tunnel memoizes repeated identical dispatches)
    chain = jax.jit(lambda x, y: ((x @ y) @ y) @ y)
    host = np.ones(int(transfer_mb) * (1 << 20) // 4, np.float32)

    state = {"x": a, "n": 0}

    def compute_step():
        state["x"] = chain(state["x"], eye)
        return state["x"]

    def transfer_step():
        # fresh host buffer per dispatch: identical device_puts are
        # memoizable under the tunnel (the host-side copy is ~ms against
        # the multi-hundred-ms tunnel transfer it guards)
        state["n"] += 1
        h = host.copy()
        h[0] = state["n"]
        return jax.device_put(h)

    def timeit_barrier_each(fn, warmup=1, iters=4, reps=5):
        # successive transfer (and both()) outputs are INDEPENDENT
        # dispatches, so each call gets its own completion fetch; the
        # per-call round-trip this adds (~ms) hits all three terms of
        # the overlap formula uniformly and mostly cancels.  Median of 5
        # reps: tunnel transfer times jitter ~10%, enough to push the
        # overlap ratio past its clamps with fewer samples.
        for _ in range(warmup):
            _materialize(fn())
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                _materialize(fn())
            ts.append((time.perf_counter() - t0) / iters)
        return sorted(ts)[len(ts) // 2]

    t_compute = timeit_barrier_each(compute_step)
    t_transfer = timeit_barrier_each(transfer_step)

    # the barrier fetches ONE scalar, so make that scalar depend on BOTH
    # the compute chain and the transfer — materializing only the
    # device_put leaf would let the compute dispatches float free and
    # fake a perfect overlap
    combine = jax.jit(
        lambda o, d: o[0, 0].astype(jnp.float32) + d[0])

    def both():
        out = compute_step()       # async dispatch
        dev = transfer_step()
        return combine(out, dev)   # one output depending on BOTH
    t_both = timeit_barrier_each(both)
    hidden = max(0.0, t_compute + t_transfer - t_both)
    denom = min(t_compute, t_transfer)
    return {
        "t_compute_ms": round(t_compute * 1e3, 3),
        "t_transfer_ms": round(t_transfer * 1e3, 3),
        "t_both_ms": round(t_both * 1e3, 3),
        "overlap_h2d": round(min(1.0, hidden / denom), 3)
        if denom > 0 else 0.0,
    }


def measure_flash_block_choice(seq=4096, heads=8, head_dim=64, batch=2,
                               candidates=((128, 128), (256, 512),
                                           (512, 1024), (1024, 1024))):
    """Measured fwd+bwd step time of the Pallas flash kernel per block
    config at a long-context shape.  The planner's kernel choice = the
    argmin; 'naive' = square 128x128 (the config a straight GPU port
    ships)."""
    from ..kernels.flash_attention import flash_attention
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (batch, seq, heads, head_dim),
                          jnp.bfloat16)
    k = jax.random.normal(jax.random.fold_in(key, 1), q.shape,
                          jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(key, 2), q.shape,
                          jnp.bfloat16)
    out = {}
    for bq, bk in candidates:
        def loss(q, k, v, _bq=bq, _bk=bk):
            o = flash_attention(q, k, v, causal=True, block_q=_bq,
                                block_k=_bk)
            return (o.astype(jnp.float32) ** 2).sum()
        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        # chain q through dq so every dispatch's inputs differ — the
        # axon tunnel memoizes repeated identical dispatches
        state = {"q": q}

        def step():
            dq, _, _ = g(state["q"], k, v)
            state["q"] = state["q"] + 1e-6 * dq
            return state["q"]
        t = _timeit(step, warmup=1, iters=4)
        out[f"{bq}x{bk}"] = round(t * 1e3, 3)
    best = min(out, key=out.get)
    return {"step_ms": out, "chosen": best,
            "config": {"seq": seq, "heads": heads, "head_dim": head_dim,
                       "batch": batch}}


def plan_vs_naive(flash_result):
    """The measured plan-vs-naive delta the VERDICT asked for: the
    calibration-driven flash block choice vs the naive 128x128 default,
    both MEASURED (flash_result comes from measure_flash_block_choice)."""
    times = flash_result["step_ms"]
    naive = times.get("128x128")
    chosen = times[flash_result["chosen"]]
    return {
        "decision": "flash_attention_block_sizes",
        "naive": {"config": "128x128", "step_ms": naive},
        "planned": {"config": flash_result["chosen"],
                    "step_ms": chosen},
        "measured_speedup_vs_naive": round(naive / chosen, 3)
        if naive and chosen else None,
    }


def calibrate_chip(small=False):
    """Measure everything; ``small`` shrinks probes for CPU test runs."""
    dev = jax.devices()[0]
    dims = (256, 512) if small else (1024, 2048, 4096, 8192)
    curve, curve_raw = measure_matmul_curve(dims=dims, light=small)
    art = {
        "platform": jax.default_backend(),
        "device_kind": dev.device_kind,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime()),
        "matmul_tflops_bf16": curve,
        # unclamped slope readings: a dim where raw > spec peak was
        # clamped TO spec in matmul_tflops_bf16 — consumers can tell
        # calibrated-from-measurement from calibrated-from-spec
        "matmul_tflops_bf16_raw": curve_raw,
        "matmul_clamped_to_spec": {
            d: (curve_raw[d] is not None and curve[d] is not None
                and curve_raw[d] > curve[d])
            for d in curve},
        "host_link": measure_host_link(size_mb=8 if small else 64),
        "overlap": measure_overlap_coefficient(
            compute_dim=512 if small else 4096,
            transfer_mb=4 if small else 16),
        "flash_blocks": measure_flash_block_choice(
            seq=256 if small else 4096,
            candidates=((128, 128), (256, 256)) if small
            else ((128, 128), (256, 512), (512, 1024), (1024, 1024))),
        "unmeasurable_on_one_chip": [
            "ici_bandwidth (needs >1 chip; ClusterSpec keeps the 45GB/s "
            "v5e link spec)",
            "dcn_bandwidth (needs >1 host)",
            "collective/compute overlap over ICI (overlap_h2d above is "
            "the host-link analogue; ClusterSpec.overlap uses it as the "
            "measured stand-in)",
        ],
    }
    try:
        stats = dev.memory_stats()
        if stats and "bytes_limit" in stats:
            art["hbm_bytes"] = int(stats["bytes_limit"])
    except Exception:
        pass
    art["plan_vs_naive"] = plan_vs_naive(art["flash_blocks"])
    measured = [v for v in art["matmul_tflops_bf16"].values()
                if v is not None]
    if not measured:
        raise RuntimeError(
            "matmul curve entirely dispatch-noise-dominated; no peak "
            "to calibrate from — rerun with larger sizes")
    peak_tflops = max(measured)
    art["cluster_spec"] = {
        "flops_per_sec": peak_tflops * 1e12,
        "mfu": 1.0,
        "overlap": art["overlap"]["overlap_h2d"],
        **({"hbm_bytes": float(art["hbm_bytes"])}
           if "hbm_bytes" in art else {}),
    }
    return art


def load_calibration(path=CALIBRATION_FILE, n_devices=None):
    """ClusterSpec from a checked-in calibration artifact; measured
    fields override the analytic defaults.  Provenance is recorded per
    constant: what the artifact measured is 'measured'; ICI/DCN
    bandwidth stay 'spec-assumed' (unmeasurable on one chip — the
    artifact's unmeasurable_on_one_chip list says so) so plan output
    can flag rankings that rest on them."""
    with open(path) as f:
        art = json.load(f)
    spec = ClusterSpec()
    for k, v in art.get("cluster_spec", {}).items():
        setattr(spec, k, v)
        spec.provenance[k] = "measured"
    # flops_per_sec is max() over the matmul curve: if the peak dim's
    # reading was clamped TO the spec-sheet value, the constant is a
    # spec number, not a measurement — say so (matmul_clamped_to_spec
    # exists in post-r4 artifacts; older ones default to 'measured')
    curve = art.get("matmul_tflops_bf16", {})
    clamped = art.get("matmul_clamped_to_spec", {})
    peaks = [d for d, v in curve.items() if v is not None]
    if peaks and "flops_per_sec" in spec.provenance:
        peak_dim = max(peaks, key=lambda d: curve[d])
        if clamped.get(peak_dim):
            spec.provenance["flops_per_sec"] = "spec-clamped"
    for k in ("ici_bandwidth", "dcn_bandwidth"):
        spec.provenance.setdefault(k, "spec-assumed")
    if n_devices is not None:
        spec.n_devices = n_devices
    return spec


def main():
    from ..artifact import persist_artifact
    small = envvars.get_bool("HETU_CALIB_SMALL")
    # cheap pre-check: a degraded run (small probes, or not on real
    # TPU) that would be refused anyway must not burn minutes of
    # matmul sweeps first
    reduced_now = small or jax.default_backend() != "tpu"
    try:
        with open(CALIBRATION_FILE) as f:
            existing = json.load(f)
    except (OSError, ValueError):
        existing = None
    if (isinstance(existing, dict) and reduced_now
            and not existing.get("reduced_scale")
            and existing.get("platform") == "tpu"):
        print(json.dumps({
            "platform": jax.default_backend(), "small": small,
            "not_written": "full-scale TPU calibration record already "
                           "present; degraded run skipped"}))
        return
    art = calibrate_chip(small=small)
    # degraded = small probes or a non-TPU backend; either must never
    # clobber a full-scale TPU calibration record (shared discipline
    # with bench.py's sweep artifacts)
    art["reduced_scale"] = small or art.get("platform") != "tpu"
    if not persist_artifact(CALIBRATION_FILE, art,
                            reduced=art["reduced_scale"]):
        print(json.dumps({"platform": art["platform"],
                          "not_written": art["not_written"]}))
        return
    print(json.dumps({"platform": art["platform"],
                      "device_kind": art["device_kind"],
                      "peak_tflops": round(
                          art["cluster_spec"]["flops_per_sec"] / 1e12, 2),
                      "overlap_h2d": art["overlap"]["overlap_h2d"],
                      "plan_vs_naive": art["plan_vs_naive"][
                          "measured_speedup_vs_naive"]}))


if __name__ == "__main__":
    main()
