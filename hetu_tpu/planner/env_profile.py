"""Environment profiler CLI (reference tools/Galvatron/test_env:
bandwidth_test / bandwidth_test_dist / overlap_test driven by
profile_env_8gpus.sh — standalone scripts whose measured coefficients
feed the cost model).

Profiles the CURRENT jax topology per mesh axis and writes
ENV_PROFILE.json:

* achieved bf16 matmul TFLOP/s (the compute term),
* per-axis collective bandwidth — allreduce, all-gather, all-to-all,
  and neighbor ppermute (the ring/ICI terms the TimeCostModel prices
  dp grad sync, fsdp gathers, MoE dispatch, and cp KV rotation with),
* the comm/compute overlap coefficient per axis (the reference's
  overlap_test measures exactly this; ClusterSpec.overlap consumes it).

Run on any topology:

    python -m hetu_tpu.planner.env_profile --axes dp=4,tp=2

On the virtual CPU mesh the numbers characterize the HOST (useful for
testing the machinery); on a real multi-chip mesh they are the ICI/DCN
measurements the one-chip calibration (chip_calibration.py) must
otherwise leave 'spec-assumed'.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from .profiler import _timeit, profile_matmul_throughput
from ..parallel.mesh import make_mesh

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
ENV_PROFILE_FILE = os.path.join(_REPO, "ENV_PROFILE.json")


def _axis_collective_bw(mesh, axis, size_mb=8):
    """Measured bytes/s for the four collective shapes the cost model
    prices over one mesh axis."""
    k = mesh.shape[axis]
    if k <= 1:
        return None
    # k*k so the per-shard buffer also splits k ways (the all-to-all
    # probe reshapes its shard into k parts)
    n = int(size_mb * (1 << 20) / 4)
    n -= n % (k * k)
    x = jnp.ones((n,), jnp.float32)
    spec = P(axis)

    def run(body, in_spec, out_spec):
        # check_vma off: the input is replicated over the mesh's OTHER
        # axes, which the static varying-axes inference can't always
        # prove for out_specs P()
        f = jax.jit(shard_map(body, mesh=mesh, in_specs=in_spec,
                              out_specs=out_spec, check_vma=False))
        return _timeit(f, x)

    out = {}
    # ring allreduce: the canonical probe (profiler.py) — one source of
    # the psum-under-shard_map measurement
    from .profiler import profile_collective_bandwidth
    out["allreduce_bytes_per_s"] = profile_collective_bandwidth(
        mesh, axis, size_mb=size_mb)

    # all-gather: each device RECEIVES the other k-1 shards of n/k
    # elements = (k-1)/k * n*4 bytes (same per-device accounting as the
    # allreduce/all-to-all rows; ADVICE r4 flagged a double /k here)
    t = run(lambda v: jax.lax.all_gather(v, axis, tiled=True), spec, P())
    out["allgather_bytes_per_s"] = (k - 1) / k * (n * 4) / t

    # all-to-all: each device exchanges (k-1)/k of its shard
    def a2a(v):
        parts = v.reshape(k, -1)
        return jax.lax.all_to_all(parts, axis, split_axis=0,
                                  concat_axis=0).reshape(-1)
    t = run(a2a, spec, spec)
    out["alltoall_bytes_per_s"] = (k - 1) / k * (n * 4 / k) / t

    # neighbor ppermute (the cp KV rotation primitive): N/k per hop
    shift = [(i, (i + 1) % k) for i in range(k)]
    t = run(lambda v: jax.lax.ppermute(v, axis, shift), spec, spec)
    out["ppermute_bytes_per_s"] = (n * 4 / k) / t
    return {kk: round(v, 1) for kk, v in out.items()}


def _axis_overlap(mesh, axis, compute_dim=1024, size_mb=4):
    """Comm/compute overlap coefficient over one axis (reference
    overlap_test): how much of an allreduce hides under an independent
    matmul dispatched in the same program.

        overlap = (t_compute + t_comm - t_together) / min(t_comm, t_compute)
    """
    k = mesh.shape[axis]
    if k <= 1:
        return None
    n = int(size_mb * (1 << 20) / 4)
    n -= n % k
    x = jnp.ones((n,), jnp.float32)
    a = jnp.full((compute_dim, compute_dim), 0.5, jnp.bfloat16)

    def comm(v):
        return jax.lax.psum(v, axis)

    def compute(m):
        return m @ m

    f_comm = jax.jit(shard_map(comm, mesh=mesh, in_specs=P(axis),
                               out_specs=P(), check_vma=False))
    f_comp = jax.jit(compute)

    def together(v, m):
        # one program holding both; outputs combined so neither can be
        # dead-code-eliminated and completion awaits both
        c = shard_map(comm, mesh=mesh, in_specs=P(axis),
                      out_specs=P(), check_vma=False)(v)
        d = compute(m)
        return c[0] + d[0, 0].astype(jnp.float32)
    f_both = jax.jit(together)

    t_comm = _timeit(f_comm, x)
    t_comp = _timeit(f_comp, a)
    t_both = _timeit(f_both, x, a)
    saved = t_comm + t_comp - t_both
    denom = min(t_comm, t_comp)
    return {
        "t_comm_ms": round(t_comm * 1e3, 3),
        "t_compute_ms": round(t_comp * 1e3, 3),
        "t_together_ms": round(t_both * 1e3, 3),
        "overlap": round(max(0.0, min(1.0, saved / denom)), 4)
        if denom > 0 else 0.0,
    }


def profile_env(axes=None, size_mb=8, compute_dim=1024, claim=None):
    """Full environment profile for a mesh of ``axes`` (default: one
    'dp' axis over every visible device).

    ``claim``: what the caller intends the numbers to characterize
    ("chip" or "host").  A CPU-platform run REFUSES a "chip" claim
    (VERDICT next #6: the virtual-mesh numbers characterize the host,
    and single-chip calibration cannot fix ICI/DCN) — the artifact
    always carries an explicit ``characterizes`` field plus a banner
    when it is not chip-grade."""
    if not axes:
        axes = {"dp": jax.device_count()}
    platform = jax.default_backend()
    characterizes = "chip" if platform in ("tpu", "gpu") else "host"
    if claim == "chip" and characterizes != "chip":
        raise ValueError(
            f"refusing to label a {platform}-platform profile as "
            f"chip-characterizing: the virtual mesh measures the HOST "
            f"(collective bandwidth over shared memory, not ICI/DCN); "
            f"run on real multi-chip hardware for a chip claim")
    mesh = make_mesh(axes)
    art = {
        "platform": platform,
        "characterizes": characterizes,
        "device_kind": jax.devices()[0].device_kind,
        "measured_at": time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime()),
        "mesh_axes": dict(mesh.shape),
        # 6 decimals: a CPU-mesh probe at small dims is ~1e-4 TFLOP/s
        # and must not round to a fake zero
        "matmul_tflops_bf16": round(
            profile_matmul_throughput(dim=compute_dim) / 1e12, 6),
        "axes": {},
    }
    if characterizes != "chip":
        art["WARNING"] = (
            "cpu-platform profile: these numbers characterize the HOST "
            "(virtual mesh over shared memory); they are NOT measured "
            "ICI/DCN bandwidths and must not be fed to a chip cost "
            "model as measurements")
    for axis in mesh.shape:
        if mesh.shape[axis] <= 1:
            continue
        art["axes"][axis] = {
            "size": mesh.shape[axis],
            "collectives": _axis_collective_bw(mesh, axis,
                                               size_mb=size_mb),
            "overlap": _axis_overlap(mesh, axis,
                                     compute_dim=compute_dim,
                                     size_mb=max(1, size_mb // 2)),
        }
    return art


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--axes", default=None,
                    help="mesh axes, e.g. dp=4,tp=2 (default: dp over "
                         "all visible devices)")
    ap.add_argument("--size-mb", type=int, default=8)
    ap.add_argument("--compute-dim", type=int, default=1024)
    ap.add_argument("--out", default=ENV_PROFILE_FILE)
    args = ap.parse_args()
    axes = None
    if args.axes:
        axes = {kv.split("=")[0]: int(kv.split("=")[1])
                for kv in args.axes.split(",")}
    art = profile_env(axes, size_mb=args.size_mb,
                      compute_dim=args.compute_dim)
    from ..artifact import atomic_json_dump
    atomic_json_dump(args.out, art)
    print(json.dumps({
        "platform": art["platform"],
        "characterizes": art["characterizes"],
        **({"WARNING": art["WARNING"]} if "WARNING" in art else {}),
        "matmul_tflops_bf16": art["matmul_tflops_bf16"],
        "axes": {a: {"allreduce_GBps": round(
            v["collectives"]["allreduce_bytes_per_s"] / 1e9, 3),
            "overlap": v["overlap"]["overlap"]}
            for a, v in art["axes"].items()},
        "out": os.path.basename(args.out)}))


if __name__ == "__main__":
    main()
