"""Dynamic-programming strategy search (Galvatron-equivalent).

Reference: tools/Galvatron/utils/dp_utils.py — ``DPAlg.fit`` is a
knapsack-style DP over (layer, memory-budget, strategy) minimizing total
time under a per-device memory cap (dp_utils.py:56-130), and
``pipeline_division_even`` splits layers into pp stages.  This module
reimplements both against the TPU cost models and emits a mesh + per-layer
sharding plan instead of process-group configs.

Memory is discretized to ``mem_unit`` (default 64 MB) buckets so the DP
table stays small; switching strategies between adjacent layers is charged
``switch_cost`` (the reference's inter_layer_cost resharding penalty).
"""

from __future__ import annotations

import numpy as np

from .cost_model import (ClusterSpec, LayerSpec, MemoryCostModel,
                         ParallelStrategy, TimeCostModel,
                         candidate_strategies)


class DPAlg:
    """min_time DP over layers x memory x strategy (dp_utils.py:56-130).

    ``fit`` returns (total_cost, per-layer strategy indices, leftover mem
    buckets); (inf, None, -1) when nothing fits."""

    def __init__(self, max_mem, layer_num, strategy_num):
        self.max_mem = int(max_mem) + 1
        self.layer_num = layer_num
        self.strategy_num = strategy_num
        self.v = None            # (L, S) int memory buckets
        self.intra = None        # (L, S) float time
        self.inter = None        # (L, S, S) float switch cost

    def set_v_and_cost(self, v, intra, inter):
        v = np.asarray(v, dtype=np.int64)
        intra = np.asarray(intra, dtype=np.float64)
        inter = np.asarray(inter, dtype=np.float64)
        assert v.shape == (self.layer_num, self.strategy_num)
        assert intra.shape == (self.layer_num, self.strategy_num)
        assert inter.shape == (self.layer_num, self.strategy_num,
                               self.strategy_num)
        self.v, self.intra, self.inter = v, intra, inter

    def fit(self):
        L, M, S = self.layer_num, self.max_mem, self.strategy_num
        f = np.zeros((M, S))
        mark = np.full((L, M, S), -1, dtype=np.int64)
        for i in range(L):
            nf = np.full((M, S), np.inf)
            for s in range(S):
                need = self.v[i, s]
                if need >= M:
                    continue
                # candidates[v, si] = f[v - need, si] + inter[i, si, s]
                cand = f[: M - need, :] + self.inter[i, :, s][None, :]
                best = np.argmin(cand, axis=1)
                rows = np.arange(M - need)
                nf[need:, s] = cand[rows, best] + self.intra[i, s]
                mark[i, need:, s] = best
            f = nf
        s = int(np.argmin(f[-1]))
        total = float(f[-1, s])
        if not np.isfinite(total):
            return np.inf, None, -1
        res = [s]
        v = M - 1
        for i in range(L - 1, 0, -1):
            ps = int(mark[i, v, res[0]])
            v -= int(self.v[i, res[0]])
            res.insert(0, ps)
        return total, res, v - int(self.v[0, res[0]])


def pipeline_division_even(layer_num, pp):
    """Even layer->stage split (reference pipeline_division_even)."""
    base, rem = divmod(layer_num, pp)
    sizes = [base + (1 if i < rem else 0) for i in range(pp)]
    stages, i = [], 0
    for sz in sizes:
        stages.append(list(range(i, i + sz)))
        i += sz
    return stages


class ParallelPlan:
    """Search result: the mesh to build and per-layer strategies."""

    def __init__(self, strategy_list, layers, cost, cluster):
        self.strategies = strategy_list      # list[ParallelStrategy]
        self.layers = layers
        self.cost = cost
        self.cluster = cluster

    @property
    def uniform(self):
        return len(set(map(str, self.strategies))) == 1

    def mesh_axes(self):
        """Axis sizes for `parallel.mesh.make_mesh` — one global mesh whose
        axis product must equal the device count.  Per-axis max works only
        for uniform plans; for mixed plans use the most common strategy's
        axes (layers with lower degree replicate over the spare extent; a
        layer wanting a *larger* degree than the mesh axis falls back to
        the mesh's)."""
        cand = {"pp": max(s.pp for s in self.strategies),
                "tp": max(s.tp for s in self.strategies),
                "cp": max(s.cp for s in self.strategies),
                "dp": max(s.dp for s in self.strategies)}
        n = self.cluster.n_devices if self.cluster else None
        prod = cand["pp"] * cand["tp"] * cand["cp"] * cand["dp"]
        if n is None or prod == n:
            return cand
        from collections import Counter
        common = Counter(map(str, self.strategies)).most_common(1)[0][0]
        s = next(x for x in self.strategies if str(x) == common)
        return {"pp": s.pp, "tp": s.tp, "cp": s.cp, "dp": s.dp}

    def stage_assignment(self):
        return pipeline_division_even(len(self.strategies),
                                      self.mesh_axes()["pp"])

    def describe(self):
        assumed = {}
        if self.cluster is not None and \
                hasattr(self.cluster, "assumed_constants"):
            assumed = self.cluster.assumed_constants()
        lines = []
        if assumed:
            # banner FIRST (VERDICT next #6): the reader must hit the
            # honesty disclaimer before the cost/layout it qualifies
            lines.append(
                "*** WARNING: cost-model constants unvalidated on "
                "hardware — "
                + ", ".join(f"{k} ({v['provenance']})"
                            for k, v in sorted(assumed.items()))
                + " ***")
        lines.append(f"total cost {self.cost * 1e3:.3f} ms/step; "
                     f"mesh {self.mesh_axes()}")
        for l, s in zip(self.layers, self.strategies):
            lines.append(f"  {l.name}: {s}")
        if assumed:
            lines.append(
                "  [cost-model constants NOT from measurement: "
                + ", ".join(f"{k} ({v['provenance']})"
                            for k, v in assumed.items()) + "]")
        return "\n".join(lines)


class PlannerSearch:
    """End-to-end search (reference ``DpOnModel``, dp_utils.py:132+).

    For each candidate pp (uniform across the model, as in Galvatron), the
    per-layer DP chooses among strategies with that pp; the best pp wins.
    ``mem_cap_fraction`` keeps headroom for the framework the way the
    reference reserves pytorch_context_mem (cost_model.py:11)."""

    def __init__(self, layers, global_batch_size, cluster=None,
                 max_tp=None, max_pp=None, allow_fsdp=True, allow_cp=True,
                 mem_unit=64 * 1024 * 1024, mem_cap_fraction=0.9,
                 switch_cost=1e-4, num_microbatches=None,
                 min_cp_block=128):
        self.layers = layers
        self.gbs = global_batch_size
        self.cluster = cluster or ClusterSpec()
        self.max_tp = max_tp
        self.max_pp = max_pp
        self.allow_fsdp = allow_fsdp
        self.allow_cp = allow_cp
        self.mem_unit = mem_unit
        self.mem_cap = self.cluster.hbm_bytes * mem_cap_fraction
        self.switch_cost = switch_cost
        self.num_microbatches = num_microbatches
        self.min_cp_block = min_cp_block

    def _costs(self, strategies):
        L, S = len(self.layers), len(strategies)
        v = np.zeros((L, S), dtype=np.int64)
        intra = np.full((L, S), np.inf)  # stays inf where gated out
        for i, layer in enumerate(self.layers):
            for j, s in enumerate(strategies):
                if s.cp > 1 and layer.seq_len / s.cp < self.min_cp_block:
                    # sequence shards below one flash-attention block are
                    # never worth the ring rotation on TPU
                    v[i, j] = 0
                    continue
                if s.dp * s.pp > self.gbs:
                    # cannot split the batch below one sample per stage
                    v[i, j] = 0
                    continue
                mem = MemoryCostModel(s, layer, self.gbs,
                                      self.cluster).total
                v[i, j] = int(np.ceil(mem / self.mem_unit))
                intra[i, j] = TimeCostModel(
                    s, layer, self.gbs, self.cluster,
                    self.num_microbatches,
                    pp_boundary_share=min(1.0, s.pp / len(self.layers)),
                ).gen_result()
        inter = np.zeros((L, S, S))
        for j in range(S):
            for k in range(S):
                if str(strategies[j]) != str(strategies[k]):
                    inter[:, j, k] = self.switch_cost
        return v, intra, inter

    def search(self):
        cands = candidate_strategies(
            self.cluster.n_devices, max_pp=self.max_pp, max_tp=self.max_tp,
            allow_fsdp=self.allow_fsdp, allow_cp=self.allow_cp)
        best = None
        mem_buckets = int(self.mem_cap / self.mem_unit)
        for pp in sorted({s.pp for s in cands}):
            if pp > len(self.layers):
                continue  # more stages than layers is degenerate
            group = [s for s in cands if s.pp == pp]
            v, intra, inter = self._costs(group)
            # A stage's devices hold only that stage's layers, so each
            # stage gets its own per-device budget and its own DP run
            # (reference: per-stage max_mem via pp_stage_dict, dp_utils.py
            # DpOnModel).  Budget beyond the stage's worst case is
            # equivalent, so cap the table size.
            stages = pipeline_division_even(len(self.layers), pp)
            total_cost, idx = 0.0, []
            for stage in stages:
                sv, si = v[stage], intra[stage]
                sin = inter[stage]
                budget = min(mem_buckets,
                             int(sv.max(axis=1).sum()) + 1)
                alg = DPAlg(budget, len(stage), len(group))
                alg.set_v_and_cost(sv, si, sin)
                cost, sidx, _ = alg.fit()
                if sidx is None:
                    idx = None
                    break
                total_cost += cost
                idx.extend(sidx)
            if idx is None:
                continue
            if best is None or total_cost < best.cost:
                best = ParallelPlan([group[i] for i in idx], self.layers,
                                    total_cost, self.cluster)
        return best
