"""Planner profiler: measure what the cost models need.

Galvatron profiles per-layer forward time and inter-GPU bandwidth with
standalone scripts (tools/Galvatron/test_env, bert/profile_forward.py)
whose outputs feed the cost models.  Here both probes are jax functions:

- :func:`profile_matmul_throughput` — achieved bf16 matmul FLOP/s (the
  ``flops_per_sec * mfu`` product).
- :func:`profile_collective_bandwidth` — ring-allreduce bytes/s over a
  mesh axis (ICI when the mesh spans real chips).
- :func:`profile_layer` — measured per-sample forward seconds for a layer
  callable, written into :class:`LayerSpec.fwd_time_per_sample`.
- :func:`measure_cluster` — bundle everything into a ClusterSpec.
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp

from .cost_model import ClusterSpec


def _timeit(fn, *args, warmup=2, iters=5):
    """Wall time per call with two tunnel-proofing measures (see
    planner/chip_calibration.py for the round-3 measurements that forced
    them): every call's completion is awaited by FETCHING a scalar of
    its output (``block_until_ready`` returns early through the axon
    tunnel, and the per-call outputs are independent dispatches — only
    awaiting the last would let the rest float past the timer), and the
    first floating-point array argument has one element SET to a
    per-iteration integer (exactly representable in any float dtype,
    unlike an additive epsilon) so no two dispatches are identical
    (identical dispatches get memoized).  The nudge costs one
    elementwise pass and the barrier one round-trip per iteration — a
    deliberate, slightly conservative bias."""
    from ..profiler import materialize_barrier

    args = list(args)
    vary = next((i for i, a in enumerate(args)
                 if hasattr(a, "dtype") and getattr(a, "ndim", 0) > 0
                 and jnp.issubdtype(a.dtype, jnp.floating)), None)
    for _ in range(warmup):
        materialize_barrier(fn(*args))
    t0 = time.perf_counter()
    for i in range(iters):
        if vary is not None:
            a = args[vary]
            args[vary] = a.at[(0,) * a.ndim].set(i + 1)
        materialize_barrier(fn(*args))
    return (time.perf_counter() - t0) / iters


def profile_matmul_throughput(dim=4096, dtype=jnp.bfloat16):
    a = jnp.ones((dim, dim), dtype)
    b = jnp.ones((dim, dim), dtype)
    f = jax.jit(lambda x, y: x @ y)
    t = _timeit(f, a, b)
    return 2.0 * dim ** 3 / t


def graph_layer_fn(output_node, feed_node):
    """Jitted ``x -> output`` from a built graph block — lets the profiler
    time REAL model layers (built from the hetu_tpu graph API) instead of
    analytic stand-ins.  Reference counterpart: Galvatron's per-model
    profile scripts time the actual torch modules
    (bert/profile_forward.py)."""
    from ..executor import Executor
    ex = Executor({"fwd": [output_node]})
    sub = ex.subexecutor["fwd"]
    params = dict(ex.var_values)

    def fn(x):
        _, _, outputs, _ = sub._trace(
            params, {}, jnp.zeros((), jnp.int32), jax.random.PRNGKey(0),
            {feed_node.name: x})
        return outputs[0]

    return jax.jit(fn)


def calibrate_layers(layers, layer_fns, batch=8, dtype=jnp.float32):
    """Measure each layer callable and write the result into its
    LayerSpec.fwd_time_per_sample (the TimeCostModel then uses measured
    time instead of the flops estimate).  ``layer_fns`` may be shorter
    than ``layers``: the last fn calibrates the remaining (identical)
    layers — the common N-identical-encoder case profiles once."""
    times = []
    for i, spec in enumerate(layers):
        if i < len(layer_fns):
            fn = layer_fns[i]
            t = profile_layer(fn, (spec.seq_len, spec.hidden),
                              batch=batch, dtype=dtype)
            times.append(t)
        else:
            t = times[-1]
        spec.fwd_time_per_sample = t
    return layers


def profile_collective_bandwidth(mesh, axis, size_mb=16):
    """Achieved allreduce bandwidth (algorithm bytes/s) over one mesh
    axis, via shard_map psum."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    k = mesh.shape[axis]
    if k <= 1:
        return float("inf")
    n = int(size_mb * 1024 * 1024 / 4)
    n -= n % k
    x = jnp.ones((n,), jnp.float32)

    # check_vma off: the input may be replicated over the mesh's other
    # axes, which static varying-axes inference can't always prove for
    # out_specs P()
    f = jax.jit(shard_map(lambda v: jax.lax.psum(v, axis), mesh=mesh,
                          in_specs=P(axis), out_specs=P(),
                          check_vma=False))
    t = _timeit(f, x)
    nbytes = n * 4 / k  # per-device message size (input sharded over axis)
    return 2.0 * (k - 1) / k * nbytes / t


def profile_layer(layer_fn, sample_shape, batch=8, dtype=jnp.float32,
                  seed=0):
    """Measured per-sample forward time of ``layer_fn(batch_input)``."""
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(batch, *sample_shape).astype(
        np.dtype(dtype.dtype.name if hasattr(dtype, "dtype") else "float32")))
    f = jax.jit(layer_fn)
    t = _timeit(f, x)
    return t / batch


def measure_cluster(mesh=None, n_devices=None, hbm_bytes=None,
                    probe_dim=4096):
    """Build a ClusterSpec from live measurements (analytic defaults fill
    anything unmeasurable on the current backend).  ``probe_dim`` sizes
    the matmul probe — shrink it on slow backends (CPU tests)."""
    spec = ClusterSpec()
    spec.n_devices = n_devices or (
        int(np.prod(list(mesh.shape.values()))) if mesh is not None
        else jax.device_count())
    achieved = profile_matmul_throughput(dim=probe_dim)
    spec.flops_per_sec = achieved
    spec.provenance["flops_per_sec"] = "measured"
    spec.mfu = 1.0  # 'achieved' already folds utilization in
    spec.provenance["mfu"] = "measured"
    if hbm_bytes:
        spec.hbm_bytes = hbm_bytes
        spec.provenance["hbm_bytes"] = "measured"   # caller-supplied cap
    else:
        try:
            stats = jax.devices()[0].memory_stats()
            if stats and "bytes_limit" in stats:
                spec.hbm_bytes = float(stats["bytes_limit"])
                spec.provenance["hbm_bytes"] = "measured"
        except Exception:
            pass
    if mesh is not None:
        for axis in mesh.shape:
            if mesh.shape[axis] > 1:
                bw = profile_collective_bandwidth(mesh, axis, size_mb=4)
                if np.isfinite(bw):
                    spec.ici_bandwidth = min(spec.ici_bandwidth, bw)
                    spec.provenance["ici_bandwidth"] = "measured"
                break
    return spec
