"""Analytic memory / time cost models for the auto-parallel planner.

Galvatron-equivalent (reference tools/Galvatron/utils/cost_model.py:3-36
``MemoryCostModel``, :38-160 ``TimeCostModel_with_overlap``), re-derived for
TPU: communication rides ICI (per-axis bidirectional ring bandwidth) or DCN
for the outermost axis, bf16 compute on the MXU, and XLA's async collectives
give compute/comm overlap modelled by a single overlap coefficient instead
of the reference's NCCL/PCIe-specific ``dp_overlap_coe``/``bct_overlap_coe``
pair (cost_model.py:49-56), which must be re-profiled per topology anyway.

All sizes are bytes, all times seconds, so profiled numbers plug in
directly.  A :class:`ClusterSpec` holds the hardware constants; defaults
approximate one TPU v5e chip and can be overwritten by the planner profiler
(hetu_tpu/planner/profiler.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class ParallelStrategy:
    """One point in the search space: (pp, tp, dp, fsdp, cp).

    Reference strategies are ``[pp, tp, dp, {'fsdp': 0/1, 'tp': consec}]``
    (dp_utils.py:4-19).  The TPU build adds ``cp`` (context parallel — the
    reference has no sequence parallelism, SURVEY.md §5.7) and drops the
    ``tp_consecutive`` flag: mesh-axis order fixes device adjacency once
    for all (parallel/mesh.py AXIS_ORDER).
    """

    pp: int = 1
    tp: int = 1
    dp: int = 1
    fsdp: bool = False
    cp: int = 1

    @property
    def n_devices(self):
        return self.pp * self.tp * self.dp * self.cp

    def __str__(self):
        dp = f"{self.dp}f" if self.fsdp else str(self.dp)
        s = f"{self.pp}-{self.tp}-{dp}"
        if self.cp > 1:
            s += f"-cp{self.cp}"
        return s


@dataclass
class ClusterSpec:
    """Hardware constants feeding both cost models.

    Defaults are order-of-magnitude v5e numbers; `planner.profiler`
    measures the real ones (matmul throughput + per-axis collective
    bandwidth) the way the reference's Galvatron profiler scripts do
    (tools/Galvatron/test_env)."""

    n_devices: int = 8
    hbm_bytes: float = 16e9
    flops_per_sec: float = 197e12      # bf16 MXU peak, one v5e chip
    mfu: float = 0.4                   # achieved fraction of peak
    ici_bandwidth: float = 45e9        # bytes/s per link direction
    dcn_bandwidth: float = 6.25e9      # bytes/s per host
    devices_per_host: int = 8          # ICI domain size (one slice/host)
    overlap: float = 0.7               # fraction of comm hidden by compute
    bytes_per_param: int = 4           # fp32 master params
    bytes_per_act: int = 2             # bf16 activations
    # constant -> how it was obtained: 'analytic-default' (this class's
    # literals), 'measured' (a live probe wrote it), or 'spec-assumed'
    # (spec-sheet value that CANNOT be measured on the available
    # hardware, e.g. ICI/DCN bandwidth on one chip).  load_calibration
    # fills this; plan_to_json surfaces the not-measured ones so a plan
    # consumer can see which cost terms ranked layouts on assumptions.
    provenance: dict = field(default_factory=dict)

    def assumed_constants(self):
        """The constants the cost model used WITHOUT a measurement."""
        keys = ("flops_per_sec", "mfu", "ici_bandwidth", "dcn_bandwidth",
                "overlap", "hbm_bytes")
        return {k: {"value": getattr(self, k),
                    "provenance": self.provenance.get(
                        k, "analytic-default")}
                for k in keys
                if self.provenance.get(k, "analytic-default")
                != "measured"}

    def collective_bw(self, axis_size, over_dcn=False):
        bw = self.dcn_bandwidth if over_dcn else self.ici_bandwidth
        return bw

    def allreduce_time(self, nbytes, axis_size, over_dcn=False):
        """Ring allreduce: 2*(k-1)/k * n / bw (same formula the reference
        uses for dp_message_size, cost_model.py:101)."""
        if axis_size <= 1 or nbytes == 0:
            return 0.0
        k = axis_size
        return 2.0 * (k - 1) / k * nbytes / self.collective_bw(k, over_dcn)

    def allgather_time(self, nbytes, axis_size, over_dcn=False):
        if axis_size <= 1 or nbytes == 0:
            return 0.0
        k = axis_size
        return (k - 1) / k * nbytes / self.collective_bw(k, over_dcn)

    reduce_scatter_time = allgather_time


@dataclass
class LayerSpec:
    """Per-layer quantities the cost models consume.  Either analytic
    (from hidden/seq sizes) or measured (profiler.profile_layer)."""

    name: str = "enc"
    param_bytes: float = 0.0           # full (unsharded) parameter bytes
    flops_per_sample: float = 0.0      # fwd flops for one sample
    act_bytes_per_sample: float = 0.0  # saved activations, one sample
    seq_len: int = 1
    hidden: int = 1
    # comm volume factor for TP: activations cross the tp cut this many
    # times per layer fwd (reference uses 4 for encoders, 6 for decoders,
    # cost_model.py:102-103)
    tp_comm_factor: int = 4
    # measured per-sample forward time (seconds); overrides the flops
    # estimate when set
    fwd_time_per_sample: float | None = None

    @classmethod
    def transformer_encoder(cls, hidden, seq_len, ffn_mult=4, name="enc",
                            bytes_per_param=4, bytes_per_act=2):
        """Analytic spec for one pre/post-LN transformer encoder layer."""
        p = (4 * hidden * hidden            # qkv + out proj
             + 2 * ffn_mult * hidden * hidden  # ffn in/out
             + 4 * hidden)                  # ln scales/biases (approx)
        flops = 2 * p * seq_len + 2 * 2 * seq_len * seq_len * hidden
        act = seq_len * hidden * (8 + 2 * ffn_mult)
        return cls(name=name, param_bytes=p * bytes_per_param,
                   flops_per_sample=flops,
                   act_bytes_per_sample=act * bytes_per_act,
                   seq_len=seq_len, hidden=hidden, tp_comm_factor=4)

    @classmethod
    def transformer_decoder(cls, hidden, seq_len, ffn_mult=4, name="dec",
                            bytes_per_param=4, bytes_per_act=2):
        """Decoder-only (GPT) block: same params as an encoder layer but
        CAUSAL attention halves the score/context matmul flops, and the
        reference prices decoders at a higher per-layer TP activation
        traffic (cost_model.py:102-103 uses 4 for encoders, 6 for
        decoders)."""
        spec = cls.transformer_encoder(hidden, seq_len, ffn_mult=ffn_mult,
                                       name=name,
                                       bytes_per_param=bytes_per_param,
                                       bytes_per_act=bytes_per_act)
        spec.flops_per_sample -= 2 * seq_len * seq_len * hidden  # causal
        spec.tp_comm_factor = 6
        return spec


class MemoryCostModel:
    """Per-device memory for one layer under a strategy.

    Mirrors reference MemoryCostModel semantics (cost_model.py:3-36):
    model states = params + grads + 2 optimizer moments (4x params, as the
    reference's ``model_states_size = 4 * parameter_size``); fsdp divides
    states by dp with the same +0.025 safety bias; activations scale with
    the per-device batch.  TP divides params and activations; CP divides
    activations along sequence."""

    FSDP_BIAS = 0.025

    def __init__(self, strategy: ParallelStrategy, layer: LayerSpec,
                 global_batch_size: int, cluster: ClusterSpec):
        s, l = strategy, layer
        self.strategy = s
        params = l.param_bytes / s.tp
        states = 4.0 * params
        if s.fsdp and s.dp > 1:
            states *= (1.0 / s.dp + self.FSDP_BIAS)
        local_bs = max(global_batch_size / (s.dp * s.pp), 1e-9)
        acts = l.act_bytes_per_sample * local_bs / (s.tp * s.cp)
        self.model_states = states
        self.activation = acts
        self.total = states + acts

    def get_memory_cost(self):
        return {"model_states": self.model_states,
                "activation": self.activation, "total": self.total}


class TimeCostModel:
    """Per-layer step time (fwd+bwd+grad sync) under a strategy.

    Reference behavior (TimeCostModel_with_overlap, cost_model.py:38-160):
    compute scales 1/tp, bwd = 2x fwd, DP gradient allreduce partially
    overlaps backward, TP adds 4 activation collectives/layer, fsdp adds a
    param allgather each of fwd/bwd, pipeline amortizes by microbatching
    ((pp + m - 1) / (pp * m), cost_model.py:124).  TPU re-derivation: one
    overlap coefficient, per-axis ICI rings, cp adds a KV ppermute ring
    whose volume is the attention KV stream."""

    def __init__(self, strategy: ParallelStrategy, layer: LayerSpec,
                 global_batch_size: int, cluster: ClusterSpec,
                 num_microbatches: int | None = None,
                 pp_boundary_share: float = 1.0):
        s, l, c = strategy, layer, cluster
        # per-device batch through a stage: gbs/dp (the /pp is carried by
        # the bubble factor below, reference fct = fwd * bs * layer_num,
        # cost_model.py:94 — bs = gbs/dp, NOT /pp)
        local_bs = max(global_batch_size / s.dp, 1e-9)
        m = num_microbatches or 4 * max(s.pp, 1)

        # --- compute ---
        if l.fwd_time_per_sample is not None:
            fwd = l.fwd_time_per_sample * local_bs / (s.tp * s.cp)
        else:
            fwd = (l.flops_per_sample * local_bs
                   / (s.tp * s.cp) / (c.flops_per_sec * c.mfu))
        bwd = 2.0 * fwd
        compute = fwd + bwd
        # pipeline bubble amortization (reference pipe_with_microbatch,
        # cost_model.py:124): x(pp+m-1)/(pp*m) = the 1/pp layer split plus
        # the (pp-1)/m bubble
        if s.pp > 1:
            compute *= (s.pp + m - 1) / (s.pp * m)

        # Axis placement follows mesh.AXIS_ORDER (tp/cp innermost): an
        # axis rides DCN once the devices inside it span more than one
        # ICI domain.
        tp_over_dcn = s.tp > c.devices_per_host
        cp_over_dcn = s.cp * s.tp > c.devices_per_host and s.cp > 1
        dp_over_dcn = s.dp * s.cp * s.tp > c.devices_per_host and s.dp > 1

        # --- gradient sync (dp axis) ---
        grad_bytes = l.param_bytes / s.tp
        if s.fsdp:
            # reduce-scatter grads + allgather params twice (fwd+bwd)
            dp_comm = (c.reduce_scatter_time(grad_bytes, s.dp,
                                             dp_over_dcn)
                       + 2.0 * c.allgather_time(grad_bytes, s.dp,
                                                dp_over_dcn))
        else:
            dp_comm = c.allreduce_time(grad_bytes, s.dp, dp_over_dcn)

        # --- tp activation collectives ---
        act_cut = (local_bs * l.seq_len * l.hidden * c.bytes_per_act
                   / s.cp)
        tp_comm = l.tp_comm_factor * c.allreduce_time(act_cut, s.tp,
                                                      tp_over_dcn)
        # backward doubles activation-collective traffic
        tp_comm *= 1.5

        # --- cp KV rotation (ring attention ppermute per step) ---
        kv_bytes = 2.0 * local_bs * l.seq_len * l.hidden * c.bytes_per_act \
            / (s.tp * s.cp)
        cp_comm = 0.0
        if s.cp > 1:
            cp_bw = c.dcn_bandwidth if cp_over_dcn else c.ici_bandwidth
            cp_comm = (s.cp - 1) * kv_bytes / cp_bw * 1.5

        # --- pp stage-boundary p2p (activation fwd + grad bwd); only
        # boundary layers pay it, so the caller scales by its share of
        # boundaries per layer (PlannerSearch passes pp/L) ---
        pp_comm = 0.0
        if s.pp > 1:
            pp_over_dcn = s.n_devices > c.devices_per_host
            pp_bw = c.dcn_bandwidth if pp_over_dcn else c.ici_bandwidth
            boundary_bytes = (2.0 * local_bs * l.seq_len * l.hidden
                              * c.bytes_per_act / (s.tp * s.cp))
            pp_comm = pp_boundary_share * boundary_bytes / pp_bw

        comm = dp_comm + tp_comm + cp_comm + pp_comm
        hidden_comm = min(comm, compute) * c.overlap
        self.compute = compute
        self.comm = comm
        self.total = compute + comm - hidden_comm

    def gen_result(self):
        return self.total


def candidate_strategies(n_devices, max_pp=None, max_tp=None, max_cp=None,
                         allow_fsdp=True, allow_cp=True):
    """Enumerate all (pp, tp, dp, fsdp, cp) with pp*tp*dp*cp == n_devices,
    powers of two per axis (reference enumerates the same lattice for 8
    GPUs, dp_utils.py:41-46)."""
    out = []

    def pows(limit):
        v, r = 1, []
        while v <= limit:
            r.append(v)
            v *= 2
        return r

    for pp in pows(min(max_pp or n_devices, n_devices)):
        if n_devices % pp:
            continue
        for tp in pows(min(max_tp or n_devices, n_devices // pp)):
            if (n_devices // pp) % tp:
                continue
            rem = n_devices // (pp * tp)
            cps = pows(min(max_cp or rem, rem)) if allow_cp else [1]
            for cp in cps:
                if rem % cp:
                    continue
                dp = rem // cp
                out.append(ParallelStrategy(pp, tp, dp, False, cp))
                if allow_fsdp and dp > 1:
                    out.append(ParallelStrategy(pp, tp, dp, True, cp))
    return out
