"""Turn a ParallelPlan into an executable configuration.

The reference's Galvatron emits per-layer (pp, tp, dp, fsdp) configs that
its own PyTorch runtime consumes (hybrid_parallel_model_dist.py).  Here a
plan becomes (a) a `jax.sharding.Mesh` and (b) an Executor `dist`
strategy that assigns NamedShardings to variables by layer membership —
the TPU-native carrier of the same information.
"""

from __future__ import annotations

import re

import jax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import make_mesh
from ..parallel.distributed_strategies import BaseSearchingStrategy


class AutoParallel(BaseSearchingStrategy):
    """Executor dist_strategy driven by a planner result.

    ``layer_of(name)`` maps a variable name to a layer index (default:
    matches the `l{i}_` and `_layer{i}_` conventions used across
    hetu_tpu.models; unmatched names fall back to strategies[0]).
    Column/row split patterns follow ModelParallel4LM.
    """

    def __init__(self, plan, layer_of=None,
                 col_patterns=("qkv", "wi", "fc1", "expand", "query",
                               "key", "value"),
                 row_patterns=("proj", "wo", "fc2", "reduce", "dense")):
        super().__init__()
        self.plan = plan
        self.layer_of = layer_of or self._default_layer_of
        self.col_patterns = col_patterns
        self.row_patterns = row_patterns

    @staticmethod
    def _default_layer_of(name):
        # anchored to the `l{i}_` / `_layer{i}_` layer-name conventions
        # used across hetu_tpu.models — a bare digit inside e.g.
        # 'fc1'/'wi2' is a sublayer index, not a layer index, and must
        # not match
        m = re.search(r"(?:^|[._])l(?:ayer)?(\d+)(?:[._]|$)", name)
        return int(m.group(1)) if m else None

    def _strategy_for(self, name):
        i = self.layer_of(name)
        if i is None or not (0 <= i < len(self.plan.strategies)):
            return self.plan.strategies[0]
        return self.plan.strategies[i]

    def configure(self, executor):
        axes = self.plan.mesh_axes()
        if executor.config.mesh is None:
            want = {k: v for k, v in axes.items() if v > 1} or {"dp": 1}
            executor.config.mesh = make_mesh(want)
        # a plan with pp > 1 drives the pipeline executor mode (strategies
        # configure before subexecutors are built, so this takes effect)
        if axes.get("pp", 1) > 1 and executor.config.pipeline is None:
            executor.config.pipeline = "gpipe"
            if executor.config.num_microbatches is None:
                executor.config.num_microbatches = 2 * axes["pp"]
        mesh_axes = set(executor.config.mesh.axis_names)
        for name, node in executor.variables.items():
            if node.sharding_spec is not None or not node.shape:
                continue
            s = self._strategy_for(name)
            lname = name.lower()
            dims = len(node.shape)
            spec = [None] * dims
            if s.tp > 1 and "tp" in mesh_axes and dims == 2:
                if any(p in lname for p in self.col_patterns):
                    spec[1] = "tp"
                elif any(p in lname for p in self.row_patterns):
                    spec[0] = "tp"
            if s.fsdp and "dp" in mesh_axes and dims >= 1:
                # shard the largest un-sharded dim over dp (ZeRO-3 style)
                free = [d for d in range(dims) if spec[d] is None]
                if free:
                    d = max(free, key=lambda d: node.shape[d])
                    if node.shape[d] % executor.config.mesh.shape["dp"] == 0:
                        spec[d] = "dp"
            if any(spec):
                node.sharding_spec = P(*spec)


def plan_to_json(plan):
    out = {"cost_s": plan.cost,
           "mesh": plan.mesh_axes(),
           "stages": plan.stage_assignment(),
           "layers": [{"name": l.name, "strategy": str(s)}
                      for l, s in zip(plan.layers, plan.strategies)]}
    if plan.cluster is not None and \
            hasattr(plan.cluster, "assumed_constants"):
        # which cost-model constants ranked this plan WITHOUT a
        # measurement (ICI/DCN bandwidth can't be measured on one chip)
        assumed = plan.cluster.assumed_constants()
        out["assumed_constants"] = assumed
        if assumed:
            # prominent honesty banner (VERDICT next #6): a consumer
            # reading only the top of the artifact must see that this
            # ranking trusts spec sheets, not measurements
            out["WARNING"] = (
                "cost-model constants unvalidated on hardware: "
                + ", ".join(f"{k} ({v['provenance']})"
                            for k, v in sorted(assumed.items()))
                + " — plan ranking is spec-assumed where marked; run "
                  "hetu_tpu.planner.env_profile on a real multi-chip "
                  "mesh to measure")
    return out
