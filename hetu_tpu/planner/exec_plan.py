"""Single-chip execution-config ranking — the planner closed over the
measured ablation space (VERDICT r3 item 6).

Galvatron's loop is: profile components on the target hardware, then let
the cost model rank FULL configurations it never ran (reference
tools/Galvatron/utils/cost_model.py:38-60 consumes per-component
profiled coefficients; bert/profile_forward.py produces them).  The
multi-device half of that loop lives in cost_model.py/search.py; this
module closes the SINGLE-CHIP half over the knobs the on-chip ablation
sweep measures (bench.py HETU_BENCH_SWEEP): per-chip batch, attention
implementation (XLA batched vs fused flash), and LM-head variant
(materialized vs fused chunked).

``ExecConfigModel`` decomposes step time into component costs

    t(b, attn, head) = c1*b + c2*b^2 + d_attn*b + d_head*b + c_fixed

fit by least squares on a calibration SUBSET of measured configs, then
predicts every config — including held-out ones — and ranks them by
throughput.  The quadratic term matters: throughput b/t(b) then has an
INTERIOR optimum at b = sqrt(c_fixed/c2), which is what the v5e
measured (batch 32 beat 48 and 64 per chip) — a linear per-sample model
can only ever crown the largest batch.  ``validate_against_sweep`` is
the closed-loop check, fit with the winner held out: the model's argmax
over the full grid must be the measured-best config, or — when two
configs measure within noise of each other — a config whose MEASURED
throughput is within ``regret_tol`` of the best (the planner's job is
to pick a config that IS fast, not to break measurement-noise ties).
"""

from __future__ import annotations

import json

import numpy as np


def _key(cfg):
    return (int(cfg["batch"]), str(cfg["attention"]), str(cfg["head"]))


class ExecConfigModel:
    """Least-squares component model over (batch, attention, head).

    Features per config: [b, b^2, b*is_flash, b*is_fused, 1] —
    per-sample base cost, super-linear efficiency-decay term (HBM
    pressure / utilization falloff past the sweet spot), per-sample
    attention-impl delta, per-sample head-variant delta, and fixed
    per-step overhead (dispatch, optimizer).
    """

    N_COEF = 5

    def __init__(self):
        self.coef = None

    @staticmethod
    def _features(cfg):
        b = float(cfg["batch"])
        return np.array([
            b,
            b * b,
            b * (cfg["attention"] == "flash"),
            b * (cfg["head"] == "fused"),
            1.0,
        ])

    def fit(self, rows):
        """rows: [{batch, attention, head, step_time_ms}]"""
        if len(rows) < self.N_COEF:
            raise ValueError(
                f"need >= {self.N_COEF} calibration configs to fit "
                f"{self.N_COEF} coefficients, got {len(rows)}")
        X = np.stack([self._features(r) for r in rows])
        y = np.array([float(r["step_time_ms"]) for r in rows])
        self.coef, *_ = np.linalg.lstsq(X, y, rcond=None)
        return self

    def predict_step_ms(self, cfg):
        assert self.coef is not None, "fit() first"
        return float(self._features(cfg) @ self.coef)

    def predict_throughput(self, cfg):
        """samples/sec — the ranking objective (matches the sweep's
        measured objective)."""
        t = self.predict_step_ms(cfg)
        if t <= 0:
            # an extrapolated negative time means the fit is outside its
            # valid region; rank it last rather than crowning it
            return 0.0
        return float(cfg["batch"]) / (t / 1e3)


def validate_against_sweep(sweep, fit_keys=None, regret_tol=0.02):
    """Fit on a subset, rank the FULL grid, compare against measured.

    ``sweep``: the SWEEP_BERT_BASE.json dict ({"configs": [...]}) or the
    list of config rows directly.  Each row: {batch, attention, head,
    step_time_ms}.  ``fit_keys``: optional iterable of (batch, attn,
    head) keys to calibrate on; default = every row EXCEPT the measured
    best (the strictest honest split: the model must predict the winner
    without having seen it).

    Returns {measured_best, predicted_best, argmax_match, regret,
    ok, spearman_rho, per_config: [...]}.  ``regret`` = 1 -
    measured_thr(predicted_best)/measured_thr(best): how much throughput
    a user loses by trusting the model's pick.  ``ok`` = exact argmax
    match OR regret <= regret_tol.
    """
    rows = sweep["configs"] if isinstance(sweep, dict) else list(sweep)
    rows = [r for r in rows
            if isinstance(r.get("step_time_ms"), (int, float))]
    # +1: the default split holds the measured-best row OUT of the fit,
    # so the fit itself still needs N_COEF rows
    need = ExecConfigModel.N_COEF + 1
    if len(rows) < need:
        raise ValueError(
            f"sweep has {len(rows)} measured rows; need >= {need} "
            f"(fit {ExecConfigModel.N_COEF} coefficients with the "
            f"winner held out)")
    thr = {_key(r): float(r["batch"]) / (r["step_time_ms"] / 1e3)
           for r in rows}
    measured_best = max(thr, key=thr.get)
    if fit_keys is None:
        fit_rows = [r for r in rows if _key(r) != measured_best]
    else:
        fit_keys = {tuple(k) for k in fit_keys}
        fit_rows = [r for r in rows if _key(r) in fit_keys]
    model = ExecConfigModel().fit(fit_rows)
    pred = {_key(r): model.predict_throughput(r) for r in rows}
    predicted_best = max(pred, key=pred.get)

    meas_order = sorted(thr, key=thr.get)
    pred_order = sorted(pred, key=pred.get)
    n = len(meas_order)
    mrank = {k: i for i, k in enumerate(meas_order)}
    prank = {k: i for i, k in enumerate(pred_order)}
    d2 = sum((mrank[k] - prank[k]) ** 2 for k in thr)
    rho = 1.0 - 6.0 * d2 / (n * (n * n - 1)) if n > 2 else 1.0

    regret = 1.0 - thr[predicted_best] / thr[measured_best]
    return {
        "measured_best": list(measured_best),
        "predicted_best": list(predicted_best),
        "argmax_match": predicted_best == measured_best,
        "regret": round(regret, 4),
        "regret_tol": regret_tol,
        "ok": predicted_best == measured_best or regret <= regret_tol,
        "spearman_rho": round(rho, 4),
        "n_configs": n,
        "n_fit": len(fit_rows),
        "coef_ms": {
            "per_sample_base": round(float(model.coef[0]), 5),
            "per_sample_sq_decay": round(float(model.coef[1]), 6),
            "per_sample_flash_delta": round(float(model.coef[2]), 5),
            "per_sample_fused_head_delta": round(float(model.coef[3]), 5),
            "fixed": round(float(model.coef[4]), 5),
        },
        "per_config": [
            {"config": list(k),
             "measured_samples_per_sec": round(thr[k], 2),
             "predicted_samples_per_sec": round(pred[k], 2)}
            for k in sorted(thr, key=thr.get, reverse=True)
        ],
    }


def validate_sweep_file(path, fit_keys=None):
    with open(path) as f:
        return validate_against_sweep(json.load(f), fit_keys=fit_keys)
