"""MoE model builders (reference examples/moe/test_moe_*.py).

``moe_mlp`` mirrors the reference example models: an MoE layer (gate of
choice from the gate family) used directly as a token classifier.
``moe_transformer_block`` is a transformer block whose FFN is the MoE
layer — the configuration the MoE papers actually benchmark.
"""

from __future__ import annotations

from .. import layers as htl
from ..graph import (
    softmaxcrossentropy_op, reduce_mean_op, array_reshape_op,
    softmaxcrossentropy_sparse_op,
)


def _make_gate(gate_type, embed_dim, num_tokens, num_experts, top_k,
               device_id=0):
    if gate_type == "top":
        return htl.TopKGate(embed_dim, num_tokens, num_experts, k=top_k)
    if gate_type == "hash":
        return htl.HashGate(embed_dim, num_tokens, num_experts)
    if gate_type == "ktop1":
        return htl.KTop1Gate(embed_dim, num_tokens, num_experts)
    if gate_type == "sam":
        return htl.SAMGate(embed_dim, num_tokens, num_experts)
    if gate_type == "balance":
        return htl.BalanceGate(embed_dim, num_tokens, num_experts)
    raise ValueError(f"unknown gate type {gate_type!r}")


def moe_mlp(x, y_, batch_size, num_tokens, model_dim, hidden_size,
            num_local_experts=2, all2all_size=1, gate_type="top", top_k=2,
            device_id=0, hierarchical=False, sparse_labels=False,
            expert_parallel=False):
    """MoE classifier (reference test_moe_base/top/hash/ktop1/sam.py).

    x: (B, T, D) tokens; y_: (B*T, C) one-hot, or (B*T,) int class ids
    with ``sparse_labels=True`` (C=model_dim one-hot targets are ~1000x
    the host->device bytes of int ids — feed sparse on TPU).
    ``expert_parallel=True`` uses the mesh-shardable StackedExperts
    formulation (run under an 'ep' mesh + ht.dist.ExpertParallel; the
    global expert count is num_local_experts * all2all_size either way).
    Returns (loss, y).
    """
    total_tokens = batch_size * num_tokens
    num_experts = num_local_experts * all2all_size
    gate = _make_gate(gate_type, model_dim, total_tokens, num_experts,
                      top_k, device_id)
    layer_name = "BalanceAssignmentLayer" if gate_type == "balance" \
        else "MoELayer"
    if expert_parallel:
        assert gate_type != "balance", (
            "balance-assignment mode uses the per-local-expert "
            "formulation; run it without expert_parallel")
        experts = htl.StackedExperts(num_experts, model_dim, hidden_size,
                                     activation="relu", name="expert")
        model = htl.MoELayer(gate=gate, experts=experts,
                             num_tokens=total_tokens, embed_dim=model_dim,
                             name=layer_name, top=top_k,
                             hierarchical=hierarchical)
    else:
        experts = [
            htl.Expert(embed_dim=model_dim, ffn_dim=hidden_size,
                       dropout_rate=0.1, activation="relu",
                       name=f"expert_{device_id * num_local_experts + i}")
            for i in range(num_local_experts)
        ]
        model = htl.MoELayer(gate=gate, experts=experts,
                             num_tokens=total_tokens,
                             embed_dim=model_dim,
                             all2all_size=all2all_size,
                             name=layer_name, top=top_k,
                             hierarchical=hierarchical)
    out = model(x)
    ce = softmaxcrossentropy_sparse_op if sparse_labels \
        else softmaxcrossentropy_op
    if gate_type == "balance":
        y = out
        loss = reduce_mean_op(ce(y, y_), [0])
    else:
        y, l_aux = out
        loss = reduce_mean_op(ce(y, y_), [0])
        if l_aux is not None:  # HashGate has no balance loss
            loss = loss + l_aux
    return loss, y


def moe_transformer_block(hidden, batch_size, seq_len, model_dim, num_heads,
                          hidden_size, num_local_experts=2, all2all_size=1,
                          gate_type="top", top_k=2, name="moe_block"):
    """Transformer block with an MoE FFN: attn -> LN -> MoE -> LN.

    hidden: (B*S, D) flattened hidden states; returns (B*S, D).
    """
    attn = htl.MultiHeadAttention(model_dim, num_heads, seq_len, batch_size,
                                  name=name + "_attn")
    ln1 = htl.LayerNorm(model_dim, name=name + "_ln1")
    ln2 = htl.LayerNorm(model_dim, name=name + "_ln2")
    h = ln1(hidden + attn(hidden))

    total_tokens = batch_size * seq_len
    experts = [htl.Expert(embed_dim=model_dim, ffn_dim=hidden_size,
                          activation="gelu", name=f"{name}_expert_{i}")
               for i in range(num_local_experts)]
    gate = _make_gate(gate_type, model_dim, total_tokens,
                      num_local_experts * all2all_size, top_k)
    moe = htl.MoELayer(gate=gate, experts=experts, num_tokens=total_tokens,
                       embed_dim=model_dim, all2all_size=all2all_size,
                       top=top_k, name="MoELayer")
    moe_out, l_aux = moe(h)
    out = ln2(h + array_reshape_op(moe_out, [total_tokens, model_dim]))
    return out
