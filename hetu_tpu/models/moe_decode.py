"""MoE serving: top-k routed expert FFN inside the ONE compiled core.

The fork is the MoE-oriented Hetu branch, yet PRs 2–18 built the whole
serving stack dense-GPT-only.  This module threads the flagship model
family through it: ``MoEDecodeConfig`` describes a GPT whose FFN blocks
(every ``moe_every``-th layer, the BertMoE alternation) are top-k
routed expert stacks, and :func:`moe_ffn` is the pure-jax serving twin
of ``layers/moe.py``'s graph-op gate math — same softmax gate, same
``capacity = k * ceil(tokens/E * cf)`` static capacity, same
rank-offset cumsum slotting, same drop rule (a token past capacity
takes the residual path, never a wrong token).  Every serving core in
``models/gpt_decode.py`` (decode step, flash prefill, verify, chunk,
mixed wave) swaps its dense FFN for this function through the shared
``_ffn_block`` seam, so offline ``generate_fast`` and the continuous-
batching engine keep decoding token-identically through ONE compiled
core — the MoE spec rides the jit-static ``cfg_tuple`` as a sixth,
hashable element.

Expert parallelism follows the ``tp_shard_params`` idiom:
:func:`ep_shard_params` places the ``*_moe_expert_stack_w1/w2`` leaves
with the expert dim over an ``ep`` mesh axis and GSPMD materializes
the dispatch/combine all-to-all around the per-expert matmuls — the
model code needs no annotations.  :func:`moe_ffn_ep_reference` is the
EXPLICIT ``shard_map`` + ``lax.all_to_all`` formulation (reference
moe_layer.py:74 placement), parity-tested against :func:`moe_ffn` and
carrying the optional int8 wire (``HETU_MOE_QUANT`` — the PR 9 codec:
quantize → all_to_all → dequantize, the EQuARX direction).

Routing statistics (per-expert load/drop counts) are computed IN the
compiled step and surfaced by the serving wrappers, so expert
imbalance — THE MoE production failure mode — is a first-class
observable in telemetry, ``hetu_top``, and the bench artifact.

Speculative decoding: the truncated-layer draft SKIPS ROUTING ENTIRELY
(``MoESpec.draft``) — its MoE layers contribute zero FFN (attention +
residual only), so drafting needs no dispatch, no capacity, and no
expert weights beyond what the target already holds; acceptance stays
exact because the target's verify pass owns every emitted token.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .. import envvars
from .gpt import GPTConfig


class MoESpec(NamedTuple):
    """Hashable MoE routing descriptor — the sixth, jit-static element
    of the serving ``cfg_tuple``.  ``draft=True`` marks the truncated-
    layer speculative draft, whose MoE layers skip the FFN sublayer
    entirely (zero contribution; the residual stream carries)."""

    num_experts: int
    top_k: int
    capacity_factor: float
    moe_every: int
    draft: bool = False
    ep_axis: Optional[str] = None

    def is_moe_layer(self, i):
        """BertMoE alternation: block i carries the MoE FFN when
        ``i % moe_every == moe_every - 1`` (1 = every block)."""
        return i % self.moe_every == self.moe_every - 1

    def moe_layers(self, L):
        """How many of the first ``L`` blocks are MoE blocks."""
        return sum(1 for i in range(L) if self.is_moe_layer(i))


class MoEDecodeConfig(GPTConfig):
    """GPTConfig + MoE routing for the serving stack.  ``ffn_size``
    keeps GPTConfig's meaning for the DENSE interleaved blocks;
    ``expert_size`` (default ``ffn_size``) is each expert's hidden
    width — equal-active-params A/Bs shrink it so that
    ``top_k * expert_size ≈ dense ffn_size``."""

    def __init__(self, num_experts=4, top_k=2, capacity_factor=1.0,
                 moe_every=1, expert_size=None, ep_axis=None, **kw):
        super().__init__(**kw)
        if num_experts < 2:
            raise ValueError(
                f"num_experts must be >= 2, got {num_experts}")
        if not 1 <= top_k <= num_experts:
            raise ValueError(
                f"top_k={top_k} outside [1, num_experts={num_experts}]")
        if not 1 <= moe_every <= self.num_hidden_layers:
            raise ValueError(
                f"moe_every={moe_every} outside [1, num_hidden_layers="
                f"{self.num_hidden_layers}]")
        if capacity_factor <= 0:
            raise ValueError(
                f"capacity_factor must be > 0, got {capacity_factor}")
        self.num_experts = int(num_experts)
        self.top_k = int(top_k)
        self.capacity_factor = float(capacity_factor)
        self.moe_every = int(moe_every)
        self.expert_size = int(expert_size or self.ffn_size)
        self.ep_axis = ep_axis


def resolve_moe_capacity(cf=None):
    """Serving capacity-factor override: an explicit value wins, else
    ``$HETU_MOE_CAPACITY`` (> 0), else None (the config's own)."""
    if cf is not None:
        return float(cf)
    raw = envvars.get_str("HETU_MOE_CAPACITY")
    if raw:
        v = float(raw)
        if v > 0:
            return v
    return None


def moe_spec_of(config, draft=False):
    """The :class:`MoESpec` a config implies, or None for a dense one.
    Duck-typed on ``num_experts`` so ``MoEDecodeConfig`` subclasses and
    hand-rolled config objects both route."""
    e = getattr(config, "num_experts", None)
    if not e:
        return None
    cf = resolve_moe_capacity() or float(
        getattr(config, "capacity_factor", 1.0))
    return MoESpec(
        num_experts=int(e),
        top_k=int(getattr(config, "top_k", 1)),
        capacity_factor=cf,
        moe_every=int(getattr(config, "moe_every", 1)),
        draft=bool(draft),
        ep_axis=getattr(config, "ep_axis", None))


def moe_capacity(spec, num_tokens):
    """Static per-expert slot count for a wave of ``num_tokens``
    (python int) — ``layers/moe.py topkgating``'s formula verbatim:
    ``k * ceil(tokens/E * capacity_factor)``, floored at ``k`` so a
    single-token wave always fits its own top-k."""
    cap = spec.top_k * math.ceil(
        (num_tokens / spec.num_experts) * spec.capacity_factor)
    return max(int(cap), spec.top_k)


def moe_ffn(params, us, x, spec, valid=None, stats=None):
    """Top-k routed expert FFN over a flat token block (the serving
    twin of ``layers/moe.py``'s gate → capacity dispatch → batched
    expert matmul → weighted combine).

    x: [T, D] (the post-LN FFN input); valid: [T] bool or None — False
    rows (pad positions, dead slots, inert ride-alongs) are excluded
    from routing so they never compete for expert capacity and never
    perturb another request's output (batch-company independence, the
    engine's core determinism contract).  Returns y [T, D]; a token
    dropped by EVERY rank contributes exactly 0 — its residual stream
    carries it unchanged, never a wrong token.

    Combine weights are the RAW softmax gate probabilities (reference
    topkgating: ``gates_s`` are un-renormalized) — so with
    ``top_k == num_experts`` the weights sum to 1 and replicated
    experts reproduce the dense FFN exactly (the oracle test).

    ``stats`` (optional dict, mutated at trace time): accumulates
    ``load``/``drop`` int32 [E] — per-expert tokens kept / tokens past
    capacity THIS call.  load + drop sums to valid_tokens * top_k per
    MoE layer, the invariant ``hetu_trace --check`` enforces.
    """
    E, k = spec.num_experts, spec.top_k
    T, D = x.shape
    cap = moe_capacity(spec, T)
    x32 = x.astype(jnp.float32)
    gw = params[f"{us}_moe_gate_weight"].astype(jnp.float32)
    gates = jax.nn.softmax(x32 @ gw, axis=-1)              # [T, E] f32
    topv, topi = jax.lax.top_k(gates, k)                   # [T, k]
    vmask = (jnp.ones((T,), bool) if valid is None
             else valid.reshape(T).astype(bool))
    acc = jnp.zeros((E,), jnp.int32)     # slots claimed by prior ranks
    dispatch = jnp.zeros((T, E, cap), jnp.float32)         # 0/1
    combine = jnp.zeros((T, E, cap), jnp.float32)          # gate-weighted
    load = jnp.zeros((E,), jnp.int32)
    drop = jnp.zeros((E,), jnp.int32)
    for r in range(k):
        mask = jax.nn.one_hot(topi[:, r], E,
                              dtype=jnp.int32) * vmask[:, None]
        # exclusive cumsum down the token axis + the slots prior ranks
        # already claimed: one shared [E, cap] pool, exactly
        # topkgating's locations1/locations2 arithmetic
        loc = jnp.cumsum(mask, axis=0) - mask + acc[None, :]
        pos = jnp.sum(loc * mask, axis=1)                  # [T]
        kept = mask * (pos < cap)[:, None]                 # [T, E]
        oh = jax.nn.one_hot(pos, cap, dtype=jnp.float32)   # [T, cap]
        d = kept.astype(jnp.float32)[:, :, None] * oh[:, None, :]
        dispatch = dispatch + d
        combine = combine + d * topv[:, r][:, None, None]
        acc = acc + jnp.sum(mask, axis=0)
        load = load + jnp.sum(kept, axis=0)
        drop = drop + jnp.sum(mask - kept, axis=0)
    cdt = x.dtype
    w1 = params[f"{us}_moe_expert_stack_w1"]               # [E, D, F]
    w2 = params[f"{us}_moe_expert_stack_w2"]               # [E, F, D]
    xe = jnp.einsum("tec,td->ecd", dispatch.astype(cdt), x)
    h = jnp.einsum("ecd,edf->ecf", xe, w1)
    b1 = params.get(f"{us}_moe_expert_stack_b1")
    if b1 is not None:
        h = h + b1[:, None, :]
    h = _gelu_tanh(h)
    h = jnp.einsum("ecf,efd->ecd", h, w2)
    b2 = params.get(f"{us}_moe_expert_stack_b2")
    if b2 is not None:
        # the bias must not leak into EMPTY capacity slots' combine
        # terms — it doesn't (their combine weight is exactly 0) — but
        # a DROPPED token's residual path must also see zero, which the
        # all-zero combine row guarantees
        h = h + b2[:, None, :]
    y = jnp.einsum("tec,ecd->td", combine.astype(cdt), h)
    if stats is not None:
        stats["load"] = stats.get("load", 0) + load
        stats["drop"] = stats.get("drop", 0) + drop
    return y.astype(x.dtype)


def _gelu_tanh(x):
    # local twin of gpt_decode._gelu_tanh (kept here so models.gpt_decode
    # -> models.moe_decode stays a one-way import)
    return 0.5 * x * (1.0 + jnp.tanh(
        0.7978845608028654 * (x + 0.044715 * x ** 3)))


# ------------------- expert-parallel placement ------------------- #


def ep_shard_params(params, mesh, config, axis="ep", name=None):
    """Place a MoE-GPT parameter dict for EXPERT-PARALLEL decoding: the
    ``*_moe_expert_stack_*`` leaves shard their leading expert dim over
    ``axis``; everything else (gate, attention, embeddings, dense FFN
    blocks) replicates.  Like ``tp_shard_params``, the decode cores
    need no other change — GSPMD propagates the expert sharding
    through the dispatch/combine einsums and materializes the token
    all-to-all at the resharding boundary.

    Validated up front by ``analysis.shard_check.check_expert_mesh``
    (axis exists, num_experts divisible) so a bad mesh is rejected
    before any buffer moves or compiles."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..analysis.shard_check import check_expert_mesh
    check_expert_mesh(mesh, int(config.num_experts), axis=axis)
    from .gpt_decode import _infer_name
    name = _infer_name(params, name)

    def spec_for(k):
        if "_moe_expert_stack_w" in k:
            return P(axis, None, None)
        if "_moe_expert_stack_b" in k:
            return P(axis, None)
        return P()

    return {k: jax.device_put(np.asarray(v),
                              NamedSharding(mesh, spec_for(k)))
            for k, v in params.items() if k.startswith(name + "_")}


def resolve_moe_quant(mode=None):
    """int8 dispatch/combine all-to-all wire: explicit ``mode`` wins,
    else ``$HETU_MOE_QUANT`` (the shared quant-knob grammar)."""
    from ..quant import resolve_quant
    return resolve_quant(mode, "HETU_MOE_QUANT")


def _a2a_wire(x, axis, split_axis, concat_axis, quant):
    """One all-to-all hop, optionally int8 on the wire (the PR 9
    codec: per-row symmetric quantize → exchange payload AND scales →
    dequantize).  Exactness note: quantization error is bounded by
    amax/254 per element (quant.py); the parity test pins the
    tolerance."""
    if not quant:
        return jax.lax.all_to_all(x, axis, split_axis=split_axis,
                                  concat_axis=concat_axis)
    from ..quant import dequantize_jax, quantize_jax
    d = x.shape[-1]
    q, scales = quantize_jax(x.astype(jnp.float32), chunk=d)
    q = jax.lax.all_to_all(q, axis, split_axis=split_axis,
                           concat_axis=concat_axis)
    scales = jax.lax.all_to_all(scales, axis, split_axis=split_axis,
                                concat_axis=concat_axis)
    return dequantize_jax(q, scales, chunk=d).astype(x.dtype)


def moe_ffn_ep_reference(params, us, x, spec, mesh, quant=None):
    """The EXPLICIT expert-parallel formulation: tokens sharded over
    the ``ep`` axis, per-shard gate + capacity dispatch, ``lax.
    all_to_all`` to expert-major, local expert matmuls over the expert
    shard, all-to-all back, per-shard combine — reference
    moe_layer.py's ``_stacked_forward`` collective placement, written
    in ``shard_map``.  ``quant``/"$HETU_MOE_QUANT" rides the exchange
    in int8 (payload + per-row scales).

    This is the parity/wire REFERENCE, not the serving hot path (the
    jitted cores use GSPMD propagation from :func:`ep_shard_params`):
    capacity is per-shard (each shard's ``T/n`` tokens), so it equals
    :func:`moe_ffn` exactly only while capacity is un-binding — which
    is precisely the regime the parity tests pin.

    x: [T, D] with T divisible by the axis size.  Returns y [T, D].
    """
    from jax.sharding import PartitionSpec as P
    from jax import shard_map  # installed by hetu_tpu._compat
    axis = spec.ep_axis or "ep"
    n = int(mesh.shape[axis])
    E = spec.num_experts
    if E % n:
        raise ValueError(
            f"num_experts={E} not divisible by {axis}={n}")
    T = x.shape[0]
    if T % n:
        raise ValueError(
            f"token count {T} not divisible by {axis}={n}")
    quant = resolve_moe_quant(quant)
    gw = params[f"{us}_moe_gate_weight"]
    w1 = params[f"{us}_moe_expert_stack_w1"]
    w2 = params[f"{us}_moe_expert_stack_w2"]
    b1 = params.get(f"{us}_moe_expert_stack_b1")
    b2 = params.get(f"{us}_moe_expert_stack_b2")
    if b1 is None:
        b1 = jnp.zeros((E, w1.shape[-1]), x.dtype)
    if b2 is None:
        b2 = jnp.zeros((E, w2.shape[-1]), x.dtype)
    k = spec.top_k
    cap = moe_capacity(spec, T // n)

    def local(xs, gw, w1, b1, w2, b2):
        # xs [T/n, D]; w1/w2/b1/b2 hold THIS shard's E/n experts
        t = xs.shape[0]
        x32 = xs.astype(jnp.float32)
        gates = jax.nn.softmax(x32 @ gw.astype(jnp.float32), axis=-1)
        topv, topi = jax.lax.top_k(gates, k)
        acc = jnp.zeros((E,), jnp.int32)
        dispatch = jnp.zeros((t, E, cap), jnp.float32)
        combine = jnp.zeros((t, E, cap), jnp.float32)
        for r in range(k):
            mask = jax.nn.one_hot(topi[:, r], E, dtype=jnp.int32)
            loc = jnp.cumsum(mask, axis=0) - mask + acc[None, :]
            pos = jnp.sum(loc * mask, axis=1)
            kept = mask * (pos < cap)[:, None]
            oh = jax.nn.one_hot(pos, cap, dtype=jnp.float32)
            d = kept.astype(jnp.float32)[:, :, None] * oh[:, None, :]
            dispatch = dispatch + d
            combine = combine + d * topv[:, r][:, None, None]
            acc = acc + jnp.sum(mask, axis=0)
        xe = jnp.einsum("tec,td->ecd", dispatch, x32)      # [E, cap, D]
        # DISPATCH all-to-all: expert-major — each device keeps its
        # E/n experts' slots from every peer: [E/n, n*cap, D]
        xe = _a2a_wire(xe, axis, 0, 1, quant)
        h = jnp.einsum("ecd,edf->ecf", xe,
                       w1.astype(jnp.float32)) + b1.astype(
                           jnp.float32)[:, None, :]
        h = _gelu_tanh(h)
        h = jnp.einsum("ecf,efd->ecd", h,
                       w2.astype(jnp.float32)) + b2.astype(
                           jnp.float32)[:, None, :]
        # COMBINE all-to-all: the exact inverse hop, back to
        # token-major [E, cap, D]
        h = _a2a_wire(h, axis, 1, 0, quant)
        y = jnp.einsum("tec,ecd->td", combine, h)
        return y.astype(xs.dtype)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(axis), P(), P(axis), P(axis), P(axis), P(axis)),
        out_specs=P(axis))
    return fn(x, gw, w1, b1, w2, b2)


# ------------------------- param builders ------------------------- #


def init_moe_params(config, name="moe", seed=0, scale=0.02):
    """Random MoE-GPT serving params (numpy): the dense-GPT naming
    contract (``{name}_wte_table`` .. per-layer attention/LN/dense FFN)
    plus, on each MoE block, the gate ``{us}_moe_gate_weight`` [D, E]
    and the StackedExperts-named stacks ``{us}_moe_expert_stack_w1``
    [E, D, F] / ``_w2`` [E, F, D] / ``_b1`` [E, F] / ``_b2`` [E, D].
    Dense interleaved blocks keep ``ffn_wi/wo`` only."""
    c = config
    spec = moe_spec_of(c)
    rng = np.random.default_rng(seed)
    D = c.hidden_size
    F_dense, F_exp = c.ffn_size, c.expert_size
    E = c.num_experts

    def r(*shape):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    p = {
        f"{name}_wte_table": r(c.vocab_size, D),
        f"{name}_wpe": r(c.max_position_embeddings, D),
        f"{name}_ln_f_scale": np.ones(D, np.float32),
        f"{name}_ln_f_bias": np.zeros(D, np.float32),
    }
    for i in range(c.num_hidden_layers):
        us = f"{name}_h{i}"
        p.update({
            f"{us}_ln1_scale": np.ones(D, np.float32),
            f"{us}_ln1_bias": np.zeros(D, np.float32),
            f"{us}_ln2_scale": np.ones(D, np.float32),
            f"{us}_ln2_bias": np.zeros(D, np.float32),
            f"{us}_attn_q_weight": r(D, D),
            f"{us}_attn_q_bias": np.zeros(D, np.float32),
            f"{us}_attn_k_weight": r(D, D),
            f"{us}_attn_k_bias": np.zeros(D, np.float32),
            f"{us}_attn_v_weight": r(D, D),
            f"{us}_attn_v_bias": np.zeros(D, np.float32),
            f"{us}_attn_proj_weight": r(D, D),
            f"{us}_attn_proj_bias": np.zeros(D, np.float32),
        })
        if spec.is_moe_layer(i):
            p.update({
                f"{us}_moe_gate_weight": r(D, E),
                f"{us}_moe_expert_stack_w1": r(E, D, F_exp),
                f"{us}_moe_expert_stack_b1": np.zeros((E, F_exp),
                                                      np.float32),
                f"{us}_moe_expert_stack_w2": r(E, F_exp, D),
                f"{us}_moe_expert_stack_b2": np.zeros((E, D),
                                                      np.float32),
            })
        else:
            p.update({
                f"{us}_ffn_wi_weight": r(D, F_dense),
                f"{us}_ffn_wi_bias": np.zeros(F_dense, np.float32),
                f"{us}_ffn_wo_weight": r(F_dense, D),
                f"{us}_ffn_wo_bias": np.zeros(D, np.float32),
            })
    return p


def convert_dense_to_moe(params, config, moe_config, name=None):
    """Replicate a DENSE GPT's FFN blocks into expert stacks: every
    expert of every MoE block carries the dense layer's exact wi/wo
    (and biases).  With ``top_k == num_experts`` the raw softmax
    combine weights sum to 1, so routing reproduces the dense FFN —
    the oracle the acceptance criteria pin (and a regression anchor
    for the gate math: any renormalization bug breaks it).  Gate
    weights are zero → uniform gates, maximally-even routing."""
    from .gpt_decode import _infer_name
    name = _infer_name(params, name)
    spec = moe_spec_of(moe_config)
    E = spec.num_experts
    out = {k: np.asarray(v) for k, v in params.items()
           if k.startswith(name + "_")}
    for i in range(moe_config.num_hidden_layers):
        if not spec.is_moe_layer(i):
            continue
        us = f"{name}_h{i}"
        wi = out.pop(f"{us}_ffn_wi_weight")
        bi = out.pop(f"{us}_ffn_wi_bias")
        wo = out.pop(f"{us}_ffn_wo_weight")
        bo = out.pop(f"{us}_ffn_wo_bias")
        D = wi.shape[0]
        out[f"{us}_moe_gate_weight"] = np.zeros((D, E), np.float32)
        out[f"{us}_moe_expert_stack_w1"] = np.broadcast_to(
            wi, (E,) + wi.shape).copy()
        out[f"{us}_moe_expert_stack_b1"] = np.broadcast_to(
            bi, (E,) + bi.shape).copy()
        out[f"{us}_moe_expert_stack_w2"] = np.broadcast_to(
            wo, (E,) + wo.shape).copy()
        out[f"{us}_moe_expert_stack_b2"] = np.broadcast_to(
            bo, (E,) + bo.shape).copy()
    return out
