"""CNN-family models (reference examples/cnn/models/*.py).

All builders share the reference signature ``model(x, y_) -> (loss, y)``
where ``x`` is a placeholder of shape (N, C, H, W) (or (N, dims) for the
dense models) and ``y_`` is one-hot labels (N, num_classes).

TPU notes: convs stay NCHW at the graph level (the conv op lowers to
``lax.conv_general_dilated`` which XLA lays out for the MXU); everything
traces into a single jitted step so the per-op Python loop the reference
pays (executor.py:1020-1058) does not exist here.
"""

from __future__ import annotations

import numpy as np

from .. import initializers as init
from ..graph import (
    matmul_op, broadcastto_op, relu_op, tanh_op, sigmoid_op, conv2d_op,
    max_pool2d_op, avg_pool2d_op, batch_normalization_op, array_reshape_op,
    softmaxcrossentropy_op, reduce_mean_op, slice_op, concat_op, mul_op,
)
from ..graph.ops_misc import Variable


def fc(x, shape, name, with_relu=True, stddev=0.1):
    """Dense layer helper (reference MLP.py:5-12)."""
    weight = init.random_normal(shape=shape, stddev=stddev,
                                name=name + "_weight")
    bias = init.random_normal(shape=shape[-1:], stddev=stddev,
                              name=name + "_bias")
    x = matmul_op(x, weight)
    x = x + broadcastto_op(bias, x)
    if with_relu:
        x = relu_op(x)
    return x


def _conv2d(x, in_ch, out_ch, kernel_size=3, stride=1, padding=1, name=""):
    weight = init.he_normal(shape=(out_ch, in_ch, kernel_size, kernel_size),
                            name=name + "_weight")
    return conv2d_op(x, weight, stride=stride, padding=padding)


def _bn(x, hidden, name, with_relu=False):
    scale = init.ones(shape=(hidden,), name=name + "_scale")
    bias = init.zeros(shape=(hidden,), name=name + "_bias")
    x = batch_normalization_op(x, scale, bias, momentum=0.9, eps=1e-5)
    return relu_op(x) if with_relu else x


def _loss_and_pred(y, y_):
    loss = softmaxcrossentropy_op(y, y_)
    loss = reduce_mean_op(loss, [0])
    return loss, y


# ---------------------------------------------------------------- dense


def mlp(x, y_):
    """3-layer MLP for MNIST (reference MLP.py:15-36)."""
    x = fc(x, (784, 256), "mlp_fc1")
    x = fc(x, (256, 256), "mlp_fc2")
    y = fc(x, (256, 10), "mlp_fc3", with_relu=False)
    return _loss_and_pred(y, y_)


def logreg(x, y_):
    """Logistic regression (reference LogReg.py)."""
    weight = init.zeros((784, 10), name="logreg_weight")
    bias = init.zeros((10,), name="logreg_bias")
    y = matmul_op(x, weight)
    y = y + broadcastto_op(bias, y)
    return _loss_and_pred(y, y_)


# ---------------------------------------------------------------- convnets


def cnn_3_layers(x, y_):
    """3-conv-layer net for MNIST (reference CNN.py)."""
    x = array_reshape_op(x, [-1, 1, 28, 28])
    x = relu_op(_conv2d(x, 1, 32, kernel_size=5, padding=2, name="cnn_conv1"))
    x = max_pool2d_op(x, 2, 2, stride=2)
    x = relu_op(_conv2d(x, 32, 64, kernel_size=5, padding=2,
                        name="cnn_conv2"))
    x = max_pool2d_op(x, 2, 2, stride=2)
    x = array_reshape_op(x, [-1, 7 * 7 * 64])
    y = fc(x, (7 * 7 * 64, 10), "cnn_fc", with_relu=False)
    return _loss_and_pred(y, y_)


def lenet(x, y_):
    """LeNet-5 for MNIST (reference LeNet.py)."""
    x = array_reshape_op(x, [-1, 1, 28, 28])
    x = tanh_op(_conv2d(x, 1, 6, kernel_size=5, padding=2,
                        name="lenet_conv1"))
    x = avg_pool2d_op(x, 2, 2, stride=2)
    x = tanh_op(_conv2d(x, 6, 16, kernel_size=5, padding=0,
                        name="lenet_conv2"))
    x = avg_pool2d_op(x, 2, 2, stride=2)
    x = array_reshape_op(x, [-1, 16 * 5 * 5])
    x = fc(x, (16 * 5 * 5, 120), "lenet_fc1")
    x = fc(x, (120, 84), "lenet_fc2")
    y = fc(x, (84, 10), "lenet_fc3", with_relu=False)
    return _loss_and_pred(y, y_)


def alexnet(x, y_, num_class=10):
    """CIFAR-sized AlexNet (reference AlexNet.py)."""
    x = relu_op(_bn(_conv2d(x, 3, 64, kernel_size=3, stride=1, padding=1,
                            name="alex_conv1"), 64, "alex_bn1"))
    x = max_pool2d_op(x, 2, 2, stride=2)
    x = relu_op(_bn(_conv2d(x, 64, 192, kernel_size=3, padding=1,
                            name="alex_conv2"), 192, "alex_bn2"))
    x = max_pool2d_op(x, 2, 2, stride=2)
    x = relu_op(_conv2d(x, 192, 384, kernel_size=3, padding=1,
                        name="alex_conv3"))
    x = relu_op(_conv2d(x, 384, 256, kernel_size=3, padding=1,
                        name="alex_conv4"))
    x = relu_op(_conv2d(x, 256, 256, kernel_size=3, padding=1,
                        name="alex_conv5"))
    x = max_pool2d_op(x, 2, 2, stride=2)
    x = array_reshape_op(x, [-1, 256 * 4 * 4])
    x = fc(x, (256 * 4 * 4, 1024), "alex_fc1")
    x = fc(x, (1024, 512), "alex_fc2")
    y = fc(x, (512, num_class), "alex_fc3", with_relu=False)
    return _loss_and_pred(y, y_)


def _vgg_block(x, in_ch, out_ch, n_convs, name):
    for i in range(n_convs):
        x = _bn(_conv2d(x, in_ch if i == 0 else out_ch, out_ch,
                        name=f"{name}_layer{i + 1}"), out_ch,
                f"{name}_bn{i + 1}", with_relu=True)
    return max_pool2d_op(x, 2, 2, padding=0, stride=2)


def vgg(x, y_, num_layers=16, num_class=10):
    """VGG-16/19 for CIFAR (reference VGG.py)."""
    if num_layers == 16:
        plan = [2, 2, 3, 3, 3]
    elif num_layers == 19:
        plan = [2, 2, 4, 4, 4]
    else:
        raise ValueError("vgg: num_layers must be 16 or 19")
    channels = [64, 128, 256, 512, 512]
    in_ch = 3
    for i, (n_convs, out_ch) in enumerate(zip(plan, channels)):
        x = _vgg_block(x, in_ch, out_ch, n_convs, f"vgg_block{i + 1}")
        in_ch = out_ch
    x = array_reshape_op(x, [-1, 512])
    x = fc(x, (512, 4096), "vgg_fc1")
    x = fc(x, (4096, 4096), "vgg_fc2")
    y = fc(x, (4096, num_class), "vgg_fc3", with_relu=False)
    return _loss_and_pred(y, y_)


def vgg16(x, y_, num_class=10):
    return vgg(x, y_, num_layers=16, num_class=num_class)


def vgg19(x, y_, num_class=10):
    return vgg(x, y_, num_layers=19, num_class=num_class)


def _basic_block(x, in_ch, out_ch, stride, name):
    """ResNet basic block (reference ResNet.py:52-70)."""
    shortcut = x
    x = _conv2d(x, in_ch, out_ch, kernel_size=3, stride=stride, padding=1,
                name=name + "_conv33a")
    x = _bn(x, out_ch, name + "_bn1", with_relu=True)
    x = _conv2d(x, out_ch, out_ch, kernel_size=3, stride=1, padding=1,
                name=name + "_conv33b")
    x = _bn(x, out_ch, name + "_bn2")
    if in_ch != out_ch or stride > 1:
        shortcut = _conv2d(shortcut, in_ch, out_ch, kernel_size=1,
                           stride=stride, padding=0, name=name + "_conv11")
        shortcut = _bn(shortcut, out_ch, name + "_bn3")
    return relu_op(x + shortcut), out_ch


def _bottleneck(x, in_ch, ch, stride, name):
    """ResNet bottleneck block (reference ResNet.py:28-50)."""
    out_ch = 4 * ch
    shortcut = x
    x = _conv2d(x, in_ch, ch, kernel_size=1, stride=stride, padding=0,
                name=name + "_conv11a")
    x = _bn(x, ch, name + "_bn1", with_relu=True)
    x = _conv2d(x, ch, ch, kernel_size=3, stride=1, padding=1,
                name=name + "_conv33")
    x = _bn(x, ch, name + "_bn2", with_relu=True)
    x = _conv2d(x, ch, out_ch, kernel_size=1, stride=1, padding=0,
                name=name + "_conv11b")
    x = _bn(x, out_ch, name + "_bn2b")
    if in_ch != out_ch or stride > 1:
        shortcut = _conv2d(shortcut, in_ch, out_ch, kernel_size=1,
                           stride=stride, padding=0, name=name + "_conv11c")
        shortcut = _bn(shortcut, out_ch, name + "_bn3")
    return relu_op(x + shortcut), out_ch


def resnet(x, y_, num_layers=18, num_class=10):
    """ResNet for CIFAR-10 (reference ResNet.py:80-133)."""
    plans = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
             101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
    if num_layers not in plans:
        raise ValueError(f"resnet: unsupported depth {num_layers}")
    layers = plans[num_layers]
    block = _bottleneck if num_layers > 34 else _basic_block
    channels = [16, 32, 64, 128]

    cur = 16
    x = _conv2d(x, 3, cur, kernel_size=3, stride=1, padding=1,
                name="resnet_initial_conv")
    x = _bn(x, cur, "resnet_initial_bn", with_relu=True)
    for i, n_blocks in enumerate(layers):
        for k in range(n_blocks):
            stride = 2 if k == 0 and i > 0 else 1
            x, cur = block(x, cur, channels[i], stride,
                           f"resnet_block_{i}_{k}")
    x = reduce_mean_op(x, [2, 3])
    y = fc(x, (cur, num_class), "resnet_final_fc", with_relu=False)
    return _loss_and_pred(y, y_)


def resnet18(x, y_, num_class=10):
    return resnet(x, y_, num_layers=18, num_class=num_class)


def resnet34(x, y_, num_class=10):
    return resnet(x, y_, num_layers=34, num_class=num_class)


def resnet50(x, y_, num_class=10):
    return resnet(x, y_, num_layers=50, num_class=num_class)


def resnet101(x, y_, num_class=10):
    return resnet(x, y_, num_layers=101, num_class=num_class)


def resnet152(x, y_, num_class=10):
    return resnet(x, y_, num_layers=152, num_class=num_class)


# ---------------------------------------------------------------- recurrent
#
# The reference unrolls 28 timesteps at graph-build time (RNN.py:39-55,
# LSTM.py:48-90); we keep that structure — XLA traces the unrolled graph
# into one fused program, so there is no per-step dispatch cost.


def rnn(x, y_, diminput=28, dimhidden=128, dimoutput=10, nsteps=28):
    """Unrolled vanilla RNN for MNIST rows (reference RNN.py)."""
    w_in = init.random_normal((diminput, dimhidden), stddev=0.1,
                              name="rnn_weight1")
    b_in = init.random_normal((dimhidden,), stddev=0.1, name="rnn_bias1")
    w_h = init.random_normal((dimhidden + dimhidden, dimhidden), stddev=0.1,
                             name="rnn_weight2")
    b_h = init.random_normal((dimhidden,), stddev=0.1, name="rnn_bias2")
    w_out = init.random_normal((dimhidden, dimoutput), stddev=0.1,
                               name="rnn_weight3")
    b_out = init.random_normal((dimoutput,), stddev=0.1, name="rnn_bias3")

    last_state = Variable("rnn_initial_state",
                          value=np.zeros((1,), dtype=np.float32),
                          trainable=False)
    for i in range(nsteps):
        cur_x = slice_op(x, (0, i * diminput), (-1, diminput))
        h = matmul_op(cur_x, w_in)
        h = h + broadcastto_op(b_in, h)
        if i == 0:
            last_state = broadcastto_op(last_state, h)
        s = concat_op(h, last_state, axis=1)
        s = matmul_op(s, w_h)
        s = s + broadcastto_op(b_h, s)
        last_state = relu_op(s)
    y = matmul_op(last_state, w_out)
    y = y + broadcastto_op(b_out, y)
    return _loss_and_pred(y, y_)


def lstm(x, y_, diminput=28, dimhidden=128, dimoutput=10, nsteps=28):
    """Unrolled LSTM for MNIST rows (reference LSTM.py)."""
    def gate_params(gname):
        w = init.random_normal((diminput, dimhidden), stddev=0.1,
                               name=f"lstm_{gname}_w")
        u = init.random_normal((dimhidden, dimhidden), stddev=0.1,
                               name=f"lstm_{gname}_u")
        b = init.random_normal((dimhidden,), stddev=0.1,
                               name=f"lstm_{gname}_b")
        return w, u, b

    fw, fu, fb = gate_params("forget_gate")
    iw, iu, ib = gate_params("input_gate")
    ow, ou, ob = gate_params("output_gate")
    cw, cu, cb = gate_params("cell")
    w_out = init.random_normal((dimhidden, dimoutput), stddev=0.1,
                               name="lstm_out_w")
    b_out = init.random_normal((dimoutput,), stddev=0.1, name="lstm_out_b")

    h = c = None
    for i in range(nsteps):
        cur_x = slice_op(x, (0, i * diminput), (-1, diminput))

        def gate(w, u, b, act):
            pre = matmul_op(cur_x, w)
            if h is not None:
                pre = pre + matmul_op(h, u)
            pre = pre + broadcastto_op(b, pre)
            return act(pre)

        f_g = gate(fw, fu, fb, sigmoid_op)
        i_g = gate(iw, iu, ib, sigmoid_op)
        o_g = gate(ow, ou, ob, sigmoid_op)
        c_tilde = gate(cw, cu, cb, tanh_op)
        c = mul_op(i_g, c_tilde) if c is None \
            else mul_op(f_g, c) + mul_op(i_g, c_tilde)
        h = mul_op(o_g, tanh_op(c))
    y = matmul_op(h, w_out)
    y = y + broadcastto_op(b_out, y)
    return _loss_and_pred(y, y_)
