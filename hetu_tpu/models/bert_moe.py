"""BERT with Mixture-of-Experts FFN layers — the flagship-LM MoE
composition (reference examples/nlp/bert/hetu_bert_moe.py:126-153, driven
by train_hetu_bert_dp_moe.py): encoder blocks whose FFN is an MoE layer,
with the per-layer auxiliary balance losses accumulated into the
training loss (reference hetu_bert_moe.py:149-152 threads ``moe_loss``
through the encoder the same way).

TPU-first differences from the reference:

* experts are the mesh-shardable ``StackedExperts`` [E, D, F]
  formulation (one batched einsum over a leading expert dim sharded on
  'ep'), not a per-local-expert python list — GSPMD emits the token
  all-to-all at the ``alltoall_op`` markers inside the one jitted step;
* ``moe_every`` interleaves dense and MoE FFN blocks (GShard-style
  alternation; ``moe_every=1`` reproduces the reference's every-layer
  placement);
* the MLM loss path keeps the fused chunked tied head (logits lazy),
  shared with the dense model via ``BertPreTrainingHeads``.

Run under ``ht.dist.ExpertParallel(ep=..., dp=...)`` — expert stacks
('*expert*' names) shard over 'ep', everything else replicates over it.
"""

from __future__ import annotations

from .. import layers
from ..graph import array_reshape_op, dropout_op, mul_byconst_op
from .bert import (
    BertAttentionBlock, BertConfig, BertEmbeddings, BertLayer, BertPooler,
    BertPreTrainingHeads, additive_attention_mask,
)


class BertMoEConfig(BertConfig):
    """BertConfig + MoE knobs.

    num_experts      global expert count (shard over 'ep' must divide it)
    top_k            experts per token (TopKGate)
    capacity_factor  static per-expert capacity multiplier
    moe_every        every Nth encoder block gets the MoE FFN, counting
                     from block moe_every-1 (1 = all blocks, the
                     reference placement; 2 = GShard alternation)
    aux_loss_weight  weight of the summed balance losses in the total
    hierarchical_a2a two-stage all-to-all over ('ici','dcn') for
                     multi-host expert meshes
    """

    def __init__(self, num_experts=8, top_k=1, capacity_factor=1.0,
                 moe_every=2, aux_loss_weight=0.01,
                 hierarchical_a2a=False, **kw):
        super().__init__(**kw)
        if num_experts < 2:
            raise ValueError(f"num_experts must be >= 2, got {num_experts}")
        if not 1 <= moe_every <= self.num_hidden_layers:
            raise ValueError(
                f"moe_every={moe_every} outside [1, num_hidden_layers="
                f"{self.num_hidden_layers}]")
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.moe_every = moe_every
        self.aux_loss_weight = aux_loss_weight
        self.hierarchical_a2a = hierarchical_a2a

    def is_moe_block(self, i):
        return i % self.moe_every == self.moe_every - 1


class BertMoELayer:
    """Encoder block with the FFN replaced by an MoE layer: the shared
    BertAttentionBlock, then MoE(gate, stacked experts) -> add&norm.
    Returns (hidden, l_aux)."""

    def __init__(self, config: BertMoEConfig, name="bert_moe_layer"):
        c = config
        self.config = c
        self.attn_block = BertAttentionBlock(config, name=name)
        tokens = c.batch_size * c.seq_len
        self.gate = layers.TopKGate(
            c.hidden_size, tokens, c.num_experts, k=c.top_k,
            capacity_factor=c.capacity_factor, name=name + "_gate")
        experts = layers.StackedExperts(
            c.num_experts, c.hidden_size, c.intermediate_size,
            # same activation normalization as the dense BertLayer:
            # gelu when asked for, relu otherwise
            activation="gelu" if c.hidden_act == "gelu" else "relu",
            name=name + "_moe")
        self.moe = layers.MoELayer(
            gate=self.gate, experts=experts, num_tokens=tokens,
            embed_dim=c.hidden_size, hierarchical=c.hierarchical_a2a,
            top=c.top_k, name="MoELayer")
        self.out_ln = layers.LayerNorm(c.hidden_size, eps=c.layer_norm_eps,
                                       name=name + "_out_ln")

    def __call__(self, hidden, attention_mask=None, kv_lens=None):
        c = self.config
        hidden = self.attn_block(hidden, attention_mask=attention_mask,
                                 kv_lens=kv_lens)
        moe_out, l_aux = self.moe(hidden)
        moe_out = array_reshape_op(
            moe_out, [c.batch_size * c.seq_len, c.hidden_size])
        if c.hidden_dropout_prob > 0:
            moe_out = dropout_op(moe_out, 1.0 - c.hidden_dropout_prob)
        return self.out_ln(hidden + moe_out), l_aux


class BertMoEModel:
    """Backbone; returns (sequence_output, pooled_output, l_aux_total).
    l_aux_total is the sum of the per-MoE-block balance losses
    (reference hetu_bert_moe.py:149-152 moe_loss accumulation)."""

    def __init__(self, config: BertMoEConfig, name="bert"):
        self.config = config
        self.embeddings = BertEmbeddings(config, name=name + "_embeddings")
        self.encoder_layers = []
        for i in range(config.num_hidden_layers):
            cls = BertMoELayer if config.is_moe_block(i) else BertLayer
            self.encoder_layers.append(cls(config, name=f"{name}_layer{i}"))
        self.pooler = BertPooler(config, name=name + "_pooler")

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 kv_lens=None):
        assert attention_mask is None or kv_lens is None, (
            "pass either attention_mask or kv_lens, not both")
        hidden = self.embeddings(input_ids, token_type_ids)
        add_mask = None
        if attention_mask is not None:
            add_mask = additive_attention_mask(self.config, attention_mask)
        l_aux_total = None
        for layer in self.encoder_layers:
            if isinstance(layer, BertMoELayer):
                hidden, l_aux = layer(hidden, attention_mask=add_mask,
                                      kv_lens=kv_lens)
                l_aux_total = l_aux if l_aux_total is None \
                    else l_aux_total + l_aux
            else:
                hidden = layer(hidden, attention_mask=add_mask,
                               kv_lens=kv_lens)
        return hidden, self.pooler(hidden), l_aux_total


class BertMoEForPreTraining:
    """MLM + NSP + weighted balance loss (reference
    train_hetu_bert_dp_moe.py adds moe_loss into the training loss).
    Head params and loss assembly are the SAME BertPreTrainingHeads the
    dense model uses — only the backbone differs."""

    def __init__(self, config: BertMoEConfig, name="bert"):
        self.config = config
        self.bert = BertMoEModel(config, name=name)
        self.heads = BertPreTrainingHeads(
            config, self.bert.embeddings.word_embeddings, name=name)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 masked_lm_labels=None, next_sentence_label=None,
                 kv_lens=None):
        c = self.config
        seq_out, pooled, l_aux = self.bert(input_ids, token_type_ids,
                                           attention_mask, kv_lens=kv_lens)
        h, logits = self.heads.mlm(seq_out)
        nsp_logits = self.heads.nsp(pooled)
        if masked_lm_labels is None:
            return logits, nsp_logits
        loss = self.heads.pretraining_loss(h, nsp_logits, masked_lm_labels,
                                           next_sentence_label)
        if l_aux is not None and c.aux_loss_weight:
            loss = loss + mul_byconst_op(l_aux, c.aux_loss_weight)
        return loss, logits, nsp_logits
