"""BERT (reference examples/nlp/bert/hetu_bert.py, ~10.7k-LoC directory).

Class structure mirrors the reference/HuggingFace lineage: Embeddings ->
Encoder(NxLayer) -> Pooler, with task heads (pretraining = MLM + NSP,
sequence classification for GLUE).  Hidden states flow flattened as
(B*S, H) 2-D matmuls — the MXU-friendly layout — exactly like the
reference keeps them for its cuBLAS path.

Static batch/seq are constructor arguments because the graph compiles to
a fixed-shape XLA program (SURVEY.md §7 "static shapes").
"""

from __future__ import annotations

import numpy as np

from .. import initializers as init
from .. import layers
from ..graph import (
    embedding_lookup_op, array_reshape_op, broadcast_shape_op, dropout_op,
    matmul_op, broadcastto_op, relu_op, gelu_op, tanh_op, slice_op,
    softmaxcrossentropy_sparse_op, tied_lm_head_xent_op,
    reduce_mean_op, reduce_sum_op, squeeze_op,
    addbyconst_op, mul_byconst_op, opposite_op, div_op, bool_op,
    full_like_op,
)


class BertConfig:
    """Hyper-parameters (reference hetu_bert.py BertConfig)."""

    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_act="gelu",
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, batch_size=8, seq_len=128,
                 use_flash_attention=False, layer_norm_eps=1e-12):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.initializer_range = initializer_range
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.use_flash_attention = use_flash_attention
        # 1e-12, matching the reference BERT (hetu_bert.py:74,886) and
        # HF — the framework-wide LayerNorm default of 1e-5 is a
        # visible parity delta at small hidden sizes
        self.layer_norm_eps = layer_norm_eps

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def large(cls, **kw):
        kw.setdefault("hidden_size", 1024)
        kw.setdefault("num_hidden_layers", 24)
        kw.setdefault("num_attention_heads", 16)
        kw.setdefault("intermediate_size", 4096)
        return cls(**kw)


class BertEmbeddings:
    """word + position + token_type embeddings -> LN -> dropout."""

    def __init__(self, config: BertConfig, name="bert_embeddings"):
        c = config
        std = c.initializer_range
        self.config = c
        self.word_embeddings = init.random_normal(
            (c.vocab_size, c.hidden_size), stddev=std,
            name=name + "_word_embeddings")
        self.position_embeddings = init.random_normal(
            (c.max_position_embeddings, c.hidden_size), stddev=std,
            name=name + "_position_embeddings")
        self.token_type_embeddings = init.random_normal(
            (c.type_vocab_size, c.hidden_size), stddev=std,
            name=name + "_token_type_embeddings")
        self.layer_norm = layers.LayerNorm(c.hidden_size, eps=c.layer_norm_eps,
                                           name=name + "_ln")

    def __call__(self, input_ids, token_type_ids=None):
        c = self.config
        b, s, h = c.batch_size, c.seq_len, c.hidden_size
        emb = embedding_lookup_op(self.word_embeddings, input_ids)
        pos = slice_op(self.position_embeddings, (0, 0), (s, h))
        emb = emb + broadcast_shape_op(pos, (b, s, h), add_axes=[0])
        if token_type_ids is not None:
            emb = emb + embedding_lookup_op(self.token_type_embeddings,
                                            token_type_ids)
        emb = array_reshape_op(emb, [b * s, h])
        emb = self.layer_norm(emb)
        if c.hidden_dropout_prob > 0:
            emb = dropout_op(emb, 1.0 - c.hidden_dropout_prob)
        return emb


class BertAttentionBlock:
    """Self-attention half of an encoder block: attention -> dropout ->
    add&norm.  Shared by the dense BertLayer and the MoE block
    (bert_moe.BertMoELayer), so attention wiring changes propagate to
    both."""

    def __init__(self, config: BertConfig, name="bert_layer"):
        c = config
        self.config = c
        self.attention = layers.MultiHeadAttention(
            c.hidden_size, c.num_attention_heads, c.seq_len, c.batch_size,
            dropout_rate=c.attention_probs_dropout_prob,
            use_flash=c.use_flash_attention, name=name + "_attn")
        self.attn_ln = layers.LayerNorm(c.hidden_size, eps=c.layer_norm_eps,
                                        name=name + "_attn_ln")

    def __call__(self, hidden, attention_mask=None, kv_lens=None):
        c = self.config
        attn = self.attention(hidden, attention_mask=attention_mask,
                              kv_lens=kv_lens)
        if c.hidden_dropout_prob > 0:
            attn = dropout_op(attn, 1.0 - c.hidden_dropout_prob)
        return self.attn_ln(hidden + attn)


class BertLayer:
    """One encoder block: self-attention -> add&norm -> FFN -> add&norm."""

    def __init__(self, config: BertConfig, name="bert_layer"):
        c = config
        act = gelu_op if c.hidden_act == "gelu" else relu_op
        self.config = c
        self.act = act
        self.attn_block = BertAttentionBlock(config, name=name)
        self.intermediate = layers.Linear(c.hidden_size, c.intermediate_size,
                                          name=name + "_intermediate")
        self.output = layers.Linear(c.intermediate_size, c.hidden_size,
                                    name=name + "_output")
        self.out_ln = layers.LayerNorm(c.hidden_size, eps=c.layer_norm_eps,
                                       name=name + "_out_ln")

    def __call__(self, hidden, attention_mask=None, kv_lens=None):
        c = self.config
        hidden = self.attn_block(hidden, attention_mask=attention_mask,
                                 kv_lens=kv_lens)
        ffn = self.output(self.act(self.intermediate(hidden)))
        if c.hidden_dropout_prob > 0:
            ffn = dropout_op(ffn, 1.0 - c.hidden_dropout_prob)
        return self.out_ln(hidden + ffn)


class BertPooler:
    """tanh projection of the [CLS] token."""

    def __init__(self, config: BertConfig, name="bert_pooler"):
        self.config = config
        self.dense = layers.Linear(config.hidden_size, config.hidden_size,
                                   name=name + "_dense")

    def __call__(self, sequence_output):
        c = self.config
        x = array_reshape_op(sequence_output,
                             [c.batch_size, c.seq_len, c.hidden_size])
        cls = slice_op(x, (0, 0, 0), (c.batch_size, 1, c.hidden_size))
        cls = array_reshape_op(cls, [c.batch_size, c.hidden_size])
        return tanh_op(self.dense(cls))


def additive_attention_mask(config, attention_mask):
    """(B, S) {0,1} mask -> additive (B,1,1,S): (1-m) * -10000."""
    c = config
    m = array_reshape_op(attention_mask, [c.batch_size, 1, 1, c.seq_len])
    return mul_byconst_op(addbyconst_op(opposite_op(m), 1.0), -10000.0)


class BertModel:
    """Backbone; returns (sequence_output (B*S,H), pooled_output (B,H))."""

    def __init__(self, config: BertConfig, name="bert"):
        self.config = config
        self.embeddings = BertEmbeddings(config, name=name + "_embeddings")
        self.encoder_layers = [BertLayer(config, name=f"{name}_layer{i}")
                               for i in range(config.num_hidden_layers)]
        self.pooler = BertPooler(config, name=name + "_pooler")

    def attention_mask_from_input(self, attention_mask):
        return additive_attention_mask(self.config, attention_mask)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 kv_lens=None):
        """``kv_lens`` [B] int node: valid-prefix lengths — keeps the
        flash kernel active under padding (an additive attention_mask
        forces the unfused path).  Mutually exclusive with
        attention_mask."""
        assert attention_mask is None or kv_lens is None, (
            "pass either attention_mask or kv_lens, not both")
        hidden = self.embeddings(input_ids, token_type_ids)
        add_mask = None
        if attention_mask is not None:
            add_mask = self.attention_mask_from_input(attention_mask)
        for layer in self.encoder_layers:
            hidden = layer(hidden, attention_mask=add_mask,
                           kv_lens=kv_lens)
        return hidden, self.pooler(hidden)


def _masked_mean(per_token_loss, labels_flat, ignored_index=-1):
    """Mean over non-ignored positions only (reference averages MLM loss
    over masked tokens, hetu_bert.py), so the MLM/NSP weighting does not
    depend on the mask rate.

    Microbatching caveat (pipeline / gradient accumulation): the
    denominator is the VALID count of whatever slice this graph sees.
    Under ``pipeline=`` the loss becomes the mean of per-microbatch
    masked means, which equals the global masked mean only when ignored
    positions are evenly distributed across microbatches — the same
    per-chunk-weighting bias standard gradient-accumulation loops have.
    Keep -1 densities roughly uniform per microbatch (e.g. shuffled MLM
    masking does this naturally) when exact equivalence matters."""
    valid = bool_op(labels_flat, full_like_op(labels_flat, ignored_index),
                    cond=2)  # labels > ignored_index
    count = addbyconst_op(reduce_sum_op(valid, [0]), 1e-12)
    return div_op(reduce_sum_op(per_token_loss, [0]), count)


class BertPreTrainingHeads:
    """Tied MLM decoder + NSP head, shared by the dense and MoE
    pretraining models (the reference's cls heads, hetu_bert.py)."""

    def __init__(self, config: BertConfig, word_embeddings, name="bert"):
        c = config
        self.config = c
        self.word_embeddings = word_embeddings
        self.transform = layers.Linear(c.hidden_size, c.hidden_size,
                                       name=name + "_mlm_transform")
        self.transform_ln = layers.LayerNorm(c.hidden_size,
                                             eps=c.layer_norm_eps,
                                             name=name + "_mlm_ln")
        self.decoder_bias = init.zeros((c.vocab_size,),
                                       name=name + "_mlm_bias")
        self.nsp = layers.Linear(c.hidden_size, 2, name=name + "_nsp")

    def mlm(self, seq_out):
        """(h, logits) for the tied MLM decoder.  The logits node is
        LAZY — training losses go through the fused chunked head on
        ``h`` instead, so the [B*S, vocab] logits chain is only ever
        computed if a caller evaluates it."""
        h = self.transform_ln(gelu_op(self.transform(seq_out)))
        logits = matmul_op(h, self.word_embeddings, trans_B=True)
        logits = logits + broadcastto_op(self.decoder_bias, logits)
        return h, logits

    def pretraining_loss(self, h, nsp_logits, masked_lm_labels,
                         next_sentence_label):
        """masked-mean MLM loss (fused chunked tied head) + NSP loss."""
        c = self.config
        labels_flat = array_reshape_op(masked_lm_labels,
                                       [c.batch_size * c.seq_len])
        mlm_loss = tied_lm_head_xent_op(
            h, self.word_embeddings, self.decoder_bias,
            labels_flat, ignored_index=-1)
        nsp_loss = softmaxcrossentropy_sparse_op(nsp_logits,
                                                 next_sentence_label)
        return (_masked_mean(mlm_loss, labels_flat)
                + reduce_mean_op(nsp_loss, [0]))


class BertForPreTraining:
    """MLM + NSP heads (reference hetu_bert.py BertForPreTraining)."""

    def __init__(self, config: BertConfig, name="bert"):
        self.config = config
        self.bert = BertModel(config, name=name)
        self.heads = BertPreTrainingHeads(
            config, self.bert.embeddings.word_embeddings, name=name)

    def _mlm_head(self, seq_out):
        return self.heads.mlm(seq_out)

    # checkpoint-name-stable attribute passthroughs (pre-round-4 callers
    # reached the head params through the model object)
    @property
    def decoder_bias(self):
        return self.heads.decoder_bias

    @property
    def transform(self):
        return self.heads.transform

    @property
    def nsp(self):
        return self.heads.nsp

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 masked_lm_labels=None, next_sentence_label=None,
                 kv_lens=None):
        seq_out, pooled = self.bert(input_ids, token_type_ids,
                                    attention_mask, kv_lens=kv_lens)
        h, logits = self.heads.mlm(seq_out)
        nsp_logits = self.heads.nsp(pooled)
        if masked_lm_labels is None:
            return logits, nsp_logits
        loss = self.heads.pretraining_loss(h, nsp_logits, masked_lm_labels,
                                           next_sentence_label)
        return loss, logits, nsp_logits


class BertForMaskedLM:
    def __init__(self, config: BertConfig, name="bert"):
        self.pretraining = BertForPreTraining(config, name=name)
        self.config = config

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 masked_lm_labels=None, kv_lens=None):
        c = self.config
        p = self.pretraining
        seq_out, _pooled = p.bert(input_ids, token_type_ids,
                                  attention_mask, kv_lens=kv_lens)
        h, logits = p._mlm_head(seq_out)
        if masked_lm_labels is None:
            return logits
        labels_flat = array_reshape_op(masked_lm_labels,
                                       [c.batch_size * c.seq_len])
        # fused chunked head for the loss; the logits node stays lazy
        # unless a caller evaluates it
        loss = tied_lm_head_xent_op(
            h, p.bert.embeddings.word_embeddings, p.decoder_bias,
            labels_flat, ignored_index=-1)
        return _masked_mean(loss, labels_flat), logits


class BertForSequenceClassification:
    """GLUE-style classifier head (reference hetu_bert.py)."""

    def __init__(self, config: BertConfig, num_labels=2, name="bert"):
        c = config
        self.config = c
        self.num_labels = num_labels
        self.bert = BertModel(config, name=name)
        self.classifier = layers.Linear(c.hidden_size, num_labels,
                                        name=name + "_classifier")

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 labels=None, kv_lens=None):
        c = self.config
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask,
                              kv_lens=kv_lens)
        if c.hidden_dropout_prob > 0:
            pooled = dropout_op(pooled, 1.0 - c.hidden_dropout_prob)
        logits = self.classifier(pooled)
        if labels is None:
            return logits
        loss = softmaxcrossentropy_sparse_op(logits, labels)
        return reduce_mean_op(loss, [0]), logits


class BertForQuestionAnswering:
    """SQuAD span-prediction head: per-token start/end logits.

    The reference's BERT example suite stages SQuAD
    (examples/nlp/bert/data/SquadDownloader.py:1, data/bertPrep.py:1);
    ``hetu_tpu.squad`` builds the window features this head consumes.
    Loss is the mean of start and end sparse cross-entropies over the
    S token positions, positions clamped to [CLS]=0 by the feature
    builder when the answer falls outside a window.
    """

    def __init__(self, config: BertConfig, name="bert"):
        c = config
        self.config = c
        self.bert = BertModel(config, name=name)
        self.qa_outputs = layers.Linear(c.hidden_size, 2,
                                        name=name + "_qa_outputs")

    def __call__(self, input_ids, token_type_ids=None,
                 attention_mask=None, start_positions=None,
                 end_positions=None, kv_lens=None):
        c = self.config
        seq, _ = self.bert(input_ids, token_type_ids, attention_mask,
                           kv_lens=kv_lens)
        logits = self.qa_outputs(seq)                      # (B*S, 2)
        logits = array_reshape_op(logits,
                                  [c.batch_size, c.seq_len, 2])
        start_logits = squeeze_op(
            slice_op(logits, (0, 0, 0), (c.batch_size, c.seq_len, 1)), 2)
        end_logits = squeeze_op(
            slice_op(logits, (0, 0, 1), (c.batch_size, c.seq_len, 1)), 2)
        if start_positions is None:
            return start_logits, end_logits
        start_loss = reduce_mean_op(
            softmaxcrossentropy_sparse_op(start_logits, start_positions),
            [0])
        end_loss = reduce_mean_op(
            softmaxcrossentropy_sparse_op(end_logits, end_positions),
            [0])
        loss = mul_byconst_op(start_loss + end_loss, 0.5)
        return loss, start_logits, end_logits
