"""Encoder-decoder MT Transformer (reference examples/nlp/hetu_transformer.py).

Vanilla "Attention is All You Need" topology: token+position embeddings,
N encoder blocks (self-attn + FFN), N decoder blocks (causal self-attn +
cross-attn + FFN), tied-or-free output projection, label-smoothing-free
sparse softmax CE with padding-id masking.

Cross-attention is built inline from the op surface (the layers.MultiHead-
Attention class is self-attention-only); causal masking is a constant
additive (1,1,S,S) lower-triangular mask broadcast over (B,nh,S,S).
"""

from __future__ import annotations

import math

import numpy as np

from .. import initializers as init
from .. import layers
from ..graph import (
    embedding_lookup_op, array_reshape_op, broadcast_shape_op, transpose_op,
    batch_matmul_op, softmax_op, mul_byconst_op, broadcastto_op, matmul_op,
    linear_op, relu_op, gelu_op, dropout_op, slice_op,
    softmaxcrossentropy_sparse_op, reduce_mean_op,
)
from ..graph.ops_misc import Variable


class TransformerConfig:
    def __init__(self, src_vocab_size=32000, tgt_vocab_size=32000,
                 hidden_size=512, num_layers=6, num_heads=8, ffn_size=2048,
                 dropout_rate=0.1, batch_size=8, src_len=64, tgt_len=64,
                 pad_id=0):
        self.src_vocab_size = src_vocab_size
        self.tgt_vocab_size = tgt_vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_size = ffn_size
        self.dropout_rate = dropout_rate
        self.batch_size = batch_size
        self.src_len = src_len
        self.tgt_len = tgt_len
        self.pad_id = pad_id


def _sinusoid_table(max_len, hidden):
    pos = np.arange(max_len)[:, None].astype(np.float32)
    dim = np.arange(hidden)[None, :].astype(np.float32)
    angle = pos / np.power(10000.0, 2 * (dim // 2) / hidden)
    table = np.zeros((max_len, hidden), dtype=np.float32)
    table[:, 0::2] = np.sin(angle[:, 0::2])
    table[:, 1::2] = np.cos(angle[:, 1::2])
    return table


class _MHA:
    """Inline multi-head attention supporting distinct q and kv sources."""

    def __init__(self, cfg, q_len, kv_len, name):
        h = cfg.hidden_size
        self.cfg = cfg
        self.q_len, self.kv_len = q_len, kv_len
        self.nh = cfg.num_heads
        self.hd = h // cfg.num_heads
        ini = init.GenXavierUniform()
        self.wq = ini(shape=(h, h), name=name + "_q_weight")
        self.wk = ini(shape=(h, h), name=name + "_k_weight")
        self.wv = ini(shape=(h, h), name=name + "_v_weight")
        self.wo = ini(shape=(h, h), name=name + "_proj_weight")

    def _heads(self, x, seq):
        b = self.cfg.batch_size
        x = array_reshape_op(x, [b, seq, self.nh, self.hd])
        return transpose_op(x, [0, 2, 1, 3])

    def __call__(self, q_in, kv_in, mask=None):
        cfg = self.cfg
        q = self._heads(matmul_op(q_in, self.wq), self.q_len)
        k = self._heads(matmul_op(kv_in, self.wk), self.kv_len)
        v = self._heads(matmul_op(kv_in, self.wv), self.kv_len)
        scores = mul_byconst_op(batch_matmul_op(q, k, trans_B=True),
                                1.0 / math.sqrt(self.hd))
        if mask is not None:
            scores = scores + broadcastto_op(mask, scores)
        probs = softmax_op(scores)
        if cfg.dropout_rate > 0:
            probs = dropout_op(probs, 1.0 - cfg.dropout_rate)
        out = batch_matmul_op(probs, v)
        out = transpose_op(out, [0, 2, 1, 3])
        out = array_reshape_op(out,
                               [cfg.batch_size * self.q_len,
                                cfg.hidden_size])
        return matmul_op(out, self.wo)


class _FFN:
    def __init__(self, cfg, name):
        self.cfg = cfg
        self.wi = layers.Linear(cfg.hidden_size, cfg.ffn_size,
                                name=name + "_wi")
        self.wo = layers.Linear(cfg.ffn_size, cfg.hidden_size,
                                name=name + "_wo")

    def __call__(self, x):
        out = self.wo(relu_op(self.wi(x)))
        if self.cfg.dropout_rate > 0:
            out = dropout_op(out, 1.0 - self.cfg.dropout_rate)
        return out


class Transformer:
    """Full encoder-decoder model; __call__ returns (loss, logits)."""

    def __init__(self, config: TransformerConfig, name="transformer"):
        cfg = config
        self.cfg = cfg
        h = cfg.hidden_size
        self.src_emb = init.random_normal((cfg.src_vocab_size, h),
                                          stddev=0.02,
                                          name=name + "_src_emb")
        self.tgt_emb = init.random_normal((cfg.tgt_vocab_size, h),
                                          stddev=0.02,
                                          name=name + "_tgt_emb")
        self.src_pos = Variable(
            name + "_src_pos", value=_sinusoid_table(cfg.src_len, h),
            trainable=False)
        self.tgt_pos = Variable(
            name + "_tgt_pos", value=_sinusoid_table(cfg.tgt_len, h),
            trainable=False)
        from ..graph.ops_attention import causal_mask_op
        self.causal_mask = causal_mask_op(cfg.tgt_len, neg=-1e9)

        self.enc = []
        for i in range(cfg.num_layers):
            self.enc.append({
                "attn": _MHA(cfg, cfg.src_len, cfg.src_len,
                             f"{name}_enc{i}_attn"),
                "ln1": layers.LayerNorm(h, name=f"{name}_enc{i}_ln1"),
                "ffn": _FFN(cfg, f"{name}_enc{i}_ffn"),
                "ln2": layers.LayerNorm(h, name=f"{name}_enc{i}_ln2"),
            })
        self.dec = []
        for i in range(cfg.num_layers):
            self.dec.append({
                "self": _MHA(cfg, cfg.tgt_len, cfg.tgt_len,
                             f"{name}_dec{i}_self"),
                "ln1": layers.LayerNorm(h, name=f"{name}_dec{i}_ln1"),
                "cross": _MHA(cfg, cfg.tgt_len, cfg.src_len,
                              f"{name}_dec{i}_cross"),
                "ln2": layers.LayerNorm(h, name=f"{name}_dec{i}_ln2"),
                "ffn": _FFN(cfg, f"{name}_dec{i}_ffn"),
                "ln3": layers.LayerNorm(h, name=f"{name}_dec{i}_ln3"),
            })
        self.out_proj = layers.Linear(h, cfg.tgt_vocab_size,
                                      name=name + "_out_proj")

    def _embed(self, ids, table, pos_table, seq):
        cfg = self.cfg
        h = cfg.hidden_size
        emb = embedding_lookup_op(table, ids)
        emb = mul_byconst_op(emb, math.sqrt(h))
        emb = emb + broadcast_shape_op(pos_table,
                                       (cfg.batch_size, seq, h),
                                       add_axes=[0])
        emb = array_reshape_op(emb, [cfg.batch_size * seq, h])
        if cfg.dropout_rate > 0:
            emb = dropout_op(emb, 1.0 - cfg.dropout_rate)
        return emb

    def encode(self, src_ids):
        cfg = self.cfg
        x = self._embed(src_ids, self.src_emb, self.src_pos, cfg.src_len)
        for blk in self.enc:
            x = blk["ln1"](x + blk["attn"](x, x))
            x = blk["ln2"](x + blk["ffn"](x))
        return x

    def decode(self, tgt_ids, memory):
        cfg = self.cfg
        x = self._embed(tgt_ids, self.tgt_emb, self.tgt_pos, cfg.tgt_len)
        for blk in self.dec:
            x = blk["ln1"](x + blk["self"](x, x, mask=self.causal_mask))
            x = blk["ln2"](x + blk["cross"](x, memory))
            x = blk["ln3"](x + blk["ffn"](x))
        return x

    def __call__(self, src_ids, tgt_ids, labels=None):
        cfg = self.cfg
        memory = self.encode(src_ids)
        hidden = self.decode(tgt_ids, memory)
        logits = self.out_proj(hidden)
        if labels is None:
            return logits
        labels_flat = array_reshape_op(labels,
                                       [cfg.batch_size * cfg.tgt_len])
        loss = softmaxcrossentropy_sparse_op(logits, labels_flat,
                                             ignored_index=cfg.pad_id)
        return reduce_mean_op(loss, [0]), logits


def transformer_mt(src_ids, tgt_ids, labels, config=None):
    """Functional wrapper matching train_hetu_transformer.py usage."""
    model = Transformer(config or TransformerConfig())
    return model(src_ids, tgt_ids, labels)
