"""Neural collaborative filtering (reference examples/rec/hetu_ncf.py).

NeuMF = GMF (elementwise product of user/item factors) + MLP tower over
concatenated factors, fused by a final linear layer.
"""

from __future__ import annotations

from .. import initializers as init
from ..graph import (
    embedding_lookup_op, slice_op, mul_op, concat_op, matmul_op, relu_op,
    sigmoid_op, binarycrossentropy_op, reduce_mean_op,
)


def neural_mf(user_input, item_input, y_, num_users, num_items,
              embed_dim=8, mlp_layers=(64, 32, 16, 8), lr=0.01,
              embedding_ctx=None):
    from .. import optimizer as optim

    layers = list(mlp_layers)
    user_emb = init.random_normal(
        (num_users, embed_dim + layers[0] // 2), stddev=0.01,
        name="user_embed", ctx=embedding_ctx)
    item_emb = init.random_normal(
        (num_items, embed_dim + layers[0] // 2), stddev=0.01,
        name="item_embed", ctx=embedding_ctx)

    user_latent = embedding_lookup_op(user_emb, user_input)
    item_latent = embedding_lookup_op(item_emb, item_input)

    mf_user = slice_op(user_latent, (0, 0), (-1, embed_dim))
    mlp_user = slice_op(user_latent, (0, embed_dim), (-1, -1))
    mf_item = slice_op(item_latent, (0, 0), (-1, embed_dim))
    mlp_item = slice_op(item_latent, (0, embed_dim), (-1, -1))

    mf_vector = mul_op(mf_user, mf_item)
    x = concat_op(mlp_user, mlp_item, axis=1)
    for i in range(1, len(layers)):
        W = init.random_normal((layers[i - 1], layers[i]), stddev=0.1,
                               name=f"W{i}")
        x = relu_op(matmul_op(x, W))

    W_out = init.random_normal((embed_dim + layers[-1], 1), stddev=0.1,
                               name=f"W{len(layers)}")
    y = sigmoid_op(matmul_op(concat_op(mf_vector, x, axis=1), W_out))
    loss = reduce_mean_op(binarycrossentropy_op(y, y_), [0])
    train_op = optim.SGDOptimizer(learning_rate=lr).minimize(loss)
    return loss, y, train_op
