"""Fast autoregressive decoding for the GPT family: KV-cached
incremental steps inside ONE jitted lax.scan.

``greedy_generate`` (gpt.py) re-runs the full fixed-S forward per token
— O(S^2) attention per token, O(S^3) per sequence — which is the
static-shape-simple demo path.  This module is the serving path: a
preallocated [L, B, S_max, H, Dh] KV cache updated at the current
position via dynamic_update_slice, attention masked to the filled
prefix, the WHOLE generation (prompt teacher-forcing + sampling) one
compiled scan.  O(S) attention per token; one compile per
(batch, S_max) shape.

Weights come from the executor's named parameters (the same contract
hf.py's importers target), so a trained-or-imported model decodes with
no re-tracing of the training graph:

    out = generate_fast(ex.var_values, cfg, prompts, num_tokens=50,
                        temperature=0.8, top_k=40, seed=0)

Sampling: greedy (temperature=0), temperature, and top-k; ``eos_id``
stops a sequence at EOS (pad after, per-step compute short-circuits
once the whole batch is done).

``_decode_step`` is the SHARED decode core: the offline scan above and
the continuous-batching serving engine (``hetu_tpu.serving``) both run
it — the offline path with one scalar position for the whole batch, the
server with a per-slot position vector (slots hold sequences of unequal
filled lengths).  ``serve_prefill_fn``/``serve_decode_fn`` below are the
server's two jitted entry points over the same arithmetic.
"""

from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp

from .. import envvars
from ..quant import kv_decode, kv_encode

NEG_INF = -1e30


# ----------------------- quantized-cache plumbing ----------------------- #
#
# An int8 KV cache (HETU_KV_QUANT) travels as a ``(int8 data, f32
# scales)`` 2-tuple wherever a plain cache array travels — jit treats it
# as a pytree, donation donates both leaves, and the engine reassigns it
# opaquely.  These helpers are the ONLY places the layout forks: writes
# encode through ``quant.kv_encode`` (one scale per position per head),
# reads either dequantize (reference/masked paths) or hand the raw
# payload + scales to the int8 decode kernels, which dequantize inside
# the online-softmax loop.


def _kv_q(cache):
    """True when ``cache`` is the quantized (data, scales) pair."""
    return isinstance(cache, (tuple, list))


def _kv_dtype(cache):
    return cache[0].dtype if _kv_q(cache) else cache.dtype


def _kv_shape(cache):
    """The payload shape (scales mirror it minus the head_dim axis)."""
    return cache[0].shape if _kv_q(cache) else cache.shape


def _kv_scatter(cache, idx, val):
    """``cache.at[idx].set(val)`` for either layout: ``val`` is the
    float K/V slab; a quantized cache encodes it and writes payload +
    scales through the SAME index (the scale planes drop only the
    trailing head_dim axis, so any index that selects ``[..., H, Dh]``
    slabs of the payload selects ``[..., H]`` slabs of the scales)."""
    if _kv_q(cache):
        data, sc = cache
        q, s = kv_encode(val)
        return (data.at[idx].set(q), sc.at[idx].set(s))
    return cache.at[idx].set(val.astype(cache.dtype))


def _kv_dus(cache, val, i, pos):
    """The offline scan's contiguous dynamic_update_slice write (one
    [B, H, Dh] slab at scalar position ``pos`` of layer ``i``), both
    layouts."""
    if _kv_q(cache):
        data, sc = cache
        q, s = kv_encode(val)
        return (jax.lax.dynamic_update_slice(
                    data, q[None, :, None], (i, 0, pos, 0, 0)),
                jax.lax.dynamic_update_slice(
                    sc, s[None, :, None], (i, 0, pos, 0)))
    return jax.lax.dynamic_update_slice(
        cache, val[None, :, None], (i, 0, pos, 0, 0))


def _kv_gather_row(cache, i, table_row, span, H, Dh):
    """One slot's logical [span, H, Dh] context gathered from a paged
    pool through its block table row (the chunk-prefill read path);
    quantized pools dequantize the gathered view."""
    if _kv_q(cache):
        data, sc = cache
        g = data[i][table_row].reshape(span, H, Dh)
        s = sc[i][table_row].reshape(span, H)
        return g.astype(jnp.float32) * s[..., None]
    return cache[i][table_row].reshape(span, H, Dh)


def _kv_slot_slice(cache, slot, sizes):
    """One slot's [L, 1, S_max, H, Dh] view of a contiguous cache (the
    reference prefill works on this slice), both layouts."""
    if _kv_q(cache):
        data, sc = cache
        return (jax.lax.dynamic_slice(data, (0, slot, 0, 0, 0), sizes),
                jax.lax.dynamic_slice(sc, (0, slot, 0, 0), sizes[:-1]))
    return jax.lax.dynamic_slice(cache, (0, slot, 0, 0, 0), sizes)


def _kv_slot_update(cache, sub, slot):
    """Write a slot view (from :func:`_kv_slot_slice`) back."""
    if _kv_q(cache):
        data, sc = cache
        return (jax.lax.dynamic_update_slice(data, sub[0],
                                             (0, slot, 0, 0, 0)),
                jax.lax.dynamic_update_slice(sc, sub[1],
                                             (0, slot, 0, 0)))
    return jax.lax.dynamic_update_slice(cache, sub, (0, slot, 0, 0, 0))


def _pow2(n, floor=1):
    """Smallest power of two >= max(n, floor) (kv_manager.round_up_pow2
    re-exports this shape policy; duplicated here to keep models ->
    serving import-free)."""
    n = max(int(n), int(floor))
    return 1 << (n - 1).bit_length()


def _resolve_fast(mode=None):
    """Serving fast-path selection, shared by ``generate_fast`` and the
    serving engine: an explicit argument wins; else ``$HETU_SERVE_FAST``
    ("1" forces the flash-prefill + ragged-decode kernels, "0" forces
    the masked/scan reference); else auto — fast on TPU, reference
    elsewhere.  Off-TPU the fast kernels run in interpret mode: correct
    (the parity suite pins it) but emulated, so the reference path
    stays the off-TPU default."""
    if mode is None:
        mode = envvars.get_str("HETU_SERVE_FAST")
    if isinstance(mode, bool):
        return mode
    s = str(mode).strip().lower()
    if s in ("1", "on", "true", "fast", "ragged", "flash"):
        return True
    if s in ("0", "off", "false", "masked", "scan", "slow"):
        return False
    return jax.default_backend() == "tpu"


def resolve_serve_ragged(mode=None):
    """Mixed-mode ragged dispatch selection (ISSUE 18), shared by the
    serving engine and its callers: an explicit argument wins; else
    ``$HETU_SERVE_RAGGED`` ("1" packs arrivals, chunk continuations,
    spec-verify, and decode streams into ONE ragged wave per step,
    "0" keeps the phase-split prefill-then-decode scheduler); else
    auto — mixed on TPU (where the one-dispatch wave erases the phase
    barrier), phase-split elsewhere (off-TPU the two schedulers cost
    the same and phase-split is the longer-soaked path)."""
    if mode is None:
        mode = envvars.get_str("HETU_SERVE_RAGGED")
    if isinstance(mode, bool):
        return mode
    s = str(mode).strip().lower()
    if s in ("1", "on", "true", "mixed", "ragged"):
        return True
    if s in ("0", "off", "false", "phase", "split", "phased"):
        return False
    return jax.default_backend() == "tpu"


def resolve_spec_k(spec=None):
    """Speculative-decoding depth shared by the engine and offline
    ``generate_fast``: an explicit ``spec`` wins (None falls back to
    ``$HETU_SPEC_K``); 0 = off.  The value is the MAXIMUM draft tokens
    per wave — the adaptive controller moves within [1, k]."""
    if spec is None:
        spec = envvars.get_int("HETU_SPEC_K")
    return max(int(spec or 0), 0)


def resolve_draft_layers(layers, total_layers):
    """Truncated-layer draft depth: explicit ``layers`` wins, then
    ``$HETU_SPEC_DRAFT_LAYERS``, then the auto policy max(1, L // 4).
    The draft IS the target's first ``layers`` blocks plus the shared
    final LN and tied embedding head — no separate weights, tokenizer,
    or loading path to maintain (early-exit style drafting)."""
    if not layers:
        layers = envvars.get_int("HETU_SPEC_DRAFT_LAYERS")
    if not layers or int(layers) <= 0:
        layers = max(1, int(total_layers) // 4)
    return min(int(layers), int(total_layers))


def _ln(x, scale, bias, eps=1e-5):
    # statistics in f32 regardless of the compute dtype: bf16 mean/var
    # over outlier channels (GPT-2 residual streams have them) loses
    # enough mantissa to flip close argmax decisions; the cast costs
    # nothing next to the matmuls
    x32 = x.astype(jnp.float32)
    m = x32.mean(axis=-1, keepdims=True)
    v = ((x32 - m) ** 2).mean(axis=-1, keepdims=True)
    y = (x32 - m) * jax.lax.rsqrt(v + eps) * scale.astype(jnp.float32) \
        + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def _gelu_tanh(x):
    # tanh approximation — the framework's gelu_op (reference kernel
    # parity; equals HF gelu_new)
    return 0.5 * x * (1.0 + jnp.tanh(
        0.7978845608028654 * (x + 0.044715 * x ** 3)))


# ------------------------- MoE plumbing ------------------------- #
#
# A MoE GPT rides the SAME six compiled cores: the cfg_tuple grows an
# optional sixth element — a hashable ``moe_decode.MoESpec`` — and the
# FFN sublayer (factored into ``_ffn_block`` below) swaps the dense
# wi/wo matmuls for top-k routed expert dispatch on the spec's MoE
# layers.  Every existing 5-tuple stays a dense GPT bit for bit; the
# spec is jit-static, so dense and MoE models compile separate programs
# through one code path.  A ``draft=True`` spec (the truncated-layer
# speculative draft) SKIPS ROUTING ENTIRELY — its MoE blocks are
# attention-only (zero FFN contribution), so drafting needs no
# dispatch, no capacity, and no expert reads; verification still owns
# every emitted token, so acceptance semantics are untouched.


def _moe_of(cfg_tuple):
    """The cfg_tuple's optional sixth element: a ``MoESpec`` routing
    descriptor, or None for a dense GPT (every pre-MoE tuple)."""
    return cfg_tuple[5] if len(cfg_tuple) > 5 else None


def _moe_active(cfg_tuple):
    """True when this core ROUTES (and therefore reports per-expert
    load/drop stats): a MoE spec that is not the routing-skipping
    draft."""
    moe = _moe_of(cfg_tuple)
    return moe is not None and not moe.draft


def _strip_moe(out, cfg_tuple):
    """Drop the trailing (load, drop, tokens) stats element the serve
    wrappers append under an active MoE cfg_tuple — the offline
    callers (``_generate_spec``) discard routing telemetry."""
    return out[:-1] if _moe_active(cfg_tuple) else out


def _moe_stats_out(stats, moe, tokens):
    """The serve wrappers' trailing return element: (load [E] int32,
    drop [E] int32, routed-token count scalar int32) summed over every
    MoE layer of the call."""
    z = jnp.zeros((moe.num_experts,), jnp.int32)
    return (jnp.asarray(stats.get("load", z), jnp.int32),
            jnp.asarray(stats.get("drop", z), jnp.int32),
            jnp.asarray(tokens, jnp.int32))


def _ffn_block(params, us, h, i, moe=None, valid=None, stats=None):
    """The FFN sublayer every core shares: LN2 then dense
    wi→gelu→wo normally; on a MoE block (``moe`` set and layer ``i``
    routed), the top-k expert dispatch of ``moe_decode.moe_ffn`` over
    ALL of the call's token positions flattened (capacity is per
    dispatch, matching training's per-batch capacity); a draft spec
    returns ``h`` untouched (attention-only block).  ``valid`` (bool,
    h's leading shape) masks pad/dead positions out of routing so they
    never compete for expert capacity; ``stats`` accumulates the
    per-expert load/drop counts."""
    if moe is not None and moe.is_moe_layer(i):
        if moe.draft:
            return h
        from .moe_decode import moe_ffn
        x = _ln(h, params[f"{us}_ln2_scale"], params[f"{us}_ln2_bias"])
        shp = x.shape
        xf = x.reshape(-1, shp[-1])
        vf = None if valid is None else jnp.broadcast_to(
            valid, shp[:-1]).reshape(-1)
        y = moe_ffn(params, us, xf, moe, valid=vf, stats=stats)
        return h + y.reshape(shp)
    x = _ln(h, params[f"{us}_ln2_scale"], params[f"{us}_ln2_bias"])
    f = _gelu_tanh(x @ params[f"{us}_ffn_wi_weight"]
                   + params[f"{us}_ffn_wi_bias"])
    f = f @ params[f"{us}_ffn_wo_weight"] + params[f"{us}_ffn_wo_bias"]
    return h + f


def _decode_step(params, cfg_tuple, cache_k, cache_v, pos, token,
                 attn="masked", block_tables=None, live_mask=None,
                 moe_stats=None, token_valid=None):
    """One incremental position: token [B] int32 at position ``pos``.
    Returns (logits [B, V], new cache_k, new cache_v).

    ``pos`` is a scalar (offline scan: the whole batch sits at one
    position) OR an int32 [B] vector (serving: every slot decodes at its
    own filled length).  Scalar positions keep the contiguous
    dynamic_update_slice write; vector positions scatter one row per
    slot and mask attention per slot.

    ``attn`` (static) picks the attention implementation: "masked"
    streams and masks (the reference), "ragged" runs the paged Pallas
    decode kernel so each slot fetches only its live KV blocks
    (kernels/decode_attention.py).

    ``block_tables`` (traced [B, T] int32, serving only) switches the
    CACHE LAYOUT to block-table paged: ``cache_k``/``cache_v`` are the
    shared ``[L, N_blocks, bs, H, Dh]`` pool, this position's k/v
    scatters into block ``block_tables[b, pos[b]//bs]`` at offset
    ``pos[b] % bs``, and attention reads each slot's blocks through its
    table ("masked" gathers + masks, "ragged" is the block-table
    kernel).  ``live_mask`` ([B] bool) redirects inert slots' ride-along
    writes to scratch block 0 and zeroes their attention span — a slot
    mid-chunked-prefill must not have its freshly written prompt KV
    clobbered by the frozen-position write the contiguous layout could
    shrug off.  Offline ``generate_fast`` and the serving engine share
    this one core; the layout is a parameter, not a fork.

    ``token_valid`` ([B] bool) excludes ride-along rows from MoE
    routing (falling back to ``live_mask`` when paged); ``moe_stats``
    (dict) accumulates per-expert load/drop across the MoE layers.
    Both are ignored by dense cfg_tuples."""
    name, L, H, Dh, S_max = cfg_tuple[:5]
    moe = _moe_of(cfg_tuple)
    if token_valid is None:
        token_valid = live_mask
    B = token.shape[0]
    hdim = H * Dh
    per_slot = jnp.ndim(pos) > 0
    paged = block_tables is not None
    h = params[f"{name}_wte_table"][token] + params[f"{name}_wpe"][pos]

    if attn == "ragged" or paged:
        from ..kernels.decode_attention import (
            paged_block_decode_attention, paged_decode_attention,
        )
        lens = ((pos + 1).astype(jnp.int32) if per_slot
                else jnp.full((B,), pos + 1, jnp.int32))
    if paged:
        bs_blk = _kv_shape(cache_k)[2]
        T = block_tables.shape[1]
        bidx = jnp.arange(B)
        wblk = block_tables[bidx, pos // bs_blk]
        woff = pos % bs_blk
        if live_mask is not None:
            lens = jnp.where(live_mask, lens, 0)
            wblk = jnp.where(live_mask, wblk, 0)
        # masked gather path: a fully-dead slot still needs one live
        # score to keep its (discarded) softmax row finite
        live = (jnp.arange(T * bs_blk)[None, None, :]
                < jnp.maximum(lens, 1)[:, None, None])
    elif per_slot:
        live = jnp.arange(S_max)[None, None, :] <= pos[:, None, None]
        bidx = jnp.arange(B)
    else:
        live = (jnp.arange(S_max) <= pos)[None, None, :]   # [1,1,S]
    for i in range(L):
        us = f"{name}_h{i}"
        x = _ln(h, params[f"{us}_ln1_scale"], params[f"{us}_ln1_bias"])
        q = x @ params[f"{us}_attn_q_weight"] + params[f"{us}_attn_q_bias"]
        k = x @ params[f"{us}_attn_k_weight"] + params[f"{us}_attn_k_bias"]
        v = x @ params[f"{us}_attn_v_weight"] + params[f"{us}_attn_v_bias"]
        q = q.reshape(B, H, Dh)
        k = k.reshape(B, H, Dh)
        v = v.reshape(B, H, Dh)
        # write this position's k/v into the cache (quantized caches
        # encode payload + per-(position, head) scales in one helper)
        if paged:
            cache_k = _kv_scatter(cache_k, (i, wblk, woff), k)
            cache_v = _kv_scatter(cache_v, (i, wblk, woff), v)
        elif per_slot:
            cache_k = _kv_scatter(cache_k, (i, bidx, pos), k)
            cache_v = _kv_scatter(cache_v, (i, bidx, pos), v)
        else:
            cache_k = _kv_dus(cache_k, k, i, pos)
            cache_v = _kv_dus(cache_v, v, i, pos)
        if _kv_q(cache_k):                 # layer views: payload+scales
            ks, ksc = cache_k[0][i], cache_k[1][i]
            vs, vsc = cache_v[0][i], cache_v[1][i]
        else:
            ks, vs = cache_k[i], cache_v[i]   # [B,S,H,Dh] | [N,bs,H,Dh]
            ksc = vsc = None
        if paged and attn == "ragged":
            o = paged_block_decode_attention(
                q, ks, vs, lens, block_tables, k_scale=ksc,
                v_scale=vsc).reshape(B, hdim)
        elif paged:
            kg = ks[block_tables].reshape(B, T * bs_blk, H, Dh)
            vg = vs[block_tables].reshape(B, T * bs_blk, H, Dh)
            if ksc is not None:
                # masked-gather reference: dequantize the gathered view
                kg = kg.astype(jnp.float32) * ksc[block_tables].reshape(
                    B, T * bs_blk, H)[..., None]
                vg = vg.astype(jnp.float32) * vsc[block_tables].reshape(
                    B, T * bs_blk, H)[..., None]
            s = jnp.einsum("bhd,bshd->bhs", q, kg) * (Dh ** -0.5)
            s = jnp.where(live, s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhs,bshd->bhd", p, vg).reshape(B, hdim)
        elif attn == "ragged":
            o = paged_decode_attention(
                q, ks, vs, lens, k_scale=ksc,
                v_scale=vsc).reshape(B, hdim)
        else:
            if ksc is not None:
                ks = kv_decode(ks, ksc)
                vs = kv_decode(vs, vsc)
            s = jnp.einsum("bhd,bshd->bhs", q, ks) * (Dh ** -0.5)
            s = jnp.where(live, s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhs,bshd->bhd", p, vs).reshape(B, hdim)
        o = o @ params[f"{us}_attn_proj_weight"] \
            + params[f"{us}_attn_proj_bias"]
        h = h + o
        h = _ffn_block(params, us, h, i, moe=moe, valid=token_valid,
                       stats=moe_stats)

    h = _ln(h, params[f"{name}_ln_f_scale"], params[f"{name}_ln_f_bias"])
    # logits in f32 regardless of compute dtype: sampling compares and
    # exponentiates them
    logits = (h @ params[f"{name}_wte_table"].T).astype(jnp.float32) \
        + params.get(f"{name}_head_bias", 0.0)
    return logits, cache_k, cache_v


def _prep_param(v, dtype=None):
    """``dtype`` on device, PRESERVING any existing placement: a
    tp_shard_params NamedSharding must survive into the scan (a
    np.asarray round-trip would gather the shards to host and re-place
    them replicated on one device, silently killing tensor-parallel
    decode).  ``dtype=None`` KEEPS the param's own dtype — bf16 params
    stay bf16, so the cache that "follows the weights" actually does
    (the old f32 default silently upcast bf16 weights AND doubled the
    cache); f64 numpy inputs still land as f32 via jax's default dtype
    canonicalization."""
    if isinstance(v, jax.Array):
        return v if dtype is None or v.dtype == dtype else v.astype(dtype)
    return jnp.asarray(np.asarray(v), dtype)


def _sample(logits, temperature, top_k, key):
    """``temperature`` is a TRACED scalar (0 = greedy, selected inside
    the program — no recompile per setting); ``top_k`` is static (XLA's
    top_k needs a static k; a handful of k settings is a handful of
    compiles)."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    t_safe = jnp.maximum(temperature, 1e-6)
    scaled = logits / t_safe
    if top_k:
        kth = jax.lax.top_k(scaled, int(top_k))[0][:, -1:]   # O(V)
        scaled = jnp.where(scaled < kth, NEG_INF, scaled)
    sampled = jax.random.categorical(key, scaled,
                                     axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


def _sample_slot(logits, temperature, top_k, key):
    """Per-slot sampling with temperature AND top_k TRACED (unlike the
    offline ``_sample``, whose static top_k would force one compile per
    distinct request setting — a serving batch mixes settings freely).
    The kth-largest threshold comes from a full sort: O(V log V), noise
    next to the decode matmuls at serving batch sizes; top_k=0 disables
    the mask."""
    greedy = jnp.argmax(logits).astype(jnp.int32)
    t_safe = jnp.maximum(temperature, 1e-6)
    scaled = logits / t_safe
    desc = -jnp.sort(-scaled)
    kth = desc[jnp.clip(top_k - 1, 0, logits.shape[-1] - 1)]
    masked = jnp.where((top_k > 0) & (scaled < kth), NEG_INF, scaled)
    sampled = jax.random.categorical(key, masked).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


@functools.partial(jax.jit,
                   static_argnames=("cfg_tuple", "top_k", "use_eos"))
def _generate_scan(params, cfg_tuple, prompt_padded, prompt_len,
                   temperature, top_k, rng, eos_id=0, pad_id=0,
                   use_eos=False):
    """The whole generation as one scan over ALL S_max-1 positions: at
    positions < prompt_len the next input token is the PROMPT's
    (teacher forcing); beyond it, the sampled one.  Scanning to the
    static S_max (rather than the request's length) keeps prompt length
    and num_tokens TRACED — one compile serves every request shape at
    this (batch, S_max); the host slices the requested span after.

    With ``use_eos`` (static: the default program is unchanged), a
    sequence that samples ``eos_id`` past its prompt emits the EOS and
    then pads with ``pad_id``; once EVERY row is done the per-step body
    is skipped via lax.cond — a runtime short-circuit inside the single
    compiled scan."""
    name, L, H, Dh, S_max = cfg_tuple[:5]
    B = prompt_padded.shape[0]
    # cache dtype follows the weights: bf16 decode halves the KV cache
    # and runs the matmuls on the fast MXU path
    cdtype = params[f"{name}_wte_table"].dtype
    cache_k = jnp.zeros((L, B, S_max, H, Dh), cdtype)
    cache_v = jnp.zeros((L, B, S_max, H, Dh), cdtype)

    def step(carry, t):
        def live_step(carry):
            cache_k, cache_v, token, rng, done = carry
            logits, cache_k, cache_v = _decode_step(
                params, cfg_tuple, cache_k, cache_v, t, token)
            rng, sub = jax.random.split(rng)
            sampled = _sample(logits, temperature, top_k, sub)
            # next input: prompt token while still inside the prompt;
            # pad once this row already emitted its EOS
            in_prompt = t + 1 < prompt_len
            nxt = jnp.where(
                in_prompt,
                prompt_padded[:, jnp.minimum(t + 1, S_max - 1)],
                jnp.where(done, jnp.int32(pad_id), sampled))
            if use_eos:
                done = done | (~in_prompt & (sampled == eos_id))
            return (cache_k, cache_v, nxt, rng, done), nxt

        if not use_eos:
            return live_step(carry)
        return jax.lax.cond(
            jnp.all(carry[4]),
            lambda c: (c, jnp.full((B,), pad_id, jnp.int32)),
            live_step, carry)

    first = prompt_padded[:, 0]
    done0 = jnp.zeros((B,), bool)
    _, toks = jax.lax.scan(
        step, (cache_k, cache_v, first, rng, done0), jnp.arange(S_max - 1))
    # toks[t] is the input token for position t+1
    return jnp.concatenate([first[:, None], toks.T], axis=1)


# --------------------------- flash prefill --------------------------- #


def _prefill_forward(params, cfg_tuple, tokens, kv_lens,
                     row_valid=None, moe_stats=None):
    """ONE full-prompt forward over a bucket-padded token block: every
    layer's K/V for all positions in one batched pass — the MXU sees
    [P, D] matmuls instead of P sequential launches of [1, D], and
    attention is the Pallas flash kernel (causal + kv_lens, so blocks
    wholly past a row's prompt length skip compute AND DMA).

    tokens: [N, P_b] int32 (positions >= kv_lens[n] are pad — their
    K/V are deterministic garbage the decode mask never admits before
    overwrite); kv_lens: [N] int32.  Returns (logits [N, V] f32 at each
    row's prompt_len-1, ks, vs [L, N, P_b, H, Dh]).

    ``row_valid`` ([N] bool) marks REAL rows: the engine pads a group
    to a pow2 N by replicating entry 0, and while those duplicate rows'
    cache writes are order-safe no-ops, a MoE cfg must keep them (and
    every pad position) out of expert routing — they would compete for
    capacity and skew the load counters.  ``moe_stats`` as in
    ``_decode_step``."""
    from ..kernels.flash_attention import flash_attention
    name, L, H, Dh, S_max = cfg_tuple[:5]
    moe = _moe_of(cfg_tuple)
    N, P_b = tokens.shape
    hdim = H * Dh
    kv_lens = kv_lens.astype(jnp.int32)
    tok_valid = jnp.arange(P_b)[None, :] < kv_lens[:, None]  # [N, P_b]
    if row_valid is not None:
        tok_valid = tok_valid & row_valid[:, None]
    h = params[f"{name}_wte_table"][tokens] \
        + params[f"{name}_wpe"][jnp.arange(P_b)][None]
    ks, vs = [], []
    for i in range(L):
        us = f"{name}_h{i}"
        x = _ln(h, params[f"{us}_ln1_scale"], params[f"{us}_ln1_bias"])
        q = (x @ params[f"{us}_attn_q_weight"]
             + params[f"{us}_attn_q_bias"]).reshape(N, P_b, H, Dh)
        k = (x @ params[f"{us}_attn_k_weight"]
             + params[f"{us}_attn_k_bias"]).reshape(N, P_b, H, Dh)
        v = (x @ params[f"{us}_attn_v_weight"]
             + params[f"{us}_attn_v_bias"]).reshape(N, P_b, H, Dh)
        o = flash_attention(q, k, v, causal=True, kv_lens=kv_lens)
        o = o.reshape(N, P_b, hdim) @ params[f"{us}_attn_proj_weight"] \
            + params[f"{us}_attn_proj_bias"]
        h = h + o
        h = _ffn_block(params, us, h, i, moe=moe, valid=tok_valid,
                       stats=moe_stats)
        ks.append(k)
        vs.append(v)
    h = _ln(h, params[f"{name}_ln_f_scale"], params[f"{name}_ln_f_bias"])
    last = h[jnp.arange(N), jnp.maximum(kv_lens - 1, 0)]     # [N, hdim]
    logits = (last @ params[f"{name}_wte_table"].T).astype(jnp.float32) \
        + params.get(f"{name}_head_bias", 0.0)
    return logits, jnp.stack(ks), jnp.stack(vs)


@functools.partial(jax.jit,
                   static_argnames=("cfg_tuple", "top_k", "use_eos"))
def _generate_flash(params, cfg_tuple, prompt_bucket, prompt_len,
                    temperature, top_k, rng, eos_id=0, pad_id=0,
                    use_eos=False):
    """``_generate_scan``'s fast-prefill twin: the prompt phase is ONE
    batched ``_prefill_forward`` pass (cache positions 0..P_b-1 filled
    via dynamic_update_slice, first token sampled from the logits at
    prompt_len-1), and the scan runs DECODE-ONLY steps — positions
    inside the prompt are skipped with lax.cond instead of
    teacher-forced one token at a time.  Compiles per (B, S_max, P_b)
    with P_b pow2-bucketed by the caller; greedy outputs match the
    teacher-forced scan (same per-position arithmetic, batched).

    Returns (first_gen [B] — the token at position prompt_len — and
    toks [B, S_max-1] where toks[:, t] is the token at position t+1,
    junk for t < prompt_len; the caller overlays the prompt)."""
    name, L, H, Dh, S_max = cfg_tuple[:5]
    B, P_b = prompt_bucket.shape
    cdtype = params[f"{name}_wte_table"].dtype
    logits, ks, vs = _prefill_forward(
        params, cfg_tuple, prompt_bucket,
        jnp.broadcast_to(prompt_len, (B,)))
    cache_k = jax.lax.dynamic_update_slice(
        jnp.zeros((L, B, S_max, H, Dh), cdtype), ks.astype(cdtype),
        (0, 0, 0, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(
        jnp.zeros((L, B, S_max, H, Dh), cdtype), vs.astype(cdtype),
        (0, 0, 0, 0, 0))
    rng, sub = jax.random.split(rng)
    first_gen = _sample(logits, temperature, top_k, sub)
    done0 = (first_gen == eos_id) if use_eos else jnp.zeros((B,), bool)

    def step(carry, t):
        def live_step(carry):
            cache_k, cache_v, token, rng, done = carry
            logits, cache_k, cache_v = _decode_step(
                params, cfg_tuple, cache_k, cache_v, t, token)
            rng, sub = jax.random.split(rng)
            sampled = _sample(logits, temperature, top_k, sub)
            nxt = jnp.where(done, jnp.int32(pad_id), sampled)
            if use_eos:
                done = done | (sampled == eos_id)
            return (cache_k, cache_v, nxt, rng, done), nxt

        skip = t < prompt_len
        if use_eos:
            skip = skip | jnp.all(carry[4])
        return jax.lax.cond(
            skip, lambda c: (c, jnp.full((B,), pad_id, jnp.int32)),
            live_step, carry)

    _, toks = jax.lax.scan(
        step, (cache_k, cache_v, first_gen, rng, done0),
        jnp.arange(S_max - 1))
    return first_gen, toks.T


# ------------------------- serving entry points ------------------------- #
#
# The continuous-batching server (hetu_tpu/serving/engine.py) drives the
# SAME ``_decode_step`` core through two jitted functions: a teacher-
# forced prefill of one new sequence into its cache slot, and one fused
# decode step over every slot with per-slot positions.  Host code owns
# the tiny scheduling state (positions, tokens, rng keys as numpy); the
# device owns only the big [L, B_slots, S_max, H, Dh] cache pair, which
# threads through each call.


def _serve_prefill(params, cfg_tuple, cache_k, cache_v, slot, prompt,
                   prompt_len, temperature, top_k, rng_key):
    """Teacher-forced prefill of ONE sequence into cache row ``slot``:
    scan the (bucket-padded) prompt writing each position's K/V, then
    sample the first generated token from the logits at prompt_len-1.
    Positions at or past prompt_len are skipped via lax.cond (the
    bucket's padded tail costs no compute); recompiles once per prompt-
    length BUCKET, not per length.  Returns (first_token, cache_k,
    cache_v, new_rng_key[, moe stats] — the trailing (load, drop,
    tokens) element appears only under an active MoE cfg_tuple)."""
    name, L, H, Dh, S_max = cfg_tuple[:5]
    moe_on = _moe_active(cfg_tuple)
    P_b = prompt.shape[0]
    V = params[f"{name}_wte_table"].shape[0]
    ck = _kv_slot_slice(cache_k, slot, (L, 1, S_max, H, Dh))
    cv = _kv_slot_slice(cache_v, slot, (L, 1, S_max, H, Dh))
    if moe_on:
        E = _moe_of(cfg_tuple).num_experts
        st0 = (jnp.zeros((E,), jnp.int32), jnp.zeros((E,), jnp.int32),
               jnp.int32(0))

    def step(carry, t):
        def live(carry):
            if moe_on:
                ck, cv, last, st = carry
                sd = {}
                logits, ck, cv = _decode_step(
                    params, cfg_tuple, ck, cv, t, prompt[t][None],
                    moe_stats=sd)
                st = (st[0] + sd["load"], st[1] + sd["drop"],
                      st[2] + 1)
                last = jnp.where(t == prompt_len - 1, logits[0], last)
                return ck, cv, last, st
            ck, cv, last = carry
            logits, ck, cv = _decode_step(
                params, cfg_tuple, ck, cv, t, prompt[t][None])
            last = jnp.where(t == prompt_len - 1, logits[0], last)
            return ck, cv, last
        return jax.lax.cond(t < prompt_len, live, lambda c: c, carry), None

    carry0 = (ck, cv, jnp.zeros((V,), jnp.float32))
    if moe_on:
        carry0 = carry0 + (st0,)
    carry, _ = jax.lax.scan(step, carry0, jnp.arange(P_b))
    ck, cv, last = carry[:3]
    cache_k = _kv_slot_update(cache_k, ck, slot)
    cache_v = _kv_slot_update(cache_v, cv, slot)
    rng_key, sub = jax.random.split(rng_key)
    first = _sample_slot(last, temperature, top_k, sub)
    out = (first, cache_k, cache_v, rng_key)
    if moe_on:
        out = out + (carry[3],)
    return out


def _serve_prefill_batch(params, cfg_tuple, cache_k, cache_v, slots,
                         prompts, prompt_lens, temperature, top_k,
                         rng_keys, row_valid=None):
    """Flash prefill of a BUCKETED GROUP of admissions in one dispatch:
    ``_prefill_forward`` computes every layer's K/V for all N prompts
    at once, the rows scatter into their cache slots, and each request
    samples its first token from its own rng stream.  slots [N] int32;
    prompts [N, P_b]; prompt_lens/temperature/top_k [N]; rng_keys
    [N, 2].  The engine pads a group to a pow2 N by REPLICATING entry 0
    (duplicate scatter indices write identical values, so the pad rows
    are order-safe no-ops).  ``row_valid`` [N] bool marks the REAL
    rows (MoE routing exclusion — see ``_prefill_forward``).  Returns
    (first_tokens [N], cache_k, cache_v, new_rng_keys[, moe stats])."""
    N, P_b = prompts.shape
    moe_on = _moe_active(cfg_tuple)
    sd = {} if moe_on else None
    logits, ks, vs = _prefill_forward(params, cfg_tuple, prompts,
                                      prompt_lens, row_valid=row_valid,
                                      moe_stats=sd)
    cache_k = _kv_scatter(cache_k,
                          (slice(None), slots, slice(0, P_b)), ks)
    cache_v = _kv_scatter(cache_v,
                          (slice(None), slots, slice(0, P_b)), vs)
    splits = jax.vmap(jax.random.split)(rng_keys)          # [N,2,2]
    new_keys, subs = splits[:, 0], splits[:, 1]
    first = jax.vmap(_sample_slot)(logits, temperature, top_k, subs)
    out = (first, cache_k, cache_v, new_keys)
    if moe_on:
        lens = jnp.clip(prompt_lens, 0, P_b)
        if row_valid is not None:
            lens = jnp.where(row_valid, lens, 0)
        out = out + (_moe_stats_out(sd, _moe_of(cfg_tuple),
                                    jnp.sum(lens)),)
    return out


def _serve_decode_step(params, cfg_tuple, cache_k, cache_v, pos, token,
                       temperature, top_k, rng_keys, attn="masked",
                       live=None):
    """One fused decode step over ALL slots: slot b consumes ``token[b]``
    at its own position ``pos[b]`` (per-slot attention masking inside
    ``_decode_step``) and samples its next token from its own rng
    stream — outputs depend only on each request's (prompt, seed,
    settings), never on slot assignment or batch company.  Free slots
    ride along harmlessly: their frozen-position writes land in rows the
    next prefill/decode overwrites before any mask admits them.
    ``attn`` (static): "masked" reference or the "ragged" paged decode
    kernel (per-slot filled lengths bound the KV blocks fetched).
    ``live`` [B] bool (MoE configs) keeps ride-along free slots out of
    expert routing; dense configs ignore it."""
    moe_on = _moe_active(cfg_tuple)
    sd = {} if moe_on else None
    logits, cache_k, cache_v = _decode_step(
        params, cfg_tuple, cache_k, cache_v, pos, token, attn=attn,
        moe_stats=sd, token_valid=live)
    splits = jax.vmap(jax.random.split)(rng_keys)          # [B,2,2]
    new_keys, subs = splits[:, 0], splits[:, 1]
    sampled = jax.vmap(_sample_slot)(logits, temperature, top_k, subs)
    out = (sampled, cache_k, cache_v, new_keys)
    if moe_on:
        n = (token.shape[0] if live is None
             else jnp.sum(live.astype(jnp.int32)))
        out = out + (_moe_stats_out(sd, _moe_of(cfg_tuple), n),)
    return out


def _serve_decode_paged(params, cfg_tuple, cache_k, cache_v, tables,
                        pos, live, token, temperature, top_k, rng_keys,
                        attn="masked"):
    """``_serve_decode_step`` over the block-table paged pool: same
    fused step, but the cache pair is the shared block pool, ``tables``
    [B, T] routes each slot's reads/writes, and ``live`` [B] bool marks
    the slots actually decoding this wave (admitted, prompt fully
    prefilled) — inert slots ride along with their writes pointed at
    scratch block 0 and their sampled token discarded by the host."""
    moe_on = _moe_active(cfg_tuple)
    sd = {} if moe_on else None
    logits, cache_k, cache_v = _decode_step(
        params, cfg_tuple, cache_k, cache_v, pos, token, attn=attn,
        block_tables=tables, live_mask=live, moe_stats=sd)
    splits = jax.vmap(jax.random.split)(rng_keys)          # [B,2,2]
    new_keys, subs = splits[:, 0], splits[:, 1]
    sampled = jax.vmap(_sample_slot)(logits, temperature, top_k, subs)
    out = (sampled, cache_k, cache_v, new_keys)
    if moe_on:
        out = out + (_moe_stats_out(
            sd, _moe_of(cfg_tuple), jnp.sum(live.astype(jnp.int32))),)
    return out


# ---------------------- speculative decoding ---------------------- #
#
# Draft-propose / batched-verify (ISSUE 10): a truncated-layer DRAFT —
# the target's first ``L_draft`` blocks plus the shared final LN and
# tied embedding head, i.e. the same param dict under a shorter
# cfg_tuple — proposes ``k`` greedy tokens per slot inside ONE scanned
# dispatch (``_spec_propose``), and the target scores all ``k+1``
# positions in ONE batched step (``_verify_step``: the teacher-forced
# forward over a per-slot ragged q-block, causal inside the block).
# Longest-prefix acceptance plus the bonus token keeps outputs
# TOKEN-IDENTICAL to the non-speculative path — greedy trivially, and
# sampled too, because every emitted token is the target's OWN
# sequential sample: position j consumes the j-th split of the
# request's rng stream (``_spec_sample`` returns the key after every
# split so the host can resume the stream at exactly the accepted
# count), and the logits at the first mismatch are conditioned on an
# all-accepted prefix, so the "bonus" sample is the true next token.


def _verify_step(params, cfg_tuple, cache_k, cache_v, pos, tokens,
                 q_len, attn="masked", block_tables=None,
                 moe_stats=None):
    """Multi-position verify: slot b consumes ``tokens[b, :q_len[b]]``
    at positions ``pos[b] .. pos[b]+q_len[b]-1`` in ONE batched step.
    Returns (logits [B, Q, V] f32, new cache_k, new cache_v) — row
    ``logits[b, j]`` is the next-token distribution after input j,
    exactly what ``j+1`` sequential ``_decode_step`` calls would yield
    (each query attends to the whole written prefix INCLUDING the
    q-block's own causal positions).

    Dead positions (``j >= q_len[b]``): contiguous caches write them
    at their natural ``pos+j`` slots — beyond the slot's live length,
    never admitted by a mask, overwritten before use — with the writes
    issued LAST-LIVE-WINS (descending j), so a dead tail clipped to
    ``S_max-1`` can never clobber a live boundary write; paged caches
    route them to scratch block 0 like every other inert write.
    ``attn``/``block_tables`` select the implementation and layout as
    in ``_decode_step``."""
    name, L, H, Dh, S_max = cfg_tuple[:5]
    moe = _moe_of(cfg_tuple)
    B, Q = tokens.shape
    hdim = H * Dh
    paged = block_tables is not None
    bidx = jnp.arange(B)
    posns = pos[:, None] + jnp.arange(Q)[None, :]          # [B, Q]
    valid = jnp.arange(Q)[None, :] < q_len[:, None]        # [B, Q]
    lens = (pos + q_len).astype(jnp.int32)   # filled after the writes
    wpe = params[f"{name}_wpe"]
    h = params[f"{name}_wte_table"][tokens] \
        + wpe[jnp.clip(posns, 0, wpe.shape[0] - 1)]        # [B, Q, hd]
    if attn == "ragged":
        from ..kernels.decode_attention import (
            paged_block_verify_attention, paged_verify_attention,
        )
    if paged:
        bs_blk = _kv_shape(cache_k)[2]
        T = block_tables.shape[1]
        posc = jnp.clip(posns, 0, S_max - 1)
        wblk = jnp.where(valid,
                         block_tables[bidx[:, None], posc // bs_blk], 0)
        woff = posc % bs_blk
        span = T * bs_blk
        ctx = jnp.arange(span)[None, None, :]
    else:
        ctx = jnp.arange(S_max)[None, None, :]
    live = ctx <= posns[:, :, None]                        # [B, Q, S]
    for i in range(L):
        us = f"{name}_h{i}"
        x = _ln(h, params[f"{us}_ln1_scale"], params[f"{us}_ln1_bias"])
        q = (x @ params[f"{us}_attn_q_weight"]
             + params[f"{us}_attn_q_bias"]).reshape(B, Q, H, Dh)
        k = (x @ params[f"{us}_attn_k_weight"]
             + params[f"{us}_attn_k_bias"]).reshape(B, Q, H, Dh)
        v = (x @ params[f"{us}_attn_v_weight"]
             + params[f"{us}_attn_v_bias"]).reshape(B, Q, H, Dh)
        if paged:
            cache_k = _kv_scatter(cache_k, (i, wblk, woff), k)
            cache_v = _kv_scatter(cache_v, (i, wblk, woff), v)
        else:
            # descending j so the (clipped) dead tail is written FIRST
            # and any live boundary write lands last and wins
            for jq in reversed(range(Q)):
                pw = jnp.minimum(posns[:, jq], S_max - 1)
                cache_k = _kv_scatter(cache_k, (i, bidx, pw), k[:, jq])
                cache_v = _kv_scatter(cache_v, (i, bidx, pw), v[:, jq])
        if _kv_q(cache_k):
            ks, ksc = cache_k[0][i], cache_k[1][i]
            vs, vsc = cache_v[0][i], cache_v[1][i]
        else:
            ks, vs = cache_k[i], cache_v[i]
            ksc = vsc = None
        if paged and attn == "ragged":
            o = paged_block_verify_attention(
                q, ks, vs, lens, q_len, block_tables, k_scale=ksc,
                v_scale=vsc).reshape(B, Q, hdim)
        elif paged:
            kg = ks[block_tables].reshape(B, span, H, Dh)
            vg = vs[block_tables].reshape(B, span, H, Dh)
            if ksc is not None:
                kg = kg.astype(jnp.float32) * ksc[block_tables].reshape(
                    B, span, H)[..., None]
                vg = vg.astype(jnp.float32) * vsc[block_tables].reshape(
                    B, span, H)[..., None]
            s = jnp.einsum("bqhd,bshd->bqhs", q, kg) * (Dh ** -0.5)
            s = jnp.where(live[:, :, None, :], s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bqhs,bshd->bqhd", p, vg).reshape(B, Q, hdim)
        elif attn == "ragged":
            o = paged_verify_attention(
                q, ks, vs, lens, q_len, k_scale=ksc,
                v_scale=vsc).reshape(B, Q, hdim)
        else:
            if ksc is not None:
                ks = kv_decode(ks, ksc)
                vs = kv_decode(vs, vsc)
            s = jnp.einsum("bqhd,bshd->bqhs", q, ks) * (Dh ** -0.5)
            s = jnp.where(live[:, :, None, :], s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bqhs,bshd->bqhd", p, vs).reshape(B, Q, hdim)
        o = o @ params[f"{us}_attn_proj_weight"] \
            + params[f"{us}_attn_proj_bias"]
        h = h + o
        h = _ffn_block(params, us, h, i, moe=moe, valid=valid,
                       stats=moe_stats)
    h = _ln(h, params[f"{name}_ln_f_scale"], params[f"{name}_ln_f_bias"])
    logits = (h @ params[f"{name}_wte_table"].T).astype(jnp.float32) \
        + params.get(f"{name}_head_bias", 0.0)
    return logits, cache_k, cache_v


def _spec_sample(logits, temperature, top_k, rng_keys, first_row=None,
                 q_len=None):
    """Sequential per-position sampling over a verify q-block: position
    j's token comes from the (j+1)-th split of each slot's rng stream —
    EXACTLY the splits j+1 non-speculative steps would consume — and
    ``keys_after[b, j]`` is the stream state after those splits, so the
    host resumes at the accepted count and the stream stays aligned
    with the non-speculative path token for token.

    ``first_row``/``q_len`` [B] generalize this to a MIXED wave: slot b
    splits its stream only at rows ``first_row[b] <= j < q_len[b]`` —
    0/1 for a decode slot and 0/k+1 for spec-verify (both sequential
    splits, as above), ``q_len-1``/``q_len`` for a prompt's FINAL
    chunk (one split, matching the phase-split prefill paths' single
    split per prompt), and ``q_len``/anything for a mid-prompt chunk
    (no split; the returned keys equal the input and the host carries
    the stream forward untouched).  Rows outside the window still
    return a (discarded) sample so the wave stays one fused dispatch.
    None (the default) keeps the pure-verify behavior: split at every
    row."""
    B, Q = logits.shape[:2]
    toks, after = [], []
    keys = rng_keys
    for j in range(Q):
        splits = jax.vmap(jax.random.split)(keys)          # [B,2,2]
        if first_row is None:
            keys = splits[:, 0]
        else:
            do = (j >= first_row) & (j < q_len)            # [B]
            keys = jnp.where(do[:, None], splits[:, 0], keys)
        toks.append(jax.vmap(_sample_slot)(logits[:, j], temperature,
                                           top_k, splits[:, 1]))
        after.append(keys)
    return jnp.stack(toks, 1), jnp.stack(after, 1)


def _serve_verify(params, cfg_tuple, cache_k, cache_v, pos, tokens,
                  q_len, temperature, top_k, rng_keys, attn="masked"):
    """One fused VERIFY wave over all slots (contiguous layout): write
    + score the q-block, then sample every position from each slot's
    own rng stream.  Returns (sampled [B, Q], cache_k, cache_v,
    keys_after [B, Q, 2][, moe stats])."""
    moe_on = _moe_active(cfg_tuple)
    sd = {} if moe_on else None
    logits, cache_k, cache_v = _verify_step(
        params, cfg_tuple, cache_k, cache_v, pos, tokens, q_len,
        attn=attn, moe_stats=sd)
    sampled, after = _spec_sample(logits, temperature, top_k, rng_keys)
    out = (sampled, cache_k, cache_v, after)
    if moe_on:
        out = out + (_moe_stats_out(
            sd, _moe_of(cfg_tuple),
            jnp.sum(jnp.clip(q_len, 0, tokens.shape[1]))),)
    return out


def _serve_verify_paged(params, cfg_tuple, cache_k, cache_v, tables,
                        pos, tokens, q_len, temperature, top_k,
                        rng_keys, attn="masked"):
    """``_serve_verify`` over the block-table paged pool (``q_len`` 0
    marks inert slots — mid-prefill or free — whose writes are routed
    to scratch and whose samples/keys the host discards)."""
    moe_on = _moe_active(cfg_tuple)
    sd = {} if moe_on else None
    logits, cache_k, cache_v = _verify_step(
        params, cfg_tuple, cache_k, cache_v, pos, tokens, q_len,
        attn=attn, block_tables=tables, moe_stats=sd)
    sampled, after = _spec_sample(logits, temperature, top_k, rng_keys)
    out = (sampled, cache_k, cache_v, after)
    if moe_on:
        out = out + (_moe_stats_out(
            sd, _moe_of(cfg_tuple),
            jnp.sum(jnp.clip(q_len, 0, tokens.shape[1]))),)
    return out


def _spec_propose(params, cfg_tuple, cache_k, cache_v, pos, token, k):
    """``k`` greedy draft steps inside ONE dispatch: a lax.scan over
    the (truncated-layer) draft's ``_decode_step``, each step feeding
    its own argmax forward — one jitted call per wave instead of k
    sequential dispatches, which is what makes drafting cheap enough
    to pay for itself even off-chip.  The draft always proposes
    greedily (no rng): acceptance, not sampling fidelity, is its job —
    the target's verify pass owns the actual sampling.  Returns
    (draft_tokens [B, k], cache_k, cache_v).

    The scan runs k+1 steps and DISCARDS the last proposal: step k
    exists to write the k-th draft token's OWN K/V into the draft
    cache, so that after a fully-accepted wave (all k drafts kept) the
    draft's history has no hole at position pos+k — without it the
    next wave's proposals attend over stale garbage there and the
    acceptance rate quietly collapses."""
    def step(carry, _):
        ck, cv, tok, p = carry
        logits, ck, cv = _decode_step(params, cfg_tuple, ck, cv, p, tok)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (ck, cv, nxt, p + 1), nxt

    (cache_k, cache_v, _, _), toks = jax.lax.scan(
        step, (cache_k, cache_v, token, pos.astype(jnp.int32)), None,
        length=k + 1)
    return jnp.swapaxes(toks, 0, 1)[:, :k], cache_k, cache_v


def _serve_prefill_chunk(params, cfg_tuple, cache_k, cache_v, table_row,
                         tokens, pos_off, n_tok, temperature, top_k,
                         rng_key, wblk, woff):
    """One CHUNK of a prompt into one slot's blocks: forward ``tokens``
    [C_b] (positions ``pos_off .. pos_off+n_tok-1``; the rest pad)
    attending to the slot's already-written context (gathered from the
    pool through ``table_row`` [T]) plus the chunk's own causal prefix,
    then scatter the chunk's K/V into blocks ``wblk``/``woff`` [C_b]
    (pad positions target scratch block 0).  This is both the chunked-
    prefill engine (long prompts fill block by block between decode
    waves) and the prefix-share tail pass (a prompt whose first
    ``pos_off`` positions came from shared blocks forwards only the
    remainder).  Returns (first_token, cache_k, cache_v, new_rng_key) —
    the sample is meaningful only on the final chunk, and the HOST
    applies new_rng_key only then, so the request's rng stream is
    split exactly once, same as the unchunked paths."""
    name, L, H, Dh, S_max = cfg_tuple[:5]
    moe = _moe_of(cfg_tuple)
    moe_on = _moe_active(cfg_tuple)
    sd = {} if moe_on else None
    C_b = tokens.shape[0]
    T = table_row.shape[0]
    bs_blk = _kv_shape(cache_k)[2]
    hdim = H * Dh
    wpe = params[f"{name}_wpe"]
    posns = pos_off + jnp.arange(C_b)
    h = params[f"{name}_wte_table"][tokens] \
        + wpe[jnp.clip(posns, 0, wpe.shape[0] - 1)]        # [C_b, hd]
    # context positions valid strictly below pos_off; chunk causal mask
    ctx_live = (jnp.arange(T * bs_blk)[None, :] < pos_off)
    ii = jnp.arange(C_b)
    self_live = (ii[None, :] <= ii[:, None]) & (ii[None, :] < n_tok)
    scale = Dh ** -0.5
    for i in range(L):
        us = f"{name}_h{i}"
        x = _ln(h, params[f"{us}_ln1_scale"], params[f"{us}_ln1_bias"])
        q = (x @ params[f"{us}_attn_q_weight"]
             + params[f"{us}_attn_q_bias"]).reshape(C_b, H, Dh)
        k = (x @ params[f"{us}_attn_k_weight"]
             + params[f"{us}_attn_k_bias"]).reshape(C_b, H, Dh)
        v = (x @ params[f"{us}_attn_v_weight"]
             + params[f"{us}_attn_v_bias"]).reshape(C_b, H, Dh)
        kc = _kv_gather_row(cache_k, i, table_row, T * bs_blk, H, Dh)
        vc = _kv_gather_row(cache_v, i, table_row, T * bs_blk, H, Dh)
        s1 = jnp.einsum("chd,shd->chs", q, kc) * scale
        s1 = jnp.where(ctx_live[:, None, :], s1, NEG_INF)
        s2 = jnp.einsum("chd,jhd->chj", q, k) * scale
        s2 = jnp.where(self_live[:, None, :], s2, NEG_INF)
        s = jnp.concatenate([s1, s2], axis=-1)
        p = jax.nn.softmax(s, axis=-1)
        o = (jnp.einsum("chs,shd->chd", p[..., :T * bs_blk], vc)
             + jnp.einsum("chj,jhd->chd", p[..., T * bs_blk:], v))
        o = o.reshape(C_b, hdim) @ params[f"{us}_attn_proj_weight"] \
            + params[f"{us}_attn_proj_bias"]
        h = h + o
        h = _ffn_block(params, us, h, i, moe=moe, valid=ii < n_tok,
                       stats=sd)
        cache_k = _kv_scatter(cache_k, (i, wblk, woff), k)
        cache_v = _kv_scatter(cache_v, (i, wblk, woff), v)
    hf = _ln(h, params[f"{name}_ln_f_scale"], params[f"{name}_ln_f_bias"])
    last = hf[jnp.maximum(n_tok - 1, 0)]
    logits = (last @ params[f"{name}_wte_table"].T).astype(jnp.float32) \
        + params.get(f"{name}_head_bias", 0.0)
    rng_key, sub = jax.random.split(rng_key)
    first = _sample_slot(logits, temperature, top_k, sub)
    out = (first, cache_k, cache_v, rng_key)
    if moe_on:
        out = out + (_moe_stats_out(sd, moe,
                                    jnp.clip(n_tok, 0, C_b)),)
    return out


def _serve_prefill_batch_paged(params, cfg_tuple, cache_k, cache_v,
                               prompts, prompt_lens, temperature, top_k,
                               rng_keys, wblk, woff, row_valid=None):
    """Flash prefill of an admission group scattered into BLOCKS: the
    same one-dispatch ``_prefill_forward`` as the contiguous fast path,
    but every (request, position)'s K/V lands in the pool block the
    host-built ``wblk``/``woff`` [N, P_b] maps name (pad positions and
    replicated pad rows target scratch block 0 / duplicate identical
    writes — order-safe).  ``row_valid`` [N] bool marks real rows (MoE
    routing exclusion).  Returns (first_tokens [N], cache_k, cache_v,
    new_rng_keys[, moe stats])."""
    moe_on = _moe_active(cfg_tuple)
    sd = {} if moe_on else None
    logits, ks, vs = _prefill_forward(params, cfg_tuple, prompts,
                                      prompt_lens, row_valid=row_valid,
                                      moe_stats=sd)
    cache_k = _kv_scatter(cache_k, (slice(None), wblk, woff), ks)
    cache_v = _kv_scatter(cache_v, (slice(None), wblk, woff), vs)
    splits = jax.vmap(jax.random.split)(rng_keys)          # [N,2,2]
    new_keys, subs = splits[:, 0], splits[:, 1]
    first = jax.vmap(_sample_slot)(logits, temperature, top_k, subs)
    out = (first, cache_k, cache_v, new_keys)
    if moe_on:
        lens = jnp.clip(prompt_lens, 0, prompts.shape[1])
        if row_valid is not None:
            lens = jnp.where(row_valid, lens, 0)
        out = out + (_moe_stats_out(sd, _moe_of(cfg_tuple),
                                    jnp.sum(lens)),)
    return out


# --- mixed-mode ragged dispatch (ISSUE 18) ------------------------- #
# ONE jitted core for the whole hot loop.  The phase-split engine runs
# up to three kernel families per scheduler iteration (flash prefill,
# decode, spec-verify) with a host barrier between the phases; the
# mixed step consumes a RAGGED WAVE DESCRIPTOR — per-slot q_len + a
# token block — in which a decode stream is a q-block of 1, a
# spec-verify wave k+1, and a prompt (or prompt chunk) its chunk
# width, all scored by one dispatch.  ``_verify_step`` was already
# this computation for the uniform-mode case; ``_mixed_step``
# generalizes its attention to per-slot SELF-FRESHNESS so every
# phase-split path's exact arithmetic survives the merge (see below),
# which is what keeps greedy outputs token-identical ragged-vs-phased
# across contiguous/paged/int8/spec/chunked configs.


def _mixed_step(params, cfg_tuple, cache_k, cache_v, pos, tokens,
                q_len, self_fresh, attn="masked", block_tables=None,
                has_fresh=False, moe_stats=None):
    """One MIXED wave: slot b consumes ``tokens[b, :q_len[b]]`` at
    positions ``pos[b] .. pos[b]+q_len[b]-1`` — whatever mode those
    tokens are (prompt chunk, draft+bonus verify block, single decode
    token).  Returns (logits [B, Q, V] f32, cache_k, cache_v); row
    ``logits[b, j]`` is the next-token distribution after input j.
    Dead positions and dead slots (``q_len`` 0) follow
    ``_verify_step``'s write/mask conventions exactly.

    The masked path's DEFAULT attention is ``_verify_step``'s full
    causal mask over the just-written cache, bit for bit — so decode,
    spec-verify, and contiguous-prefill slots produce exactly the
    phase-split engine's logits (write-then-read self arithmetic,
    including the int8 round-trip).  Paged PROMPT-CHUNK slots are the
    one mode whose phase-split comparator (``_serve_prefill_chunk``)
    keeps the chunk's own K/V FRESH; when a wave carries any
    (``has_fresh``, static — steady-state decode waves skip the extra
    compute entirely), the fresh-self two-part variant (context masked
    strictly below ``pos`` + causal scores over the in-flight q-block)
    is computed as well and selected for the slots ``self_fresh`` [B]
    marks.  The ragged path hands the whole wave to the mixed-mode
    kernel, which reads everything back from the pool (the fast path's
    existing round-trip semantics)."""
    name, L, H, Dh, S_max = cfg_tuple[:5]
    moe = _moe_of(cfg_tuple)
    B, Q = tokens.shape
    hdim = H * Dh
    paged = block_tables is not None
    bidx = jnp.arange(B)
    posns = pos[:, None] + jnp.arange(Q)[None, :]          # [B, Q]
    valid = jnp.arange(Q)[None, :] < q_len[:, None]        # [B, Q]
    lens = (pos + q_len).astype(jnp.int32)   # filled after the writes
    wpe = params[f"{name}_wpe"]
    h = params[f"{name}_wte_table"][tokens] \
        + wpe[jnp.clip(posns, 0, wpe.shape[0] - 1)]        # [B, Q, hd]
    if attn == "ragged":
        from ..kernels.ragged_attention import (
            ragged_attention, ragged_paged_attention,
        )
    if paged:
        bs_blk = _kv_shape(cache_k)[2]
        T = block_tables.shape[1]
        posc = jnp.clip(posns, 0, S_max - 1)
        wblk = jnp.where(valid,
                         block_tables[bidx[:, None], posc // bs_blk], 0)
        woff = posc % bs_blk
        span = T * bs_blk
    else:
        span = S_max
    ctx = jnp.arange(span)[None, None, :]
    live = ctx <= posns[:, :, None]                        # [B, Q, S]
    # fresh-self variant: context strictly below the write window plus
    # a causal mask over the in-flight q-block
    ctx_live = (jnp.arange(span)[None, :] < pos[:, None])  # [B, S]
    jj = jnp.arange(Q)
    self_live = (jj[None, None, :] <= jj[None, :, None]) \
        & valid[:, None, :]                                # [B, Q, Q]
    scale = Dh ** -0.5
    quant = _kv_q(cache_k)
    for i in range(L):
        us = f"{name}_h{i}"
        x = _ln(h, params[f"{us}_ln1_scale"], params[f"{us}_ln1_bias"])
        q = (x @ params[f"{us}_attn_q_weight"]
             + params[f"{us}_attn_q_bias"]).reshape(B, Q, H, Dh)
        k = (x @ params[f"{us}_attn_k_weight"]
             + params[f"{us}_attn_k_bias"]).reshape(B, Q, H, Dh)
        v = (x @ params[f"{us}_attn_v_weight"]
             + params[f"{us}_attn_v_bias"]).reshape(B, Q, H, Dh)
        if paged:
            cache_k = _kv_scatter(cache_k, (i, wblk, woff), k)
            cache_v = _kv_scatter(cache_v, (i, wblk, woff), v)
        else:
            # descending j: dead (clipped) tail first, live wins last
            for jq in reversed(range(Q)):
                pw = jnp.minimum(posns[:, jq], S_max - 1)
                cache_k = _kv_scatter(cache_k, (i, bidx, pw), k[:, jq])
                cache_v = _kv_scatter(cache_v, (i, bidx, pw), v[:, jq])
        if quant:
            ks, ksc = cache_k[0][i], cache_k[1][i]
            vs, vsc = cache_v[0][i], cache_v[1][i]
        else:
            ks, vs = cache_k[i], cache_v[i]
            ksc = vsc = None
        if paged and attn == "ragged":
            o = ragged_paged_attention(
                q, ks, vs, lens, q_len, block_tables, k_scale=ksc,
                v_scale=vsc).reshape(B, Q, hdim)
        elif attn == "ragged":
            o = ragged_attention(q, ks, vs, lens, q_len, k_scale=ksc,
                                 v_scale=vsc).reshape(B, Q, hdim)
        else:
            if paged:
                kg = ks[block_tables].reshape(B, span, H, Dh)
                vg = vs[block_tables].reshape(B, span, H, Dh)
                if ksc is not None:
                    kg = kg.astype(jnp.float32) * ksc[
                        block_tables].reshape(B, span, H)[..., None]
                    vg = vg.astype(jnp.float32) * vsc[
                        block_tables].reshape(B, span, H)[..., None]
            else:
                kg, vg = ks, vs
                if ksc is not None:
                    kg = kv_decode(kg, ksc)
                    vg = kv_decode(vg, vsc)
            # default: _verify_step's full mask over the written cache
            s_raw = jnp.einsum("bqhd,bshd->bqhs", q, kg) * scale
            sw = jnp.where(live[:, :, None, :], s_raw, NEG_INF)
            p = jax.nn.softmax(sw, axis=-1)
            o = jnp.einsum("bqhs,bshd->bqhd", p, vg)
            if has_fresh:
                # _serve_prefill_chunk's arithmetic for chunk slots:
                # read-back context + the chunk's own FRESH K/V
                s1 = jnp.where(ctx_live[:, None, None, :], s_raw,
                               NEG_INF)
                s2 = jnp.einsum("bqhd,bjhd->bqhj", q, k) * scale
                s2 = jnp.where(self_live[:, :, None, :], s2, NEG_INF)
                pf = jax.nn.softmax(
                    jnp.concatenate([s1, s2], axis=-1), axis=-1)
                o_fresh = jnp.einsum("bqhs,bshd->bqhd",
                                     pf[..., :span], vg) \
                    + jnp.einsum("bqhj,bjhd->bqhd", pf[..., span:], v)
                o = jnp.where(self_fresh[:, None, None, None],
                              o_fresh, o)
            o = o.reshape(B, Q, hdim)
        o = o @ params[f"{us}_attn_proj_weight"] \
            + params[f"{us}_attn_proj_bias"]
        h = h + o
        h = _ffn_block(params, us, h, i, moe=moe, valid=valid,
                       stats=moe_stats)
    h = _ln(h, params[f"{name}_ln_f_scale"], params[f"{name}_ln_f_bias"])
    logits = (h @ params[f"{name}_wte_table"].T).astype(jnp.float32) \
        + params.get(f"{name}_head_bias", 0.0)
    return logits, cache_k, cache_v


def _serve_mixed(params, cfg_tuple, cache_k, cache_v, pos, tokens,
                 q_len, first_row, self_fresh, temperature, top_k,
                 rng_keys, attn="masked"):
    """One fused MIXED wave over all slots (contiguous layout): write +
    score every slot's ragged q-block, then sample each slot's live
    sampling window from its own rng stream (``first_row`` per
    ``_spec_sample``).  Returns (sampled [B, Q], cache_k, cache_v,
    keys_after [B, Q, 2][, moe stats])."""
    moe_on = _moe_active(cfg_tuple)
    sd = {} if moe_on else None
    logits, cache_k, cache_v = _mixed_step(
        params, cfg_tuple, cache_k, cache_v, pos, tokens, q_len,
        self_fresh, attn=attn, moe_stats=sd)
    sampled, after = _spec_sample(logits, temperature, top_k, rng_keys,
                                  first_row, q_len)
    out = (sampled, cache_k, cache_v, after)
    if moe_on:
        out = out + (_moe_stats_out(
            sd, _moe_of(cfg_tuple),
            jnp.sum(jnp.clip(q_len, 0, tokens.shape[1]))),)
    return out


def _serve_mixed_paged(params, cfg_tuple, cache_k, cache_v, tables,
                       pos, tokens, q_len, first_row, self_fresh,
                       temperature, top_k, rng_keys, attn="masked",
                       has_fresh=False):
    """``_serve_mixed`` over the block-table paged pool (``q_len`` 0
    marks inert slots, whose writes route to scratch block 0 and whose
    samples/keys the host discards).  ``has_fresh`` (static) marks
    waves carrying prompt-chunk slots — see ``_mixed_step``."""
    moe_on = _moe_active(cfg_tuple)
    sd = {} if moe_on else None
    logits, cache_k, cache_v = _mixed_step(
        params, cfg_tuple, cache_k, cache_v, pos, tokens, q_len,
        self_fresh, attn=attn, block_tables=tables,
        has_fresh=has_fresh, moe_stats=sd)
    sampled, after = _spec_sample(logits, temperature, top_k, rng_keys,
                                  first_row, q_len)
    out = (sampled, cache_k, cache_v, after)
    if moe_on:
        out = out + (_moe_stats_out(
            sd, _moe_of(cfg_tuple),
            jnp.sum(jnp.clip(q_len, 0, tokens.shape[1]))),)
    return out


@functools.lru_cache(maxsize=None)
def serve_mixed_fn(donate=True, attn="masked"):
    """Jitted ``_serve_mixed`` — the contiguous mixed-mode wave (see
    ``serve_prefill_fn`` for the donation rationale).  Compiles per
    q-block bucket Q; the engine pow2-buckets the wave width, so the
    ladder is log-bounded."""
    kw = {"static_argnames": ("cfg_tuple", "attn")}
    if donate:
        kw["donate_argnums"] = (2, 3)
    fn = jax.jit(_serve_mixed, **kw)
    return functools.partial(fn, attn=attn)


@functools.lru_cache(maxsize=None)
def serve_mixed_paged_fn(donate=True, attn="masked"):
    """Jitted ``_serve_mixed_paged`` — the block-table mixed-mode wave,
    the production dispatch behind ``$HETU_SERVE_RAGGED``.  Compiles
    per (Q bucket, has_fresh): steady-state decode waves skip the
    chunk-slot variant's extra softmax entirely."""
    kw = {"static_argnames": ("cfg_tuple", "attn", "has_fresh")}
    if donate:
        kw["donate_argnums"] = (2, 3)
    fn = jax.jit(_serve_mixed_paged, **kw)
    return functools.partial(fn, attn=attn)


@functools.lru_cache(maxsize=None)
def serve_prefill_fn(donate=True):
    """Jitted ``_serve_prefill``; ``donate=True`` donates the cache pair
    so XLA updates it in place — without donation every call pays a
    full-cache copy (the scatter/update allocates a fresh buffer),
    which dwarfs the step's matmuls at serving cache sizes."""
    kw = {"static_argnames": ("cfg_tuple",)}
    if donate:
        kw["donate_argnums"] = (2, 3)
    return jax.jit(_serve_prefill, **kw)


@functools.lru_cache(maxsize=None)
def serve_prefill_batch_fn(donate=True):
    """Jitted ``_serve_prefill_batch`` — the fast path's admission
    dispatch (see ``serve_prefill_fn`` for the donation rationale).
    Compiles per (group bucket N, prompt bucket P_b) pair; both are
    pow2-bucketed by the engine, so the ladder bounds the cache."""
    kw = {"static_argnames": ("cfg_tuple",)}
    if donate:
        kw["donate_argnums"] = (2, 3)
    return jax.jit(_serve_prefill_batch, **kw)


@functools.lru_cache(maxsize=None)
def serve_decode_fn(donate=True, attn="masked"):
    """Jitted ``_serve_decode_step`` (see ``serve_prefill_fn``)."""
    kw = {"static_argnames": ("cfg_tuple", "attn")}
    if donate:
        kw["donate_argnums"] = (2, 3)
    fn = jax.jit(_serve_decode_step, **kw)
    return functools.partial(fn, attn=attn)


@functools.lru_cache(maxsize=None)
def serve_decode_paged_fn(donate=True, attn="masked"):
    """Jitted ``_serve_decode_paged`` — the block-table fused step (see
    ``serve_prefill_fn`` for the donation rationale; donating the POOL
    pair matters even more here, since it is the engine's entire KV
    memory)."""
    kw = {"static_argnames": ("cfg_tuple", "attn")}
    if donate:
        kw["donate_argnums"] = (2, 3)
    fn = jax.jit(_serve_decode_paged, **kw)
    return functools.partial(fn, attn=attn)


@functools.lru_cache(maxsize=None)
def serve_verify_fn(donate=True, attn="masked"):
    """Jitted ``_serve_verify`` — the speculative wave's batched
    verification step over the contiguous cache (see
    ``serve_prefill_fn`` for the donation rationale).  Compiles per
    q-block width Q = spec_k + 1; adaptive k varies per-slot ``q_len``
    INSIDE one compile, so the ladder is one entry per engine."""
    kw = {"static_argnames": ("cfg_tuple", "attn")}
    if donate:
        kw["donate_argnums"] = (2, 3)
    fn = jax.jit(_serve_verify, **kw)
    return functools.partial(fn, attn=attn)


@functools.lru_cache(maxsize=None)
def serve_verify_paged_fn(donate=True, attn="masked"):
    """Jitted ``_serve_verify_paged`` — the block-table verify wave."""
    kw = {"static_argnames": ("cfg_tuple", "attn")}
    if donate:
        kw["donate_argnums"] = (2, 3)
    fn = jax.jit(_serve_verify_paged, **kw)
    return functools.partial(fn, attn=attn)


@functools.lru_cache(maxsize=None)
def spec_propose_fn(donate=True):
    """Jitted ``_spec_propose`` (draft cache pair donated).  Compiles
    per draft length k — the adaptive controller moves k through a
    pow2 ladder, so at most log2(spec_k)+1 entries exist."""
    kw = {"static_argnames": ("cfg_tuple", "k")}
    if donate:
        kw["donate_argnums"] = (2, 3)
    return jax.jit(_spec_propose, **kw)


@functools.lru_cache(maxsize=None)
def serve_prefill_chunk_fn(donate=True):
    """Jitted ``_serve_prefill_chunk``; compiles per (chunk bucket,
    table width) — the engine pads chunks to one fixed pow2 bucket, so
    the ladder stays bounded."""
    kw = {"static_argnames": ("cfg_tuple",)}
    if donate:
        kw["donate_argnums"] = (2, 3)
    return jax.jit(_serve_prefill_chunk, **kw)


@functools.lru_cache(maxsize=None)
def serve_prefill_batch_paged_fn(donate=True):
    """Jitted ``_serve_prefill_batch_paged`` — the paged engine's
    batched-admission flash dispatch."""
    kw = {"static_argnames": ("cfg_tuple",)}
    if donate:
        kw["donate_argnums"] = (2, 3)
    return jax.jit(_serve_prefill_batch_paged, **kw)


def teacher_forced_logits(params, config, seq, kv_fake_quant=False,
                          name=None):
    """Per-position next-token logits [P, V] of ONE sequence under
    teacher forcing, optionally with every layer's K/V FAKE-QUANTIZED
    (``quant.kv_encode`` → ``kv_decode``) before attention.

    Storing KV as int8 and dequantizing inside the decode kernel is
    arithmetically identical to fake-quantizing K/V here, so this is
    the margin-gate ORACLE for ``HETU_KV_QUANT``: measure
    ``delta = max |logits_q - logits_exact|`` over a corpus, and every
    position whose exact top-2 logit margin exceeds ``2 * delta`` is
    GUARANTEED top-1-identical under int8 KV — the "tolerance-tested
    threshold" the quant_ab quality gate asserts.  Positions inside the
    threshold are genuine near-ties where either token is defensible.
    """
    c = config
    from .moe_decode import moe_spec_of
    moe = moe_spec_of(c)
    name = _infer_name(params, name)
    params = {k: _prep_param(v) for k, v in params.items()
              if k.startswith(name + "_")}
    L, H = c.num_hidden_layers, c.num_attention_heads
    Dh = c.hidden_size // H
    seq = jnp.asarray(seq, jnp.int32)
    P = seq.shape[0]
    hdim = H * Dh
    h = params[f"{name}_wte_table"][seq] \
        + params[f"{name}_wpe"][jnp.arange(P)]
    causal = jnp.tril(jnp.ones((P, P), bool))
    for i in range(L):
        us = f"{name}_h{i}"
        x = _ln(h, params[f"{us}_ln1_scale"], params[f"{us}_ln1_bias"])
        q = (x @ params[f"{us}_attn_q_weight"]
             + params[f"{us}_attn_q_bias"]).reshape(P, H, Dh)
        k = (x @ params[f"{us}_attn_k_weight"]
             + params[f"{us}_attn_k_bias"]).reshape(P, H, Dh)
        v = (x @ params[f"{us}_attn_v_weight"]
             + params[f"{us}_attn_v_bias"]).reshape(P, H, Dh)
        if kv_fake_quant:
            k = kv_decode(*kv_encode(k)).astype(k.dtype)
            v = kv_decode(*kv_encode(v)).astype(v.dtype)
        s = jnp.einsum("phd,shd->hps", q, k) * (Dh ** -0.5)
        s = jnp.where(causal[None], s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("hps,shd->phd", p, v).reshape(P, hdim)
        o = o @ params[f"{us}_attn_proj_weight"] \
            + params[f"{us}_attn_proj_bias"]
        h = h + o
        h = _ffn_block(params, us, h, i, moe=moe)
    h = _ln(h, params[f"{name}_ln_f_scale"], params[f"{name}_ln_f_bias"])
    logits = (h @ params[f"{name}_wte_table"].T).astype(jnp.float32) \
        + params.get(f"{name}_head_bias", 0.0)
    return logits


def _infer_name(params, name=None):
    """The model's parameter-name prefix; explicit ``name`` wins, else
    inferred when exactly one ``*_wte_table`` is present."""
    if name is not None:
        return name
    tables = [k[:-len("_wte_table")] for k in params
              if k.endswith("_wte_table")]
    if len(tables) != 1:
        raise ValueError(
            f"params hold {len(tables)} *_wte_table entries ({tables}); "
            f"pass name= to pick the model")
    return tables[0]


def tp_shard_params(params, mesh, config, axis="tp", name=None):
    """Place a GPT parameter dict for TENSOR-PARALLEL decoding: the
    Megatron column/row split by name (q/k/v and ffn_wi column-split
    over ``axis``, attn_proj and ffn_wo row-split, embeddings/LNs
    replicated).  ``generate_fast`` needs no other change — GSPMD
    propagates the shardings through the decode scan, splitting the
    per-head attention and FFN across the mesh (multi-chip serving).

    Requires num_attention_heads % mesh.shape[axis] == 0 so the column
    split lands on head boundaries."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    tp = mesh.shape[axis]
    if config.num_attention_heads % tp:
        raise ValueError(
            f"num_attention_heads={config.num_attention_heads} not "
            f"divisible by {axis}={tp}: the column split must land on "
            f"head boundaries")
    name = _infer_name(params, name)

    def spec_for(k):
        if any(t in k for t in ("_attn_q_weight", "_attn_k_weight",
                                "_attn_v_weight", "_ffn_wi_weight")):
            return P(None, axis)
        if any(t in k for t in ("_attn_proj_weight", "_ffn_wo_weight")):
            return P(axis, None)
        if any(t in k for t in ("_attn_q_bias", "_attn_k_bias",
                                "_attn_v_bias", "_ffn_wi_bias")):
            return P(axis)
        return P()

    return {k: jax.device_put(np.asarray(v),
                              NamedSharding(mesh, spec_for(k)))
            for k, v in params.items() if k.startswith(name + "_")}


def _generate_spec(params, cfg_tuple, draft_layers, prompts, num_tokens,
                   temperature, top_k, seed, eos_id, pad_id, spec_k):
    """Offline speculative generation: the serving building blocks
    (batched flash prefill, scanned draft propose, batched verify)
    driven by a host loop over the whole batch in lockstep — rows whose
    acceptance differs simply sit at different positions (the per-slot
    position vectors are the hard part, and they already exist).
    Greedy outputs are token-identical to the non-speculative
    ``generate_fast`` paths; sampling draws per-row rng streams
    (PRNGKey(seed + row)), so sampled outputs match the serving
    engine's per-request streams, not the offline batch-keyed scan.
    Finished rows ride along with q_len 0 (their state frozen)."""
    name, L, H, Dh, S_max = cfg_tuple[:5]
    moe = _moe_of(cfg_tuple)
    cfg_d = (name, draft_layers, H, Dh, S_max)
    if moe is not None:
        # the draft skips routing entirely: attention-only MoE blocks
        cfg_d = cfg_d + (moe._replace(draft=True),)
    B, P = prompts.shape
    cdtype = params[f"{name}_wte_table"].dtype
    Q = spec_k + 1
    ck = jnp.zeros((L, B, S_max, H, Dh), cdtype)
    cv = jnp.zeros((L, B, S_max, H, Dh), cdtype)
    dck = jnp.zeros((draft_layers, B, S_max, H, Dh), cdtype)
    dcv = jnp.zeros((draft_layers, B, S_max, H, Dh), cdtype)
    P_b = min(_pow2(P, floor=8), S_max)
    padb = np.zeros((B, P_b), np.int32)
    padb[:, :P] = prompts
    padb = jnp.asarray(padb)
    slots = np.arange(B, dtype=np.int32)
    lens = np.full(B, P, np.int32)
    temps = np.full(B, temperature, np.float32)
    topks = np.full(B, top_k, np.int32)
    keys = np.stack([np.asarray(jax.random.PRNGKey(seed + r), np.uint32)
                     for r in range(B)])
    prefill = serve_prefill_batch_fn(True)
    first, ck, cv, keys = _strip_moe(
        prefill(params, cfg_tuple, ck, cv, slots, padb, lens, temps,
                topks, keys), cfg_tuple)
    # draft cache prefill: same prompts, truncated depth; its sampled
    # tokens and key splits are discarded (the draft never samples;
    # a draft MoE spec appends no stats either)
    _, dck, dcv, _ = prefill(params, cfg_d, dck, dcv, slots, padb,
                             lens, temps, topks, np.array(keys))
    propose = spec_propose_fn(True)
    verify = serve_verify_fn(True)
    first = np.asarray(first, np.int32)
    keys = np.array(keys, np.uint32)
    pos = np.full(B, P, np.int32)
    tok = first.copy()
    total = P + int(num_tokens)
    out = np.full((B, total), pad_id, np.int32)
    out[:, :P] = prompts
    out[:, P] = first
    emitted = np.ones(B, np.int32)
    done = emitted >= num_tokens
    if eos_id is not None:
        done |= first == eos_id
    while not done.all():
        draft, dck, dcv = propose(params, cfg_d, dck, dcv, pos, tok,
                                  k=spec_k)
        draft = np.asarray(draft)
        tokens = np.zeros((B, Q), np.int32)
        tokens[:, 0] = tok
        tokens[:, 1:] = draft
        qlen = np.where(done, 0,
                        np.minimum(Q, num_tokens - emitted)).astype(
                            np.int32)
        tgt, ck, cv, after = _strip_moe(
            verify(params, cfg_tuple, ck, cv, pos, tokens, qlen,
                   temps, topks, keys), cfg_tuple)
        tgt = np.asarray(tgt)
        after = np.array(after, np.uint32)
        for b in range(B):
            if done[b]:
                continue
            ql = int(qlen[b])
            a = 0
            while a < ql - 1 and tgt[b, a] == tokens[b, a + 1]:
                a += 1
            emit = [int(t) for t in tgt[b, :a + 1]]
            if eos_id is not None and eos_id in emit:
                emit = emit[:emit.index(eos_id) + 1]
            n = len(emit)
            out[b, P + emitted[b]:P + emitted[b] + n] = emit
            emitted[b] += n
            pos[b] += n
            tok[b] = emit[-1]
            keys[b] = after[b, n - 1]
            if emitted[b] >= num_tokens or \
                    (eos_id is not None and emit[-1] == eos_id):
                done[b] = True
    return out


def generate_fast(params, config, prompts, num_tokens, temperature=0.0,
                  top_k=0, seed=0, name=None, dtype=None, eos_id=None,
                  pad_id=0, prefill=None, spec=None,
                  spec_draft_layers=None):
    """KV-cached generation.

    params: {name: array} (e.g. ``executor.var_values`` — pass it
      directly — or the output of ``hf.convert_gpt2``); config:
      GPTConfig (hidden size, layers, heads, max_position_embeddings);
      prompts: non-empty list of token-id lists (same length each, or a
      [B, P] array); name: the model's parameter-name prefix — inferred
      when the params hold exactly one ``*_wte_table``; dtype:
      ``jnp.bfloat16`` halves weights AND the KV cache and takes the
      fast MXU path (logits/sampling stay f32); default FOLLOWS the
      params' own dtype (bf16 weights → bf16 cache);
      eos_id: a row that samples this id past its prompt emits it, then
      ``pad_id`` for the rest of the requested span (and per-step
      compute short-circuits once every row is done) — both traced, so
      different EOS/pad ids share one compile; prefill: "flash" runs
      the prompt as ONE batched full-prompt pass (Pallas flash
      attention, pow2-bucketed prompt length), "scan" teacher-forces it
      token by token inside the scan (the reference), default consults
      ``$HETU_SERVE_FAST`` then auto-selects flash on TPU — greedy
      outputs are identical either way; spec: > 0 enables speculative
      decoding (default ``$HETU_SPEC_K``): a truncated-layer draft
      (``spec_draft_layers`` of this model's own blocks, default
      ``$HETU_SPEC_DRAFT_LAYERS`` / max(1, L // 4)) proposes ``spec``
      tokens per wave and the target verifies them in one batched
      step — greedy outputs stay token-identical to the
      non-speculative paths; sampled outputs draw per-ROW rng streams
      (seed + row), matching the serving engine rather than the
      batch-keyed offline scan.
      Returns [B, P + num_tokens] numpy int32.
    """
    prompts = np.asarray(prompts, np.int32)
    if prompts.ndim == 1:
        prompts = prompts[None]
    B, P = prompts.shape
    if P < 1:
        raise ValueError("prompt must hold at least one token")
    if num_tokens < 1:
        raise ValueError(f"num_tokens must be >= 1, got {num_tokens}")
    total = P + int(num_tokens)
    c = config
    name = _infer_name(params, name)
    S_max = c.max_position_embeddings
    if total > S_max:
        raise ValueError(f"prompt + num_tokens = {total} exceeds "
                         f"max_position_embeddings {S_max}")
    Dh = c.hidden_size // c.num_attention_heads
    cfg_tuple = (name, c.num_hidden_layers, c.num_attention_heads,
                 Dh, S_max)
    from .moe_decode import moe_spec_of
    mspec = moe_spec_of(c)
    if mspec is not None:
        # the hashable MoESpec rides the jit-static cfg_tuple as an
        # optional sixth element — dense 5-tuples compile unchanged
        cfg_tuple = cfg_tuple + (mspec,)
    # dtype=None FOLLOWS the params (bf16 weights decode bf16 with a
    # bf16 cache — the "follow the weights" contract; the old default
    # silently upcast everything to f32)
    params = {k: _prep_param(v, dtype)
              for k, v in params.items() if k.startswith(name + "_")}
    spec_k = resolve_spec_k(spec)
    if spec_k:
        dl = resolve_draft_layers(spec_draft_layers, c.num_hidden_layers)
        return _generate_spec(params, cfg_tuple, dl, prompts,
                              int(num_tokens), float(temperature),
                              int(top_k), int(seed), eos_id,
                              int(pad_id), spec_k)
    common = dict(eos_id=jnp.int32(-1 if eos_id is None else eos_id),
                  pad_id=jnp.int32(pad_id), use_eos=eos_id is not None)
    if _resolve_fast(prefill):
        P_b = min(_pow2(P, floor=8), S_max)
        padb = np.zeros((B, P_b), np.int32)
        padb[:, :P] = prompts
        first, toks = _generate_flash(
            params, cfg_tuple, jnp.asarray(padb), jnp.int32(P),
            jnp.float32(temperature), int(top_k),
            jax.random.PRNGKey(seed), **common)
        out = np.zeros((B, total), np.int32)
        out[:, :P] = prompts
        out[:, P] = np.asarray(first)
        if total > P + 1:
            out[:, P + 1:] = np.asarray(toks)[:, P:total - 1]
        return out
    pad = np.zeros((B, S_max), np.int32)
    pad[:, :P] = prompts
    out = _generate_scan(params, cfg_tuple, jnp.asarray(pad),
                         jnp.int32(P), jnp.float32(temperature),
                         int(top_k), jax.random.PRNGKey(seed), **common)
    return np.asarray(out[:, :total])
