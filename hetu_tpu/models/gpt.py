"""Decoder-only causal LM (GPT-2 topology) — pre-LN blocks, learned
positions, tied LM head.

The reference repo is BERT-centric (examples/nlp/bert/hetu_bert.py has
no decoder-only family); this model widens the zoo along the axis the
long-context example (examples/nlp/train_long_context.py) exercises
inline, with the framework's measured-fast pieces composed by default:

* fused QKV projection (layers.MultiHeadAttention fused_qkv),
* flash attention from seq >= 1024 (the measured v5e crossover; XLA's
  batched attention below it) unless the caller pins ``use_flash``,
* fused chunked tied LM head for the training loss
  (tied_lm_head_xent_op) with the logits node kept lazy.
"""

from __future__ import annotations

from .. import initializers as init
from .. import layers
from ..graph import (
    embedding_lookup_op, array_reshape_op,
    linear_op, gelu_op, dropout_op, tied_lm_head_xent_op,
)
from .bert import _masked_mean


class GPTConfig:
    def __init__(self, vocab_size=50257, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 ffn_mult=4, max_position_embeddings=1024,
                 dropout_rate=0.1, batch_size=8, seq_len=1024,
                 use_flash=None):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.ffn_size = ffn_mult * hidden_size
        self.max_position_embeddings = max_position_embeddings
        self.dropout_rate = dropout_rate
        self.batch_size = batch_size
        if seq_len > max_position_embeddings:
            raise ValueError(
                f"seq_len={seq_len} exceeds max_position_embeddings="
                f"{max_position_embeddings}: the learned position table "
                f"has no rows past that, and the slice would otherwise "
                f"surface as an opaque broadcast error when adding "
                f"positions")
        self.seq_len = seq_len
        # None = measured v5e crossover: flash from seq 1024 up — but
        # only with dropout off, because the fused kernel has no probs
        # dropout and MultiHeadAttention would silently fall back to the
        # unfused SxS chain (exactly what flash exists to avoid at long
        # seq).  Pinning use_flash=True with dropout on is an error, not
        # a silent fallback.
        if use_flash is None:
            self.use_flash = seq_len >= 1024 and dropout_rate == 0.0
        else:
            if use_flash and dropout_rate > 0.0:
                raise ValueError(
                    "use_flash=True requires dropout_rate=0: the flash "
                    "kernel has no attention-probs dropout and the "
                    "layer would silently fall back to unfused "
                    "attention")
            self.use_flash = use_flash

    @classmethod
    def small(cls, **kw):
        return cls(**kw)

    @classmethod
    def medium(cls, **kw):
        kw.setdefault("hidden_size", 1024)
        kw.setdefault("num_hidden_layers", 24)
        kw.setdefault("num_attention_heads", 16)
        return cls(**kw)


class GPTBlock:
    """Pre-LN: x + attn(ln1(x)); x + ffn(ln2(x))."""

    def __init__(self, config: GPTConfig, name="gpt_block"):
        c = config
        self.ln1 = layers.LayerNorm(c.hidden_size, name=name + "_ln1")
        self.ln2 = layers.LayerNorm(c.hidden_size, name=name + "_ln2")
        self.attn = layers.MultiHeadAttention(
            c.hidden_size, c.num_attention_heads, c.seq_len,
            c.batch_size, dropout_rate=c.dropout_rate,
            use_flash=c.use_flash, causal=True, name=name + "_attn")
        self.wi = layers.Linear(c.hidden_size, c.ffn_size,
                                name=name + "_ffn_wi")
        self.wo = layers.Linear(c.ffn_size, c.hidden_size,
                                name=name + "_ffn_wo")
        self.keep_prob = 1.0 - c.dropout_rate

    def __call__(self, h, kv_lens=None):
        a = self.attn(self.ln1(h), kv_lens=kv_lens)
        if self.keep_prob < 1.0:
            a = dropout_op(a, self.keep_prob)
        h = h + a
        f = self.wo(gelu_op(self.wi(self.ln2(h))))
        if self.keep_prob < 1.0:
            f = dropout_op(f, self.keep_prob)
        return h + f


class GPTModel:
    def __init__(self, config: GPTConfig, name="gpt"):
        c = config
        self.config = c
        self.wte = layers.Embedding(c.vocab_size, c.hidden_size,
                                    name=name + "_wte")
        self.wpe = init.random_normal(
            (c.max_position_embeddings, c.hidden_size), stddev=0.02,
            name=name + "_wpe")
        self.blocks = [GPTBlock(c, name=f"{name}_h{i}")
                       for i in range(c.num_hidden_layers)]
        self.ln_f = layers.LayerNorm(c.hidden_size, name=name + "_ln_f")
        self.keep_prob = 1.0 - c.dropout_rate

    def __call__(self, input_ids, kv_lens=None):
        """input_ids: (B, S) int -> hidden (B*S, H).

        Batch-POLYMORPHIC: positions add by natural broadcasting and
        reshapes use -1, so the same graph works at any local batch —
        e.g. inside a dp-sharded pipeline body where each microbatch
        sees batch_size/(pp*dp) rows."""
        c = self.config
        h = embedding_lookup_op(self.wte.embedding_table, input_ids)
        pos = self.wpe if c.max_position_embeddings == c.seq_len else \
            _slice_rows(self.wpe, c.seq_len)
        h = h + pos                      # [B,S,H] + [S,H] broadcasts
        h = array_reshape_op(h, [-1, c.hidden_size])
        if self.keep_prob < 1.0:
            h = dropout_op(h, self.keep_prob)
        for blk in self.blocks:
            h = blk(h, kv_lens=kv_lens)
        return self.ln_f(h)


def _slice_rows(node, n):
    from ..graph import slice_op
    return slice_op(node, [0, 0], [n, -1])


class GPTForCausalLM:
    """Next-token LM.  ``labels`` are the pre-shifted targets (callers
    shift by one position host-side, padding the tail with -1, which is
    ignored).  Head is TIED to wte; the training loss runs through the
    fused chunked head, logits stay lazy."""

    def __init__(self, config: GPTConfig, name="gpt"):
        c = config
        self.config = c
        self.transformer = GPTModel(config, name=name)
        self.head_bias = init.zeros((c.vocab_size,),
                                    name=name + "_head_bias")

    def __call__(self, input_ids, labels=None, kv_lens=None):
        h = self.transformer(input_ids, kv_lens=kv_lens)
        table = self.transformer.wte.embedding_table
        logits = linear_op(h, table, self.head_bias, trans_B=True)
        if labels is None:
            return logits
        labels_flat = array_reshape_op(labels, [-1])
        loss_vec = tied_lm_head_xent_op(h, table, self.head_bias,
                                        labels_flat, ignored_index=-1)
        # mean over NON-IGNORED positions only (bert.py _masked_mean):
        # -1-padded tails must not dilute the loss/gradient scale
        return _masked_mean(loss_vec, labels_flat), logits


def greedy_generate(executor, name, ids_node, logits_node_index, prompt,
                    num_tokens, seq_len, pad_id=0):
    """Greedy decoding with the static-shape graph: the same fixed-S
    forward is re-run per generated token and position t-1's logits are
    read out host-side — causal masking makes the padded tail beyond t
    irrelevant to that row.  O(S) forwards of O(S) tokens (no KV cache;
    the graph executor compiles ONE program and reuses it, which is the
    static-shape-friendly formulation).  ``executor`` runs subgraph
    ``name`` whose ``logits_node_index``-th output is the [B*S, V]
    logits of ``ids_node``."""
    import numpy as np

    prompt = list(prompt)
    if not 0 < len(prompt) < seq_len:
        raise ValueError(
            f"prompt length {len(prompt)} must be in (0, {seq_len})")
    if num_tokens < 1:
        raise ValueError(f"num_tokens must be >= 1, got {num_tokens}")
    if len(prompt) + num_tokens > seq_len:
        raise ValueError(
            f"prompt ({len(prompt)}) + num_tokens ({num_tokens}) exceeds "
            f"the graph's fixed seq_len ({seq_len}); generate in a "
            f"longer-seq graph or request fewer tokens")
    ids = np.full((1, seq_len), pad_id, np.int32)
    ids[0, :len(prompt)] = prompt
    end = len(prompt) + num_tokens
    for t in range(len(prompt), end):
        out = executor.run(name, feed_dict={ids_node: ids})
        logits = np.asarray(out[logits_node_index])
        ids[0, t] = int(logits.reshape(seq_len, -1)[t - 1].argmax())
    return ids[0, :end].tolist()
