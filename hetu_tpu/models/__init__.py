"""Model zoo: every model family the reference ships as examples.

Reference coverage (SURVEY.md §2.6 "Examples" row):
- CNN family   (examples/cnn/models/): MLP, LogReg, 3-layer CNN, LeNet,
  AlexNet, VGG-16/19, ResNet-18/34/50/101/152, RNN, LSTM
- NLP          (examples/nlp/): BERT (hetu_bert.py), MT Transformer
  (hetu_transformer.py)
- CTR          (examples/ctr/models/): WDL (adult/criteo), DCN, DeepFM, DC
- Rec          (examples/rec/hetu_ncf.py): NCF
- MoE          (examples/moe/): MoE MLP classifiers with the gate family

Each CNN-family builder keeps the reference's functional signature
``model(x, y_) -> (loss, y)`` so reference training scripts map 1:1;
BERT/Transformer are classes (the reference's BERT is class-based too).
"""

from .cnn import (
    mlp, logreg, cnn_3_layers, lenet, alexnet, vgg, vgg16, vgg19,
    resnet, resnet18, resnet34, resnet50, resnet101, resnet152,
    rnn, lstm, fc,
)
from .bert import (
    BertConfig, BertModel, BertForPreTraining,
    BertForSequenceClassification, BertForMaskedLM,
    BertForQuestionAnswering,
)
from .bert_moe import (
    BertMoEConfig, BertMoEModel, BertMoEForPreTraining,
)
from .transformer import TransformerConfig, Transformer, transformer_mt
from .gpt import GPTConfig, GPTModel, GPTForCausalLM
from .ctr import (
    wdl_adult, wdl_criteo, dcn_criteo, deepfm_criteo, dc_criteo,
)
from .ncf import neural_mf
from .moe_models import moe_mlp, moe_transformer_block
from .moe_decode import (
    MoEDecodeConfig, MoESpec, moe_spec_of, moe_capacity, moe_ffn,
    moe_ffn_ep_reference, ep_shard_params, init_moe_params,
    convert_dense_to_moe, resolve_moe_capacity, resolve_moe_quant,
)
