"""CTR models (reference examples/ctr/models/*.py): WDL, DCN, DeepFM, DC.

Signatures mirror the reference: ``model(dense_input, sparse_input, y_)``
returning ``(loss, prediction, y_, train_op)``.  ``feature_dimension`` and
``embedding_size`` are keyword-overridable (reference hard-codes Criteo's
33,762,577 rows) so the same builders run in tests and with the PS/cache
hybrid path (embeddings placed on host via ctx, Variable.py:57-63
semantics).
"""

from __future__ import annotations

from .. import initializers as init
from ..graph import (
    matmul_op, broadcastto_op, relu_op, sigmoid_op, embedding_lookup_op,
    array_reshape_op, concat_op, mul_op, reduce_sum_op, reduce_mean_op,
    softmaxcrossentropy_op, binarycrossentropy_op, mul_byconst_op,
)


def _sgd(lr):
    from .. import optimizer as optim
    return optim.SGDOptimizer(learning_rate=lr)


def wdl_adult(X_deep, X_wide, y_, lr=5 / 128):
    """Wide&Deep on the Adult census dataset (reference wdl_adult.py).

    X_deep: list of 12 sparse int columns (8 embedded + 4 passed through);
    X_wide: (N, 809) dense wide features; y_: (N, 2) one-hot.
    """
    dim_wide = 809

    W = init.random_normal([dim_wide + 20, 2], stddev=0.1, name="W")
    W1 = init.random_normal([68, 50], stddev=0.1, name="W1")
    b1 = init.random_normal([50], stddev=0.1, name="b1")
    W2 = init.random_normal([50, 20], stddev=0.1, name="W2")
    b2 = init.random_normal([20], stddev=0.1, name="b2")

    X_deep_input = None
    for i in range(8):
        emb = init.random_normal([50, 8], stddev=0.1,
                                 name=f"Embedding_deep_{i}")
        now = embedding_lookup_op(emb, X_deep[i])
        now = array_reshape_op(now, (-1, 8))
        X_deep_input = now if X_deep_input is None \
            else concat_op(X_deep_input, now, 1)
    for i in range(4):
        now = array_reshape_op(X_deep[i + 8], (-1, 1))
        X_deep_input = concat_op(X_deep_input, now, 1)

    mat1 = matmul_op(X_deep_input, W1)
    relu1 = relu_op(mat1 + broadcastto_op(b1, mat1))
    mat2 = matmul_op(relu1, W2)
    dmodel = relu_op(mat2 + broadcastto_op(b2, mat2))

    wmodel = matmul_op(concat_op(X_wide, dmodel, 1), W)

    prediction = wmodel
    loss = reduce_mean_op(softmaxcrossentropy_op(prediction, y_), [0])
    train_op = _sgd(lr).minimize(loss)
    return loss, prediction, y_, train_op


def wdl_criteo(dense_input, sparse_input, y_, feature_dimension=33762577,
               embedding_size=128, lr=0.01, embedding_ctx=None):
    """Wide&Deep on Criteo (reference wdl_criteo.py)."""
    Embedding = init.random_normal([feature_dimension, embedding_size],
                                   stddev=0.01, name="snd_order_embedding",
                                   ctx=embedding_ctx)
    sparse = embedding_lookup_op(Embedding, sparse_input)
    sparse = array_reshape_op(sparse, (-1, 26 * embedding_size))

    W1 = init.random_normal([13, 256], stddev=0.01, name="W1")
    W2 = init.random_normal([256, 256], stddev=0.01, name="W2")
    W3 = init.random_normal([256, 256], stddev=0.01, name="W3")
    W4 = init.random_normal([256 + 26 * embedding_size, 1], stddev=0.01,
                            name="W4")

    y3 = matmul_op(relu_op(matmul_op(relu_op(matmul_op(dense_input, W1)),
                                     W2)), W3)
    y = sigmoid_op(matmul_op(concat_op(sparse, y3, axis=1), W4))

    loss = reduce_mean_op(binarycrossentropy_op(y, y_), [0])
    train_op = _sgd(lr).minimize(loss)
    return loss, y, y_, train_op


def _cross_layer(x0, x1, embedding_len, name):
    """DCN cross layer: y = x0 * (x1 w) + b + x1 (reference dcn_criteo.py)."""
    weight = init.random_normal(shape=(embedding_len, 1), stddev=0.01,
                                name=name + "_weight")
    bias = init.random_normal(shape=(embedding_len,), stddev=0.01,
                              name=name + "_bias")
    x1w = matmul_op(x1, weight)
    y = mul_op(x0, broadcastto_op(x1w, x0))
    return y + x1 + broadcastto_op(bias, y)


def dcn_criteo(dense_input, sparse_input, y_, feature_dimension=33762577,
               embedding_size=128, lr=0.003, num_cross_layers=3,
               embedding_ctx=None):
    """Deep&Cross on Criteo (reference dcn_criteo.py)."""
    Embedding = init.random_normal([feature_dimension, embedding_size],
                                   stddev=0.01, name="snd_order_embedding",
                                   ctx=embedding_ctx)
    sparse = embedding_lookup_op(Embedding, sparse_input)
    sparse = array_reshape_op(sparse, (-1, 26 * embedding_size))
    x = concat_op(sparse, dense_input, axis=1)
    embedding_len = 26 * embedding_size + 13

    cross = x
    for i in range(num_cross_layers):
        cross = _cross_layer(x, cross, embedding_len, f"cross{i}")

    W1 = init.random_normal([embedding_len, 256], stddev=0.01, name="W1")
    W2 = init.random_normal([256, 256], stddev=0.01, name="W2")
    W3 = init.random_normal([256, 256], stddev=0.01, name="W3")
    W4 = init.random_normal([256 + embedding_len, 1], stddev=0.01,
                            name="W4")
    y3 = matmul_op(relu_op(matmul_op(relu_op(matmul_op(x, W1)), W2)), W3)
    y = sigmoid_op(matmul_op(concat_op(cross, y3, axis=1), W4))

    loss = reduce_mean_op(binarycrossentropy_op(y, y_), [0])
    train_op = _sgd(lr).minimize(loss)
    return loss, y, y_, train_op


def deepfm_criteo(dense_input, sparse_input, y_, feature_dimension=33762577,
                  embedding_size=128, lr=0.01, embedding_ctx=None):
    """DeepFM on Criteo (reference deepfm_criteo.py dfm_criteo)."""
    # first-order FM terms
    Embedding1 = init.random_normal([feature_dimension, 1], stddev=0.01,
                                    name="fst_order_embedding",
                                    ctx=embedding_ctx)
    FM_W = init.random_normal([13, 1], stddev=0.01, name="dense_parameter")
    sparse_1dim = embedding_lookup_op(Embedding1, sparse_input)
    y1 = matmul_op(dense_input, FM_W) + reduce_sum_op(sparse_1dim, axes=1)

    # second-order FM terms: 0.5 * ((sum e)^2 - sum e^2)
    Embedding2 = init.random_normal([feature_dimension, embedding_size],
                                    stddev=0.01,
                                    name="snd_order_embedding",
                                    ctx=embedding_ctx)
    e = embedding_lookup_op(Embedding2, sparse_input)
    e_sum = reduce_sum_op(e, axes=1)
    sum_sq = mul_op(e_sum, e_sum)
    sq_sum = reduce_sum_op(mul_op(e, e), axes=1)
    y2 = reduce_sum_op(mul_byconst_op(sum_sq + mul_byconst_op(sq_sum, -1.0),
                                      0.5), axes=1, keepdims=True)

    # DNN over flattened embeddings
    flatten = array_reshape_op(e, (-1, 26 * embedding_size))
    W1 = init.random_normal([26 * embedding_size, 256], stddev=0.01,
                            name="W1")
    W2 = init.random_normal([256, 256], stddev=0.01, name="W2")
    W3 = init.random_normal([256, 1], stddev=0.01, name="W3")
    y3 = matmul_op(relu_op(matmul_op(relu_op(matmul_op(flatten, W1)), W2)),
                   W3)

    y = sigmoid_op(y1 + y2 + y3)
    loss = reduce_mean_op(binarycrossentropy_op(y, y_), [0])
    train_op = _sgd(lr).minimize(loss)
    return loss, y, y_, train_op


def _residual_layer(x0, input_dim, hidden_dim, name):
    w1 = init.random_normal((input_dim, hidden_dim), stddev=0.1,
                            name=name + "_weight_1")
    b1 = init.random_normal((hidden_dim,), stddev=0.1, name=name + "_bias_1")
    w2 = init.random_normal((hidden_dim, input_dim), stddev=0.1,
                            name=name + "_weight_2")
    b2 = init.random_normal((input_dim,), stddev=0.1, name=name + "_bias_2")
    h = matmul_op(x0, w1)
    h = relu_op(h + broadcastto_op(b1, h))
    out = matmul_op(h, w2)
    out = out + broadcastto_op(b2, out)
    return relu_op(out + x0)


def dc_criteo(dense_input, sparse_input, y_, feature_dimension=33762577,
              embedding_size=8, lr=0.001, num_layers=5, embedding_ctx=None):
    """Deep Crossing on Criteo (reference dc_criteo.py)."""
    Embedding = init.random_normal([feature_dimension, embedding_size],
                                   stddev=0.01, name="snd_order_embedding",
                                   ctx=embedding_ctx)
    sparse = embedding_lookup_op(Embedding, sparse_input)
    sparse = array_reshape_op(sparse, (-1, 26 * embedding_size))
    x = concat_op(sparse, dense_input, axis=1)

    input_dim = 26 * embedding_size + 13
    for i in range(num_layers):
        x = _residual_layer(x, input_dim, input_dim, f"residual{i}")

    W = init.random_normal((input_dim, 1), stddev=0.1, name="dc_out_weight")
    y = sigmoid_op(matmul_op(x, W))
    loss = reduce_mean_op(binarycrossentropy_op(y, y_), [0])
    train_op = _sgd(lr).minimize(loss)
    return loss, y, y_, train_op
