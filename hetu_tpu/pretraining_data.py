"""Raw-corpus BERT/GPT pretraining data pipeline (reference
examples/nlp/bert/create_pretraining_data.py:146-476 + load_data.py).

Turns a raw text corpus — one sentence per line, blank lines between
documents — into fixed-shape pretraining arrays:

* ``create_bert_pretraining_data``: [CLS] A [SEP] B [SEP] instances with
  50% random-next NSP sampling, random front/back truncation, and
  80/10/10 masked-LM corruption (mask/keep/random), the reference's
  instance recipe.  Labels come out as a DENSE [N, S] grid with -1 at
  unmasked positions — the form the model's fused masked-mean loss
  consumes — instead of the reference's (positions, labels) pair lists,
  which exist to feed its gather-based loss.
* ``create_gpt_pretraining_data``: documents packed into a contiguous
  token stream and cut into [N, S] blocks with pre-shifted next-token
  labels (-1 on the final position), the decoder-family equivalent.
* ``build_wordpiece_vocab``: an offline vocab builder (whole words +
  suffix pieces + specials) so the pipeline is hermetic — the reference
  downloads a fixed vocab.txt from S3; with zero egress we build one
  from the corpus itself when none is checked in.

Everything is host-side numpy; batches feed placeholders or the
Dataloader ring unchanged.
"""

from __future__ import annotations

import collections

import numpy as np

IGNORE_INDEX = -1
SPECIALS = ("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]")


def read_documents(path, tokenizer):
    """Corpus file -> list of documents, each a list of token lists
    (reference create_training_instances:150-173: one sentence per
    line, blank line = document boundary, empty docs dropped)."""
    docs = [[]]
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                if docs[-1]:
                    docs.append([])
                continue
            toks = tokenizer.tokenize(line)
            if toks:
                docs[-1].append(toks)
    return [d for d in docs if d]


def build_wordpiece_vocab(corpus_path, out_path=None, max_words=8000):
    """Offline vocab: specials, then a character base vocab (plain and
    '##'-continued, so EVERY word decomposes into pieces instead of
    collapsing to [UNK]), then corpus words by frequency.  Hermetic
    replacement for the reference's downloaded vocab.txt (its
    tokenization.py assumes one exists); round-trips through
    BertTokenizer.from_pretrained.

    Default ``out_path`` is ``<corpus>.vocab.txt`` — a clearly derived
    name that never clobbers a curated vocab.txt sitting next to the
    corpus."""
    from .tokenizers.bert_tokenizer import BasicTokenizer
    basic = BasicTokenizer(do_lower_case=True)
    counts = collections.Counter()
    chars = set()
    with open(corpus_path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                toks = basic.tokenize(line)
                counts.update(toks)
                for t in toks:
                    chars.update(t)
    vocab = list(SPECIALS)
    vocab.extend(sorted(chars))
    vocab.extend("##" + c for c in sorted(chars))
    seen = set(vocab)
    for w, _n in counts.most_common(max_words):
        if w not in seen:
            vocab.append(w)
            seen.add(w)
    if out_path is None:
        out_path = corpus_path + ".vocab.txt"
    with open(out_path, "w", encoding="utf-8") as f:
        f.write("\n".join(vocab) + "\n")
    return out_path


def load_or_build_tokenizer(corpus_path, vocab_path=None):
    """The shared vocab-bootstrap: use ``vocab_path`` when given, else
    build (or reuse) the derived ``<corpus>.vocab.txt``."""
    from .tokenizers import BertTokenizer
    if not vocab_path:
        vocab_path = build_wordpiece_vocab(corpus_path)
    return BertTokenizer.from_pretrained(vocab_path)


def corpus_token_stream(corpus_path, tokenizer, eos_token="[SEP]"):
    """All documents as ONE flat np.int32 id stream with ``eos_token``
    between documents — the decoder-family packing input."""
    docs = read_documents(corpus_path, tokenizer)
    if not docs:
        raise ValueError(f"no documents in corpus {corpus_path}")
    eos = tokenizer.vocab.get(eos_token, 0)
    stream = []
    for doc in docs:
        for sent in doc:
            stream.extend(tokenizer.convert_tokens_to_ids(sent))
        stream.append(eos)
    return np.asarray(stream, np.int32)


def _mask_tokens(tokens, masked_lm_prob, max_predictions_per_seq,
                 vocab_words, rng):
    """80/10/10 masked-LM corruption over non-special positions
    (reference create_masked_lm_predictions:314-364).  Returns
    (corrupted tokens, {position: original token})."""
    cand = [i for i, t in enumerate(tokens) if t not in ("[CLS]", "[SEP]")]
    rng.shuffle(cand)
    n_pred = min(max_predictions_per_seq,
                 max(1, int(round(len(tokens) * masked_lm_prob))))
    out = list(tokens)
    labels = {}
    for i in cand[:n_pred]:
        r = rng.random()
        if r < 0.8:
            out[i] = "[MASK]"
        elif r < 0.9:
            pass                                   # keep original
        else:
            out[i] = vocab_words[rng.randint(0, len(vocab_words) - 1)]
        labels[i] = tokens[i]
    return out, labels


def _truncate_pair(tokens_a, tokens_b, max_num_tokens, rng):
    """Trim the longer side, randomly from front or back (reference
    truncate_seq_pair:367-383)."""
    while len(tokens_a) + len(tokens_b) > max_num_tokens:
        trunc = tokens_a if len(tokens_a) > len(tokens_b) else tokens_b
        if rng.random() < 0.5:
            del trunc[0]
        else:
            trunc.pop()


def _instances_from_document(docs, doc_index, max_seq_length,
                             short_seq_prob, masked_lm_prob,
                             max_predictions_per_seq, vocab_words, rng):
    """NSP instance construction for one document (reference
    create_instances_from_document:191-311): greedy sentence chunks to a
    target length, random A/B split, 50% random-next B drawn from
    another document (unused segments pushed back)."""
    document = docs[doc_index]
    max_num_tokens = max_seq_length - 3          # [CLS] a [SEP] b [SEP]
    target_len = max_num_tokens
    if rng.random() < short_seq_prob:
        target_len = rng.randint(2, max_num_tokens)

    instances = []
    chunk, chunk_len = [], 0
    i = 0
    while i < len(document):
        chunk.append(document[i])
        chunk_len += len(document[i])
        if i == len(document) - 1 or chunk_len >= target_len:
            if chunk:
                a_end = 1 if len(chunk) < 2 else rng.randint(
                    1, len(chunk) - 1)
                tokens_a = [t for seg in chunk[:a_end] for t in seg]
                tokens_b = []
                is_random_next = False
                if len(chunk) == 1 or (len(docs) > 1
                                       and rng.random() < 0.5):
                    # random-next: B from another document; put the
                    # unused tail of this chunk back
                    target_b = target_len - len(tokens_a)
                    rand_doc_idx = doc_index
                    for _ in range(10):
                        rand_doc_idx = rng.randint(0, len(docs) - 1)
                        if rand_doc_idx != doc_index:
                            break
                    if rand_doc_idx != doc_index:
                        is_random_next = True
                        rand_doc = docs[rand_doc_idx]
                        start = rng.randint(0, len(rand_doc) - 1)
                        for seg in rand_doc[start:]:
                            tokens_b.extend(seg)
                            if len(tokens_b) >= target_b:
                                break
                        i -= len(chunk) - a_end
                if not is_random_next:
                    tokens_b = [t for seg in chunk[a_end:] for t in seg]
                if tokens_a and tokens_b:
                    _truncate_pair(tokens_a, tokens_b, max_num_tokens, rng)
                    tokens = (["[CLS]"] + tokens_a + ["[SEP]"]
                              + tokens_b + ["[SEP]"])
                    seg_ids = ([0] * (len(tokens_a) + 2)
                               + [1] * (len(tokens_b) + 1))
                    tokens, labels = _mask_tokens(
                        tokens, masked_lm_prob, max_predictions_per_seq,
                        vocab_words, rng)
                    instances.append((tokens, seg_ids, labels,
                                      int(is_random_next)))
            chunk, chunk_len = [], 0
        i += 1
    return instances


def create_bert_pretraining_data(corpus_path, tokenizer, max_seq_length=128,
                                 dupe_factor=2, short_seq_prob=0.1,
                                 masked_lm_prob=0.15,
                                 max_predictions_per_seq=20, seed=12345):
    """Corpus file -> dict of fixed-shape arrays:

    input_ids / token_type_ids / attention_mask: [N, S] int32/float32
    masked_lm_labels: [N, S] int32, IGNORE_INDEX except masked positions
    next_sentence_label: [N] int32 (1 = random next)
    """
    rng = np.random.RandomState(seed)

    class _R:        # reference uses python random; keep one interface
        random = staticmethod(lambda: float(rng.rand()))
        randint = staticmethod(
            lambda a, b: int(rng.randint(a, b + 1)))    # inclusive hi
        shuffle = staticmethod(rng.shuffle)

    docs = read_documents(corpus_path, tokenizer)
    if not docs:
        raise ValueError(f"no documents in corpus {corpus_path}")
    vocab_words = list(tokenizer.vocab.keys())
    instances = []
    for _ in range(dupe_factor):
        order = list(range(len(docs)))
        rng.shuffle(order)
        for di in order:
            instances.extend(_instances_from_document(
                docs, di, max_seq_length, short_seq_prob, masked_lm_prob,
                max_predictions_per_seq, vocab_words, _R))
    rng.shuffle(instances)

    n, s = len(instances), max_seq_length
    pad_id = tokenizer.vocab.get("[PAD]", 0)
    ids = np.full((n, s), pad_id, np.int32)
    seg = np.zeros((n, s), np.int32)
    mask = np.zeros((n, s), np.float32)
    mlm = np.full((n, s), IGNORE_INDEX, np.int32)
    nsp = np.zeros((n,), np.int32)
    for j, (tokens, seg_ids, labels, is_rand) in enumerate(instances):
        tok_ids = tokenizer.convert_tokens_to_ids(tokens)
        L = len(tok_ids)
        ids[j, :L] = tok_ids
        seg[j, :L] = seg_ids
        mask[j, :L] = 1.0
        for pos, orig in labels.items():
            mlm[j, pos] = tokenizer.convert_tokens_to_ids([orig])[0]
        nsp[j] = is_rand
    return {"input_ids": ids, "token_type_ids": seg,
            "attention_mask": mask, "masked_lm_labels": mlm,
            "next_sentence_label": nsp}


def create_gpt_pretraining_data(corpus_path, tokenizer, seq_len=128,
                                eos_token="[SEP]"):
    """Decoder-family packing: all documents joined into one token
    stream (eos between docs), cut into [N, seq_len] blocks; labels are
    the stream shifted by one with IGNORE_INDEX at each block's last
    position (the next token lives in the following block)."""
    stream = corpus_token_stream(corpus_path, tokenizer,
                                 eos_token=eos_token)
    n = len(stream) // seq_len
    if n == 0:
        raise ValueError(
            f"corpus has {len(stream)} tokens < seq_len {seq_len}")
    arr = np.asarray(stream[:n * seq_len], np.int32).reshape(n, seq_len)
    labels = np.full((n, seq_len), IGNORE_INDEX, np.int32)
    labels[:, :-1] = arr[:, 1:]
    return {"input_ids": arr, "labels": labels}


class PretrainingBatches:
    """Shuffling epoch iterator over the instance arrays; yields dicts
    of [batch, ...] slices (drop-last).  Feed to placeholders or wrap in
    the Dataloader ring."""

    def __init__(self, data, batch_size, seed=0):
        self.data = data
        self.batch_size = batch_size
        self.n = next(iter(data.values())).shape[0]
        if self.n < batch_size:
            raise ValueError(
                f"{self.n} instances < batch_size {batch_size}; lower "
                f"the batch size or raise dupe_factor")
        self.rng = np.random.RandomState(seed)

    def __iter__(self):
        order = self.rng.permutation(self.n)
        for i in range(0, self.n - self.batch_size + 1, self.batch_size):
            sel = order[i:i + self.batch_size]
            yield {k: v[sel] for k, v in self.data.items()}
