"""ONE quantization layer for every byte-bound seam.

Network bytes cap training (PS push/pull, dp gradient aggregation) and
HBM bytes cap serving (KV capacity bounds concurrent slots); EQuARX
(PAPERS.md) shows int8 collectives inside XLA lose negligible quality.
This module is the jax_graft version of that idea, shared verbatim by
three consumers so their error characteristics are identical:

- **PS transport** (``ps/client.py`` / ``ps/server.py``): gradients are
  quantized host-side into a :class:`QuantArray` before ``wire.dumps``
  and dequantized server-side before the optimizer step (pull responses
  symmetrically) — ``HETU_PS_QUANT=int8``.
- **Collectives** (``graph/ops_comm.py``): a quantize→all_gather→
  dequantize comm-op pair over a mesh axis, statically verified by
  ``analysis/shard_check.py`` — ``HETU_COMM_QUANT=int8``.
- **Serving KV** (``serving/kv_manager.py`` + the decode kernels): an
  int8 KV pool with per-(position, head) scales, dequantized inside the
  online-softmax loop — ``HETU_KV_QUANT=int8``.

Scheme: SYMMETRIC per-chunk int8.  A chunk of values shares one f32
scale ``amax / 127``; encode is ``round(x / scale)`` clipped to
[-127, 127], decode ``q * scale``.  Per-element error is bounded by
``scale / 2 = amax / 254`` — ~0.4% of the chunk's largest magnitude —
which is the tolerance every parity gate in ``tests/test_quant.py``
tests against.  All-zero chunks encode with scale 1.0 so decode is
exactly zero.  The jax half is pure ``jnp`` (traces, shards, vmaps);
the numpy half never touches a device (PS servers must not grab one).

Everything here is OFF by default: with the three knobs unset, no call
site changes a single byte of behavior.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import envvars

# elements per scale on the PS wire (flat chunking of arbitrary shapes);
# small enough that one outlier only poisons 256 neighbors, big enough
# that scale overhead is ~1.5% of the int8 payload
DEFAULT_CHUNK = 256

_Q_MODES = ("int8",)


def resolve_quant(mode, env_name):
    """Shared knob grammar: an explicit ``mode`` wins ("int8" enables,
    None/""/"0"/"off" disables); else the registered env var decides.
    Returns "int8" or None."""
    if mode is None:
        mode = envvars.get_str(env_name)
    if mode is None:
        return None
    s = str(mode).strip().lower()
    if s in ("", "0", "off", "none", "false"):
        return None
    if s in _Q_MODES:
        return s
    raise ValueError(
        f"unknown quantization mode {mode!r} (via {env_name}); "
        f"supported: {_Q_MODES}")


def wire_chunk():
    """Chunk size for the flat wire codec (``HETU_QUANT_CHUNK``)."""
    return int(envvars.get_int("HETU_QUANT_CHUNK") or DEFAULT_CHUNK)


def ps_quant():
    return resolve_quant(None, "HETU_PS_QUANT")


def comm_quant():
    return resolve_quant(None, "HETU_COMM_QUANT")


def kv_quant():
    return resolve_quant(None, "HETU_KV_QUANT")


def active_modes():
    """Compact provenance string of the quantization knobs in effect —
    stamped on bench rows/headlines so quantized and unquantized
    measurements can never be compared silently ("off" when everything
    is default)."""
    on = [f"{k}={v}" for k, v in (("ps", ps_quant()),
                                  ("comm", comm_quant()),
                                  ("kv", kv_quant())) if v]
    return ",".join(on) if on else "off"


# --------------------------------------------------------------------- #
# numpy half: the PS wire codec (host-side, device-free)
# --------------------------------------------------------------------- #

def quantize_np(x, chunk=DEFAULT_CHUNK):
    """Flat per-chunk symmetric int8 encode of a float array: returns
    (q int8 [x.size], scales f32 [ceil(size/chunk)]).  The trailing
    partial chunk is padded with zeros for the scale reduction only —
    ``q`` keeps exactly ``x.size`` elements."""
    flat = np.ascontiguousarray(x, np.float32).reshape(-1)
    n = flat.size
    chunk = int(chunk)
    n_chunks = max(-(-n // chunk), 1)
    padded = np.zeros(n_chunks * chunk, np.float32)
    padded[:n] = flat
    amax = np.abs(padded.reshape(n_chunks, chunk)).max(axis=1)
    scales = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.rint(padded.reshape(n_chunks, chunk) / scales[:, None])
    q = np.clip(q, -127, 127).astype(np.int8).reshape(-1)[:n]
    return q, scales


def dequantize_np(q, scales, chunk=DEFAULT_CHUNK):
    """Inverse of :func:`quantize_np` (flat f32 [q.size])."""
    q = np.asarray(q, np.int8).reshape(-1)
    chunk = int(chunk)
    n_chunks = len(scales)
    padded = np.zeros(n_chunks * chunk, np.float32)
    padded[:q.size] = q.astype(np.float32)
    out = padded.reshape(n_chunks, chunk) * \
        np.asarray(scales, np.float32)[:, None]
    return out.reshape(-1)[:q.size]


class QuantArray:
    """A quantized ndarray in flight on the PS wire: the int8 payload,
    its per-chunk f32 scales, and the original shape/dtype.  The wire
    codec (``ps/wire.py`` tag ``Q``) carries this pair natively; the
    receiving side calls :meth:`decode` (servers before the optimizer
    step, clients after a quantized pull)."""

    __slots__ = ("q", "scales", "shape", "dtype", "chunk")

    def __init__(self, q, scales, shape, dtype="<f4", chunk=DEFAULT_CHUNK):
        self.q = q
        self.scales = scales
        self.shape = tuple(int(d) for d in shape)
        self.dtype = str(dtype)
        self.chunk = int(chunk)

    @classmethod
    def encode(cls, x, chunk=DEFAULT_CHUNK):
        x = np.asarray(x)
        q, scales = quantize_np(x, chunk)
        return cls(q, scales, x.shape, np.dtype(np.float32).str, chunk)

    def decode(self):
        out = dequantize_np(self.q, self.scales, self.chunk)
        return out.reshape(self.shape).astype(np.dtype(self.dtype))

    @property
    def nbytes(self):
        return self.q.nbytes + self.scales.nbytes

    def __repr__(self):
        return (f"QuantArray(shape={self.shape}, chunk={self.chunk}, "
                f"{self.nbytes}B)")


def maybe_decode(x):
    """``x.decode()`` when ``x`` is a :class:`QuantArray`, else ``x``
    unchanged — the one-line guard every PS server verb uses."""
    return x.decode() if isinstance(x, QuantArray) else x


# float payloads smaller than this many elements stay f32 on the wire:
# below it the scale/metadata overhead eats the win, and exactness of
# tiny control-plane arrays (row-shard metadata, 0-d scalars) is worth
# more than a handful of bytes
WIRE_MIN_SIZE = 1024


def should_quantize(x):
    """True when a value is worth quantizing for the wire: a floating
    ndarray with at least :data:`WIRE_MIN_SIZE` elements."""
    return (isinstance(x, np.ndarray)
            and np.issubdtype(x.dtype, np.floating)
            and x.size >= WIRE_MIN_SIZE)


def wire_savings(qarr):
    """Bytes a quantized payload saves vs its f32 original (>= 0) —
    feeds the ``ps.rpc.bytes_saved`` counter on both push and pull."""
    orig = int(np.prod(qarr.shape, dtype=np.int64)) * 4
    return max(orig - qarr.nbytes, 0)


# --------------------------------------------------------------------- #
# jax half: traced encode/decode (comm ops + KV cache)
# --------------------------------------------------------------------- #

def quantize_jax(x, chunk=DEFAULT_CHUNK):
    """Traced twin of :func:`quantize_np` over the LAST axis: chunks of
    ``chunk`` trailing elements share a scale.  Returns (q int8 with
    x's shape, scales f32 with shape ``x.shape[:-1] + (n_chunks,)``).
    Requires the last dim to divide by ``chunk`` (callers pick chunk =
    a divisor; the comm pair flattens + pads first)."""
    chunk = int(chunk)
    *lead, last = x.shape
    if last % chunk:
        raise ValueError(
            f"last dim {last} not divisible by quant chunk {chunk}")
    g = x.astype(jnp.float32).reshape(*lead, last // chunk, chunk)
    amax = jnp.max(jnp.abs(g), axis=-1)
    scales = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(g / scales[..., None]), -127, 127)
    return (q.astype(jnp.int8).reshape(x.shape), scales)


def dequantize_jax(q, scales, chunk=DEFAULT_CHUNK):
    """Inverse of :func:`quantize_jax` (f32, q's shape)."""
    chunk = int(chunk)
    *lead, last = q.shape
    g = q.astype(jnp.float32).reshape(*lead, last // chunk, chunk)
    return (g * scales[..., None]).reshape(q.shape)


def kv_encode(x):
    """KV-cache encode: one scale per (..., head) over the head_dim
    values of ``x`` [..., H, Dh] — fine-grained enough that greedy
    decode stays top-1-identical on the parity gates.  Returns
    (q int8 [..., H, Dh], scales f32 [..., H])."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scales = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scales[..., None]),
                 -127, 127)
    return q.astype(jnp.int8), scales.astype(jnp.float32)


def kv_decode(q, scales):
    """Inverse of :func:`kv_encode` (f32)."""
    return q.astype(jnp.float32) * scales[..., None]
