"""Dataloader: prefetching batch feeder with DP sharding.

Reference: python/hetu/dataloader.py (Dataloader ring of pinned CPU arrays
:30-100, DP sharding set_dp_rank :102, model-parallel slicing :110-141,
DataloaderOp multiplexing named loaders :186).

TPU-native: batches are assembled host-side as numpy and handed to the
jitted step via sharded ``jax.device_put``.  ``start_prefetch`` (wired
automatically by the executor when ``config.prefetch`` is on) runs the
host-side work — fancy-index slicing, dtype coercion, and the sharded
device_put itself — on a background thread feeding a bounded ring
(default depth 3, the reference's queue_size), so the training loop pops
device-resident batches without paying the host work on the critical
path.  This is the TPU equivalent of the reference's pinned-ring +
worker design (dataloader.py:30-100).
"""

from __future__ import annotations

import collections
import threading
import time

import numpy as np

from . import locks
from .graph.node import Op
from .context import cpu


class _PrefetchRing:
    """Bounded single-producer background prefetch."""

    def __init__(self, producer, depth=3, transform=None):
        self.producer = producer
        self.transform = transform
        self.depth = depth
        self.buf = collections.deque()
        self.cv = locks.TracedCondition(name="dataloader.ring")
        self.stopped = False
        self.error = None
        self.thread = threading.Thread(target=self._work, daemon=True)
        self.thread.start()

    def _work(self):
        while True:
            with self.cv:
                while len(self.buf) >= self.depth and not self.stopped:
                    self.cv.wait()
                if self.stopped:
                    return
            try:
                item = self.producer()
                if self.transform is not None:
                    item = self.transform(item)
            except BaseException as e:     # surfaced on the next get()
                with self.cv:
                    self.error = e
                    self.cv.notify_all()
                return
            with self.cv:
                self.buf.append(item)
                self.cv.notify_all()

    def _wait_nonempty(self):
        with self.cv:
            while not self.buf and self.error is None and not self.stopped:
                self.cv.wait()
            if not self.buf and self.error is not None:
                raise self.error

    def get(self):
        from . import telemetry
        if telemetry.enabled():
            # ring health: how long the trainer blocked on the producer
            # (wait > 0 means the host pipeline, not the chip, paces the
            # step) and how full the lookahead ran after the pop
            t0 = time.perf_counter()
            self._wait_nonempty()
            telemetry.observe("dataloader.wait_ms",
                              (time.perf_counter() - t0) * 1e3)
            with self.cv:
                item = self.buf.popleft()
                telemetry.set_gauge("dataloader.ring_depth",
                                    len(self.buf))
                self.cv.notify_all()
            return item
        self._wait_nonempty()
        with self.cv:
            item = self.buf.popleft()
            self.cv.notify_all()
        return item

    def peek(self):
        self._wait_nonempty()
        with self.cv:
            return self.buf[0]

    def stop(self):
        with self.cv:
            self.stopped = True
            self.cv.notify_all()
        # join: an in-flight producer() mutates the loader's position
        # state; callers (load_state_dict) reset that state right after
        # stop() and must not race the worker's last write
        if self.thread is not threading.current_thread():
            self.thread.join()


class Dataloader:
    def __init__(self, raw_data, batch_size, name="default", func=None,
                 drop_last=True, shuffle=False, seed=0):
        self.func = func if func else (lambda x: x)
        self.raw_data = np.asarray(self.func(raw_data))
        if self.raw_data.dtype == np.float64:
            self.raw_data = self.raw_data.astype(np.float32)
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        self.shuffle = shuffle
        # epoch-seeded permutation: paired loaders (features/labels) with
        # the same length and seed shuffle IDENTICALLY every epoch, keeping
        # (x, y) aligned — the reference pairs loaders implicitly by never
        # reshuffling (dataloader.py seq = arange)
        self.seed = seed
        self._epoch = 0
        self.name = str(name)
        self.dp_rank = None
        self.dp_nrank = None
        self._shard = None
        self.parts = None
        self._initialized = False
        self._ring = None
        self._consumed = 0      # batches handed to the trainer (ring
                                # lookahead excluded) — checkpoint state

    # ---- DP / MP hooks (reference dataloader.py:102-141) ---- #

    def set_dp_rank(self, dp_rank, dp_nrank):
        self.dp_rank = dp_rank
        self.dp_nrank = dp_nrank

    def set_mp_parts(self, cur_part, parts):
        self.cur_part = cur_part
        self.parts = parts

    def set_batch_shard(self, lo, hi):
        """Multi-host: keep only rows [lo, hi) of every (full) batch —
        the rows this process's addressable devices hold under the feed
        sharding.  Epoch/shuffle bookkeeping stays GLOBAL (identical on
        every process), so the union of all processes' shards is exactly
        the single-process batch and trajectories match; each process
        slices, coerces, and device_puts only 1/P of the bytes
        (reference per-worker dp-sharded loaders, dataloader.py:22-28)."""
        self._shard = (int(lo), int(hi))

    # -------------------------------------------------------- #

    def init_states(self):
        if self._initialized:
            return
        data = self.raw_data
        if self.dp_nrank is not None:
            cur = data.shape[0] // self.dp_nrank
            data = data[cur * self.dp_rank: cur * (self.dp_rank + 1)]
        self.data = data
        self.samples_num = len(data)
        assert self.batch_size <= self.samples_num, (
            f"batch size {self.batch_size} > dataset size {self.samples_num}")
        if self.drop_last:
            self.batch_num = self.samples_num // self.batch_size
        else:
            self.batch_num = int(np.ceil(self.samples_num / self.batch_size))
        self.shape = (self.batch_size,) + self.data.shape[1:]
        self.seq = np.arange(self.samples_num)
        self.index = 0
        self.batch_id = 0
        self._initialized = True

    def _reshuffle(self):
        self._epoch += 1
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self._epoch)
            self.seq = rng.permutation(self.samples_num)

    def start_prefetch(self, depth=3, transform=None):
        """Run batch assembly (and ``transform``, e.g. a sharded
        device_put) on a background thread feeding a bounded ring."""
        if self._ring is not None:
            return
        assert getattr(self, "_peeked", None) is None, (
            "start_prefetch before the first peek/get")
        self.init_states()
        self._ring = _PrefetchRing(self._next_batch, depth, transform)

    def stop_prefetch(self):
        if self._ring is not None:
            self._ring.stop()
            self._ring = None

    def get_arr(self):
        self._consumed += 1
        if self._ring is not None:
            return self._ring.get()
        if getattr(self, "_peeked", None) is not None:
            batch, self._peeked = self._peeked, None
            return batch
        return self._next_batch()

    # ---- checkpoint state (exact mid-epoch resume; the reference's
    # Dataloader has no state capture, SURVEY §5.4) ---- #

    def state_dict(self):
        return {"consumed": self._consumed, "seed": self.seed,
                "shuffle": self.shuffle}

    def load_state_dict(self, state):
        """Fast-forward to `consumed` batches deterministically: the
        epoch permutation is a pure function of (seed, epoch), so the
        position is computed, not replayed.  A running prefetch ring is
        drained and restarted at the restored position; any lookahead it
        held is discarded."""
        if "seed" in state and state["seed"] != self.seed:
            raise ValueError(
                f"dataloader '{self.name}' checkpoint was written with "
                f"seed={state['seed']}, this loader has seed={self.seed} "
                f"— the replayed shuffle order would silently diverge")
        if "shuffle" in state and bool(state["shuffle"]) != self.shuffle:
            raise ValueError(
                f"dataloader '{self.name}' checkpoint shuffle="
                f"{state['shuffle']} != this loader's {self.shuffle}")
        ring = self._ring
        if ring is not None:
            depth, transform = ring.depth, ring.transform
            self.stop_prefetch()
        self._peeked = None
        self._initialized = False
        self.init_states()
        consumed = int(state["consumed"])
        epoch, within = divmod(consumed, self.batch_num)
        self._epoch = 0
        for _ in range(epoch):
            self._reshuffle()
        self.index = min(within * self.batch_size, self.samples_num)
        self.batch_id = within
        self._consumed = consumed
        if ring is not None:
            self.start_prefetch(depth, transform)

    def peek_arr(self):
        """The batch the next get_arr() will return, without consuming it
        (the executor's PS-embedding prefetch looks ahead one batch,
        reference dataloader.py ring lookahead)."""
        if self._ring is not None:
            return self._ring.peek()
        if getattr(self, "_peeked", None) is None:
            self._peeked = self._next_batch()
        return self._peeked

    def _next_batch(self):
        self.init_states()
        remaining = self.samples_num - self.index
        if remaining < self.batch_size and not (
                remaining > 0 and not self.drop_last):
            self.index = 0
            self.batch_id = 0
            self._reshuffle()
            remaining = self.samples_num
        size = min(self.batch_size, remaining) if not self.drop_last \
            else self.batch_size
        sel = self.seq[self.index:self.index + size]
        if self._shard is not None and size == self.batch_size:
            # slice BEFORE the gather: only this process's rows are
            # fancy-indexed/copied (partial tails stay global — their
            # row split would not line up with the full-batch sharding)
            sel = sel[self._shard[0]:self._shard[1]]
        batch = self.data[sel]
        self.index += size
        self.batch_id += 1
        if not self.drop_last and self.index >= self.samples_num:
            # partial tail served; next call starts a fresh epoch
            self.index = 0
            self.batch_id = 0
            self._reshuffle()
        return batch

    def get_cur_shape(self):
        return self.shape


class DataloaderOp(Op):
    """Graph node multiplexing named loaders (reference dataloader.py:186).
    The executor recognizes this node, pulls the next host batch for the
    active subgraph name, and feeds it like a placeholder."""

    def __init__(self, dataloaders):
        super().__init__(name="Dataloader", ctx=cpu(0))
        norm = []
        for dl in dataloaders:
            if isinstance(dl, (list, tuple)):
                norm.append(Dataloader(*dl))
            else:
                norm.append(dl)
        self.dataloaders = {dl.name: dl for dl in norm}

    def set_dp_rank(self, dp_rank, dp_nrank):
        for dl in self.dataloaders.values():
            dl.set_dp_rank(dp_rank, dp_nrank)

    def set_batch_shard(self, lo, hi):
        for dl in self.dataloaders.values():
            dl.set_batch_shard(lo, hi)

    def get_batch_num(self, name):
        self.dataloaders[name].init_states()
        return self.dataloaders[name].batch_num

    def get_arr(self, name):
        return self.dataloaders[name].get_arr()

    def peek_arr(self, name):
        return self.dataloaders[name].peek_arr()

    def get_cur_shape(self, name):
        self.dataloaders[name].init_states()
        return self.dataloaders[name].get_cur_shape()

    def gradient(self, output_grad):
        return None

    def compute(self, input_vals, tc):
        raise AssertionError("DataloaderOp is fed by the executor")


def dataloader_op(dataloaders):
    return DataloaderOp(dataloaders)


class GNNDataLoaderOp(DataloaderOp):
    """Graph-data loader placeholder (reference dataloader.py:147); the
    graph variant feeds externally-registered ndarrays."""

    _graph = None
    _nxt_graph = None

    def __init__(self, handler, ctx=None):
        Op.__init__(self, name="GNNDataloader", ctx=ctx or cpu(0))
        self.handler = handler

    @classmethod
    def step(cls, graph):
        cls._graph = cls._nxt_graph
        cls._nxt_graph = graph

    def get_arr(self, name):
        return self.handler(self._graph)

    def get_batch_num(self, name):
        return None
