"""Static analysis: pre-trace graph verification, parallelism checking,
and the repo lint gate.

Three independent tools that all run BEFORE any jit trace or chip
allocation, so a miswired graph or a misconfigured plan fails in
milliseconds with the offending node named instead of as an XLA stack
dump (or an on-chip crash) minutes later:

- :mod:`.verify` — topo-walk any ``Op`` graph and abstract-eval every
  node (``jax.eval_shape`` over ``Op.compute``), building a per-node
  shape/dtype table; raises :class:`~.verify.GraphVerifyError` naming
  the node, its op type, input shapes/dtypes and producers.  Also
  detects cycles, duplicate names, dead nodes, f32 creep in bf16
  subgraphs, and rng-consuming nodes in rng-less traces.
- :mod:`.shard_check` — validate a graph + mesh + plan statically:
  comm-op axes exist in the mesh, dp/tp divisibility, pipeline stage
  sanity, and the static collective-ordering check (the build-time
  sibling of ``parallel/collective_check.py``).
- :mod:`.lint` — AST rules over the repo itself (env-var registry
  discipline, no host calls in ``Op.compute``, no wall-clock/RNG
  seeding in jitted code, donation on hot-path jits, lock discipline:
  raw-lock / unguarded-shared-write / sleep-under-lock / dead-knob);
  CLI at ``bin/hetu_lint.py``.
- :mod:`.concurrency` — the concurrency sanitizer's analysis surface:
  lockdep violation reporting over :mod:`hetu_tpu.locks` and the
  seeded deterministic-interleaving fuzz driver
  (``run_interleaved``/``HETU_SCHED_FUZZ``).
- :mod:`.jit_audit` — recompile sentinel: engines register their
  jitted steps under ``HETU_VALIDATE=1`` and snapshots assert the
  "one compile per (bucket, config) signature" contract.

``Executor`` and ``ServingEngine`` run verify + shard_check at build
when ``HETU_VALIDATE=1`` (default-on under pytest), emitting JSONL
records in the launcher's failure-log shape (:mod:`.report`).
"""

from .verify import (GraphVerifyError, VerifyReport, verify_graph,
                     check_cycles)
from .shard_check import (ShardCheckError, check_parallelism,
                          check_mesh_axes, check_divisibility,
                          check_pipeline_stages, check_stage_assignment,
                          collective_sequence, check_collective_order_static,
                          check_expert_mesh, check_expert_alltoall)
from .report import emit_records, validation_log_path
from .integration import validate_executor_build, validate_subgraph_feeds, \
    validate_serving
from .concurrency import (LockdepError, lockdep_report,
                          assert_lockdep_clean, run_interleaved,
                          sched_point, lockdep_reset,
                          lockdep_violations)
from .jit_audit import JitAuditError

__all__ = [
    "LockdepError", "lockdep_report", "assert_lockdep_clean",
    "run_interleaved", "sched_point", "lockdep_reset",
    "lockdep_violations", "JitAuditError",
    "GraphVerifyError", "VerifyReport", "verify_graph", "check_cycles",
    "ShardCheckError", "check_parallelism", "check_mesh_axes",
    "check_divisibility", "check_pipeline_stages", "check_stage_assignment",
    "collective_sequence", "check_collective_order_static",
    "check_expert_mesh", "check_expert_alltoall",
    "emit_records", "validation_log_path",
    "validate_executor_build", "validate_subgraph_feeds", "validate_serving",
]
