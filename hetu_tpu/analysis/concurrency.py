"""Concurrency sanitizer driver: lockdep reports + the interleaving
fuzzer's test harness.

The instrumented primitives live in :mod:`hetu_tpu.locks` (every lock
in the repo is constructed there — lint rule ``raw-lock``); this
module is the ANALYSIS surface over them, sibling to ``verify``/
``shard_check``/``lint``:

- **Lockdep reporting** — :func:`lockdep_report` formats the recorded
  violations (lock-order inversions, blocking-work-under-a-lock,
  over-threshold holds) as a ``GraphVerifyError``-style multi-line
  diagnostic naming both lock sites and both acquisition stacks;
  :func:`assert_lockdep_clean` raises :class:`LockdepError` on any —
  the suite's red/green seam, mirrored at trace level by
  ``hetu_trace --check``'s ``lockdep`` rule (any ``lockdep_violation``
  event in a merged stream = red).

- **Deterministic interleaving** — :func:`run_interleaved` runs N
  thunks on N threads under a seeded cooperative scheduler
  (``HETU_SCHED_FUZZ=<seed>`` or an explicit ``seed=``): every traced
  lock acquire/release and every explicit :func:`sched_point` is a
  preemption point where a ``random.Random(seed)`` picks the next
  runnable thread.  The schedule is a pure function of the seed, so
  hammer tests sweep a seed RANGE and any invariant violation found
  on seed N replays on seed N — the ``HETU_CHAOS`` reproducibility
  contract applied to thread schedules.  With no seed (env unset,
  ``seed=None``) the thunks run on free OS threads: a byte-identical
  no-op next to plain ``threading.Thread`` use.
"""

from __future__ import annotations

import threading

from .. import envvars, locks
from ..locks import (TracedLock, TracedRLock, TracedCondition,     # noqa: F401
                     sched_point, note_blocking, lockdep_enabled,
                     lockdep_reset, lockdep_violations, lockdep_edges,
                     format_violation)

__all__ = [
    "LockdepError", "lockdep_report", "assert_lockdep_clean",
    "run_interleaved", "fuzz_seed", "sched_point", "note_blocking",
    "lockdep_enabled", "lockdep_reset", "lockdep_violations",
    "lockdep_edges", "format_violation",
    "TracedLock", "TracedRLock", "TracedCondition",
]


class LockdepError(RuntimeError):
    """Raised by :func:`assert_lockdep_clean`; ``.violations`` carries
    the structured records behind the formatted message."""

    def __init__(self, msg, violations):
        super().__init__(msg)
        self.violations = violations


def lockdep_report() -> str:
    """Every recorded violation, formatted; '' when clean."""
    return "\n\n".join(format_violation(v) for v in lockdep_violations())


def assert_lockdep_clean(context=""):
    """Raise :class:`LockdepError` if any lockdep violation has been
    recorded since the last reset (suite stages and tests call this
    after a hammer run)."""
    vs = lockdep_violations()
    if vs:
        head = f"{len(vs)} lockdep violation(s)" \
               + (f" in {context}" if context else "")
        raise LockdepError(head + ":\n\n" + lockdep_report(), vs)


def fuzz_seed():
    """The active fuzz seed (``HETU_SCHED_FUZZ``), or None."""
    return envvars.get_int("HETU_SCHED_FUZZ")


def run_interleaved(*thunks, seed=None, max_wait=30.0):
    """Run each thunk on its own thread; with a seed, under the
    deterministic scheduler.

    ``seed=None`` defers to ``HETU_SCHED_FUZZ``; if that is unset too,
    the thunks run on free OS threads (no scheduler installed, no
    instrumentation cost anywhere).  Thread identity for scheduling is
    the thunk's INDEX in the call, so the schedule does not depend on
    OS start order.  The first exception any thunk raises is re-raised
    here after all threads finish."""
    if seed is None:
        seed = fuzz_seed()
    errors = []

    if seed is None:
        def _plain(i, fn):
            try:
                fn()
            except BaseException as e:       # noqa: BLE001 — re-raised
                errors.append((i, e))
        threads = [threading.Thread(target=_plain, args=(i, fn),
                                    name=f"interleave-{i}", daemon=True)
                   for i, fn in enumerate(thunks)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(max_wait)
    else:
        sched = locks.InterleaveScheduler(seed, expected=len(thunks),
                                          max_wait=max_wait)

        def _fuzzed(i, fn):
            sched.register(i)
            try:
                fn()
            except BaseException as e:       # noqa: BLE001 — re-raised
                errors.append((i, e))
            finally:
                sched.unregister()

        locks.install_scheduler(sched)
        try:
            threads = [threading.Thread(target=_fuzzed, args=(i, fn),
                                        name=f"interleave-{i}",
                                        daemon=True)
                       for i, fn in enumerate(thunks)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(max_wait + 5.0)
        finally:
            locks.install_scheduler(None)

    alive = [t.name for t in threads if t.is_alive()]
    if alive:
        raise RuntimeError(
            f"run_interleaved(seed={seed}): threads {alive} did not "
            f"finish within {max_wait}s")
    if errors:
        # re-raise the thunk's own exception (the docstring's
        # contract; a wrapper type would break pytest.raises at every
        # caller) — the traceback already points into the thunk
        raise errors[0][1]
