"""AST lint rules for the repo's JAX/TPU footguns.

Four rules, each born from a real regression class in this codebase:

- ``env-registry`` — every ``HETU_*`` environment read must go through
  the typed registry (``hetu_tpu/envvars.py``).  Raw
  ``os.environ["HETU_X"]`` reads scatter defaults and parsing rules
  across the tree (there were 60 before the registry) and leave knobs
  undocumented.  Writes (``os.environ["X"] = v``) stay legal: the
  launcher stamps child environments by design.  Also flags registry
  getters called with a name the registry does not know.
- ``np-in-compute`` — no host-library calls (``np.*``) inside
  ``Op.compute``/``jax_fn``/``collective`` bodies: they either break
  the jit trace outright or silently materialize on host per call.
  Static shape/metadata helpers (``np.prod``, ``np.dtype``, ...) are
  allowed — they run at trace time on python ints.
- ``time-in-jit`` — no wall-clock reads or global-RNG seeding inside
  jit-scoped code (``compute``/``jax_fn`` bodies, ``@jax.jit``
  functions, functions passed to ``jax.jit`` in the same module): the
  value freezes at trace time and silently never updates again.
- ``jit-donate`` — hot-path jits (step/decode/prefill functions, which
  carry caches or optimizer state) must declare donation; without it
  every call copies the whole carried buffer (measured 320x on the
  serving cache scatter).
- ``event-emit`` — JSONL event emission (``f.write(json.dumps(...) +
  "\\n")``) outside ``hetu_tpu/telemetry/`` is an error: the repo once
  grew FOUR independent emitters that merely happened to share a
  record shape; ``telemetry.emit()`` is the one pipeline, and this
  rule keeps it that way the same way ``env-registry`` keeps the env
  registry authoritative.

``bin/hetu_lint.py`` is the CLI; ``tests/test_lint_clean.py`` keeps the
repo itself clean, making the gate permanent tier-1.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass

RULES = ("env-registry", "np-in-compute", "time-in-jit", "jit-donate",
         "event-emit")

# trace-safe static/metadata helpers: run on python ints at trace time
_NP_ALLOWED = frozenset({
    "prod", "dtype", "issubdtype", "iinfo", "finfo", "shape", "ndim",
})

# method names whose bodies execute inside a jit trace (Op protocol)
_TRACE_METHODS = frozenset({"compute", "jax_fn", "collective"})

# wall-clock / global-rng calls that freeze at trace time
_TIME_CALLS = frozenset({
    ("time", "time"), ("time", "perf_counter"), ("time", "monotonic"),
    ("random", "seed"), ("random", "random"),
})
_NP_RANDOM = frozenset({"seed", "RandomState", "default_rng", "rand",
                        "randn", "randint", "random", "uniform",
                        "normal"})

# jitted-function names that carry donated state on the hot path
_HOT_JIT_HINTS = ("step", "decode", "prefill")


@dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    msg: str

    def __str__(self):
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] " \
               f"{self.msg}"


def _attr_chain(node):
    """'os.environ.get' -> ['os', 'environ', 'get'] (or None)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _const_str(node):
    return node.value if isinstance(node, ast.Constant) \
        and isinstance(node.value, str) else None


def _registry_names():
    try:
        from ..envvars import REGISTRY
        return set(REGISTRY)
    except Exception:
        return None


# --------------------------------------------------------------------- #
# rule: env-registry
# --------------------------------------------------------------------- #

def _check_env_registry(tree, path, findings):
    if os.path.basename(path) == "envvars.py":
        return
    registry = _registry_names()
    for node in ast.walk(tree):
        if isinstance(node, ast.Subscript):
            chain = _attr_chain(node.value)
            if chain and chain[-1] == "environ" \
                    and isinstance(node.ctx, ast.Load):
                key = _const_str(node.slice)
                if key and key.startswith("HETU_"):
                    findings.append(Finding(
                        path, node.lineno, node.col_offset,
                        "env-registry",
                        f"raw os.environ[{key!r}] read; use "
                        f"hetu_tpu.envvars.get_*({key!r})"))
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if not chain:
                continue
            is_env_get = (chain[-1] == "get"
                          and len(chain) >= 2
                          and chain[-2] == "environ") \
                or chain[-1] == "getenv"
            if is_env_get and node.args:
                key = _const_str(node.args[0])
                if key and key.startswith("HETU_"):
                    findings.append(Finding(
                        path, node.lineno, node.col_offset,
                        "env-registry",
                        f"raw environ read of {key!r}; use "
                        f"hetu_tpu.envvars.get_*({key!r})"))
            # registry getter called with an unregistered literal name
            if registry is not None and chain[-1].startswith(("get_",
                                                              "require_",
                                                              "is_set")) \
                    and len(chain) >= 2 and chain[-2] == "envvars" \
                    and node.args:
                key = _const_str(node.args[0])
                if key and key.startswith("HETU_") \
                        and key not in registry:
                    findings.append(Finding(
                        path, node.lineno, node.col_offset,
                        "env-registry",
                        f"{key!r} is not registered in "
                        f"hetu_tpu/envvars.py"))


# --------------------------------------------------------------------- #
# rules: np-in-compute + time-in-jit
# --------------------------------------------------------------------- #

def _jitted_function_names(tree):
    """Names of module-level functions that end up inside jax.jit:
    decorated with it, or passed to it by name anywhere in the file."""
    jitted = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                chain = _attr_chain(target)
                if chain and chain[-1] == "jit":
                    jitted.add(node.name)
                if isinstance(dec, ast.Call):
                    # functools.partial(jax.jit, ...)
                    for arg in dec.args:
                        c = _attr_chain(arg)
                        if c and c[-1] == "jit":
                            jitted.add(node.name)
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain and chain[-1] == "jit":
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        jitted.add(arg.id)
    return jitted


def _iter_trace_scopes(tree):
    """Yield (FunctionDef, why) for every function whose body runs
    inside a trace: Op protocol methods and jitted functions."""
    jitted = _jitted_function_names(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for fn in node.body:
                if isinstance(fn, ast.FunctionDef) \
                        and fn.name in _TRACE_METHODS:
                    yield fn, f"{node.name}.{fn.name}"
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in jitted:
                yield node, f"jitted fn {node.name}"


def _check_trace_bodies(tree, path, findings):
    seen = set()
    for fn, why in _iter_trace_scopes(tree):
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain:
                continue
            root = chain[0]
            if root in ("np", "numpy"):
                if len(chain) >= 3 and chain[1] == "random" \
                        and chain[2] in _NP_RANDOM:
                    findings.append(Finding(
                        path, node.lineno, node.col_offset,
                        "time-in-jit",
                        f"{'.'.join(chain)} inside {why}: host RNG "
                        f"state freezes at trace time; use tc.rng_for/"
                        f"jax.random"))
                elif chain[-1] not in _NP_ALLOWED:
                    findings.append(Finding(
                        path, node.lineno, node.col_offset,
                        "np-in-compute",
                        f"host call {'.'.join(chain)} inside {why}: "
                        f"breaks the trace or materializes on host "
                        f"per step; use jnp"))
            elif tuple(chain[:2]) in _TIME_CALLS:
                findings.append(Finding(
                    path, node.lineno, node.col_offset, "time-in-jit",
                    f"{'.'.join(chain)} inside {why}: the value "
                    f"freezes at trace time and never updates"))


# --------------------------------------------------------------------- #
# rule: jit-donate
# --------------------------------------------------------------------- #

def _check_jit_donate(tree, path, findings):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if not chain or chain[-1] != "jit":
            continue
        if not node.args or not isinstance(node.args[0], ast.Name):
            continue
        name = node.args[0].id.lower()
        if not any(h in name for h in _HOT_JIT_HINTS):
            continue
        kw_names = {k.arg for k in node.keywords}
        if None in kw_names:
            continue    # **kwargs expansion: donation decided upstream
        if not kw_names & {"donate_argnums", "donate_argnames"}:
            findings.append(Finding(
                path, node.lineno, node.col_offset, "jit-donate",
                f"jax.jit({node.args[0].id}) on a hot-path function "
                f"without donate_argnums/donate_argnames: every call "
                f"copies the carried state (cache/params) instead of "
                f"updating in place"))


# --------------------------------------------------------------------- #
# rule: event-emit
# --------------------------------------------------------------------- #

def _check_event_emit(tree, path, findings):
    # the telemetry sink is the ONE place allowed to write JSONL events
    norm = path.replace(os.sep, "/")
    if "/telemetry/" in norm or norm.startswith("telemetry/"):
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if not chain or chain[-1] != "write" or not node.args:
            continue
        arg = node.args[0]
        has_dumps = any(
            isinstance(x, ast.Call)
            and (_attr_chain(x.func) or [])[-2:] in (["json", "dumps"],
                                                     ["dumps"])
            for x in ast.walk(arg))
        has_newline = any(
            isinstance(x, ast.Constant) and isinstance(x.value, str)
            and "\n" in x.value for x in ast.walk(arg))
        if has_dumps and has_newline:
            findings.append(Finding(
                path, node.lineno, node.col_offset, "event-emit",
                "JSONL event emission outside hetu_tpu/telemetry/: "
                "route records through telemetry.emit() (one pipeline, "
                "one contract) instead of writing json lines directly"))


# --------------------------------------------------------------------- #
# driver
# --------------------------------------------------------------------- #

_RULE_FNS = {
    "env-registry": _check_env_registry,
    "np-in-compute": _check_trace_bodies,   # shares a walker with
    "time-in-jit": _check_trace_bodies,     # time-in-jit
    "jit-donate": _check_jit_donate,
    "event-emit": _check_event_emit,
}


def lint_source(src, path="<string>", rules=RULES):
    """Lint one source string; returns [Finding]."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, e.offset or 0, "syntax",
                        f"cannot parse: {e.msg}")]
    findings = []
    ran = set()
    for rule in rules:
        fn = _RULE_FNS[rule]
        if id(fn) in ran:
            continue
        ran.add(id(fn))
        fn(tree, path, findings)
    rules = set(rules)
    return [f for f in findings if f.rule in rules or f.rule == "syntax"]


def lint_file(path, rules=RULES):
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path=path, rules=rules)


def iter_py_files(paths):
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif p.endswith(".py"):
            yield p


def lint_paths(paths, rules=RULES):
    findings = []
    for f in iter_py_files(paths):
        findings.extend(lint_file(f, rules=rules))
    return findings


def main(argv=None):
    """CLI: ``hetu_lint.py [--rules r1,r2] [--env-table] paths...``.
    Exits non-zero when findings exist; ``--env-table`` prints the
    generated env-var documentation table and exits."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="hetu_lint",
        description="AST lint gate for hetu_tpu (env registry, host "
                    "calls in compute, wall-clock in jit, hot-path "
                    "donation)")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories to lint")
    ap.add_argument("--rules", default=",".join(RULES),
                    help=f"comma-separated subset of {RULES}")
    ap.add_argument("--env-table", action="store_true",
                    help="print the HETU_* env-var markdown table "
                         "generated from hetu_tpu/envvars.py and exit")
    args = ap.parse_args(argv)
    if args.env_table:
        from ..envvars import env_table
        print(env_table())
        return 0
    rules = tuple(r.strip() for r in args.rules.split(",") if r.strip())
    unknown = [r for r in rules if r not in RULES]
    if unknown:
        ap.error(f"unknown rule(s) {unknown}; choose from {RULES}")
    if not args.paths:
        ap.error("no paths given")
    findings = lint_paths(args.paths, rules=rules)
    for f in findings:
        print(f)
    if findings:
        print(f"{len(findings)} finding(s)")
        return 1
    return 0
