"""AST lint rules for the repo's JAX/TPU footguns.

Four rules, each born from a real regression class in this codebase:

- ``env-registry`` — every ``HETU_*`` environment read must go through
  the typed registry (``hetu_tpu/envvars.py``).  Raw
  ``os.environ["HETU_X"]`` reads scatter defaults and parsing rules
  across the tree (there were 60 before the registry) and leave knobs
  undocumented.  Writes (``os.environ["X"] = v``) stay legal: the
  launcher stamps child environments by design.  Also flags registry
  getters called with a name the registry does not know.
- ``np-in-compute`` — no host-library calls (``np.*``) inside
  ``Op.compute``/``jax_fn``/``collective`` bodies: they either break
  the jit trace outright or silently materialize on host per call.
  Static shape/metadata helpers (``np.prod``, ``np.dtype``, ...) are
  allowed — they run at trace time on python ints.
- ``time-in-jit`` — no wall-clock reads or global-RNG seeding inside
  jit-scoped code (``compute``/``jax_fn`` bodies, ``@jax.jit``
  functions, functions passed to ``jax.jit`` in the same module): the
  value freezes at trace time and silently never updates again.
- ``jit-donate`` — hot-path jits (step/decode/prefill functions, which
  carry caches or optimizer state) must declare donation; without it
  every call copies the whole carried buffer (measured 320x on the
  serving cache scatter).
- ``event-emit`` — JSONL event emission (``f.write(json.dumps(...) +
  "\\n")``) outside ``hetu_tpu/telemetry/`` is an error: the repo once
  grew FOUR independent emitters that merely happened to share a
  record shape; ``telemetry.emit()`` is the one pipeline, and this
  rule keeps it that way the same way ``env-registry`` keeps the env
  registry authoritative.
- ``raw-lock`` — any ``threading.Lock/RLock/Condition`` constructed
  outside ``hetu_tpu/locks.py``: every lock in the tree must be a
  Traced wrapper so the lockdep sanitizer and the interleaving fuzzer
  (``HETU_LOCKDEP``/``HETU_SCHED_FUZZ``) see EVERY synchronization
  point — one raw lock is a blind spot in both.
- ``unguarded-shared-write`` — in a class that owns a lock, an
  attribute that is mutated under a ``with <lock>`` somewhere must be
  mutated under it EVERYWHERE (public methods): a single bare
  ``self._x = ...`` next to ten guarded ones is exactly how the
  flight-ring snapshot race survived three PRs.  Underscore-prefixed
  methods are exempt — they are the documented caller-holds-the-lock
  internals (cstable's ``_replay``/``_lookup`` contract).
- ``sleep-under-lock`` — ``time.sleep`` lexically inside a ``with``
  on a lock-ish attribute: sleeping in a critical section stalls every
  waiter for the full duration; move the sleep out or use a condvar
  wait with a timeout.
- ``dead-knob`` — a registry entry (a literal ``_reg("HETU_X", ...)``
  declaration, i.e. ``envvars.py``) whose name appears nowhere else in
  the linted tree: a knob nothing reads is documentation that lies.
  Cross-file; runs only when the linted paths include a declaring
  file, so linting a subtree without the registry stays quiet.

``bin/hetu_lint.py`` is the CLI; ``tests/test_lint_clean.py`` keeps the
repo itself clean, making the gate permanent tier-1.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass

RULES = ("env-registry", "np-in-compute", "time-in-jit", "jit-donate",
         "event-emit", "raw-lock", "unguarded-shared-write",
         "sleep-under-lock", "dead-knob")

# trace-safe static/metadata helpers: run on python ints at trace time
_NP_ALLOWED = frozenset({
    "prod", "dtype", "issubdtype", "iinfo", "finfo", "shape", "ndim",
})

# method names whose bodies execute inside a jit trace (Op protocol)
_TRACE_METHODS = frozenset({"compute", "jax_fn", "collective"})

# wall-clock / global-rng calls that freeze at trace time
_TIME_CALLS = frozenset({
    ("time", "time"), ("time", "perf_counter"), ("time", "monotonic"),
    ("random", "seed"), ("random", "random"),
})
_NP_RANDOM = frozenset({"seed", "RandomState", "default_rng", "rand",
                        "randn", "randint", "random", "uniform",
                        "normal"})

# jitted-function names that carry donated state on the hot path
_HOT_JIT_HINTS = ("step", "decode", "prefill")


@dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    msg: str

    def __str__(self):
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] " \
               f"{self.msg}"


def _attr_chain(node):
    """'os.environ.get' -> ['os', 'environ', 'get'] (or None)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _const_str(node):
    return node.value if isinstance(node, ast.Constant) \
        and isinstance(node.value, str) else None


def _registry_names():
    try:
        from ..envvars import REGISTRY
        return set(REGISTRY)
    except Exception:
        return None


# --------------------------------------------------------------------- #
# rule: env-registry
# --------------------------------------------------------------------- #

def _check_env_registry(tree, path, findings):
    if os.path.basename(path) == "envvars.py":
        return
    registry = _registry_names()
    for node in ast.walk(tree):
        if isinstance(node, ast.Subscript):
            chain = _attr_chain(node.value)
            if chain and chain[-1] == "environ" \
                    and isinstance(node.ctx, ast.Load):
                key = _const_str(node.slice)
                if key and key.startswith("HETU_"):
                    findings.append(Finding(
                        path, node.lineno, node.col_offset,
                        "env-registry",
                        f"raw os.environ[{key!r}] read; use "
                        f"hetu_tpu.envvars.get_*({key!r})"))
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if not chain:
                continue
            is_env_get = (chain[-1] == "get"
                          and len(chain) >= 2
                          and chain[-2] == "environ") \
                or chain[-1] == "getenv"
            if is_env_get and node.args:
                key = _const_str(node.args[0])
                if key and key.startswith("HETU_"):
                    findings.append(Finding(
                        path, node.lineno, node.col_offset,
                        "env-registry",
                        f"raw environ read of {key!r}; use "
                        f"hetu_tpu.envvars.get_*({key!r})"))
            # registry getter called with an unregistered literal name
            if registry is not None and chain[-1].startswith(("get_",
                                                              "require_",
                                                              "is_set")) \
                    and len(chain) >= 2 and chain[-2] == "envvars" \
                    and node.args:
                key = _const_str(node.args[0])
                if key and key.startswith("HETU_") \
                        and key not in registry:
                    findings.append(Finding(
                        path, node.lineno, node.col_offset,
                        "env-registry",
                        f"{key!r} is not registered in "
                        f"hetu_tpu/envvars.py"))


# --------------------------------------------------------------------- #
# rules: np-in-compute + time-in-jit
# --------------------------------------------------------------------- #

def _jitted_function_names(tree):
    """Names of module-level functions that end up inside jax.jit:
    decorated with it, or passed to it by name anywhere in the file."""
    jitted = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                chain = _attr_chain(target)
                if chain and chain[-1] == "jit":
                    jitted.add(node.name)
                if isinstance(dec, ast.Call):
                    # functools.partial(jax.jit, ...)
                    for arg in dec.args:
                        c = _attr_chain(arg)
                        if c and c[-1] == "jit":
                            jitted.add(node.name)
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain and chain[-1] == "jit":
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        jitted.add(arg.id)
    return jitted


def _iter_trace_scopes(tree):
    """Yield (FunctionDef, why) for every function whose body runs
    inside a trace: Op protocol methods and jitted functions."""
    jitted = _jitted_function_names(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for fn in node.body:
                if isinstance(fn, ast.FunctionDef) \
                        and fn.name in _TRACE_METHODS:
                    yield fn, f"{node.name}.{fn.name}"
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in jitted:
                yield node, f"jitted fn {node.name}"


def _check_trace_bodies(tree, path, findings):
    seen = set()
    for fn, why in _iter_trace_scopes(tree):
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain:
                continue
            root = chain[0]
            if root in ("np", "numpy"):
                if len(chain) >= 3 and chain[1] == "random" \
                        and chain[2] in _NP_RANDOM:
                    findings.append(Finding(
                        path, node.lineno, node.col_offset,
                        "time-in-jit",
                        f"{'.'.join(chain)} inside {why}: host RNG "
                        f"state freezes at trace time; use tc.rng_for/"
                        f"jax.random"))
                elif chain[-1] not in _NP_ALLOWED:
                    findings.append(Finding(
                        path, node.lineno, node.col_offset,
                        "np-in-compute",
                        f"host call {'.'.join(chain)} inside {why}: "
                        f"breaks the trace or materializes on host "
                        f"per step; use jnp"))
            elif tuple(chain[:2]) in _TIME_CALLS:
                findings.append(Finding(
                    path, node.lineno, node.col_offset, "time-in-jit",
                    f"{'.'.join(chain)} inside {why}: the value "
                    f"freezes at trace time and never updates"))


# --------------------------------------------------------------------- #
# rule: jit-donate
# --------------------------------------------------------------------- #

def _check_jit_donate(tree, path, findings):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if not chain or chain[-1] != "jit":
            continue
        if not node.args or not isinstance(node.args[0], ast.Name):
            continue
        name = node.args[0].id.lower()
        if not any(h in name for h in _HOT_JIT_HINTS):
            continue
        kw_names = {k.arg for k in node.keywords}
        if None in kw_names:
            continue    # **kwargs expansion: donation decided upstream
        if not kw_names & {"donate_argnums", "donate_argnames"}:
            findings.append(Finding(
                path, node.lineno, node.col_offset, "jit-donate",
                f"jax.jit({node.args[0].id}) on a hot-path function "
                f"without donate_argnums/donate_argnames: every call "
                f"copies the carried state (cache/params) instead of "
                f"updating in place"))


# --------------------------------------------------------------------- #
# rule: event-emit
# --------------------------------------------------------------------- #

def _check_event_emit(tree, path, findings):
    # the telemetry sink is the ONE place allowed to write JSONL events
    norm = path.replace(os.sep, "/")
    if "/telemetry/" in norm or norm.startswith("telemetry/"):
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if not chain or chain[-1] != "write" or not node.args:
            continue
        arg = node.args[0]
        has_dumps = any(
            isinstance(x, ast.Call)
            and (_attr_chain(x.func) or [])[-2:] in (["json", "dumps"],
                                                     ["dumps"])
            for x in ast.walk(arg))
        has_newline = any(
            isinstance(x, ast.Constant) and isinstance(x.value, str)
            and "\n" in x.value for x in ast.walk(arg))
        if has_dumps and has_newline:
            findings.append(Finding(
                path, node.lineno, node.col_offset, "event-emit",
                "JSONL event emission outside hetu_tpu/telemetry/: "
                "route records through telemetry.emit() (one pipeline, "
                "one contract) instead of writing json lines directly"))


# --------------------------------------------------------------------- #
# rules: lock discipline (raw-lock / unguarded-shared-write /
# sleep-under-lock)
# --------------------------------------------------------------------- #

# constructor names that make an attribute a "lock" for these rules
_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "TracedLock",
                         "TracedRLock", "TracedCondition"})
# attribute-name fragments treated as lock-ish guards in with-blocks
_LOCKISH = ("lock", "_mu", "mutex", "cv", "cond")


def _is_lock_ctor(node):
    if not isinstance(node, ast.Call):
        return False
    chain = _attr_chain(node.func)
    return bool(chain) and chain[-1] in _LOCK_CTORS


def _lockish_name(name):
    low = name.lower()
    return any(h in low for h in _LOCKISH) \
        or low.endswith("_mu") or low in ("mu", "cv")


def _self_attr(node):
    """'self.<attr>' -> attr name (or None)."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _check_raw_lock(tree, path, findings):
    if os.path.basename(path) == "locks.py":
        return    # the one legal construction site (and the wrappers'
        # own raw internals, which must not recurse into themselves)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain and chain[0] == "threading" \
                and chain[-1] in ("Lock", "RLock", "Condition"):
            findings.append(Finding(
                path, node.lineno, node.col_offset, "raw-lock",
                f"raw threading.{chain[-1]}() outside hetu_tpu/locks.py;"
                f" use locks.Traced{chain[-1]}(name) so lockdep and the"
                f" interleaving fuzzer see this synchronization point"))


def _guard_names(items):
    """Lock-ish self attributes guarding a With statement."""
    names = set()
    for item in items:
        ctx = item.context_expr
        if isinstance(ctx, ast.Call):
            ctx = ctx.func
        attr = _self_attr(ctx)
        if attr and _lockish_name(attr):
            names.add(attr)
    return names


def _write_targets(node):
    if isinstance(node, ast.Assign):
        return node.targets
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return [node.target]
    return []


def _check_lock_discipline(tree, path, findings):
    """unguarded-shared-write + sleep-under-lock (one class walker)."""
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        owns_lock = any(
            _is_lock_ctor(n.value)
            and any(_self_attr(t) for t in n.targets)
            for n in ast.walk(cls) if isinstance(n, ast.Assign))
        # pass 1: attributes the class itself treats as lock-protected
        # (assigned under a with on a lock-ish self attribute anywhere)
        protected = set()

        def scan_protected(node, guarded):
            if isinstance(node, ast.With):
                g = guarded or bool(_guard_names(node.items))
                for child in node.body:
                    scan_protected(child, g)
                return
            if guarded:
                for t in _write_targets(node):
                    attr = _self_attr(t)
                    if attr and attr.startswith("_") \
                            and not _lockish_name(attr):
                        protected.add(attr)
            for child in ast.iter_child_nodes(node):
                scan_protected(child, guarded)

        if owns_lock:
            scan_protected(cls, False)

        # pass 2: public methods writing a protected attr outside the
        # lock, and time.sleep inside any lock-ish with (any method)
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            public = not fn.name.startswith("_")

            def scan(node, guarded):
                if isinstance(node, ast.With):
                    g = guarded or bool(_guard_names(node.items))
                    for child in node.body:
                        scan(child, g)
                    return
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    return   # nested defs run later, on other threads
                if isinstance(node, ast.Call) and guarded:
                    chain = _attr_chain(node.func)
                    if chain == ["time", "sleep"]:
                        findings.append(Finding(
                            path, node.lineno, node.col_offset,
                            "sleep-under-lock",
                            f"time.sleep inside a with-lock block in "
                            f"{cls.name}.{fn.name}: every waiter "
                            f"stalls for the full sleep; move it out "
                            f"or wait on a condvar with a timeout"))
                if public and owns_lock and not guarded:
                    for t in _write_targets(node):
                        attr = _self_attr(t)
                        if attr in protected:
                            findings.append(Finding(
                                path, node.lineno, node.col_offset,
                                "unguarded-shared-write",
                                f"{cls.name}.{fn.name} writes "
                                f"self.{attr} without the lock, but "
                                f"{cls.name} mutates it under a "
                                f"with-lock elsewhere: every mutation "
                                f"of shared state must hold the lock"))
                for child in ast.iter_child_nodes(node):
                    scan(child, guarded)

            for stmt in fn.body:
                scan(stmt, False)


# --------------------------------------------------------------------- #
# rule: dead-knob (cross-file; driven from lint_paths)
# --------------------------------------------------------------------- #

_KNOB_RE = None


def _declared_knobs(tree):
    """``_reg("HETU_X", ...)`` registry declarations -> {(name, line)}.

    Parsed from the AST rather than importing the live REGISTRY so the
    rule works on any tree (and on its own test fixture), and so each
    finding anchors at the declaring line instead of file:1."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and node.func.id == "_reg" \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str) \
                and node.args[0].value.startswith("HETU_"):
            names.add((node.args[0].value, node.lineno))
    return names


def _check_dead_knobs(py_files):
    """Registry declarations that no OTHER linted file references (any
    textual ``HETU_*`` occurrence counts — getter calls, launcher env
    stamping, f-string prefixes in docs).  Declaring files contribute
    declarations, not references: the registry row itself never keeps
    a knob alive."""
    global _KNOB_RE
    import re
    if _KNOB_RE is None:
        _KNOB_RE = re.compile(r"HETU_[A-Z0-9_]+")
    declares = []                 # (path, name, lineno)
    refs = set()
    for f in py_files:
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
        except OSError:
            continue
        decl = set()
        try:
            decl = _declared_knobs(ast.parse(src))
        except SyntaxError:
            pass
        if decl:
            declares.extend((f, n, ln) for n, ln in decl)
        else:
            refs.update(_KNOB_RE.findall(src))
    findings = []
    for path, name, lineno in sorted(declares):
        if name not in refs:
            findings.append(Finding(
                path, lineno, 0, "dead-knob",
                f"registered env var {name!r} is read nowhere in the "
                f"linted tree: delete the registry row or wire the "
                f"knob up (a documented knob nothing reads is a lie)"))
    return findings


def _noop_rule(tree, path, findings):
    """dead-knob is cross-file; per-file linting contributes nothing."""


# --------------------------------------------------------------------- #
# driver
# --------------------------------------------------------------------- #

_RULE_FNS = {
    "env-registry": _check_env_registry,
    "np-in-compute": _check_trace_bodies,   # shares a walker with
    "time-in-jit": _check_trace_bodies,     # time-in-jit
    "jit-donate": _check_jit_donate,
    "event-emit": _check_event_emit,
    "raw-lock": _check_raw_lock,
    "unguarded-shared-write": _check_lock_discipline,  # shares a class
    "sleep-under-lock": _check_lock_discipline,        # walker
    "dead-knob": _noop_rule,    # cross-file: handled in lint_paths
}


def lint_source(src, path="<string>", rules=RULES):
    """Lint one source string; returns [Finding]."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, e.offset or 0, "syntax",
                        f"cannot parse: {e.msg}")]
    findings = []
    ran = set()
    for rule in rules:
        fn = _RULE_FNS[rule]
        if id(fn) in ran:
            continue
        ran.add(id(fn))
        fn(tree, path, findings)
    rules = set(rules)
    return [f for f in findings if f.rule in rules or f.rule == "syntax"]


def lint_file(path, rules=RULES):
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path=path, rules=rules)


def iter_py_files(paths):
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif p.endswith(".py"):
            yield p


def lint_paths(paths, rules=RULES):
    findings = []
    files = list(iter_py_files(paths))
    for f in files:
        findings.extend(lint_file(f, rules=rules))
    if "dead-knob" in rules:
        findings.extend(_check_dead_knobs(files))
    return findings


def main(argv=None):
    """CLI: ``hetu_lint.py [--rules r1,r2] [--env-table] paths...``.
    Exits non-zero when findings exist; ``--env-table`` prints the
    generated env-var documentation table and exits."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="hetu_lint",
        description="AST lint gate for hetu_tpu (env registry, host "
                    "calls in compute, wall-clock in jit, hot-path "
                    "donation)")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories to lint")
    ap.add_argument("--rules", default=",".join(RULES),
                    help=f"comma-separated subset of {RULES}")
    ap.add_argument("--env-table", action="store_true",
                    help="print the HETU_* env-var markdown table "
                         "generated from hetu_tpu/envvars.py and exit")
    args = ap.parse_args(argv)
    if args.env_table:
        from ..envvars import env_table
        print(env_table())
        return 0
    rules = tuple(r.strip() for r in args.rules.split(",") if r.strip())
    unknown = [r for r in rules if r not in RULES]
    if unknown:
        ap.error(f"unknown rule(s) {unknown}; choose from {RULES}")
    if not args.paths:
        ap.error("no paths given")
    findings = lint_paths(args.paths, rules=rules)
    for f in findings:
        print(f)
    if findings:
        print(f"{len(findings)} finding(s)")
        return 1
    return 0
