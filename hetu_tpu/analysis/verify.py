"""Pre-trace graph verifier: abstract-eval every node, fail with names.

The reference Hetu hand-writes ``infer_shape`` per op (every
``gpu_ops/*.py`` file), so a miswired graph fails at BUILD time with the
offending node named.  Our port replaced that surface with one generic
``jax.eval_shape`` hook (``graph/node.py Op.infer_shape``) — but nothing
called it graph-wide, so a shape/dtype mistake surfaced as a jit-trace
stack dump deep inside XLA with no node attribution.  This module closes
that gap: :func:`verify_graph` topo-walks any ``Op`` graph and
abstract-evals each node (shape AND dtype — ``eval_shape`` costs no
FLOPs and no device), building a per-node table; the first failure
raises :class:`GraphVerifyError` naming the node, its op type, its input
shapes/dtypes, and the producing nodes — no jit traceback.

Also detected, because the topo walk sees the whole graph anyway:

- cycles (``find_topo_sort`` silently mis-orders them),
- duplicate node names (would collide in feeds/params dicts),
- dead nodes (given the build universe, nodes unreachable from any
  output — usually a forgotten eval node or a detached adjoint),
- unexpected f32 creep inside bf16 subgraphs (an op that silently
  upcasts defeats the mixed-precision policy's MXU savings),
- rng-consuming nodes (dropout &c.) in traces built without an rng.

Structural problems (cycle/duplicate/shape/rng) raise; advisory ones
(dead nodes, dtype creep) land in ``VerifyReport.findings`` so callers
can log them in the launcher's record shape (:mod:`.report`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..graph.node import Op, TraceContext
from ..graph.ops_misc import PlaceholderOp

# sentinel for "shape unknown until feed time" — propagates through
# consumers so build-time verification checks everything it CAN see and
# the run-time pass (concrete feed shapes) covers the rest
UNKNOWN = "<unknown>"


class GraphVerifyError(Exception):
    """A statically-detected graph defect.  ``node`` is the offending Op
    (when one is attributable), ``kind`` the defect class: ``cycle``,
    ``duplicate_name``, ``shape``, ``rng_missing``."""

    def __init__(self, message, node=None, kind="shape"):
        super().__init__(message)
        self.node = node
        self.kind = kind


class VerifyReport:
    """Result of a successful verification.

    ``table`` maps node name -> abstract output (a ShapeDtypeStruct,
    a pytree of them for multi-output ops, ``UNKNOWN`` for nodes
    downstream of unshaped feeds, or None for executor-internal nodes
    like optimizers).  ``findings`` is a list of advisory dicts
    ({"kind", "node", ...}); ``rng_consumers`` the nodes that drew rng.
    """

    def __init__(self):
        self.table = {}
        self.findings = []
        self.rng_consumers = []

    def shape_of(self, node):
        out = self.table.get(node.name if isinstance(node, Op) else node)
        return tuple(out.shape) if hasattr(out, "shape") else None

    def dtype_of(self, node):
        out = self.table.get(node.name if isinstance(node, Op) else node)
        return out.dtype if hasattr(out, "dtype") else None

    def verified_count(self):
        return sum(1 for v in self.table.values()
                   if hasattr(v, "shape") or isinstance(v, (tuple, list)))


# --------------------------------------------------------------------- #
# structural checks
# --------------------------------------------------------------------- #

def check_cycles(eval_nodes):
    """Iterative 3-color DFS over ``inputs`` edges; raises
    GraphVerifyError(kind='cycle') naming the cycle's nodes.  Run before
    ``find_topo_sort`` anywhere correctness matters: its visited-set DFS
    TERMINATES on a cycle but returns a silently wrong order."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {}
    for root in eval_nodes:
        if color.get(id(root), WHITE) != WHITE:
            continue
        # stack of (node, input iterator); path tracks the gray chain
        stack = [(root, iter(root.inputs))]
        color[id(root)] = GRAY
        path = [root]
        while stack:
            node, it = stack[-1]
            child = next(it, None)
            if child is None:
                stack.pop()
                path.pop()
                color[id(node)] = BLACK
                continue
            c = color.get(id(child), WHITE)
            if c == GRAY:
                start = next(i for i, n in enumerate(path)
                             if n is child)
                cyc = " -> ".join(n.name for n in path[start:] + [child])
                raise GraphVerifyError(
                    f"cycle in graph: {cyc}", node=child, kind="cycle")
            if c == WHITE:
                color[id(child)] = GRAY
                stack.append((child, iter(child.inputs)))
                path.append(child)


def _topo(eval_nodes):
    """Cycle-checked topo order (post-order DFS, iterative)."""
    check_cycles(eval_nodes)
    from ..graph.autodiff import find_topo_sort
    return find_topo_sort(eval_nodes)


def _check_duplicate_names(topo):
    seen = {}
    for n in topo:
        other = seen.get(n.name)
        if other is not None and other is not n:
            raise GraphVerifyError(
                f"duplicate node name {n.name!r}: {type(other).__name__} "
                f"and {type(n).__name__} — feeds/params are name-keyed, "
                f"so one value would silently bind both nodes",
                node=n, kind="duplicate_name")
        seen[n.name] = n


# --------------------------------------------------------------------- #
# abstract evaluation
# --------------------------------------------------------------------- #

class _AbstractParams:
    """``tc.params`` stand-in: hands back zero arrays shaped like the
    variable (BatchNorm running stats &c.).  Values are only ever traced
    under ``eval_shape``, so nothing big is computed — but the arrays ARE
    materialized host-side; state vars are small by construction."""

    def __getitem__(self, node):
        shape = tuple(getattr(node, "shape", None) or ())
        dtype = getattr(node, "dtype", None) or jnp.float32
        return jnp.zeros(shape, dtype)

    def __contains__(self, node):
        return getattr(node, "shape", None) is not None


class _RecordingTC(TraceContext):
    """TraceContext that records rng consumption instead of requiring a
    key — verification must see WHICH nodes need rng even when the trace
    being modeled has none."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.rng_consumers = []

    def rng_for(self, node):
        self.rng_consumers.append(node)
        if self._rng is None:
            self._rng = jax.random.PRNGKey(0)
        return super().rng_for(node)


def _fmt_aval(v):
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        return f"{jnp.dtype(v.dtype).name}{tuple(v.shape)}"
    if isinstance(v, (tuple, list)):
        return "(" + ", ".join(_fmt_aval(e) for e in v) + ")"
    return str(v)


def _abstract_eval(node, in_avals, tc):
    """One node through ``jax.eval_shape`` of its own ``compute`` —
    the graph-wide driver for the per-op ``infer_shape``/``eval_shape``
    hook (ops overriding ``infer_shape`` keep shape authority; dtype
    still comes from the eval)."""
    out = jax.eval_shape(lambda *a: node.compute(list(a), tc), *in_avals)
    if type(node).infer_shape is not Op.infer_shape and \
            hasattr(out, "shape"):
        # an op with a hand-written infer_shape is the authority on its
        # shape; cross-check it against the eval so the two hooks can
        # never silently diverge
        shapes = [tuple(a.shape) for a in in_avals
                  if hasattr(a, "shape")]
        dtypes = [a.dtype for a in in_avals if hasattr(a, "dtype")]
        declared = tuple(node.infer_shape(shapes, dtypes))
        if declared != tuple(out.shape):
            raise GraphVerifyError(
                f"{node.name} ({type(node).__name__}): infer_shape "
                f"declares {declared} but compute produces "
                f"{tuple(out.shape)}", node=node, kind="shape")
    return out


def verify_graph(eval_nodes, *, feed_shapes=None, feed_dtypes=None,
                 rng_available=True, mixed_precision=None, config=None,
                 mesh=None, all_nodes=None, skip_ids=frozenset()):
    """Verify the graph rooted at ``eval_nodes``; returns a
    :class:`VerifyReport` or raises :class:`GraphVerifyError`.

    feed_shapes/feed_dtypes: name -> shape/dtype for placeholders and
    dataloader nodes whose shape the graph does not carry (run-time
    validation passes the concrete feed signature; build-time passes
    whatever is known and leaves the rest ``UNKNOWN``).
    rng_available: whether the trace being modeled carries an rng key;
    rng-consuming nodes without one raise (kind='rng_missing').
    mixed_precision: the executor's compute-dtype policy — float inputs
    are modeled in this dtype and f32 creep back is reported.
    all_nodes: optional build universe; members unreachable from
    ``eval_nodes`` are reported as dead-node findings.
    skip_ids: ``id(node)`` set the executor special-cases to None
    (e.g. IndexedSlices consumed only by the optimizer).
    """
    feed_shapes = feed_shapes or {}
    feed_dtypes = feed_dtypes or {}
    eval_nodes = [n for n in eval_nodes if n is not None]
    topo = _topo(eval_nodes)
    _check_duplicate_names(topo)

    report = VerifyReport()
    if all_nodes is not None:
        reachable = {id(n) for n in topo}
        for n in all_nodes:
            if id(n) not in reachable:
                report.findings.append({
                    "kind": "dead_node", "node": n.name,
                    "op": type(n).__name__,
                    "detail": "unreachable from every output"})

    mp = mixed_precision
    if mp in ("bf16", "bfloat16"):
        mp = jnp.bfloat16
    elif mp in ("fp16", "float16"):
        mp = jnp.float16

    def cast_in(aval):
        # model the executor's graph-entry cast (float feeds/params
        # compute in the policy dtype)
        if mp is not None and hasattr(aval, "dtype") \
                and jnp.issubdtype(aval.dtype, jnp.floating):
            return jax.ShapeDtypeStruct(aval.shape, mp)
        return aval

    tc = _RecordingTC(params=_AbstractParams(), rng=None,
                      training=True, mesh=mesh, config=config,
                      step=jnp.zeros((), jnp.int32))
    tc.rng_ids = {n.id: i for i, n in enumerate(topo)}

    from ..dataloader import DataloaderOp
    from ..optimizer import OptimizerOp

    avals = {}
    eval_ids = {id(n) for n in eval_nodes}
    for node in topo:
        if isinstance(node, PlaceholderOp):
            shape = node.shape if node.shape is not None \
                else feed_shapes.get(node.name)
            if shape is None:
                avals[id(node)] = UNKNOWN
            else:
                dtype = feed_dtypes.get(node.name) or node.dtype \
                    or jnp.float32
                avals[id(node)] = cast_in(
                    jax.ShapeDtypeStruct(tuple(shape), dtype))
        elif isinstance(node, DataloaderOp):
            # shape must come from the caller (the executor passes the
            # SUBGRAPH's own loader shape — train and validate loaders
            # behind one DataloaderOp can batch differently)
            shape = feed_shapes.get(node.name)
            dtype = feed_dtypes.get(node.name)
            if shape is None:
                avals[id(node)] = UNKNOWN
            else:
                avals[id(node)] = cast_in(jax.ShapeDtypeStruct(
                    tuple(shape), dtype or jnp.float32))
        elif isinstance(node, OptimizerOp) or id(node) in skip_ids:
            # executor-internal: no dataflow value to type
            avals[id(node)] = None
        else:
            in_avals = [avals[id(i)] for i in node.inputs]
            if any(a is UNKNOWN or a is None for a in in_avals):
                avals[id(node)] = UNKNOWN
                report.table[node.name] = UNKNOWN
                continue
            try:
                out = _abstract_eval(node, in_avals, tc)
            except GraphVerifyError:
                raise
            except Exception as e:  # noqa: BLE001 — any trace failure
                ins = ", ".join(
                    f"{i.name}={_fmt_aval(a)}"
                    for i, a in zip(node.inputs, in_avals))
                raise GraphVerifyError(
                    f"graph verification failed at node {node.name!r} "
                    f"(op {type(node).__name__}) — abstract eval of its "
                    f"compute raised {type(e).__name__}: {e}\n"
                    f"  inputs: {ins or '(none)'}\n"
                    f"  produced by: "
                    f"{[i.name for i in node.inputs] or '(leaf)'}",
                    node=node, kind="shape") from e
            avals[id(node)] = out
            if mp is not None and hasattr(out, "dtype") \
                    and out.dtype == jnp.float32 \
                    and id(node) not in eval_ids \
                    and any(hasattr(a, "dtype") and a.dtype == mp
                            for a in in_avals
                            if hasattr(a, "dtype")):
                # outputs (losses/metrics) legitimately report f32; an
                # INTERIOR f32 widening silently defeats the policy
                report.findings.append({
                    "kind": "dtype_creep", "node": node.name,
                    "op": type(node).__name__,
                    "detail": f"f32 output from "
                              f"{jnp.dtype(mp).name} inputs"})
        report.table[node.name] = avals[id(node)]

    report.rng_consumers = [n.name for n in tc.rng_consumers]
    if not rng_available and tc.rng_consumers:
        names = sorted({n.name for n in tc.rng_consumers})
        raise GraphVerifyError(
            f"nodes {names} consume RNG but the trace is built without "
            f"an rng key (inference/serving path) — their outputs would "
            f"assert at trace time", node=tc.rng_consumers[0],
            kind="rng_missing")
    return report
