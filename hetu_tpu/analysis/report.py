"""JSONL reporting for the static checks — a thin stream adapter over
the ONE telemetry sink (telemetry/events.py).

Contract unchanged since PR 1: every record is ``{"t": <epoch seconds,
3 decimals>, "event": <kind>, **fields}`` appended as one JSON line.
Records belong to the ``validate`` stream, so they land in
``$HETU_VALIDATE_LOG`` (legacy path — the same ``tail -f | jq``
pipeline as the failure log) plus the merged ``$HETU_TELEMETRY_LOG``.
"""

from __future__ import annotations

from .. import envvars
from ..telemetry import events as _events


def validation_log_path():
    """The JSONL sink for verifier/shard-check records, or None."""
    return envvars.get_path("HETU_VALIDATE_LOG")


def make_record(event, **fields):
    """One contract-shaped record: {"t": ..., "event": event, **fields}."""
    return _events.make_record(event, **fields)


def emit_records(records, path=None):
    """Route records (dicts from :func:`make_record`) through the
    telemetry sink's ``validate`` stream (``path`` overrides the
    stream's env-var sink).  Best-effort: an unwritable log must never
    take down a build that validated fine."""
    if not records:
        return records
    return _events.get_sink().emit_prebuilt(records, stream="validate",
                                            path=path)
