"""JSONL reporting for the static checks, in the launcher's record shape.

One contract across the repo (PR 1's event-log convention,
``launcher.py _event`` / ``serving/metrics.py event``): every record is
``{"t": <epoch seconds, 3 decimals>, "event": <kind>, **fields}``
appended as one JSON line, so the same ``tail -f | jq`` pipeline reads
failure events, serving telemetry, and (now) verifier reports.
"""

from __future__ import annotations

import json
import time

from .. import envvars


def validation_log_path():
    """The JSONL sink for verifier/shard-check records, or None."""
    return envvars.get_path("HETU_VALIDATE_LOG")


def make_record(event, **fields):
    """One launcher-shaped record: {"t": ..., "event": event, **fields}."""
    return {"t": round(time.time(), 3), "event": event, **fields}


def emit_records(records, path=None):
    """Append records (dicts from :func:`make_record`) to ``path`` or
    ``$HETU_VALIDATE_LOG``.  Best-effort: an unwritable log must never
    take down a build that validated fine."""
    path = path if path is not None else validation_log_path()
    if not path or not records:
        return records
    try:
        with open(path, "a") as f:
            for rec in records:
                f.write(json.dumps(rec, default=str) + "\n")
    except OSError:
        pass
    return records
