"""Build-time wiring: Executor / ServingEngine run the static checks.

``HETU_VALIDATE=1`` (default-on under pytest, tests/conftest.py) makes
every executor build and every new feed-shape compile run
:func:`~.verify.verify_graph` + :func:`~.shard_check.check_parallelism`
BEFORE jax traces anything, and every serving-engine build validate its
params against its config.  Each validation appends JSONL records in
the launcher's failure-log shape (:mod:`.report`) to
``$HETU_VALIDATE_LOG`` when set.

Two passes per subgraph, because feed shapes arrive late:

- **build** (``Executor.__init__``): everything derivable from the
  graph alone — cycles, duplicate names, comm axes, sharding
  divisibility, pipeline stage plans, plus shape/dtype propagation
  through every node whose inputs are fully shaped (variables have
  declared shapes; only fed placeholders are UNKNOWN).
- **feeds** (``SubExecutor.run``, once per new feed signature, just
  before the compile that would otherwise produce the XLA stack dump):
  the same walk with the concrete feed shapes, so feed-dependent
  mismatches also fail named-node-first.
"""

from __future__ import annotations

import numpy as np

from .. import envvars
from .report import emit_records, make_record
from .shard_check import ShardCheckError, check_parallelism
from .verify import GraphVerifyError, verify_graph


def validation_enabled() -> bool:
    return envvars.get_bool("HETU_VALIDATE")


def _coerce(dt):
    # mirror gather_feeds' host-side dtype coercion (x64 stays off)
    s = str(dt)
    if s == "float64":
        return np.float32
    if s == "int64":
        return np.int32
    return dt


def _feed_sig_maps(feeds):
    shapes, dtypes = {}, {}
    for k, v in (feeds or {}).items():
        shape = getattr(v, "shape", None)
        if shape is None:
            shape = np.shape(v)
        shapes[k] = tuple(shape)
        dt = getattr(v, "dtype", None)
        if dt is not None:
            dtypes[k] = _coerce(dt)
    return shapes, dtypes


def _validate_sub(ex, sub, phase, feeds=None):
    feed_shapes, feed_dtypes = _feed_sig_maps(feeds)
    # pipeline subgraphs bake the MICROBATCH shape: the executor splits
    # each fed global batch into M chunks along dim 0 before tracing
    # (pipeline_executor._split_microbatches), so validation must model
    # the per-microbatch shapes.  Non-divisible feeds are left out —
    # the executor raises its own (already named) error for those.
    if feeds is None:
        # build phase: dataloader batch shapes are known pre-feed from
        # THIS subgraph's wired loaders
        for dl in getattr(sub, "dataloader_ops", ()):
            loader = getattr(dl, "dataloaders", {}).get(sub.name)
            if loader is not None and getattr(loader, "shape", None):
                feed_shapes.setdefault(dl.name, tuple(loader.shape))
                data = getattr(loader, "data", None)
                if getattr(data, "dtype", None) is not None:
                    feed_dtypes.setdefault(dl.name, _coerce(data.dtype))
    M = getattr(sub, "num_microbatches", None)
    if M and M > 1 and feed_shapes:
        skip = getattr(sub, "non_batch_feeds", frozenset())
        split = {}
        for k, shape in feed_shapes.items():
            if k in skip:
                split[k] = shape
            elif shape and shape[0] % M == 0:
                split[k] = (shape[0] // M,) + tuple(shape[1:])
        feed_shapes = split
    cfg = ex.config
    records = []
    try:
        rep = verify_graph(
            sub.eval_nodes,
            feed_shapes=feed_shapes, feed_dtypes=feed_dtypes,
            rng_available=True,
            mixed_precision=cfg.mixed_precision,
            config=cfg, mesh=ex.mesh,
            skip_ids=frozenset(getattr(sub, "skip_dense", ())))
        findings = check_parallelism(
            sub.eval_nodes, ex.mesh, config=cfg,
            feed_shapes={k: v for k, v in feed_shapes.items()
                         if not k.startswith("__ps")})
        records.append(make_record(
            "graph_verified", subgraph=sub.name, phase=phase,
            nodes=len(rep.table), verified=rep.verified_count(),
            findings=rep.findings + findings))
    except (GraphVerifyError, ShardCheckError) as e:
        records.append(make_record(
            "graph_verify_error", subgraph=sub.name, phase=phase,
            kind=getattr(e, "kind", "unknown"),
            node=getattr(getattr(e, "node", None), "name", None),
            error=str(e)))
        emit_records(records)
        raise
    emit_records(records)
    return records


def validate_executor_build(executor):
    """Executor.__init__ hook: verify every named subgraph with the
    shapes known pre-feed.  Raises GraphVerifyError/ShardCheckError on
    the first defect (no jit traceback, no chip allocation)."""
    if not validation_enabled():
        return None
    out = []
    for sub in executor.subexecutor.values():
        out += _validate_sub(executor, sub, phase="build")
    return out


def validate_subgraph_feeds(executor, sub, feeds):
    """SubExecutor.run hook, once per NEW feed signature (the call
    sites gate on compile-cache misses): re-verify with concrete feed
    shapes so feed-dependent mismatches fail before the trace."""
    if not validation_enabled():
        return None
    return _validate_sub(executor, sub, phase="feeds", feeds=feeds)


# --------------------------------------------------------------------- #
# serving
# --------------------------------------------------------------------- #

def validate_serving(params, config, name, mesh=None):
    """ServingEngine build hook: params/config consistency before any
    cache allocation or compile.  Uses the same error/record contract
    as the graph path."""
    if not validation_enabled():
        return None
    records = []
    try:
        H = int(config.hidden_size)
        heads = int(config.num_attention_heads)
        if H % heads != 0:
            raise ShardCheckError(
                f"serving config: hidden_size {H} is not divisible by "
                f"num_attention_heads {heads}", kind="divisibility")
        wte = params.get(f"{name}_wte_table")
        if wte is None:
            raise GraphVerifyError(
                f"serving params: missing {name}_wte_table (model "
                f"prefix {name!r}; params hold "
                f"{sorted(params)[:8]}...)", kind="shape")
        if tuple(wte.shape)[1] != H:
            raise GraphVerifyError(
                f"serving params: {name}_wte_table has embed dim "
                f"{tuple(wte.shape)[1]}, config.hidden_size is {H}",
                kind="shape")
        wpe = params.get(f"{name}_wpe")
        if wpe is not None:
            if tuple(wpe.shape)[1] != H:
                raise GraphVerifyError(
                    f"serving params: {name}_wpe embed dim "
                    f"{tuple(wpe.shape)[1]} != hidden_size {H}",
                    kind="shape")
            if tuple(wpe.shape)[0] < int(config.max_position_embeddings):
                raise GraphVerifyError(
                    f"serving params: {name}_wpe covers "
                    f"{tuple(wpe.shape)[0]} positions, config asks "
                    f"{int(config.max_position_embeddings)}",
                    kind="shape")
        # MoE serving configs (models/moe_decode.py): every MoE block
        # must carry the gate + stacked expert weights with the expert
        # count the config declares — a per-expert leaf with the wrong
        # leading dim is exactly the corrupt rolling-swap payload the
        # PR 15 shape validation exists to catch, so catch it at build
        # too
        from ..models.moe_decode import moe_spec_of
        spec = moe_spec_of(config)
        if spec is not None:
            E = spec.num_experts
            for i in range(int(config.num_hidden_layers)):
                if not spec.is_moe_layer(i):
                    continue
                us = f"{name}_h{i}"
                gate = params.get(f"{us}_moe_gate_weight")
                w1 = params.get(f"{us}_moe_expert_stack_w1")
                w2 = params.get(f"{us}_moe_expert_stack_w2")
                for leaf, v in (("moe_gate_weight", gate),
                                ("moe_expert_stack_w1", w1),
                                ("moe_expert_stack_w2", w2)):
                    if v is None:
                        raise GraphVerifyError(
                            f"serving params: MoE layer {i} is missing "
                            f"{us}_{leaf} (config routes every "
                            f"{spec.moe_every}th block through "
                            f"{E} experts)", kind="shape")
                if tuple(gate.shape) != (H, E):
                    raise GraphVerifyError(
                        f"serving params: {us}_moe_gate_weight has "
                        f"shape {tuple(gate.shape)}, config wants "
                        f"({H}, {E})", kind="shape")
                for leaf, v, dim, want in (
                        ("moe_expert_stack_w1", w1, 0, E),
                        ("moe_expert_stack_w2", w2, 0, E),
                        ("moe_expert_stack_w1", w1, 1, H),
                        ("moe_expert_stack_w2", w2, 2, H)):
                    if tuple(v.shape)[dim] != want:
                        raise GraphVerifyError(
                            f"serving params: {us}_{leaf} dim {dim} is "
                            f"{tuple(v.shape)[dim]}, config wants "
                            f"{want} (shape {tuple(v.shape)})",
                            kind="shape")
        dtypes = sorted({str(v.dtype) for v in params.values()
                         if hasattr(v, "dtype")})
        records.append(make_record(
            "serving_verified", model=name, params=len(params),
            hidden=H, heads=heads, dtypes=dtypes,
            moe=(None if spec is None else
                 {"experts": spec.num_experts, "top_k": spec.top_k,
                  "moe_every": spec.moe_every})))
    except (GraphVerifyError, ShardCheckError) as e:
        records.append(make_record(
            "graph_verify_error", model=name, phase="serving",
            kind=getattr(e, "kind", "unknown"), error=str(e)))
        emit_records(records)
        raise
    emit_records(records)
    return records
