"""Recompile sentinel: the "ONE compiled core" claim, asserted.

The serving engine's perf story leans on two compile-count claims that
were, until now, untested as claims: PR 15's rolling weight swap is
"no recompile" (every jitted step takes the param dict as an argument,
so a swap must not grow any jit cache), and PR 18's mixed-mode ragged
dispatch serves a whole mixed trace through ONE compiled kernel per
(bucket, config) signature.  A silent regression — a shape leaking
into a static argument, a dtype flapping between waves — shows up only
as a mysterious slowdown on chip.

This module makes the claim checkable in milliseconds on CPU:

- every ``ServingEngine`` registers its jitted step functions here at
  build when ``HETU_VALIDATE=1`` (the same gate as the graph verifier:
  zero presence in production paths);
- :func:`snapshot` reads each function's jit-cache entry count
  (``jitted._cache_size()``); :func:`assert_no_recompile` diffs two
  snapshots and raises :class:`JitAuditError` naming every function
  whose cache GREW — serving the same traffic twice, or swapping
  weights, must be a no-op diff;
- when the running jax exposes ``jax.monitoring`` event listeners, a
  process-wide compile counter (``jit.compiles`` in the metrics
  registry) is kept as corroborating telemetry.

``tests/test_jit_audit.py`` is the regression gate; suite stage 00k
runs the same check before chip time.
"""

from __future__ import annotations

import weakref

from .. import envvars

__all__ = ["JitAuditError", "register_engine", "registered",
           "snapshot", "assert_no_recompile", "install_monitor",
           "compiles", "reset"]

# the jitted-step attributes an engine may carry (absent/None skipped)
_ENGINE_FNS = ("_prefill", "_prefill_chunk", "_prefill_batch",
               "_decode", "_mixed", "_verify", "_propose",
               "_draft_prefill")

_ENGINES: list = []       # [(label, weakref-to-engine)]
_N_REGISTERED = 0
_MONITOR = {"installed": False, "compiles": 0}


class JitAuditError(RuntimeError):
    """A jit cache grew where the engine contract says it must not."""


def register_engine(engine, label=None):
    """Track an engine's jitted step functions (weakly — a retired
    replica drops out of the audit with its last reference).  Called by
    ``ServingEngine.__init__`` under ``HETU_VALIDATE=1``."""
    global _N_REGISTERED
    _N_REGISTERED += 1
    if label is None:
        label = f"{getattr(engine, '_name', 'engine')}#{_N_REGISTERED}"
    _ENGINES.append((label, weakref.ref(engine)))
    return label


def registered() -> list:
    """Labels of engines still alive in the audit."""
    return [lbl for lbl, ref in _ENGINES if ref() is not None]


def _cache_size(fn):
    try:
        return int(fn._cache_size())
    except Exception:
        return None


def snapshot() -> dict:
    """{"<label>.<attr>": jit-cache entry count} over every live
    registered engine (functions without a readable cache skipped)."""
    out = {}
    for label, ref in _ENGINES:
        engine = ref()
        if engine is None:
            continue
        for attr in _ENGINE_FNS:
            fn = getattr(engine, attr, None)
            if fn is None:
                continue
            n = _cache_size(fn)
            if n is not None:
                out[f"{label}.{attr}"] = n
    return out


def assert_no_recompile(before, after=None, context=""):
    """Raise :class:`JitAuditError` for every jitted step whose cache
    grew between the two snapshots; returns ``after``.

    New keys in ``after`` (an engine built between snapshots) are not
    recompiles; keys that vanished (engine retired) are ignored."""
    if after is None:
        after = snapshot()
    grew = [(k, before[k], after[k])
            for k in before if k in after and after[k] > before[k]]
    if grew:
        where = f" during {context}" if context else ""
        detail = "; ".join(f"{k}: {a} -> {b} cache entries"
                           for k, a, b in grew)
        raise JitAuditError(
            f"jit recompile{where}: {detail} — the engine contract is "
            f"ONE compile per (bucket, config) signature; a growing "
            f"cache means a shape/dtype/static-arg leaked into the "
            f"dispatch (or a weight swap stopped being swap-in-place)")
    return after


def install_monitor():
    """Best-effort process-wide compile counter via ``jax.monitoring``
    (newer jax only; silently absent elsewhere).  Idempotent."""
    if _MONITOR["installed"]:
        return True
    try:
        from jax import monitoring

        def _on_event(event, **kw):
            if "compil" in str(event):
                _MONITOR["compiles"] += 1
                try:
                    from ..telemetry.metrics import REGISTRY
                    REGISTRY.counter("jit.compiles").inc()
                except Exception:
                    pass

        monitoring.register_event_listener(_on_event)
        _MONITOR["installed"] = True
        return True
    except Exception:
        return False


def compiles() -> int:
    """Compiles seen by the monitor since install (0 if unavailable)."""
    return _MONITOR["compiles"]


def reset():
    """Forget registered engines (test isolation; the monitor and its
    counter persist — listeners cannot be unregistered)."""
    global _N_REGISTERED
    _ENGINES.clear()
    _N_REGISTERED = 0


def enabled() -> bool:
    """Mirror of the validate gate the engine wiring checks."""
    return envvars.get_bool("HETU_VALIDATE")
