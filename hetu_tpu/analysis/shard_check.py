"""Static parallelism checker: graph + mesh + plan, validated pre-chip.

Auto-parallel systems (Galvatron, Alpa — PAPERS.md) validate a
placement/partition plan BEFORE committing it to devices; hand-written
plans deserve the same guarantee.  Today a bad mesh axis, a non-divisible
dp/tp split, or an uneven pipeline assignment only fails on-chip —
where a debug cycle costs a TPU allocation.  Everything here runs on the
host in microseconds:

- :func:`check_mesh_axes` — every comm op (``graph/ops_comm.py``)
  references an axis that exists in the mesh (``parallel/mesh.py``).
- :func:`check_divisibility` — parameter sharding specs and batch feeds
  divide evenly over their mesh axes (batch/heads/vocab vs dp/tp).
- :func:`check_pipeline_stages` — the pipeline partitioner's stage plan
  is sound for the requested stage count: a uniform body exists, and the
  layer count splits evenly over the stages.
- :func:`check_stage_assignment` — explicit per-node stage maps are
  contiguous and monotone, with cross-stage edges only through comm ops
  (the reference's PipelineSend/Receive boundary invariant).
- :func:`check_collective_order_static` — per-group collective sequences
  agree (the build-time sibling of ``parallel/collective_check.py``,
  which needs a traced shard_map program; this one needs only the graph).
- :func:`check_quantized_collectives` — every quantized collective is a
  complete quantize→collective→dequantize trio on one axis (a quantize
  without its paired dequantize across the collective is rejected
  before compile — the HETU_COMM_QUANT pair contract).
- :func:`check_expert_mesh` — MoE expert-parallel placement: the expert
  mesh axis exists and num_experts divides evenly across it (the
  ``ep_shard_params``/MoE-serving gate).
- :func:`check_expert_alltoall` — expert dispatch/combine all-to-all
  pairing (the quant-pair analog): every capacity dispatch reaches a
  weighted combine, the exchanges between them come in matched pairs on
  one agreed axis — an odd or axis-mixed exchange chain leaves tokens
  on the wrong device.

:func:`check_parallelism` is the umbrella the executor wires in under
``HETU_VALIDATE=1``: hard violations raise :class:`ShardCheckError`;
advisory ones come back as findings dicts.
"""

from __future__ import annotations

from ..graph.node import Op
from ..graph.ops_comm import (CollectiveOp, DequantizeCommOp,
                              PipelineReceiveOp, PipelineSendOp,
                              QuantAllReduceCommunicateOp, QuantizeCommOp)
from ..graph.ops_misc import PlaceholderOp


class ShardCheckError(Exception):
    """A statically-detected parallelism misconfiguration.  ``node`` is
    the offending Op when attributable; ``kind`` one of ``mesh_axis``,
    ``divisibility``, ``pipeline``, ``stage_assignment``,
    ``collective_order``."""

    def __init__(self, message, node=None, kind="mesh_axis"):
        super().__init__(message)
        self.node = node
        self.kind = kind


# --------------------------------------------------------------------- #
# MoE expert-parallel placement (ISSUE 20; Synthesizing Optimal
# Parallelism Placement — PAPERS.md — grounds the layout choices)
# --------------------------------------------------------------------- #

def check_expert_mesh(mesh, num_experts, axis="ep"):
    """Validate an expert-parallel placement BEFORE any device_put or
    compile: the expert ``axis`` must exist in ``mesh`` and
    ``num_experts`` must divide evenly across it (each shard owns
    E/size whole experts — a ragged split would misalign every
    dispatch/combine all-to-all block).  Raises
    ShardCheckError(kind='expert_mesh'); returns the axis size."""
    if mesh is None:
        raise ShardCheckError(
            "expert-parallel placement needs a mesh (got None)",
            kind="expert_mesh")
    names = tuple(mesh.axis_names)
    if axis not in names:
        raise ShardCheckError(
            f"expert mesh axis {axis!r} absent from mesh axes {names} "
            f"— the expert stacks would silently replicate and the "
            f"dispatch all-to-all would no-op", kind="expert_mesh")
    size = dict(zip(names, mesh.devices.shape))[axis]
    if num_experts % size != 0:
        raise ShardCheckError(
            f"num_experts={num_experts} is not divisible by expert "
            f"mesh axis {axis!r} (size {size}) — each shard must own "
            f"E/size whole experts for the a2a block layout to hold",
            kind="expert_mesh")
    return size


def check_expert_alltoall(eval_nodes):
    """Expert dispatch/combine all-to-all pairing — the quant-pair
    analog for MoE graphs (``layers/moe.py`` emits
    LayoutTransform → a2a → expert FFN → a2a → ReverseLayoutTransform):

    - every capacity dispatch (``LayoutTransformOp``) must reach a
      weighted combine (a ``ReverseLayoutTransform*`` descendant) —
      an uncombined dispatch leaves expert-major capacity buffers in
      the graph exactly like a quantize without its dequantize;
    - every combine must descend from a dispatch (its
      indices/locations are meaningless otherwise);
    - the exchanges BETWEEN a dispatch and its combine must come in
      matched pairs (dispatch-side + return-side) — an odd count ends
      the combine on the wrong device's rows;
    - all exchanges in one dispatch↔combine span agree on the axis.

    Raises ShardCheckError(kind='a2a_pair'); returns the
    (dispatch, [a2a...], combine) spans found."""
    from ..graph.ops_moe import (AllToAllOp, HAllToAllOp,
                                 LayoutTransformOp)
    topo = _topo_of(eval_nodes)
    anc = {}
    for n in topo:
        s = set()
        for i in n.inputs:
            s.add(id(i))
            s |= anc.get(id(i), set())
        anc[id(n)] = s

    def _axes(n):
        return (tuple(n.axes) if isinstance(n, HAllToAllOp)
                else (n.axis,))

    a2a = [n for n in topo if isinstance(n, (AllToAllOp, HAllToAllOp))]
    disp = [n for n in topo if isinstance(n, LayoutTransformOp)]
    comb = [n for n in topo
            if type(n).__name__.startswith("ReverseLayoutTransform")
            and "Gradient" not in type(n).__name__]
    spans = []
    for d in disp:
        outs = [c for c in comb if id(d) in anc[id(c)]]
        if not outs:
            raise ShardCheckError(
                f"expert dispatch {d.name} has no paired "
                f"ReverseLayoutTransform combine downstream — the "
                f"capacity buffers never return to token order (the "
                f"a2a analog of a quantize without its dequantize)",
                node=d, kind="a2a_pair")
        for c in outs:
            between = [a for a in a2a
                       if id(d) in anc[id(a)] and id(a) in anc[id(c)]]
            if len(between) % 2 != 0:
                raise ShardCheckError(
                    f"expert dispatch {d.name} -> combine {c.name} "
                    f"crosses {len(between)} all-to-all exchange(s) — "
                    f"exchanges must pair (dispatch-side + "
                    f"return-side); an odd chain combines another "
                    f"device's expert rows", node=c, kind="a2a_pair")
            ax = {_axes(a) for a in between}
            if len(ax) > 1:
                raise ShardCheckError(
                    f"expert dispatch {d.name} -> combine {c.name} "
                    f"mixes all-to-all axes {sorted(ax)} — the return "
                    f"exchange must undo the dispatch exchange on the "
                    f"SAME axis", node=c, kind="a2a_pair")
            spans.append((d, between, c))
    for c in comb:
        if not any(id(d) in anc[id(c)] for d in disp):
            raise ShardCheckError(
                f"expert combine {c.name} has no dispatch ancestor — "
                f"its indices/locations never routed these rows",
                node=c, kind="a2a_pair")
    return spans


# --------------------------------------------------------------------- #
# quantized-collective pairing (HETU_COMM_QUANT pairs; EQuARX lineage)
# --------------------------------------------------------------------- #

def check_quantized_collectives(eval_nodes):
    """Every quantized collective must be a complete, axis-consistent
    quantize→collective→dequantize trio (``graph/ops_comm``):

    - a ``QuantizeCommOp``'s output feeds ONLY quantized collectives
      (its (int8, scales) pair is meaningless to any other consumer, and
      a quantize whose pair never crosses a collective + dequantize
      would silently hand int8 garbage downstream);
    - a ``QuantAllReduceCommunicateOp`` takes exactly a quantize and
      feeds only dequantizes;
    - a ``DequantizeCommOp`` decodes exactly a quantized collective;
    - all three agree on the mesh axis.

    Raises ShardCheckError(kind='quant_pair'); returns the trios found
    as [(quantize, collective, dequantize), ...]."""
    topo = _topo_of(eval_nodes)
    consumers = {}
    for n in topo:
        for i in n.inputs:
            consumers.setdefault(id(i), []).append(n)
    trios = []
    for n in topo:
        if isinstance(n, QuantizeCommOp):
            cons = consumers.get(id(n), [])
            bad = [c for c in cons
                   if not isinstance(c, QuantAllReduceCommunicateOp)]
            if bad or not cons:
                raise ShardCheckError(
                    f"quantize {n.name} has no paired dequantize across "
                    f"a quantized collective: consumed by "
                    f"{[c.name for c in bad] or 'nothing'} — emit the "
                    f"trio via quantized_allreduce_op (the (int8, "
                    f"scales) pair must cross a "
                    f"QuantAllReduceCommunicateOp into a "
                    f"DequantizeCommOp)", node=n, kind="quant_pair")
        elif isinstance(n, QuantAllReduceCommunicateOp):
            src = n.inputs[0]
            if not isinstance(src, QuantizeCommOp):
                raise ShardCheckError(
                    f"quantized collective {n.name} consumes "
                    f"{src.name} ({type(src).__name__}), not a "
                    f"QuantizeCommOp — all_gathering raw f32 through "
                    f"the quantized pair moves full-width bytes and "
                    f"breaks the dequantize contract", node=n,
                    kind="quant_pair")
            cons = consumers.get(id(n), [])
            deqs = [c for c in cons if isinstance(c, DequantizeCommOp)]
            if not deqs or len(deqs) != len(cons):
                others = [c.name for c in cons
                          if not isinstance(c, DequantizeCommOp)]
                raise ShardCheckError(
                    f"quantized collective {n.name} (axis {n.axis!r}) "
                    f"has no paired DequantizeCommOp"
                    + (f"; consumed by {others}" if others else "")
                    + " — a quantize without its dequantize across the "
                    "collective leaves int8 payloads in the graph",
                    node=n, kind="quant_pair")
            for d in deqs + [src]:
                if getattr(d, "axis", n.axis) != n.axis:
                    raise ShardCheckError(
                        f"quantized trio disagrees on the mesh axis: "
                        f"{src.name}/{n.name}/{[x.name for x in deqs]} "
                        f"mix {d.axis!r} and {n.axis!r}", node=n,
                        kind="quant_pair")
            for d in deqs:
                trios.append((src, n, d))
        elif isinstance(n, DequantizeCommOp):
            src = n.inputs[0]
            if not isinstance(src, QuantAllReduceCommunicateOp):
                raise ShardCheckError(
                    f"dequantize {n.name} consumes {src.name} "
                    f"({type(src).__name__}), not a quantized "
                    f"collective — the pair must cross the collective",
                    node=n, kind="quant_pair")
    return trios


def _comm_nodes(topo):
    return [n for n in topo
            if isinstance(n, (CollectiveOp, PipelineSendOp,
                              PipelineReceiveOp))]


def _topo_of(eval_nodes):
    from ..graph.autodiff import find_topo_sort
    return find_topo_sort([n for n in eval_nodes if n is not None])


# --------------------------------------------------------------------- #
# mesh-axis existence
# --------------------------------------------------------------------- #

def check_mesh_axes(eval_nodes, mesh):
    """Every comm op's axis must name a mesh axis.  Under a shard_map
    trace a missing axis is a NameError deep in jax; under pjit it makes
    the op silently a no-op — either way the plan is wrong.  Skipped
    when there is no mesh (pure single-device jit: comm ops are
    documented identities there)."""
    if mesh is None:
        return []
    axes = set(mesh.axis_names)
    comm = _comm_nodes(_topo_of(eval_nodes))
    for n in comm:
        axis = getattr(n, "axis", None)
        if axis is not None and axis not in axes:
            raise ShardCheckError(
                f"comm op {n.name} ({type(n).__name__}) references mesh "
                f"axis {axis!r} but the mesh has axes "
                f"{tuple(mesh.axis_names)} — the collective would "
                f"silently no-op under pjit and NameError under "
                f"shard_map", node=n, kind="mesh_axis")
    return comm


# --------------------------------------------------------------------- #
# divisibility (dp/tp splits)
# --------------------------------------------------------------------- #

def check_divisibility(eval_nodes, mesh, feed_shapes=None):
    """Sharding specs must divide their dims; returns advisory findings
    for feeds that will silently fall back to replication.

    Hard errors: a variable's ``sharding_spec`` names a missing mesh
    axis, or shards a dim the axis size does not divide (GSPMD would
    reject the NamedSharding at placement — on-chip).  Advisory: a
    batch feed whose dim 0 the 'dp' axis does not divide (the executor
    silently replicates it, usually a misconfigured global batch)."""
    findings = []
    if mesh is None:
        return findings
    topo = _topo_of(eval_nodes)
    shape_by_axis = dict(zip(mesh.axis_names,
                             mesh.devices.shape))
    for n in topo:
        if not isinstance(n, PlaceholderOp):
            continue
        spec = getattr(n, "sharding_spec", None)
        if spec is None or n.shape is None:
            continue
        for dim, entry in enumerate(tuple(spec)):
            if entry is None:
                continue
            for axis in (entry if isinstance(entry, (tuple, list))
                         else (entry,)):
                size = shape_by_axis.get(axis)
                if size is None:
                    raise ShardCheckError(
                        f"variable {n.name!r} sharding_spec {spec} "
                        f"names axis {axis!r} absent from mesh axes "
                        f"{tuple(mesh.axis_names)}", node=n,
                        kind="divisibility")
                if dim >= len(n.shape) or n.shape[dim] % size != 0:
                    dim_sz = n.shape[dim] if dim < len(n.shape) else None
                    raise ShardCheckError(
                        f"variable {n.name!r} dim {dim} (size {dim_sz}) "
                        f"is not divisible by mesh axis {axis!r} "
                        f"(size {size}) — sharding_spec {spec} cannot "
                        f"be placed", node=n, kind="divisibility")
    dp = shape_by_axis.get("dp")
    if dp and dp > 1:
        for name, shape in (feed_shapes or {}).items():
            if shape and len(shape) >= 1 and shape[0] % dp != 0:
                findings.append({
                    "kind": "feed_not_dp_divisible", "node": name,
                    "detail": f"batch dim {shape[0]} % dp {dp} != 0; "
                              f"the feed will be replicated, not "
                              f"sharded"})
    return findings


# --------------------------------------------------------------------- #
# pipeline stage plans
# --------------------------------------------------------------------- #

def check_pipeline_stages(loss, num_stages, mesh=None, pipeline=None):
    """Validate the pipeline partition of ``loss`` for ``num_stages``.

    Hard error: the graph has a uniform repeated body of R units but
    R % num_stages != 0 (uneven stages: the trimmed units silently pile
    into the 'pre' stage, skewing the balance the schedule assumes).
    Advisory finding: no uniform body at all (the executor falls back to
    the trajectory-equivalent microbatch-scan path — correct, but the
    'pp' mesh axis buys nothing)."""
    findings = []
    S = int(num_stages or (mesh.shape.get("pp", 1)
                           if mesh is not None else 1))
    if S <= 1:
        return findings
    from ..parallel.partition import (find_cuts, _find_periodic_body,
                                      _make_blocks)
    from ..graph.autodiff import find_topo_sort
    topo = find_topo_sort([loss])
    blocks = _make_blocks(topo, find_cuts(topo))
    run = _find_periodic_body(blocks, 2)
    if run is None:
        findings.append({
            "kind": "pipeline_no_uniform_body", "node": loss.name,
            "detail": f"no uniform repeated body found for "
                      f"{S}-stage pipelining; the microbatch-scan "
                      f"fallback will run without stage parallelism"})
        return findings
    _, units, _ = run
    if units < S:
        raise ShardCheckError(
            f"pipeline plan for {loss.name!r}: only {units} uniform "
            f"body unit(s) for {S} stages — at least one stage would "
            f"be empty", node=loss, kind="pipeline")
    if units % S != 0:
        raise ShardCheckError(
            f"pipeline plan for {loss.name!r}: {units} uniform body "
            f"units do not split evenly over {S} stages "
            f"({units} % {S} = {units % S}) — the surplus layers would "
            f"silently fold into the pre-stage and unbalance the "
            f"schedule; use a layer count divisible by num_stages",
            node=loss, kind="pipeline")
    if pipeline not in (None, "gpipe", "1f1b", "pipedream", "hetpipe"):
        raise ShardCheckError(
            f"unknown pipeline mode {pipeline!r}", kind="pipeline")
    return findings


def check_stage_assignment(eval_nodes, stage_of, num_stages=None):
    """Validate an EXPLICIT node -> stage map (hand-written plans).

    - stage ids form a contiguous 0..S-1 range (no empty stages);
    - monotone: a consumer's stage >= every producer's stage
      (activations only flow forward);
    - cross-stage edges go ONLY through pipeline comm ops
      (PipelineSend/PipelineReceive) and advance exactly one stage —
      the reference's single-tensor boundary invariant.

    ``stage_of`` maps node or node-name -> int stage."""
    topo = _topo_of(eval_nodes)

    def stage(n):
        if n in stage_of:
            return stage_of[n]
        return stage_of.get(n.name)

    used = sorted({s for s in (stage(n) for n in topo) if s is not None})
    if not used:
        return []
    S = int(num_stages or (max(used) + 1))
    if used != list(range(S)):
        missing = sorted(set(range(S)) - set(used))
        raise ShardCheckError(
            f"stage assignment uses stages {used} of 0..{S - 1}: "
            f"stage(s) {missing} are empty — assignments must be "
            f"contiguous", kind="stage_assignment")
    for n in topo:
        s_n = stage(n)
        if s_n is None:
            continue
        for inp in n.inputs:
            s_i = stage(inp)
            if s_i is None or s_i == s_n:
                continue
            if s_i > s_n:
                raise ShardCheckError(
                    f"stage assignment is not monotone: {n.name} "
                    f"(stage {s_n}) consumes {inp.name} (stage {s_i}) "
                    f"— activations cannot flow backward",
                    node=n, kind="stage_assignment")
            is_comm = isinstance(n, (PipelineReceiveOp, PipelineSendOp)) \
                or isinstance(inp, (PipelineSendOp, PipelineReceiveOp))
            if not is_comm:
                raise ShardCheckError(
                    f"cross-stage edge {inp.name} (stage {s_i}) -> "
                    f"{n.name} (stage {s_n}) bypasses the pipeline comm "
                    f"ops — only PipelineSend/PipelineReceive may cross "
                    f"a stage boundary", node=n, kind="stage_assignment")
            if s_n - s_i != 1:
                raise ShardCheckError(
                    f"cross-stage edge {inp.name} -> {n.name} skips "
                    f"stages ({s_i} -> {s_n}) — pipeline transport is "
                    f"neighbor-to-neighbor", node=n,
                    kind="stage_assignment")
    return []


# --------------------------------------------------------------------- #
# static collective ordering
# --------------------------------------------------------------------- #

def collective_sequence(eval_nodes, axes=None):
    """The graph's comm-op sequence in topo order:
    [(op_class_name, axis), ...], optionally filtered to ``axes``.
    Under SPMD every device runs this same sequence — recording it makes
    divergence across separately-built per-stage/per-group programs
    checkable (:func:`check_collective_order_static`)."""
    seq = []
    for n in _comm_nodes(_topo_of(eval_nodes)):
        axis = getattr(n, "axis", None)
        if axes is None or axis in axes:
            seq.append((type(n).__name__, axis))
    return seq


def check_collective_order_static(group_sequences, axes=None):
    """Every mesh group must issue the SAME collective sequence, or the
    axis deadlocks (the static sibling of
    ``parallel.collective_check.check_collective_order``, for graphs
    built per group/stage rather than one traced shard_map program).

    ``group_sequences``: {group_name: sequence} where a sequence is
    either a node list (passed through :func:`collective_sequence`) or a
    pre-extracted [(op, axis), ...] list."""
    norm = {}
    for name, seq in group_sequences.items():
        if seq and isinstance(seq[0], Op):
            seq = collective_sequence(seq, axes=axes)
        elif axes is not None:
            seq = [(op, ax) for op, ax in seq if ax in axes]
        norm[name] = list(seq)
    names = list(norm)
    for other in names[1:]:
        if norm[other] != norm[names[0]]:
            raise ShardCheckError(
                f"collective sequences diverge across mesh groups: "
                f"{names[0]!r} issues {norm[names[0]] or 'none'} but "
                f"{other!r} issues {norm[other] or 'none'} — devices "
                f"disagreeing on the collective order deadlock the "
                f"axis", kind="collective_order")
    return norm[names[0]] if names else []


# --------------------------------------------------------------------- #
# umbrella
# --------------------------------------------------------------------- #

def check_parallelism(eval_nodes, mesh, config=None, feed_shapes=None):
    """Run every static parallelism check that applies to this graph +
    mesh + config.  Raises :class:`ShardCheckError` on hard violations;
    returns advisory findings."""
    eval_nodes = [n for n in eval_nodes if n is not None]
    findings = []
    check_mesh_axes(eval_nodes, mesh)
    check_quantized_collectives(eval_nodes)
    if mesh is not None:
        # the dispatch/combine pairing rule only bites under a parallel
        # mesh — a mesh-less executor may legitimately evaluate a bare
        # LayoutTransform (e.g. to inspect the capacity buffer directly)
        check_expert_alltoall(eval_nodes)
    findings += check_divisibility(eval_nodes, mesh,
                                   feed_shapes=feed_shapes)
    if config is not None and getattr(config, "pipeline", None):
        from ..optimizer import OptimizerOp
        S = getattr(config, "num_stages", None) or (
            mesh.shape.get("pp", 1) if mesh is not None else 1)
        losses = [n for n in eval_nodes
                  if not isinstance(n, OptimizerOp)]
        has_opt = any(isinstance(n, OptimizerOp) for n in eval_nodes)
        if has_opt and len(losses) == 1 and S and S > 1:
            findings += check_pipeline_stages(
                losses[0], S, mesh=mesh,
                pipeline=getattr(config, "pipeline", None))
    return findings
