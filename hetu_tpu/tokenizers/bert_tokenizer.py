"""BERT wordpiece tokenizer (reference tokenizers/bert_tokenizer.py).

Pure-python, offline: `from_pretrained` resolves only local vocab files
(the reference downloads from S3, bert_tokenizer.py:122-158; this build has
no egress, so pass a path).  Algorithmic behavior matches the reference:
basic tokenization (lowercase, accent stripping, punctuation splitting,
CJK spacing, control-char cleaning) followed by greedy longest-match-first
wordpiece with '##' continuation prefixes.
"""

from __future__ import annotations

import collections
import os
import unicodedata


def load_vocab(vocab_file):
    """vocab file: one token per line -> OrderedDict token -> id.

    Ids are assigned sequentially per line (reference
    bert_tokenizer.py:52-64) so they match the embedding rows a checkpoint
    was trained with; tokens are whitespace-stripped so CRLF files load
    correctly."""
    vocab = collections.OrderedDict()
    with open(vocab_file, "r", encoding="utf-8") as f:
        for idx, line in enumerate(f):
            token = line.strip()
            vocab[token] = idx
    # a trailing newline yields one empty token; drop it unless the file
    # really maps "" (it never does in practice)
    vocab.pop("", None)
    return vocab


def whitespace_tokenize(text):
    text = text.strip()
    return text.split() if text else []


def _is_whitespace(char):
    if char in (" ", "\t", "\n", "\r"):
        return True
    return unicodedata.category(char) == "Zs"


def _is_control(char):
    if char in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(char).startswith("C")


def _is_punctuation(char):
    cp = ord(char)
    # ASCII non-alphanumeric ranges count as punctuation (reference
    # bert_tokenizer.py:350-363)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(char).startswith("P")


class BasicTokenizer:
    """Whitespace/punctuation/accent/CJK normalization pass."""

    def __init__(self, do_lower_case=True,
                 never_split=("[UNK]", "[SEP]", "[PAD]", "[CLS]",
                              "[MASK]")):
        self.do_lower_case = do_lower_case
        self.never_split = set(never_split)

    def tokenize(self, text):
        text = self._clean_text(text)
        text = self._tokenize_chinese_chars(text)
        out = []
        for token in whitespace_tokenize(text):
            if token in self.never_split:
                out.append(token)
                continue
            if self.do_lower_case:
                token = self._run_strip_accents(token.lower())
            out.extend(self._run_split_on_punc(token))
        return whitespace_tokenize(" ".join(out))

    def _run_strip_accents(self, text):
        text = unicodedata.normalize("NFD", text)
        return "".join(c for c in text
                       if unicodedata.category(c) != "Mn")

    def _run_split_on_punc(self, text):
        if text in self.never_split:
            return [text]
        out, word = [], []
        for char in text:
            if _is_punctuation(char):
                out.append(char)
                word = []
            else:
                if not word:
                    out.append("")
                word.append(char)
                out[-1] += char
        return [t for t in out if t]

    def _tokenize_chinese_chars(self, text):
        out = []
        for char in text:
            if self._is_chinese_char(ord(char)):
                out.append(f" {char} ")
            else:
                out.append(char)
        return "".join(out)

    @staticmethod
    def _is_chinese_char(cp):
        return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
                or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
                or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
                or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)

    def _clean_text(self, text):
        out = []
        for char in text:
            cp = ord(char)
            if cp == 0 or cp == 0xFFFD or _is_control(char):
                continue
            out.append(" " if _is_whitespace(char) else char)
        return "".join(out)


class WordpieceTokenizer:
    """Greedy longest-match-first subword split (reference :270-324)."""

    def __init__(self, vocab, unk_token="[UNK]",
                 max_input_chars_per_word=100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_input_chars_per_word = max_input_chars_per_word

    def tokenize(self, text):
        out = []
        for token in whitespace_tokenize(text):
            chars = list(token)
            if len(chars) > self.max_input_chars_per_word:
                out.append(self.unk_token)
                continue
            is_bad, start, sub_tokens = False, 0, []
            while start < len(chars):
                end = len(chars)
                cur = None
                while start < end:
                    substr = "".join(chars[start:end])
                    if start > 0:
                        substr = "##" + substr
                    if substr in self.vocab:
                        cur = substr
                        break
                    end -= 1
                if cur is None:
                    is_bad = True
                    break
                sub_tokens.append(cur)
                start = end
            out.extend([self.unk_token] if is_bad else sub_tokens)
        return out


class BertTokenizer:
    """End-to-end BERT tokenizer (reference :76-158)."""

    def __init__(self, vocab_file, do_lower_case=True, max_len=None,
                 never_split=("[UNK]", "[SEP]", "[PAD]", "[CLS]",
                              "[MASK]")):
        if not os.path.isfile(vocab_file):
            raise ValueError(f"vocab file not found: {vocab_file}")
        self.vocab = load_vocab(vocab_file)
        self.ids_to_tokens = {v: k for k, v in self.vocab.items()}
        self.basic_tokenizer = BasicTokenizer(
            do_lower_case=do_lower_case, never_split=never_split)
        self.wordpiece_tokenizer = WordpieceTokenizer(vocab=self.vocab)
        self.max_len = max_len if max_len is not None else int(1e12)

    def tokenize(self, text):
        tokens = []
        for token in self.basic_tokenizer.tokenize(text):
            tokens.extend(self.wordpiece_tokenizer.tokenize(token))
        return tokens

    def convert_tokens_to_ids(self, tokens):
        ids = [self.vocab[t] if t in self.vocab
               else self.vocab.get("[UNK]", 0) for t in tokens]
        if len(ids) > self.max_len:
            raise ValueError(
                f"sequence length {len(ids)} > max_len {self.max_len}")
        return ids

    def convert_ids_to_tokens(self, ids):
        return [self.ids_to_tokens[i] for i in ids]

    def encode(self, text_a, text_b=None, max_length=None, pad=True):
        """[CLS] a [SEP] (b [SEP]) with token_type ids + mask — the input
        recipe of examples/nlp/bert."""
        ta = self.tokenize(text_a)
        tb = self.tokenize(text_b) if text_b else []
        max_length = max_length or self.max_len
        budget = max_length - (3 if tb else 2)
        while len(ta) + len(tb) > budget:
            (ta if len(ta) >= len(tb) else tb).pop()
        tokens = ["[CLS]"] + ta + ["[SEP]"]
        types = [0] * len(tokens)
        if tb:
            tokens += tb + ["[SEP]"]
            types += [1] * (len(tb) + 1)
        ids = self.convert_tokens_to_ids(tokens)
        mask = [1] * len(ids)
        if pad:
            pad_id = self.vocab.get("[PAD]", 0)
            while len(ids) < max_length:
                ids.append(pad_id)
                types.append(0)
                mask.append(0)
        return {"input_ids": ids, "token_type_ids": types,
                "attention_mask": mask}

    @classmethod
    def from_pretrained(cls, vocab_path, **kwargs):
        """Local path only (no egress): a vocab.txt file or a directory
        containing one."""
        if os.path.isdir(vocab_path):
            vocab_path = os.path.join(vocab_path, "vocab.txt")
        return cls(vocab_path, **kwargs)
