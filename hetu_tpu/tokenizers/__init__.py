"""Tokenizers (reference python/hetu/tokenizers/, 612 LoC)."""

from .bert_tokenizer import (BasicTokenizer, BertTokenizer,
                             WordpieceTokenizer, load_vocab,
                             whitespace_tokenize)

__all__ = ["BertTokenizer", "BasicTokenizer", "WordpieceTokenizer",
           "load_vocab", "whitespace_tokenize"]
