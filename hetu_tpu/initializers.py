"""Initializers: constant/uniform/normal/truncated-normal + Xavier/He/Lecun.

Reference: python/hetu/initializers.py (BaseInit:9, ConstantInit:42, ...,
factory helpers at bottom; `init_on_ps` variant at :28-38 initializes on the
parameter server — here PS-resident embedding tables reuse the same
generator seeded identically on the server process).

Each initializer is a value *spec*; generation happens once on host via
jax.random with a key folded with the variable's node id, so multi-process
replicas initialize identically (replacing the reference's seed + node.id
scheme, initializers.py:14).
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from .graph.ops_misc import PlaceholderOp


class BaseInit:
    def __init__(self, shape):
        self.shape = tuple(int(s) for s in shape)

    def generate(self, key, dtype=jnp.float32):
        raise NotImplementedError


class ConstantInit(BaseInit):
    def __init__(self, constant, shape):
        super().__init__(shape)
        self.constant = constant

    def generate(self, key, dtype=jnp.float32):
        return jnp.full(self.shape, self.constant, dtype=dtype)


class ZerosInit(ConstantInit):
    def __init__(self, shape):
        super().__init__(0.0, shape)


class OnesInit(ConstantInit):
    def __init__(self, shape):
        super().__init__(1.0, shape)


class UniformInit(BaseInit):
    def __init__(self, low, high, shape):
        super().__init__(shape)
        self.low, self.high = low, high

    def generate(self, key, dtype=jnp.float32):
        return jax.random.uniform(key, self.shape, dtype=jnp.float32,
                                  minval=self.low, maxval=self.high).astype(dtype)


class NormalInit(BaseInit):
    def __init__(self, mean, stddev, shape):
        super().__init__(shape)
        self.mean, self.stddev = mean, stddev

    def generate(self, key, dtype=jnp.float32):
        return (self.mean + self.stddev *
                jax.random.normal(key, self.shape, dtype=jnp.float32)).astype(dtype)


class TruncatedNormalInit(BaseInit):
    def __init__(self, mean, stddev, shape):
        super().__init__(shape)
        self.mean, self.stddev = mean, stddev

    def generate(self, key, dtype=jnp.float32):
        x = jax.random.truncated_normal(key, -2.0, 2.0, self.shape,
                                        dtype=jnp.float32)
        return (self.mean + self.stddev * x).astype(dtype)


class ReversedTruncatedNormalInit(TruncatedNormalInit):
    pass


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv OIHW
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class XavierNormalInit(BaseInit):
    def __init__(self, shape, gain=1.0):
        super().__init__(shape)
        self.gain = gain

    def generate(self, key, dtype=jnp.float32):
        fan_in, fan_out = _fans(self.shape)
        std = self.gain * math.sqrt(2.0 / (fan_in + fan_out))
        return (std * jax.random.normal(key, self.shape, jnp.float32)).astype(dtype)


class XavierUniformInit(BaseInit):
    def __init__(self, shape, gain=1.0):
        super().__init__(shape)
        self.gain = gain

    def generate(self, key, dtype=jnp.float32):
        fan_in, fan_out = _fans(self.shape)
        limit = self.gain * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, self.shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class HeNormalInit(BaseInit):
    def generate(self, key, dtype=jnp.float32):
        fan_in, _ = _fans(self.shape)
        std = math.sqrt(2.0 / fan_in)
        return (std * jax.random.normal(key, self.shape, jnp.float32)).astype(dtype)


class HeUniformInit(BaseInit):
    def generate(self, key, dtype=jnp.float32):
        fan_in, _ = _fans(self.shape)
        limit = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, self.shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class LecunNormalInit(BaseInit):
    def generate(self, key, dtype=jnp.float32):
        fan_in, _ = _fans(self.shape)
        std = math.sqrt(1.0 / fan_in)
        return (std * jax.random.normal(key, self.shape, jnp.float32)).astype(dtype)


class LecunUniformInit(BaseInit):
    def generate(self, key, dtype=jnp.float32):
        fan_in, _ = _fans(self.shape)
        limit = math.sqrt(3.0 / fan_in)
        return jax.random.uniform(key, self.shape, jnp.float32,
                                  -limit, limit).astype(dtype)


# --------------------------------------------------------------------- #
# factory functions returning variable nodes (reference initializers.py
# bottom half; usage e.g. examples/cnn/models/ResNet.py:15)
# --------------------------------------------------------------------- #

def _var(init, name, trainable=True, ctx=None, dtype=jnp.float32):
    return PlaceholderOp(name, initializer=init, trainable=trainable,
                         ctx=ctx, dtype=dtype)


def constant(shape, fill_value=0.0, name="constant_init", trainable=True,
             ctx=None, dtype=jnp.float32):
    return _var(ConstantInit(fill_value, shape), name, trainable, ctx, dtype)


def zeros(shape, name="zeros_init", trainable=True, ctx=None, dtype=jnp.float32):
    return _var(ZerosInit(shape), name, trainable, ctx, dtype)


def ones(shape, name="ones_init", trainable=True, ctx=None, dtype=jnp.float32):
    return _var(OnesInit(shape), name, trainable, ctx, dtype)


def random_uniform(shape, minval=-0.05, maxval=0.05, name="uniform_init",
                   trainable=True, ctx=None, dtype=jnp.float32):
    return _var(UniformInit(minval, maxval, shape), name, trainable, ctx, dtype)


def random_normal(shape, mean=0.0, stddev=0.05, name="normal_init",
                  trainable=True, ctx=None, dtype=jnp.float32):
    return _var(NormalInit(mean, stddev, shape), name, trainable, ctx, dtype)


def truncated_normal(shape, mean=0.0, stddev=0.05, name="truncated_normal_init",
                     trainable=True, ctx=None, dtype=jnp.float32):
    return _var(TruncatedNormalInit(mean, stddev, shape), name, trainable, ctx, dtype)


def xavier_normal(shape, gain=1.0, name="xavier_normal_init", trainable=True,
                  ctx=None, dtype=jnp.float32):
    return _var(XavierNormalInit(shape, gain), name, trainable, ctx, dtype)


def xavier_uniform(shape, gain=1.0, name="xavier_uniform_init", trainable=True,
                   ctx=None, dtype=jnp.float32):
    return _var(XavierUniformInit(shape, gain), name, trainable, ctx, dtype)


def he_normal(shape, name="he_normal_init", trainable=True, ctx=None,
              dtype=jnp.float32):
    return _var(HeNormalInit(shape), name, trainable, ctx, dtype)


def he_uniform(shape, name="he_uniform_init", trainable=True, ctx=None,
               dtype=jnp.float32):
    return _var(HeUniformInit(shape), name, trainable, ctx, dtype)


def lecun_normal(shape, name="lecun_normal_init", trainable=True, ctx=None,
                 dtype=jnp.float32):
    return _var(LecunNormalInit(shape), name, trainable, ctx, dtype)


def lecun_uniform(shape, name="lecun_uniform_init", trainable=True, ctx=None,
                  dtype=jnp.float32):
    return _var(LecunUniformInit(shape), name, trainable, ctx, dtype)


# --------------------------------------------------------------------- #
# Gen* generator factories (reference initializers.py:320-372): return a
# callable(shape=..., name=...) -> variable node, used by layer classes.
# --------------------------------------------------------------------- #

def _gen(make_init):
    def generator(shape=None, name="init", trainable=True, ctx=None,
                  dtype=jnp.float32):
        return _var(make_init(shape), name, trainable, ctx, dtype)
    return generator


def GenZeros():
    return _gen(lambda s: ZerosInit(s))


def GenOnes():
    return _gen(lambda s: OnesInit(s))


def GenConstant(fill_value=0.0):
    return _gen(lambda s: ConstantInit(fill_value, s))


def GenTruncatedNormal(mean=0.0, stddev=1.0):
    return _gen(lambda s: TruncatedNormalInit(mean, stddev, s))


def GenNormal(mean=0.0, stddev=1.0):
    return _gen(lambda s: NormalInit(mean, stddev, s))


def GenUniform(minval=-1.0, maxval=1.0):
    return _gen(lambda s: UniformInit(minval, maxval, s))


def GenXavierNormal(gain=1.0):
    return _gen(lambda s: XavierNormalInit(s, gain))


def GenXavierUniform(gain=1.0):
    return _gen(lambda s: XavierUniformInit(s, gain))


GenGeneralXavierNormal = GenXavierNormal
GenGeneralXavierUniform = GenXavierUniform


def GenHeNormal():
    return _gen(lambda s: HeNormalInit(s))


def GenHeUniform():
    return _gen(lambda s: HeUniformInit(s))


def GenLecunNormal():
    return _gen(lambda s: LecunNormalInit(s))


def GenLecunUniform():
    return _gen(lambda s: LecunUniformInit(s))


# GenEmpty / GenReversedTruncatedNormal parity aliases
nulls = zeros
