"""Runtime bring-up compatibility shims.

Reference exports (gpu_ops/__init__.py:118-296 "Executor/runtime" group):
``wrapped_mpi_nccl_init``, ``new_group_comm``, ``worker_init`` etc. — the
MPI/NCCL/PS process bootstrap (executor.py:60-105).

On TPU: `jax.distributed.initialize()` replaces MPI+NCCL bootstrap; mesh
axes replace communicator groups; the PS roles map to hetu_tpu.ps server
processes.  These functions keep reference scripts runnable.
"""

from __future__ import annotations

import jax

from . import envvars

_worker_comm = None


def wrapped_mpi_nccl_init(init_nccl=True, devices=None):
    """Multi-host bring-up (reference executor.py:60-71).  Under a single
    process this is a no-op returning a handle exposing rank info."""
    import os

    class _Comm:
        def __init__(self):
            self.rank = jax.process_index()
            self.nrank = jax.process_count()
            self.local_rank = 0
            self.dev_id = 0

        def ncclCommInitRank(self):
            pass

    if envvars.is_set("HETU_TPU_COORDINATOR"):
        jax.distributed.initialize(
            coordinator_address=envvars.get_str("HETU_TPU_COORDINATOR"),
            num_processes=envvars.get_int("HETU_TPU_NUM_PROCS"),
            process_id=envvars.get_int("HETU_TPU_PROC_ID"))
    return _Comm()


def new_group_comm(device_group=None):
    """Sub-communicator creation (mpi_nccl_comm.py:164-250) — on TPU a
    mesh-axis name stands in for a communicator; nothing to allocate."""
    return device_group


def get_worker_communicate():
    global _worker_comm
    if _worker_comm is None:
        from .ps.client import PSClient
        _worker_comm = PSClient.get()
    return _worker_comm


def worker_init():
    from .ps.client import PSClient
    global _worker_comm
    _worker_comm = PSClient.get()


def worker_finish():
    global _worker_comm
    if _worker_comm is not None:
        _worker_comm.finalize()
        _worker_comm = None


def server_init():
    from .ps.server import PSServer
    PSServer.serve_from_env()


def server_finish():
    pass


def scheduler_init():
    from .ps.server import Scheduler
    Scheduler.serve_from_env()


def scheduler_finish():
    pass
