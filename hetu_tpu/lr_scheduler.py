"""LR schedulers: Fixed/Step/MultiStep/Exponential/ReduceOnPlateau + warmup.

Reference: python/hetu/lr_scheduler.py (142 LoC).  Schedules here are pure
functions of the jitted step counter so they trace into the step program
(the reference recomputes lr host-side each step).  ReduceOnPlateau is
inherently host-driven (depends on observed loss) and keeps a host API.
"""

from __future__ import annotations

import jax.numpy as jnp


class LRScheduler:
    def value(self, step):
        raise NotImplementedError

    def get(self, step=0):
        return float(self.value(jnp.asarray(step)))


class FixedScheduler(LRScheduler):
    def __init__(self, learning_rate):
        self.learning_rate = learning_rate

    def value(self, step):
        return jnp.asarray(self.learning_rate, jnp.float32)


class StepScheduler(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1):
        self.learning_rate = learning_rate
        self.step_size = step_size
        self.gamma = gamma

    def value(self, step):
        k = (step // self.step_size).astype(jnp.float32)
        return self.learning_rate * (self.gamma ** k)


class MultiStepScheduler(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1):
        self.learning_rate = learning_rate
        self.milestones = sorted(milestones)
        self.gamma = gamma

    def value(self, step):
        k = jnp.zeros((), jnp.float32)
        for m in self.milestones:
            k = k + (step >= m).astype(jnp.float32)
        return self.learning_rate * (self.gamma ** k)


class ExponentialScheduler(LRScheduler):
    def __init__(self, learning_rate, gamma=0.99, step_size=1):
        self.learning_rate = learning_rate
        self.gamma = gamma
        self.step_size = step_size

    def value(self, step):
        k = (step // self.step_size).astype(jnp.float32)
        return self.learning_rate * (self.gamma ** k)


class LinearWarmupScheduler(LRScheduler):
    """Linear warmup then linear/constant decay — used by BERT pretraining
    (reference examples/nlp/bert uses torch-style schedules)."""

    def __init__(self, learning_rate, warmup_steps, total_steps=None,
                 end_lr=0.0):
        self.learning_rate = learning_rate
        self.warmup_steps = max(1, warmup_steps)
        self.total_steps = total_steps
        self.end_lr = end_lr

    def value(self, step):
        step = step.astype(jnp.float32)
        warm = self.learning_rate * step / self.warmup_steps
        if self.total_steps is None:
            after = jnp.asarray(self.learning_rate, jnp.float32)
        else:
            frac = jnp.clip((step - self.warmup_steps)
                            / max(1, self.total_steps - self.warmup_steps), 0, 1)
            after = self.learning_rate + frac * (self.end_lr - self.learning_rate)
        return jnp.where(step < self.warmup_steps, warm, after)


class CosineScheduler(LRScheduler):
    def __init__(self, learning_rate, total_steps, warmup_steps=0, end_lr=0.0):
        self.learning_rate = learning_rate
        self.total_steps = total_steps
        self.warmup_steps = warmup_steps
        self.end_lr = end_lr

    def value(self, step):
        step = step.astype(jnp.float32)
        warm = self.learning_rate * step / max(1, self.warmup_steps)
        frac = jnp.clip((step - self.warmup_steps)
                        / max(1, self.total_steps - self.warmup_steps), 0, 1)
        cos = self.end_lr + 0.5 * (self.learning_rate - self.end_lr) \
            * (1 + jnp.cos(jnp.pi * frac))
        if self.warmup_steps == 0:
            return cos
        return jnp.where(step < self.warmup_steps, warm, cos)


class ReduceOnPlateauScheduler(LRScheduler):
    """Host-driven: call ``step_metric(value)`` each eval; ``value`` reads
    the current lr (reference lr_scheduler.py ReduceOnPlateau)."""

    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, min_lr=0.0):
        self.lr = learning_rate
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.min_lr = min_lr
        self.best = None
        self.bad = 0

    def step_metric(self, metric):
        metric = float(metric)
        better = (self.best is None
                  or (self.mode == "min" and metric < self.best - self.threshold)
                  or (self.mode == "max" and metric > self.best + self.threshold))
        if better:
            self.best = metric
            self.bad = 0
        else:
            self.bad += 1
            if self.bad > self.patience:
                self.lr = max(self.min_lr, self.lr * self.factor)
                self.bad = 0
        return self.lr

    def value(self, step):
        return jnp.asarray(self.lr, jnp.float32)
