"""Parameter server: keyed tensor store with server-side optimizers.

Reference: ps-lite PSHandler<kParameterServer> (PSFHandle.h:17) with
server-side optimizers (server/optimizer.h:36-275: SGD/Momentum/Nesterov/
AdaGrad/Adam), Param/Param2D/CacheTable storage (server/param.h), SSP
clocks (ssp_handler.h), preduce partner matching (preduce_handler.cc), and
the PSFunc RPC surface (psf/PSFunc.h:33-57: DensePush/Pull, DDPushPull,
SparsePush/Pull, SDPushPull, SSPushPull, ParamInit/Clear/Save/Load,
SyncEmbedding/PushEmbedding, SSPInit/SSPSync, PReduceGetPartner).

TPU-native: the server lives host-side on the TPU-VM (embeddings exceed
HBM; SURVEY.md §2.2 'TPU equivalent').  Two transports: in-process (zero
copy, default for single-host) and length-prefixed TCP carrying the
TYPED wire codec (ps/wire.py — plain-data envelope only, no pickle on
network bytes; ps-lite frames typed protobuf + raw buffers the same
way) for multi-process / multi-host.  Numpy is the compute engine
server-side — the hot sparse rows path is vectorized gather/scatter,
the same work the reference does in C++ loops.
"""

from __future__ import annotations

import ctypes
import os
import socket
import socketserver
import struct
import threading
import time

import numpy as np

from . import faults, wire
from .. import envvars, locks
from ..quant import QuantArray, maybe_decode, should_quantize, wire_chunk


# ----------------------------------------------------------------- #
# native core: fused C++ update loops (hetu_tpu/native/ps_core.cpp),
# mirroring the reference's C++ server optimizers (server/optimizer.h).
# Numpy paths below remain the fallback when no compiler exists.
# ----------------------------------------------------------------- #

def _load_native():
    from ..native import build_and_load

    lib = build_and_load("ps_core.cpp", "libps_core.so",
                         deps=("ps_kernels.h",))
    if lib is None:
        return None
    f32p = ctypes.POINTER(ctypes.c_float)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i64 = ctypes.c_int64
    f32 = ctypes.c_float
    lib.ps_dense_sgd.argtypes = [f32p, f32p, i64, f32]
    lib.ps_dense_momentum.argtypes = [f32p, f32p, f32p, i64, f32, f32,
                                      ctypes.c_int]
    lib.ps_dense_adagrad.argtypes = [f32p, f32p, f32p, i64, f32, f32]
    lib.ps_dense_adam.argtypes = [f32p, f32p, f32p, f32p, i64, f32, f32,
                                  f32, f32, i64]
    lib.ps_sparse_sgd.argtypes = [f32p, i64p, f32p, i64, i64, f32]
    lib.ps_sparse_momentum.argtypes = [f32p, f32p, i64p, f32p, i64, i64,
                                       f32, f32, ctypes.c_int]
    lib.ps_sparse_adagrad.argtypes = [f32p, f32p, i64p, f32p, i64, i64,
                                      f32, f32]
    lib.ps_sparse_adam.argtypes = [f32p, f32p, f32p, i64p, f32p, i64,
                                   i64, f32, f32, f32, f32, i64]
    lib.ps_sparse_accum.argtypes = [f32p, i64p, f32p, i64, i64]
    lib.ps_sparse_gather.argtypes = [f32p, i64p, f32p, i64, i64]
    lib.ps_bump_versions.argtypes = [i64p, i64p, i64]
    return lib


_NATIVE = _load_native()


def _fp(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _ip(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f32_ready(*arrays):
    """Arrays safe to hand to the float32 C loops (dtype + layout)."""
    return _NATIVE is not None and all(
        a.dtype == np.float32 and a.flags["C_CONTIGUOUS"]
        for a in arrays)


def _dense_ready(value, grad, *state):
    """Dense fast path: exact shape match (the numpy fallback also
    supports broadcastable grads; those take the fallback)."""
    return value.shape == grad.shape and _f32_ready(value, grad, *state)


def _check_ids(ids, nrows):
    """Bounds-check before raw pointer arithmetic — preserves the
    IndexError the numpy paths raised for bad ids (the C loops would
    corrupt server memory instead)."""
    if len(ids) and (int(ids.min()) < 0 or int(ids.max()) >= nrows):
        raise IndexError(
            f"sparse ids out of range for table with {nrows} rows")


def _sparse_ready(value, ids, rows, *state):
    """Sparse fast path: 2D table, float32 everywhere, int64 contiguous
    ids within bounds, rows shaped (k, cols)."""
    if value.ndim != 2 or not _f32_ready(value, rows, *state):
        return False
    if ids.dtype != np.int64 or not ids.flags["C_CONTIGUOUS"]:
        return False
    if rows.shape != (len(ids), value.shape[1]):
        return False
    _check_ids(ids, value.shape[0])
    return True


# --------------------------------------------------------------------- #
# server-side optimizers (reference server/optimizer.h)
# --------------------------------------------------------------------- #

class ServerOptimizer:
    def __init__(self, learning_rate=0.1, **kwargs):
        self.lr = learning_rate

    def init_state(self, shape):
        return {}

    def apply_dense(self, value, grad, state):
        raise NotImplementedError

    def apply_sparse(self, value, ids, rows, state):
        """ids unique-merged client-side or here; default: dense emulation
        over touched rows."""
        uniq, inv = np.unique(ids, return_inverse=True)
        merged = np.zeros((len(uniq), rows.shape[-1]), rows.dtype)
        np.add.at(merged, inv, rows)
        self._sparse_rows(value, uniq, merged, state)

    def _sparse_rows(self, value, uniq, merged, state):
        value[uniq] -= self.lr * merged


class ServerSGD(ServerOptimizer):
    def apply_dense(self, value, grad, state):
        if _dense_ready(value, grad):
            _NATIVE.ps_dense_sgd(_fp(value), _fp(grad), value.size,
                                 self.lr)
            return
        value -= self.lr * grad

    def apply_sparse(self, value, ids, rows, state):
        if _sparse_ready(value, ids, rows):
            _NATIVE.ps_sparse_sgd(_fp(value), _ip(ids), _fp(rows),
                                  len(ids), value.shape[-1], self.lr)
            return
        super().apply_sparse(value, ids, rows, state)


class ServerMomentum(ServerOptimizer):
    def __init__(self, learning_rate=0.1, momentum=0.9, nesterov=False):
        super().__init__(learning_rate)
        self.momentum = momentum
        self.nesterov = nesterov

    def init_state(self, shape):
        return {"v": np.zeros(shape, np.float32)}

    def apply_dense(self, value, grad, state):
        if _dense_ready(value, grad, state["v"]):
            _NATIVE.ps_dense_momentum(_fp(value), _fp(state["v"]),
                                      _fp(grad), value.size, self.lr,
                                      self.momentum, int(self.nesterov))
            return
        v = state["v"]
        v *= self.momentum
        v -= self.lr * grad
        if self.nesterov:
            value += self.momentum * v - self.lr * grad
        else:
            value += v

    def apply_sparse(self, value, ids, rows, state):
        if _sparse_ready(value, ids, rows, state["v"]):
            _NATIVE.ps_sparse_momentum(
                _fp(value), _fp(state["v"]), _ip(ids), _fp(rows),
                len(ids), value.shape[-1], self.lr, self.momentum,
                int(self.nesterov))
            return
        super().apply_sparse(value, ids, rows, state)

    def _sparse_rows(self, value, uniq, merged, state):
        v = state["v"]
        v[uniq] = self.momentum * v[uniq] - self.lr * merged
        if self.nesterov:
            value[uniq] += self.momentum * v[uniq] - self.lr * merged
        else:
            value[uniq] += v[uniq]


class ServerNesterov(ServerMomentum):
    def __init__(self, learning_rate=0.1, momentum=0.9):
        super().__init__(learning_rate, momentum, nesterov=True)


class ServerAdaGrad(ServerOptimizer):
    def __init__(self, learning_rate=0.1, initial_accumulator_value=0.0,
                 eps=1e-7):
        super().__init__(learning_rate)
        self.init_acc = initial_accumulator_value
        self.eps = eps

    def init_state(self, shape):
        return {"acc": np.full(shape, self.init_acc, np.float32)}

    def apply_dense(self, value, grad, state):
        if _dense_ready(value, grad, state["acc"]):
            _NATIVE.ps_dense_adagrad(_fp(value), _fp(state["acc"]),
                                     _fp(grad), value.size, self.lr,
                                     self.eps)
            return
        state["acc"] += grad * grad
        value -= self.lr * grad / (np.sqrt(state["acc"]) + self.eps)

    def apply_sparse(self, value, ids, rows, state):
        if _sparse_ready(value, ids, rows, state["acc"]):
            _NATIVE.ps_sparse_adagrad(
                _fp(value), _fp(state["acc"]), _ip(ids), _fp(rows),
                len(ids), value.shape[-1], self.lr, self.eps)
            return
        super().apply_sparse(value, ids, rows, state)

    def _sparse_rows(self, value, uniq, merged, state):
        acc = state["acc"]
        acc[uniq] += merged * merged
        value[uniq] -= self.lr * merged / (np.sqrt(acc[uniq]) + self.eps)


class ServerAdam(ServerOptimizer):
    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-7):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.eps = beta1, beta2, epsilon

    def init_state(self, shape):
        return {"m": np.zeros(shape, np.float32),
                "v": np.zeros(shape, np.float32), "t": np.zeros((), np.int64)}

    def apply_dense(self, value, grad, state):
        state["t"] += 1
        t = int(state["t"])
        m, v = state["m"], state["v"]
        if _dense_ready(value, grad, m, v):
            _NATIVE.ps_dense_adam(_fp(value), _fp(m), _fp(v), _fp(grad),
                                  value.size, self.lr, self.beta1,
                                  self.beta2, self.eps, t)
            return
        m *= self.beta1
        m += (1 - self.beta1) * grad
        v *= self.beta2
        v += (1 - self.beta2) * grad * grad
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        value -= self.lr * mhat / (np.sqrt(vhat) + self.eps)

    def apply_sparse(self, value, ids, rows, state):
        if _sparse_ready(value, ids, rows, state["m"], state["v"]):
            state["t"] += 1
            _NATIVE.ps_sparse_adam(
                _fp(value), _fp(state["m"]), _fp(state["v"]), _ip(ids),
                _fp(rows), len(ids), value.shape[-1], self.lr,
                self.beta1, self.beta2, self.eps, int(state["t"]))
            return
        super().apply_sparse(value, ids, rows, state)

    def _sparse_rows(self, value, uniq, merged, state):
        state["t"] += 1
        t = float(state["t"])
        m, v = state["m"], state["v"]
        m[uniq] = self.beta1 * m[uniq] + (1 - self.beta1) * merged
        v[uniq] = self.beta2 * v[uniq] + (1 - self.beta2) * merged * merged
        mhat = m[uniq] / (1 - self.beta1 ** t)
        vhat = v[uniq] / (1 - self.beta2 ** t)
        value[uniq] -= self.lr * mhat / (np.sqrt(vhat) + self.eps)


SERVER_OPTIMIZERS = {
    "sgd": ServerSGD, "SGD": ServerSGD,
    "momentum": ServerMomentum, "Momentum": ServerMomentum,
    "nesterov": ServerNesterov, "Nesterov": ServerNesterov,
    "adagrad": ServerAdaGrad, "AdaGrad": ServerAdaGrad,
    "adam": ServerAdam, "Adam": ServerAdam,
}


class _Param:
    """One stored tensor + optimizer slot state + per-row versions for the
    cache-sync protocol (reference server/param.h Param2D/CacheTable)."""

    def __init__(self, value, optimizer, opt_spec=(None, None)):
        self.value = value
        self.optimizer = optimizer
        self.state = optimizer.init_state(value.shape) if optimizer else {}
        # the (opt_name, opt_args) this param was created with — the
        # replica-resync path re-creates the table on a restarted
        # primary from this spec (ps/sharded.py resync_shard)
        self.opt_spec = opt_spec
        # per-row version counters (only meaningful for 2D tables)
        self.versions = np.zeros(value.shape[0], np.int64) \
            if value.ndim == 2 else None
        self.lock = locks.TracedLock("ps.param")


_AUTOSERVE = object()     # sentinel: serve_van registers future tables too


class PSServer:
    """The parameter server.  All public methods are the PSFunc surface."""

    _instance = None

    def __init__(self):
        self.params = {}
        # serving KV cold store (ISSUE 17): spilled prefix payloads,
        # key -> (payload, version) — a namespace of its own, never
        # cast through the f32 param path
        self.kv_cold = {}
        self.lock = locks.TracedLock("ps.server")
        # SSP: per-key worker clocks (reference ssp_handler.h)
        self.ssp_clocks = {}
        self.ssp_bound = {}
        self.ssp_cv = locks.TracedCondition(name="ps.ssp")
        # preduce matchmaking (reference preduce_handler.cc)
        self._preduce_groups = {}
        self._preduce_seq = 0
        self._preduce_last = {}   # (key, rank) -> last match seq
        self._preduce_cv = locks.TracedCondition(name="ps.preduce")
        # barrier for BSP (reference PSFHandle BarrierWorker)
        self._barrier_count = {}
        self._barrier_cv = locks.TracedCondition(name="ps.barrier")

    # ---------------- lifecycle ---------------- #

    @classmethod
    def get(cls):
        if cls._instance is None:
            cls._instance = PSServer()
        return cls._instance

    @classmethod
    def serve_from_env(cls):
        port = envvars.get_int("HETU_PS_PORT")
        server = cls.get()
        tcp = server.serve_tcp(port, block=False)
        if envvars.get_bool("HETU_PS_VAN"):
            # fast tier: qualifying tables auto-register as clients
            # create them; workers discover it via the van_info RPC
            vport = server.enable_van_autoserve(
                envvars.get_int("HETU_PS_VAN_PORT"))
            print(f"[ps] native van listening on :{vport}", flush=True)
        # announce to the rendezvous scheduler, if one is configured
        _register_with_scheduler(port)
        tcp.serve_forever()

    def serve_tcp(self, port, block=True):
        self._tcp = _serve_object_tcp(self, port, block)
        return self._tcp

    def serve_van(self, keys=None, port=0):
        """Attach the native C++ van (ps/van.py, reference ps-lite
        zmq_van tier): the selected tables' sparse push/pull/push-pull
        are served zero-copy by C++ threads ON THE SAME BUFFERS the
        python PSFunc surface uses.  2-D float32 tables with any
        server-side optimizer from the SGD family qualify (the van
        applies SGD/Momentum/Nesterov/AdaGrad/Adam in-kernel, sharing
        the python tier's slot state — reference server/optimizer.h);
        their python lock becomes a composite lock shared with the
        van's per-table mutex, so both tiers serialize.

        Registration is race-free when it happens before workers start
        pushing to the table (the ``enable_van_autoserve`` path
        registers at creation).  A table already receiving traffic is
        swapped under its param lock, so in-flight python ops drain
        first; an op that read the OLD lock object but had not yet
        acquired it can still overlap the van's first requests for one
        op — prefer autoserve for live tables.

        Returns (port, {key: van_key_id}) — VanClient speaks van ids.
        """
        with self.lock:
            return self._serve_van_locked(keys, port)

    @staticmethod
    def _van_qualifies(p):
        """The van serves 2-D float32 buffers whose server optimizer it
        can apply in-kernel: the whole SERVER_OPTIMIZERS family, plus
        optimizer-less tables (accumulate mode — the HET cache
        write-back path, which also gets the sync_embedding verb)."""
        return ((p.optimizer is None
                 or isinstance(p.optimizer, (ServerSGD, ServerMomentum,
                                             ServerAdaGrad, ServerAdam)))
                and p.value.ndim == 2 and p.value.dtype == np.float32)

    def _serve_van_locked(self, keys=None, port=0):
        """serve_van body; caller holds self.lock (param_init's
        autoserve hook runs inside its own locked region)."""
        from .van import NativeVan, VanSharedLock
        if getattr(self, "_van", None) is None:
            self._van = NativeVan()
            # HETU_PS_VAN_BIND_ALL=1 exposes the (authentication-free)
            # fast tier beyond loopback for true multi-host heturun
            # deployments; "", "0" and "false" all mean loopback-only
            self._van_port = self._van.listen(
                port,
                bind_all=envvars.get_bool("HETU_PS_VAN_BIND_ALL"))
            self._van_keys = {}
        if keys is _AUTOSERVE:
            # every FUTURE qualifying table registers on creation
            # (heturun deployments init tables over RPC after the
            # server is up — see enable_van_autoserve)
            self._van_auto = True
            keys = None
        if keys is None:
            keys = [k for k, p in self.params.items()
                    if self._van_qualifies(p)]
        for k in keys:
            if k in self._van_keys:
                continue
            p = self.params[k]
            if not self._van_qualifies(p):
                raise ValueError(
                    f"van can only serve 2-D float32 tables (optimizer "
                    f"from the SGD family, or none = accumulate); "
                    f"{k!r} is {p.value.dtype}/{p.value.ndim}-D with "
                    f"{type(p.optimizer).__name__}")
            kid = len(self._van_keys)
            # the registered (contiguous) arrays ARE the served
            # buffers; the param points at exactly them and shares the
            # van's per-table mutex.  Register + lock swap run under
            # the param's EXISTING lock so any python op already inside
            # the table drains before the van can serve it (lock order
            # self.lock -> p.lock matches every PSFunc site).
            with p.lock:
                p.value = self._van.register_table(
                    kid, p.value, p.optimizer, p.state,
                    versions=p.versions)
                p.lock = VanSharedLock(p.lock, self._van, kid)
            self._van_keys[k] = kid
        return self._van_port, dict(self._van_keys)

    def enable_van_autoserve(self, port=0):
        """heturun deployment hook (HETU_PS_VAN=1): start the van now
        and auto-register every qualifying table as clients create it;
        workers discover the port/key map via ``van_info`` RPC."""
        return self.serve_van(keys=_AUTOSERVE, port=port)[0]

    def van_info(self):
        """(van port | None, {key: van key id}) — the RPC workers call
        to discover the fast tier."""
        with self.lock:      # the TCP server is threaded; shutdown()
            if getattr(self, "_van", None) is None:   # mutates under
                return None, {}                        # this lock
            return self._van_port, dict(self._van_keys)

    def _van_autoserve_locked(self, key):
        """Called at table creation (self.lock held) when autoserve is
        on; non-qualifying tables stay python-tier, but a registration
        FAILURE on a qualifying table stays loud."""
        if getattr(self, "_van_auto", False) and \
                self._van_qualifies(self.params[key]):
            self._serve_van_locked([key])

    def shutdown(self):
        hb = getattr(self, "_server_hb_stop", None)
        if hb is not None:
            hb.set()             # a dead server must stop reading alive
            self._server_hb_stop = None
        if getattr(self, "_tcp", None) is not None:
            self._tcp.shutdown()
            self._tcp = None
        if getattr(self, "_van", None) is not None:
            from .van import VanSharedLock
            with self.lock:
                # restore plain python locks BEFORE stopping the van: a
                # VanSharedLock over a destroyed handle would crash any
                # later PSFunc op on the key
                for k in getattr(self, "_van_keys", {}):
                    p = self.params.get(k)
                    if p is not None and isinstance(p.lock,
                                                    VanSharedLock):
                        p.lock = p.lock.pylock
                self._van_keys = {}
                self._van_auto = False
            self._van.stop()
            self._van = None

    # ---------------- PSFunc surface ---------------- #

    def param_init(self, key, shape, init_type="constant", arg1=0.0,
                   arg2=1.0, seed=0, opt=None, opt_args=None,
                   param_type=0):
        """ParamInit (PSFunc.h kParamInit; initializers.py init_on_ps)."""
        with self.lock:
            if key in self.params:
                return False
            rng = np.random.RandomState(seed)
            shape = tuple(shape)
            if init_type in ("constant", 0):
                value = np.full(shape, arg1, np.float32)
            elif init_type in ("uniform", 1):
                value = rng.uniform(arg1, arg2, shape).astype(np.float32)
            elif init_type in ("normal", "gaussian", 2):
                value = (arg1 + arg2 * rng.randn(*shape)).astype(np.float32)
            elif init_type in ("truncated_normal", 3):
                value = np.clip(rng.randn(*shape), -2, 2)
                value = (arg1 + arg2 * value).astype(np.float32)
            else:
                raise ValueError(f"unknown init type {init_type}")
            optimizer = None
            if opt is not None:
                optimizer = SERVER_OPTIMIZERS[opt](**(opt_args or {}))
            self.params[key] = _Param(value, optimizer, (opt, opt_args))
            self._van_autoserve_locked(key)
            return True

    def param_set(self, key, value, opt=None, opt_args=None):
        """Create-or-overwrite a param with an explicit value array.

        The executor's Hybrid/PS bridge: exact-value parity with the
        device-side initializer (param_init's distribution types can't
        reproduce a jax-PRNG init bit-for-bit).  Overwriting resets
        optimizer slot state and row versions.

        Always copies: np.asarray over a jax CPU array is zero-copy, and a
        donated step buffer would silently corrupt the stored table."""
        value = np.array(maybe_decode(value), np.float32, order="C",
                         copy=True)
        optimizer = None
        if opt is not None:
            optimizer = SERVER_OPTIMIZERS[opt](**(opt_args or {}))
        with self.lock:
            vkeys = getattr(self, "_van_keys", {})
            if key in vkeys:
                # a van-served key is RE-REGISTERED in place (the C++
                # tier swaps its pointers under the table mutex) rather
                # than refused — the executor bridge re-sets tables on
                # load_dict.  A respec the van cannot serve would
                # silently detach the fast tier, so that stays loud.
                from .van import VanSharedLock
                new_p = _Param(value, optimizer, (opt, opt_args))
                if not self._van_qualifies(new_p):
                    raise ValueError(
                        f"{key!r} is served by the native van and the "
                        f"new spec ({value.dtype}/{value.ndim}-D, "
                        f"{type(optimizer).__name__}) does not qualify "
                        f"— the van cannot be detached from a key")
                kid = vkeys[key]
                pylock = self.params[key].lock.pylock
                with pylock:       # drain python ops; the register
                    new_p.value = self._van.register_table(   # itself
                        kid, new_p.value, new_p.optimizer,    # fences
                        new_p.state, versions=new_p.versions)  # van
                    new_p.lock = VanSharedLock(pylock, self._van, kid)
                    self.params[key] = new_p
                return True
            self.params[key] = _Param(value, optimizer, (opt, opt_args))
            self._van_autoserve_locked(key)
            return True

    def param_spec(self, key):
        """(shape, opt_name, opt_args) a param was created with — lets a
        failover client or the supervisor rebuild the table elsewhere
        (replica resync) with identical server-side update semantics."""
        p = self.params[key]
        return tuple(p.value.shape), p.opt_spec[0], p.opt_spec[1]

    def param_assign(self, key, value):
        """In-place value overwrite that PRESERVES the server-side
        optimizer and its slot state (param_set would reset them) — the
        checkpoint-restore path."""
        value = np.asarray(maybe_decode(value), np.float32)
        with self.lock:
            p = self.params.get(key)
            if p is None:
                self.params[key] = _Param(value.copy(), None)
                return True
        with p.lock:
            p.value[...] = value
        return True

    def param_clear(self, key):
        with self.lock:
            if key in getattr(self, "_van_keys", {}):
                raise ValueError(
                    f"{key!r} is served by the native van; clearing it "
                    f"would leave the C++ tier serving freed memory")
            self.params.pop(key, None)

    # ---------------- serving KV cold store (ISSUE 17) ---------------- #
    # The tiered-KV ladder's coldest rung (serving/kv_tiers.py): spilled
    # prefix payloads — the export_blocks wire dict, int8 or exact —
    # live in their OWN namespace dict, versioned per put, and never
    # pass through the f32 param path (a cast would corrupt the int8
    # planes).  Public methods = PSFunc surface: callable through every
    # transport, chaos/telemetry included, like any other op.

    def kv_put(self, key, payload, version=0):
        """Park one cold payload under ``key`` (the tier store keys by
        prefix hash).  Last write wins; the version stamp lets a fetch
        refuse an entry someone overwrote behind its index."""
        with self.lock:
            self.kv_cold[key] = (payload, int(version))
        return True

    def kv_get(self, key):
        """``(payload, version)`` or None — a miss is an answer, not an
        error (the tier ladder degrades to cold prefill)."""
        with self.lock:
            return self.kv_cold.get(key)

    def kv_del(self, key):
        """Drop a cold payload (a fetch ends the residency); True when
        something was actually removed."""
        with self.lock:
            return self.kv_cold.pop(key, None) is not None

    def kv_keys(self):
        """Resident cold-store keys (introspection/tests)."""
        with self.lock:
            return sorted(self.kv_cold)

    def param_save(self, key, path):
        p = self.params[key]
        with p.lock:
            np.save(os.path.join(path, f"ps_param_{key}.npy"), p.value)

    def param_load(self, key, path):
        p = self.params[key]
        with p.lock:
            p.value[...] = np.load(os.path.join(path, f"ps_param_{key}.npy"))

    @staticmethod
    def _q_out(value, quant):
        """Quantize a pull response when the client asked for it (the
        pull half of the HETU_PS_QUANT pair); qualifying values only —
        tiny/integer payloads stay exact."""
        if quant == "int8" and should_quantize(value):
            return QuantArray.encode(value, wire_chunk())
        return value

    def pull(self, key, quant=None):
        p = self.params[key]
        with p.lock:
            return self._q_out(p.value.copy(), quant)

    def push(self, key, grad):
        """DensePush: apply grad through the server optimizer (or raw add
        when no optimizer, matching reference kDensePush accumulate).
        Quantized payloads (QuantArray) are dequantized HERE, before the
        optimizer step — the server optimizes over the dequantized grad,
        so primary and replica (which replays the same quantized frame)
        walk identical trajectories."""
        grad = maybe_decode(grad)
        p = self.params[key]
        with p.lock:
            if p.optimizer is not None:
                p.optimizer.apply_dense(p.value, np.asarray(grad), p.state)
            else:
                p.value += np.asarray(grad)

    def dd_pushpull(self, key, grad, quant=None):
        grad = maybe_decode(grad)
        p = self.params[key]
        with p.lock:
            if p.optimizer is not None:
                p.optimizer.apply_dense(p.value, np.asarray(grad), p.state)
            else:
                p.value += np.asarray(grad)
            return self._q_out(p.value.copy(), quant)

    def sparse_pull(self, key, ids, quant=None):
        p = self.params[key]
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).reshape(-1))
        with p.lock:
            if p.value.ndim == 2 and _f32_ready(p.value):
                _check_ids(ids, p.value.shape[0])
                out = np.empty((len(ids), p.value.shape[1]), np.float32)
                _NATIVE.ps_sparse_gather(_fp(p.value), _ip(ids), _fp(out),
                                         len(ids), p.value.shape[1])
                return self._q_out(out, quant)
            return self._q_out(p.value[ids], quant)

    def sparse_push(self, key, ids, rows):
        rows = maybe_decode(rows)
        p = self.params[key]
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).reshape(-1))
        rows = np.ascontiguousarray(
            np.asarray(rows, np.float32).reshape(len(ids), -1))
        with p.lock:
            if p.optimizer is not None:
                p.optimizer.apply_sparse(p.value, ids, rows, p.state)
            elif _sparse_ready(p.value, ids, rows):
                _NATIVE.ps_sparse_accum(_fp(p.value), _ip(ids), _fp(rows),
                                        len(ids), p.value.shape[1])
            else:
                np.add.at(p.value, ids, rows)
            if p.versions is not None:
                if _NATIVE is not None and \
                        p.versions.flags["C_CONTIGUOUS"]:
                    _check_ids(ids, len(p.versions))
                    _NATIVE.ps_bump_versions(_ip(p.versions), _ip(ids),
                                             len(ids))
                else:
                    p.versions[np.unique(ids)] += 1

    def sd_pushpull(self, key, ids, rows, pull_ids=None, quant=None):
        self.sparse_push(key, ids, rows)
        return self.sparse_pull(
            key, pull_ids if pull_ids is not None else ids, quant=quant)

    def ss_pushpull(self, key, ids, rows, pull_ids, quant=None):
        return self.sd_pushpull(key, ids, rows, pull_ids, quant=quant)

    # ---------------- cache sync (HET protocol) ---------------- #

    def sync_embedding(self, key, ids, stored_versions, bound,
                       quant=None):
        """kSyncEmbedding (hetu_client.cc): return rows whose server version
        exceeds the client's stored version by more than ``bound``.
        ``quant="int8"`` ships the row payload as a QuantArray (the
        HETU_PS_QUANT pull pair — serving cache misses ride this)."""
        p = self.params[key]
        ids = np.asarray(ids, np.int64).reshape(-1)
        stored_versions = np.asarray(stored_versions, np.int64).reshape(-1)
        with p.lock:
            server_v = p.versions[ids]
            stale = (server_v - stored_versions) > bound
            return (ids[stale], self._q_out(p.value[ids[stale]], quant),
                    server_v[stale])

    def push_embedding(self, key, ids, rows, versions=None):
        """kPushEmbedding: apply client-accumulated embedding grads."""
        self.sparse_push(key, ids, rows)

    def push_sync_embedding(self, key, ids, rows, sync_ids,
                            stored_versions, bound):
        self.sparse_push(key, ids, rows)
        return self.sync_embedding(key, sync_ids, stored_versions, bound)

    # ---------------- SSP / BSP ---------------- #

    def ssp_init(self, group, worker, bound):
        with self.ssp_cv:
            self.ssp_clocks.setdefault(group, {})[worker] = 0
            self.ssp_bound[group] = bound

    def ssp_sync(self, group, worker, timeout=60.0):
        """Advance worker clock; block while ahead of slowest by > bound."""
        with self.ssp_cv:
            self.ssp_clocks[group][worker] += 1
            self.ssp_cv.notify_all()
            bound = self.ssp_bound[group]
            deadline = time.time() + timeout
            while True:
                clocks = self.ssp_clocks[group]
                if clocks[worker] - min(clocks.values()) <= bound:
                    return clocks[worker]
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError("ssp_sync timed out")
                self.ssp_cv.wait(remaining)

    def barrier(self, group, worker, nworkers, timeout=60.0):
        """BSP barrier (reference BarrierWorker)."""
        with self._barrier_cv:
            gen, count = self._barrier_count.get(group, (0, 0))
            count += 1
            if count >= nworkers:
                self._barrier_count[group] = (gen + 1, 0)
                self._barrier_cv.notify_all()
                return
            self._barrier_count[group] = (gen, count)
            deadline = time.time() + timeout
            while self._barrier_count.get(group, (0, 0))[0] == gen:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError("barrier timed out")
                self._barrier_cv.wait(remaining)

    # ---------------- preduce matchmaking ---------------- #

    def preduce_get_partner(self, key, rank, max_worker, wait_time):
        """kPReduceGetPartner (preduce_handler.cc): batch arriving workers
        into a group; return (member ranks, match seq) once the group
        fills or ``wait_time`` (seconds) elapses.  The server-assigned
        sequence number gives all members a shared scratch-key namespace
        (local counters diverge when group membership varies)."""
        with self._preduce_cv:
            group = self._preduce_groups.setdefault(key, [])
            group.append(rank)
            self._preduce_cv.notify_all()
            deadline = time.time() + wait_time
            while len(group) < max_worker:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._preduce_cv.wait(remaining)
            members = sorted(group)
            # first member to wake stamps the match and clears the batch
            if self._preduce_groups.get(key) is group:
                self._preduce_seq += 1
                self._preduce_groups[key] = []
                seq = self._preduce_seq
                for m in members:
                    self._preduce_last[(key, m)] = seq
            else:
                seq = self._preduce_last.get((key, rank), 0)
            return members, seq

    # ---------------- introspection ---------------- #

    def get_loads(self):
        return {k: int(np.prod(p.value.shape)) for k, p in self.params.items()}


# --------------------------------------------------------------------- #
# TCP framing
# --------------------------------------------------------------------- #

def _send_msg(sock, payload: bytes):
    # gather write: one syscall/segment, no header+payload concat copy
    # (payloads are multi-MB embedding batches)
    header = struct.pack("!Q", len(payload))
    total = len(header) + len(payload)
    try:
        sent = sock.sendmsg([header, payload])
    except (AttributeError, OSError):
        sock.sendall(header)
        sock.sendall(payload)
        return
    if sent < total:        # rare partial send: finish with a copy
        rest = memoryview(bytes(header) + bytes(payload))[sent:]
        sock.sendall(rest)


def _recv_msg(sock):
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    (n,) = struct.unpack("!Q", header)
    return _recv_exact(sock, n)


def _recv_exact(sock, n):
    # recv_into a preallocated buffer: O(n), vs the O(n^2) bytes+=chunk
    # pattern that dominated large-message latency
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            return None
        got += r
    return buf      # wire.loads decodes arrays zero-copy from this buffer


def _serve_object_tcp(obj, port, block=True):
    """Serve ``obj``'s public methods over the length-prefixed TCP
    framing.  Requests come in two shapes:

    * legacy ``(method, args, kwargs)``;
    * ``('__req2__', client_id, seq, method, args, kwargs)`` — the
      reliable framing the hardened client sends.  The server keeps a
      one-slot replay cache per client: a request whose seq was already
      served gets the CACHED response replayed instead of re-applying the
      method (ps-lite resender.h parity — without this, a client retry
      after a lost response would double-apply a push)."""
    import collections as _collections
    replay = _collections.OrderedDict()   # client_id -> (seq, payload)
    replay_cv = locks.TracedCondition(name="ps.replay")
    _MAX_CLIENTS = 1024                   # LRU bound: one slot per client

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            try:
                while True:
                    raw = _recv_msg(self.request)
                    if raw is None:
                        return
                    msg = wire.loads(raw)
                    cid = seq = None
                    if isinstance(msg, tuple) and msg \
                            and msg[0] == "__req2__":
                        _, cid, seq, method, args, kwargs = msg
                        with replay_cv:
                            cached = replay.get(cid)
                            if cached is not None and cached[0] == seq:
                                # retransmit of an IN-FLIGHT request
                                # (payload None): wait for the original
                                # to finish, then replay its response —
                                # never execute twice
                                while cached is not None and \
                                        cached[0] == seq and \
                                        cached[1] is None:
                                    replay_cv.wait(1.0)
                                    cached = replay.get(cid)
                                if cached is not None and \
                                        cached[0] == seq:
                                    _send_msg(self.request, cached[1])
                                    continue
                            replay[cid] = (seq, None)   # mark in flight
                            replay.move_to_end(cid)
                            while len(replay) > _MAX_CLIENTS:
                                replay.popitem(last=False)
                    else:
                        method, args, kwargs = msg
                    # server-side chaos seam: a HETU_CHAOS plan with a
                    # role matching this process can SIGKILL it mid-run
                    # (the one-shot shard-loss fault) or slow its
                    # responses; loss kinds stay client-side where the
                    # resend machinery lives
                    plan = faults.plan_from_env()
                    if plan is not None:
                        f = plan.draw(method,
                                      kinds=("kill", "slow", "delay"))
                        if f.kind in ("slow", "delay"):
                            time.sleep(f.seconds)
                    from .. import telemetry
                    tel = telemetry.enabled()
                    t_handle = time.perf_counter() if tel else 0.0
                    try:
                        if method.startswith("_"):
                            raise AttributeError(
                                f"non-public method {method!r}")
                        result = getattr(obj, method)(*args, **kwargs)
                        payload = wire.dumps((True, result))
                    except Exception as e:  # noqa: BLE001
                        payload = wire.dumps((False, repr(e)))
                        if tel:
                            telemetry.inc("ps.server.errors")
                    if tel:
                        # server half of the RPC accounting: apply time
                        # + request/response bytes per verb
                        telemetry.observe(
                            "ps.server.handle_ms." + str(method),
                            (time.perf_counter() - t_handle) * 1e3)
                        telemetry.inc("ps.server.requests")
                        telemetry.inc("ps.server.bytes_in", len(raw))
                        telemetry.inc("ps.server.bytes_out",
                                      len(payload))
                    if cid is not None:
                        with replay_cv:
                            replay[cid] = (seq, payload)
                            replay_cv.notify_all()
                    _send_msg(self.request, payload)
            except (ConnectionResetError, BrokenPipeError, OSError):
                return

    class Threaded(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    srv = Threaded(("0.0.0.0", port), Handler)
    if block:
        srv.serve_forever()
    else:
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
    return srv


class Scheduler:
    """Rendezvous role (ps-lite Postoffice/scheduler parity): servers
    REGISTER themselves; workers BLOCK until the expected server group is
    complete and receive the address list.  With the TCP transport,
    workers then connect directly to servers — the scheduler is only the
    bootstrap, exactly the reference scheduler's role.

    Env contract: servers set HETU_SCHEDULER_ADDR (+ optional
    HETU_PS_INDEX / HETU_PS_ADVERTISE) and register on startup; workers
    with HETU_SCHEDULER_ADDR and no static HETU_PS_ADDR(S) resolve the
    group via ``get_servers`` (expected count HETU_PS_NSERVERS)."""

    def __init__(self):
        self._servers = {}           # index -> addr
        self._cv = locks.TracedCondition(name="scheduler")
        self._beats = {}             # "role:id" -> last monotonic beat

    def register_server(self, index, addr):
        with self._cv:
            self._servers[int(index)] = str(addr)
            self._beats[f"server:{int(index)}"] = time.monotonic()
            self._cv.notify_all()
        return True

    # ---- liveness (ps-lite postoffice heartbeat-map parity) ---- #

    def heartbeat(self, role, node_id):
        """Record a node's liveness beat (ps-lite Postoffice keeps the
        same heartbeat map; there is no elastic replacement in the
        reference either — SURVEY §5.3 — detection feeds the operator /
        launcher, recovery is checkpoint/restart)."""
        with self._cv:
            self._beats[f"{role}:{node_id}"] = time.monotonic()
        return True

    def health(self, stale_after=15.0):
        """{node: {age_s, alive}} for every node that ever beat; a node
        silent for > stale_after seconds reports alive=False."""
        now = time.monotonic()
        with self._cv:
            return {node: {"age_s": round(now - t, 3),
                           "alive": (now - t) <= float(stale_after)}
                    for node, t in self._beats.items()}

    def get_servers(self, expected, timeout=60.0):
        """Block until ``expected`` servers registered; return addresses
        ordered by server index.  TimeoutError (surfaced client-side as a
        server error) when the group never completes."""
        deadline = time.time() + float(timeout)
        with self._cv:
            while len(self._servers) < int(expected):
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(
                        f"scheduler rendezvous: {len(self._servers)}/"
                        f"{expected} servers registered within {timeout}s")
                self._cv.wait(remaining)
            return [a for _, a in sorted(self._servers.items())]

    def num_servers(self):
        with self._cv:
            return len(self._servers)

    def serve_tcp(self, port, block=True):
        self._tcp = _serve_object_tcp(self, port, block)
        return self._tcp

    def shutdown(self):
        if getattr(self, "_tcp", None) is not None:
            self._tcp.shutdown()
            self._tcp = None

    @classmethod
    def serve_from_env(cls):
        port = envvars.get_int("HETU_SCHEDULER_PORT")
        cls().serve_tcp(port)


def _register_with_scheduler(port):
    """Server-side registration (called by serve_from_env when a
    scheduler is configured).  Also starts the server's ongoing
    liveness beats: register_server only SEEDS the health map — without
    beats every healthy server would read dead after the staleness
    window."""
    sched = envvars.get_str("HETU_SCHEDULER_ADDR")
    if not sched:
        return
    from .client import _TCPTransport
    host, sport = sched.rsplit(":", 1)
    t = _TCPTransport(host, int(sport))
    index = envvars.get_int("HETU_PS_INDEX")
    adv = envvars.get_str("HETU_PS_ADVERTISE") \
        or f"{socket.gethostname()}:{port}"
    t.call("register_server", index, adv)
    t.close()
    interval = envvars.get_float("HETU_HEARTBEAT_INTERVAL")
    srv = PSServer.get()
    # stoppable + restart-safe: shutdown() must silence the beats (a
    # dead server that keeps beating defeats the liveness map), and a
    # re-register must not stack threads for a stale index
    old = getattr(srv, "_server_hb_stop", None)
    if old is not None:
        old.set()
    stop = threading.Event()
    srv._server_hb_stop = stop

    def beat():
        bt = _TCPTransport(host, int(sport),
                           timeout=max(1.0, interval / 2),
                           connect_timeout=max(1.0, interval / 2),
                           retries=1)
        while not stop.is_set():
            try:
                bt.call("heartbeat", "server", index)
            except Exception:
                pass
            stop.wait(interval)
        bt.close()

    threading.Thread(target=beat, daemon=True,
                     name=f"ps-heartbeat-server-{index}").start()


