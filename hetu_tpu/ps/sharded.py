"""Sharded PS client: one logical parameter server over N server
processes.

Reference: ps-lite's server GROUP — keys are range-partitioned across
servers by the Postoffice (ps-lite/include/ps/internal/postoffice.h), so
embedding traffic and storage scale with server count.  Here:

- 2-D tables are ROW-sharded: server s stores rows {i : i % N == s} at
  local index i // N (round-robin balances hot heads of zipfian id
  distributions better than contiguous ranges).  Sparse push/pull split
  the id set per shard and fan out concurrently; dense pull/push
  reassemble/scatter the full table.
- other params route whole to ``hash(key) % N``.
- coordination ops (barrier, SSP clocks, preduce matchmaking) live on
  server 0 — they are tiny and need a single view.
- the HET cache sync protocol (versioned sync/push_embedding) is NOT
  row-sharded here; point the cache at one server of the group.

Which rows-sharding applies to a key is recorded on server 0
(``__rows__<key>`` metadata), so a worker that did not create the table
still routes correctly.

``PSClient.get()`` returns this client automatically when the launcher
exposes several servers via HETU_PS_ADDRS.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .client import PSClient, _TCPTransport, _LocalTransport


class _LocalServerTransport:
    """Like _LocalTransport but against an explicit server instance (for
    in-process multi-server tests)."""

    def __init__(self, server):
        self.server = server

    def call(self, method, *args, **kwargs):
        return getattr(self.server, method)(*args, **kwargs)

    def close(self):
        pass


class ShardedPSClient:
    def __init__(self, addrs=None, servers=None, rank=0, nrank=1):
        if servers is not None:
            transports = [_LocalServerTransport(s) for s in servers]
        else:
            addrs = addrs or os.environ.get("HETU_PS_ADDRS", "").split(",")
            addrs = [a for a in addrs if a]
            if not addrs:
                transports = [_LocalTransport()]
            else:
                transports = []
                for a in addrs:
                    host, port = a.rsplit(":", 1)
                    transports.append(_TCPTransport(host, int(port)))
        self.clients = [PSClient(t, rank=rank, nrank=nrank)
                        for t in transports]
        self.n = len(self.clients)
        self.rank = rank
        self.nrank = nrank
        # _pool serves EXTERNAL async submissions (the executor's
        # ps_lookup_async duck-types it); _fan_pool is private to the
        # per-shard fan-out — sharing one pool deadlocks when an external
        # task occupying every worker then blocks on _fan results
        self._pool = ThreadPoolExecutor(
            max_workers=max(self.n, 2), thread_name_prefix="ps-shard")
        self._fan_pool = ThreadPoolExecutor(
            max_workers=max(self.n, 2), thread_name_prefix="ps-fan")
        self._row_sharded = {}      # key -> (rows, width) or None

    # ------------------------------------------------------------------ #

    def _home(self, key):
        import zlib
        return self.clients[zlib.crc32(key.encode()) % self.n]

    def _rows_of(self, key):
        meta = self._meta_of(key)
        return None if meta is None else meta[0]

    def _meta_of(self, key):
        if key in self._row_sharded:
            return self._row_sharded[key]
        try:
            arr = np.asarray(self.clients[0].pull("__rows__" + key))
            meta = (int(arr[0]), int(arr[1]) if arr.size > 1 else None)
        except Exception:
            meta = None
        self._row_sharded[key] = meta
        return meta

    def _fan(self, fn_per_shard):
        futs = [self._fan_pool.submit(fn_per_shard, s)
                for s in range(self.n)]
        return [f.result() for f in futs]

    # ---------------- Worker API ---------------- #

    def param_set(self, key, value, opt=None, opt_args=None):
        value = np.asarray(value, np.float32)
        if value.ndim == 2 and self.n > 1:
            self.clients[0].param_set("__rows__" + key,
                                      np.asarray(value.shape, np.float32))
            self._row_sharded[key] = (value.shape[0], value.shape[1])
            self._fan(lambda s: self.clients[s].param_set(
                key, value[s::self.n], opt=opt, opt_args=opt_args))
            return True
        self._row_sharded[key] = None
        return self._home(key).param_set(key, value, opt=opt,
                                         opt_args=opt_args)

    def parameter_init(self, key, shape, **kw):
        # sharded init of 2-D tables is delegated to param_set by the
        # executor bridge; plain inits route whole
        self._row_sharded[key] = None
        return self._home(key).parameter_init(key, shape, **kw)

    def pull(self, key):
        rows = self._rows_of(key)
        if rows is None:
            return self._home(key).pull(key)
        parts = self._fan(lambda s: np.asarray(self.clients[s].pull(key)))
        out = np.empty((rows, parts[0].shape[1]), np.float32)
        for s, p in enumerate(parts):
            out[s::self.n] = p
        return out

    def push(self, key, grad):
        grad = np.asarray(grad, np.float32)
        rows = self._rows_of(key)
        if rows is None:
            return self._home(key).push(key, grad)
        self._fan(lambda s: self.clients[s].push(key, grad[s::self.n]))

    def sparse_pull(self, key, ids):
        ids = np.asarray(ids, np.int64)
        meta = self._meta_of(key)
        if meta is None:
            return self._home(key).sparse_pull(key, ids)
        if len(ids) == 0:
            return np.empty((0, meta[1] or 0), np.float32)
        shard = ids % self.n
        local = ids // self.n

        def one(s):
            m = shard == s
            if not m.any():
                return None
            return np.asarray(self.clients[s].sparse_pull(key, local[m]))
        parts = self._fan(one)
        width = meta[1] or next(p.shape[1] for p in parts
                                if p is not None)
        out = np.empty((len(ids), width), np.float32)
        for s, p in enumerate(parts):
            if p is not None:
                out[shard == s] = p
        return out

    def sparse_push(self, key, ids, rows_arr):
        ids = np.asarray(ids, np.int64)
        rows_arr = np.asarray(rows_arr, np.float32)
        if self._rows_of(key) is None:
            return self._home(key).sparse_push(key, ids, rows_arr)
        shard = ids % self.n
        local = ids // self.n

        def one(s):
            m = shard == s
            if m.any():
                self.clients[s].sparse_push(key, local[m], rows_arr[m])
        self._fan(one)

    def sd_pushpull(self, key, ids, rows_arr, pull_ids=None):
        ids = np.asarray(ids, np.int64)
        rows_arr = np.asarray(rows_arr, np.float32)
        pids = ids if pull_ids is None else np.asarray(pull_ids, np.int64)
        meta = self._meta_of(key)
        if meta is None:
            return self._home(key).sd_pushpull(key, ids, rows_arr, pids)
        # ONE fused round trip per shard (this is the hot CTR path)
        shard, local = ids % self.n, ids // self.n
        pshard, plocal = pids % self.n, pids // self.n

        def one(s):
            m, mp = shard == s, pshard == s
            if not m.any() and not mp.any():
                return None
            return np.asarray(self.clients[s].sd_pushpull(
                key, local[m], rows_arr[m], plocal[mp]))
        parts = self._fan(one)
        width = meta[1] or next(p.shape[1] for p in parts
                                if p is not None)
        out = np.empty((len(pids), width), np.float32)
        for s, p in enumerate(parts):
            if p is not None:
                out[pshard == s] = p
        return out

    ss_pushpull = sd_pushpull

    def save(self, key, path):
        os.makedirs(path, exist_ok=True)
        if self._rows_of(key) is None:
            return self._home(key).save(key, path)
        table = self.pull(key)
        np.save(os.path.join(path, f"ps_param_{key}.npy"), table)

    def load(self, key, path):
        if self._rows_of(key) is None:
            # the server loads from ITS filesystem (multi-host: the file
            # lives where save() wrote it)
            return self._home(key).load(key, path)
        arr = np.load(os.path.join(path, f"ps_param_{key}.npy"))
        # param_assign keeps each shard's server optimizer + slot state
        self._fan(lambda s: self.clients[s].t.call(
            "param_assign", key, arr[s::self.n]))

    def clear(self, key):
        self._row_sharded.pop(key, None)
        self._fan(lambda s: self.clients[s].clear(key))

    def wait(self, ticket):
        return self.clients[0].wait(ticket)

    # ---------------- coordination: server 0 ---------------- #

    def ssp_init(self, group=0, bound=0):
        return self.clients[0].ssp_init(group, bound)

    def ssp_sync(self, group=0):
        return self.clients[0].ssp_sync(group)

    def BarrierWorker(self, group=0):
        return self.clients[0].BarrierWorker(group)

    def preduce_get_partner(self, key, max_worker, wait_time):
        return self.clients[0].preduce_get_partner(key, max_worker,
                                                   wait_time)

    def getLoads(self):
        return self._fan(lambda s: self.clients[s].getLoads())

    def finalize(self):
        self._pool.shutdown(wait=True)
        self._fan_pool.shutdown(wait=True)
        for c in self.clients:
            c.finalize()

    # cache sync protocol: single-server only (see module docstring)
    def sync_embedding(self, *a, **kw):
        raise NotImplementedError(
            "HET cache sync is not row-sharded; point the CacheSparseTable "
            "at one server of the group")

    push_embedding = sync_embedding
    push_sync_embedding = sync_embedding
