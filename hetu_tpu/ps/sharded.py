"""Sharded PS client: one logical parameter server over N server
processes, with optional replica-group failover.

Reference: ps-lite's server GROUP — keys are range-partitioned across
servers by the Postoffice (ps-lite/include/ps/internal/postoffice.h), so
embedding traffic and storage scale with server count.  Here:

- 2-D tables are ROW-sharded: server s stores rows {i : i % N == s} at
  local index i // N (round-robin balances hot heads of zipfian id
  distributions better than contiguous ranges).  Sparse push/pull split
  the id set per shard and fan out concurrently; dense pull/push
  reassemble/scatter the full table.
- other params route whole to ``hash(key) % N``.
- coordination ops (barrier, SSP clocks, preduce matchmaking) live on
  server 0 — they are tiny and need a single view.
- the HET cache sync protocol (versioned sync/push_embedding) is NOT
  row-sharded here; point the cache at one server of the group.

Which rows-sharding applies to a key is recorded on server 0
(``__rows__<key>`` metadata), so a worker that did not create the table
still routes correctly.

Replication / failover (``HETU_PS_REPLICATE=1`` or ``replicate=True``,
N > 1 only): every key primaried on server ``s`` keeps a replica under
``__rep__<key>`` on its ring backup ``(s+1) % N``.  Mutations are
applied to the primary and then async-replayed (FIFO, one replication
thread, so stateful server optimizers see the identical update order)
onto the replica, whose own server-side optimizer instance walks the
identical trajectory.  When an op on a primary exhausts the transport's
retry budget (PSConnectionError — the wire's (client_id, seq) replay
cache makes the retries themselves idempotent), the client marks the
shard failed and fails over to the backup's replica for reads AND
writes; the backup is then the authority, so nothing double-applies.  A
restarted primary must be re-seeded from its replica BEFORE rejoining —
``resync_shard(s)`` (or the supervisor's ``resync_primary``) copies
value + optimizer spec back and returns traffic to the primary.
Caveat: resync re-creates optimizer slot state fresh (exact-trajectory
equivalence across a failover holds for SGD; stateful optimizers
converge but do not match bit-for-bit after a resync).

Failovers/resyncs append structured records to ``failure_events``; when
a rendezvous scheduler is configured its heartbeat map is consulted
(best-effort) to stamp the event with cluster-level liveness.

``PSClient.get()`` returns this client automatically when the launcher
exposes several servers via HETU_PS_ADDRS.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .client import (PSClient, PSConnectionError, _TCPTransport,
                     _LocalTransport, _local_chaos_call)
from .. import locks

REPLICA_PREFIX = "__rep__"


def _env_replicate():
    from .. import envvars
    return envvars.get_bool("HETU_PS_REPLICATE")


class _LocalServerTransport:
    """Like _LocalTransport but against an explicit server instance (for
    in-process multi-server tests)."""

    def __init__(self, server):
        self.server = server

    def call(self, method, *args, **kwargs):
        return _local_chaos_call(self.server, method, args, kwargs)

    def close(self):
        pass


def _plain(key):
    return key


def _replica(key):
    return REPLICA_PREFIX + key


class ShardedPSClient:
    def __init__(self, addrs=None, servers=None, rank=0, nrank=1,
                 replicate=None):
        if servers is not None:
            transports = [_LocalServerTransport(s) for s in servers]
        else:
            from .. import envvars
            addrs = addrs or envvars.get_list("HETU_PS_ADDRS")
            addrs = [a for a in addrs if a]
            if not addrs:
                transports = [_LocalTransport()]
            else:
                transports = []
                for a in addrs:
                    host, port = a.rsplit(":", 1)
                    transports.append(_TCPTransport(host, int(port)))
        self.clients = [PSClient(t, rank=rank, nrank=nrank)
                        for t in transports]
        self.n = len(self.clients)
        self.rank = rank
        self.nrank = nrank
        self.replicate = (_env_replicate() if replicate is None
                          else bool(replicate)) and self.n > 1
        # _pool serves EXTERNAL async submissions (the executor's
        # ps_lookup_async duck-types it); _fan_pool is private to the
        # per-shard fan-out — sharing one pool deadlocks when an external
        # task occupying every worker then blocks on _fan results
        self._pool = ThreadPoolExecutor(
            max_workers=max(self.n, 2), thread_name_prefix="ps-shard")
        self._fan_pool = ThreadPoolExecutor(
            max_workers=max(self.n, 2), thread_name_prefix="ps-fan")
        # ONE replication worker: FIFO replay keeps the replica's
        # (stateful) server optimizer on the primary's update order
        self._rep_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ps-replica") \
            if self.replicate else None
        self._row_sharded = {}      # key -> (rows, width) or None
        self._failed = set()        # shard indices currently failed over
        self._fail_mu = locks.TracedLock("ps.shard_fail")
        self.failure_events = []    # structured failover/resync log

    # ------------------------------------------------------------------ #

    def _event(self, kind, **fields):
        # failover/resync records ride the failure stream of the one
        # telemetry sink (merged JSONL + in-memory list, same shape)
        from .. import telemetry
        rec = telemetry.emit(kind, _stream="failure", **fields)
        self.failure_events.append(rec)
        print(f"[ps-client] {kind}: {fields}", flush=True)

    def _sched_health(self):
        """Best-effort scheduler liveness snapshot for event context."""
        from .. import envvars
        sched = envvars.get_str("HETU_SCHEDULER_ADDR")
        if not sched:
            return None
        try:
            host, port = sched.rsplit(":", 1)
            t = _TCPTransport(host, int(port), timeout=2.0,
                              connect_timeout=2.0, retries=1)
            h = t.call("health")
            t.close()
            return {k: v["alive"] for k, v in h.items()}
        except Exception:
            return None

    def _backup(self, s):
        return (s + 1) % self.n

    def _mark_failed(self, s, err):
        with self._fail_mu:
            if s in self._failed:
                return
            self._failed.add(s)
        self._event("ps_shard_failover", shard=s, backup=self._backup(s),
                    error=f"{type(err).__name__}: {err}"[:200],
                    scheduler_view=self._sched_health())

    def _exec(self, s, op):
        """Run ``op(client, keymap)`` against shard ``s``'s primary,
        failing over to the ring backup's replica namespace when the
        primary is (or becomes) unreachable."""
        with self._fail_mu:
            failed = s in self._failed
        if not failed:
            try:
                return op(self.clients[s], _plain)
            except PSConnectionError as e:
                if not self.replicate:
                    raise
                self._mark_failed(s, e)
        return op(self.clients[self._backup(s)], _replica)

    def _replicate_op(self, s, op):
        """Async FIFO replay of a mutation onto shard ``s``'s replica
        (no-op when the shard is failed over — the backup already took
        the write directly)."""
        if not self.replicate:
            return
        b = self._backup(s)
        with self._fail_mu:
            if s in self._failed:
                return
        backup = self.clients[b]

        def run():
            with self._fail_mu:
                # the backup HOST is also shard b's primary: if that
                # shard is already marked dead, don't burn a retry
                # budget per queued write against a dead socket.  (A
                # write whose SOURCE shard failed after queueing must
                # still run: it carries a primary-applied mutation the
                # now-authoritative replica lacks.)
                if b in self._failed:
                    return
            try:
                op(backup, _replica)
            except PSConnectionError as e:
                self._event("ps_replica_write_failed", shard=s,
                            backup=b,
                            error=f"{type(e).__name__}: {e}"[:200])
                # a dead backup is ALSO a dead primary (same process):
                # propagate so shard b's traffic fails over promptly
                self._mark_failed(b, e)
            except Exception as e:  # noqa: BLE001 — degraded, not fatal
                self._event("ps_replica_write_failed", shard=s,
                            backup=b,
                            error=f"{type(e).__name__}: {e}"[:200])
        self._rep_pool.submit(run)

    def _home_idx(self, key):
        import zlib
        return zlib.crc32(key.encode()) % self.n

    def _home(self, key):
        return self.clients[self._home_idx(key)]

    def _rows_of(self, key):
        meta = self._meta_of(key)
        return None if meta is None else meta[0]

    def _meta_of(self, key):
        if key in self._row_sharded:
            return self._row_sharded[key]
        try:
            arr = np.asarray(self._exec(
                0, lambda cli, km: cli.pull(km("__rows__" + key))))
            meta = (int(arr[0]), int(arr[1]) if arr.size > 1 else None)
        except PSConnectionError:
            raise           # a dead, un-replicated server 0 must stay
        except Exception:   # loud — "no metadata" would misroute keys
            meta = None
        self._row_sharded[key] = meta
        return meta

    def _fan(self, fn_per_shard):
        futs = [self._fan_pool.submit(fn_per_shard, s)
                for s in range(self.n)]
        return [f.result() for f in futs]

    # ---------------- Worker API ---------------- #

    def param_set(self, key, value, opt=None, opt_args=None):
        value = np.asarray(value, np.float32)
        if value.ndim == 2 and self.n > 1:
            shape_arr = np.asarray(value.shape, np.float32)
            self._exec(0, lambda cli, km: cli.param_set(
                km("__rows__" + key), shape_arr))
            if self.replicate:
                # synchronous at creation: the replica must exist BEFORE
                # any failure can route to it (creation is rare; the hot
                # path replicates async)
                self.clients[self._backup(0)].param_set(
                    _replica("__rows__" + key), shape_arr)
            self._row_sharded[key] = (value.shape[0], value.shape[1])

            def one(s):
                self._exec(s, lambda cli, km: cli.param_set(
                    km(key), value[s::self.n], opt=opt, opt_args=opt_args))
                if self.replicate:
                    self.clients[self._backup(s)].param_set(
                        _replica(key), value[s::self.n], opt=opt,
                        opt_args=opt_args)
            self._fan(one)
            return True
        self._row_sharded[key] = None
        h = self._home_idx(key)
        out = self._exec(h, lambda cli, km: cli.param_set(
            km(key), value, opt=opt, opt_args=opt_args))
        if self.replicate:
            self.clients[self._backup(h)].param_set(
                _replica(key), value, opt=opt, opt_args=opt_args)
        return out

    def parameter_init(self, key, shape, **kw):
        # sharded init of 2-D tables is delegated to param_set by the
        # executor bridge; plain inits route whole.  Replication uses
        # the same deterministic (seeded) init, so replica == primary.
        self._row_sharded[key] = None
        h = self._home_idx(key)
        out = self._exec(h, lambda cli, km: cli.parameter_init(
            km(key), shape, **kw))
        if self.replicate:
            self.clients[self._backup(h)].parameter_init(
                _replica(key), shape, **kw)
        return out

    def pull(self, key):
        rows = self._rows_of(key)
        if rows is None:
            return self._exec(self._home_idx(key),
                              lambda cli, km: cli.pull(km(key)))
        parts = self._fan(lambda s: np.asarray(self._exec(
            s, lambda cli, km: cli.pull(km(key)))))
        out = np.empty((rows, parts[0].shape[1]), np.float32)
        for s, p in enumerate(parts):
            out[s::self.n] = p
        return out

    def push(self, key, grad):
        grad = np.asarray(grad, np.float32)
        rows = self._rows_of(key)
        if rows is None:
            h = self._home_idx(key)
            out = self._exec(h, lambda cli, km: cli.push(km(key), grad))
            self._replicate_op(h, lambda cli, km: cli.push(km(key), grad))
            return out

        def one(s):
            part = grad[s::self.n]
            self._exec(s, lambda cli, km: cli.push(km(key), part))
            self._replicate_op(s, lambda cli, km: cli.push(km(key), part))
        self._fan(one)

    def sparse_pull(self, key, ids):
        ids = np.asarray(ids, np.int64)
        meta = self._meta_of(key)
        if meta is None:
            return self._exec(self._home_idx(key),
                              lambda cli, km: cli.sparse_pull(km(key), ids))
        if len(ids) == 0:
            return np.empty((0, meta[1] or 0), np.float32)
        shard = ids % self.n
        local = ids // self.n

        def one(s):
            m = shard == s
            if not m.any():
                return None
            sub = local[m]
            return np.asarray(self._exec(
                s, lambda cli, km: cli.sparse_pull(km(key), sub)))
        parts = self._fan(one)
        width = meta[1] or next(p.shape[1] for p in parts
                                if p is not None)
        out = np.empty((len(ids), width), np.float32)
        for s, p in enumerate(parts):
            if p is not None:
                out[shard == s] = p
        return out

    def sparse_push(self, key, ids, rows_arr):
        ids = np.asarray(ids, np.int64)
        rows_arr = np.asarray(rows_arr, np.float32)
        if self._rows_of(key) is None:
            h = self._home_idx(key)
            out = self._exec(h, lambda cli, km: cli.sparse_push(
                km(key), ids, rows_arr))
            self._replicate_op(h, lambda cli, km: cli.sparse_push(
                km(key), ids, rows_arr))
            return out
        shard = ids % self.n
        local = ids // self.n

        def one(s):
            m = shard == s
            if m.any():
                sub, rsub = local[m], rows_arr[m]
                self._exec(s, lambda cli, km: cli.sparse_push(
                    km(key), sub, rsub))
                self._replicate_op(s, lambda cli, km: cli.sparse_push(
                    km(key), sub, rsub))
        self._fan(one)

    def sd_pushpull(self, key, ids, rows_arr, pull_ids=None):
        ids = np.asarray(ids, np.int64)
        rows_arr = np.asarray(rows_arr, np.float32)
        pids = ids if pull_ids is None else np.asarray(pull_ids, np.int64)
        meta = self._meta_of(key)
        if meta is None:
            h = self._home_idx(key)
            out = self._exec(h, lambda cli, km: cli.sd_pushpull(
                km(key), ids, rows_arr, pids))
            self._replicate_op(h, lambda cli, km: cli.sparse_push(
                km(key), ids, rows_arr))
            return out
        # ONE fused round trip per shard (this is the hot CTR path)
        shard, local = ids % self.n, ids // self.n
        pshard, plocal = pids % self.n, pids // self.n

        def one(s):
            m, mp = shard == s, pshard == s
            if not m.any() and not mp.any():
                return None
            sub, rsub, psub = local[m], rows_arr[m], plocal[mp]
            out = np.asarray(self._exec(
                s, lambda cli, km: cli.sd_pushpull(km(key), sub, rsub,
                                                   psub)))
            if m.any():
                # replicate the PUSH half only (the pull is a read)
                self._replicate_op(s, lambda cli, km: cli.sparse_push(
                    km(key), sub, rsub))
            return out
        parts = self._fan(one)
        width = meta[1] or next(p.shape[1] for p in parts
                                if p is not None)
        out = np.empty((len(pids), width), np.float32)
        for s, p in enumerate(parts):
            if p is not None:
                out[pshard == s] = p
        return out

    ss_pushpull = sd_pushpull

    def save(self, key, path):
        os.makedirs(path, exist_ok=True)
        if self._rows_of(key) is None:
            return self._exec(self._home_idx(key),
                              lambda cli, km: cli.save(km(key), path))
        table = self.pull(key)
        np.save(os.path.join(path, f"ps_param_{key}.npy"), table)

    def load(self, key, path):
        if self._rows_of(key) is None:
            # the server loads from ITS filesystem (multi-host: the file
            # lives where save() wrote it)
            return self._exec(self._home_idx(key),
                              lambda cli, km: cli.load(km(key), path))
        arr = np.load(os.path.join(path, f"ps_param_{key}.npy"))
        # param_assign keeps each shard's server optimizer + slot state

        def one(s):
            part = arr[s::self.n]
            self._exec(s, lambda cli, km: cli.t.call(
                "param_assign", km(key), part))
            self._replicate_op(s, lambda cli, km: cli.t.call(
                "param_assign", km(key), part))
        self._fan(one)

    # ---------------- versioned weight pull ---------------- #
    # Live weight sync (serving/weight_sync.py): the trainer stamps a
    # monotonically increasing fleet version next to the weights it
    # pushes; a serving-side coordinator pulls the pytree under a
    # torn-read guard (version re-checked after the last key) so a
    # push racing the pull can never hand the fleet a mixed snapshot.

    WEIGHTS_VERSION_KEY = "__weights_version__"

    def set_weights_version(self, version):
        """Stamp the resident weights with ``version`` (call AFTER the
        weight push completes — pullers treat the stamp as the commit
        point)."""
        self.param_set(self.WEIGHTS_VERSION_KEY,
                       np.asarray([float(version)], np.float32))

    def weights_version(self):
        """The committed weight version, or None when never stamped."""
        try:
            v = np.asarray(self.pull(self.WEIGHTS_VERSION_KEY)).ravel()
        except Exception:  # noqa: BLE001 — unstamped PS
            return None
        return int(v[0]) if v.size else None

    def pull_versioned(self, keys, retries=1):
        """Pull ``keys`` as one version-consistent snapshot: returns
        ``(params, version)``.  The version stamp is read before and
        after the keys; a mismatch (a push landed mid-pull) retries the
        whole snapshot, then raises — a torn pytree must never reach a
        serving engine."""
        last = (None, None)
        for _ in range(int(retries) + 1):
            v0 = self.weights_version()
            params = {k: self.pull(k) for k in keys}
            v1 = self.weights_version()
            if v0 == v1:
                return params, v1
            last = (v0, v1)
            self._event("ps_version_skew", before=v0, after=v1)
        raise RuntimeError(
            f"versioned pull torn across a push "
            f"(v{last[0]} -> v{last[1]}) after {retries + 1} attempts")

    def clear(self, key):
        self._row_sharded.pop(key, None)

        def one(s):
            self._exec(s, lambda cli, km: cli.clear(km(key)))
            self._replicate_op(s, lambda cli, km: cli.clear(km(key)))
        self._fan(one)

    def wait(self, ticket):
        return self.clients[0].wait(ticket)

    # ---------------- serving KV cold store (ISSUE 17) ------------- #
    # spilled prefix payloads route whole to hash(key) % N — same as
    # non-row-sharded params — with the usual async replica write and
    # primary-failover read through _exec

    def kv_put(self, key, payload, version=0):
        h = self._home_idx(key)
        out = self._exec(
            h, lambda cli, km: cli.kv_put(km(key), payload, version))
        self._replicate_op(
            h, lambda cli, km: cli.kv_put(km(key), payload, version))
        return out

    def kv_get(self, key):
        return self._exec(
            self._home_idx(key), lambda cli, km: cli.kv_get(km(key)))

    def kv_del(self, key):
        h = self._home_idx(key)
        out = self._exec(h, lambda cli, km: cli.kv_del(km(key)))
        self._replicate_op(h, lambda cli, km: cli.kv_del(km(key)))
        return out

    def kv_keys(self):
        seen = set()
        for keys in self._fan(
                lambda s: self._exec(s, lambda cli, km: cli.kv_keys())):
            for k in keys or ():
                if not k.startswith(REPLICA_PREFIX):
                    seen.add(k)
        return sorted(seen)

    # ---------------- failover lifecycle ---------------- #

    def drain_replication(self, timeout=30.0):
        """Block until queued async replica writes have been applied
        (the chaos tests compare replica contents; callers normally
        never need this)."""
        if self._rep_pool is None:
            return
        self._rep_pool.submit(lambda: None).result(timeout=timeout)

    def failed_shards(self):
        with self._fail_mu:
            return sorted(self._failed)

    def resync_shard(self, s):
        """Copy shard ``s``'s replica (held by its ring backup) back
        onto a RESTARTED primary, then return traffic to it.  The
        primary must be reachable; value + optimizer spec are restored
        (optimizer slot state restarts fresh — see module docstring)."""
        self.drain_replication()
        b = self._backup(s)
        backup, primary = self.clients[b], self.clients[s]
        restored = []
        for rkey in sorted(backup.getLoads()):
            if not rkey.startswith(REPLICA_PREFIX):
                continue
            key = rkey[len(REPLICA_PREFIX):]
            _, opt, opt_args = backup.t.call("param_spec", rkey)
            primary.param_set(key, np.asarray(backup.pull(rkey)),
                              opt=opt, opt_args=opt_args)
            restored.append(key)
        # the restarted server is also the ring BACKUP of shard s-1:
        # rebuild that replica from its (live) primary, or a later
        # failure of s-1 would fail over onto pre-crash data
        prev = (s - 1) % self.n
        if prev != s:
            try:
                pcli = self.clients[prev]
                for key in sorted(pcli.getLoads()):
                    if key.startswith(REPLICA_PREFIX):
                        continue
                    _, opt, opt_args = pcli.t.call("param_spec", key)
                    primary.param_set(_replica(key),
                                      np.asarray(pcli.pull(key)),
                                      opt=opt, opt_args=opt_args)
            except Exception as e:  # noqa: BLE001 — degraded, not fatal
                self._event("ps_replica_rebuild_failed", shard=prev,
                            backup=s,
                            error=f"{type(e).__name__}: {e}"[:200])
        with self._fail_mu:
            self._failed.discard(s)
        self._event("ps_shard_resynced", shard=s, backup=b,
                    keys=len(restored))
        return restored

    # ---------------- coordination: server 0 ---------------- #

    def ssp_init(self, group=0, bound=0):
        return self.clients[0].ssp_init(group, bound)

    def ssp_sync(self, group=0):
        return self.clients[0].ssp_sync(group)

    def BarrierWorker(self, group=0):
        return self.clients[0].BarrierWorker(group)

    def preduce_get_partner(self, key, max_worker, wait_time):
        return self.clients[0].preduce_get_partner(key, max_worker,
                                                   wait_time)

    def getLoads(self):
        return self._fan(lambda s: self._exec(
            s, lambda cli, km: cli.getLoads()))

    def finalize(self):
        self._pool.shutdown(wait=True)
        self._fan_pool.shutdown(wait=True)
        if self._rep_pool is not None:
            self._rep_pool.shutdown(wait=True)
        for c in self.clients:
            c.finalize()

    # cache sync protocol: single-server only (see module docstring)
    def sync_embedding(self, *a, **kw):
        raise NotImplementedError(
            "HET cache sync is not row-sharded; point the CacheSparseTable "
            "at one server of the group")

    push_embedding = sync_embedding
    push_sync_embedding = sync_embedding


def resync_primary(addrs, index):
    """Supervisor hook (launcher.run_cluster): after respawning the PS
    process at ``addrs[index]``, copy its replica back from the ring
    backup so it rejoins with current data.  Returns the restored key
    names."""
    c = ShardedPSClient(addrs=addrs, replicate=True)
    try:
        return c.resync_shard(index)
    finally:
        c.finalize()
