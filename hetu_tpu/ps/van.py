"""Native PS van: the C++ throughput tier for the sparse hot path
(reference ps-lite/src/zmq_van.h role; VERDICT r3 missing #5).

The Python ``PSServer`` remains the full-feature surface (PSFunc API,
optimizers, SSP/BSP, HET sync); ``NativeVan`` serves ONE pattern —
sparse push / pull / push-pull with server-side SGD on a registered
embedding table — entirely from C++ threads over a binary protocol, so
no Python executes per request.  The registered table IS the server's
numpy buffer (zero copy between the tiers); Python paths touching a
registered table coordinate through the van's per-table mutex
(``table_lock``/``table_unlock``).

    van = NativeVan()
    port = van.listen()
    van.register_sgd_table(0, server_value_array, lr=0.01)
    cli = VanClient("127.0.0.1", port, dim=value.shape[1])
    rows = cli.sd_pushpull(0, ids, grads)
"""

from __future__ import annotations

import ctypes
import socket
import struct

import numpy as np

from ..native import build_and_load

_OP_PUSH, _OP_PULL, _OP_PUSHPULL = 1, 2, 3
_HDR = struct.Struct("<BII")          # op, key, n  (little-endian)
_LEN = struct.Struct("<I")

_LIB = None


def _load():
    global _LIB
    if _LIB is None:
        lib = build_and_load("ps_van.cpp", "libps_van.so",
                             extra_flags=("-pthread",))
        if lib is not None:
            lib.van_create.restype = ctypes.c_void_p
            lib.van_listen.restype = ctypes.c_int
            lib.van_listen.argtypes = [ctypes.c_void_p, ctypes.c_int]
            f32p = ctypes.POINTER(ctypes.c_float)
            i64p = ctypes.POINTER(ctypes.c_int64)
            lib.van_register_sgd_table.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, f32p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_float, i64p]
            for name in ("van_table_lock", "van_table_unlock",
                         "van_stop", "van_destroy"):
                getattr(lib, name).argtypes = [ctypes.c_void_p] \
                    if name in ("van_stop", "van_destroy") else \
                    [ctypes.c_void_p, ctypes.c_uint32]
        _LIB = lib if lib is not None else False
    return _LIB or None


def van_available():
    return _load() is not None


class NativeVan:
    """Owns one C++ serving loop; tables are registered numpy buffers."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native van unavailable (no toolchain)")
        self._l = lib
        self._h = lib.van_create()
        self._tables = {}            # key -> value array (keepalive)
        self.port = None

    def listen(self, port=0):
        got = self._l.van_listen(self._h, int(port))
        if not got:
            raise OSError(f"van failed to bind port {port}")
        self.port = got
        return got

    def register_sgd_table(self, key, value, lr, versions=None):
        """``value``: C-contiguous float32 [nrows, dim] — the SERVER's
        buffer; updates land in place.  ``versions``: optional int64
        [nrows] HET version counters, bumped per pushed row."""
        value = np.ascontiguousarray(value, np.float32)
        assert value.ndim == 2
        vp = None
        if versions is not None:
            versions = np.ascontiguousarray(versions, np.int64)
            assert len(versions) == value.shape[0]
            vp = versions.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        self._l.van_register_sgd_table(
            self._h, int(key),
            value.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            value.shape[0], value.shape[1], float(lr), vp)
        # keep BOTH buffers alive for the van's lifetime
        self._tables[int(key)] = (value, versions)
        return value

    def table_lock(self, key):
        self._l.van_table_lock(self._h, int(key))

    def table_unlock(self, key):
        self._l.van_table_unlock(self._h, int(key))

    def table_array(self, key):
        return self._tables[int(key)][0]

    def stop(self):
        if self._h:
            self._l.van_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class VanSharedLock:
    """Composite lock for a table served by BOTH tiers: acquires the
    python _Param lock AND the van's per-table C++ mutex, so python
    PSFunc paths and C++ van threads serialize on the same buffer.
    Drop-in for the ``with p.lock:`` sites in ps/server.py."""

    def __init__(self, pylock, van, key_id):
        self.pylock = pylock
        self.van = van
        self.key_id = int(key_id)

    def __enter__(self):
        self.pylock.acquire()
        self.van.table_lock(self.key_id)
        return self

    def __exit__(self, *exc):
        self.van.table_unlock(self.key_id)
        self.pylock.release()
        return False


class VanClient:
    """Blocking binary-protocol client for one van."""

    def __init__(self, host, port, dim, timeout=30.0):
        self.dim = int(dim)
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _roundtrip(self, op, key, ids, rows, want_rows):
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        n = len(ids)
        parts = [_HDR.pack(op, key, n), memoryview(ids).cast("B")]
        if rows is not None:
            rows = np.ascontiguousarray(rows, np.float32).reshape(
                n, self.dim)
            parts.append(memoryview(rows).cast("B"))
        total = sum(len(p) for p in parts)
        # scatter-gather send: no join copy of the multi-MB row payload
        self._sock.sendmsg([_LEN.pack(total)] + parts)
        out_len = self._recv_exact(4)
        (m,) = _LEN.unpack(out_len)
        payload = self._recv_exact(m)
        if payload[0] != 1:
            raise RuntimeError(
                "van rejected the request (unknown key, id out of "
                "range, or malformed frame)")
        if want_rows:
            arr = np.frombuffer(payload, np.float32, offset=1)
            return arr.reshape(n, self.dim).copy()
        return None

    def _recv_exact(self, n):
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            r = self._sock.recv_into(view[got:])
            if r == 0:
                raise ConnectionError("van closed the connection")
            got += r
        return bytes(buf)

    def push(self, key, ids, grads):
        self._roundtrip(_OP_PUSH, key, ids, grads, want_rows=False)

    def pull(self, key, ids):
        return self._roundtrip(_OP_PULL, key, ids, None, want_rows=True)

    def sd_pushpull(self, key, ids, grads):
        return self._roundtrip(_OP_PUSHPULL, key, ids, grads,
                               want_rows=True)

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
