"""Native PS van: the C++ throughput tier for the sparse hot path
(reference ps-lite/src/zmq_van.h role; VERDICT r3 missing #5).

The Python ``PSServer`` remains the full-feature surface (PSFunc API,
optimizers, SSP/BSP, HET sync); ``NativeVan`` serves ONE pattern —
sparse push / pull / push-pull with a server-side optimizer on a
registered embedding table — entirely from C++ threads over a binary
protocol, so no Python executes per request.  The whole server
optimizer family is applied in-kernel (SGD/Momentum/Nesterov/AdaGrad/
Adam — reference ps-lite/include/ps/server/optimizer.h:36-275).  The
registered table IS the server's numpy buffer, and the optimizer slot
state (velocity / accumulator / m,v / Adam step) aliases the Python
tier's state arrays (zero copy between the tiers); Python paths
touching a registered table coordinate through the van's per-table
mutex (``table_lock``/``table_unlock``).

    van = NativeVan()
    port = van.listen()
    van.register_sgd_table(0, server_value_array, lr=0.01)
    cli = VanClient("127.0.0.1", port, dim=value.shape[1])
    rows = cli.sd_pushpull(0, ids, grads)
"""

from __future__ import annotations

import ctypes
import socket
import struct

import numpy as np

from ..native import build_and_load

_OP_PUSH, _OP_PULL, _OP_PUSHPULL, _OP_SYNCEMB = 1, 2, 3, 4
_HDR = struct.Struct("<BII")          # op, key, n  (little-endian)
_LEN = struct.Struct("<I")

_LIB = None


def _load():
    global _LIB
    if _LIB is None:
        lib = build_and_load("ps_van.cpp", "libps_van.so",
                             extra_flags=("-pthread",),
                             deps=("ps_kernels.h",))
        if lib is not None:
            lib.van_create.restype = ctypes.c_void_p
            lib.van_listen.restype = ctypes.c_int
            lib.van_listen.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_int]
            f32p = ctypes.POINTER(ctypes.c_float)
            i64p = ctypes.POINTER(ctypes.c_int64)
            lib.van_register_sgd_table.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, f32p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_float, i64p]
            lib.van_register_table.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, f32p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_int, f32p, f32p, i64p, i64p]
            for name in ("van_table_lock", "van_table_unlock",
                         "van_stop", "van_destroy"):
                getattr(lib, name).argtypes = [ctypes.c_void_p] \
                    if name in ("van_stop", "van_destroy") else \
                    [ctypes.c_void_p, ctypes.c_uint32]
        _LIB = lib if lib is not None else False
    return _LIB or None


def van_available():
    return _load() is not None


class NativeVan:
    """Owns one C++ serving loop; tables are registered numpy buffers."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native van unavailable (no toolchain)")
        self._l = lib
        self._h = lib.van_create()
        self._tables = {}            # key -> value array (keepalive)
        self.port = None

    def listen(self, port=0, bind_all=False):
        got = self._l.van_listen(self._h, int(port), int(bool(bind_all)))
        if not got:
            raise OSError(f"van failed to bind port {port}")
        self.port = got
        return got

    def register_sgd_table(self, key, value, lr, versions=None):
        """``value``: C-contiguous float32 [nrows, dim] — the SERVER's
        buffer; updates land in place.  ``versions``: optional int64
        [nrows] HET version counters, bumped per pushed row."""
        value = np.ascontiguousarray(value, np.float32)
        assert value.ndim == 2
        vp = None
        if versions is not None:
            versions = np.ascontiguousarray(versions, np.int64)
            assert len(versions) == value.shape[0]
            vp = versions.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        self._l.van_register_sgd_table(
            self._h, int(key),
            value.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            value.shape[0], value.shape[1], float(lr), vp)
        # keep BOTH buffers alive for the van's lifetime
        self._tables[int(key)] = (value, versions)
        return value

    def register_table(self, key, value, optimizer, state,
                       versions=None):
        """Register a table with its full server optimizer (reference
        zmq_van + server/optimizer.h: the C++ tier applies the SAME
        optimizer family the python tier does).

        ``optimizer``: a ``Server{SGD,Momentum,Nesterov,AdaGrad,Adam}``
        from ps/server.py.  ``state``: that param's slot-state dict —
        its arrays are (re)made contiguous, REPLACED IN PLACE in the
        dict, and registered, so both tiers advance ONE set of slots.
        Returns the (possibly re-allocated contiguous) value array the
        param must now point at.
        """
        from .server import (ServerAdaGrad, ServerAdam, ServerMomentum,
                             ServerSGD)
        value = np.ascontiguousarray(value, np.float32)
        assert value.ndim == 2
        f32p = ctypes.POINTER(ctypes.c_float)
        i64p = ctypes.POINTER(ctypes.c_int64)

        def _slot(name):
            arr = np.ascontiguousarray(state[name], np.float32)
            assert arr.shape == value.shape
            state[name] = arr          # the python tier must see the
            return arr                 # SAME memory the van mutates

        kind, hp1, hp2, eps, nesterov = 0, 0.0, 0.0, 0.0, 0
        s1 = s2 = step = None
        if optimizer is None:
            kind = 4        # accumulate (the HET cache write-back mode)
        elif type(optimizer) is ServerSGD:
            kind = 0
        elif isinstance(optimizer, ServerMomentum):   # incl. Nesterov
            kind, hp1 = 1, optimizer.momentum
            nesterov = int(optimizer.nesterov)
            s1 = _slot("v")
        elif isinstance(optimizer, ServerAdaGrad):
            kind, eps = 2, optimizer.eps
            s1 = _slot("acc")
        elif isinstance(optimizer, ServerAdam):
            kind = 3
            hp1, hp2, eps = optimizer.beta1, optimizer.beta2, optimizer.eps
            s1, s2 = _slot("m"), _slot("v")
            # the 0-d step counter is shared as-is (ascontiguousarray
            # would promote it to 1-d and break the python tier's
            # ``int(state["t"])``)
            if state["t"].dtype != np.int64:
                state["t"] = state["t"].astype(np.int64)
            step = state["t"]
        else:
            raise ValueError(
                f"van cannot serve {type(optimizer).__name__}")
        vp = None
        if versions is not None:
            versions = np.ascontiguousarray(versions, np.int64)
            assert len(versions) == value.shape[0]
            vp = versions.ctypes.data_as(i64p)
        self._l.van_register_table(
            self._h, int(key), value.ctypes.data_as(f32p),
            value.shape[0], value.shape[1], kind,
            float(optimizer.lr) if optimizer is not None else 0.0,
            float(hp1), float(hp2), float(eps), nesterov,
            s1.ctypes.data_as(f32p) if s1 is not None else None,
            s2.ctypes.data_as(f32p) if s2 is not None else None,
            step.ctypes.data_as(i64p) if step is not None else None,
            vp)
        # keep every registered buffer alive for the van's lifetime
        self._tables[int(key)] = (value, versions, s1, s2, step)
        return value

    def table_lock(self, key):
        self._l.van_table_lock(self._h, int(key))

    def table_unlock(self, key):
        self._l.van_table_unlock(self._h, int(key))

    def table_array(self, key):
        return self._tables[int(key)][0]

    def stop(self):
        if self._h:
            self._l.van_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class VanSharedLock:
    """Composite lock for a table served by BOTH tiers: acquires the
    python _Param lock AND the van's per-table C++ mutex, so python
    PSFunc paths and C++ van threads serialize on the same buffer.
    Drop-in for the ``with p.lock:`` sites in ps/server.py."""

    def __init__(self, pylock, van, key_id):
        self.pylock = pylock
        self.van = van
        self.key_id = int(key_id)

    def __enter__(self):
        self.pylock.acquire()
        self.van.table_lock(self.key_id)
        return self

    def __exit__(self, *exc):
        self.van.table_unlock(self.key_id)
        self.pylock.release()
        return False


class VanTransportError(ConnectionError):
    """A van round-trip failed at the socket level.  ``maybe_applied``
    says whether the server may already have APPLIED the request: the
    van applies only after reading a complete frame, so a failure while
    SENDING means not-applied (safe to retry elsewhere), while a
    failure while awaiting the response means the push may have landed
    — callers must not re-apply it through another tier."""

    def __init__(self, msg, maybe_applied):
        super().__init__(msg)
        self.maybe_applied = maybe_applied


class VanClient:
    """Blocking binary-protocol client for one van.

    ``dim`` is optional: pushes carry it in the row payload and pull
    responses reveal it in the frame length, so a dim-less client can
    serve tables of any width (the PSClient fast-tier route uses this).
    """

    def __init__(self, host, port, dim=None, timeout=30.0):
        self.dim = None if dim is None else int(dim)
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _send_frame(self, parts):
        """sendmsg + drain: sendmsg may queue only part of a multi-MB
        payload (python docs: the caller must finish delivery)."""
        total = sum(len(p) for p in parts)
        sent = self._sock.sendmsg(parts)
        if sent < total:
            rest = b"".join(bytes(p) for p in parts)   # rare path
            self._sock.sendall(rest[sent:])

    def _exchange(self, parts, maybe_applied_on_recv, reject_msg):
        """One frame out, one frame back.  Socket failures surface as
        VanTransportError; ``maybe_applied_on_recv`` says whether a
        failure while awaiting the response can mean the server already
        applied the request (pushes) or not (pure reads).  Returns the
        response payload past the ok byte."""
        total = sum(len(p) for p in parts)
        sent_all = False
        try:
            # scatter-gather send: no join copy of the multi-MB payload
            self._send_frame([_LEN.pack(total)] + parts)
            sent_all = True
            (m,) = _LEN.unpack(self._recv_exact(4))
            payload = self._recv_exact(m)
        except (OSError, ConnectionError) as e:
            raise VanTransportError(
                f"van round-trip failed while "
                f"{'awaiting the response' if sent_all else 'sending'}"
                f": {type(e).__name__}: {e}",
                maybe_applied=sent_all and maybe_applied_on_recv) from e
        if payload[0] != 1:
            raise RuntimeError(reject_msg)
        return payload

    def _roundtrip(self, op, key, ids, rows, want_rows):
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        n = len(ids)
        parts = [_HDR.pack(op, key, n), memoryview(ids).cast("B")]
        # a zero-id push carries no row payload (and reshape(0, -1) is
        # a numpy error) — the server accepts the 0-byte row section
        if rows is not None and n > 0:
            rows = np.ascontiguousarray(rows, np.float32)
            rows = rows.reshape(n, -1 if self.dim is None else self.dim)
            parts.append(memoryview(rows).cast("B"))
        payload = self._exchange(
            parts, maybe_applied_on_recv=op != _OP_PULL,
            reject_msg="van rejected the request (unknown key, id out "
                       "of range, or malformed frame)")
        if want_rows:
            if n == 0:       # reshape(0, -1) is a numpy error; width
                return np.zeros((0, self.dim or 0), np.float32)
            arr = np.frombuffer(payload, np.float32, offset=1)
            return arr.reshape(n, -1).copy()
        return None

    def _recv_exact(self, n):
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            r = self._sock.recv_into(view[got:])
            if r == 0:
                raise ConnectionError("van closed the connection")
            got += r
        return bytes(buf)

    def sync_embedding(self, key, ids, stored_versions, bound):
        """HET cache sync (server sync_embedding semantics): returns
        (stale_ids, rows, server_versions) for rows whose server
        version exceeds the stored one by more than ``bound``."""
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        stored = np.ascontiguousarray(stored_versions,
                                      np.int64).reshape(-1)
        assert len(stored) == len(ids)
        n = len(ids)
        parts = [_HDR.pack(_OP_SYNCEMB, key, n),
                 memoryview(ids).cast("B"), memoryview(stored).cast("B"),
                 struct.pack("<q", int(bound))]
        payload = self._exchange(
            parts, maybe_applied_on_recv=False,   # sync is a pure read
            reject_msg="van rejected sync_embedding (unknown key, no "
                       "version counters, id out of range, or "
                       "oversize response)")
        (m,) = _LEN.unpack(payload[1:5])
        off = 5
        stale_ids = np.frombuffer(payload, np.int64, count=m,
                                  offset=off).copy()
        off += m * 8
        row_bytes = len(payload) - off - m * 8
        dim = row_bytes // (4 * m) if m else (self.dim or 0)
        rows = np.frombuffer(payload, np.float32, count=m * dim,
                             offset=off).reshape(m, dim).copy()
        off += m * dim * 4
        versions = np.frombuffer(payload, np.int64, count=m,
                                 offset=off).copy()
        return stale_ids, rows, versions

    def push(self, key, ids, grads):
        self._roundtrip(_OP_PUSH, key, ids, grads, want_rows=False)

    def pull(self, key, ids):
        return self._roundtrip(_OP_PULL, key, ids, None, want_rows=True)

    def sd_pushpull(self, key, ids, grads):
        return self._roundtrip(_OP_PUSHPULL, key, ids, grads,
                               want_rows=True)

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
