"""PS client: the ps-lite Worker API surface over in-process or TCP.

Reference: ps-lite Worker (worker/worker.h:19-65: pull/push/dd_pushpull/
sparse_pull/sparse_push/sd_pushpull/ss_pushpull/parameter_init/save/load/
wait) and the flat C exports consumed via ctypes (python_binding.cc:8-140:
Init/Pull/Push/..., ssp_init/ssp_sync/preduce_get_partner/getLoads).

Async semantics parity: push/pull return a ticket; ``wait(ticket)`` blocks
(reference Worker::wait) — implemented with a small thread pool so PS
traffic overlaps the jitted device step exactly like the reference overlaps
PS RPCs with CUDA compute via the d2h stream + PSEvent
(ParameterServerCommunicate.py:29-36, stream.py:73-87).
"""

from __future__ import annotations

import os
import socket
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor, Future

from .. import envvars, locks, quant

import numpy as np

from . import faults, wire

from .server import PSServer, _send_msg, _recv_msg
from .van import VanClient, VanTransportError


class PSConnectionError(ConnectionError):
    """A PS request could not be completed after retries.  Raised instead
    of hanging — the failure mode VERDICT r2 flagged (a dropped packet or
    dead server mid-training surfaced as a hang or pickle error)."""


# ---------------- wire quantization (HETU_PS_QUANT=int8) ---------------- #
#
# Gradients quantize CLIENT-side into a quant.QuantArray right before
# wire.dumps and dequantize SERVER-side before the optimizer step; pulls
# run the same pair in reverse (the client passes quant=... and decodes
# the response).  The ~3.7x wire reduction shows up directly in the
# per-shard ps.rpc.bytes_sent/recv counters; ps.rpc.bytes_saved records
# the delta.  Everything below is a no-op with the knob unset — the
# default wire stays byte-identical.

def _q_encode(arr):
    """QuantArray when int8 wire quantization is on and ``arr``
    qualifies (float, >= quant.WIRE_MIN_SIZE elements); else ``arr``
    unchanged.  Counts the saved bytes."""
    if quant.ps_quant() != "int8" or not quant.should_quantize(arr):
        return arr
    qa = quant.QuantArray.encode(arr, quant.wire_chunk())
    from .. import telemetry
    if telemetry.enabled():
        telemetry.inc("ps.rpc.bytes_saved", quant.wire_savings(qa))
    return qa


def _q_decode(value):
    """Decode a quantized response payload (pull half of the pair);
    plain arrays pass through.  Counts the saved bytes."""
    if isinstance(value, quant.QuantArray):
        from .. import telemetry
        if telemetry.enabled():
            telemetry.inc("ps.rpc.bytes_saved",
                          quant.wire_savings(value))
        return value.decode()
    return value


def _q_mode():
    """The quant argument verbs forward to the server (None = exact)."""
    return quant.ps_quant()


class _TCPTransport:
    """Reliable request/response over TCP.

    ps-lite robustness parity (resender.h + Van timeouts): every request
    carries a (client_id, seq) pair; on timeout or connection loss the
    client reconnects and resends, and the SERVER suppresses duplicate
    application by replaying the cached response for a seq it has already
    served (requests are serial per client thread, so a one-slot replay
    cache per client suffices).  After ``retries`` failed attempts a
    ``PSConnectionError`` surfaces — never a hang.

    Tunables (env): HETU_PS_TIMEOUT (per-call seconds, default 60),
    HETU_PS_CONNECT_TIMEOUT (default 10), HETU_PS_RETRIES (default 3)."""

    def __init__(self, host, port, timeout=None, connect_timeout=None,
                 retries=None):
        self._local = threading.local()
        self.host, self.port = host, port
        self.timeout = float(
            timeout if timeout is not None
            else envvars.get_float("HETU_PS_TIMEOUT"))
        self.connect_timeout = float(
            connect_timeout if connect_timeout is not None
            else envvars.get_float("HETU_PS_CONNECT_TIMEOUT"))
        self.retries = int(
            retries if retries is not None
            else envvars.get_int("HETU_PS_RETRIES"))

    def _state(self):
        st = self._local
        if getattr(st, "client_id", None) is None:
            st.client_id = uuid.uuid4().hex
            st.seq = 0
            st.sock = None
        return st

    def _connect(self):
        s = socket.create_connection((self.host, self.port),
                                     timeout=self.connect_timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(self.timeout)
        return s

    def call(self, method, *args, **kwargs):
        from .. import telemetry
        # lockdep held-across seam: an RPC (connect + send + recv, up
        # to retries x timeout seconds) under any traced lock turns
        # that lock's critical section into an unbounded wait
        locks.note_blocking("ps_rpc", method=method)
        st = self._state()
        st.seq += 1
        payload = wire.dumps(
            ("__req2__", st.client_id, st.seq, method, args, kwargs))
        chaos = faults.plan_from_env()
        last_err = None
        tel = telemetry.enabled()
        shard = f"{self.host}:{self.port}"
        t_call = time.perf_counter() if tel else 0.0
        for attempt in range(self.retries):
            # chaos seam (HETU_CHAOS): one decision per ATTEMPT, so an
            # injected loss exercises exactly the reconnect/resend path
            # a real one would (the seq is fixed per call — a post-apply
            # loss makes the server see a true duplicate)
            fault = chaos.draw(method) if chaos is not None else None
            try:
                if fault is not None:
                    if fault.kind == "delay":
                        time.sleep(fault.seconds)
                    elif fault.kind == "drop":
                        raise faults.InjectedFault(
                            "chaos: request dropped before send")
                    elif fault.kind == "reset":
                        raise faults.InjectedFault(
                            "chaos: connection reset")
                if st.sock is None:
                    st.sock = self._connect()
                _send_msg(st.sock, payload)
                raw = _recv_msg(st.sock)
                if raw is None:
                    raise ConnectionResetError("PS closed the connection")
                ok, result = wire.loads(raw)
                if not ok:
                    raise RuntimeError(
                        f"PS server error in {method}: {result}")
                if fault is not None and fault.kind == "dup":
                    # the server applied and answered, but the response
                    # is "lost": the retry resends the SAME seq and the
                    # server's replay cache must answer without
                    # re-applying (resender.h parity under test)
                    raise faults.InjectedFault(
                        "chaos: response dropped after apply")
                if fault is not None and fault.kind == "slow":
                    time.sleep(fault.seconds)
                if tel:
                    # per-shard RPC accounting (PS client half of the
                    # reference NCCLProfiler's comm visibility)
                    telemetry.observe(
                        "ps.rpc_ms." + method,
                        (time.perf_counter() - t_call) * 1e3)
                    telemetry.inc(f"ps.rpc.calls[{shard}]")
                    telemetry.inc("ps.rpc.bytes_sent", len(payload))
                    telemetry.inc("ps.rpc.bytes_recv", len(raw))
                    if attempt:
                        telemetry.inc("ps.rpc.recovered")
                return result
            except (OSError, ConnectionError, socket.timeout, EOFError,
                    wire.WireError) as e:
                last_err = e
                if tel:
                    telemetry.inc(f"ps.rpc.retries[{shard}]")
                    if isinstance(e, socket.timeout):
                        telemetry.inc(f"ps.rpc.timeouts[{shard}]")
                if st.sock is not None:
                    try:
                        st.sock.close()
                    except OSError:
                        pass
                    st.sock = None
                if attempt < self.retries - 1 and \
                        not isinstance(e, faults.InjectedFault):
                    # no backoff for synthetic losses: chaos runs model
                    # packet loss, not congestion
                    time.sleep(min(2.0, 0.2 * (attempt + 1)))
        if tel:
            telemetry.inc(f"ps.rpc.failures[{shard}]")
        telemetry.flight.RECORDER.dump(
            "ps_connection_error", method=method, shard=shard,
            retries=self.retries)
        raise PSConnectionError(
            f"PS request {method!r} to {self.host}:{self.port} failed "
            f"after {self.retries} attempts (last: "
            f"{type(last_err).__name__}: {last_err}); the server is down "
            f"or unreachable") from last_err

    def close(self):
        if getattr(self._local, "sock", None) is not None:
            self._local.sock.close()
            self._local.sock = None


def _local_chaos_call(server, method, args, kwargs):
    """In-process chaos seam shared by every local transport (here and
    sharded._LocalServerTransport).  There is no socket to resend over,
    so losses retry immediately; ``dup`` cannot double-apply in-process
    (a returned response cannot be lost) and degrades to a no-op
    decision; ``kill`` and the latency kinds behave as on the wire."""
    from .. import telemetry
    tel = telemetry.enabled()
    t_call = time.perf_counter() if tel else 0.0

    def _done(result):
        if tel:
            telemetry.observe("ps.rpc_ms." + method,
                              (time.perf_counter() - t_call) * 1e3)
            telemetry.inc("ps.rpc.calls[local]")
        return result

    chaos = faults.plan_from_env()
    if chaos is None:
        return _done(getattr(server, method)(*args, **kwargs))
    last = None
    for _ in range(3):
        fault = chaos.draw(method)
        if fault.kind == "delay":
            time.sleep(fault.seconds)
        elif fault.kind in ("drop", "reset"):
            last = faults.InjectedFault(f"chaos: {fault.kind} (local)")
            if tel:
                telemetry.inc("ps.rpc.retries[local]")
            continue
        result = getattr(server, method)(*args, **kwargs)
        if fault.kind == "slow":
            time.sleep(fault.seconds)
        return _done(result)
    if tel:
        telemetry.inc("ps.rpc.failures[local]")
    telemetry.flight.RECORDER.dump(
        "ps_connection_error", method=method, shard="local", retries=3)
    raise PSConnectionError(
        f"local PS call {method!r} dropped by chaos 3 times") from last


class _LocalTransport:
    def __init__(self):
        self.server = PSServer.get()

    def call(self, method, *args, **kwargs):
        return _local_chaos_call(self.server, method, args, kwargs)

    def close(self):
        pass


class PSClient:
    _instance = None

    def __init__(self, transport=None, rank=0, nrank=1):
        if transport is None:
            addr = envvars.get_str("HETU_PS_ADDR")
            if addr:
                host, port = addr.rsplit(":", 1)
                transport = _TCPTransport(host, int(port))
            else:
                transport = _LocalTransport()
        self.t = transport
        self.rank = rank
        self.nrank = nrank
        self._pool = ThreadPoolExecutor(max_workers=4,
                                        thread_name_prefix="ps-client")
        self._hb_stop = None
        # native-van fast tier: per-thread discovery + socket (the van
        # protocol is one blocking socket, not thread-safe).  All
        # sockets ever opened are also tracked process-wide so
        # finalize() can close the ones pool threads created.
        self._van_local = threading.local()
        self._van_clients = []
        self._van_clients_mu = locks.TracedLock("ps.van_clients")

    def start_heartbeat(self, interval=5.0, role="worker", node_id=None):
        """Beat the scheduler's liveness map (HETU_SCHEDULER_ADDR) every
        ``interval`` seconds from a daemon thread — the ps-lite
        Postoffice heartbeat role.  No-op without a scheduler."""
        sched = envvars.get_str("HETU_SCHEDULER_ADDR")
        if not sched or self._hb_stop is not None:
            return False
        host, port = sched.rsplit(":", 1)
        node = str(self.rank if node_id is None else node_id)
        stop = threading.Event()
        self._hb_stop = stop

        def beat():
            # short timeout, one retry: a stalled RPC must cost one
            # beat, not wedge the loop past the staleness window
            t = _TCPTransport(host, int(port),
                              timeout=max(1.0, interval / 2),
                              connect_timeout=max(1.0, interval / 2),
                              retries=1)
            first = True
            while True:
                if not first and stop.wait(interval):
                    break
                first = False
                try:
                    # immediate first beat: an early-crashing node must
                    # still APPEAR in the health map before dying
                    t.call("heartbeat", role, node)
                except Exception:
                    pass          # scheduler gone: detection is ITS job
            t.close()

        threading.Thread(target=beat, daemon=True,
                         name=f"ps-heartbeat-{role}-{node}").start()
        return True

    def stop_heartbeat(self):
        if self._hb_stop is not None:
            self._hb_stop.set()
            self._hb_stop = None

    @classmethod
    def get(cls):
        if cls._instance is None:
            rank = envvars.get_int("HETU_PS_RANK")
            nrank = envvars.get_int("HETU_PS_NRANK")
            addrs = envvars.get_list("HETU_PS_ADDRS")
            sched = envvars.get_str("HETU_SCHEDULER_ADDR")
            if not addrs and not envvars.is_set("HETU_PS_ADDR") and sched:
                # rendezvous: block until the expected server group has
                # registered, then connect directly (ps-lite Postoffice
                # bootstrap role).  The expected count is REQUIRED:
                # defaulting it would let early workers see a partial
                # group and shard keys inconsistently.
                nserv = envvars.get_int("HETU_PS_NSERVERS")
                if nserv is None:
                    raise ValueError(
                        "HETU_SCHEDULER_ADDR is set but HETU_PS_NSERVERS "
                        "is not: workers must agree on the server-group "
                        "size or they would shard keys inconsistently")
                host, port = sched.rsplit(":", 1)
                t = _TCPTransport(host, int(port))
                addrs = t.call(
                    "get_servers", int(nserv),
                    envvars.get_float("HETU_PS_TIMEOUT"))
                t.close()
                if len(addrs) == 1:
                    h2, p2 = addrs[0].rsplit(":", 1)
                    cls._instance = PSClient(
                        transport=_TCPTransport(h2, int(p2)),
                        rank=rank, nrank=nrank)
                    return cls._instance
            if len(addrs) > 1:
                # launcher exposed a server group: shard keys across it
                from .sharded import ShardedPSClient
                cls._instance = ShardedPSClient(addrs=addrs, rank=rank,
                                                nrank=nrank)
            else:
                cls._instance = PSClient(rank=rank, nrank=nrank)
        return cls._instance

    def finalize(self):
        self._pool.shutdown(wait=True)
        # close EVERY van socket ever opened, including the ones pool
        # threads created in their own thread-local state (each holds a
        # serve_conn thread on the server until closed)
        with self._van_clients_mu:
            clients, self._van_clients = self._van_clients, []
        for cli in clients:
            cli.close()
        st = getattr(self._van_local, "state", None)
        if st is not None:
            st["cli"] = None
        self.t.close()
        PSClient._instance = None

    # ---------------- Worker API (worker.h:19-65) ---------------- #

    def parameter_init(self, key, shape, init_type="constant", arg1=0.0,
                       arg2=1.0, seed=0, opt=None, opt_args=None,
                       param_type=0):
        return self.t.call("param_init", key, tuple(shape), init_type, arg1,
                           arg2, seed, opt, opt_args, param_type)

    def param_set(self, key, value, opt=None, opt_args=None):
        """Create-or-overwrite with an explicit value (executor bridge).
        Rides the quantized wire when HETU_PS_QUANT is set (the resync/
        replication paths move big tables through here), so replica
        rebuilds pay int8 bytes too; small control-plane arrays stay
        exact (quant.WIRE_MIN_SIZE floor)."""
        return self.t.call("param_set", key,
                           _q_encode(np.asarray(value, np.float32)),
                           opt, opt_args)

    def pull(self, key, async_=False):
        if async_:
            return self._pool.submit(self._pull_sync, key)
        return self._pull_sync(key)

    def _pull_sync(self, key):
        q = _q_mode()
        if q:
            return _q_decode(self.t.call("pull", key, quant=q))
        return self.t.call("pull", key)

    def push(self, key, grad, async_=False):
        grad = _q_encode(np.asarray(grad, np.float32))
        if async_:
            return self._pool.submit(self.t.call, "push", key, grad)
        return self.t.call("push", key, grad)

    def dd_pushpull(self, key, grad, async_=False):
        grad = np.asarray(grad, np.float32)
        if async_:
            return self._pool.submit(self._dd_pushpull_sync, key, grad)
        return self._dd_pushpull_sync(key, grad)

    def _dd_pushpull_sync(self, key, grad):
        q = _q_mode()
        if q:
            return _q_decode(self.t.call(
                "dd_pushpull", key, _q_encode(grad), quant=q))
        return self.t.call("dd_pushpull", key, grad)

    # The three sparse verbs route through the server's native C++ van
    # when it serves the key (reference: workers speak to the zmq_van
    # tier directly; the Executor's hybrid phases A/B inherit this).
    # Discovery is one van_info RPC; connection-level van failures fall
    # back to the python tier permanently for this thread.

    _VAN_REFRESH_S = 5.0      # re-ask van_info for missing keys at most
    _VAN_MAX_CONNECT_TRIES = 3   # this often; give up connecting after

    def _van_route(self, key):
        """(VanClient, van_key_id) when the server's native van serves
        ``key``; None otherwise.  Discovery failures and unseen keys
        are re-checked at most every ``_VAN_REFRESH_S`` seconds, so a
        serve_van() issued after traffic started still gets picked up;
        repeated connect failures retire the fast tier per-thread."""
        if not envvars.get_bool("HETU_PS_USE_VAN"):
            return None
        st = getattr(self._van_local, "state", None)
        if st is None:
            st = {"port": None, "keys": {}, "cli": None,
                  "checked_at": 0.0, "connect_fails": 0, "dead": False}
            self._van_local.state = st
        if st["dead"]:
            return None
        if key not in st["keys"]:
            now = time.monotonic()
            if now - st["checked_at"] < self._VAN_REFRESH_S:
                return None
            st["checked_at"] = now
            try:
                port, keys = self.t.call("van_info")
            except Exception:
                return None       # transient: retry after the window
            st["port"], st["keys"] = port, dict(keys)
            if key not in st["keys"]:
                return None
        if st["port"] is None:
            return None
        if st["cli"] is None:
            host = getattr(self.t, "host", "127.0.0.1")
            try:
                st["cli"] = VanClient(
                    host, st["port"],
                    timeout=envvars.get_float("HETU_PS_TIMEOUT"))
            except OSError:
                st["connect_fails"] += 1
                if st["connect_fails"] >= self._VAN_MAX_CONNECT_TRIES:
                    st["dead"] = True
                return None
            with self._van_clients_mu:
                self._van_clients.append(st["cli"])
        return st["cli"], st["keys"][key]

    def _van_drop(self):
        st = self._van_local.state
        if st["cli"] is not None:
            st["cli"].close()
        st["cli"] = None
        st["dead"] = True

    def _van_push_failed(self, key, err):
        """A van push failed at the socket level.  The van applies a
        request only after reading its complete frame, so a SEND-side
        failure is safe to retry through the python tier; a failure
        awaiting the response means the update may already be in the
        shared buffers — re-applying it there would double the step, so
        that surfaces as PSConnectionError instead (the resender-style
        dedup the python wire has does not exist on the van protocol)."""
        self._van_drop()
        if err.maybe_applied:
            raise PSConnectionError(
                f"van push for {key!r} failed awaiting the response; "
                f"the update may already be applied, so it is NOT "
                f"retried through the python tier (double-apply). "
                f"Last error: {err}") from err

    def sparse_pull(self, key, ids, async_=False):
        ids = np.asarray(ids, np.int64)
        if async_:
            return self._pool.submit(self._sparse_pull_sync, key, ids)
        return self._sparse_pull_sync(key, ids)

    def _sparse_pull_sync(self, key, ids):
        route = self._van_route(key) if ids.size else None
        if route is not None:
            cli, kid = route
            try:
                return cli.pull(kid, ids)
            except (OSError, ConnectionError):
                self._van_drop()    # reads are idempotent: fall back
            except RuntimeError:
                # van rejected (e.g. a pull too large for its 1 GiB
                # frame): nothing was applied and the connection is
                # healthy — the python tier is the authority
                pass
        q = _q_mode()
        if q:
            return _q_decode(self.t.call("sparse_pull", key, ids,
                                         quant=q))
        return self.t.call("sparse_pull", key, ids)

    def sparse_push(self, key, ids, rows, async_=False):
        ids = np.asarray(ids, np.int64)
        rows = np.asarray(rows, np.float32)
        if async_:
            return self._pool.submit(self._sparse_push_sync, key, ids,
                                     rows)
        return self._sparse_push_sync(key, ids, rows)

    def _sparse_push_sync(self, key, ids, rows):
        route = self._van_route(key) if ids.size else None
        if route is not None:
            cli, kid = route
            try:
                return cli.push(kid, ids, rows)
            except VanTransportError as e:
                self._van_push_failed(key, e)   # raises if maybe-applied
            except RuntimeError:
                pass   # van rejected the frame: NOT applied, safe retry
        return self.t.call("sparse_push", key, ids, _q_encode(rows))

    def sd_pushpull(self, key, ids, rows, pull_ids=None, async_=False):
        ids = np.asarray(ids, np.int64)
        rows = np.asarray(rows, np.float32)
        if async_:
            return self._pool.submit(self._sd_pushpull_sync, key, ids,
                                     rows, pull_ids)
        return self._sd_pushpull_sync(key, ids, rows, pull_ids)

    def _sd_pushpull_sync(self, key, ids, rows, pull_ids):
        # pull-only shards (sharded CTR hot path) still route: the van
        # accepts a zero-id push, and the python tier's sd_pushpull
        # always pushes — a shared Adam table's step counter must
        # advance the same way on both tiers
        want = bool(ids.size) or pull_ids is not None
        route = self._van_route(key) if want else None
        if route is not None:
            cli, kid = route
            try:
                if pull_ids is None:
                    return cli.sd_pushpull(kid, ids, rows)
                cli.push(kid, ids, rows)
            except VanTransportError as e:
                self._van_push_failed(key, e)   # raises if maybe-applied
            except RuntimeError:
                pass   # van rejected the frame: NOT applied, safe retry
            else:
                # the push landed; the (idempotent) pull half completes
                # through the pull route, which has its own fallback
                return self._sparse_pull_sync(
                    key, np.asarray(pull_ids, np.int64))
        q = _q_mode()
        if q:
            return _q_decode(self.t.call(
                "sd_pushpull", key, ids, _q_encode(rows), pull_ids,
                quant=q))
        return self.t.call("sd_pushpull", key, ids, rows, pull_ids)

    def ss_pushpull(self, key, ids, rows, pull_ids, async_=False):
        return self.sd_pushpull(key, ids, rows, pull_ids, async_=async_)

    def wait(self, ticket):
        if isinstance(ticket, Future):
            return ticket.result()
        return ticket

    def save(self, key, path):
        os.makedirs(path, exist_ok=True)
        return self.t.call("param_save", key, path)

    def load(self, key, path):
        return self.t.call("param_load", key, path)

    def clear(self, key):
        return self.t.call("param_clear", key)

    # ---------------- serving KV cold store (ISSUE 17) ------------- #
    # thin wrappers over the PSServer kv_* surface: the tiered-KV
    # ladder (serving/kv_tiers.py) parks spilled prefix payloads here

    def kv_put(self, key, payload, version=0):
        return self.t.call("kv_put", key, payload, version)

    def kv_get(self, key):
        return self.t.call("kv_get", key)

    def kv_del(self, key):
        return self.t.call("kv_del", key)

    def kv_keys(self):
        return self.t.call("kv_keys")

    # ---------------- SSP / BSP / preduce ---------------- #

    def ssp_init(self, group=0, bound=0):
        return self.t.call("ssp_init", group, self.rank, bound)

    def ssp_sync(self, group=0):
        return self.t.call("ssp_sync", group, self.rank)

    def BarrierWorker(self, group=0):
        return self.t.call("barrier", group, self.rank, self.nrank)

    def preduce_get_partner(self, key, max_worker, wait_time):
        return self.t.call("preduce_get_partner", key, self.rank,
                           max_worker, wait_time)

    # ---------------- cache sync ---------------- #
    # The HET verbs ride the van too (r5): sync_embedding is op 4 on
    # the C++ tier; push_embedding is a push on an accumulate-mode
    # table.  push_sync_embedding decomposes into the two frames — the
    # python server also takes the param lock once per half, so the
    # interleaving semantics are identical.

    def sync_embedding(self, key, ids, stored_versions, bound):
        route = self._van_route(key)
        if route is not None:
            cli, kid = route
            try:
                return cli.sync_embedding(kid, ids, stored_versions,
                                          bound)
            except (OSError, ConnectionError):
                self._van_drop()    # pure read: safe fallback
            except RuntimeError:
                pass                # rejected (e.g. no versions)
        q = _q_mode()
        if q:
            # int8 pull pair on the HET sync verb: the serving cache's
            # miss path pulls through here, so HETU_PS_QUANT shrinks
            # cold-start / post-outage refill bytes the same ~3.7x the
            # dense pulls get
            s_ids, s_rows, s_vers = self.t.call(
                "sync_embedding", key, ids, stored_versions, bound,
                quant=q)
            return s_ids, _q_decode(s_rows), s_vers
        return self.t.call("sync_embedding", key, ids, stored_versions,
                           bound)

    def push_embedding(self, key, ids, rows):
        # server-side push_embedding IS sparse_push (accumulate on an
        # optimizer-less table); reuse its van route + fallback contract
        return self.sparse_push(key, ids, rows)

    def push_sync_embedding(self, key, ids, rows, sync_ids, stored_versions,
                            bound):
        if self._van_route(key) is not None:
            self.push_embedding(key, ids, rows)
            return self.sync_embedding(key, sync_ids, stored_versions,
                                       bound)
        return self.t.call("push_sync_embedding", key, ids, rows, sync_ids,
                           stored_versions, bound)

    def getLoads(self):
        return self.t.call("get_loads")
