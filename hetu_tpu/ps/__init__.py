"""Parameter server + HET-style embedding cache (host-side subsystem).

Reference: ps-lite (§2.2 of SURVEY.md) + src/hetu_cache (§2.3).  Built in
stages: in-process server (this round) -> multi-process ZMQ-free TCP server
-> C++ hot path.  See server.py / client.py / cache.py.
"""

from .server import PSServer, Scheduler
from .client import PSClient, PSConnectionError
from .sharded import ShardedPSClient
from .faults import FaultPlan

__all__ = ["PSServer", "Scheduler", "PSClient", "PSConnectionError",
           "ShardedPSClient", "FaultPlan"]
