"""Typed wire codec for the PS transport — no pickle on network bytes.

The reference's ps-lite frames typed protobuf messages + raw tensor
buffers (ps-lite/src/meta.proto, zmq_van.h); round 2 shipped
length-prefixed *pickle*, which is fine single-tenant but deserializes
arbitrary objects from the network (VERDICT r2 "weak": unusable beyond
a trust boundary).  This codec encodes exactly the value envelope the
PSFunc surface uses — None/bool/int/float/str/bytes/ndarray and
list/tuple/dict compositions — and nothing else: decoding can only ever
produce plain data, never code or constructor calls.

Layout: one tag byte per value, then a fixed or length-prefixed
payload; arrays carry (dtype-str, shape) and their raw C-contiguous
buffer, decoded zero-copy via np.frombuffer over the receive buffer.
Quantized arrays (tag ``Q``: hetu_tpu.quant.QuantArray) are first-class
— chunk + original dtype/shape + the int8 payload + f32 scales — so an
int8 push/pull ships ~3.7x fewer bytes without leaving the plain-data
envelope (the receiver rebuilds a QuantArray holder, never code).

Scalar-widening contract: numpy *scalars* are normalized on the wire —
np.bool_ → bool, integer scalars → int64, floating scalars → float64
(the decoder returns Python bool/int/float).  Integer scalars outside
int64 range (e.g. np.uint64 above 2**63-1) are rejected with WireError
at encode time.  Arrays keep their exact dtype; put values in a 0-d
ndarray if dtype or full uint64 range must survive the trip.
"""

from __future__ import annotations

import struct

import numpy as np

_U32 = struct.Struct("!I")
_I64 = struct.Struct("!q")
_F64 = struct.Struct("!d")


class WireError(ValueError):
    pass


def _is_quant(obj):
    from ..quant import QuantArray
    return isinstance(obj, QuantArray)


def _enc(obj, out):
    if obj is None:
        out.append(b"N")
    elif obj is True:
        out.append(b"T")
    elif obj is False:
        out.append(b"F")
    elif isinstance(obj, np.bool_):
        # np.bool_ is not a subclass of int/np.integer; without this
        # branch a numpy bool scalar would fall through to WireError.
        out.append(b"T" if obj else b"F")
    elif isinstance(obj, (int, np.integer)):
        out.append(b"I")
        out.append(_I64.pack(int(obj)))
    elif isinstance(obj, (float, np.floating)):
        out.append(b"D")
        out.append(_F64.pack(float(obj)))
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out.append(b"S")
        out.append(_U32.pack(len(raw)))
        out.append(raw)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        raw = bytes(obj)
        out.append(b"B")
        out.append(_U32.pack(len(raw)))
        out.append(raw)
    elif isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        if arr.nbytes >= (1 << 32):
            raise WireError("array payloads are capped at 4 GiB per "
                            "message; shard the request")
        dt = arr.dtype.str.encode("ascii")      # e.g. b'<f4'
        out.append(b"A")
        out.append(bytes([len(dt)]))
        out.append(dt)
        # np.ascontiguousarray silently promotes 0-d to 1-d, so the
        # shape on the wire must be the ORIGINAL's — a 0-d scalar array
        # used to come back as shape (1,) (caught by the quant-era
        # round-trip property tests; dtype/range survival for scalars
        # is exactly what 0-d arrays are documented for)
        out.append(bytes([obj.ndim]))
        for d in obj.shape:
            out.append(_I64.pack(d))
        out.append(_U32.pack(arr.nbytes))
        # memoryview, not tobytes(): b"".join reads buffers directly, so
        # the multi-MB embedding payloads skip a full extra copy (the
        # list holds the view, which keeps arr's buffer alive)
        out.append(arr.reshape(-1).data)
    elif _is_quant(obj):
        # quantized-array pair (quant.QuantArray): still plain data —
        # int8 payload + f32 scales + shape/dtype/chunk metadata, no
        # constructor call beyond rebuilding the dataclass-like holder
        out.append(b"Q")
        out.append(_I64.pack(obj.chunk))
        _enc(obj.dtype, out)
        _enc(tuple(int(d) for d in obj.shape), out)
        _enc(np.ascontiguousarray(obj.q, np.int8), out)
        _enc(np.ascontiguousarray(obj.scales, np.float32), out)
    elif isinstance(obj, (list, tuple)):
        out.append(b"L" if isinstance(obj, list) else b"U")
        out.append(_U32.pack(len(obj)))
        for item in obj:
            _enc(item, out)
    elif isinstance(obj, dict):
        out.append(b"M")
        out.append(_U32.pack(len(obj)))
        for k, v in obj.items():
            if not isinstance(k, str):
                raise WireError(f"dict keys must be str, got {type(k)}")
            _enc(k, out)
            _enc(v, out)
    else:
        raise WireError(
            f"type {type(obj).__name__} is outside the PS wire envelope")


# above this many payload bytes, encoding under a held lock is flagged
# by lockdep (note_blocking): a multi-megabyte join/copy is real wall
# time inside someone's critical section
_BLOCKING_BYTES = 1 << 20


def dumps(obj) -> bytes:
    out = []
    try:
        _enc(obj, out)
    except WireError:
        raise
    except Exception as e:   # out-of-range ints, oversized strings, ...
        raise WireError(f"cannot encode for the PS wire: {e}") from e
    from .. import locks
    if locks.lockdep_enabled():
        # len() of the array memoryviews counts ELEMENTS; nbytes is
        # the wire size
        n = sum(b.nbytes if isinstance(b, memoryview) else len(b)
                for b in out)
        if n >= _BLOCKING_BYTES:
            locks.note_blocking("wire_dumps", bytes=n)
    return b"".join(out)


def _dec(buf, off):
    tag = buf[off:off + 1]
    off += 1
    if tag == b"N":
        return None, off
    if tag == b"T":
        return True, off
    if tag == b"F":
        return False, off
    if tag == b"I":
        return _I64.unpack_from(buf, off)[0], off + 8
    if tag == b"D":
        return _F64.unpack_from(buf, off)[0], off + 8
    if tag == b"S":
        (n,) = _U32.unpack_from(buf, off)
        off += 4
        return bytes(buf[off:off + n]).decode("utf-8"), off + n
    if tag == b"B":
        (n,) = _U32.unpack_from(buf, off)
        off += 4
        return bytes(buf[off:off + n]), off + n
    if tag == b"A":
        dlen = buf[off]
        off += 1
        dt = np.dtype(bytes(buf[off:off + dlen]).decode("ascii"))
        off += dlen
        ndim = buf[off]
        off += 1
        shape = []
        for _ in range(ndim):
            shape.append(_I64.unpack_from(buf, off)[0])
            off += 8
        (n,) = _U32.unpack_from(buf, off)
        off += 4
        arr = np.frombuffer(buf, dtype=dt, count=n // dt.itemsize,
                            offset=off).reshape(shape)
        return arr, off + n
    if tag == b"Q":
        from ..quant import QuantArray
        chunk = _I64.unpack_from(buf, off)[0]
        off += 8
        dtype, off = _dec(buf, off)
        shape, off = _dec(buf, off)
        q, off = _dec(buf, off)
        scales, off = _dec(buf, off)
        return QuantArray(q, scales, shape, dtype, chunk), off
    if tag in (b"L", b"U"):
        (n,) = _U32.unpack_from(buf, off)
        off += 4
        items = []
        for _ in range(n):
            item, off = _dec(buf, off)
            items.append(item)
        return (items if tag == b"L" else tuple(items)), off
    if tag == b"M":
        (n,) = _U32.unpack_from(buf, off)
        off += 4
        d = {}
        for _ in range(n):
            k, off = _dec(buf, off)
            v, off = _dec(buf, off)
            d[k] = v
        return d, off
    raise WireError(f"bad wire tag {tag!r} at offset {off - 1}")


def loads(buf):
    try:
        obj, off = _dec(buf, 0)
    except WireError:
        raise
    except Exception as e:   # truncated/corrupt frames: struct.error,
        raise WireError(     # IndexError, UnicodeDecodeError, ...
            f"corrupt wire frame: {e}") from e
    if off != len(buf):
        raise WireError(f"trailing bytes: {len(buf) - off}")
    return obj
