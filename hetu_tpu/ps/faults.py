"""Deterministic fault injection for the PS transports (chaos harness).

Every PS tier (PSClient, ShardedPSClient, CacheSparseTable, the van
fallback path) funnels its python-wire RPCs through ``_TCPTransport.call``
or ``_LocalTransport.call`` (ps/client.py), so injecting at that seam
faults the whole stack with zero call-site changes.  Activation is via
the ``HETU_CHAOS`` env var so launcher-spawned server and worker
processes inherit the plan; ``HETU_CHAOS_ROLE`` scopes a plan to one
role (the launcher stamps ``server:<idx>`` / ``worker:<rank>``).

Spec grammar (comma-separated ``k=v``)::

    seed=<int>        decision-stream seed (default 0)
    drop=<p>          P[request lost BEFORE the server sees it]
    dup=<p>           P[response lost AFTER the server applied it] — the
                      client retries, so the server receives a DUPLICATE;
                      the replay cache must suppress re-application
    reorder=<p>       alias of dup (a delayed-then-retransmitted request
                      arrives behind its successor; same observable:
                      a duplicate seq at the server)
    reset=<p>         P[connection reset before the call]
    delay=<p>:<s>     P[<s> seconds of extra latency before the call]
    slow=<p>:<s>      P[<s> seconds of server slowness after applying]
    kill=<n>          one-shot SIGKILL of THIS process at the n-th
                      evaluated event (1-based; the chaos test's
                      mid-training shard kill).  A seam drawing with
                      ``inline=True`` (the serving replica harness)
                      gets ``Fault("kill")`` back instead of the
                      process-wide SIGKILL and handles the death itself
    wedge=<n>         one-shot WEDGE at the n-th evaluated event: the
                      victim stays alive but stops making progress (and
                      stops heartbeating) — the mid-run hang class of
                      failure.  Only fires at seams that opt in via
                      ``kinds`` containing "wedge" (the serving replica
                      step seam); transports never draw it
    role=<name>       plan active only when HETU_CHAOS_ROLE == name
                      (prefix match: role=server matches server:0).
                      Seams hosting several roles in ONE process (the
                      router's replica fleet) pass their role to
                      ``draw(role=...)`` explicitly, overriding the env.
                      ``role=swap`` scopes a plan to the live-weight-
                      sync seams (serving/weight_sync.py): the
                      coordinator draws at ``swap.version_push``
                      (kinds drop/reset = a corrupt/stale version read,
                      rejecting the rollout), then per replica at
                      ``swap.drain`` (kill mid-drain) and
                      ``swap.apply`` (kill after the buffers moved,
                      before the probe) — ``kill=<n>`` picks the seam
                      by draw position.
                      ``role=autoscale`` scopes a plan to the elastic-
                      fleet seams (serving/router.py): the router draws
                      at ``autoscale.scale_up`` (kill the busiest PEER
                      while a new replica is mid-bring-up) and
                      ``autoscale.drain`` (kill the RETIRING replica
                      itself mid-drain) — both must lose zero requests;
                      ``kill=<n>`` picks the seam by draw position, as
                      with role=swap

Determinism: decision ``i`` is a pure function of ``(seed, i)`` (a
blake2 hash, not an RNG object), so a spec replays the identical fault
sequence for a serial caller regardless of wall clock or prior library
RNG use.  The event counter is per-plan (per-process); concurrent
callers interleave counter draws nondeterministically, so equivalence
tests drive a single thread.

Example::

    HETU_CHAOS="seed=7,drop=0.1,dup=0.1,delay=0.05:0.02" python train.py
"""

from __future__ import annotations

import hashlib
import os
import signal
import struct
import threading


def _restart_count():
    """Supervisor incarnation index (0 = first run of this process)."""
    from .. import envvars
    return envvars.get_int("HETU_RESTART_COUNT")


class InjectedFault(ConnectionError):
    """A chaos-injected transport failure (subclass of ConnectionError so
    the client's existing retry machinery treats it like the real
    thing)."""


class Fault:
    """One drawn event: ``kind`` in {none, drop, dup, reset, delay, slow,
    kill, wedge} plus the latency for the timed kinds."""

    __slots__ = ("kind", "seconds")

    def __init__(self, kind, seconds=0.0):
        self.kind = kind
        self.seconds = seconds

    def __repr__(self):
        return (f"Fault({self.kind!r}"
                + (f", {self.seconds}s" if self.seconds else "") + ")")


def _u01(seed, n):
    """Deterministic uniform in [0, 1): hash of (seed, n) — stable across
    processes, platforms, and interpreter restarts."""
    h = hashlib.blake2b(b"%d:%d" % (seed, n), digest_size=8).digest()
    return struct.unpack("<Q", h)[0] / 2.0 ** 64


class FaultPlan:
    def __init__(self, seed=0, drop=0.0, dup=0.0, reset=0.0,
                 delay=(0.0, 0.0), slow=(0.0, 0.0), kill=None, wedge=None,
                 role=None):
        self.seed = int(seed)
        self.drop = float(drop)
        self.dup = float(dup)
        self.reset = float(reset)
        self.delay = (float(delay[0]), float(delay[1]))
        self.slow = (float(slow[0]), float(slow[1]))
        self.kill = None if kill is None else int(kill)
        self.wedge = None if wedge is None else int(wedge)
        self.role = role
        self._n = 0
        from .. import locks
        self._mu = locks.TracedLock("chaos.plan")
        # observability: how often each kind actually fired
        self.fired = {k: 0 for k in
                      ("drop", "dup", "reset", "delay", "slow", "kill",
                       "wedge")}

    # ---------------- spec parsing ---------------- #

    @classmethod
    def from_spec(cls, spec):
        """Parse the HETU_CHAOS grammar (see module docstring)."""
        kw = {}
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"chaos spec item {part!r} is not k=v")
            k, v = part.split("=", 1)
            k = k.strip()
            v = v.strip()
            if k in ("seed", "kill", "wedge"):
                kw[k] = int(v)
            elif k in ("drop", "dup", "reorder", "reset"):
                key = "dup" if k == "reorder" else k
                kw[key] = kw.get(key, 0.0) + float(v)
            elif k in ("delay", "slow"):
                p, _, s = v.partition(":")
                kw[k] = (float(p), float(s or "0.01"))
            elif k == "role":
                kw[k] = v
            else:
                raise ValueError(f"unknown chaos spec key {k!r}")
        return cls(**kw)

    def active(self, role=None):
        """Role gate: a role-scoped plan only fires in matching
        processes (HETU_CHAOS_ROLE, prefix match).  ``role`` overrides
        the env lookup for seams hosting several roles in one process
        (the router's replica fleet stamps ``replica<k>``)."""
        if self.role is None:
            return True
        if role is not None:
            return str(role).startswith(self.role)
        from .. import envvars
        return envvars.get_str("HETU_CHAOS_ROLE").startswith(self.role)

    # ---------------- the decision stream ---------------- #

    def draw(self, method=None, kinds=None, role=None, inline=False):
        """Consume one decision and return the Fault for it.  ``kinds``
        restricts which kinds may fire at this seam (the counter always
        advances, so restricted and unrestricted callers share one
        deterministic stream).  A ``kill`` event SIGKILLs this process
        and does not return — unless ``inline`` is set, in which case
        ``Fault("kill")`` is returned and the caller owns the death
        (the serving replica harness, where a fleet of roles shares one
        process and a SIGKILL would take out the survivors too).
        ``role`` overrides the env role for the gate (see ``active``);
        a non-matching role never advances the counter, so each
        replica's step stream is independently deterministic."""
        if not self.active(role):
            return Fault("none")
        with self._mu:
            self._n += 1
            n = self._n
        if self.kill is not None and n == self.kill and \
                (kinds is None or "kill" in kinds) and \
                _restart_count() == 0:
            # one-shot across RESTARTS too: a supervisor-respawned
            # incarnation (HETU_RESTART_COUNT > 0) must not re-fire the
            # kill, or recovery could never be observed
            self.fired["kill"] += 1
            if inline:
                return Fault("kill")
            try:
                # the kill's black box: dump the flight ring BEFORE the
                # SIGKILL (the process gets no other chance) — a failed
                # dump must never save the victim
                from ..telemetry.flight import RECORDER
                RECORDER.dump("chaos_kill", chaos_event=n,
                              method=str(method))
            except Exception:  # noqa: BLE001
                pass
            os.kill(os.getpid(), signal.SIGKILL)
        if self.wedge is not None and n == self.wedge and \
                kinds is not None and "wedge" in kinds:
            # wedges only fire at seams that can act them out (the
            # replica step loop); transports draw without "wedge" and
            # simply consume the position
            self.fired["wedge"] += 1
            return Fault("wedge")
        u = _u01(self.seed, n)
        edge = 0.0
        for kind, p, secs in (("drop", self.drop, 0.0),
                              ("dup", self.dup, 0.0),
                              ("reset", self.reset, 0.0),
                              ("delay", self.delay[0], self.delay[1]),
                              ("slow", self.slow[0], self.slow[1])):
            edge += p
            if u < edge:
                if kinds is not None and kind not in kinds:
                    return Fault("none")
                self.fired[kind] += 1
                return Fault(kind, secs)
        return Fault("none")


# ---------------- env activation ---------------- #

_plans = {}


def _make_plans_mu():
    from .. import locks
    return locks.TracedLock("chaos.plans")


_plans_mu = _make_plans_mu()


def plan_from_env():
    """The process-wide FaultPlan for the current HETU_CHAOS value, or
    None when chaos is off.  Cached per spec string so the decision
    counter persists across transports/calls; re-reading the env every
    call keeps test toggling cheap and race-free."""
    from .. import envvars
    spec = envvars.get_str("HETU_CHAOS")
    if not spec:
        return None
    with _plans_mu:
        plan = _plans.get(spec)
        if plan is None:
            plan = _plans[spec] = FaultPlan.from_spec(spec)
    return plan


def reset_plans():
    """Forget cached plans (test isolation: a reused spec string starts
    a fresh decision stream)."""
    with _plans_mu:
        _plans.clear()
