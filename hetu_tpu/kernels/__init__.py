"""Pallas TPU kernels for the hot ops.

Planned contents (SURVEY.md §2.1 'TPU equivalent'): fused flash attention,
MoE capacity dispatch, top-k gating helpers.  Modules register themselves
here as they land; import errors mean the kernel is not built yet — all
call sites fall back to the jnp compositions in hetu_tpu.graph.
"""

__all__ = []

try:
    from . import flash_attention  # noqa: F401
    __all__.append("flash_attention")
except ImportError:  # pallas unavailable: call sites fall back to jnp paths
    pass

try:
    from . import decode_attention  # noqa: F401
    __all__.append("decode_attention")
except ImportError:  # pallas unavailable: serving falls back to masked
    pass

try:
    from . import ragged_attention  # noqa: F401
    __all__.append("ragged_attention")
except ImportError:  # pallas unavailable: mixed mode falls back to masked
    pass
