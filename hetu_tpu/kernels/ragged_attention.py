"""ONE ragged mixed-mode attention kernel for the whole serving hot
loop (ISSUE 18, Ragged Paged Attention lineage).

The phase-split engine runs three kernel families per scheduler
iteration — flash prefill for admissions, the decode kernel for
continuing streams, the verify kernel for speculative waves — with a
scheduling barrier between the phases.  This module collapses them:
every slot in a wave carries its OWN ``q_len`` (1 for decode, k+1 for
spec-verify, a chunk of prompt for prefill/chunked-prefill), and one
kernel call scores the whole mixed wave.  Mechanically it is the
verify-kernel computation with nothing verify-specific left in it:

  - grid (slot, kv-block), kv innermost, so the online-softmax
    accumulators (one f32 (m, l, acc) row per (head, query)) persist in
    VMEM scratch across a slot's kv steps;
  - per-slot ``q_len``/``kv_len``/block-table rows ride in as SCALAR
    PREFETCH so the kv block-index maps can see them;
  - blocks wholly past a slot's filled length REVISIT its last live
    block (a repeated index skips the DMA — flash_attention's
    ``_causal_kv_index`` trick) and their compute is skipped with
    ``@pl.when``, so a wave's KV traffic is O(sum(kv_len)), not
    O(B * S_max);
  - scores and the output accumulate in f32 over bf16 pools;
  - the int8 twin takes per-(position, head) scale planes on the same
    revisit index maps and dequantizes INSIDE the online-softmax loop
    (no f32 pool is ever materialized);
  - ``q_len = 1`` degenerates exactly to the decode kernel's mask, so
    a decode-only wave pays no mixed-mode tax.

There is ONE parameterized kernel body (``_ragged_kernel``) behind all
four layouts (contiguous/block-table x f32/int8) and ONE masked-gather
reference (``ragged_masked_reference``) for off-TPU interpret-mode
parity — kernels/decode_attention.py's four per-mode references now
delegate here, and its per-mode kernels remain as parity oracles behind
the existing ``$HETU_SERVE_FAST``/phase-split paths.

The kernel reads the q-block's own K/V back from the pool (the
engine's mixed step writes before it attends), so a lossy cache dtype
(bf16/int8) round-trips prefill chunks exactly like the phase-split
fast path round-trips decode/verify positions; the masked engine path
(``_verify_step``'s mixed mode) keeps the phase-split engine's exact
per-mode arithmetic instead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _fit_block
from .decode_attention import (_LANES, _online_softmax_multi,
                               _use_interpret, _verify_finalize)


def _ragged_kernel(*refs, scale, bk, n_kv, nq, quant, tabled):
    """The single mixed-mode body.  ``refs`` is the Pallas positional
    layout — scalar-prefetch (lens, q_lens[, block_tables]) then
    operands (q, k[, k_scale], v[, v_scale]) then the output and the
    (m, l, acc) scratch — sliced by the two static flags: ``quant``
    adds the int8 scale planes, ``tabled`` the block-table ref (consumed
    only by the index maps).  Everything mode-specific is per-slot DATA
    (q_len, kv_len), never a code path: a decode slot is q_len=1, a
    spec-verify slot k+1, a prefill chunk its chunk width, all in the
    same wave."""
    i = 2 + (1 if tabled else 0)     # skip lens/qlens[/tables] refs
    lens_ref, qlens_ref = refs[0], refs[1]
    q_ref = refs[i]
    if quant:
        k_ref, ks_ref, v_ref, vs_ref = refs[i + 1:i + 5]
        i += 5
    else:
        k_ref, v_ref = refs[i + 1:i + 3]
        i += 3
    o_ref, m_ref, l_ref, acc_ref = refs[i:i + 4]

    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    filled = lens_ref[b]

    # blocks wholly past this slot's filled prefix are dead: their DMA
    # was already skipped by the revisit index map; skip the compute too
    @pl.when(j * bk < filled)
    def _compute():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        if quant:
            k = k.astype(jnp.float32) * ks_ref[0][..., None]
            v = v.astype(jnp.float32) * vs_ref[0][..., None]
            q = q.astype(jnp.float32)
        _online_softmax_multi(q, k, v, filled, qlens_ref[b], j, bk,
                              scale, m_ref, l_ref, acc_ref)

    @pl.when(j == n_kv - 1)
    def _finalize():
        _verify_finalize(o_ref, m_ref, l_ref, acc_ref, nq,
                         q_ref.shape[2], q_ref.shape[3])


def _call_ragged(q, lengths, q_lens, operands, *, bk, n_kv, quant,
                 tabled, in_specs, scalars, interpret):
    B, Q, H, Dh = q.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=(B, n_kv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, Q, H, Dh), lambda b, j, *_: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H * Q, _LANES), jnp.float32),   # running max
            pltpu.VMEM((H * Q, _LANES), jnp.float32),   # running denom
            pltpu.VMEM((H * Q, Dh), jnp.float32),       # output acc
        ],
    )
    return pl.pallas_call(
        functools.partial(_ragged_kernel, scale=Dh ** -0.5, bk=bk,
                          n_kv=n_kv, nq=Q, quant=quant, tabled=tabled),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Q, H, Dh), q.dtype),
        interpret=interpret,
    )(*scalars, *operands)


def ragged_attention(q, k, v, lengths, q_lens, *, block_k=128,
                     k_scale=None, v_scale=None, interpret=None):
    """The mixed wave over the slot-contiguous cache layout.

    q: [B, Q, H, Dh] — one q-block per slot, already written to the
    cache (rows past ``q_lens[b]`` are inert pad whose output the host
    discards); k, v: [B, S_max, H, Dh] (one layer's ``cache_k[i]``);
    lengths: [B] int32 filled counts INCLUDING the q-block's live
    rows; q_lens: [B] int32 live queries per slot — 1 decodes, k+1
    verifies, a chunk width prefills, mixed freely in one call.
    Returns o [B, Q, H, Dh] in q's dtype; a slot with lengths 0
    returns zeros.  Int8 caches pass ``k_scale``/``v_scale``
    [B, S_max, H] f32."""
    B, Q, H, Dh = q.shape
    S = k.shape[1]
    bk = _fit_block(block_k, S)
    if interpret is None:
        interpret = _use_interpret()
    quant = k_scale is not None

    def kv_idx(b, j, lens_ref, qlens_ref):
        # dead blocks revisit the slot's last live block: the repeated
        # index skips the DMA entirely
        last = jnp.maximum(lens_ref[b] - 1, 0) // bk
        return (b, jnp.minimum(j, last), 0, 0)

    def sc_idx(b, j, lens_ref, qlens_ref):
        last = jnp.maximum(lens_ref[b] - 1, 0) // bk
        return (b, jnp.minimum(j, last), 0)

    q_spec = pl.BlockSpec((1, Q, H, Dh),
                          lambda b, j, lens, qlens: (b, 0, 0, 0))
    if quant:
        in_specs = [q_spec,
                    pl.BlockSpec((1, bk, H, Dh), kv_idx),
                    pl.BlockSpec((1, bk, H), sc_idx),
                    pl.BlockSpec((1, bk, H, Dh), kv_idx),
                    pl.BlockSpec((1, bk, H), sc_idx)]
        operands = (q, k, k_scale, v, v_scale)
    else:
        in_specs = [q_spec,
                    pl.BlockSpec((1, bk, H, Dh), kv_idx),
                    pl.BlockSpec((1, bk, H, Dh), kv_idx)]
        operands = (q, k, v)
    return _call_ragged(
        q, lengths, q_lens, operands, bk=bk, n_kv=S // bk, quant=quant,
        tabled=False, in_specs=in_specs,
        scalars=(lengths.astype(jnp.int32), q_lens.astype(jnp.int32)),
        interpret=interpret)


def ragged_paged_attention(q, pool_k, pool_v, lengths, q_lens,
                           block_tables, *, k_scale=None, v_scale=None,
                           interpret=None):
    """The mixed wave over the BLOCK-TABLE paged pool — the serving
    engine's production mixed-mode dispatch.

    q: [B, Q, H, Dh]; pool_k, pool_v: [N_blocks, bs, H, Dh] (the shared
    pool, one layer); block_tables: [B, T] int32 — entry (b, j) is the
    pool block holding slot b's positions [j*bs, (j+1)*bs); lengths /
    q_lens: [B] int32 as in :func:`ragged_attention` (dead table
    entries may hold any valid pool index — the engine points them at
    scratch block 0).  Each slot DMAs exactly ceil(lengths[b]/bs) live
    pool blocks through its scalar-prefetched table row; shared prefix
    blocks are fetched per-slot but stored once.  Int8 pools pass
    ``k_scale``/``v_scale`` [N_blocks, bs, H] f32."""
    B, Q, H, Dh = q.shape
    bs = pool_k.shape[1]
    T = block_tables.shape[1]
    if interpret is None:
        interpret = _use_interpret()
    quant = k_scale is not None

    def kv_idx(b, j, lens_ref, qlens_ref, bt_ref):
        last = jnp.maximum(lens_ref[b] - 1, 0) // bs
        return (bt_ref[b, jnp.minimum(j, last)], 0, 0, 0)

    def sc_idx(b, j, lens_ref, qlens_ref, bt_ref):
        last = jnp.maximum(lens_ref[b] - 1, 0) // bs
        return (bt_ref[b, jnp.minimum(j, last)], 0, 0)

    q_spec = pl.BlockSpec((1, Q, H, Dh),
                          lambda b, j, lens, qlens, bt: (b, 0, 0, 0))
    if quant:
        in_specs = [q_spec,
                    pl.BlockSpec((1, bs, H, Dh), kv_idx),
                    pl.BlockSpec((1, bs, H), sc_idx),
                    pl.BlockSpec((1, bs, H, Dh), kv_idx),
                    pl.BlockSpec((1, bs, H), sc_idx)]
        operands = (q, pool_k, k_scale, pool_v, v_scale)
    else:
        in_specs = [q_spec,
                    pl.BlockSpec((1, bs, H, Dh), kv_idx),
                    pl.BlockSpec((1, bs, H, Dh), kv_idx)]
        operands = (q, pool_k, pool_v)
    return _call_ragged(
        q, lengths, q_lens, operands, bk=bs, n_kv=T, quant=quant,
        tabled=True, in_specs=in_specs,
        scalars=(lengths.astype(jnp.int32), q_lens.astype(jnp.int32),
                 block_tables.astype(jnp.int32)),
        interpret=interpret)


def ragged_masked_reference(q, k, v, lengths, q_lens=None, k_scale=None,
                            v_scale=None):
    """THE masked-gather oracle (f32) — one parameterized reference for
    every mode and layout: decode (q_lens 1), verify (k+1), prefill
    chunks, and any mix, contiguous or gathered-from-pool, f32 or int8
    (dequantized through the per-(position, head) scale planes first).
    ``q_lens=None`` means every row is live (a full q-block).  Query
    ``jq`` of slot b sits at absolute position
    ``lengths[b] - q_lens[b] + jq`` and admits kv positions up to
    itself; rows past ``q_lens[b]`` clip to the last live position so
    their (discarded) softmax stays finite; a slot with lengths 0
    returns zeros.  kernels/decode_attention.py's four per-mode
    references are thin delegates of this function."""
    if k_scale is not None:
        k = k.astype(jnp.float32) * k_scale[..., None]
        v = v.astype(jnp.float32) * v_scale[..., None]
    B, Q = q.shape[:2]
    if q_lens is None:
        q_lens = jnp.full((B,), Q, jnp.int32)
    S = k.shape[1]
    posq = jnp.clip(
        (lengths - q_lens)[:, None] + jnp.arange(Q)[None, :], 0,
        jnp.maximum(lengths - 1, 0)[:, None])              # [B, Q]
    s = jnp.einsum("bqhd,bshd->bqhs", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (q.shape[-1] ** -0.5)
    live = jnp.arange(S)[None, None, None, :] <= posq[:, :, None, None]
    s = jnp.where(live, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bqhs,bshd->bqhd", p, v.astype(jnp.float32))
    return out * (lengths > 0)[:, None, None, None]


def ragged_paged_reference(q, pool_k, pool_v, lengths, q_lens,
                           block_tables, k_scale=None, v_scale=None):
    """Gather-then-mask oracle for the block-table mixed kernel:
    materialize each slot's logical [T*bs] KV view from the pool and
    delegate to :func:`ragged_masked_reference`."""
    B = q.shape[0]
    bs = pool_k.shape[1]
    T = block_tables.shape[1]
    k = pool_k[block_tables].reshape(B, T * bs, *pool_k.shape[2:])
    v = pool_v[block_tables].reshape(B, T * bs, *pool_v.shape[2:])
    ks = vs = None
    if k_scale is not None:
        ks = k_scale[block_tables].reshape(B, T * bs, *k_scale.shape[2:])
        vs = v_scale[block_tables].reshape(B, T * bs, *v_scale.shape[2:])
    return ragged_masked_reference(q, k, v, lengths, q_lens, ks, vs)
