"""Ragged paged decode-attention Pallas kernel for the serving engine.

One fused decode step attends q_len=1 per cache slot over that slot's
OWN filled prefix.  The masked reference path (``_decode_step``'s
einsum) streams and masks the full padded ``S_max`` for every slot, so
a slot holding 80 tokens in a 2048-position bucket pays ~25x the
attention FLOPs and KV DMA it needs.  This kernel makes the step scale
with actual tokens: grid (slots, kv_blocks), per-slot filled lengths
ride in as SCALAR-PREFETCH (``PrefetchScalarGridSpec``) so the kv
block-index map can see them — blocks wholly past a slot's filled
length map back to its LAST LIVE block (flash_attention's
``_causal_kv_index`` revisit trick: a repeated index skips the DMA
entirely), and their compute is separately skipped with ``@pl.when``.
A slot therefore fetches exactly ``ceil(filled / block_k)`` KV blocks,
and the ragged batch's total traffic is O(sum(filled)) instead of
O(B * S_max).

The online-softmax accumulators (m, l, acc) live in VMEM scratch and
persist across the kv steps of one slot (TPU grids execute
sequentially, kv innermost).  Scores and the output accumulate in f32
regardless of the cache dtype (bf16 caches keep full-precision
softmax), matching the flash prefill kernel's accounting.

Decode is inference-only — no VJP.  On non-TPU backends the kernel
runs in interpret mode, so the same code path is testable on the CPU
harness (parity suite in tests/test_serve_fastpath.py).

INT8 KV (``HETU_KV_QUANT``, Ragged Paged Attention lineage): both
kernels take optional ``k_scale``/``v_scale`` planes — the cache stays
int8 in HBM and dequantizes INSIDE the online-softmax loop (per
(position, head) scales ride the same revisit index maps, so dead
blocks skip their DMA too); no f32 pool is ever materialized, which is
what lets ~3.7x more tokens fit per HBM byte.

MULTI-TOKEN VERIFY (speculative decoding, ISSUE 10): the ``*_verify_*``
kernels generalize q_len=1 to a ``k+1``-position q-block per slot —
the target model's batched verification of a draft's proposals.  Same
grid, same scalar-prefetched lengths/tables, same revisit-index DMA
skipping; the q-block is causal INSIDE itself (query ``jq`` at absolute
position ``lens - q_len + jq`` admits kv positions up to itself), so
one kernel call scores all proposed positions exactly as ``k+1``
sequential decode steps would.  Accumulators widen to one online-softmax
state per (head, query) pair; everything else is unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _fit_block, _prec

_LANES = 128


def _online_softmax_update(q, k, v, filled, j, bk, scale, m_ref, l_ref,
                           acc_ref):
    """One KV block's contribution to a slot's online softmax: shared
    verbatim by the f32/bf16 kernels and the int8 variants (which
    dequantize k/v right before calling this — the dequant lives INSIDE
    the online-softmax loop, no f32 pool is ever materialized)."""
    H = q.shape[0]
    # s[h, s] = q[h] . k[s, h] — per-head matvec, batched over heads
    s = jax.lax.dot_general(
        q, k, (((1,), (2,)), ((0,), (1,))),
        precision=_prec(q.dtype),
        preferred_element_type=jnp.float32) * scale   # [H, bk]
    kv_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (H, bk), 1)
    s = jnp.where(kv_pos < filled, s, NEG_INF)
    m_prev = m_ref[:, 0:1]
    l_prev = l_ref[:, 0:1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - safe_m)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    alpha = jnp.exp(jnp.clip(m_prev - m_new, max=0.0))
    alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0, alpha)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((0,), (1,))),
        precision=_prec(v.dtype),
        preferred_element_type=jnp.float32)           # [H, Dh]
    acc_ref[:] = acc_ref[:] * alpha + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)


def _decode_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                   acc_ref, *, scale, bk, n_kv):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    filled = lens_ref[b]

    # blocks wholly past this slot's filled prefix are dead: their DMA
    # was already skipped by the revisit index map; skip the compute too
    @pl.when(j * bk < filled)
    def _compute():
        _online_softmax_update(q_ref[0, 0], k_ref[0], v_ref[0], filled,
                               j, bk, scale, m_ref, l_ref, acc_ref)

    @pl.when(j == n_kv - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        denom = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _decode_kernel_int8(lens_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref,
                        o_ref, m_ref, l_ref, acc_ref, *, scale, bk,
                        n_kv):
    """Int8 twin of ``_decode_kernel``: the KV blocks arrive as int8
    payloads plus per-(position, head) f32 scales (two extra refs with
    the same revisit index maps, so dead blocks skip the scale DMA
    too), and dequantize to f32 INSIDE the online-softmax loop — the
    HBM traffic is int8, the softmax accounting identical to the f32
    kernel."""
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    filled = lens_ref[b]

    @pl.when(j * bk < filled)
    def _compute():
        k = k_ref[0].astype(jnp.float32) * ks_ref[0][..., None]
        v = v_ref[0].astype(jnp.float32) * vs_ref[0][..., None]
        _online_softmax_update(q_ref[0, 0].astype(jnp.float32), k, v,
                               filled, j, bk, scale, m_ref, l_ref,
                               acc_ref)

    @pl.when(j == n_kv - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        denom = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _use_interpret():
    return jax.default_backend() != "tpu"


def paged_decode_attention(q, k, v, lengths, *, block_k=128,
                           k_scale=None, v_scale=None, interpret=None):
    """One decode position per slot over a paged/ragged KV cache.

    q: [B, H, Dh] (this step's query per slot); k, v: [B, S_max, H, Dh]
    (the cache rows, one per slot — the layer's ``cache_k[i]``);
    lengths: [B] int32 — positions 0..lengths[b]-1 of slot b are live
    (the slot's filled count INCLUDING the position just written).
    Returns o [B, H, Dh] in q's dtype.  Each slot fetches only
    ``ceil(lengths[b] / block_k)`` KV blocks; a slot with lengths 0
    returns zeros (matching the masked reference's fully-dead-row
    convention).

    INT8 caches: pass k/v as int8 with ``k_scale``/``v_scale``
    [B, S_max, H] f32 (one scale per position per head — the
    ``HETU_KV_QUANT`` layout); the kernel DMAs int8 and dequantizes
    inside the online-softmax loop.
    """
    B, H, Dh = q.shape
    S = k.shape[1]
    bk = _fit_block(block_k, S)
    n_kv = S // bk
    scale = Dh ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    quantized = k_scale is not None

    def kv_idx(b, j, lens_ref):
        # dead blocks revisit the slot's last live block: the repeated
        # index skips the DMA (same trick as _causal_kv_index)
        last = jnp.maximum(lens_ref[b] - 1, 0) // bk
        return (b, jnp.minimum(j, last), 0, 0)

    def sc_idx(b, j, lens_ref):
        last = jnp.maximum(lens_ref[b] - 1, 0) // bk
        return (b, jnp.minimum(j, last), 0)

    if quantized:
        kernel = _decode_kernel_int8
        in_specs = [
            pl.BlockSpec((1, 1, H, Dh), lambda b, j, lens: (b, 0, 0, 0)),
            pl.BlockSpec((1, bk, H, Dh), kv_idx),
            pl.BlockSpec((1, bk, H), sc_idx),
            pl.BlockSpec((1, bk, H, Dh), kv_idx),
            pl.BlockSpec((1, bk, H), sc_idx),
        ]
        operands = (q[:, None], k, k_scale, v, v_scale)
    else:
        kernel = _decode_kernel
        in_specs = [
            pl.BlockSpec((1, 1, H, Dh), lambda b, j, lens: (b, 0, 0, 0)),
            pl.BlockSpec((1, bk, H, Dh), kv_idx),
            pl.BlockSpec((1, bk, H, Dh), kv_idx),
        ]
        operands = (q[:, None], k, v)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, n_kv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, H, Dh),
                               lambda b, j, lens: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, _LANES), jnp.float32),   # running max
            pltpu.VMEM((H, _LANES), jnp.float32),   # running denom
            pltpu.VMEM((H, Dh), jnp.float32),       # output accumulator
        ],
    )
    out = pl.pallas_call(
        functools.partial(kernel, scale=scale, bk=bk, n_kv=n_kv),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, 1, H, Dh), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), *operands)
    return out[:, 0]


def _block_decode_kernel(lens_ref, bt_ref, q_ref, k_ref, v_ref, o_ref,
                         m_ref, l_ref, acc_ref, *, scale, bk, n_kv):
    """Block-table twin of ``_decode_kernel``: same online-softmax body
    (the extra scalar-prefetch ref is the block table, consumed only by
    the index maps — kv positions are still ``j * bk + iota`` because
    table entry j holds the sequence's j-th block)."""
    del bt_ref
    _decode_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                   acc_ref, scale=scale, bk=bk, n_kv=n_kv)


def _block_decode_kernel_int8(lens_ref, bt_ref, q_ref, k_ref, ks_ref,
                              v_ref, vs_ref, o_ref, m_ref, l_ref,
                              acc_ref, *, scale, bk, n_kv):
    """Block-table twin of ``_decode_kernel_int8``: int8 pool blocks +
    per-(position, head) scale blocks, both routed through the table's
    index maps, dequantized inside the online-softmax loop."""
    del bt_ref
    _decode_kernel_int8(lens_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref,
                        o_ref, m_ref, l_ref, acc_ref, scale=scale,
                        bk=bk, n_kv=n_kv)


def paged_block_decode_attention(q, pool_k, pool_v, lengths,
                                 block_tables, *, k_scale=None,
                                 v_scale=None, interpret=None):
    """One decode position per slot over a BLOCK-TABLE paged KV pool.

    q: [B, H, Dh]; pool_k, pool_v: [N_blocks, bs, H, Dh] — the SHARED
    block pool (one layer's ``cache_k[i]``), where a sequence's KV
    lives in the pool blocks its table names; block_tables: [B, T]
    int32 — entry (b, j) is the pool block holding slot b's positions
    [j*bs, (j+1)*bs); lengths: [B] int32 filled counts (0 = inert slot,
    returns zeros).  Dead table entries may hold any valid pool index
    (the engine points them at scratch block 0).

    Grid (slots, table entries), both scalar-prefetched: the kv index
    map reads ``block_tables[b, j]`` so each slot DMAs exactly its own
    ``ceil(lengths[b]/bs)`` live blocks from the pool — entries past
    the filled length revisit the last live block (repeated index =
    DMA skipped) and their compute is skipped with ``@pl.when``.
    Shared prefix blocks are fetched per-slot but STORED once in HBM,
    which is the capacity win this kernel exists for.  f32
    online-softmax over bf16 pools, matching ``paged_decode_attention``.

    INT8 pools (``HETU_KV_QUANT``): pass the pools as int8 with
    ``k_scale``/``v_scale`` [N_blocks, bs, H] f32 — the scale blocks
    ride the same table index maps (dead entries skip their DMA too)
    and dequantize inside the online-softmax loop, so the capacity win
    compounds ~3.7x on top of prefix sharing.
    """
    B, H, Dh = q.shape
    bs = pool_k.shape[1]
    T = block_tables.shape[1]
    scale = Dh ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    quantized = k_scale is not None

    def kv_idx(b, j, lens_ref, bt_ref):
        last = jnp.maximum(lens_ref[b] - 1, 0) // bs
        return (bt_ref[b, jnp.minimum(j, last)], 0, 0, 0)

    def sc_idx(b, j, lens_ref, bt_ref):
        last = jnp.maximum(lens_ref[b] - 1, 0) // bs
        return (bt_ref[b, jnp.minimum(j, last)], 0, 0)

    if quantized:
        kernel = _block_decode_kernel_int8
        in_specs = [
            pl.BlockSpec((1, 1, H, Dh),
                         lambda b, j, lens, bt: (b, 0, 0, 0)),
            pl.BlockSpec((1, bs, H, Dh), kv_idx),
            pl.BlockSpec((1, bs, H), sc_idx),
            pl.BlockSpec((1, bs, H, Dh), kv_idx),
            pl.BlockSpec((1, bs, H), sc_idx),
        ]
        operands = (q[:, None], pool_k, k_scale, pool_v, v_scale)
    else:
        kernel = _block_decode_kernel
        in_specs = [
            pl.BlockSpec((1, 1, H, Dh),
                         lambda b, j, lens, bt: (b, 0, 0, 0)),
            pl.BlockSpec((1, bs, H, Dh), kv_idx),
            pl.BlockSpec((1, bs, H, Dh), kv_idx),
        ]
        operands = (q[:, None], pool_k, pool_v)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, T),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, H, Dh),
                               lambda b, j, lens, bt: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, _LANES), jnp.float32),
            pltpu.VMEM((H, _LANES), jnp.float32),
            pltpu.VMEM((H, Dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(kernel, scale=scale, bk=bs, n_kv=T),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, 1, H, Dh), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), block_tables.astype(jnp.int32),
      *operands)
    return out[:, 0]


def paged_block_decode_reference(q, pool_k, pool_v, lengths,
                                 block_tables, k_scale=None,
                                 v_scale=None):
    """Gather-then-mask oracle for the block-table kernel: the
    decode (q_len 1) degenerate of the unified ragged paged reference
    (dequantizing int8 pools through their gathered scale planes — the
    masked-gather reference path the engine runs off-TPU)."""
    from .ragged_attention import ragged_paged_reference
    ones = jnp.ones_like(lengths)
    return ragged_paged_reference(q[:, None], pool_k, pool_v, lengths,
                                  ones, block_tables, k_scale,
                                  v_scale)[:, 0]


# ------------------------------------------------------------------- #
# multi-token verify kernels (speculative decoding)
# ------------------------------------------------------------------- #


def _query_positions(filled, qlen, nq):
    """Absolute position of each query in a slot's verify q-block:
    query ``jq`` sits at ``filled - qlen + jq``; dead queries
    (``jq >= qlen``) clip to the last live position so their (discarded)
    softmax rows stay finite, and a fully-inert slot (filled 0) clips
    to 0 — the ``l == 0`` finalize guard zeroes its output anyway."""
    qidx = jax.lax.broadcasted_iota(jnp.int32, (1, nq, 1), 1)
    return jnp.clip(filled - qlen + qidx, 0,
                    jnp.maximum(filled - 1, 0))


def _online_softmax_multi(q, k, v, filled, qlen, j, bk, scale, m_ref,
                          l_ref, acc_ref):
    """One KV block's contribution to a VERIFY q-block's online softmax:
    ``q`` [Q, H, Dh] against ``k``/``v`` [bk, H, Dh], one accumulator
    row per (head, query).  The causal mask inside the q-block falls out
    of the per-query absolute positions — query jq admits kv positions
    up to ``filled - qlen + jq``, which for qlen=1 degenerates to the
    single-query kernel's ``< filled`` mask."""
    Q, H, Dh = q.shape
    R = H * Q
    # s[h, qj, s] = q[qj, h] . k[s, h] — batched over heads
    qt = jnp.swapaxes(q, 0, 1)                            # [H, Q, Dh]
    s = jax.lax.dot_general(
        qt, k, (((2,), (2,)), ((0,), (1,))),
        precision=_prec(q.dtype),
        preferred_element_type=jnp.float32) * scale       # [H, Q, bk]
    kv_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (H, Q, bk), 2)
    posq = _query_positions(filled, qlen, Q)              # [1, Q, 1]
    s = jnp.where(kv_pos <= posq, s, NEG_INF)
    s = s.reshape(R, bk)
    m_prev = m_ref[:, 0:1]
    l_prev = l_ref[:, 0:1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - safe_m)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    alpha = jnp.exp(jnp.clip(m_prev - m_new, max=0.0))
    alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0, alpha)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p.reshape(H, Q, bk).astype(v.dtype), v,
        (((2,), (0,)), ((0,), (1,))),
        precision=_prec(v.dtype),
        preferred_element_type=jnp.float32)               # [H, Q, Dh]
    acc_ref[:] = acc_ref[:] * alpha + pv.reshape(R, Dh)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)


def _verify_finalize(o_ref, m_ref, l_ref, acc_ref, nq, heads, dh):
    l = l_ref[:, 0:1]
    denom = jnp.where(l == 0.0, 1.0, l)
    o = (acc_ref[:] / denom).reshape(heads, nq, dh)
    o_ref[0] = jnp.swapaxes(o, 0, 1).astype(o_ref.dtype)


def _verify_kernel(lens_ref, qlens_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, scale, bk, n_kv, nq):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    filled = lens_ref[b]

    @pl.when(j * bk < filled)
    def _compute():
        _online_softmax_multi(q_ref[0], k_ref[0], v_ref[0], filled,
                              qlens_ref[b], j, bk, scale, m_ref, l_ref,
                              acc_ref)

    @pl.when(j == n_kv - 1)
    def _finalize():
        _verify_finalize(o_ref, m_ref, l_ref, acc_ref, nq,
                         q_ref.shape[2], q_ref.shape[3])


def _verify_kernel_int8(lens_ref, qlens_ref, q_ref, k_ref, ks_ref,
                        v_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref, *,
                        scale, bk, n_kv, nq):
    """Int8 twin of ``_verify_kernel`` (see ``_decode_kernel_int8`` for
    the dequant-inside-the-loop rationale)."""
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    filled = lens_ref[b]

    @pl.when(j * bk < filled)
    def _compute():
        k = k_ref[0].astype(jnp.float32) * ks_ref[0][..., None]
        v = v_ref[0].astype(jnp.float32) * vs_ref[0][..., None]
        _online_softmax_multi(q_ref[0].astype(jnp.float32), k, v,
                              filled, qlens_ref[b], j, bk, scale,
                              m_ref, l_ref, acc_ref)

    @pl.when(j == n_kv - 1)
    def _finalize():
        _verify_finalize(o_ref, m_ref, l_ref, acc_ref, nq,
                         q_ref.shape[2], q_ref.shape[3])


def paged_verify_attention(q, k, v, lengths, q_lens, *, block_k=128,
                           k_scale=None, v_scale=None, interpret=None):
    """A ``Q``-position verify q-block per slot over the slot-contiguous
    ragged cache.

    q: [B, Q, H, Dh] — this wave's q-block per slot (the draft's k
    proposals plus the carried token, already written to the cache);
    k, v: [B, S_max, H, Dh]; lengths: [B] int32 — the slot's filled
    count INCLUDING the q-block's live positions; q_lens: [B] int32 —
    live queries per slot (rows jq >= q_lens[b] are inert: their output
    is finite garbage the host discards).  Returns o [B, Q, H, Dh].
    Each slot still fetches only ``ceil(lengths[b] / block_k)`` KV
    blocks; the causal structure inside the q-block is enforced by
    per-query position masks, so the call scores exactly what q_lens[b]
    sequential decode steps would.  Int8 caches: pass
    ``k_scale``/``v_scale`` [B, S_max, H] f32 as in
    :func:`paged_decode_attention`."""
    B, Q, H, Dh = q.shape
    S = k.shape[1]
    bk = _fit_block(block_k, S)
    n_kv = S // bk
    scale = Dh ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    quantized = k_scale is not None

    def kv_idx(b, j, lens_ref, qlens_ref):
        last = jnp.maximum(lens_ref[b] - 1, 0) // bk
        return (b, jnp.minimum(j, last), 0, 0)

    def sc_idx(b, j, lens_ref, qlens_ref):
        last = jnp.maximum(lens_ref[b] - 1, 0) // bk
        return (b, jnp.minimum(j, last), 0)

    if quantized:
        kernel = _verify_kernel_int8
        in_specs = [
            pl.BlockSpec((1, Q, H, Dh),
                         lambda b, j, lens, qlens: (b, 0, 0, 0)),
            pl.BlockSpec((1, bk, H, Dh), kv_idx),
            pl.BlockSpec((1, bk, H), sc_idx),
            pl.BlockSpec((1, bk, H, Dh), kv_idx),
            pl.BlockSpec((1, bk, H), sc_idx),
        ]
        operands = (q, k, k_scale, v, v_scale)
    else:
        kernel = _verify_kernel
        in_specs = [
            pl.BlockSpec((1, Q, H, Dh),
                         lambda b, j, lens, qlens: (b, 0, 0, 0)),
            pl.BlockSpec((1, bk, H, Dh), kv_idx),
            pl.BlockSpec((1, bk, H, Dh), kv_idx),
        ]
        operands = (q, k, v)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, n_kv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Q, H, Dh),
                               lambda b, j, lens, qlens: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H * Q, _LANES), jnp.float32),   # running max
            pltpu.VMEM((H * Q, _LANES), jnp.float32),   # running denom
            pltpu.VMEM((H * Q, Dh), jnp.float32),       # output acc
        ],
    )
    return pl.pallas_call(
        functools.partial(kernel, scale=scale, bk=bk, n_kv=n_kv, nq=Q),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Q, H, Dh), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), q_lens.astype(jnp.int32), *operands)


def _block_verify_kernel(lens_ref, qlens_ref, bt_ref, q_ref, k_ref,
                         v_ref, o_ref, m_ref, l_ref, acc_ref, *, scale,
                         bk, n_kv, nq):
    del bt_ref
    _verify_kernel(lens_ref, qlens_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, scale=scale, bk=bk,
                   n_kv=n_kv, nq=nq)


def _block_verify_kernel_int8(lens_ref, qlens_ref, bt_ref, q_ref,
                              k_ref, ks_ref, v_ref, vs_ref, o_ref,
                              m_ref, l_ref, acc_ref, *, scale, bk,
                              n_kv, nq):
    del bt_ref
    _verify_kernel_int8(lens_ref, qlens_ref, q_ref, k_ref, ks_ref,
                        v_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref,
                        scale=scale, bk=bk, n_kv=n_kv, nq=nq)


def paged_block_verify_attention(q, pool_k, pool_v, lengths, q_lens,
                                 block_tables, *, k_scale=None,
                                 v_scale=None, interpret=None):
    """``paged_verify_attention`` over the BLOCK-TABLE paged pool: the
    verify q-block reads each slot's live pool blocks through its
    scalar-prefetched table row, exactly like
    :func:`paged_block_decode_attention` (dead entries revisit = DMA
    skipped; shared prefix blocks stored once), with the q-block causal
    masks of the contiguous verify kernel.  q: [B, Q, H, Dh]; pools
    [N_blocks, bs, H, Dh]; lengths/q_lens [B]; block_tables [B, T].
    Int8 pools pass ``k_scale``/``v_scale`` [N_blocks, bs, H] f32."""
    B, Q, H, Dh = q.shape
    bs = pool_k.shape[1]
    T = block_tables.shape[1]
    scale = Dh ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    quantized = k_scale is not None

    def kv_idx(b, j, lens_ref, qlens_ref, bt_ref):
        last = jnp.maximum(lens_ref[b] - 1, 0) // bs
        return (bt_ref[b, jnp.minimum(j, last)], 0, 0, 0)

    def sc_idx(b, j, lens_ref, qlens_ref, bt_ref):
        last = jnp.maximum(lens_ref[b] - 1, 0) // bs
        return (bt_ref[b, jnp.minimum(j, last)], 0, 0)

    if quantized:
        kernel = _block_verify_kernel_int8
        in_specs = [
            pl.BlockSpec((1, Q, H, Dh),
                         lambda b, j, lens, qlens, bt: (b, 0, 0, 0)),
            pl.BlockSpec((1, bs, H, Dh), kv_idx),
            pl.BlockSpec((1, bs, H), sc_idx),
            pl.BlockSpec((1, bs, H, Dh), kv_idx),
            pl.BlockSpec((1, bs, H), sc_idx),
        ]
        operands = (q, pool_k, k_scale, pool_v, v_scale)
    else:
        kernel = _block_verify_kernel
        in_specs = [
            pl.BlockSpec((1, Q, H, Dh),
                         lambda b, j, lens, qlens, bt: (b, 0, 0, 0)),
            pl.BlockSpec((1, bs, H, Dh), kv_idx),
            pl.BlockSpec((1, bs, H, Dh), kv_idx),
        ]
        operands = (q, pool_k, pool_v)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, T),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Q, H, Dh),
                               lambda b, j, lens, qlens, bt:
                               (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H * Q, _LANES), jnp.float32),
            pltpu.VMEM((H * Q, _LANES), jnp.float32),
            pltpu.VMEM((H * Q, Dh), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(kernel, scale=scale, bk=bs, n_kv=T, nq=Q),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Q, H, Dh), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), q_lens.astype(jnp.int32),
      block_tables.astype(jnp.int32), *operands)


def masked_verify_reference(q, k, v, lengths, q_lens, k_scale=None,
                            v_scale=None):
    """Exact masked oracle (f32) for the verify kernels: per-query
    causal masks over the full padded cache — the same arithmetic
    ``_verify_step``'s einsum path runs.  Now a thin delegate of the
    unified ragged reference (a verify wave IS a ragged wave)."""
    from .ragged_attention import ragged_masked_reference
    return ragged_masked_reference(q, k, v, lengths, q_lens, k_scale,
                                   v_scale)


def paged_block_verify_reference(q, pool_k, pool_v, lengths, q_lens,
                                 block_tables, k_scale=None,
                                 v_scale=None):
    """Gather-then-mask oracle for the block-table verify kernel — a
    thin delegate of the unified ragged paged reference."""
    from .ragged_attention import ragged_paged_reference
    return ragged_paged_reference(q, pool_k, pool_v, lengths, q_lens,
                                  block_tables, k_scale, v_scale)


def masked_decode_reference(q, k, v, lengths, k_scale=None,
                            v_scale=None):
    """Exact masked-``S_max`` oracle (f32) for the parity suite: the
    same arithmetic ``_decode_step``'s einsum path runs, minus the
    compute-dtype shortcuts.  A decode step is the q_len-1 degenerate
    of the unified ragged reference (position ``lengths - 1`` admits
    kv < ``lengths``; a dead slot is zeroed by the same guard), so this
    is now a thin delegate of it."""
    from .ragged_attention import ragged_masked_reference
    ones = jnp.ones_like(lengths)
    return ragged_masked_reference(q[:, None], k, v, lengths, ones,
                                   k_scale, v_scale)[:, 0]
