"""Pallas TPU flash attention (forward kernel + custom VJP).

The hot op of every transformer in the model zoo (SURVEY.md §2.1 "TPU
equivalent": the genuinely custom kernels become Pallas).  Blockwise
online-softmax attention: for each query block the kernel streams key/value
blocks through VMEM, keeping running max/denominator, so the S x S score
matrix never leaves VMEM and HBM traffic is O(S*D) instead of O(S^2).

Grid: (batch*heads, q_blocks, kv_blocks); the kv dimension is innermost so
the VMEM scratch accumulators (m, l, acc) persist across kv steps of one
query block (TPU grids execute sequentially).  Causal blocks strictly above
the diagonal are skipped with @pl.when — ~2x fewer FLOPs for causal LM.

Backward: custom_vjp recomputing through the pure-jnp blockwise oracle
(parallel/context_parallel.blockwise_attention) — numerically identical
math, O(S) memory via block streaming; a fused Pallas backward kernel is a
future optimization.

On non-TPU backends the kernel runs in interpret mode, so the same code
path is testable on the 8-device CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128


def _fit_block(block, length):
    """Largest divisor of ``length`` that is <= min(block, length), so any
    sequence length works (non-divisible requests shrink the block rather
    than assert)."""
    b = min(block, length)
    while length % b:
        b -= 1
    return b


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                *, scale, causal, bq, bk, n_kv):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        # block (qi, kj) is live unless every q position < every kv position
        run = (kj * bk) <= (qi * bq + bq - 1)

    @pl.when(run if causal else True)
    def _compute():
        q = q_ref[0]          # [bq, D]
        k = k_ref[0]          # [bk, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kv_pos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= kv_pos, s, NEG_INF)
        m_prev = m_ref[:, 0:1]                      # [bq, 1]
        l_prev = l_ref[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - safe_m)
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(jnp.clip(m_prev - m_new, max=0.0))
        alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0, alpha)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kj == n_kv - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        denom = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, causal, block_q, block_k, interpret):
    """q, k, v: [BH, S, D] -> o: [BH, S, D]."""
    BH, S, D = q.shape
    Sk = k.shape[1]
    bq = _fit_block(block_q, S)
    bk = _fit_block(block_k, Sk)
    n_q, n_kv = S // bq, Sk // bk
    scale = D ** -0.5

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk, n_kv=n_kv)
    return pl.pallas_call(
        kernel,
        grid=(BH, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),   # running max
            pltpu.VMEM((bq, _LANES), jnp.float32),   # running denom
            pltpu.VMEM((bq, D), jnp.float32),        # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)


def _use_interpret():
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    return _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                      block_k=block_k, interpret=_use_interpret())


def _oracle(q, k, v, causal):
    """Pure-jnp blockwise attention on [BH, S, D] (bwd recompute path)."""
    from ..parallel.context_parallel import blockwise_attention
    # blockwise_attention expects [B, S, H, D]; fold BH into batch, H=1
    qo = q[:, :, None, :]
    ko = k[:, :, None, :]
    vo = v[:, :, None, :]
    out = blockwise_attention(qo, ko, vo, block_size=512, causal=causal)
    return out[:, :, 0, :]


def _flash_fwd_rule(q, k, v, causal, block_q, block_k):
    o = _flash(q, k, v, causal, block_q, block_k)
    return o, (q, k, v)


def _flash_bwd_rule(causal, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _oracle(q, k, v, causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, *, causal=False, block_q=128, block_k=128):
    """Flash attention on [B, S, H, D] (framework layout).

    Differentiable; runs the Pallas kernel forward (interpret mode off-TPU)
    and a blockwise-recompute backward.
    """
    B, S, H, D = q.shape
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)
    o = _flash(fold(q), fold(k), fold(v), causal, block_q, block_k)
    return o.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def mha_reference(q, k, v, *, causal=False):
    """Exact attention oracle on [B, S, H, D] for tests."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((Sq, Sk), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
