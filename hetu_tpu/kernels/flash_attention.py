"""Pallas TPU flash attention (forward kernel + custom VJP).

The hot op of every transformer in the model zoo (SURVEY.md §2.1 "TPU
equivalent": the genuinely custom kernels become Pallas).  Blockwise
online-softmax attention: for each query block the kernel streams key/value
blocks through VMEM, keeping running max/denominator, so the S x S score
matrix never leaves VMEM and HBM traffic is O(S*D) instead of O(S^2).

Grid: (batch*heads, q_blocks, kv_blocks); the kv dimension is innermost so
the VMEM scratch accumulators (m, l, acc) persist across kv steps of one
query block (TPU grids execute sequentially).  Causal blocks strictly above
the diagonal are skipped with @pl.when — ~2x fewer FLOPs for causal LM.

Backward: fused Pallas kernels (FlashAttention-2 style).  The forward
additionally emits the per-row logsumexp; the backward recomputes P
block-by-block from (q, k, lse) in VMEM — never materializing the S x S
matrix — with two passes: a dK/dV kernel whose grid iterates query blocks
innermost (accumulating [bk, D] scratch per kv block) and a dQ kernel
iterating kv blocks innermost.  delta = rowsum(dO * O) is a cheap fused
XLA reduction outside the kernels.  This covers the 2/3 of attention
FLOPs that the old oracle-recompute backward left to XLA's generic path
(the hot-op role of reference src/ops/MatrixMult.cu-class kernels).

On non-TPU backends the kernels run in interpret mode, so the same code
path is testable on the 8-device CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128



def _prec(dtype):
    """fp32 inputs get full-precision MXU passes (the accuracy path);
    bf16 stays on the fast path.  Without this, fp32 attention grads on
    TPU drift ~4e-3 from exact (default matmul precision is bf16)."""
    return jax.lax.Precision.HIGHEST if dtype == jnp.float32 \
        else jax.lax.Precision.DEFAULT

def _fit_block(block, length):
    """Largest divisor of ``length`` that is <= min(block, length), so any
    sequence length works (non-divisible requests shrink the block rather
    than assert)."""
    b = min(block, length)
    while length % b:
        b -= 1
    return b


def _causal_kv_index(bq, bk):
    """kv-block index map with the dead-block DMA skip: above-diagonal
    (causally dead) kv blocks map to the LAST LIVE block for the q row —
    pallas skips the DMA when a block's index repeats across grid steps,
    so the dead half of the grid moves no bytes (compute is separately
    skipped by pl.when).  At 32k this halves the kv streaming traffic."""
    def idx(b, i, j):
        return (b, jnp.minimum(j, (i * bq + bq - 1) // bk), 0)
    return idx


def _causal_q_row(bq, bk, n_q):
    """q-row mirror of _causal_kv_index for the dkv kernel: below-diagonal
    (dead) q rows map to the FIRST LIVE row, upper-clamped to n_q - 1 for
    cross-attention where kv runs longer than q (every row of such a
    column is dead, but the DMA index must stay in range)."""
    def row(b, j, i):
        return jnp.maximum(i, jnp.minimum((j * bk) // bq, n_q - 1))
    return row


def _fwd_kernel(*refs, scale, causal, masked, carried, bq, bk, n_kv):
    oc_ref = lc_ref = None
    if masked:
        (kvlen_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_ref, l_ref, acc_ref) = refs
    elif carried:
        (q_ref, k_ref, v_ref, oc_ref, lc_ref, o_ref, lse_ref,
         m_ref, l_ref, acc_ref) = refs
    else:
        (q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_ref, l_ref, acc_ref) = refs
    b = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        if carried:
            # fused merge epilogue (ring attention): seed the running
            # (m, l, acc) from the PREVIOUS rotation's normalized output
            # and lse.  Any (m, l, acc) with acc/l == o_c and
            # m + log l == lse_c continues the stream exactly; we pick
            # l = 1, m = lse_c — so the cross-rotation combine costs no
            # separate pass over the output at all.
            lse_c = lc_ref[0, 0]                       # [bq] f32
            live = lse_c > NEG_INF / 2
            m_ref[:] = jnp.broadcast_to(
                jnp.where(live, lse_c, NEG_INF)[:, None], m_ref.shape)
            l_ref[:] = jnp.broadcast_to(
                jnp.where(live, 1.0, 0.0)[:, None], l_ref.shape)
            acc_ref[:] = oc_ref[0] * jnp.where(live, 1.0, 0.0)[:, None]
        else:
            m_ref[:] = jnp.full_like(m_ref, NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        # block (qi, kj) is live unless every q position < every kv position
        run = (kj * bk) <= (qi * bq + bq - 1)
    if masked:
        # blocks entirely past this sequence's kv length are dead
        run = jnp.logical_and(run, kj * bk < kvlen_ref[b])

    @pl.when(run)
    def _compute():
        q = q_ref[0]          # [bq, D]
        k = k_ref[0]          # [bk, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            precision=_prec(q.dtype),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal or masked:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kv_pos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            live = jnp.ones((bq, bk), jnp.bool_)
            if causal:
                live = q_pos >= kv_pos
            if masked:
                live = jnp.logical_and(live, kv_pos < kvlen_ref[b])
            s = jnp.where(live, s, NEG_INF)
        m_prev = m_ref[:, 0:1]                      # [bq, 1]
        l_prev = l_ref[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - safe_m)
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(jnp.clip(m_prev - m_new, max=0.0))
        alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0, alpha)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            precision=_prec(v.dtype),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kj == n_kv - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        denom = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        # per-row logsumexp for the fused backward; +inf on fully-masked
        # rows so exp(s - lse) recomputes p = 0 there
        m = m_ref[:, 0]
        lse = jnp.where(l[:, 0] == 0.0, -NEG_INF,
                        jnp.where(m <= NEG_INF / 2, -NEG_INF,
                                  m + jnp.log(l[:, 0])))
        lse_ref[0, 0] = lse


def _flash_fwd(q, k, v, kv_lens, *, causal, block_q, block_k, interpret,
               carry=None):
    """q, k, v: [BH, S, D] (+ optional kv_lens [BH]) -> o: [BH, S, D].

    ``carry``: optional (o_carry [BH, S, D] f32, lse_carry [BH, 1, S]
    f32) — the previous partial's normalized output and lse, merged in
    the kernel prologue (ring attention).  With a carry the output o is
    f32 (it keeps accumulating across rotations)."""
    BH, S, D = q.shape
    Sk = k.shape[1]
    bq = _fit_block(block_q, S)
    bk = _fit_block(block_k, Sk)
    n_q, n_kv = S // bq, Sk // bk
    scale = D ** -0.5
    masked = kv_lens is not None
    carried = carry is not None
    assert not (masked and carried), "kv_lens + carry not combined"

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, masked=masked,
        carried=carried, bq=bq, bk=bk, n_kv=n_kv)
    lens_spec = [pl.BlockSpec(memory_space=pltpu.SMEM)] if masked else []
    lens_arg = (kv_lens,) if masked else ()
    carry_spec = [
        pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
    ] if carried else []
    carry_arg = (carry[0].astype(jnp.float32),
                 carry[1].astype(jnp.float32)) if carried else ()

    if causal:
        kv_idx = _causal_kv_index(bq, bk)
    else:
        def kv_idx(b, i, j):
            return (b, j, 0)
    return pl.pallas_call(
        kernel,
        grid=(BH, n_q, n_kv),
        in_specs=lens_spec + [
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), kv_idx),
            pl.BlockSpec((1, bk, D), kv_idx),
        ] + carry_spec,
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D),
                                 jnp.float32 if carried else q.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),   # running max
            pltpu.VMEM((bq, _LANES), jnp.float32),   # running denom
            pltpu.VMEM((bq, D), jnp.float32),        # output accumulator
        ],
        interpret=interpret,
    )(*lens_arg, q, k, v, *carry_arg)


# --------------------------------------------------------------------------- #
# fused backward (FlashAttention-2): recompute P per block from (q, k, lse)
# --------------------------------------------------------------------------- #

def _recompute_p(q, k, lse, *, scale, causal, qi, kj, bq, bk, kvlen=None):
    """[bq, bk] probabilities for one block pair, fp32."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        precision=_prec(q.dtype),
        preferred_element_type=jnp.float32) * scale
    p = jnp.exp(s - lse[:, None])
    if causal or kvlen is not None:
        q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        kv_pos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        live = jnp.ones((bq, bk), jnp.bool_)
        if causal:
            live = q_pos >= kv_pos
        if kvlen is not None:
            live = jnp.logical_and(live, kv_pos < kvlen)
        p = jnp.where(live, p, 0.0)
    return p


def _bwd_dkv_kernel(*refs, scale, causal, masked, bq, bk, n_q):
    if masked:
        (kvlen_ref, q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    b = pl.program_id(0)
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = (qi * bq + bq - 1) >= (kj * bk)
    if masked:
        run = jnp.logical_and(run, kj * bk < kvlen_ref[b])

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        k = k_ref[0]
        v = v_ref[0]
        p = _recompute_p(q, k, lse, scale=scale, causal=causal,
                         qi=qi, kj=kj, bq=bq, bk=bk,
                         kvlen=kvlen_ref[b] if masked else None)
        # dV += P^T dO
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            precision=_prec(do.dtype),
            preferred_element_type=jnp.float32)
        # dP = dO V^T ; dS = P * (dP - delta) * scale ; dK += dS^T Q
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            precision=_prec(do.dtype),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            precision=_prec(q.dtype),
            preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(*refs, scale, causal, masked, bq, bk, n_kv):
    if masked:
        (kvlen_ref, k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc) = refs
    else:
        (k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc) = refs
    b = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = (kj * bk) <= (qi * bq + bq - 1)
    if masked:
        run = jnp.logical_and(run, kj * bk < kvlen_ref[b])

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        k = k_ref[0]
        v = v_ref[0]
        p = _recompute_p(q, k, lse, scale=scale, causal=causal,
                         qi=qi, kj=kj, bq=bq, bk=bk,
                         kvlen=kvlen_ref[b] if masked else None)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            precision=_prec(do.dtype),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        # dQ += dS K
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            precision=_prec(k.dtype),
            preferred_element_type=jnp.float32)

    @pl.when(kj == n_kv - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, kv_lens, o, lse, g, *, causal, block_q, block_k,
               interpret, g_lse=None):
    """[BH, S, D] gradients via the fused kernels.

    ``g_lse``: optional cotangent of the lse output (ring attention's
    combine differentiates it); folds into delta since d lse/d s = P,
    giving dS = P*(dP - delta + g_lse)."""
    BH, S, D = q.shape
    Sk = k.shape[1]
    bq = _fit_block(block_q, S)
    bk = _fit_block(block_k, Sk)
    n_q, n_kv = S // bq, Sk // bk
    scale = D ** -0.5
    masked = kv_lens is not None
    lens_spec = [pl.BlockSpec(memory_space=pltpu.SMEM)] if masked else []
    lens_arg = (kv_lens,) if masked else ()
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                          # [BH, S]
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    delta = delta[:, None, :]                         # [BH, 1, S]

    if causal:
        q_row = _causal_q_row(bq, bk, n_q)

        def q_idx(b, j, i):
            return (b, q_row(b, j, i), 0)

        def stat_idx(b, j, i):
            return (b, 0, q_row(b, j, i))
    else:
        def q_idx(b, j, i):
            return (b, i, 0)

        def stat_idx(b, j, i):
            return (b, 0, i)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          masked=masked, bq=bq, bk=bk, n_q=n_q),
        grid=(BH, n_kv, n_q),
        in_specs=lens_spec + [
            pl.BlockSpec((1, bq, D), q_idx),                       # q
            pl.BlockSpec((1, bq, D), q_idx),                       # dO
            pl.BlockSpec((1, 1, bq), stat_idx),                    # lse
            pl.BlockSpec((1, 1, bq), stat_idx),                    # delta
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),   # k
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),   # v
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(*lens_arg, q, g, lse, delta, k, v)

    if causal:
        kv_idx_dq = _causal_kv_index(bq, bk)
    else:
        def kv_idx_dq(b, i, j):
            return (b, j, 0)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          masked=masked, bq=bq, bk=bk, n_kv=n_kv),
        grid=(BH, n_q, n_kv),
        in_specs=lens_spec + [
            pl.BlockSpec((1, bk, D), kv_idx_dq),                   # k
            pl.BlockSpec((1, bk, D), kv_idx_dq),                   # v
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),   # q
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),   # dO
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),   # lse
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),   # delta
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(*lens_arg, k, v, q, g, lse, delta)
    return dq, dk, dv


def _use_interpret():
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, masked, causal, block_q, block_k):
    # kv_lens rides inside q's tuple when masked (custom_vjp wants a
    # fixed arity of differentiable args; lens are integers, not
    # differentiable)
    q, kv_lens = q if masked else (q, None)
    o, _ = _flash_fwd(q, k, v, kv_lens, causal=causal, block_q=block_q,
                      block_k=block_k, interpret=_use_interpret())
    return o


def _flash_fwd_rule(q, k, v, masked, causal, block_q, block_k):
    q, kv_lens = q if masked else (q, None)
    o, lse = _flash_fwd(q, k, v, kv_lens, causal=causal, block_q=block_q,
                        block_k=block_k, interpret=_use_interpret())
    return o, (q, k, v, kv_lens, o, lse)


def _flash_bwd_rule(masked, causal, block_q, block_k, res, g):
    q, k, v, kv_lens, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, kv_lens, o, lse, g, causal=causal,
                            block_q=block_q, block_k=block_k,
                            interpret=_use_interpret())
    if masked:
        import numpy as np
        zeros_lens = np.zeros(kv_lens.shape, dtype=jax.dtypes.float0)
        return (dq, zeros_lens), dk, dv
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_stats(q, k, v, causal, block_q, block_k):
    """Like ``_flash`` but also returns the per-row logsumexp — the
    combination statistic ring attention needs to merge per-KV-block
    partial outputs (o_i, lse_i) across rotations."""
    o, lse = _flash_fwd(q, k, v, None, causal=causal, block_q=block_q,
                        block_k=block_k, interpret=_use_interpret())
    return o, lse[:, 0, :]


def _flash_stats_fwd_rule(q, k, v, causal, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, None, causal=causal, block_q=block_q,
                        block_k=block_k, interpret=_use_interpret())
    return (o, lse[:, 0, :]), (q, k, v, o, lse)


def _flash_stats_bwd_rule(causal, block_q, block_k, res, g):
    # With lse = m + log l an OUTPUT carrying cotangent g_lse, the FA2
    # dS formula gains a P*g_lse term: dS = P*(dP - delta + g_lse) —
    # i.e. the same kernels with delta shifted by -g_lse (d lse/d s = P).
    q, k, v, o, lse = res
    g_o, g_lse = g
    dq, dk, dv = _flash_bwd(
        q, k, v, None, o, lse, g_o, causal=causal, block_q=block_q,
        block_k=block_k, interpret=_use_interpret(), g_lse=g_lse)
    return dq, dk, dv


_flash_stats.defvjp(_flash_stats_fwd_rule, _flash_stats_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash_stats_carry(q, k, v, o_c, lse_c, causal, block_q, block_k):
    """``_flash_stats`` with the cross-block merge fused into the kernel
    prologue: (o_c, lse_c) is the previous partial (normalized output +
    lse, [BH, S, D] f32 / [BH, S] f32) and the returned (o, lse) is the
    EXACT streaming-softmax continuation — ring attention's per-rotation
    combine costs zero extra passes over the output."""
    o, lse = _flash_fwd(q, k, v, None, causal=causal, block_q=block_q,
                        block_k=block_k, interpret=_use_interpret(),
                        carry=(o_c, lse_c[:, None, :]))
    return o, lse[:, 0, :]


def _flash_stats_carry_fwd_rule(q, k, v, o_c, lse_c, causal, block_q,
                                block_k):
    o, lse = _flash_fwd(q, k, v, None, causal=causal, block_q=block_q,
                        block_k=block_k, interpret=_use_interpret(),
                        carry=(o_c, lse_c[:, None, :]))
    return (o, lse[:, 0, :]), (q, k, v, o_c, lse_c, o, lse)


def _flash_stats_carry_bwd_rule(causal, block_q, block_k, res, g):
    """dq/dk/dv run the unchanged FA2 kernels — with the carry folded
    into lse, the recomputed P = exp(s - lse_total) and delta =
    rowsum(dO*O) are already the right normalized quantities.  The carry
    behaves like one virtual key row with "value" o_c and score lse_c:

        w_c    = exp(lse_c - lse_total)
        d o_c  = w_c * dO
        d lse_c = w_c * (dO . o_c - delta + g_lse)

    (the same dS = P*(dP - delta + g_lse) shape the kernels use)."""
    q, k, v, o_c, lse_c, o, lse = res
    g_o, g_lse = g
    dq, dk, dv = _flash_bwd(
        q, k, v, None, o, lse, g_o.astype(q.dtype), causal=causal,
        block_q=block_q, block_k=block_k, interpret=_use_interpret(),
        g_lse=g_lse)
    lse_tot = lse[:, 0, :]                               # [BH, S]
    g_o32 = g_o.astype(jnp.float32)
    w_c = jnp.where(lse_c <= NEG_INF / 2, 0.0,
                    jnp.exp(lse_c - lse_tot))            # [BH, S]
    d_o_c = w_c[:, :, None] * g_o32
    delta = jnp.sum(g_o32 * o.astype(jnp.float32), axis=-1)
    dot_c = jnp.sum(g_o32 * o_c.astype(jnp.float32), axis=-1)
    g_lse32 = (jnp.zeros_like(delta) if g_lse is None
               else g_lse.astype(jnp.float32))
    d_lse_c = w_c * (dot_c - delta + g_lse32)
    return dq, dk, dv, d_o_c, d_lse_c


_flash_stats_carry.defvjp(_flash_stats_carry_fwd_rule,
                          _flash_stats_carry_bwd_rule)


def flash_attention_with_carry(q, k, v, o_carry, lse_carry, *,
                               causal=False, block_q=512, block_k=1024):
    """Flash attention on [B, S, H, D] continuing a previous partial.

    ``o_carry`` [B, S, H, D] float32 (normalized), ``lse_carry``
    [B, H, S] float32 (NEG_INF where the carry is empty).  Returns
    (o [B, S, H, D] float32, lse [B, H, S] float32) — the streaming
    combination of the carry with attention over THIS (k, v), exactly
    equal to attending over the concatenated key sets.  Differentiable
    in all five array arguments; ring attention chains it so the
    per-rotation (o, lse) merge runs inside the kernel prologue instead
    of as a separate elementwise pass."""
    B, S, H, D = q.shape

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)
    o, lse = _flash_stats_carry(
        fold(q), fold(k), fold(v), fold(o_carry),
        lse_carry.reshape(B * H, S), causal, block_q, block_k)
    o = o.reshape(B, H, S, D).transpose(0, 2, 1, 3)
    lse = lse.reshape(B, H, S)
    lse = jnp.where(lse >= -NEG_INF / 2, NEG_INF, lse)
    return o, lse


def flash_attention_with_lse(q, k, v, *, causal=False, block_q=512,
                             block_k=1024):
    """Flash attention on [B, S, H, D] returning (o, lse).

    ``o`` is [B, S, H, D]; ``lse`` is [B, H, S] float32 per-row
    logsumexp (``-1e30`` on rows with no live keys).  Differentiable in
    both outputs — the building block for ring attention's cross-block
    combine."""
    B, S, H, D = q.shape

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)
    o, lse = _flash_stats(fold(q), fold(k), fold(v), causal,
                          block_q, block_k)
    o = o.reshape(B, H, S, D).transpose(0, 2, 1, 3)
    lse = lse.reshape(B, H, S)
    # dead rows carry +1e30 from the kernel (so exp(s-lse)=0 in its own
    # backward); for cross-block combination they must read as "empty"
    lse = jnp.where(lse >= -NEG_INF / 2, NEG_INF, lse)
    return o, lse


def flash_attention(q, k, v, *, causal=False, kv_lens=None, block_q=512,
                    block_k=1024):
    """Flash attention on [B, S, H, D] (framework layout).

    Differentiable; Pallas kernels forward AND backward (interpret mode
    off-TPU).  ``kv_lens`` [B] int32 masks keys/values at positions >=
    kv_lens[b] (the BERT-style padding mask); blocks wholly past the
    length are skipped, so ragged batches also save FLOPs.  Default
    blocks are tuned on v5e: 512x1024 is 1.8-2.4x faster than the
    unfused softmax(QK^T)V chain at S=4k-8k causal and at parity for
    S=512, with O(S) instead of O(S^2) memory; 128x128 blocks
    underutilize the MXU (2-4x slower than these defaults).
    """
    B, S, H, D = q.shape
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)
    if kv_lens is not None:
        lens = jnp.repeat(kv_lens.astype(jnp.int32), H)      # [B*H]
        o = _flash((fold(q), lens), fold(k), fold(v), True, causal,
                   block_q, block_k)
    else:
        o = _flash(fold(q), fold(k), fold(v), False, causal,
                   block_q, block_k)
    return o.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def mha_reference(q, k, v, *, causal=False, kv_lens=None):
    """Exact attention oracle on [B, S, H, D] for tests."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    Sq, Sk = q.shape[1], k.shape[1]
    if causal:
        mask = jnp.tril(jnp.ones((Sq, Sk), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    if kv_lens is not None:
        live = jnp.arange(Sk)[None, :] < kv_lens[:, None]    # [B, Sk]
        s = jnp.where(live[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    if kv_lens is not None:
        # fully-padded rows: softmax over all-NEG_INF degenerates to
        # uniform; the kernel emits exactly 0 there — match it
        out = out * (kv_lens > 0)[:, None, None, None]
    return out
