"""Executor: named subgraphs compiled to jitted XLA step functions.

API-parity with the reference Executor/HetuConfig
(gpu_ops/executor.py:134,365,570): ``Executor({'train': [loss, train_op],
'validate': [...]})`` then ``run(name, feed_dict)``.

Architectural divergence (SURVEY.md §1): the reference walks a topo-sorted
op list per step, launching one CUDA kernel per op over five streams with
event-based ordering (executor.py:1005-1061) and a static memory-reuse plan
(memory_pool.py).  Here each named subgraph is traced ONCE per feed-shape
into a single XLA program: fusion replaces per-op dispatch, buffer donation
replaces the memory planner, XLA async collectives replace stream overlap.

Distribution: a `jax.sharding.Mesh` + per-leaf NamedShardings on params and
feeds replace the reference's graph-rewriting (AllReduce op splicing,
optimizer.py:145-164).  Gradient reduction is inserted by XLA from the
shardings alone.
"""

from __future__ import annotations

import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .graph.node import Op, TraceContext
from .graph.autodiff import find_topo_sort
from .graph.ops_misc import PlaceholderOp
from .graph.ops_embed import IndexedSlicesOp
from .optimizer import OptimizerOp


class _ParamView:
    """Node-keyed view over the name-keyed param dict used inside traces."""

    def __init__(self, d):
        self._d = d

    def __getitem__(self, node):
        return self._d[node.name]

    def __contains__(self, node):
        return node.name in self._d


class _ExtraOutputs(dict):
    """Node-keyed writes, name-keyed storage."""

    def __setitem__(self, node, value):
        super().__setitem__(node.name if isinstance(node, Op) else node, value)


class HetuConfig:
    """Runtime config (reference executor.py:134-211 slot list).  Most
    reference knobs exist for API parity; stream/overlap knobs are no-ops
    under XLA and documented as such."""

    def __init__(self, eval_node_list=None, train_name=None, val_name=None,
                 comm_mode=None, use_sparse_pull=True, cstable_policy=None,
                 bsp=-1, prefetch=True, enable_lazy=False, cache_bound=100,
                 log_path=None, my_eval_nodes=None, dist_strategy=None,
                 pipeline=None, overlap=True, use_preduce=False,
                 use_nccl_collectives=True, seed=0, mesh=None,
                 num_microbatches=None, dtype=jnp.float32,
                 mixed_precision=None):
        self.comm_mode = comm_mode
        self.use_sparse_pull = use_sparse_pull
        self.cstable_policy = cstable_policy
        self.bsp = bsp
        self.prefetch = prefetch
        self.enable_lazy = enable_lazy
        self.cache_bound = cache_bound
        self.log_path = log_path
        self.dist_strategy = dist_strategy
        self.pipeline = pipeline
        self.overlap = overlap
        self.use_preduce = use_preduce
        self.use_nccl_collectives = use_nccl_collectives
        self.seed = seed
        self.mesh = mesh
        self.num_microbatches = num_microbatches
        self.dtype = dtype
        # compute dtype policy: None = full precision; "bf16"/jnp.bfloat16
        # casts params+float feeds at graph entry, keeps fp32 master
        # weights in the optimizer (MXU wants bf16 matmuls)
        if mixed_precision in ("bf16", "bfloat16"):
            mixed_precision = jnp.bfloat16
        elif mixed_precision in ("fp16", "float16"):
            mixed_precision = jnp.float16
        self.mixed_precision = mixed_precision
        self.ps_comm = None


class SubExecutor:
    """One named subgraph compiled to a jitted step function, cached per
    feed-shape signature (reference SubExecutor at executor.py:570, but the
    whole compute loop collapses into XLA)."""

    def __init__(self, name, eval_nodes, executor):
        self.name = name
        self.eval_nodes = eval_nodes
        self.executor = executor
        self.topo = find_topo_sort(eval_nodes)
        self.optimizer_ops = [n for n in self.topo if isinstance(n, OptimizerOp)]
        self.training = len(self.optimizer_ops) > 0
        self.feeds = [n for n in self.topo
                      if isinstance(n, PlaceholderOp) and not n.is_variable]
        from .dataloader import DataloaderOp
        self.dataloader_ops = [n for n in self.topo
                               if isinstance(n, DataloaderOp)]
        # IndexedSlices nodes consumed only sparsely are never densified
        consumers = {}
        for n in self.topo:
            for i in n.inputs:
                consumers.setdefault(id(i), []).append(n)
        self.skip_dense = set()
        for n in self.topo:
            if isinstance(n, IndexedSlicesOp):
                cons = consumers.get(id(n), [])
                if cons and all(isinstance(c, OptimizerOp) for c in cons):
                    self.skip_dense.add(id(n))
        self._compiled = {}

    # ------------------------------------------------------------------ #

    def _trace(self, params, opt_states, step, rng, feeds):
        tc = TraceContext(params=_ParamView(params), rng=rng,
                          training=self.training, mesh=self.executor.mesh,
                          config=self.executor.config, step=step)
        tc.extra_outputs = _ExtraOutputs()
        vals = {}
        new_opt_states = dict(opt_states)
        mp = self.executor.config.mixed_precision

        def _cast_in(v):
            # graph entry: float params/feeds compute in the policy dtype;
            # masters stay fp32 in `params` (optimizer reads those)
            if mp is not None and hasattr(v, "dtype") \
                    and jnp.issubdtype(v.dtype, jnp.floating):
                return v.astype(mp)
            return v

        from .dataloader import DataloaderOp
        for node in self.topo:
            if isinstance(node, DataloaderOp):
                vals[id(node)] = _cast_in(feeds[node.name])
            elif isinstance(node, PlaceholderOp):
                if node.name in params:
                    vals[id(node)] = _cast_in(params[node.name])
                else:
                    vals[id(node)] = _cast_in(feeds[node.name])
            elif isinstance(node, OptimizerOp):
                grad_vals = []
                for i, g in enumerate(node.inputs):
                    if i in node.sparse_inputs:
                        grad_vals.append((vals[id(g.ids_node)],
                                          vals[id(g.values_node)]))
                    else:
                        grad_vals.append(vals[id(g)])
                new_opt_states[node.name] = node.apply(
                    grad_vals, tc, opt_states[node.name])
                vals[id(node)] = None
            elif id(node) in self.skip_dense:
                vals[id(node)] = None
            else:
                vals[id(node)] = node.compute(
                    [vals[id(i)] for i in node.inputs], tc)
        outputs = [vals[id(n)] for n in self.eval_nodes]
        if mp is not None:
            # report losses/metrics in fp32
            outputs = [o.astype(jnp.float32) if hasattr(o, "dtype")
                       and jnp.issubdtype(o.dtype, jnp.floating) else o
                       for o in outputs]
        new_params = dict(params)
        for k, v in tc.extra_outputs.items():
            if k in params and hasattr(v, "dtype") \
                    and v.dtype != params[k].dtype:
                # state written from a bf16 trace (e.g. BN running stats)
                # must not narrow the fp32 master copy
                v = v.astype(params[k].dtype)
            new_params[k] = v
        return new_params, new_opt_states, outputs

    def _compile(self, feed_sig):
        ex = self.executor

        def step_fn(params, opt_states, step, rng, feeds):
            new_params, new_opt, outputs = self._trace(
                params, opt_states, step, rng, feeds)
            # only optimizer steps advance the counter — eval passes must
            # not skew Adam bias correction / LR schedules
            new_step = step + 1 if self.training else step
            return new_params, new_opt, new_step, outputs

        jit_kwargs = dict(donate_argnums=(0, 1))
        if ex.mesh is not None:
            param_sh = {k: ex.param_sharding(k) for k in ex.var_values}
            feed_sh = {name: ex.feed_sharding(name, shape)
                       for name, shape, _ in feed_sig}
            rep = NamedSharding(ex.mesh, P())
            opt_sh = _opt_sharding_like(ex, ex.opt_states)
            jit_kwargs["in_shardings"] = (
                param_sh, opt_sh, rep, rep, feed_sh)
            # pin updated params/opt states to their input shardings —
            # otherwise GSPMD may pick a different output layout and the
            # next step's in_shardings check fails
            jit_kwargs["out_shardings"] = (param_sh, opt_sh, rep, None)
        return jax.jit(step_fn, **jit_kwargs)

    @property
    def batch_num(self):
        nums = [dl.get_batch_num(self.name) for dl in self.dataloader_ops]
        nums = [n for n in nums if n is not None]
        return min(nums) if nums else None

    def run(self, feed_dict, convert_to_numpy_ret_vals=False):
        ex = self.executor
        feeds = {}
        for dl in self.dataloader_ops:
            feeds[dl.name] = dl.get_arr(self.name)
        for node, value in feed_dict.items():
            name = node.name if isinstance(node, Op) else node
            feeds[name] = value
        for name in list(feeds):
            v = feeds[name]
            if isinstance(v, jax.Array) and v.dtype not in (
                    jnp.float64, jnp.int64):
                continue  # already device-resident; avoid a blocking D2H
            arr = np.asarray(v)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            if arr.dtype == np.int64:
                arr = arr.astype(np.int32)
            feeds[name] = arr
        feed_sig = tuple(sorted(
            (k, tuple(v.shape), str(v.dtype)) for k, v in feeds.items()))
        if feed_sig not in self._compiled:
            self._compiled[feed_sig] = self._compile(feed_sig)
        fn = self._compiled[feed_sig]
        if ex.mesh is not None:
            feeds = {k: ex.device_put_feed(k, v) for k, v in feeds.items()}
        ex.rng, sub = jax.random.split(ex.rng)
        ex.var_values, ex.opt_states, ex.step, outputs = fn(
            ex.var_values, ex.opt_states, ex.step, sub, feeds)
        results = []
        for n, o in zip(self.eval_nodes, outputs):
            if o is None:
                results.append(None)
            elif convert_to_numpy_ret_vals:
                results.append(np.asarray(o))
            else:
                results.append(o)
        return results


def _opt_sharding_like(ex, opt_states):
    """Optimizer slot states inherit their parameter's sharding (they are
    created with zeros_like(param)), so declare whatever each leaf
    actually has; replicated otherwise."""
    rep = NamedSharding(ex.mesh, P())
    return jax.tree_util.tree_map(
        lambda x: x.sharding if isinstance(x, jax.Array)
        and hasattr(x, "sharding") else rep, opt_states)


class Executor:
    """Multi-subgraph driver (reference executor.py:365-541)."""

    def __init__(self, eval_node_dict, config=None, **kargs):
        if isinstance(eval_node_dict, list):
            eval_node_dict = {"default": eval_node_dict}
        self.eval_node_dict = eval_node_dict
        self.config = config if config is not None else HetuConfig(**kargs)
        self.mesh = self.config.mesh
        self.rng = jax.random.PRNGKey(self.config.seed)
        self.step = jnp.zeros((), jnp.int32)

        all_nodes = find_topo_sort(
            [n for nodes in eval_node_dict.values() for n in nodes])
        # hidden state vars (e.g. batch-norm running stats)
        for node in list(all_nodes):
            for sv in getattr(node, "state_vars", []):
                all_nodes.append(sv)
        self.variables = {}
        seen_names = set()
        for n in all_nodes:
            if isinstance(n, PlaceholderOp) and n.is_variable:
                assert n.name not in seen_names, f"duplicate variable name {n.name}"
                seen_names.add(n.name)
                self.variables[n.name] = n

        # strategy hook: assigns mesh + sharding specs before init
        if self.config.dist_strategy is not None:
            self.config.dist_strategy.configure(self)
            self.mesh = self.config.mesh

        self.var_values = {name: n.init_value(self.config.seed)
                           for name, n in self.variables.items()}
        if self.mesh is not None:
            self.var_values = {
                k: jax.device_put(v, self.param_sharding(k))
                for k, v in self.var_values.items()}

        self.subexecutor = {}
        self.opt_states = {}
        for name, nodes in eval_node_dict.items():
            sub = SubExecutor(name, nodes, self)
            self.subexecutor[name] = sub
            for opt_op in sub.optimizer_ops:
                if opt_op.name not in self.opt_states:
                    self.opt_states[opt_op.name] = opt_op.init_state(
                        _ParamView(self.var_values))

    # ------------------------------------------------------------------ #
    # sharding helpers
    # ------------------------------------------------------------------ #

    def param_sharding(self, name):
        node = self.variables[name]
        spec = getattr(node, "sharding_spec", None)
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, spec if spec is not None else P())

    def feed_sharding(self, name, shape):
        """Feeds shard along the batch dim over the 'dp' axis if present."""
        if self.mesh is None:
            return None
        if "dp" in self.mesh.axis_names and len(shape) >= 1:
            return NamedSharding(self.mesh, P("dp"))
        return NamedSharding(self.mesh, P())

    def device_put_feed(self, name, value):
        return jax.device_put(value, self.feed_sharding(name, value.shape))

    # ------------------------------------------------------------------ #

    def run(self, name="default", eval_node_list=None, feed_dict=None,
            convert_to_numpy_ret_vals=False, **kwargs):
        if isinstance(name, dict) and feed_dict is None:
            # positional style: executor.run(feed_dict)
            feed_dict, name = name, "default"
        feed_dict = feed_dict or {}
        return self.subexecutor[name].run(feed_dict, convert_to_numpy_ret_vals)

    # ------------------------------------------------------------------ #
    # checkpointing (reference executor.py:461-541; strictly better — we
    # save optimizer slot state, step, and rng as well, SURVEY.md §5.4)
    # ------------------------------------------------------------------ #

    def save(self, path, file=None, varlist=None):
        os.makedirs(path, exist_ok=True)
        fname = os.path.join(path, file or "checkpoint.pkl")
        params = {k: np.asarray(v) for k, v in self.var_values.items()
                  if varlist is None or k in varlist}
        opt = jax.tree_util.tree_map(lambda x: np.asarray(x), self.opt_states)
        with open(fname, "wb") as f:
            pickle.dump({"params": params, "opt_states": opt,
                         "step": int(self.step),
                         "rng": np.asarray(self.rng)}, f)

    def load(self, path, file=None, consider_splits=False):
        fname = os.path.join(path, file or "checkpoint.pkl")
        with open(fname, "rb") as f:
            ckpt = pickle.load(f)
        self.load_dict(ckpt["params"])
        if ckpt.get("opt_states"):
            loaded = jax.tree_util.tree_map(jnp.asarray, ckpt["opt_states"])
            # OptimizerOp node names embed the global node id, which differs
            # across processes/builds; remap saved states onto the current
            # optimizer ops by their (stable) per-variable key sets.
            remapped = {}
            used = set()
            for cur_key, cur_state in self.opt_states.items():
                match = None
                for old_key, old_state in loaded.items():
                    if old_key not in used and \
                            set(old_state) == set(cur_state):
                        match = old_key
                        break
                if match is not None:
                    used.add(match)
                    remapped[cur_key] = loaded[match]
                else:
                    remapped[cur_key] = cur_state
            self.opt_states = remapped
        if "step" in ckpt:
            self.step = jnp.asarray(ckpt["step"], jnp.int32)
        if "rng" in ckpt:
            self.rng = jnp.asarray(ckpt["rng"], jnp.uint32)

    def load_dict(self, state_dict):
        for k, v in state_dict.items():
            if k in self.var_values:
                arr = jnp.asarray(v)
                if self.mesh is not None:
                    arr = jax.device_put(arr, self.param_sharding(k))
                self.var_values[k] = arr

    def load_seeds(self, seed):
        self.rng = jax.random.PRNGKey(seed)

    def return_tensor_values(self):
        return {k: np.asarray(v) for k, v in self.var_values.items()}

    def profile(self, feed_shapes=None, log_file=None, profiler="gpu"):
        from .profiler import HetuProfiler
        return HetuProfiler(self, feed_shapes, log_file)

    def recordLoads(self):
        pass

    @property
    def batch_num(self):
        # dataloader integration supplies this; see dataloader.py
        subs = list(self.subexecutor.values())
        return subs[0].batch_num if subs and hasattr(subs[0], "batch_num") else None


def gradients(output_node, node_list, insert_grad=None, return_all=False):
    from .graph.autodiff import gradients as _g
    return _g(output_node, node_list, insert_grad, return_all)
