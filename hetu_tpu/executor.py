"""Executor: named subgraphs compiled to jitted XLA step functions.

API-parity with the reference Executor/HetuConfig
(gpu_ops/executor.py:134,365,570): ``Executor({'train': [loss, train_op],
'validate': [...]})`` then ``run(name, feed_dict)``.

Architectural divergence (SURVEY.md §1): the reference walks a topo-sorted
op list per step, launching one CUDA kernel per op over five streams with
event-based ordering (executor.py:1005-1061) and a static memory-reuse plan
(memory_pool.py).  Here each named subgraph is traced ONCE per feed-shape
into a single XLA program: fusion replaces per-op dispatch, buffer donation
replaces the memory planner, XLA async collectives replace stream overlap.

Distribution: a `jax.sharding.Mesh` + per-leaf NamedShardings on params and
feeds replace the reference's graph-rewriting (AllReduce op splicing,
optimizer.py:145-164).  Gradient reduction is inserted by XLA from the
shardings alone.
"""

from __future__ import annotations

import os
import pickle
import threading

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .graph.node import Op, TraceContext
from .graph.autodiff import find_topo_sort
from .graph.ops_misc import PlaceholderOp
from .graph.ops_embed import IndexedSlicesOp
from .optimizer import OptimizerOp


class _ParamView:
    """Node-keyed view over the name-keyed param dict used inside traces."""

    def __init__(self, d):
        self._d = d

    def __getitem__(self, node):
        return self._d[node.name]

    def __contains__(self, node):
        return node.name in self._d


class _ExtraOutputs(dict):
    """Node-keyed writes, name-keyed storage."""

    def __setitem__(self, node, value):
        super().__setitem__(node.name if isinstance(node, Op) else node, value)


class HetuConfig:
    """Runtime config (reference executor.py:134-211 slot list).

    Knob semantics here:
      comm_mode      None/'AllReduce' = pure jit path (gradient reduction
                     comes from shardings); 'Hybrid' = embedding tables
                     live on the PS (with the HET cache when
                     cstable_policy is set) while dense grads stay on
                     device; 'PS' = dense params also round-trip the PS
                     with server-side optimizers.
      cstable_policy 'LRU'/'LFU'/'LFUOpt' — cache-enabled embedding path.
      cache_bound    cache capacity in rows per embedding table.
      bsp            -1 async, 0 per-step barrier, >0 SSP staleness bound
                     (multi-worker PS training).
      prefetch       overlap next batch's PS embedding lookup with the
                     current step (dataloader-fed ids only).
      async_push     opt-in: drain phase B (grad D2H + PS/cache push)
                     on a background worker; the next step's lookups
                     join it first, so read-your-writes semantics (and
                     the staleness-0 trajectory) are unchanged.  Pays
                     off only when the training loop has host work to
                     overlap (data augmentation, metrics, multi-table
                     steps); in a tight run() loop the join lands
                     immediately and the thread handoff is pure
                     overhead (measured 27->41 ms/step on the CTR
                     shape), so the default stays synchronous.
      use_sparse_pull sparse row pull vs full-table pull in PS mode.
      enable_lazy / overlap / use_nccl_collectives — no-ops by design:
                     everything is lazily traced into one jitted program,
                     XLA overlaps collectives, and collectives are always
                     XLA's (documented, accepted for API parity).
      pipeline       'gpipe'/'1f1b'/'pipedream'/'hetpipe' — training
                     subgraphs run through the pipeline partitioner +
                     microbatch schedules (pipeline_executor.py); with a
                     'pp' mesh axis and a uniform repeated body the SPMD
                     scan pipeline is used.  num_stages/num_microbatches/
                     sync_every parameterize it.
      use_preduce — raises; drive parallel.preduce.PartialReduce directly.
    """

    def __init__(self, eval_node_list=None, train_name=None, val_name=None,
                 comm_mode=None, use_sparse_pull=True, cstable_policy=None,
                 bsp=-1, prefetch=True, async_push=False, enable_lazy=False,
                 cache_bound=100,
                 log_path=None, my_eval_nodes=None, dist_strategy=None,
                 pipeline=None, overlap=True, use_preduce=False,
                 use_nccl_collectives=True, seed=0, mesh=None,
                 num_microbatches=None, num_stages=None, sync_every=None,
                 non_batch_feeds=(), dtype=jnp.float32,
                 mixed_precision=None, ps_comm=None,
                 shard_pipeline_ends=True):
        if comm_mode not in (None, "AllReduce", "PS", "Hybrid"):
            raise ValueError(f"comm_mode must be None/'AllReduce'/'PS'/"
                             f"'Hybrid', got {comm_mode!r}")
        self.comm_mode = comm_mode
        self.use_sparse_pull = use_sparse_pull
        if cstable_policy is not None and comm_mode not in ("PS", "Hybrid"):
            raise ValueError("cstable_policy requires comm_mode='PS' or "
                             "'Hybrid' (the cache fronts the PS)")
        self.cstable_policy = cstable_policy
        self.bsp = bsp
        self.prefetch = prefetch
        self.async_push = async_push
        self.enable_lazy = enable_lazy
        self.cache_bound = cache_bound
        self.log_path = log_path
        self.dist_strategy = dist_strategy
        if pipeline not in (None, "gpipe", "1f1b", "pipedream", "hetpipe"):
            raise ValueError(f"unknown pipeline mode {pipeline!r}")
        self.pipeline = pipeline
        self.num_stages = num_stages
        self.sync_every = sync_every
        # pipeline mode: feed names that are per-step constants (e.g. an
        # [S, S] attention mask), passed whole to every microbatch rather
        # than split along dim 0
        self.non_batch_feeds = tuple(non_batch_feeds)
        self.overlap = overlap
        if use_preduce:
            raise NotImplementedError(
                "use_preduce: drive parallel.preduce.PartialReduce "
                "directly (host-coordinated subgroup mean over the PS)")
        self.use_preduce = use_preduce
        self.use_nccl_collectives = use_nccl_collectives
        self.seed = seed
        self.mesh = mesh
        self.num_microbatches = num_microbatches
        self.dtype = dtype
        # compute dtype policy: None = full precision; "bf16"/jnp.bfloat16
        # casts params+float feeds at graph entry, keeps fp32 master
        # weights in the optimizer (MXU wants bf16 matmuls)
        if mixed_precision in ("bf16", "bfloat16"):
            mixed_precision = jnp.bfloat16
        elif mixed_precision in ("fp16", "float16"):
            mixed_precision = jnp.float16
        self.mixed_precision = mixed_precision
        self.ps_comm = ps_comm
        # pipeline mode: place big pre/post ("end") tensors 1/S-sharded
        # over the 'pp' axis instead of replicated per stage (see
        # Executor._shard_end_params_over_pp)
        self.shard_pipeline_ends = shard_pipeline_ends


# below this per-batch size the background device_put costs more (thread
# contention on dispatch) than the H2D it hides; measured on the v5e
# tunnel, small batches run fastest with host-only ring assembly
_RING_DEVICE_PUT_MIN_BYTES = 4 << 20


def _wire_prefetch(sub):
    """Wire this subgraph's dataloaders: multi-host batch sharding, then
    background prefetch rings (config.prefetch; reference 3-deep ring,
    dataloader.py:30-100).

    Multi-host (VERDICT r2 item 5): each process's loader is told to
    produce only the batch rows its addressable devices hold under the
    feed sharding — host RAM traffic and feed work per process stay
    constant as processes are added, instead of every process
    materializing the identical global batch (the reference's per-worker
    dp-sharded loaders, dataloader.py:22-28).

    Loaders feeding PS embedding lookups stay host-side AND unsharded —
    phase A needs the raw global ids as numpy.  Large batches
    additionally device_put (with the feed sharding) inside the ring so
    the H2D transfer leaves the critical path; small batches stay
    host-only (the put is cheaper than the thread contention it
    causes)."""
    ex = sub.executor
    ps_srcs = {id(lk.inputs[1]) for lk in getattr(sub, "ps_lookups", [])}
    for dl_op in sub.dataloader_ops:
        loaders = getattr(dl_op, "dataloaders", None)
        loader = loaders.get(sub.name) if loaders else None
        if loader is None or loader._ring is not None:
            continue
        is_ps = id(dl_op) in ps_srcs
        if not is_ps:
            loader.init_states()
            # drop_last only: a partial global tail would be
            # indistinguishable from a local shard by row count
            if ex.multiprocess and loader._shard is None \
                    and loader.drop_last:
                rows = ex.process_batch_rows(dl_op.name,
                                             tuple(loader.shape))
                if rows is not None:
                    loader.set_batch_shard(*rows)
                    # keyed by local row count: one DataloaderOp name can
                    # front loaders with different batch sizes
                    ex._proc_shard.setdefault(dl_op.name, {})[
                        rows[1] - rows[0]] = (
                        rows[0], rows[1], loader.shape[0])
        if not ex.config.prefetch:
            continue
        transform = None
        if not is_ps:
            local_rows = loader.shape[0] if loader._shard is None \
                else loader._shard[1] - loader._shard[0]
            nbytes = local_rows * int(np.prod(loader.shape[1:])) * \
                loader.data.dtype.itemsize
            if nbytes >= _RING_DEVICE_PUT_MIN_BYTES:
                def transform(arr, _n=dl_op.name):
                    arr = np.asarray(arr)
                    if arr.dtype == np.float64:
                        arr = arr.astype(np.float32)
                    if arr.dtype == np.int64:
                        arr = arr.astype(np.int32)
                    return ex.device_put_feed(_n, arr)
        loader.start_prefetch(transform=transform)


def _bucket_len(n):
    """Next power of two >= n (min 64): pads the variable unique-row
    count to a handful of shapes so the shape-keyed compile cache stays
    small while the host link still ships ~n rows."""
    b = 64
    while b < n:
        b <<= 1
    return b


def stable_rng_ids(sub):
    """node.id -> topo position: a build-invariant RNG stream index
    (two builds of the same graph give every node the same position,
    while raw ids shift with the global counter).  Cached on the
    subexecutor; shared by the plain and pipeline executors so their
    dropout/rand streams follow one contract."""
    ids = getattr(sub, "_rng_ids", None)
    if ids is None:
        ids = sub._rng_ids = {n.id: i for i, n in enumerate(sub.topo)}
    return ids


def gather_feeds(sub, feed_dict, peek=False):
    """Collect dataloader + fed values into a name-keyed dict, coercing
    dtypes host-side.  Device-resident jax.Arrays pass through untouched
    (np.asarray on them would force a blocking D2H).  ``peek`` reads the
    dataloaders WITHOUT consuming a batch — analysis paths (profiler
    lower/compile) must not advance the training data position."""
    if not getattr(sub, "_prefetch_wired", False):
        sub._prefetch_wired = True
        _wire_prefetch(sub)
    feeds = {}
    for dl in sub.dataloader_ops:
        feeds[dl.name] = dl.peek_arr(sub.name) if peek \
            else dl.get_arr(sub.name)
    for node, value in feed_dict.items():
        name = node.name if isinstance(node, Op) else node
        feeds[name] = value
    for name in list(feeds):
        v = feeds[name]
        if isinstance(v, jax.Array) and v.dtype not in (
                jnp.float64, jnp.int64):
            continue
        arr = np.asarray(v)
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        if arr.dtype == np.int64:
            arr = arr.astype(np.int32)
        feeds[name] = arr
    return feeds


class SubExecutor:
    """One named subgraph compiled to a jitted step function, cached per
    feed-shape signature (reference SubExecutor at executor.py:570, but the
    whole compute loop collapses into XLA)."""

    def __init__(self, name, eval_nodes, executor):
        self.name = name
        self.eval_nodes = eval_nodes
        self.executor = executor
        self.topo = find_topo_sort(eval_nodes)
        self.optimizer_ops = [n for n in self.topo if isinstance(n, OptimizerOp)]
        self.training = len(self.optimizer_ops) > 0
        self.feeds = [n for n in self.topo
                      if isinstance(n, PlaceholderOp) and not n.is_variable]
        from .dataloader import DataloaderOp
        self.dataloader_ops = [n for n in self.topo
                               if isinstance(n, DataloaderOp)]
        # IndexedSlices nodes consumed only sparsely are never densified
        consumers = {}
        for n in self.topo:
            for i in n.inputs:
                consumers.setdefault(id(i), []).append(n)
        self.skip_dense = set()
        for n in self.topo:
            if isinstance(n, IndexedSlicesOp):
                cons = consumers.get(id(n), [])
                if cons and all(isinstance(c, OptimizerOp) for c in cons):
                    self.skip_dense.add(id(n))
        # PS-managed embedding lookups: their rows are gathered host-side
        # (from the PS / HET cache) before the jitted step and fed in; the
        # table itself never materializes on device
        from .graph.ops_embed import EmbeddingLookupOp
        self.ps_lookups = []     # EmbeddingLookupOp nodes on PS tables
        self.ps_var_names = frozenset(executor.ps_sparse_vars) \
            | frozenset(executor.ps_dense_vars)
        if executor.ps_sparse_vars:
            for n in self.topo:
                if isinstance(n, EmbeddingLookupOp) and \
                        n.inputs[0].name in executor.ps_sparse_vars:
                    src = n.inputs[1]
                    from .dataloader import DataloaderOp
                    if not (isinstance(src, DataloaderOp) or
                            (isinstance(src, PlaceholderOp)
                             and not src.is_variable)):
                        raise NotImplementedError(
                            f"PS embedding lookup ids must come straight "
                            f"from a feed or dataloader (got "
                            f"{type(src).__name__} feeding {n.name}); the "
                            f"host gather needs concrete ids pre-step")
                    self.ps_lookups.append(n)
        self._ps_lookup_ids = set(id(n) for n in self.ps_lookups)
        self._prefetched = {}    # lookup node name -> (ids, Future)
        self._compiled = {}
        # async phase B: one worker drains the grad D2H + PS/cache push
        # off the critical path (reference overlaps push with the next
        # batch via CSEvent streams, stream.py:90-105); ordering with
        # the next lookup is enforced by _join_phase_b
        self._phase_b_pool = None
        if self.training and self.ps_var_names \
                and executor.config.async_push:
            from concurrent.futures import ThreadPoolExecutor
            self._phase_b_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"psb-{name}")

    # ------------------------------------------------------------------ #

    def _stable_rng_ids(self):
        return stable_rng_ids(self)

    def _trace(self, params, opt_states, step, rng, feeds):
        tc = TraceContext(params=_ParamView(params), rng=rng,
                          training=self.training, mesh=self.executor.mesh,
                          config=self.executor.config, step=step)
        tc.rng_ids = self._stable_rng_ids()
        tc.extra_outputs = _ExtraOutputs()
        vals = {}
        new_opt_states = dict(opt_states)
        side_outputs = {}
        mp = self.executor.config.mixed_precision

        def _cast_in(v):
            # graph entry: float params/feeds compute in the policy dtype;
            # masters stay fp32 in `params` (optimizer reads those)
            if mp is not None and hasattr(v, "dtype") \
                    and jnp.issubdtype(v.dtype, jnp.floating):
                return v.astype(mp)
            return v

        from .dataloader import DataloaderOp
        for node in self.topo:
            if id(node) in self._ps_lookup_ids:
                # PS-managed embedding: UNIQUE rows pre-gathered
                # host-side; the in-trace gather re-expands them
                # (device-side dedup — the host link carries U unique
                # rows, not B*T positions; reference dedups on GPU via
                # IndexedSlices, src/ops/IndexedSlices.cu).  Unique rows
                # are keyed per TABLE (several lookups share one fetch);
                # the expansion map is per lookup.
                uniq = _cast_in(feeds["__psuniq__" + node.inputs[0].name])
                inv = feeds["__psinv__" + node.name]
                vals[id(node)] = jnp.take(uniq, inv, axis=0)
            elif isinstance(node, DataloaderOp):
                vals[id(node)] = _cast_in(feeds[node.name])
            elif isinstance(node, PlaceholderOp):
                if node.name in self.executor.ps_sparse_vars:
                    vals[id(node)] = None  # table lives on the PS
                elif node.name in params:
                    vals[id(node)] = _cast_in(params[node.name])
                else:
                    vals[id(node)] = _cast_in(feeds[node.name])
            elif isinstance(node, OptimizerOp):
                grad_vals = []
                for i, g in enumerate(node.inputs):
                    if i in node.sparse_inputs:
                        grad_vals.append((vals[id(g.ids_node)],
                                          vals[id(g.values_node)]))
                    else:
                        grad_vals.append(vals[id(g)])
                new_opt_states[node.name] = node.apply(
                    grad_vals, tc, opt_states[node.name],
                    ps_vars=self.ps_var_names, side_outputs=side_outputs)
                vals[id(node)] = None
            elif id(node) in self.skip_dense:
                vals[id(node)] = None
            else:
                vals[id(node)] = node.compute(
                    [vals[id(i)] for i in node.inputs], tc)
        # dedup the embedding grads on DEVICE: segment-sum per-position
        # rows into the unique-row slots so phase B ships U rows back,
        # mirroring the forward's unique-row feed.  The adjoint carries
        # vocab ids (possibly concatenated across several lookups into
        # the table); searchsorted against the sorted unique-id feed maps
        # them to slots.
        for var in {lk.inputs[0].name for lk in self.ps_lookups}:
            if var in side_outputs and var in self.executor.ps_sparse_vars:
                ids, rows = side_outputs[var]
                uniq_ids = feeds["__psuniqids__" + var]
                slot = jnp.searchsorted(uniq_ids,
                                        ids.astype(uniq_ids.dtype))
                g_uniq = jnp.zeros(
                    (uniq_ids.shape[0], rows.shape[-1]),
                    rows.dtype).at[slot].add(rows)
                if mp is not None:
                    # grads were computed in the policy dtype; shipping
                    # them D2H at that width halves the host-link bytes
                    # (the PS applies the update in fp32 regardless)
                    g_uniq = g_uniq.astype(mp)
                side_outputs[var] = g_uniq
        outputs = [vals[id(n)] for n in self.eval_nodes]
        if mp is not None:
            # report losses/metrics in fp32
            outputs = [o.astype(jnp.float32) if hasattr(o, "dtype")
                       and jnp.issubdtype(o.dtype, jnp.floating) else o
                       for o in outputs]
        new_params = dict(params)
        for k, v in tc.extra_outputs.items():
            if k in params and hasattr(v, "dtype") \
                    and v.dtype != params[k].dtype:
                # state written from a bf16 trace (e.g. BN running stats)
                # must not narrow the fp32 master copy
                v = v.astype(params[k].dtype)
            new_params[k] = v
        return new_params, new_opt_states, outputs, side_outputs

    def _compile(self, feed_sig):
        ex = self.executor

        def step_fn(params, opt_states, step, rng, feeds):
            # rng splits INSIDE the jitted program (an eager per-step
            # split is a full host<->device round trip on a tunneled TPU)
            new_rng, sub = jax.random.split(rng)
            new_params, new_opt, outputs, side = self._trace(
                params, opt_states, step, sub, feeds)
            # only optimizer steps advance the counter — eval passes must
            # not skew Adam bias correction / LR schedules
            new_step = step + 1 if self.training else step
            return new_params, new_opt, new_step, new_rng, outputs, side

        jit_kwargs = dict(donate_argnums=(0, 1))
        if ex.mesh is not None:
            param_sh = {k: ex.param_sharding(k) for k in ex.var_values}
            feed_sh = {name: ex.feed_sharding(name, shape)
                       for name, shape, _ in feed_sig}
            rep = NamedSharding(ex.mesh, P())
            opt_sh = _opt_sharding_like(ex, ex.opt_states)
            jit_kwargs["in_shardings"] = (
                param_sh, opt_sh, rep, rep, feed_sh)
            # pin updated params/opt states to their input shardings —
            # otherwise GSPMD may pick a different output layout and the
            # next step's in_shardings check fails
            jit_kwargs["out_shardings"] = (param_sh, opt_sh, rep, rep,
                                           None, None)
        return jax.jit(step_fn, **jit_kwargs)

    @property
    def batch_num(self):
        nums = [dl.get_batch_num(self.name) for dl in self.dataloader_ops]
        nums = [n for n in nums if n is not None]
        return min(nums) if nums else None

    def run(self, feed_dict, convert_to_numpy_ret_vals=False):
        from . import telemetry
        ex = self.executor
        feeds = gather_feeds(self, feed_dict)
        # read-your-writes: the previous step's async push must land in
        # the cache/PS before this step's lookups
        ex.join_ps_push()
        with telemetry.span("exec.phase_a", subgraph=self.name):
            ps_ids = self._ps_phase_a(feeds)
        feed_sig = tuple(sorted(
            (k, tuple(v.shape), str(v.dtype)) for k, v in feeds.items()))
        compiled_now = feed_sig not in self._compiled
        if compiled_now:
            # pre-trace validation with the concrete feed shapes: a
            # miswired graph fails HERE with the node named, not as an
            # XLA stack dump out of the compile below (HETU_VALIDATE=1)
            telemetry.inc("exec.compile_cache_miss")
            with telemetry.span("exec.compile", subgraph=self.name):
                from .analysis import validate_subgraph_feeds
                validate_subgraph_feeds(ex, self, feeds)
                self._compiled[feed_sig] = self._compile(feed_sig)
        fn = self._compiled[feed_sig]
        if ex.mesh is not None:
            feeds = {k: ex.device_put_feed(k, v) for k, v in feeds.items()}
        # dispatch covers trace+compile on a cache-miss step (jax.jit is
        # lazy — the first call lowers); `compiled` marks those spans so
        # the trace attributes the fat step correctly
        with telemetry.span("exec.dispatch", subgraph=self.name,
                            compiled=compiled_now):
            ex.var_values, ex.opt_states, ex.step, ex.rng, outputs, side \
                = fn(ex.var_values, ex.opt_states, ex.step, ex.rng, feeds)
        telemetry.inc("exec.steps")
        if self.ps_var_names and self.training:
            if self._phase_b_pool is not None:
                # the worker blocks on the grads' D2H, pushes, THEN
                # prefetches (so the prefetched rows see the update);
                # the main thread returns to the training loop
                def _push():
                    with telemetry.span("exec.phase_b",
                                        subgraph=self.name, mode="async"):
                        self._ps_phase_b(side, ps_ids)
                    self._ps_prefetch()
                ex._ps_push_future = self._phase_b_pool.submit(_push)
            else:
                with telemetry.span("exec.phase_b", subgraph=self.name,
                                    mode="sync"):
                    self._ps_phase_b(side, ps_ids)
                self._ps_prefetch()
        else:
            self._ps_prefetch()
        results = []
        for n, o in zip(self.eval_nodes, outputs):
            if o is None:
                results.append(None)
            elif convert_to_numpy_ret_vals:
                results.append(np.asarray(o))
            else:
                results.append(o)
        return results

    # ------------------------------------------------------------------ #
    # Hybrid/PS host phases (reference ParameterServerCommunicate.py:38-57
    # push-pull compute, :193-204 prefetch; executor.py:253-258 cache
    # wiring).  Phase A gathers embedding rows for the batch from the PS /
    # HET cache; phase B pushes the step's grads back; prefetch overlaps
    # the NEXT batch's lookup with everything after dispatch.
    # ------------------------------------------------------------------ #

    def _ps_phase_a(self, feeds):
        """Gather UNIQUE rows for every PS-managed lookup; returns
        {var: unique ids}.  The host link (PCIe in the reference, the
        tunnel here) carries U unique rows, padded to power-of-two
        buckets so the jitted step compiles a handful of shapes, not one
        per batch; the in-trace gather re-expands to B*T positions."""
        ex = self.executor
        ps_ids = {}
        by_var = {}
        for lk in self.ps_lookups:
            by_var.setdefault(lk.inputs[0].name, []).append(lk)
        for var_name, lks in by_var.items():
            id_arrays = [np.asarray(feeds[lk.inputs[1].name])
                         for lk in lks]
            all_flat = np.concatenate(
                [a.reshape(-1).astype(np.int64) for a in id_arrays])
            pre = self._prefetched.pop(var_name, None)
            if pre is not None and np.array_equal(pre[0], all_flat):
                _, uniq, fut = pre
                rows = fut.result()
            else:
                uniq = np.unique(all_flat)
                rows = ex.ps_lookup(var_name, uniq)
            rows = np.asarray(rows, np.float32).reshape(len(uniq), -1)
            mp = ex.config.mixed_precision
            if mp is not None:
                # the trace casts float feeds to the policy dtype anyway;
                # casting host-side halves the H2D bytes for the rows
                rows = rows.astype(mp)
            upad = _bucket_len(len(uniq))
            if upad > len(uniq):
                rows = np.concatenate(
                    [rows, np.zeros((upad - len(uniq), rows.shape[1]),
                                    rows.dtype)])
            # sorted unique ids, padded with a +inf-like sentinel so the
            # device searchsorted stays within a sorted array.  int32:
            # jax (x64 off) would silently demote an int64 feed and
            # overflow the sentinel into the middle of the "sorted" array
            uniq_pad = np.full(upad, np.iinfo(np.int32).max, np.int32)
            uniq_pad[:len(uniq)] = uniq
            feeds["__psuniq__" + var_name] = rows
            feeds["__psuniqids__" + var_name] = uniq_pad
            for lk, ids in zip(lks, id_arrays):
                inv = np.searchsorted(uniq, ids.reshape(-1))
                feeds["__psinv__" + lk.name] = \
                    inv.reshape(ids.shape).astype(np.int32)
            ps_ids[var_name] = uniq
        # dense-PS params ('PS' mode): refresh from the server so other
        # workers' pushes are visible (BSP/SSP pacing via config.bsp)
        for name in ex.ps_dense_vars:
            if ex.ps_dense_dirty.pop(name, False):
                val = ex.ps_comm.pull(name)
                if ex.mesh is not None:
                    arr = ex.place_value(np.asarray(val),
                                         ex.param_sharding(name))
                else:
                    arr = jnp.asarray(val)
                ex.var_values[name] = arr
        return ps_ids

    def _ps_phase_b(self, side, ps_ids):
        """Push grads: sparse rows -> cache/PS, dense grads -> PS.
        Sparse rows arrive already segment-summed into unique-row slots
        (device-side dedup), so the push is duplicate-free."""
        ex = self.executor
        for var_name, g in side.items():
            g = np.asarray(g, np.float32)
            if var_name in ex.ps_sparse_vars:
                uniq = ps_ids[var_name]
                ex.ps_update(var_name, uniq, g[:len(uniq)])
            else:
                ex._ps_push_guarded("dense", var_name, None, g)
                ex.ps_dense_dirty[var_name] = True
        ex.ps_step_sync()

    def _ps_prefetch(self):
        """Overlap the next batch's embedding lookup (dataloader ids only:
        the next feed is peekable without advancing the loader)."""
        ex = self.executor
        if not ex.config.prefetch or not self.ps_lookups:
            return
        from .dataloader import DataloaderOp
        by_var = {}
        for lk in self.ps_lookups:
            by_var.setdefault(lk.inputs[0].name, []).append(lk)
        for var_name, lks in by_var.items():
            srcs = [lk.inputs[1] for lk in lks]
            if not all(isinstance(s, DataloaderOp) for s in srcs):
                continue
            try:
                id_arrays = [np.asarray(s.peek_arr(self.name))
                             for s in srcs]
            except Exception:
                continue
            all_flat = np.concatenate(
                [a.reshape(-1).astype(np.int64) for a in id_arrays])
            uniq = np.unique(all_flat)
            fut = ex.ps_lookup_async(var_name, uniq)
            if fut is not None:
                self._prefetched[var_name] = (all_flat, uniq, fut)


def _opt_sharding_like(ex, opt_states):
    """Optimizer slot states inherit their parameter's sharding (they are
    created with zeros_like(param)), so declare whatever each leaf
    actually has; replicated otherwise."""
    rep = NamedSharding(ex.mesh, P())
    return jax.tree_util.tree_map(
        lambda x: x.sharding if isinstance(x, jax.Array)
        and hasattr(x, "sharding") else rep, opt_states)


class Executor:
    """Multi-subgraph driver (reference executor.py:365-541)."""

    def __init__(self, eval_node_dict, config=None, **kargs):
        if isinstance(eval_node_dict, list):
            eval_node_dict = {"default": eval_node_dict}
        self.eval_node_dict = eval_node_dict
        self.config = config if config is not None else HetuConfig(**kargs)
        self.mesh = self.config.mesh
        self.rng = jax.random.PRNGKey(self.config.seed)
        self.step = jnp.zeros((), jnp.int32)

        all_nodes = find_topo_sort(
            [n for nodes in eval_node_dict.values() for n in nodes])
        # hidden state vars (e.g. batch-norm running stats)
        for node in list(all_nodes):
            for sv in getattr(node, "state_vars", []):
                all_nodes.append(sv)
        self.variables = {}
        seen_names = set()
        for n in all_nodes:
            if isinstance(n, PlaceholderOp) and n.is_variable:
                assert n.name not in seen_names, f"duplicate variable name {n.name}"
                seen_names.add(n.name)
                self.variables[n.name] = n

        # strategy hook: assigns mesh + sharding specs before init
        if self.config.dist_strategy is not None:
            self.config.dist_strategy.configure(self)
            self.mesh = self.config.mesh

        # pipeline the ends (VERDICT r2 item 3): big embedding/head
        # tensors get 'pp'-sharded BEFORE placement so neither their
        # storage nor their optimizer slots are replicated per stage
        if (self.mesh is not None and "pp" in self.mesh.axis_names
                and self.config.pipeline in ("gpipe", "1f1b")
                and self.config.shard_pipeline_ends):
            self._shard_end_params_over_pp(eval_node_dict)

        # Hybrid/PS comm modes: embedding tables move to the PS (with the
        # HET cache when cstable_policy is set); in 'PS' mode dense params
        # are server-optimized too.  Must run before device init so the
        # big tables never materialize in HBM.
        self.ps_comm = None
        self.ps_sparse_vars = {}
        self.ps_dense_vars = {}
        self.ps_dense_dirty = {}
        self.cstables = {}
        self.ps_var_opt = {}
        self._ps_opt_specs = {}
        self._ssp_inited = False
        self._ps_push_future = None   # pending async phase B (one step)
        # outage handling for the direct (cache-less) hybrid path:
        # pushes that cannot reach the PS buffer here and replay on the
        # next successful contact, bounded by HETU_PS_BACKLOG_STEPS
        self._ps_push_backlog = []
        if self.config.comm_mode in ("PS", "Hybrid"):
            self._setup_ps(all_nodes)

        self.var_values = {name: n.init_value(self.config.seed)
                           for name, n in self.variables.items()
                           if name not in self.ps_sparse_vars}
        if self.mesh is not None:
            self.var_values = {
                k: self.place_value(v, self.param_sharding(k))
                for k, v in self.var_values.items()}

        # feed name -> (lo, hi, global_batch): dataloader feeds this
        # process produces only the local rows of (multi-host sharding)
        self._proc_shard = {}
        self.subexecutor = {}
        self.opt_states = {}
        self._opt_ops = {}
        for name, nodes in eval_node_dict.items():
            has_opt = any(isinstance(n, OptimizerOp) for n in nodes)
            if self.config.pipeline is not None and has_opt:
                from .pipeline_executor import PipelineSubExecutor
                sub = PipelineSubExecutor(name, nodes, self)
            else:
                sub = SubExecutor(name, nodes, self)
            self.subexecutor[name] = sub
            for opt_op in sub.optimizer_ops:
                prev = self._opt_ops.get(opt_op.name)
                if prev is not None and prev is not opt_op:
                    raise ValueError(
                        f"two distinct optimizers cover the same variable "
                        f"set (stable name {opt_op.name!r}); their slot "
                        f"states would collide — give them disjoint "
                        f"var_lists")
                self._opt_ops[opt_op.name] = opt_op
                if opt_op.name not in self.opt_states:
                    self.opt_states[opt_op.name] = opt_op.init_state(
                        _ParamView(self.var_values),
                        skip=sub.ps_var_names)

        # static checks (HETU_VALIDATE=1): verify every subgraph's
        # shapes/dtypes and the mesh/plan BEFORE any trace or chip work;
        # a defect raises GraphVerifyError/ShardCheckError naming the
        # node (analysis/integration.py; no-op when validation is off)
        from .analysis import validate_executor_build
        validate_executor_build(self)

    # ------------------------------------------------------------------ #
    # Hybrid/PS setup + host-side embedding API
    # (reference executor.py:253-258 cache wiring, optimizer.py:145-164
    # comm-mode routing, ParameterServerCommunicate.py push-pull)
    # ------------------------------------------------------------------ #

    def _setup_ps(self, all_nodes):
        from .ps.client import PSClient
        from .graph.ops_embed import EmbeddingLookupOp, IndexedSlicesOp
        from .optimizer import SGDOptimizer

        cfg = self.config
        self.ps_comm = cfg.ps_comm or PSClient.get()
        cfg.ps_comm = self.ps_comm

        consumers = {}
        for n in all_nodes:
            for i in n.inputs:
                consumers.setdefault(id(i), []).append(n)
        for op in all_nodes:
            if isinstance(op, OptimizerOp):
                for v in op.var_list:
                    self.ps_var_opt[v.name] = op.optimizer

        for name, node in self.variables.items():
            if not node.trainable:
                continue
            cons = consumers.get(id(node), [])
            # a table can live on the PS iff its device value is only ever
            # needed row-wise: lookups and sparse adjoints.  ANY number of
            # lookups composes — autodiff keeps multi-lookup adjoints
            # sparse (merge_indexed_slices concat) and phase A fetches the
            # union of their ids once per table.
            n_lookups = sum(1 for c in cons
                            if isinstance(c, EmbeddingLookupOp)
                            and c.inputs[0] is node)
            sparse_ok = getattr(node, "is_embed", False) and \
                n_lookups >= 1 and all(
                (isinstance(c, (EmbeddingLookupOp, IndexedSlicesOp))
                 and c.inputs[0] is node) or isinstance(c, OptimizerOp)
                for c in cons)
            if sparse_ok:
                self.ps_sparse_vars[name] = node
            elif cfg.comm_mode == "PS":
                self.ps_dense_vars[name] = node

        def _spec_for(name, opt):
            if opt is None:
                return None
            if getattr(opt, "l2reg", 0.0):
                raise NotImplementedError(
                    f"l2reg on PS-managed var '{name}': the server applies "
                    f"the update and has no l2 term")
            spec = opt.server_opt_spec()
            if spec is None:
                raise NotImplementedError(
                    f"{type(opt).__name__} (or an LR schedule) has no PS "
                    f"server-side counterpart for var '{name}'; use the "
                    f"cache path (cstable_policy) or SGD/Momentum/"
                    f"AdaGrad/Adam with a scalar LR")
            return spec

        for name, node in self.ps_sparse_vars.items():
            val = np.asarray(node.init_value(cfg.seed), np.float32)
            opt = self.ps_var_opt.get(name)
            if cfg.cstable_policy:
                # HET cache: the worker applies SGD scaling locally and the
                # server raw-accumulates the pushed deltas (hetu_cache
                # write-back semantics) — other optimizers would need their
                # slot state inside every cache line.  LR SCHEDULES are
                # fine: each push scales by the pushing step's lr_value
                # (ps_update reads the step index), so scheduled-SGD
                # deltas accumulate exactly like the dense path.
                if opt is not None and (type(opt) is not SGDOptimizer
                                        or opt.l2reg):
                    raise NotImplementedError(
                        "the HET cache path accumulates -lr*grad deltas; "
                        "only SGD (fixed or scheduled LR, no l2) is "
                        "supported on cached embeddings (reference "
                        "hetu_cache ditto)")
                # the HET cache's versioned sync protocol needs the whole
                # table on ONE server; with a sharded client the table
                # lives whole on its home server of the group
                cache_comm = self.ps_comm._home(name) \
                    if hasattr(self.ps_comm, "_home") else self.ps_comm
                cache_comm.param_set(name, val)
                self._ps_opt_specs[name] = None
                from .cache.cstable import CacheSparseTable
                self.cstables[name] = CacheSparseTable(
                    cfg.cache_bound, val.shape[0], val.shape[1], key=name,
                    comm=cache_comm, policy=cfg.cstable_policy)
            else:
                spec = _spec_for(name, opt)
                self._ps_opt_specs[name] = spec
                self.ps_comm.param_set(
                    name, val, opt=spec and spec[0],
                    opt_args=spec and spec[1])

        for name, node in self.ps_dense_vars.items():
            val = np.asarray(node.init_value(cfg.seed), np.float32)
            spec = _spec_for(name, self.ps_var_opt.get(name))
            self._ps_opt_specs[name] = spec
            self.ps_comm.param_set(name, val, opt=spec and spec[0],
                                   opt_args=spec and spec[1])

    def ps_lookup(self, name, ids):
        """Rows for `ids` from the HET cache or the PS (phase A)."""
        ids = np.asarray(ids)
        ct = self.cstables.get(name)
        if ct is not None:
            return ct.embedding_lookup(ids)
        if self._ps_push_backlog:
            # recovery-ordering: buffered pushes must land before the
            # next read observes the table (replay failure just means
            # the PS is still down — the read below reports that)
            try:
                self._ps_replay_backlog()
            except ConnectionError:
                pass
        if self.config.use_sparse_pull:
            flat = ids.reshape(-1).astype(np.int64)
            uniq, inv = np.unique(flat, return_inverse=True)
            rows = np.asarray(self.ps_comm.sparse_pull(name, uniq),
                              np.float32)
            return rows[inv].reshape(*ids.shape, rows.shape[-1])
        table = np.asarray(self.ps_comm.pull(name), np.float32)
        return table[ids.reshape(-1)].reshape(*ids.shape, table.shape[-1])

    def ps_lookup_async(self, name, ids):
        ct = self.cstables.get(name)
        if ct is not None:
            return ct.embedding_lookup_async(ids)
        pool = getattr(self.ps_comm, "_pool", None)
        if pool is None:
            return None
        return pool.submit(self.ps_lookup, name, ids)

    def ps_update(self, name, ids, rows):
        """Push one step's embedding grads (phase B).  Cache path: the
        worker scales to -lr*grad deltas (write-back accumulate); direct
        path: raw grads, the server optimizer applies the update."""
        rows = np.asarray(rows, np.float32)
        rows = rows.reshape(-1, rows.shape[-1])
        flat = np.asarray(ids).reshape(-1).astype(np.int64)
        ct = self.cstables.get(name)
        if ct is not None:
            opt = self.ps_var_opt[name]
            # the device step already advanced self.step; the update being
            # pushed used the pre-increment step's LR
            lr = float(np.asarray(opt.lr_value(
                jnp.asarray(max(int(self.step) - 1, 0), jnp.int32))))
            # phase B hands us the device-side segment-summed UNIQUE rows
            # (_ps_phase_b passes phase A's sorted-unique ids) — skip the
            # cache's host re-dedup pass
            ct.embedding_update(flat, -lr * rows, assume_unique=True)
        else:
            self._ps_push_guarded("sparse", name, flat, rows)

    def _ps_replay_backlog(self):
        """Drain pushes buffered during a PS outage (FIFO)."""
        while self._ps_push_backlog:
            kind, name, ids, rows = self._ps_push_backlog[0]
            if kind == "sparse":
                self.ps_comm.sparse_push(name, ids, rows)
            else:
                self.ps_comm.push(name, rows)
            self._ps_push_backlog.pop(0)

    def _ps_push_guarded(self, kind, name, ids, rows):
        """Direct-path push with outage buffering: a PS that cannot be
        reached costs a bounded backlog entry, not the training run.
        The (client_id, seq) wire dedup makes the eventual replay safe
        against the retries that preceded the buffering."""
        from .ps.client import PSConnectionError
        try:
            self._ps_replay_backlog()
            if kind == "sparse":
                self.ps_comm.sparse_push(name, ids, rows)
            else:
                self.ps_comm.push(name, rows)
        except ConnectionError as e:
            from .envvars import get_int
            limit = get_int("HETU_PS_BACKLOG_STEPS")
            self._ps_push_backlog.append((kind, name, ids, rows))
            if len(self._ps_push_backlog) > limit:
                raise PSConnectionError(
                    f"PS outage: push backlog exceeded "
                    f"HETU_PS_BACKLOG_STEPS={limit} buffered steps "
                    f"(last failure: {e})") from e

    def ps_step_sync(self):
        """BSP/SSP pacing after each training step (config.bsp)."""
        bsp = self.config.bsp
        if self.ps_comm is None or bsp is None or bsp < 0:
            return
        if bsp == 0:
            self.ps_comm.BarrierWorker()
        else:
            if not self._ssp_inited:
                self.ps_comm.ssp_init(0, bsp)
                self._ssp_inited = True
            self.ps_comm.ssp_sync(0)

    def join_ps_push(self):
        """Wait for (and surface errors from) the pending async phase-B
        push.  Called before any PS/cache read and before flush/save."""
        fut = self._ps_push_future
        if fut is not None:
            self._ps_push_future = None
            fut.result()

    def ps_perf_summary(self):
        """Cache counters per table (reference cstable perf counters)."""
        self.join_ps_push()
        return {name: ct.perf_summary() for name, ct in self.cstables.items()}

    # ------------------------------------------------------------------ #
    # sharding helpers
    # ------------------------------------------------------------------ #

    @property
    def multiprocess(self):
        """True when the mesh spans jax processes (multi-host SPMD over
        DCN/ICI via jax.distributed; reference's multi-node NCCL/MPI
        role, SURVEY §5.8).  Every process must build the identical graph
        and run the identical steps.  Cached: the mesh is fixed at
        construction and this sits on the per-feed hot path."""
        mpv = getattr(self, "_multiprocess", None)
        if mpv is None:
            if self.mesh is None:
                mpv = False
            else:
                pid = jax.process_index()
                mpv = any(d.process_index != pid
                          for d in self.mesh.devices.flat)
            self._multiprocess = mpv
        return mpv

    def place_value(self, value, sharding):
        """Place a host (or replicated-device) value with `sharding`.
        Single-process: plain device_put.  Multi-process: device_put of a
        cross-process sharding is illegal, so each process supplies its
        addressable shards from the (identical) host value.  Values that
        already carry the target sharding (e.g. ring-prefetched feeds)
        pass through untouched."""
        if sharding is None:
            return jnp.asarray(value)
        if isinstance(value, jax.Array) and \
                value.sharding.is_equivalent_to(sharding, value.ndim):
            return value
        if not self.multiprocess:
            return jax.device_put(value, sharding)
        value = np.asarray(value)
        return jax.make_array_from_callback(
            value.shape, sharding, lambda idx: value[idx])

    def _shard_end_params_over_pp(self, eval_node_dict):
        """Pipeline the non-uniform ends, the TPU way (reference:
        pipeline_subexecutor.py:29-81 folds embedding into stage 0 and
        head+loss into the last stage so each lives on one stage's
        devices).

        A scan pipeline wants uniform stages, and on TPU the same memory
        goal has a more direct expression: every big pre/post ("end")
        tensor is SHARDED over the otherwise-idle 'pp' mesh axis, so each
        stage holds 1/S of the embedding and head (and of their optimizer
        slots) instead of a full replica — the same total footprint as
        the reference's one-stage residency, better balanced, and it
        needs no schedule surgery for tied embedding/LM-head weights
        (both use sites read the same sharded array; GSPMD inserts the
        batched collectives and sums the grads).  Runs before parameter
        placement; fills only specs the user left unset."""
        from .parallel.partition import partition
        S = self.mesh.shape["pp"]
        min_elems = 1 << 18          # don't bother with biases/LN params
        for name, nodes in eval_node_dict.items():
            if not any(isinstance(n, OptimizerOp) for n in nodes):
                continue
            losses = [n for n in nodes if not isinstance(n, OptimizerOp)]
            if len(losses) != 1:
                continue
            topo = find_topo_sort(losses)
            if any(getattr(n, "state_vars", []) for n in topo):
                continue          # such graphs take the microbatch-scan path
            plan = partition(losses[0], S)
            if not plan.uniform:
                continue
            ends = {id(v): v for v in plan.pre_params + plan.post_params}
            for var in ends.values():
                if getattr(var, "sharding_spec", None) is not None:
                    continue      # user spec wins
                shape = tuple(var.shape or ())
                if not shape or int(np.prod(shape)) < min_elems:
                    continue
                divisible = [i for i, s in enumerate(shape) if s % S == 0]
                if not divisible:
                    continue
                dim = max(divisible, key=lambda i: shape[i])
                spec = [None] * len(shape)
                spec[dim] = "pp"
                var.sharding_spec = P(*spec)

    def param_sharding(self, name):
        node = self.variables[name]
        spec = getattr(node, "sharding_spec", None)
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, spec if spec is not None else P())

    def feed_sharding(self, name, shape):
        """Feeds shard along the batch dim over the 'dp' axis if present;
        on a pure expert-parallel mesh tokens are data-parallel over the
        expert group (reference MoE: DP and EP share the same devices)."""
        if self.mesh is None:
            return None
        axes = ["dp"]
        if "dp" not in self.mesh.axis_names:
            axes.append("ep")   # pure-EP mesh: tokens are DP over 'ep'
        for ax in axes:
            if ax in self.mesh.axis_names and len(shape) >= 1 \
                    and shape[0] % self.mesh.shape[ax] == 0:
                return NamedSharding(self.mesh, P(ax))
        return NamedSharding(self.mesh, P())

    def process_batch_rows(self, name, global_shape):
        """Rows [lo, hi) of the dim-0-sharded feed ``name`` that THIS
        process's addressable devices hold, or None when the feed is not
        cleanly dim-0-sharded / the process's rows are not one contiguous
        range / the whole batch is addressable anyway."""
        sharding = self.feed_sharding(name, global_shape)
        if sharding is None or not self.multiprocess:
            return None
        spec = tuple(sharding.spec)
        if not spec or spec[0] is None \
                or any(s is not None for s in spec[1:]):
            return None
        try:
            imap = sharding.devices_indices_map(tuple(global_shape))
        except Exception:
            return None
        pid = jax.process_index()
        spans = sorted(
            {( (idx[0].start or 0),
               (idx[0].stop if idx[0].stop is not None
                else global_shape[0]) )
             for d, idx in imap.items() if d.process_index == pid})
        if not spans:
            return None
        lo, hi = spans[0]
        for s, e in spans[1:]:
            if s > hi:
                return None        # holes: keep the global convention
            hi = max(hi, e)
        if (lo, hi) == (0, int(global_shape[0])):
            return None
        return lo, hi

    def device_put_feed(self, name, value):
        """Feed placement.  Dataloader feeds wired by _wire_prefetch
        arrive as this process's LOCAL batch shard (rows [lo, hi) of the
        global batch) and are assembled into the global array without
        any process ever materializing the whole batch.  Everything else
        keeps the legacy convention: every process feeds the identical
        GLOBAL batch and each keeps only its addressable shards."""
        info = self._proc_shard.get(name, {}).get(value.shape[0]) \
            if self._proc_shard else None
        if info is not None:
            lo, hi, gb = info
            if value.shape[0] == hi - lo:
                v = np.asarray(value)
                gshape = (gb,) + tuple(v.shape[1:])
                sharding = self.feed_sharding(name, gshape)

                def local_rows(idx):
                    sl = idx[0]
                    s = (sl.start or 0) - lo
                    e = (sl.stop if sl.stop is not None else gb) - lo
                    return v[(slice(s, e),) + tuple(idx[1:])]

                return jax.make_array_from_callback(gshape, sharding,
                                                    local_rows)
        return self.place_value(value,
                                self.feed_sharding(name, value.shape))

    # ------------------------------------------------------------------ #

    def run(self, name="default", eval_node_list=None, feed_dict=None,
            convert_to_numpy_ret_vals=False, **kwargs):
        if isinstance(name, dict) and feed_dict is None:
            # positional style: executor.run(feed_dict)
            feed_dict, name = name, "default"
        feed_dict = feed_dict or {}
        return self.subexecutor[name].run(feed_dict, convert_to_numpy_ret_vals)

    # ------------------------------------------------------------------ #
    # checkpointing (reference executor.py:461-541; strictly better — we
    # save optimizer slot state, step, and rng as well, SURVEY.md §5.4)
    # ------------------------------------------------------------------ #

    def save(self, path, file=None, varlist=None, sharded=False,
             async_=False):
        """Checkpoint params + optimizer slots + step + rng (reference
        executor.py:461-485 saves params only; SURVEY §5.4 'strictly
        better').  ``sharded=True`` writes an orbax checkpoint: each
        device stores only its shard (no host gather of the full state —
        required once params exceed one host's RAM), ``async_=True``
        returns immediately and flushes in the background
        (``wait_for_checkpoint()`` joins it)."""
        self.join_ps_push()
        if sharded or async_:
            return self._save_orbax(path, async_=async_)
        if self.multiprocess:
            raise ValueError(
                "pickle save cannot gather shards held by other "
                "processes; use save(path, sharded=True) — orbax writes "
                "each process's shards collectively")
        os.makedirs(path, exist_ok=True)
        fname = os.path.join(path, file or "checkpoint.pkl")
        # copy=True: np.asarray over jax CPU arrays is zero-copy and the
        # buffers are donated to the next step — a view would rot
        params = {k: np.array(v, copy=True)
                  for k, v in self.var_values.items()
                  if varlist is None or k in varlist}
        # PS-managed vars: the server (after a cache flush) is the source
        # of truth, not the device copy
        for name in list(self.ps_sparse_vars) + list(self.ps_dense_vars):
            if varlist is not None and name not in varlist:
                continue
            ct = self.cstables.get(name)
            if ct is not None:
                ct.flush()
            params[name] = np.asarray(self.ps_comm.pull(name))
        opt = jax.tree_util.tree_map(lambda x: np.asarray(x), self.opt_states)
        with open(fname, "wb") as f:
            pickle.dump({"params": params, "opt_states": opt,
                         "step": int(self.step),
                         "rng": np.asarray(self.rng),
                         "dataloaders": self._loader_states()}, f)

    def _loaders(self):
        # keys must be stable across BUILDS (auto node names embed the
        # global id counter): subgraph name + topo position + loader name
        seen = {}
        for sub_name in sorted(self.subexecutor):
            sub = self.subexecutor[sub_name]
            for i, dl_op in enumerate(getattr(sub, "dataloader_ops", [])):
                for key, loader in getattr(dl_op, "dataloaders",
                                           {}).items():
                    seen[f"{sub_name}:{i}:{key}"] = loader
        return seen

    def _loader_states(self):
        """Exact mid-epoch resume state (reference loses the iterator
        position on restart; SURVEY §5.4 'strictly better')."""
        return {k: ld.state_dict() for k, ld in self._loaders().items()}

    def _restore_loaders(self, states):
        loaders = self._loaders()
        missing = []
        for k, st in (states or {}).items():
            if k in loaders:
                loaders[k].load_state_dict(st)
            else:
                missing.append(k)
        if missing:
            import warnings
            warnings.warn(
                f"checkpoint dataloader state {missing} has no match in "
                f"this build (graph structure changed?); those data "
                f"streams restart from batch 0 while params resume at "
                f"step {int(self.step)}", stacklevel=2)

    # ---- orbax path: sharded + async ---- #

    def _orbax_state(self):
        self.join_ps_push()
        state = {"params": dict(self.var_values),
                 "opt_states": self.opt_states,
                 "step": self.step, "rng": self.rng}
        for name in list(self.ps_sparse_vars) + list(self.ps_dense_vars):
            ct = self.cstables.get(name)
            if ct is not None:
                ct.flush()
            state["params"][name] = jnp.asarray(
                np.asarray(self.ps_comm.pull(name)))
        return state

    def _save_orbax(self, path, async_=False):
        import json
        import orbax.checkpoint as ocp
        loaders_file = os.path.join(os.path.abspath(path), "loaders.json")
        path = os.path.abspath(os.path.join(path, "orbax"))
        self.wait_for_checkpoint()
        # dataloader positions are a handful of host-side scalars; a JSON
        # sidecar keeps them out of the sharded tree so per-loader schema
        # changes can never make the orbax restore structure-mismatch.
        # The payload is stamped with the step and published (atomic
        # rename) only AFTER the orbax tree is durable, so a crash at any
        # point leaves either a matching pair or a stamp mismatch the
        # restore detects — never a silent position/params divergence.
        payload = json.dumps({"step": int(self.step),
                              "loaders": self._loader_states()},
                             default=int)

        def publish():
            os.makedirs(os.path.dirname(loaders_file), exist_ok=True)
            tmp = loaders_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, loaders_file)

        if async_:
            # one AsyncCheckpointer per executor, reused across saves —
            # a fresh instance per save would churn its thread pool and
            # leak resources over a long run if any close were missed
            ck = getattr(self, "_async_ckptr", None)
            if ck is None:
                ck = self._async_ckptr = ocp.AsyncCheckpointer(
                    ocp.StandardCheckpointHandler())
            ck.save(path, args=ocp.args.StandardSave(
                self._orbax_state()), force=True)

            def wait_then_publish():
                ck.wait_until_finished()
                publish()

            self._sidecar_thread = threading.Thread(
                target=wait_then_publish, daemon=True)
            self._sidecar_thread.start()
        else:
            with ocp.StandardCheckpointer() as ckptr:
                ckptr.save(path, self._orbax_state(), force=True)
            publish()

    def close(self):
        """Release executor-held host resources (the async-checkpoint
        thread pool).  Safe to call more than once; subsequent saves
        re-create what they need."""
        self.wait_for_checkpoint(close=True)

    def __del__(self):
        # best-effort backstop for executors discarded without close():
        # an un-closed AsyncCheckpointer keeps its thread pool (and can
        # keep the interpreter alive at exit)
        try:
            if getattr(self, "_async_ckptr", None) is not None:
                self.close()
        except Exception:
            pass

    def wait_for_checkpoint(self, close=False):
        """Join any in-flight async save.  The checkpointer instance is
        kept for reuse by later saves; pass ``close=True`` (teardown) to
        release its thread pool."""
        t = getattr(self, "_sidecar_thread", None)
        if t is not None:
            t.join()
            self._sidecar_thread = None
        ck = getattr(self, "_async_ckptr", None)
        if ck is not None:
            ck.wait_until_finished()
            if close:
                ck.close()
                self._async_ckptr = None

    def _restore_superset(self, ocp, path, target):
        """Restore a checkpoint whose tree holds keys the current build no
        longer has (forward compat): target = current abstract leaves where
        keys overlap, on-disk shape/dtype for the rest.  Returns the
        restored state (extras included — callers filter) or None."""
        try:
            with ocp.StandardCheckpointer() as ckptr:
                meta = ckptr.metadata(path)
            # StepMetadata -> TreeMetadata -> nested {key: ArrayMetadata}
            tree = getattr(getattr(meta, "item_metadata", meta),
                           "tree", None)
            if tree is None and isinstance(meta, dict):
                # older orbax returns the nested metadata tree directly
                tree = meta
            if tree is None:
                return None
            tree = dict(tree)

            # the on-disk tree must COVER the target: a checkpoint missing
            # current keys is a real mismatch (renamed param, wrong model)
            # that must surface as the original error, not silently
            # restore partial state
            def covered(t, m):
                if isinstance(t, dict):
                    return isinstance(m, dict) and all(
                        k in m and covered(v, m[k]) for k, v in t.items())
                return not isinstance(m, dict)

            if not covered(target, tree):
                return None

            t2 = dict(target)
            # legacy in-tree dataloader scalars ride along (cheap); every
            # OTHER extra (e.g. materialized causal masks — potentially
            # hundreds of MB) is skipped outright by the partial restore,
            # never read or materialized
            if "dataloaders" in tree and "dataloaders" not in t2:
                t2["dataloaders"] = jax.tree_util.tree_map(
                    lambda m: jax.ShapeDtypeStruct(
                        tuple(m.shape), np.dtype(m.dtype)),
                    tree["dataloaders"])
            try:
                with ocp.Checkpointer(
                        ocp.PyTreeCheckpointHandler()) as ckptr:
                    return ckptr.restore(path, args=ocp.args.PyTreeRestore(
                        item=t2,
                        restore_args=ocp.checkpoint_utils
                        .construct_restore_args(t2),
                        partial_restore=True))
            except Exception:
                # older orbax has no working partial restore (the
                # restore_args must cover every on-disk key): widen the
                # target to the FULL on-disk tree — extras are read and
                # materialized (the cost partial restore avoids), then
                # discarded by the callers' key filtering
                def merge(t, m):
                    if isinstance(m, dict):
                        t = t if isinstance(t, dict) else {}
                        return {k: merge(t.get(k), mv)
                                for k, mv in m.items()}
                    if t is not None:
                        return t
                    return jax.ShapeDtypeStruct(tuple(m.shape),
                                                np.dtype(m.dtype))

                t3 = merge(t2, tree)
                with ocp.Checkpointer(
                        ocp.PyTreeCheckpointHandler()) as ckptr:
                    return ckptr.restore(path, args=ocp.args.PyTreeRestore(
                        item=t3,
                        restore_args=ocp.checkpoint_utils
                        .construct_restore_args(t3)))
        except Exception:
            return None

    def load_sharded(self, path):
        """Restore an orbax checkpoint, placing each leaf directly with
        THIS executor's shardings (resharding across different meshes /
        layouts happens inside orbax — a tp2-saved checkpoint restores
        onto an fsdp8 executor without a full-state host bounce)."""
        import json
        import orbax.checkpoint as ocp
        # join any in-flight async save first: its sidecar publishes only
        # after the orbax finalize, and restoring inside that window would
        # silently drop the dataloader positions
        self.wait_for_checkpoint()
        loaders_file = os.path.join(os.path.abspath(path), "loaders.json")
        path = os.path.abspath(os.path.join(path, "orbax"))
        cur = self._orbax_state()

        def abstract(x):
            x = jnp.asarray(x) if not hasattr(x, "dtype") else x
            sharding = getattr(x, "sharding", None)
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=sharding)
        target = jax.tree_util.tree_map(abstract, cur)
        loader_states, sidecar_step = None, None
        if os.path.exists(loaders_file):
            with open(loaders_file) as f:
                sidecar = json.load(f)
            loader_states = sidecar.get("loaders", sidecar)
            sidecar_step = sidecar.get("step")
        try:
            with ocp.StandardCheckpointer() as ckptr:
                state = ckptr.restore(path, target)
        except Exception as core_err:
            # Orbax needs an exact tree match, so a checkpoint whose tree
            # is a SUPERSET of the current state fails the target above —
            # e.g. non-trainable Variables an older build stored that this
            # build computes in-trace (causal masks), or in-tree dataloader
            # state.  Rebuild the target from the checkpoint's own
            # metadata (current abstract leaf where keys overlap, on-disk
            # shape/dtype for the extras), restore, and discard extras.
            state = self._restore_superset(ocp, path, target)
            if state is not None:
                loader_states = state.pop("dataloaders", loader_states)
            # checkpoints from builds that stored dataloader state INSIDE
            # the orbax tree: retry with that subtree mirrored from each
            # schema those builds ever wrote.  If nothing matches, surface
            # the original error — don't let the compat chain mask a real
            # shape/dtype problem.
            def loader_target(keys):
                # np dtypes: orbax stored the in-tree python scalars as
                # int64/bool_, not jax's int32 default
                return {
                    name: {k: jax.ShapeDtypeStruct(
                        (), np.asarray(v).dtype)
                        for k, v in st.items() if k in keys}
                    for name, st in self._loader_states().items()}

            if state is None:
                for keys in (("consumed", "seed", "shuffle"),
                             ("consumed", "seed")):
                    t2 = dict(target)
                    t2["dataloaders"] = loader_target(keys)
                    try:
                        with ocp.StandardCheckpointer() as ckptr:
                            state = ckptr.restore(path, t2)
                        loader_states = state.pop("dataloaders", None)
                        break
                    except Exception:
                        state = None
            if state is None:
                raise core_err
        params = state["params"]
        for name in list(self.ps_sparse_vars) + list(self.ps_dense_vars):
            if name in params:
                self.load_dict({name: np.asarray(params.pop(name))})
        self.var_values = {k: v for k, v in params.items()
                           if k in self.variables
                           and k not in self.ps_sparse_vars}
        self.opt_states = state["opt_states"]
        self.step = jnp.asarray(state["step"], jnp.int32)
        self.rng = jnp.asarray(state["rng"], jnp.uint32)
        if loader_states and sidecar_step is not None \
                and sidecar_step != int(self.step):
            # crash window between the orbax finalize and the sidecar
            # publish (or vice versa): positions belong to another save
            import warnings
            warnings.warn(
                f"dataloader sidecar is stamped step {sidecar_step} but "
                f"the checkpoint restored step {int(self.step)}; "
                f"ignoring it — data streams restart from batch 0",
                stacklevel=2)
            loader_states = None
        if loader_states:
            self._restore_loaders(loader_states)

    def load(self, path, file=None, consider_splits=False):
        if os.path.isdir(os.path.join(path, "orbax")) and not os.path.exists(
                os.path.join(path, file or "checkpoint.pkl")):
            return self.load_sharded(path)
        fname = os.path.join(path, file or "checkpoint.pkl")
        with open(fname, "rb") as f:
            ckpt = pickle.load(f)
        self.load_dict(ckpt["params"])
        if ckpt.get("opt_states"):
            loaded = ckpt["opt_states"]        # raw checkpoint leaves

            def _placed(cur_state, new_state):
                """Restore leaves directly onto the placement their
                freshly-initialized counterparts already have — a bare
                jnp.asarray would pin everything to device 0 and the
                next jitted step would reject the mixed placements."""
                if self.mesh is None:
                    return jax.tree_util.tree_map(jnp.asarray, new_state)
                try:
                    return jax.tree_util.tree_map(
                        lambda c, n: self.place_value(np.asarray(n),
                                                      c.sharding)
                        if hasattr(c, "sharding") else jnp.asarray(n),
                        cur_state, new_state)
                except ValueError:         # structure changed; keep raw
                    return jax.tree_util.tree_map(jnp.asarray, new_state)

            # optimizer names are checkpoint-stable (hash of the var set),
            # so direct lookup works; the key-set match remains only as a
            # fallback for checkpoints written before stable naming
            remapped = {}
            used = set()
            for cur_key, cur_state in self.opt_states.items():
                if cur_key in loaded:
                    used.add(cur_key)
                    remapped[cur_key] = _placed(cur_state, loaded[cur_key])
                    continue
                match = None
                for old_key, old_state in loaded.items():
                    if old_key not in used and \
                            set(old_state) == set(cur_state):
                        match = old_key
                        break
                if match is not None:
                    used.add(match)
                    remapped[cur_key] = _placed(cur_state, loaded[match])
                else:
                    remapped[cur_key] = cur_state
            self.opt_states = remapped
        if "step" in ckpt:
            self.step = jnp.asarray(ckpt["step"], jnp.int32)
        if "rng" in ckpt:
            self.rng = jnp.asarray(ckpt["rng"], jnp.uint32)
        if ckpt.get("dataloaders"):
            self._restore_loaders(ckpt["dataloaders"])

    def load_dict(self, state_dict):
        self.join_ps_push()
        from .cache.cstable import CacheSparseTable
        for k, v in state_dict.items():
            if k in self.ps_sparse_vars or k in self.ps_dense_vars:
                spec = self._ps_opt_specs.get(k)
                comm = self.ps_comm
                if k in self.cstables and hasattr(comm, "_home"):
                    comm = comm._home(k)   # cache tables live whole
                comm.param_set(k, np.asarray(v, np.float32),
                               opt=spec and spec[0],
                               opt_args=spec and spec[1])
                ct = self.cstables.get(k)
                if ct is not None:
                    # drop cached lines; they refer to pre-load values.
                    # comm stays the HOME server (sharded groups don't
                    # speak the cache's versioned sync protocol)
                    self.cstables[k] = CacheSparseTable(
                        ct.cache.limit if hasattr(ct.cache, "limit")
                        else self.config.cache_bound,
                        ct.vocab, ct.width, key=k, comm=comm,
                        policy=self.config.cstable_policy,
                        pull_bound=ct.pull_bound, push_bound=ct.push_bound)
                if k in self.ps_dense_vars:
                    if self.mesh is not None:
                        arr = self.place_value(np.asarray(v),
                                               self.param_sharding(k))
                    else:
                        arr = jnp.asarray(v)
                    self.var_values[k] = arr
                    self.ps_dense_dirty.pop(k, None)
                continue
            if k in self.var_values:
                if self.mesh is not None:
                    arr = self.place_value(np.asarray(v),
                                           self.param_sharding(k))
                else:
                    arr = jnp.asarray(v)
                self.var_values[k] = arr

    def load_seeds(self, seed):
        self.rng = jax.random.PRNGKey(seed)

    def return_tensor_values(self):
        self.join_ps_push()
        # copies, not views: the underlying buffers are donated next step
        out = {k: np.array(v, copy=True)
               for k, v in self.var_values.items()}
        # PS-managed vars: the server (post cache-flush) is authoritative;
        # the device copy of a dense-PS var lags by one step
        for name in list(self.ps_sparse_vars) + list(self.ps_dense_vars):
            ct = self.cstables.get(name)
            if ct is not None:
                ct.flush()
            out[name] = np.asarray(self.ps_comm.pull(name))
        return out

    def profile(self, feed_shapes=None, log_file=None, profiler="gpu"):
        from .profiler import HetuProfiler
        return HetuProfiler(self, feed_shapes, log_file)

    def recordLoads(self):
        pass

    @property
    def batch_num(self):
        # dataloader integration supplies this; see dataloader.py
        subs = list(self.subexecutor.values())
        return subs[0].batch_num if subs and hasattr(subs[0], "batch_num") else None


def gradients(output_node, node_list, insert_grad=None, return_all=False):
    from .graph.autodiff import gradients as _g
    return _g(output_node, node_list, insert_grad, return_all)
